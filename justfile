# Task runner (reference analog: the gpu-pruner justfile).

build:
    cmake -G Ninja -S . -B build && cmake --build build

# tier-1 verify: the ROADMAP.md "Tier-1 verify" command VERBATIM (bash:
# it uses PIPESTATUS). tests/test_justfile_guard.py fails the build if
# this recipe drifts from ROADMAP.md.
verify:
    #!/usr/bin/env bash
    set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc

test: build
    ./build/tpupruner_tests
    python -m pytest tests/ -q

# unit tiers only (fast)
test-unit: build
    ./build/tpupruner_tests
    python -m pytest tests/test_domain.py tests/test_query_template.py -q

# hermetic end-to-end tier (fake Prometheus + fake K8s API, TLS, OTLP)
test-e2e: build
    python -m pytest tests/ -q -k "pipeline or querytest or auth or tls or otlp"

# live-cluster tier (reference analog: just kind-create / test-e2e running
# the #[ignore]-gated tests/e2e.rs against a throwaway kind cluster)
kind-create:
    kind create cluster --name tpu-pruner
    kubectl apply -f hack/kind/crds.yaml
    kubectl wait --for condition=established --timeout=60s \
        crd/jobsets.jobset.x-k8s.io crd/leaderworkersets.leaderworkerset.x-k8s.io \
        crd/notebooks.kubeflow.org crd/inferenceservices.serving.kserve.io

kind-delete:
    kind delete cluster --name tpu-pruner

test-e2e-kind: build
    TP_E2E_KIND=1 python -m pytest tests/e2e_kind -q

# sanitizer builds (the race/memory tier the reference lacks, SURVEY.md §5)
test-asan:
    cmake -G Ninja -S . -B build-asan -DTP_SANITIZE=ON && cmake --build build-asan
    ./build-asan/tpupruner_tests
    ./build-asan/tpupruner_fuzz 200000

test-tsan:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests

# hermetic e2e tier with the TSan daemon: races in the resolve fan-out,
# consumer pool, metrics server, and OTLP exporter surface here
test-tsan-e2e: test-tsan
    TP_DAEMON_PATH=./build-tsan/tpu-pruner TSAN_OPTIONS=exitcode=66 \
        python -m pytest tests/test_pipeline_e2e.py tests/test_otlp.py tests/test_leader.py tests/e2e_kind -q

test-asan-e2e:
    cmake -G Ninja -S . -B build-asan -DTP_SANITIZE=ON && cmake --build build-asan
    TP_DAEMON_PATH=./build-asan/tpu-pruner \
        python -m pytest tests/test_pipeline_e2e.py tests/test_otlp.py tests/test_leader.py tests/e2e_kind -q

# deterministic mutation fuzz over the untrusted-input surfaces
fuzz iterations="500000": build
    ./build/tpupruner_fuzz {{iterations}}

bench: build
    python bench.py

# dry-run against a live cluster (current kubeconfig + GMP frontend)
run prometheus_url="http://frontend.gmp-system.svc:9090":
    ./build/tpu-pruner --prometheus-url {{prometheus_url}} --run-mode dry-run -d

querytest query url:
    ./build/tpu-pruner querytest '{{query}}' {{url}}

docker-build:
    docker build -t tpu-pruner:latest .

# fast output-path check of the benchmark (16x-shrunk cluster, n=1; the
# summary line carries smoke:true — never a measurement)
bench-smoke:
    TP_BENCH_SMOKE=1 python bench.py

# flight-recorder smoke: record two daemon cycles against the hermetic
# fakes, then replay every capsule offline (fakes torn down first) —
# non-zero exit on decision drift. tests/test_justfile_guard.py pins the
# recipe to the module it invokes.
replay-smoke:
    python -m tpu_pruner.testing.replay_smoke

# fleet-federation smoke: 3 real member daemons (one browned out, one
# killed mid-run) → hub → assert the merged report (totals sum,
# per-cluster-minimum coverage, UNREACHABLE row) and the offline
# 3-ledger merge. tests/test_justfile_guard.py pins the recipe to the
# module it invokes.
fleet-smoke:
    python -m tpu_pruner.testing.fleet_smoke

# federation-at-scale smoke: 100 scripted lightweight members under one
# real hub in snapshot vs --fleet-delta on vs +streamed modes — merged
# views asserted byte-identical across all three, the quiesced delta
# round asserted ≥10x cheaper than snapshot polling in bytes AND hub
# CPU, churn propagation timed. TP_PLANET_PODS=0 skips the 250k-pod
# single-cluster rung so the smoke fits CI minutes.
# tests/test_justfile_guard.py pins the recipe.
fleet-mega:
    TP_PLANET_MEMBERS=100 TP_PLANET_PODS=0 python bench.py --planet-only

# policy-gym smoke: synthetic 200-cycle trace corpus (trace_gen) recorded
# by the real daemon, replayed against 3 policies in one pass, winner
# flag line printed — non-zero exit when the scoring contract breaks.
# tests/test_justfile_guard.py pins the recipe to the module it invokes.
gym-smoke:
    python -m tpu_pruner.testing.gym_smoke

# capacity-observatory smoke: one --capacity on member over a sliced
# fixture (1 whole-free spare + 2 consolidatable tenant slices) → the
# member /debug/capacity inventory, the hub /debug/fleet/capacity
# rollup and the bit-for-bit `analyze --capacity-report` defrag replay
# asserted end to end. tests/test_justfile_guard.py pins the recipe to
# the module it invokes.
capacity-smoke:
    python -m tpu_pruner.testing.capacity_smoke

# mega-bench smoke: the 50k-pod tier scaled down to 10,240 pods so CI can
# run it in minutes — every tier target is still asserted inside
# run_mega_tier (shard resolve speedup >1 on multi-core hosts, capsules
# recorded under N shards replay bit-for-bit, warm steady-state API calls
# O(churn), warm p50 detect→scaledown under the 100 ms bar), so a miss
# exits non-zero. tests/test_justfile_guard.py pins the recipe to
# bench.py --mega-only.
bench-mega:
    TP_MEGA_PODS=10240 python bench.py --mega-only

# differential-reconcile race tier: the dirty-tracker + decision cache
# (written by the producer's plan/commit while consumer threads report
# actuation outcomes) and the informer's dirty journal under
# ThreadSanitizer (substring filter of the native test binary)
tsan-incremental:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests incremental
    ./build-tsan/tpupruner_tests informer

# shard-engine race tier: the sharded resolve fan-out, worker pool reuse
# and the informer's concurrent 410+relist coalescing under
# ThreadSanitizer (substring filter of the native test binary)
tsan-shard:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests shard
    ./build-tsan/tpupruner_tests informer

# shared-transport race tier: the h2 multiplexing client (concurrent
# streams on one connection, GOAWAY retry, fallback demotion) and the
# informer's LIST/watch-over-h2 path under ThreadSanitizer (substring
# filter of the native test binary)
tsan-transport:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests h2
    ./build-tsan/tpupruner_tests informer

# zero-copy JSON memory tier: the arena Doc decoder's parity units plus
# the mutation fuzzer's Doc-vs-Value accept/tree invariant under
# AddressSanitizer — string_view-into-buffer decoding is exactly the
# code whose lifetime bugs ASan catches and plain asserts don't
asan-json:
    cmake -G Ninja -S . -B build-asan -DTP_SANITIZE=ON && cmake --build build-asan
    ./build-asan/tpupruner_tests json
    ./build-asan/tpupruner_fuzz 200000

# binary-wire memory tier: the proto decoder's units plus its
# truncation/byte-flip parity sweeps under AddressSanitizer —
# varint/length-delimited scanning over untrusted bytes is exactly the
# code whose OOB reads ASan catches and plain asserts don't
asan-proto:
    cmake -G Ninja -S . -B build-asan -DTP_SANITIZE=ON && cmake --build build-asan
    ./build-asan/tpupruner_tests proto

# compact-store memory tier: the intern table (concurrent relist units),
# the packed PodRecord builders and their materialization parity corpus
# (escape/UTF-8 edges), and the Doc-arena recycling under
# AddressSanitizer — offset-into-blob string packing is exactly the code
# whose OOB reads ASan catches and plain asserts don't
asan-store:
    cmake -G Ninja -S . -B build-asan -DTP_SANITIZE=ON && cmake --build build-asan
    ./build-asan/tpupruner_tests compact

# planet-1M store smoke: the 1,000,000-pod compact-store rung scaled to
# 65,536 pods so CI can run it in minutes — every envelope assertion is
# still live inside run_store_scale_rung (bytes-per-pod bar, compact
# on/off steady-state RSS ratio ≥2x, pipelined cold sync no worse than
# serial, shard-curve or its 1-core skip marker), so a miss exits
# non-zero. The flagship run is the default TP_PLANET_STORE_PODS=1000000.
# tests/test_justfile_guard.py pins the recipe to bench.py
# --planet-1m-only.
bench-planet-1m:
    TP_PLANET_STORE_PODS=65536 python bench.py --planet-1m-only

# binary-wire race tier: the fused decode → journal_touch → store-upsert
# path (reflector threads apply proto frames while the producer drains
# the dirty journal) plus the informer machinery it rides, under
# ThreadSanitizer (substring filter of the native test binary)
tsan-wire:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests proto
    ./build-tsan/tpupruner_tests informer

# delta-federation race tier: the member-side change journal (cycle
# publishers vs parked long-pollers on the same condition variable) and
# the hub's merge math the poll fan-out feeds, under ThreadSanitizer
# (substring filter of the native test binary)
tsan-fleet:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests delta
    ./build-tsan/tpupruner_tests fleet

# standalone TPU capture: probe + fleet eval + bench_tpu_last_good.json
# (run EARLY in a round / whenever the chip tunnel is up; exits 1 when no
# real accelerator measurement happened)
bench-tpu:
    python bench.py --tpu-only

# opt-in real-hardware policy tier: XLA + Mosaic-Pallas verdict parity
# (f32 and int8+cumsum) on an actual TPU chip
test-policy-tpu:
    TP_POLICY_TPU=1 python -m pytest tests/test_policy_tpu.py -q

# chaos smoke: three seeded fault scenarios against the real daemon
# (multi-fault storm byte-identical to an undisturbed control, 2x
# SIGKILL ledger accounting, stale-evidence veto + recovery under
# --signal-guard on) — non-zero exit on any invariant miss, <60 s.
# tests/test_justfile_guard.py pins the recipe to the module it invokes.
chaos-smoke:
    python -m tpu_pruner.testing.chaos_smoke

# long-soak drift smoke: 500 warm back-to-back daemon cycles under
# seeded background chaos, per-window RSS/CPU sampled and the flat-slope
# bar asserted inside run_soak_tier. 500 cycles sit inside allocator
# warmup, so the smoke loosens the RSS bar to 2 MB/1k cycles; the
# flagship run is the default TP_SOAK_CYCLES=10000 at the tight 512 kB
# bar. tests/test_justfile_guard.py pins the recipe to bench.py
# --soak-only.
soak-smoke:
    TP_SOAK_CYCLES=500 TP_SOAK_RSS_SLOPE_KB=2048 python bench.py --soak-only

# chaos race tier: the seeded backoff policy's shared retry telemetry
# (concurrent recorders vs the metrics renderer) and the per-cycle
# deadline watchdog (producer arms/disarms vs phase-boundary probes)
# under ThreadSanitizer (substring filter of the native test binary)
tsan-chaos:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests backoff
    ./build-tsan/tpupruner_tests watchdog

# event-dispatcher smoke: three scenarios against the real daemon
# (metric flip patched in <1 s against a 60 s interval, event-vs-cycle
# audit byte-identity on a quiesced cluster, --pause-after hysteresis
# streak) — non-zero exit on any invariant miss, <60 s.
# tests/test_justfile_guard.py pins the recipe to the module it invokes.
event-smoke:
    python -m tpu_pruner.testing.event_smoke

# event-engine race tier: the timer wheel + sliding-window token bucket
# (dispatcher advance vs informer-notify schedule/cancel, consumer
# try_acquire vs /debug/timers stats reads) and the informer's dirty
# journal under ThreadSanitizer (substring filter of the native test
# binary)
tsan-event:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests timerwheel
    ./build-tsan/tpupruner_tests informer

# provenance-trace smoke: record a traced action, breach a 1 ms
# detect→action SLO so the trace pins past ring eviction, fetch it by id
# at /debug/traces/<id>, and render the waterfall three ways (analyze
# --trace by id, --slow, offline capsule stamp) — non-zero exit on any
# miss. tests/test_justfile_guard.py pins the recipe to the module.
trace-smoke:
    python -m tpu_pruner.testing.trace_smoke

# trace-engine race tier: concurrent span begin/add/arm/actuation-end/
# export against ring eviction and /debug/traces index reads under
# ThreadSanitizer (substring filter of the native test binary)
tsan-trace:
    cmake -G Ninja -S . -B build-tsan -DTP_TSAN=ON && cmake --build build-tsan
    ./build-tsan/tpupruner_tests trace
    ./build-tsan/tpupruner_tests informer
