// CLI parse + metric-plane endpoint resolution units (reference analog:
// clap derive validation on struct Cli, gpu-pruner main.rs:46-119).
#include "testing.hpp"

#include <vector>

#include "tpupruner/cli.hpp"

using tpupruner::cli::Cli;
using tpupruner::cli::CliError;

namespace {

Cli parse(std::vector<const char*> argv) {
  argv.insert(argv.begin(), "tpu-pruner");
  return tpupruner::cli::parse(static_cast<int>(argv.size()),
                               const_cast<char**>(argv.data()));
}

bool parse_fails(std::vector<const char*> argv, const std::string& needle) {
  try {
    parse(std::move(argv));
  } catch (const CliError& e) {
    return std::string(e.what()).find(needle) != std::string::npos;
  }
  return false;
}

}  // namespace

TP_TEST(cli_requires_some_metric_plane) {
  TP_CHECK(parse_fails({}, "--prometheus-url or --gcp-project"));
}

TP_TEST(cli_prometheus_url_and_gcp_project_exclusive) {
  TP_CHECK(parse_fails({"--prometheus-url", "http://p:9090", "--gcp-project", "proj"},
                       "mutually exclusive"));
}

TP_TEST(cli_prometheus_url_used_verbatim) {
  Cli cli = parse({"--prometheus-url", "http://thanos:9091"});
  TP_CHECK_EQ(tpupruner::cli::prometheus_base(cli), "http://thanos:9091");
}

TP_TEST(cli_gcp_project_resolves_cloud_monitoring_base) {
  Cli cli = parse({"--gcp-project", "ml-prod"});
  TP_CHECK_EQ(tpupruner::cli::prometheus_base(cli),
              "https://monitoring.googleapis.com/v1/projects/ml-prod/location/global/prometheus");
}

TP_TEST(cli_monitoring_endpoint_override) {
  Cli cli = parse({"--gcp-project", "p1", "--monitoring-endpoint", "http://127.0.0.1:9/"});
  TP_CHECK_EQ(tpupruner::cli::prometheus_base(cli),
              "http://127.0.0.1:9/v1/projects/p1/location/global/prometheus");
}

TP_TEST(cli_metric_schema_auto_resolution) {
  // auto → gke-system under --gcp-project (the Cloud Monitoring PromQL API
  // is the only plane serving kubernetes_io:node_accelerator_* names),
  // gmp for a plain Prometheus URL; explicit choices always win.
  TP_CHECK_EQ(parse({"--prometheus-url", "http://p"}).metric_schema, "gmp");
  TP_CHECK_EQ(parse({"--gcp-project", "p1"}).metric_schema, "gke-system");
  TP_CHECK_EQ(parse({"--gcp-project", "p1", "--metric-schema", "gmp"}).metric_schema, "gmp");
  TP_CHECK_EQ(parse({"--prometheus-url", "http://p", "--metric-schema", "gke-system"})
                  .metric_schema,
              "gke-system");
  TP_CHECK(parse_fails({"--prometheus-url", "http://p", "--metric-schema", "bogus"},
                       "invalid value for --metric-schema"));
  // auto is per-device: the pre-existing `--gcp-project --device gpu`
  // invocation (DCGM profile over the Cloud Monitoring PromQL API) must
  // keep working — auto resolves it to gmp, never to an error.
  TP_CHECK_EQ(parse({"--gcp-project", "p1", "--device", "gpu"}).metric_schema, "gmp");
  // only an EXPLICIT gke-system choice conflicts with device=gpu
  TP_CHECK(parse_fails({"--gcp-project", "p1", "--device", "gpu",
                        "--metric-schema", "gke-system"},
                       "--metric-schema=gke-system requires --device=tpu"));
}

TP_TEST(cli_join_flags_reach_query_args) {
  Cli cli = parse({"--gcp-project", "p1", "--join-metric", "kube_pod_info",
                   "--join-resource", "none"});
  auto a = tpupruner::cli::to_query_args(cli);
  TP_CHECK_EQ(a.metric_schema, "gke-system");
  TP_CHECK_EQ(a.join_metric, "kube_pod_info");
  TP_CHECK_EQ(a.join_resource, "");  // "none" disables the resource selector
}

TP_TEST(cli_metrics_port_semantics) {
  // unset and "0" both mean disabled (an operator's explicit 0 must not
  // start binding random ports); "auto" = ephemeral; else the port.
  TP_CHECK_EQ(parse({"--prometheus-url", "http://p"}).metrics_port, -1);
  TP_CHECK_EQ(parse({"--prometheus-url", "http://p", "--metrics-port", "0"}).metrics_port, -1);
  TP_CHECK_EQ(parse({"--prometheus-url", "http://p", "--metrics-port", "auto"}).metrics_port, 0);
  TP_CHECK_EQ(parse({"--prometheus-url", "http://p", "--metrics-port", "8080"}).metrics_port,
              8080);
  TP_CHECK(parse_fails({"--prometheus-url", "http://p", "--metrics-port", "65536"},
                       "out of range"));
}
