#include "testing.hpp"

int main(int argc, char** argv) { return tptest::run_all(argc, argv); }
