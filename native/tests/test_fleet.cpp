// Fleet federation units: cluster-label stamping (the exposition choke
// point), the hub's merge math (totals that sum, per-cluster-minimum
// coverage, UNREACHABLE semantics), and identity resolution. The e2e
// behavior (real members + hub binary) rides tests/test_fleet.py.
#include <cstdlib>

#include "testing.hpp"
#include "tpupruner/fleet.hpp"
#include "tpupruner/json.hpp"

namespace fleet = tpupruner::fleet;
using tpupruner::json::Value;

namespace {

fleet::MemberSnapshot member(const std::string& cluster, bool reachable,
                             double coverage, bool guard_on, double reclaimed,
                             double idle = 0) {
  fleet::MemberSnapshot m;
  m.url = "http://" + cluster;
  m.cluster = cluster;
  m.reachable = reachable;
  m.ever_reached = reachable;
  m.staleness_s = reachable ? 0 : -1;
  m.polls = 3;
  m.failures = reachable ? 0 : 3;
  if (!reachable) m.last_error = "connection refused";
  Value totals = Value::object();
  totals.set("idle_seconds", Value(idle));
  totals.set("active_seconds", Value(0.0));
  totals.set("reclaimed_chip_seconds", Value(reclaimed));
  Value wl = Value::object();
  wl.set("cluster", Value(cluster));
  wl.set("tracked", Value(static_cast<int64_t>(1)));
  wl.set("totals", std::move(totals));
  wl.set("workloads", Value::array());
  m.workloads = std::move(wl);
  Value sig = Value::object();
  sig.set("enabled", Value(guard_on));
  if (guard_on) {
    sig.set("coverage_ratio", Value(coverage));
    sig.set("brownout", Value(coverage < 0.9));
  }
  m.signals = std::move(sig);
  Value decisions = Value::array();
  Value d = Value::object();
  d.set("pod", Value(cluster + "-pod"));
  decisions.push_back(std::move(d));
  Value dec = Value::object();
  dec.set("decisions", std::move(decisions));
  m.decisions = std::move(dec);
  return m;
}

const Value* find_cluster_row(const Value& doc, const char* list_key,
                              const std::string& cluster) {
  const Value* rows = doc.find(list_key);
  if (!rows || !rows->is_array()) return nullptr;
  for (const Value& row : rows->as_array()) {
    if (row.get_string("cluster") == cluster) return &row;
  }
  return nullptr;
}

}  // namespace

TP_TEST(stamp_exposition_labels_every_sample_line) {
  std::string body =
      "# HELP tpu_pruner_x help\n"
      "# TYPE tpu_pruner_x counter\n"
      "tpu_pruner_x 3\n"
      "tpu_pruner_h_bucket{phase=\"q\",le=\"+Inf\"} 2 # {trace_id=\"ab\"} 0.1 9\n"
      "tpu_pruner_h_sum{phase=\"q\"} 0.5\n";
  std::string out = fleet::stamp_exposition(body, "east");
  TP_CHECK(out.find("tpu_pruner_x{cluster=\"east\"} 3\n") != std::string::npos);
  TP_CHECK(out.find("tpu_pruner_h_bucket{cluster=\"east\",phase=\"q\",le=\"+Inf\"} 2 "
                    "# {trace_id=\"ab\"} 0.1 9\n") != std::string::npos);
  TP_CHECK(out.find("tpu_pruner_h_sum{cluster=\"east\",phase=\"q\"} 0.5\n") !=
           std::string::npos);
  // comments untouched
  TP_CHECK(out.find("# HELP tpu_pruner_x help\n") != std::string::npos);
  // idempotent: a second stamp (or a pre-labelled hub row) changes nothing
  TP_CHECK_EQ(fleet::stamp_exposition(out, "east"), out);
  TP_CHECK_EQ(fleet::stamp_exposition("m{cluster=\"w\"} 1\n", "east"),
              "m{cluster=\"w\"} 1\n");
  // empty cluster: no-op
  TP_CHECK_EQ(fleet::stamp_exposition(body, ""), body);
}

TP_TEST(aggregate_totals_sum_over_clusters) {
  auto view = fleet::aggregate(
      {member("a", true, 1.0, true, 100.0, 10.0),
       member("b", true, 1.0, true, 7.5, 5.0)},
      30);
  const Value* totals = view.workloads.find("fleet_totals");
  TP_CHECK(totals != nullptr);
  TP_CHECK_EQ(totals->find("reclaimed_chip_seconds")->as_double(), 107.5);
  TP_CHECK_EQ(totals->find("idle_seconds")->as_double(), 15.0);
  TP_CHECK_EQ(view.workloads.find("tracked_total")->as_int(), 2);
  // per-cluster sections carry each member's own totals verbatim
  const Value* a = find_cluster_row(view.workloads, "clusters", "a");
  TP_CHECK(a != nullptr);
  TP_CHECK_EQ(a->find("totals")->find("reclaimed_chip_seconds")->as_double(), 100.0);
}

TP_TEST(aggregate_coverage_is_minimum_never_mean) {
  auto view = fleet::aggregate(
      {member("a", true, 1.0, true, 0),
       member("b", true, 0.25, true, 0),
       member("c", true, 1.0, true, 0)},
      30);
  // mean would be 0.75; the fleet figure must be b's 0.25
  TP_CHECK_EQ(view.signals.find("coverage_min")->as_double(), 0.25);
  const Value* brownouts = view.signals.find("brownout_clusters");
  TP_CHECK_EQ(brownouts->as_array().size(), static_cast<size_t>(1));
  TP_CHECK_EQ(brownouts->as_array()[0].as_string(), "b");
}

TP_TEST(aggregate_unreachable_pins_minimum_to_zero) {
  auto view = fleet::aggregate(
      {member("a", true, 1.0, true, 0), member("dark", false, 0, false, 0)},
      30);
  TP_CHECK_EQ(view.signals.find("coverage_min")->as_double(), 0.0);
  const Value* unreachable = view.signals.find("unreachable_clusters");
  TP_CHECK_EQ(unreachable->as_array()[0].as_string(), "dark");
  const Value* row = find_cluster_row(view.clusters, "members", "dark");
  TP_CHECK_EQ(row->get_string("status"), "UNREACHABLE");
  TP_CHECK_EQ(row->get_string("last_error"), "connection refused");
  TP_CHECK_EQ(view.clusters.find("unreachable")->as_int(), 1);
  // the dark member's last-known ledger data is kept, flagged, summed
  const Value* wl = find_cluster_row(view.workloads, "clusters", "dark");
  TP_CHECK_EQ(wl->get_string("status"), "UNREACHABLE");
  TP_CHECK(view.metrics_text.find("tpu_pruner_fleet_member_up{cluster=\"dark\"} 0") !=
           std::string::npos);
  TP_CHECK(view.metrics_text.find("tpu_pruner_fleet_members_unreachable 1") !=
           std::string::npos);
}

TP_TEST(aggregate_guard_off_members_contribute_nothing) {
  // guard-off member alongside a browned one: minimum is the browned
  // member's ratio, not diluted and not zeroed by the guard-off member
  auto view = fleet::aggregate(
      {member("off", true, 0, false, 0), member("b", true, 0.4, true, 0)}, 30);
  TP_CHECK_EQ(view.signals.find("coverage_min")->as_double(), 0.4);
  // no guard anywhere → nothing to judge → 1.0
  view = fleet::aggregate({member("off", true, 0, false, 0)}, 30);
  TP_CHECK_EQ(view.signals.find("coverage_min")->as_double(), 1.0);
  // guard-off member serves no per-member coverage row
  TP_CHECK(view.metrics_text.find("tpu_pruner_fleet_coverage_ratio{cluster=\"off\"}") ==
           std::string::npos);
}

TP_TEST(aggregate_stale_member_reads_unreachable) {
  auto m = member("lagging", true, 1.0, true, 0);
  m.staleness_s = 120;  // reachable flag stale: last success 2 min ago
  auto view = fleet::aggregate({m}, /*stale_after_s=*/30);
  const Value* row = find_cluster_row(view.clusters, "members", "lagging");
  TP_CHECK_EQ(row->get_string("status"), "UNREACHABLE");
  TP_CHECK_EQ(view.signals.find("coverage_min")->as_double(), 0.0);
}

TP_TEST(aggregate_never_polled_member_is_pending) {
  fleet::MemberSnapshot m;
  m.url = "http://new";
  m.cluster = "new";
  m.polls = 0;
  auto view = fleet::aggregate({m}, 30);
  const Value* row = find_cluster_row(view.clusters, "members", "new");
  TP_CHECK_EQ(row->get_string("status"), "PENDING");
}

TP_TEST(aggregate_orders_clusters_deterministically) {
  auto view = fleet::aggregate(
      {member("zeta", true, 1.0, true, 0), member("alpha", true, 1.0, true, 0)},
      30);
  const Value& rows = *view.clusters.find("members");
  TP_CHECK_EQ(rows.as_array()[0].get_string("cluster"), "alpha");
  TP_CHECK_EQ(rows.as_array()[1].get_string("cluster"), "zeta");
}

TP_TEST(aggregate_caps_decisions_per_member) {
  auto m = member("a", true, 1.0, true, 0);
  Value decisions = Value::array();
  for (int i = 0; i < 10; ++i) {
    Value d = Value::object();
    d.set("pod", Value("p" + std::to_string(i)));
    decisions.push_back(std::move(d));
  }
  Value dec = Value::object();
  dec.set("decisions", std::move(decisions));
  m.decisions = std::move(dec);
  auto view = fleet::aggregate({m}, 30, /*decisions_per_member=*/3);
  const Value* row = find_cluster_row(view.decisions, "clusters", "a");
  const Value& kept = *row->find("decisions");
  TP_CHECK_EQ(kept.as_array().size(), static_cast<size_t>(3));
  // the LAST K survive (most recent decisions)
  TP_CHECK_EQ(kept.as_array()[2].get_string("pod"), "p9");
}

TP_TEST(cluster_identity_resolution_order) {
  ::setenv("TPU_PRUNER_CLUSTER_NAME", "from-env", 1);
  TP_CHECK_EQ(fleet::resolve_cluster_name("from-flag"), "from-flag");
  TP_CHECK_EQ(fleet::resolve_cluster_name(""), "from-env");
  ::unsetenv("TPU_PRUNER_CLUSTER_NAME");
  fleet::set_cluster_name("my-cluster");
  TP_CHECK_EQ(fleet::cluster_name(), "my-cluster");
  fleet::set_cluster_name("");  // empty never sticks
  TP_CHECK_EQ(fleet::cluster_name(), "default");
  fleet::reset_for_test();
}

TP_TEST(hub_metric_families_are_prefixed_and_complete) {
  auto families = fleet::hub_metric_families();
  TP_CHECK(families.size() >= 10);
  for (const std::string& f : families) {
    TP_CHECK(f.rfind("tpu_pruner_fleet_", 0) == 0);
  }
  // every family rendered by aggregate appears in the canonical list
  auto view = fleet::aggregate({member("a", true, 0.5, true, 1.0)}, 30);
  for (const std::string& f :
       {"tpu_pruner_fleet_members", "tpu_pruner_fleet_coverage_ratio_min",
        "tpu_pruner_fleet_member_up", "tpu_pruner_fleet_reclaimed_chip_seconds_total"}) {
    TP_CHECK(view.metrics_text.find(f) != std::string::npos);
  }
}
