// Unified retry/backoff policy + cycle watchdog units (PR 15 chaos
// tier). The `just tsan-chaos` recipe runs these under ThreadSanitizer
// via the binary's substring filter ("backoff" / "watchdog"), so the
// concurrent cases double as the race tier for both modules.
#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <ctime>
#include <functional>
#include <thread>
#include <vector>

#include "testing.hpp"
#include "tpupruner/backoff.hpp"
#include "tpupruner/watchdog.hpp"

namespace backoff = tpupruner::backoff;
namespace watchdog = tpupruner::watchdog;

TP_TEST(backoff_exp_delay_matches_legacy_informer_formula) {
  // seed 0 must reproduce the pre-unification informer backoff
  // bit-for-bit: min(500 << min(a,5), 10000) + hash(path+attempt) % 500.
  backoff::Policy p;
  const std::string path = "/api/v1/pods";
  for (int a = 0; a <= 8; ++a) {
    int64_t base = std::min<int64_t>(500LL << std::min(a, 5), 10000);
    int64_t jitter = static_cast<int64_t>(
        std::hash<std::string>{}(path + std::to_string(a)) % 500);
    TP_CHECK_EQ(p.exp_delay_ms(path, a), base + jitter);
  }
}

TP_TEST(backoff_hinted_delay_caps_hint_before_jitter) {
  // The legacy 429 formula: min(hint, cap - jitter_ms) + hash(path)%500.
  // Capping BEFORE the jitter keeps the spread for long Retry-After
  // values instead of collapsing them all onto cap_ms.
  backoff::Policy p;
  const std::string path = "/apis/apps/v1/deployments";
  int64_t jitter = static_cast<int64_t>(std::hash<std::string>{}(path) % 500);
  TP_CHECK_EQ(p.hinted_delay_ms(path, 1000), 1000 + jitter);
  TP_CHECK_EQ(p.hinted_delay_ms(path, 50000), 9500 + jitter);
  TP_CHECK(p.hinted_delay_ms(path, 50000) < 10000);  // documented worst case
}

TP_TEST(backoff_seeded_jitter_deterministic_and_decorrelated) {
  backoff::Policy a;
  a.seed = 42;
  backoff::Policy b;
  b.seed = 42;
  backoff::Policy c;
  c.seed = 43;
  bool seeds_differ_somewhere = false;
  for (const char* key : {"alpha", "beta", "gamma", "delta", "epsilon"}) {
    // Same seed ⇒ identical jitter (the replayability contract the
    // chaos harness depends on); always within [0, jitter_ms).
    TP_CHECK_EQ(a.jitter(key), b.jitter(key));
    TP_CHECK(a.jitter(key) >= 0 && a.jitter(key) < a.jitter_ms);
    if (a.jitter(key) != c.jitter(key)) seeds_differ_somewhere = true;
  }
  // Different seeds ⇒ decorrelated sequences (5 keys all colliding by
  // chance is ~(1/500)^5).
  TP_CHECK(seeds_differ_somewhere);
}

TP_TEST(backoff_parse_retry_after_forms) {
  TP_CHECK_EQ(backoff::parse_retry_after_ms("3"), 3000);
  // delta-seconds clamp to [1, 10] BEFORE the *1000 multiply
  TP_CHECK_EQ(backoff::parse_retry_after_ms("0"), 1000);
  TP_CHECK_EQ(backoff::parse_retry_after_ms("100"), 10000);
  // out-of-int64 delta throws inside stoll, falls to the date parse,
  // lands on the 1 s default instead of a negative/overflowed wait
  TP_CHECK_EQ(backoff::parse_retry_after_ms("99999999999999999999999"), 1000);
  TP_CHECK_EQ(backoff::parse_retry_after_ms("not-a-date"), 1000);
  // HTTP-date in the past → default (never a negative wait)
  TP_CHECK_EQ(backoff::parse_retry_after_ms("Wed, 21 Oct 2015 07:28:00 GMT"), 1000);
  // HTTP-date a few seconds out → a positive bounded wait
  std::time_t future = std::time(nullptr) + 5;
  std::tm tm{};
  gmtime_r(&future, &tm);
  char buf[64];
  std::strftime(buf, sizeof(buf), "%a, %d %b %Y %H:%M:%S GMT", &tm);
  int64_t ms = backoff::parse_retry_after_ms(buf);
  TP_CHECK(ms >= 3000 && ms <= 5000);
}

TP_TEST(backoff_record_retry_counts_and_renders) {
  backoff::reset_for_test();
  backoff::record_retry("k8s", "http429", 1.2);
  backoff::record_retry("k8s", "http429", 1.2);
  backoff::record_retry("transport", "stale_conn", 0.0);
  std::string text = backoff::render_metrics(false);
  TP_CHECK(text.find("tpu_pruner_retries_total{endpoint=\"k8s\",cause=\"http429\"} 2") !=
           std::string::npos);
  TP_CHECK(text.find("tpu_pruner_retries_total{endpoint=\"transport\","
                     "cause=\"stale_conn\"} 1") != std::string::npos);
  TP_CHECK(text.find("tpu_pruner_backoff_seconds_count 3") != std::string::npos);
  // 0.0 lands in every bucket; the 1.2 s pair only from le=2.5 up
  TP_CHECK(text.find("tpu_pruner_backoff_seconds_bucket{le=\"1\"} 1") !=
           std::string::npos);
  TP_CHECK(text.find("tpu_pruner_backoff_seconds_bucket{le=\"2.5\"} 3") !=
           std::string::npos);
  TP_CHECK(text.find("tpu_pruner_backoff_seconds_bucket{le=\"+Inf\"} 3") !=
           std::string::npos);
  // OpenMetrics rendering keeps the 0.0.4-compatible family shape
  std::string om = backoff::render_metrics(true);
  TP_CHECK(om.find("# TYPE tpu_pruner_retries_total unknown") != std::string::npos);
  backoff::reset_for_test();
}

TP_TEST(backoff_metric_families_canonical) {
  const auto& families = backoff::metric_families();
  TP_CHECK_EQ(families.size(), static_cast<size_t>(2));
  TP_CHECK_EQ(families[0], std::string("tpu_pruner_retries_total"));
  TP_CHECK_EQ(families[1], std::string("tpu_pruner_backoff_seconds"));
  // Every canonical family must actually render (the /metrics-serving
  // drift test enumerates what the daemon serves).
  std::string text = backoff::render_metrics(false);
  for (const std::string& f : families) {
    TP_CHECK(text.find("# HELP " + f) != std::string::npos);
  }
}

TP_TEST(backoff_sleep_interruptible_honors_stop) {
  std::atomic<bool> stop{true};
  auto t0 = std::chrono::steady_clock::now();
  TP_CHECK(!backoff::sleep_interruptible(60000, &stop));
  auto elapsed = std::chrono::steady_clock::now() - t0;
  TP_CHECK(elapsed < std::chrono::seconds(2));  // aborted, not slept
}

TP_TEST(backoff_concurrent_record_and_render) {
  // TSan tier: concurrent recorders + a renderer on the shared telemetry.
  backoff::reset_for_test();
  std::vector<std::thread> threads;
  for (int i = 0; i < 4; ++i) {
    threads.emplace_back([i] {
      for (int n = 0; n < 200; ++n) {
        backoff::record_retry("k8s", i % 2 ? "relist" : "watch", 0.1 * (n % 7));
      }
    });
  }
  threads.emplace_back([] {
    for (int n = 0; n < 50; ++n) (void)backoff::render_metrics(n % 2 == 0);
  });
  for (auto& t : threads) t.join();
  std::string text = backoff::render_metrics(false);
  TP_CHECK(text.find("tpu_pruner_backoff_seconds_count 800") != std::string::npos);
  backoff::reset_for_test();
}

TP_TEST(watchdog_disabled_never_trips) {
  watchdog::configure(0);
  watchdog::arm();
  TP_CHECK(!watchdog::expired());
  watchdog::check("resolve");  // must not throw
  watchdog::disarm();
}

TP_TEST(watchdog_expires_and_throws_at_phase_boundary) {
  watchdog::configure(20);  // ms
  watchdog::arm();
  std::this_thread::sleep_for(std::chrono::milliseconds(60));
  TP_CHECK(watchdog::expired());
  bool threw = false;
  try {
    watchdog::check("resolve");
  } catch (const watchdog::CycleTimeout& e) {
    threw = true;
    TP_CHECK(std::string(e.what()).find("'resolve'") != std::string::npos);
    TP_CHECK(std::string(e.what()).find("--cycle-deadline") != std::string::npos);
  }
  TP_CHECK(threw);
  // disarmed ⇒ quiet again, whatever the deadline
  watchdog::disarm();
  TP_CHECK(!watchdog::expired());
  watchdog::check("resolve");
  watchdog::configure(0);
}

TP_TEST(watchdog_rearm_resets_the_clock) {
  watchdog::configure(50);
  watchdog::arm();
  std::this_thread::sleep_for(std::chrono::milliseconds(80));
  TP_CHECK(watchdog::expired());
  watchdog::arm();  // next cycle: fresh deadline
  TP_CHECK(!watchdog::expired());
  watchdog::disarm();
  watchdog::configure(0);
}

TP_TEST(watchdog_concurrent_arm_check_probe) {
  // TSan tier: the producer arms/disarms while phase boundaries (and the
  // metrics thread reading expired()) probe concurrently.
  watchdog::configure(1);
  std::atomic<bool> done{false};
  std::thread prober([&] {
    while (!done.load()) {
      try {
        watchdog::check("probe");
      } catch (const watchdog::CycleTimeout&) {
      }
      (void)watchdog::expired();
    }
  });
  for (int i = 0; i < 500; ++i) {
    watchdog::arm();
    watchdog::disarm();
  }
  done.store(true);
  prober.join();
  watchdog::configure(0);
}
