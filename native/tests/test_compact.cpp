// Compact interned pod store (native/include/tpupruner/compact.hpp).
//
// Two contracts are load-bearing enough to pin natively:
//   1. The intern table is safe under concurrent intern+lookup — a relist
//      decodes pages on the sync pool while warm cycles read entries, so
//      this is the TSan target (`just asan-store` runs it sanitized).
//   2. A PodRecord materializes to EXACTLY the Value the non-compact
//      decode produces — dump() byte-identity over JSON and protobuf
//      forms, including escape/UTF-8 edges — and the strict-subset
//      builder REFUSES anything it could not round-trip, falling back to
//      the exact representation instead of guessing.
#include "testing.hpp"

#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include "tpupruner/compact.hpp"
#include "tpupruner/core.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/proto.hpp"

namespace compact = tpupruner::compact;
namespace proto = tpupruner::proto;
using tpupruner::json::Value;

namespace {

// ── tiny encoder (the C++ twin of tpu_pruner/testing/wire_proto.py) ──

std::string enc_varint(uint64_t n) {
  std::string out;
  while (true) {
    uint8_t b = n & 0x7F;
    n >>= 7;
    if (n) out.push_back(static_cast<char>(b | 0x80));
    else {
      out.push_back(static_cast<char>(b));
      return out;
    }
  }
}

std::string enc_tag(uint32_t field, uint32_t wt) { return enc_varint((field << 3) | wt); }

std::string enc_ld(uint32_t field, const std::string& data) {
  return enc_tag(field, 2) + enc_varint(data.size()) + data;
}

std::string enc_str(uint32_t field, const std::string& s) { return enc_ld(field, s); }

std::string enc_demo_pod() {
  std::string meta = enc_str(1, "pod-0") + enc_str(3, "ml") + enc_str(5, "uid-0") +
                     enc_str(6, "41");
  meta += enc_ld(11, enc_str(1, "app") + enc_str(2, "demo"));
  std::string owner = enc_str(1, "ReplicaSet") + enc_str(3, "rs-0") + enc_str(4, "uid-rs") +
                      enc_str(5, "apps/v1") + enc_tag(6, 0) + enc_varint(1);
  meta += enc_ld(13, owner);
  std::string quantity = enc_ld(2, enc_str(1, "4"));
  std::string requests = enc_ld(2, enc_str(1, "google.com/tpu") + quantity);
  std::string limits = enc_ld(1, enc_str(1, "google.com/tpu") + quantity);
  std::string container = enc_str(1, "main") + enc_ld(8, limits + requests);
  std::string spec = enc_ld(2, container) + enc_str(10, "node-7");
  std::string status = enc_str(1, "Running");
  return enc_ld(1, meta) + enc_ld(2, spec) + enc_ld(3, status);
}

}  // namespace

// ── intern table ────────────────────────────────────────────────────────

TP_TEST(compact_intern_dedup_and_roundtrip) {
  compact::Interner& in = compact::interner();
  uint32_t a = in.intern("compact-test-ns-alpha");
  uint32_t b = in.intern("compact-test-ns-beta");
  TP_CHECK(a != b);
  TP_CHECK_EQ(in.intern("compact-test-ns-alpha"), a);
  TP_CHECK_EQ(std::string(in.str(a)), std::string("compact-test-ns-alpha"));
  TP_CHECK_EQ(std::string(in.str(b)), std::string("compact-test-ns-beta"));
  // the empty string is a valid (and common: generateName-only pods) key
  uint32_t e = in.intern("");
  TP_CHECK_EQ(std::string(in.str(e)), std::string(""));
}

TP_TEST(compact_intern_concurrent_relist) {
  // The TSan target: writer threads intern a churning key set (what the
  // sync pool does during a relist) while reader threads resolve ids
  // interned moments earlier. Any lock hole shows up as a data race on
  // the shard maps or a dangling string_view.
  compact::Interner& in = compact::interner();
  constexpr int kThreads = 4;
  constexpr int kKeys = 400;
  std::atomic<bool> failed{false};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      std::vector<uint32_t> ids;
      ids.reserve(kKeys);
      for (int i = 0; i < kKeys; ++i) {
        // overlapping across threads (i) and thread-unique (t:i) keys
        std::string shared = "compact-race-shared-" + std::to_string(i);
        std::string unique =
            "compact-race-" + std::to_string(t) + "-" + std::to_string(i);
        uint32_t sid = in.intern(shared);
        uint32_t uid = in.intern(unique);
        ids.push_back(uid);
        if (std::string(in.str(sid)) != shared) failed.store(true);
        if (std::string(in.str(uid)) != unique) failed.store(true);
        // re-intern must dedup even under contention
        if (in.intern(shared) != sid) failed.store(true);
      }
      for (int i = 0; i < kKeys; ++i) {
        std::string expect = "compact-race-" + std::to_string(t) + "-" + std::to_string(i);
        if (std::string(in.str(ids[i])) != expect) failed.store(true);
      }
    });
  }
  for (auto& th : threads) th.join();
  TP_CHECK(!failed.load());
  TP_CHECK(in.count() > 0);
  TP_CHECK(in.bytes() > 0);
}

// ── record materialization parity ───────────────────────────────────────

namespace {

// Assert the compact record round-trips `text` byte-identically.
void check_json_roundtrip(const std::string& text) {
  Value v = Value::parse(text);
  auto rec = compact::record_from_value(v);
  TP_CHECK(rec.has_value());
  TP_CHECK_EQ(rec->to_value().dump(), v.dump());
}

}  // namespace

TP_TEST(compact_record_json_parity_corpus) {
  // The recorded LIST/watch shapes the store sees, plus the edges the
  // satellite calls out: escapes, UTF-8, empty label maps, empty
  // containers, generateName-only metadata, gpu + tpu chips.
  check_json_roundtrip(R"({"apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "pod-0", "namespace": "ml", "uid": "uid-0",
                 "resourceVersion": "41", "labels": {"app": "demo"},
                 "ownerReferences": [{"apiVersion": "apps/v1", "kind": "ReplicaSet",
                                      "name": "rs-0", "uid": "uid-rs",
                                      "controller": true}]},
    "spec": {"containers": [{"name": "main",
              "resources": {"limits": {"google.com/tpu": "4"},
                            "requests": {"google.com/tpu": "4"}}}]},
    "status": {"phase": "Running"}})");
  check_json_roundtrip(R"({"apiVersion": "v1", "kind": "Pod",
    "metadata": {"generateName": "burst-", "namespace": "ns"},
    "spec": {"containers": []}})");
  check_json_roundtrip(R"({"apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "esc", "namespace": "ns",
                 "labels": {"quote\"key": "tab\tval", "nl": "a\nb"},
                 "annotations": {"übergroß": "ключ"}},
    "spec": {"nodeName": "node-ü", "containers": [{"name": "c"}]},
    "status": {"message": "back\\slash \"x\"", "reason": "Evicted"}})");
  check_json_roundtrip(R"({"apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "empty-maps", "namespace": "ns",
                 "labels": {}, "annotations": {}, "ownerReferences": []},
    "spec": {"containers": [{"name": "c", "resources": {}}]},
    "status": {}})");
  check_json_roundtrip(R"({"apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "gpu", "namespace": "ns",
                 "creationTimestamp": "2026-01-02T03:04:05Z",
                 "selfLink": "/api/v1/x"},
    "spec": {"containers": [{"name": "c", "image": "i",
              "resources": {"limits": {"nvidia.com/gpu": "8"},
                            "requests": {"nvidia.com/gpu": "2"}}}]},
    "status": {"phase": "Pending", "message": "m", "reason": "r"}})");
}

TP_TEST(compact_record_chips_match_core_accounting) {
  const char* text = R"({"apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "chips", "namespace": "ns"},
    "spec": {"containers": [
      {"name": "a", "resources": {"limits": {"google.com/tpu": "4"},
                                  "requests": {"google.com/tpu": "2"}}},
      {"name": "b", "resources": {"requests": {"nvidia.com/gpu": "3"}}}]},
    "status": {"phase": "Running"}})";
  Value v = Value::parse(text);
  auto rec = compact::record_from_value(v);
  TP_CHECK(rec.has_value());
  // max(limits, requests) per container, both devices: 4 tpu + 3 gpu
  TP_CHECK_EQ(static_cast<int64_t>(rec->chips),
              tpupruner::core::pod_chip_count(v, "tpu") +
                  tpupruner::core::pod_chip_count(v, "gpu"));
}

TP_TEST(compact_record_refuses_out_of_schema_shapes) {
  // Every refusal keeps the exact original representation in the store —
  // so a refusal is a correctness non-event, but a silent ACCEPT of one
  // of these would corrupt the materialized bytes.
  const char* shapes[] = {
      // unknown metadata key
      R"({"apiVersion": "v1", "kind": "Pod",
          "metadata": {"name": "x", "namespace": "ns", "finalizers": ["a"]},
          "spec": {"containers": []}})",
      // non-string label value
      R"({"apiVersion": "v1", "kind": "Pod",
          "metadata": {"name": "x", "namespace": "ns", "labels": {"a": 1}},
          "spec": {"containers": []}})",
      // unknown spec key
      R"({"apiVersion": "v1", "kind": "Pod",
          "metadata": {"name": "x", "namespace": "ns"},
          "spec": {"containers": [], "hostNetwork": true}})",
      // unknown container key
      R"({"apiVersion": "v1", "kind": "Pod",
          "metadata": {"name": "x", "namespace": "ns"},
          "spec": {"containers": [{"name": "c", "env": []}]}})",
      // unknown status key
      R"({"apiVersion": "v1", "kind": "Pod",
          "metadata": {"name": "x", "namespace": "ns"},
          "spec": {"containers": []},
          "status": {"phase": "Running", "hostIP": "1.2.3.4"}})",
      // null where the subset wants a string
      R"({"apiVersion": "v1", "kind": "Pod",
          "metadata": {"name": null, "namespace": "ns"},
          "spec": {"containers": []}})",
  };
  for (const char* text : shapes) {
    TP_CHECK(!compact::record_from_value(Value::parse(text)).has_value());
  }
}

TP_TEST(compact_record_proto_parity) {
  // record_from_proto must materialize EXACTLY what the lazy
  // object_to_value path yields for the same bytes.
  std::string body = enc_demo_pod();
  Value lazy = proto::object_to_value(body, "v1", "Pod");
  compact::PodRecord rec = compact::record_from_proto(body, "v1", "Pod");
  TP_CHECK_EQ(rec.to_value().dump(), lazy.dump());
  TP_CHECK_EQ(static_cast<int64_t>(rec.chips), tpupruner::core::pod_chip_count(lazy));
  TP_CHECK(rec.bytes() < body.size() + sizeof(compact::PodRecord) + 256);
}

TP_TEST(compact_record_proto_duplicate_fields_last_wins) {
  // Repeated metadata (field 1) replaces the whole sub-object, exactly
  // like proto.cpp's object_to_value (out.set is last-wins).
  std::string meta1 = enc_str(1, "first") + enc_str(3, "ns");
  std::string meta2 = enc_str(1, "second") + enc_str(3, "ns") +
                      enc_ld(11, enc_str(1, "k") + enc_str(2, "v"));
  std::string body = enc_ld(1, meta1) + enc_ld(1, meta2);
  Value lazy = proto::object_to_value(body, "v1", "Pod");
  compact::PodRecord rec = compact::record_from_proto(body, "v1", "Pod");
  TP_CHECK_EQ(rec.to_value().dump(), lazy.dump());
}

TP_TEST(compact_record_proto_throws_where_lazy_would) {
  // Truncated length prefix: both decode paths must throw ParseError —
  // cold_sync relies on matching error behavior to keep get()-time
  // semantics when it falls back to raw bytes.
  std::string body = enc_demo_pod();
  std::string truncated = body.substr(0, body.size() / 2);
  bool lazy_threw = false, record_threw = false;
  try {
    proto::object_to_value(truncated, "v1", "Pod");
  } catch (const tpupruner::json::ParseError&) {
    lazy_threw = true;
  }
  try {
    compact::record_from_proto(truncated, "v1", "Pod");
  } catch (const tpupruner::json::ParseError&) {
    record_threw = true;
  }
  TP_CHECK_EQ(lazy_threw, record_threw);
}

// ── doc arena recycling ─────────────────────────────────────────────────

TP_TEST(compact_doc_arena_recycles_across_parses) {
  using tpupruner::json::Doc;
  auto before = tpupruner::json::doc_arena_stats();
  { auto doc = Doc::parse(R"({"a": [1, 2, 3], "b": {"c": "d"}})"); }
  auto mid = tpupruner::json::doc_arena_stats();
  TP_CHECK(mid.returns > before.returns || mid.drops > before.drops);
  { auto doc = Doc::parse(R"({"e": [4, 5, 6], "f": {"g": "h"}})"); }
  auto after = tpupruner::json::doc_arena_stats();
  // the second parse draws the arena the first one returned
  TP_CHECK(after.reuses > before.reuses || after.drops > mid.drops);
}
