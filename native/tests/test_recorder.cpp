// Flight-recorder units: capsule serialize/deserialize round-trips through
// the on-disk ring, ring bounding + restart reload, id hygiene, and the
// replay engine's pure-replay/what-if mechanics. The e2e behavior (real
// daemon, fakes, analyze --replay) rides tests/test_flight_recorder.py.
#include <cstdlib>
#include <unistd.h>

#include "testing.hpp"
#include "tpupruner/audit.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/recorder.hpp"

namespace recorder = tpupruner::recorder;
namespace audit = tpupruner::audit;
using tpupruner::json::Value;

namespace {

std::string make_tmpdir() {
  char tmpl[] = "/tmp/tp-recorder-XXXXXX";
  char* dir = ::mkdtemp(tmpl);
  TP_CHECK(dir != nullptr);
  return dir;
}

Value run_config() {
  Value qa = Value::object();
  qa.set("device", Value("tpu"));
  qa.set("duration", Value(30));
  qa.set("metric_schema", Value("gmp"));
  Value cfg = Value::object();
  cfg.set("query_args", std::move(qa));
  cfg.set("run_mode", Value("dry-run"));
  cfg.set("dry_run", Value(true));
  cfg.set("enabled_resources", Value("drsinjl"));
  cfg.set("duration_min", Value(30));
  cfg.set("grace_s", Value(300));
  cfg.set("lookback_s", Value(2100));
  cfg.set("max_scale_per_cycle", Value(0));
  cfg.set("watch_cache", Value("off"));
  return cfg;
}

const char* kPromBody =
    "{\"status\":\"success\",\"data\":{\"resultType\":\"vector\",\"result\":"
    "[{\"metric\":{\"exported_pod\":\"p1\",\"exported_namespace\":\"ml\","
    "\"exported_container\":\"main\",\"accelerator_type\":\"v5e\","
    "\"node_type\":\"v5e\",\"accelerator_id\":\"0\"},"
    "\"value\":[1000,\"0\"]}]}}";

Value old_pod() {
  return Value::parse(
      "{\"metadata\":{\"name\":\"p1\",\"namespace\":\"ml\","
      "\"creationTimestamp\":\"2020-01-01T00:00:00Z\"},"
      "\"status\":{\"phase\":\"Running\"}}");
}

// The DecisionRecord the dry-run pipeline produces for the capsule above —
// recorded verbatim so pure replay must reproduce it bit-for-bit.
Value expected_decision(uint64_t cycle) {
  audit::DecisionRecord rec;
  rec.cycle = cycle;
  rec.ns = "ml";
  rec.pod = "p1";
  rec.signal_metric = "tensorcore/duty_cycle";
  rec.signal_value = 0.0;
  rec.has_signal = true;
  rec.accelerator = "v5e";
  rec.lookback_s = 2100;
  rec.owner_chain = {"Pod/ml/p1", "ReplicaSet/ml/rs", "Deployment/ml/dep"};
  rec.root_kind = "Deployment";
  rec.root_ns = "ml";
  rec.root_name = "dep";
  rec.reason = audit::Reason::DryRun;
  rec.action = "none";
  rec.detail = "would have paused (run-mode dry-run)";
  return rec.to_json();
}

// Seal one full capsule for `cycle` through the capture API.
void seal_cycle(uint64_t cycle) {
  recorder::begin_cycle(cycle, 1754000000 + static_cast<int64_t>(cycle));
  recorder::record_prom_body(cycle, kPromBody);
  recorder::record_resolve_now(cycle, 1754000000);
  Value pod = old_pod();
  recorder::record_pod(cycle, "ml/p1", &pod, false, "");
  recorder::record_resolution(cycle, "ml/p1",
                              {"Pod/ml/p1", "ReplicaSet/ml/rs", "Deployment/ml/dep"},
                              "Deployment", "ml", "dep", "Deployment:uid1", "");
  recorder::record_stats(cycle, 1, 1, 0);
  recorder::record_decision(cycle, expected_decision(cycle));
  recorder::arm(cycle, 0);  // dry-run: seals immediately
}

}  // namespace

TP_TEST(recorder_capsule_roundtrip_and_replay) {
  recorder::reset_for_test();
  std::string dir = make_tmpdir();
  recorder::configure(dir, 8);
  TP_CHECK(recorder::enabled());
  recorder::set_run_context(run_config(), "idle_query_placeholder == 0");
  seal_cycle(1);

  Value index = recorder::index_json();
  TP_CHECK_EQ(index.find("capsules")->as_array().size(), size_t{1});
  std::string id = index.find("capsules")->as_array()[0].get_string("id");
  TP_CHECK(!id.empty());

  // serialize → file → deserialize: the capsule is self-contained
  std::string body = recorder::capsule_body(id);
  TP_CHECK(!body.empty());
  Value capsule = Value::parse(body);
  TP_CHECK_EQ(capsule.get_string("id"), id);
  TP_CHECK_EQ(capsule.find("cycle")->as_int(), int64_t{1});
  TP_CHECK_EQ(capsule.find("prom")->get_string("body"), std::string(kPromBody));
  TP_CHECK(capsule.find("pods")->find("ml/p1") != nullptr);
  TP_CHECK(capsule.find("resolutions")->find("ml/p1") != nullptr);
  TP_CHECK_EQ(capsule.find("decisions")->as_array().size(), size_t{1});

  // pure replay reproduces the recorded decision bit-for-bit
  Value result = recorder::replay(capsule, Value::object());
  TP_CHECK(result.find("match")->as_bool());
  TP_CHECK_EQ(result.find("drift")->as_array().size(), size_t{0});
  TP_CHECK_EQ(result.find("replayed")->as_array().size(), size_t{1});

  // what-if run_mode flips the dry-run record to a predicted SCALED
  Value what_if = Value::object();
  what_if.set("run_mode", Value("scale-down"));
  Value flipped = recorder::replay(capsule, what_if);
  TP_CHECK(!flipped.find("match")->as_bool());
  const Value& flips = *flipped.find("flips");
  TP_CHECK_EQ(flips.as_array().size(), size_t{1});
  TP_CHECK_EQ(flips.as_array()[0].find("to")->get_string("reason"), std::string("SCALED"));
  TP_CHECK(flips.as_array()[0].find("predicted")->as_bool());

  // what-if lookback pushes the pod below min age
  Value tighter = Value::object();
  tighter.set("lookback", Value("200000h"));
  Value aged = recorder::replay(capsule, tighter);
  TP_CHECK_EQ(aged.find("flips")->as_array()[0].find("to")->get_string("reason"),
              std::string("BELOW_MIN_AGE"));

  // unknown what-if keys throw (loud, not a silent no-op)
  bool threw = false;
  Value bogus = Value::object();
  bogus.set("bogus", Value(1));
  try {
    recorder::replay(capsule, bogus);
  } catch (const std::exception&) {
    threw = true;
  }
  TP_CHECK(threw);
  recorder::reset_for_test();
}

TP_TEST(recorder_ring_bounds_and_reload) {
  recorder::reset_for_test();
  std::string dir = make_tmpdir();
  recorder::configure(dir, 2);
  recorder::set_run_context(run_config(), "q");
  seal_cycle(1);
  seal_cycle(2);
  seal_cycle(3);

  Value index = recorder::index_json();
  const auto& capsules = index.find("capsules")->as_array();
  TP_CHECK_EQ(capsules.size(), size_t{2});  // keep=2: oldest pruned
  TP_CHECK_EQ(capsules[0].find("cycle")->as_int(), int64_t{2});
  TP_CHECK_EQ(capsules[1].find("cycle")->as_int(), int64_t{3});
  // the pruned capsule's file is gone, the survivors' files are readable
  for (const Value& c : capsules) {
    TP_CHECK(!recorder::capsule_body(c.get_string("id")).empty());
  }

  // restart: reconfigure over the same dir rebuilds the index from disk
  recorder::reset_for_test();
  recorder::configure(dir, 8);
  Value reloaded = recorder::index_json();
  TP_CHECK_EQ(reloaded.find("capsules")->as_array().size(), size_t{2});
  TP_CHECK_EQ(reloaded.find("capsules")->as_array()[0].find("cycle")->as_int(), int64_t{2});
  recorder::reset_for_test();
}

TP_TEST(recorder_capsule_body_rejects_unsafe_ids) {
  recorder::reset_for_test();
  std::string dir = make_tmpdir();
  recorder::configure(dir, 2);
  TP_CHECK_EQ(recorder::capsule_body("../../etc/passwd"), std::string(""));
  TP_CHECK_EQ(recorder::capsule_body("a/b"), std::string(""));
  TP_CHECK_EQ(recorder::capsule_body(""), std::string(""));
  recorder::reset_for_test();
}

TP_TEST(recorder_disabled_hooks_are_noops) {
  recorder::reset_for_test();
  TP_CHECK(!recorder::enabled());
  // none of these may crash or create state while disabled
  recorder::begin_cycle(1, 1000);
  recorder::record_prom_body(1, "x");
  Value pod = old_pod();
  recorder::record_pod(1, "ml/p1", &pod, false, "");
  recorder::arm(1, 0);
  recorder::seal_all();
  TP_CHECK_EQ(recorder::index_json().find("capsules")->as_array().size(), size_t{0});
  recorder::reset_for_test();
}
