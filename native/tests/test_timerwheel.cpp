// Event-engine time plane (native/src/timerwheel.cpp): the hierarchical
// timer wheel and the sliding-window token bucket. Everything here runs
// under an injected clock — determinism (same schedule sequence, same
// expiry order) is the contract the dispatcher and the byte-identity
// suite lean on, so most tests pin exact firing orders, not just sets.
#include "testing.hpp"

#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include "tpupruner/timerwheel.hpp"

namespace timerwheel = tpupruner::timerwheel;
using timerwheel::TokenBucket;
using timerwheel::Wheel;

TP_TEST(timerwheel_fires_in_due_order) {
  Wheel w(0);
  w.schedule("c", 300);
  w.schedule("a", 100);
  w.schedule("b", 200);
  TP_CHECK_EQ(w.size(), static_cast<size_t>(3));
  auto fired = w.advance(250);
  TP_CHECK_EQ(fired.size(), static_cast<size_t>(2));
  TP_CHECK_EQ(fired[0], std::string("a"));
  TP_CHECK_EQ(fired[1], std::string("b"));
  TP_CHECK_EQ(w.size(), static_cast<size_t>(1));
  fired = w.advance(300);
  TP_CHECK_EQ(fired.size(), static_cast<size_t>(1));
  TP_CHECK_EQ(fired[0], std::string("c"));
}

TP_TEST(timerwheel_same_due_tie_breaks_by_key) {
  // Equal deadlines expire in key order — slot layout must never leak
  // into the observable order (determinism across builds).
  Wheel w(0);
  w.schedule("z", 128);
  w.schedule("a", 128);
  w.schedule("m", 128);
  auto fired = w.advance(200);
  TP_CHECK_EQ(fired.size(), static_cast<size_t>(3));
  TP_CHECK_EQ(fired[0], std::string("a"));
  TP_CHECK_EQ(fired[1], std::string("m"));
  TP_CHECK_EQ(fired[2], std::string("z"));
}

TP_TEST(timerwheel_reschedule_replaces_deadline) {
  Wheel w(0);
  w.schedule("k", 100);
  w.schedule("k", 10000);  // re-arm pushes the deadline out
  TP_CHECK_EQ(w.size(), static_cast<size_t>(1));
  TP_CHECK(w.advance(5000).empty());
  auto fired = w.advance(10000);
  TP_CHECK_EQ(fired.size(), static_cast<size_t>(1));
  TP_CHECK_EQ(fired[0], std::string("k"));
}

TP_TEST(timerwheel_cancel_disarms) {
  Wheel w(0);
  w.schedule("k", 100);
  TP_CHECK(w.cancel("k"));
  TP_CHECK(!w.cancel("k"));  // second cancel: not scheduled
  TP_CHECK(w.advance(1000).empty());
  TP_CHECK_EQ(w.next_due(), static_cast<int64_t>(-1));
}

TP_TEST(timerwheel_next_due_tracks_earliest) {
  Wheel w(0);
  TP_CHECK_EQ(w.next_due(), static_cast<int64_t>(-1));
  w.schedule("far", 100000);
  w.schedule("near", 500);
  TP_CHECK_EQ(w.next_due(), static_cast<int64_t>(500));
  (void)w.advance(600);
  TP_CHECK_EQ(w.next_due(), static_cast<int64_t>(100000));
}

TP_TEST(timerwheel_cascade_across_levels) {
  // A deadline beyond level 0's horizon (kTickMs * kSlots = 4096 ms)
  // parks in a coarser level and must cascade down as the clock walks —
  // firing at its due time, not at its level's coarse boundary.
  Wheel w(0);
  const int64_t due = Wheel::kTickMs * Wheel::kSlots * 3 + 777;  // level ≥ 1
  w.schedule("deep", due);
  int64_t t = 0;
  std::vector<std::string> fired;
  while (t < due + Wheel::kTickMs) {
    t += Wheel::kTickMs;  // tick-by-tick: exercises the cascade path
    for (auto& k : w.advance(t)) fired.push_back(k);
    if (!fired.empty()) break;
  }
  TP_CHECK_EQ(fired.size(), static_cast<size_t>(1));
  TP_CHECK_EQ(fired[0], std::string("deep"));
  TP_CHECK(t >= due);                    // never early
  TP_CHECK(t < due + 2 * Wheel::kTickMs);  // and within a tick of due
}

TP_TEST(timerwheel_large_jump_fires_everything_due) {
  // A clock jump far past the tick-walk cap (injected test clocks, first
  // advance after construction) must still fire every due entry, in the
  // same (due, key) order the walk would have produced.
  Wheel w(0);
  w.schedule("b", 5000);
  w.schedule("a", 1000);
  w.schedule("future", 10'000'000);
  auto fired = w.advance(9'000'000);  // >> kTickMs * kSlots * 4
  TP_CHECK_EQ(fired.size(), static_cast<size_t>(2));
  TP_CHECK_EQ(fired[0], std::string("a"));
  TP_CHECK_EQ(fired[1], std::string("b"));
  TP_CHECK_EQ(w.size(), static_cast<size_t>(1));
  TP_CHECK_EQ(w.next_due(), static_cast<int64_t>(10'000'000));
}

TP_TEST(timerwheel_deterministic_across_runs) {
  // Same schedule script → byte-identical firing sequence, regardless of
  // how advances are batched.
  auto run = [](int64_t step) {
    Wheel w(0);
    for (int i = 0; i < 50; ++i) {
      w.schedule("k" + std::to_string(i), (i * 9973) % 20000);
    }
    std::vector<std::string> order;
    for (int64_t t = 0; t <= 20000; t += step) {
      for (auto& k : w.advance(t)) order.push_back(k);
    }
    return order;
  };
  TP_CHECK(run(64) == run(1000));
  TP_CHECK(run(64) == run(20000));  // one big jump
}

TP_TEST(timerwheel_monotonic_clock_never_rewinds) {
  Wheel w(0);
  w.schedule("k", 500);
  (void)w.advance(1000);
  // A smaller now_ms clamps to the current clock instead of rewinding.
  w.schedule("k2", 1100);
  TP_CHECK(w.advance(100).empty());
  auto fired = w.advance(1100);
  TP_CHECK_EQ(fired.size(), static_cast<size_t>(1));
}

TP_TEST(timerwheel_token_bucket_window_slides) {
  TokenBucket b(2, 1000);
  TP_CHECK(b.try_acquire(0));
  TP_CHECK(b.try_acquire(100));
  TP_CHECK(!b.try_acquire(500));  // saturated: 2 grants inside [._, 500]
  TP_CHECK_EQ(b.available(500), static_cast<int64_t>(0));
  // The grant at t=0 ages out exactly after window_ms.
  TP_CHECK(!b.try_acquire(999));
  TP_CHECK(b.try_acquire(1000));
  // Now grants at 100 and 1000 occupy the window.
  TP_CHECK(!b.try_acquire(1050));
  TP_CHECK(b.try_acquire(1100));
}

TP_TEST(timerwheel_token_bucket_zero_capacity_unlimited) {
  // capacity 0 mirrors --max-scale-per-cycle 0: no cap at all.
  TokenBucket b(0, 1000);
  for (int i = 0; i < 1000; ++i) TP_CHECK(b.try_acquire(i));
  TP_CHECK(b.available(500) > 1'000'000);  // effectively unbounded
}

TP_TEST(timerwheel_concurrent_schedule_advance) {
  // The dispatcher advances the wheel while the informer's notify path
  // and (in tests) the sim seam may schedule/cancel concurrently — the
  // TSan tier (just tsan-event) runs exactly this interleaving. The
  // bucket sees the same treatment: producer-thread try_acquire racing
  // /debug/timers stats_json reads.
  Wheel w(0);
  TokenBucket b(100000, 1'000'000);
  std::atomic<bool> stop{false};
  std::atomic<int64_t> clock{0};
  std::atomic<size_t> fired_count{0};
  std::thread advancer([&] {
    while (!stop.load()) {
      fired_count += w.advance(clock.fetch_add(Wheel::kTickMs)).size();
      (void)w.stats_json();
      (void)b.stats_json();
    }
  });
  std::vector<std::thread> schedulers;
  for (int t = 0; t < 3; ++t) {
    schedulers.emplace_back([&, t] {
      for (int i = 0; i < 2000; ++i) {
        std::string key = "t" + std::to_string(t) + "-" + std::to_string(i % 97);
        w.schedule(key, clock.load() + (i % 50) * Wheel::kTickMs);
        if (i % 7 == 0) (void)w.cancel(key);
        (void)b.try_acquire(clock.load());
        (void)w.next_due();
      }
    });
  }
  for (auto& th : schedulers) th.join();
  stop.store(true);
  advancer.join();
  // Drain: everything still armed fires on one final far-future advance.
  fired_count += w.advance(clock.load() + 100'000'000).size();
  TP_CHECK_EQ(w.size(), static_cast<size_t>(0));
  TP_CHECK(fired_count.load() > 0);
}
