#include "testing.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/util.hpp"

using namespace tpupruner;
using json::Value;

TP_TEST(json_parse_scalars) {
  TP_CHECK(Value::parse("null").is_null());
  TP_CHECK_EQ(Value::parse("true").as_bool(), true);
  TP_CHECK_EQ(Value::parse("false").as_bool(), false);
  TP_CHECK_EQ(Value::parse("42").as_int(), 42);
  TP_CHECK_EQ(Value::parse("-7").as_int(), -7);
  TP_CHECK_EQ(Value::parse("2.5").as_double(), 2.5);
  TP_CHECK_EQ(Value::parse("1e3").as_double(), 1000.0);
  TP_CHECK_EQ(Value::parse("\"hi\"").as_string(), std::string("hi"));
}

TP_TEST(json_parse_structures) {
  Value v = Value::parse(R"({"a": [1, 2, {"b": "c"}], "d": null})");
  TP_CHECK(v.is_object());
  TP_CHECK_EQ(v.find("a")->as_array().size(), size_t(3));
  TP_CHECK_EQ(v.at_path("a")->as_array()[2].get_string("b"), std::string("c"));
  TP_CHECK(v.find("d")->is_null());
  TP_CHECK(v.find("missing") == nullptr);
}

TP_TEST(json_string_escapes) {
  Value v = Value::parse(R"("line\n\t\"q\" é 😀")");
  const std::string& s = v.as_string();
  TP_CHECK(s.find('\n') != std::string::npos);
  TP_CHECK(s.find("\"q\"") != std::string::npos);
  TP_CHECK(s.find("\xc3\xa9") != std::string::npos);      // é
  TP_CHECK(s.find("\xf0\x9f\x98\x80") != std::string::npos);  // 😀 via surrogate pair
}

TP_TEST(json_roundtrip) {
  const char* text = R"({"metadata":{"name":"p","namespace":"ns"},"spec":{"replicas":0},"x":[1,2.5,"s",null,true]})";
  Value v = Value::parse(text);
  Value v2 = Value::parse(v.dump());
  TP_CHECK(v == v2);
}

TP_TEST(json_dump_compact_and_pretty) {
  Value v = Value::object();
  v.set("b", Value(1)).set("a", Value("x"));
  TP_CHECK_EQ(v.dump(), std::string(R"({"a":"x","b":1})"));
  TP_CHECK(v.dump(2).find("\n  \"a\": \"x\"") != std::string::npos);
}

TP_TEST(json_parse_errors) {
  bool threw = false;
  try {
    Value::parse("{\"a\": }");
  } catch (const json::ParseError&) {
    threw = true;
  }
  TP_CHECK(threw);
  threw = false;
  try {
    Value::parse("[1,2]trailing");
  } catch (const json::ParseError&) {
    threw = true;
  }
  TP_CHECK(threw);
}

TP_TEST(json_strict_number_grammar) {
  for (const char* bad : {".", ".5", "1.", "01", "1e+", "1e", "-", "+1"}) {
    bool threw = false;
    try {
      Value::parse(bad);
    } catch (const json::ParseError&) {
      threw = true;
    }
    TP_CHECK(threw);
  }
  TP_CHECK_EQ(Value::parse("0.5").as_double(), 0.5);
  TP_CHECK_EQ(Value::parse("-0.5e+2").as_double(), -50.0);
  // int64 overflow degrades to double rather than failing
  TP_CHECK(Value::parse("99999999999999999999").is_number());
}

TP_TEST(json_rejects_lone_low_surrogate) {
  bool threw = false;
  try {
    Value::parse("\"\\udc00\"");
  } catch (const json::ParseError&) {
    threw = true;
  }
  TP_CHECK(threw);
}

TP_TEST(json_at_path_nested) {
  Value v = Value::parse(R"({"spec":{"predictor":{"minReplicas":0}}})");
  TP_CHECK_EQ(v.at_path("spec.predictor.minReplicas")->as_int(), 0);
  TP_CHECK(v.at_path("spec.missing.x") == nullptr);
}

TP_TEST(json_copy_on_write_isolation) {
  Value a = Value::parse(R"({"k":[1]})");
  Value b = a;
  b.set("k", Value(2));
  TP_CHECK(a.find("k")->is_array());
  TP_CHECK_EQ(b.find("k")->as_int(), 2);
}

TP_TEST(util_rfc3339_roundtrip) {
  int64_t t = 1785312000;  // 2026-07-29T08:00:00Z
  std::string s = util::format_rfc3339(t);
  TP_CHECK_EQ(s, std::string("2026-07-29T08:00:00Z"));
  auto parsed = util::parse_rfc3339(s);
  TP_CHECK(parsed.has_value());
  TP_CHECK_EQ(*parsed, t);
}

TP_TEST(util_rfc3339_offsets_and_fractions) {
  auto a = util::parse_rfc3339("2026-07-29T08:00:00.123456Z");
  TP_CHECK(a.has_value());
  TP_CHECK_EQ(*a, 1785312000);
  auto b = util::parse_rfc3339("2026-07-29T10:00:00+02:00");
  TP_CHECK(b.has_value());
  TP_CHECK_EQ(*b, 1785312000);
  auto c = util::parse_rfc3339("2026-07-29T06:00:00-02:00");
  TP_CHECK(c.has_value());
  TP_CHECK_EQ(*c, 1785312000);
  auto d = util::parse_rfc3339("2026-07-29T10:00:00+0200");  // colon-less offset
  TP_CHECK(d.has_value());
  TP_CHECK_EQ(*d, 1785312000);
  TP_CHECK(!util::parse_rfc3339("2026-07-29T10:00:00+2").has_value());
  TP_CHECK(!util::parse_rfc3339("2026-07-29T10:00:00+99:00").has_value());
  TP_CHECK(!util::parse_rfc3339("garbage").has_value());
}

TP_TEST(util_random_hex32_shape_and_uniqueness) {
  std::string a = util::random_hex32();
  std::string b = util::random_hex32();
  TP_CHECK_EQ(a.size(), size_t(32));
  TP_CHECK(a != b);
  for (char c : a) TP_CHECK((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f'));
}

TP_TEST(util_split_and_trim) {
  auto parts = util::split("a,b,,c", ',');
  TP_CHECK_EQ(parts.size(), size_t(4));
  TP_CHECK_EQ(parts[2], std::string(""));
  TP_CHECK_EQ(util::trim("  x \n"), std::string("x"));
  TP_CHECK(util::starts_with("https://x", "https://"));
}

TP_TEST(util_url_encode) {
  TP_CHECK_EQ(util::url_encode("a b&c=d"), std::string("a%20b%26c%3Dd"));
  TP_CHECK_EQ(util::url_encode("safe-._~"), std::string("safe-._~"));
}

// ── arena / zero-copy Doc parser (the transport hot-path decoder) ───────

TP_TEST(json_doc_parity_on_wire_shapes) {
  // The two real wire shapes the zero-copy path decodes every cycle: a
  // Prometheus matrix and a pod LIST page. Doc::parse must produce a tree
  // indistinguishable from Value::parse on the same bytes.
  const char* bodies[] = {
      R"({"status":"success","data":{"resultType":"vector","result":[
        {"metric":{"pod":"t-0","namespace":"ml"},"value":[1722249000.123,"0"]},
        {"metric":{"exported_pod":"t-1"},"value":[1722249000.123,"0.5"]}]}})",
      R"({"kind":"PodList","apiVersion":"v1","metadata":{"resourceVersion":"812",
        "continue":"tok"},"items":[{"metadata":{"name":"w-0","namespace":"tpu",
        "creationTimestamp":"2026-07-28T10:00:00Z"},"spec":{"containers":[
        {"resources":{"requests":{"google.com/tpu":"4"}}}]},
        "status":{"phase":"Running"}}]})",
      R"([0,-1,1e308,-2.5e-308,9223372036854775807,"  \u00e9 😀\\\"\n",null,true,false])",
  };
  for (const char* text : bodies) {
    json::DocPtr doc = json::Doc::parse(text);
    Value v = Value::parse(text);
    TP_CHECK(doc->to_value() == v);
    TP_CHECK_EQ(doc->to_value().dump(), v.dump());
  }
}

TP_TEST(json_doc_cursor_walk) {
  json::DocPtr doc = json::Doc::parse(
      R"({"metadata":{"name":"p","labels":{"a":"1"}},"items":[10,20,30],"n":2.5})");
  json::Doc::Node root = doc->root();
  TP_CHECK(root.is_object());
  TP_CHECK_EQ(root.size(), size_t(3));
  TP_CHECK_EQ(root.at_path("metadata.name")->as_string(), std::string("p"));
  TP_CHECK_EQ(root.find("metadata")->get_string("name"), std::string_view("p"));
  TP_CHECK(!root.find("missing").has_value());
  json::Doc::Node items = *root.find("items");
  TP_CHECK_EQ(items.size(), size_t(3));
  TP_CHECK_EQ(items.child(2).as_int(), int64_t(30));
  // O(1) sibling stepping must visit the same children as child(i).
  json::Doc::Node it = items.first_child();
  int64_t sum = 0;
  for (size_t i = 0; i < items.size(); ++i, it = it.next_sibling()) sum += it.as_int();
  TP_CHECK_EQ(sum, int64_t(60));
  auto [key, n] = root.member(2);
  TP_CHECK_EQ(key, std::string_view("n"));
  TP_CHECK_EQ(n.as_double(), 2.5);
  // Stable (doc, index) handles — the informer store's entry shape.
  uint32_t idx = root.find("metadata")->index();
  TP_CHECK_EQ(doc->node(idx).get_string("name"), std::string_view("p"));
}

TP_TEST(json_doc_strings_view_into_body) {
  // The zero-copy property itself: an escape-free string payload is a
  // view into the owned response buffer, not a copy; escaped strings
  // decode into the side arena (and still compare equal to Value::parse).
  json::DocPtr doc = json::Doc::parse(R"({"plain":"abcdef","esc":"a\nb"})");
  std::string_view plain = doc->root().find("plain")->as_sv();
  const std::string& body = doc->body();
  TP_CHECK(plain.data() >= body.data() && plain.data() < body.data() + body.size());
  TP_CHECK_EQ(doc->root().find("esc")->as_string(), std::string("a\nb"));
}

TP_TEST(json_doc_error_parity) {
  // Accept/reject must agree with Value::parse on the edge corpus the
  // Python parity tests also pin: truncations, bad escapes, lone
  // surrogates, trailing garbage, depth bombs.
  const char* cases[] = {
      "", "{", "[1,", "{\"a\":}", "\"unterminated", "\"bad\\q\"",
      "\"\\ud800\"", "01", "1.2.3", "[1] trailing", "nul", "tru",
      R"({"a":1,"a":2,"b":3})", "[[[[[[[[[[1]]]]]]]]]]", "  42  ",
  };
  for (const char* text : cases) {
    bool value_ok = true, doc_ok = true;
    Value v;
    try {
      v = Value::parse(text);
    } catch (const json::ParseError&) {
      value_ok = false;
    }
    json::DocPtr doc;
    try {
      doc = json::Doc::parse(text);
    } catch (const json::ParseError&) {
      doc_ok = false;
    }
    TP_CHECK_EQ(doc_ok, value_ok);
    if (value_ok) {
      TP_CHECK(doc->to_value() == v);
      // duplicate keys: last occurrence wins in BOTH parsers
      if (v.is_object() && v.find("a")) TP_CHECK_EQ(doc->root().find("a")->as_int(), v.find("a")->as_int());
    }
  }
}
