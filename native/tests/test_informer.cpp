// Informer store + event application (native/include/tpupruner/informer.hpp).
// The pure core the reflector thread drives: these tests pin the event
// ordering, bookmark, and relist-replace semantics without a server (the
// Python tier covers the live list+watch loop against the fake apiserver).
// Concurrency (store reads under reflector writes) runs under TSan via
// `just test-tsan`.
#include "testing.hpp"

#include <thread>
#include <vector>

#include "tpupruner/informer.hpp"

using tpupruner::informer::ClusterCache;
using tpupruner::informer::Reflector;
using tpupruner::informer::ResourceSpec;
using tpupruner::informer::Store;
using tpupruner::informer::spec_for;
using tpupruner::json::Value;
namespace k8s = tpupruner::k8s;

namespace {

// A client that never talks: apply_* methods under test issue no requests.
const k8s::Client& offline_client() {
  static k8s::Client client = [] {
    k8s::Config cfg;
    cfg.api_url = "http://127.0.0.1:1";
    return k8s::Client(std::move(cfg));
  }();
  return client;
}

Value pod_event(const char* type, const char* ns, const char* name, const char* rv,
                const char* phase = "Running") {
  return Value::parse(std::string(R"({"type":")") + type +
                      R"(","object":{"apiVersion":"v1","kind":"Pod","metadata":{"namespace":")" +
                      ns + R"(","name":")" + name + R"(","resourceVersion":")" + rv +
                      R"("},"status":{"phase":")" + phase + R"("}}})");
}

}  // namespace

TP_TEST(informer_store_replace_and_lookup) {
  Store store;
  std::map<std::string, Value> snapshot;
  snapshot["/api/v1/namespaces/ml/pods/a"] = Value::parse(R"({"metadata":{"name":"a"}})");
  snapshot["/api/v1/namespaces/ml/pods/b"] = Value::parse(R"({"metadata":{"name":"b"}})");
  store.replace(std::move(snapshot));
  TP_CHECK_EQ(store.size(), size_t{2});
  TP_CHECK(store.get("/api/v1/namespaces/ml/pods/a").has_value());
  TP_CHECK(!store.get("/api/v1/namespaces/ml/pods/zzz").has_value());
  // replace is wholesale: objects deleted while the watch was down vanish
  store.replace({});
  TP_CHECK_EQ(store.size(), size_t{0});
  TP_CHECK(!store.get("/api/v1/namespaces/ml/pods/a").has_value());
}

TP_TEST(informer_event_ordering_added_modified_deleted) {
  Reflector r(offline_client(), *spec_for("pods"));
  TP_CHECK(r.apply_event(pod_event("ADDED", "ml", "p", "5", "Pending")));
  auto obj = r.get("/api/v1/namespaces/ml/pods/p");
  TP_CHECK(obj.has_value());
  TP_CHECK_EQ(obj->at_path("status.phase")->as_string(), std::string("Pending"));

  // MODIFIED replaces the stored object (last write wins, server order)
  TP_CHECK(r.apply_event(pod_event("MODIFIED", "ml", "p", "6", "Running")));
  obj = r.get("/api/v1/namespaces/ml/pods/p");
  TP_CHECK_EQ(obj->at_path("status.phase")->as_string(), std::string("Running"));

  TP_CHECK(r.apply_event(pod_event("DELETED", "ml", "p", "7")));
  TP_CHECK(!r.get("/api/v1/namespaces/ml/pods/p").has_value());

  auto stats = r.stats();
  TP_CHECK_EQ(stats.adds, uint64_t{1});
  TP_CHECK_EQ(stats.updates, uint64_t{1});
  TP_CHECK_EQ(stats.deletes, uint64_t{1});
  TP_CHECK_EQ(stats.resource_version, std::string("7"));
}

TP_TEST(informer_bookmark_advances_rv_without_touching_objects) {
  Reflector r(offline_client(), *spec_for("pods"));
  TP_CHECK(r.apply_event(pod_event("ADDED", "ml", "p", "5")));
  Value bookmark = Value::parse(
      R"({"type":"BOOKMARK","object":{"kind":"Pod","metadata":{"resourceVersion":"42"}}})");
  TP_CHECK(r.apply_event(bookmark));
  auto stats = r.stats();
  TP_CHECK_EQ(stats.bookmarks, uint64_t{1});
  TP_CHECK_EQ(stats.resource_version, std::string("42"));
  TP_CHECK_EQ(stats.objects, uint64_t{1});  // bookmark carries no object delta
}

TP_TEST(informer_error_event_demands_relist) {
  Reflector r(offline_client(), *spec_for("pods"));
  Value gone = Value::parse(
      R"({"type":"ERROR","object":{"kind":"Status","code":410,"message":"too old"}})");
  // false = the stream can't be trusted; the reflector loop relists
  TP_CHECK(!r.apply_event(gone));
}

TP_TEST(informer_unknown_event_type_is_ignored) {
  Reflector r(offline_client(), *spec_for("pods"));
  Value odd = Value::parse(R"({"type":"WAT","object":{"metadata":{"name":"x"}}})");
  TP_CHECK(r.apply_event(odd));  // no relist, no store change
  TP_CHECK_EQ(r.stats().objects, uint64_t{0});
}

TP_TEST(informer_apply_list_adopts_snapshot_and_rv) {
  Reflector r(offline_client(), *spec_for("pods"));
  // pre-existing entry that the relist snapshot no longer contains
  TP_CHECK(r.apply_event(pod_event("ADDED", "ml", "stale", "3")));
  Value list = Value::parse(R"({
    "kind": "List", "metadata": {"resourceVersion": "9"},
    "items": [
      {"metadata": {"namespace": "ml", "name": "fresh", "resourceVersion": "8"}},
      {"metadata": {"namespace": "other", "name": "fresh2", "resourceVersion": "9"}}
    ]})");
  r.apply_list(list);
  TP_CHECK(r.synced());
  TP_CHECK(!r.get("/api/v1/namespaces/ml/pods/stale").has_value());
  TP_CHECK(r.get("/api/v1/namespaces/ml/pods/fresh").has_value());
  TP_CHECK(r.get("/api/v1/namespaces/other/pods/fresh2").has_value());
  auto stats = r.stats();
  TP_CHECK_EQ(stats.resource_version, std::string("9"));
  TP_CHECK_EQ(stats.relists, uint64_t{1});
}

TP_TEST(informer_object_path_requires_full_metadata) {
  Reflector pods(offline_client(), *spec_for("pods"));
  Value no_ns = Value::parse(R"({"metadata":{"name":"x"}})");
  TP_CHECK_EQ(pods.object_path_of(no_ns), std::string(""));
  Reflector rs(offline_client(), *spec_for("replicasets"));
  Value full = Value::parse(R"({"metadata":{"namespace":"ml","name":"rs1"}})");
  TP_CHECK_EQ(rs.object_path_of(full),
              std::string("/apis/apps/v1/namespaces/ml/replicasets/rs1"));
}

TP_TEST(informer_cluster_cache_routes_by_path_shape) {
  ClusterCache cache(offline_client(),
                     {*spec_for("pods"), *spec_for("replicasets"), *spec_for("jobsets")});
  // nothing synced yet: every lookup says "ask the API server"
  TP_CHECK(!cache.get("/api/v1/namespaces/ml/pods/p").has_value());
  TP_CHECK(!cache.all_synced());
  TP_CHECK(!cache.pods_synced());
  // unwatched resources and unparseable paths also answer nullopt
  TP_CHECK(!cache.get("/apis/kubeflow.org/v1/namespaces/ml/notebooks/n").has_value());
  TP_CHECK(!cache.get("/not/an/object/path").has_value());
}

TP_TEST(informer_store_concurrent_readers_and_writer) {
  // The daemon's shape: resolve fan-out reads while the reflector applies
  // events. Run readers against a writer; TSan (just test-tsan) turns any
  // unlocked access into a failure.
  Store store;
  std::atomic<bool> done{false};
  std::thread writer([&] {
    for (int i = 0; i < 500; ++i) {
      std::string key = "/api/v1/namespaces/ml/pods/p" + std::to_string(i % 16);
      store.upsert(key, Value::parse(R"({"metadata":{"name":"p"}})"));
      if (i % 3 == 0) store.erase(key);
    }
    done.store(true);
  });
  std::vector<std::thread> readers;
  for (int t = 0; t < 4; ++t) {
    readers.emplace_back([&] {
      while (!done.load()) {
        for (int i = 0; i < 16; ++i) {
          auto v = store.get("/api/v1/namespaces/ml/pods/p" + std::to_string(i));
          if (v) TP_CHECK(v->at_path("metadata.name") != nullptr);
        }
      }
    });
  }
  writer.join();
  for (auto& t : readers) t.join();
}

TP_TEST(informer_relist_requests_coalesce) {
  // A 410/ERROR landing while a relist is already pending must not queue
  // a second relist: one LIST services every request that accumulated
  // while it was in flight.
  Reflector r(offline_client(), *spec_for("pods"));
  Value gone = Value::parse(
      R"({"type":"ERROR","object":{"kind":"Status","code":410,"message":"too old"}})");
  TP_CHECK(!r.apply_event(gone));
  TP_CHECK(r.relist_pending());
  TP_CHECK_EQ(r.stats().relist_requests, uint64_t{1});
  // second 410 before the relist lands: coalesced, still one request
  TP_CHECK(!r.apply_event(gone));
  TP_CHECK_EQ(r.stats().relist_requests, uint64_t{1});
  // the relist LIST services the request
  r.apply_list(Value::parse(
      R"({"kind":"List","metadata":{"resourceVersion":"12"},"items":[]})"));
  TP_CHECK(!r.relist_pending());
  TP_CHECK_EQ(r.stats().relists, uint64_t{1});
  // a NEW 410 after recovery opens a fresh request
  TP_CHECK(!r.apply_event(gone));
  TP_CHECK_EQ(r.stats().relist_requests, uint64_t{2});
}

TP_TEST(informer_concurrent_410_and_relist_is_race_free) {
  // The satellite contract (ISSUE 8): a watch 410 arriving while a LIST
  // is in flight must neither race (TSan-clean: resource_version_ and the
  // stats block are shared between the two paths) nor double-relist.
  // One thread replays relist LISTs, another storms 410 ERROR events and
  // watch frames; afterwards the counters must show every LIST applied
  // and coalesced (not stacked) relist requests.
  Reflector r(offline_client(), *spec_for("pods"));
  constexpr int kLists = 200;
  constexpr int kEvents = 500;
  std::thread lister([&] {
    for (int i = 0; i < kLists; ++i) {
      r.apply_list(Value::parse(
          R"({"kind":"List","metadata":{"resourceVersion":")" + std::to_string(1000 + i) +
          R"("},"items":[{"metadata":{"namespace":"ml","name":"p0","resourceVersion":")" +
          std::to_string(1000 + i) + R"("}}]})"));
    }
  });
  std::atomic<bool> error_event_kept_stream{false};  // must stay false
  std::thread eventer([&] {
    Value gone = Value::parse(
        R"({"type":"ERROR","object":{"kind":"Status","code":410,"message":"too old"}})");
    for (int i = 0; i < kEvents; ++i) {
      if (r.apply_event(gone)) error_event_kept_stream.store(true);
      r.apply_event(pod_event("MODIFIED", "ml", "p0", std::to_string(2000 + i).c_str()));
    }
  });
  lister.join();
  eventer.join();
  TP_CHECK(!error_event_kept_stream.load());
  auto stats = r.stats();
  TP_CHECK_EQ(stats.relists, uint64_t{kLists});
  // Coalescing bound: between two applied LISTs at most ONE request can
  // open (the exchange gate), so requests can never exceed LISTs + 1.
  TP_CHECK(stats.relist_requests <= uint64_t{kLists + 1});
  TP_CHECK(stats.relist_requests >= 1);
  TP_CHECK(r.synced());
}
