// Delta-federation protocol units: the member-side change journal
// (epoch monotonicity, quiesced answers, coalescing, journal-window
// overflow → resync, generation mismatch → resync, decisions ring
// reconstruction) and the hub-side apply_delta state machine — including
// the property the whole tentpole rests on: after ANY publish/poll
// interleaving, the hub's reconstructed documents EQUAL the member's
// full renders. The e2e surface (real hub binary over scripted members)
// rides tests/test_fleet_delta.py; the concurrency shape (publishers vs
// long-pollers) runs here under `just tsan-fleet`.
#include <algorithm>
#include <atomic>
#include <chrono>
#include <map>
#include <string>
#include <thread>
#include <vector>

#include "testing.hpp"
#include "tpupruner/delta.hpp"
#include "tpupruner/json.hpp"

namespace delta = tpupruner::delta;
using tpupruner::json::Value;

namespace {

// A mutable member-surface fixture the journal renders from.
struct Member {
  Value workloads = Value::object();
  Value signals = Value::object();
  Value decisions = Value::object();
  std::map<std::string, Value> rows;
  std::vector<Value> dec_records;
  int64_t dec_capacity = 4;
  int64_t dec_dropped = 0;

  Member() {
    signals.set("cluster", Value("unit"));
    signals.set("enabled", Value(true));
    signals.set("coverage_ratio", Value(1.0));
    rebuild();
  }

  void set_row(const std::string& key, double reclaimed) {
    Value row = Value::object();
    row.set("workload", Value(key));
    row.set("kind", Value("Deployment"));
    row.set("namespace", Value("ml"));
    row.set("name", Value(key));
    row.set("chips", Value(static_cast<int64_t>(4)));
    row.set("idle_seconds", Value(1.0));
    row.set("reclaimed_chip_seconds", Value(reclaimed));
    rows[key] = std::move(row);
    rebuild();
  }

  void remove_row(const std::string& key) {
    rows.erase(key);
    rebuild();
  }

  void append_decision(const std::string& pod) {
    Value rec = Value::object();
    rec.set("pod", Value(pod));
    dec_records.push_back(std::move(rec));
    while (dec_records.size() > static_cast<size_t>(dec_capacity)) {
      dec_records.erase(dec_records.begin());
      ++dec_dropped;
    }
    rebuild();
  }

  void rebuild() {
    // Member array order: key-ascending then stable reclaimed-descending
    // (ledger::workloads_json's comparator).
    std::vector<const Value*> ordered;
    for (const auto& [k, v] : rows) ordered.push_back(&v);
    std::stable_sort(ordered.begin(), ordered.end(), [](const Value* a, const Value* b) {
      return a->find("reclaimed_chip_seconds")->as_double() >
             b->find("reclaimed_chip_seconds")->as_double();
    });
    Value arr = Value::array();
    double reclaimed = 0;
    for (const Value* r : ordered) {
      reclaimed += r->find("reclaimed_chip_seconds")->as_double();
      arr.push_back(*r);
    }
    Value totals = Value::object();
    totals.set("idle_seconds", Value(static_cast<double>(rows.size())));
    totals.set("active_seconds", Value(0.0));
    totals.set("reclaimed_chip_seconds", Value(reclaimed));
    workloads = Value::object();
    workloads.set("cluster", Value("unit"));
    workloads.set("sort", Value("reclaimed"));
    workloads.set("tracked", Value(static_cast<int64_t>(rows.size())));
    workloads.set("totals", std::move(totals));
    workloads.set("workloads", std::move(arr));

    Value dec_arr = Value::array();
    for (const Value& r : dec_records) dec_arr.push_back(r);
    decisions = Value::object();
    decisions.set("cluster", Value("unit"));
    decisions.set("capacity", Value(dec_capacity));
    decisions.set("dropped", Value(dec_dropped));
    decisions.set("decisions", std::move(dec_arr));
  }
};

struct Harness {
  Member member;
  delta::Journal journal;
  delta::DeltaState state;
  delta::MemberDocs docs;

  Harness() {
    journal.set_renderers(delta::Renderers{
        [this] { return member.workloads; },
        [this] { return member.signals; },
        [this] { return member.decisions; },
    });
    // Activate (the first poll primes the journal from the renderers).
  }

  Value poll(int64_t wait_ms = 0) {
    std::string q = delta::cursor_query(state, wait_ms);
    Value resp = Value::parse(journal.handle_request(q, nullptr));
    delta::ApplyResult res = delta::apply_delta(state, resp, docs);
    TP_CHECK(res.ok);
    return resp;
  }

  // The tentpole invariant: reconstruction equals the member's renders.
  void check_equal() {
    TP_CHECK_EQ(docs.workloads.dump(), member.workloads.dump());
    TP_CHECK_EQ(docs.signals.dump(), member.signals.dump());
    TP_CHECK_EQ(docs.decisions.dump(), member.decisions.dump());
  }
};

}  // namespace

TP_TEST(delta_first_poll_serves_full_snapshot) {
  Harness h;
  h.member.set_row("Deployment/ml/a", 5.0);
  Value resp = h.poll();
  TP_CHECK(resp.find("full") != nullptr);
  TP_CHECK(resp.find("resync") == nullptr);  // first contact, not a resync
  h.check_equal();
}

TP_TEST(delta_quiesced_poll_is_tiny_and_changeless) {
  Harness h;
  h.member.set_row("Deployment/ml/a", 5.0);
  h.poll();
  std::string q = delta::cursor_query(h.state, 0);
  std::string body = h.journal.handle_request(q, nullptr);
  TP_CHECK(body.size() < 120);  // {"cluster","epoch","gen","since"} only
  Value resp = Value::parse(body);
  TP_CHECK(resp.find("surfaces") == nullptr);
  delta::ApplyResult res = delta::apply_delta(h.state, resp, h.docs);
  TP_CHECK(res.ok);
  TP_CHECK(!res.changed);
}

TP_TEST(delta_row_churn_ships_only_changed_rows) {
  Harness h;
  for (int i = 0; i < 8; ++i) h.member.set_row("Deployment/ml/r" + std::to_string(i), i);
  h.poll();
  h.journal.publish();
  h.member.set_row("Deployment/ml/r3", 100.0);
  h.journal.publish();
  Value resp = h.poll();
  const Value* wl = resp.find("surfaces")->find("workloads");
  TP_CHECK(wl != nullptr);
  TP_CHECK_EQ(wl->find("upserts")->as_array().size(), size_t{1});
  TP_CHECK_EQ(wl->find("upserts")->as_array()[0].get_string("workload"),
              "Deployment/ml/r3");
  h.check_equal();  // incl. the re-sorted array order (r3 now leads)
}

TP_TEST(delta_coalesces_repeated_changes_to_one_row) {
  Harness h;
  h.member.set_row("Deployment/ml/a", 1.0);
  h.poll();
  for (int i = 0; i < 5; ++i) {
    h.member.set_row("Deployment/ml/a", 10.0 + i);
    h.journal.publish();
  }
  Value resp = h.poll();
  // Five publishes between polls, ONE upsert: latest-state per key, the
  // informer's coalescing rule at the fleet layer.
  const Value* wl = resp.find("surfaces")->find("workloads");
  TP_CHECK_EQ(wl->find("upserts")->as_array().size(), size_t{1});
  TP_CHECK_EQ(wl->find("upserts")->as_array()[0].find("reclaimed_chip_seconds")->as_double(),
              14.0);
  h.check_equal();
}

TP_TEST(delta_remove_ships_tombstone) {
  Harness h;
  h.member.set_row("Deployment/ml/a", 1.0);
  h.member.set_row("Deployment/ml/b", 2.0);
  h.poll();
  h.member.remove_row("Deployment/ml/a");
  h.journal.publish();
  Value resp = h.poll();
  const Value* wl = resp.find("surfaces")->find("workloads");
  TP_CHECK_EQ(wl->find("removes")->as_array().size(), size_t{1});
  TP_CHECK_EQ(wl->find("removes")->as_array()[0].as_string(), "Deployment/ml/a");
  h.check_equal();
}

TP_TEST(delta_journal_overflow_forces_resync) {
  Harness h;
  h.journal.set_log_cap(4);
  h.member.set_row("Deployment/ml/a", 1.0);
  h.poll();
  // Blow far past the 4-entry window between polls.
  for (int i = 0; i < 16; ++i) {
    h.member.set_row("Deployment/ml/x" + std::to_string(i), i);
    h.journal.publish();
  }
  Value resp = h.poll();
  const Value* r = resp.find("resync");
  TP_CHECK(r && r->as_bool());
  TP_CHECK(resp.find("full") != nullptr);
  h.check_equal();
}

TP_TEST(delta_generation_mismatch_forces_resync) {
  Harness h;
  h.member.set_row("Deployment/ml/a", 1.0);
  h.poll();
  // Member restart: journal reborn, epoch space reset, surfaces changed.
  h.journal.reset_for_test();
  h.journal.set_renderers(delta::Renderers{
      [&h] { return h.member.workloads; },
      [&h] { return h.member.signals; },
      [&h] { return h.member.decisions; },
  });
  h.member.set_row("Deployment/ml/b", 9.0);
  Value resp = h.poll();
  const Value* r = resp.find("resync");
  TP_CHECK(r && r->as_bool());
  h.check_equal();
}

TP_TEST(delta_decisions_ring_reconstructs_through_wrap) {
  Harness h;  // capacity 4
  h.member.append_decision("ml/p1");
  h.poll();
  // Append 6 records (> capacity): the hub's ring must wrap identically,
  // including the dropped count.
  for (int i = 2; i <= 7; ++i) h.member.append_decision("ml/p" + std::to_string(i));
  h.journal.publish();
  Value resp = h.poll();
  const Value* dec = resp.find("surfaces")->find("decisions");
  TP_CHECK(dec->find("replace")->as_bool());  // every retained record is fresh
  h.check_equal();
  // And a partial append after the wrap extends rather than replaces.
  h.member.append_decision("ml/p8");
  h.journal.publish();
  Value resp2 = h.poll();
  const Value* dec2 = resp2.find("surfaces")->find("decisions");
  TP_CHECK(!dec2->find("replace")->as_bool());
  TP_CHECK_EQ(dec2->find("appends")->as_array().size(), size_t{1});
  h.check_equal();
}

TP_TEST(delta_signals_ship_whole_doc_on_change) {
  Harness h;
  h.poll();
  h.member.signals.set("coverage_ratio", Value(0.25));
  h.member.signals.set("brownout", Value(true));
  h.journal.publish();
  Value resp = h.poll();
  TP_CHECK(resp.find("surfaces")->find("signals") != nullptr);
  h.check_equal();
}

TP_TEST(delta_randomized_interleaving_reconstructs_exactly) {
  // Deterministic pseudo-random walk over every mutation kind with a
  // small journal window (resyncs happen en route): after EVERY poll the
  // reconstruction must equal the member's renders bit for bit.
  Harness h;
  h.journal.set_log_cap(8);
  uint32_t rng = 0xC0FFEE;
  auto next = [&rng] { return rng = rng * 1664525u + 1013904223u; };
  for (int step = 0; step < 200; ++step) {
    switch (next() % 5) {
      case 0:
        h.member.set_row("Deployment/ml/r" + std::to_string(next() % 12),
                         static_cast<double>(next() % 1000) / 10.0);
        break;
      case 1:
        h.member.remove_row("Deployment/ml/r" + std::to_string(next() % 12));
        break;
      case 2:
        h.member.append_decision("ml/p" + std::to_string(next() % 50));
        break;
      case 3:
        h.member.signals.set("coverage_ratio",
                             Value(static_cast<double>(next() % 100) / 100.0));
        break;
      case 4:
        break;  // quiesced publish
    }
    h.journal.publish();
    if (next() % 3 == 0) {  // poll only sometimes: deltas batch up
      h.poll();
      h.check_equal();
    }
  }
  h.poll();
  h.check_equal();
}

TP_TEST(delta_concurrent_publish_and_longpoll_is_race_free) {
  // The TSan target (`just tsan-fleet`): publishers hammer the journal
  // while long-pollers wait/drain concurrently.
  Harness h;
  h.poll();
  std::atomic<bool> stop{false};
  std::thread publisher([&] {
    for (int i = 0; i < 200 && !stop.load(); ++i) {
      h.member.set_row("Deployment/ml/hot", static_cast<double>(i));
      h.journal.publish();
    }
    stop.store(true);
  });
  std::thread poller([&] {
    delta::DeltaState st;
    delta::MemberDocs docs;
    while (!stop.load()) {
      Value resp = Value::parse(
          h.journal.handle_request(delta::cursor_query(st, 5), nullptr));
      delta::apply_delta(st, resp, docs);
    }
  });
  publisher.join();
  stop.store(true);
  h.journal.wake_all();
  poller.join();
  h.poll();
  h.check_equal();
}

TP_TEST(delta_cursor_query_shapes) {
  delta::DeltaState st;
  TP_CHECK_EQ(delta::cursor_query(st, 0), "since=-1");
  st.primed = true;
  st.gen = "123-9";
  st.epoch = 42;
  TP_CHECK_EQ(delta::cursor_query(st, 0), "since=42&gen=123-9");
  TP_CHECK_EQ(delta::cursor_query(st, 2500), "since=42&gen=123-9&wait_ms=2500");
}

TP_TEST(delta_longpoll_wakes_on_publish) {
  Harness h;
  h.member.set_row("Deployment/ml/a", 1.0);
  h.poll();
  std::thread waker([&h] {
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    h.member.set_row("Deployment/ml/a", 2.0);
    h.journal.publish();
  });
  auto t0 = std::chrono::steady_clock::now();
  Value resp = h.poll(5000);  // would park 5s without the wake
  double waited = std::chrono::duration<double>(
      std::chrono::steady_clock::now() - t0).count();
  waker.join();
  TP_CHECK(waited < 3.0);
  TP_CHECK(resp.find("surfaces") != nullptr);
  h.check_equal();
}
