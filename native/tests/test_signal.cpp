// Signal-quality watchdog units: verdict thresholds, coverage math,
// serialize round-trip, evidence-query shape, and the export registry
// (the e2e behavior rides tests/test_signal_guard.py).
#include "testing.hpp"
#include "tpupruner/audit.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/query.hpp"
#include "tpupruner/signal.hpp"

namespace signal = tpupruner::signal;
namespace query = tpupruner::query;
using tpupruner::core::PodMetricSample;
using tpupruner::json::Value;

namespace {

Value evidence_row(const std::string& ns, const std::string& pod, const char* stat,
                   double value) {
  Value metric = Value::object();
  metric.set("exported_pod", Value(pod));
  metric.set("exported_namespace", Value(ns));
  metric.set("signal_stat", Value(std::string(stat)));
  Value sample = Value::array();
  sample.push_back(Value(0));
  sample.push_back(Value(std::to_string(value)));
  Value row = Value::object();
  row.set("metric", std::move(metric));
  row.set("value", std::move(sample));
  return row;
}

Value response_of(std::vector<Value> rows) {
  Value result = Value::array();
  for (Value& r : rows) result.push_back(std::move(r));
  Value data = Value::object();
  data.set("resultType", Value(std::string("vector")));
  data.set("result", std::move(result));
  Value resp = Value::object();
  resp.set("status", Value(std::string("success")));
  resp.set("data", std::move(data));
  return resp;
}

PodMetricSample candidate(const std::string& ns, const std::string& pod) {
  PodMetricSample s;
  s.ns = ns;
  s.name = pod;
  return s;
}

signal::Config default_cfg() {
  signal::Config cfg;
  cfg.scrape_interval_s = 30;
  cfg.max_age_s = 300;
  cfg.min_coverage = 0.9;
  cfg.window_s = 1800;
  return cfg;
}

}  // namespace

TP_TEST(signal_verdict_thresholds) {
  // window 1800 / scrape 30 → 60 expected → GAPPY floor at 30.
  signal::Config cfg = default_cfg();
  TP_CHECK_EQ(cfg.min_samples(), 30.0);
  Value resp = response_of({
      evidence_row("ml", "ok", "samples", 60), evidence_row("ml", "ok", "age", 10),
      evidence_row("ml", "old", "samples", 60), evidence_row("ml", "old", "age", 301),
      evidence_row("ml", "thin", "samples", 29), evidence_row("ml", "thin", "age", 10),
      // exactly at the floor/threshold stays healthy (strict comparisons)
      evidence_row("ml", "edge", "samples", 30), evidence_row("ml", "edge", "age", 300),
  });
  signal::Assessment a = signal::assess(
      resp,
      {candidate("ml", "ok"), candidate("ml", "old"), candidate("ml", "thin"),
       candidate("ml", "edge"), candidate("ml", "ghost")},
      cfg, 7);
  TP_CHECK_EQ(a.cycle, 7u);
  TP_CHECK_EQ(a.pods.size(), 5u);
  TP_CHECK_EQ(std::string(signal::verdict_name(a.pods[0].verdict)), std::string("healthy"));
  TP_CHECK_EQ(std::string(signal::verdict_name(a.pods[1].verdict)), std::string("stale"));
  TP_CHECK_EQ(std::string(signal::verdict_name(a.pods[2].verdict)), std::string("gappy"));
  TP_CHECK_EQ(std::string(signal::verdict_name(a.pods[3].verdict)), std::string("healthy"));
  TP_CHECK_EQ(std::string(signal::verdict_name(a.pods[4].verdict)), std::string("absent"));
  // stale wins over gappy when both apply: freshness is the sharper fact
  Value both = response_of({
      evidence_row("ml", "p", "samples", 1), evidence_row("ml", "p", "age", 9999),
  });
  signal::Assessment b = signal::assess(both, {candidate("ml", "p")}, cfg, 1);
  TP_CHECK(b.pods[0].verdict == signal::Verdict::Stale);
}

TP_TEST(signal_coverage_math_and_brownout) {
  signal::Config cfg = default_cfg();
  Value resp = response_of({
      evidence_row("ml", "a", "samples", 60), evidence_row("ml", "a", "age", 1),
  });
  // 1 healthy of 2 → coverage 0.5 < 0.9 → brownout
  signal::Assessment a =
      signal::assess(resp, {candidate("ml", "a"), candidate("ml", "b")}, cfg, 1);
  TP_CHECK_EQ(a.coverage_ratio, 0.5);
  TP_CHECK(a.brownout);
  TP_CHECK_EQ(a.count(signal::Verdict::Healthy), 1u);
  TP_CHECK_EQ(a.count(signal::Verdict::Absent), 1u);
  // empty candidate set: vacuous full coverage, never a brownout
  signal::Assessment empty = signal::assess(resp, {}, cfg, 1);
  TP_CHECK_EQ(empty.coverage_ratio, 1.0);
  TP_CHECK(!empty.brownout);
  // coverage exactly at the floor does not brown out (strict <)
  cfg.min_coverage = 0.5;
  signal::Assessment at_floor =
      signal::assess(resp, {candidate("ml", "a"), candidate("ml", "b")}, cfg, 1);
  TP_CHECK(!at_floor.brownout);
}

TP_TEST(signal_min_samples_floor_never_below_one) {
  signal::Config cfg = default_cfg();
  cfg.window_s = 10;  // scrape slower than the window → floor clamps to 1
  cfg.scrape_interval_s = 60;
  TP_CHECK_EQ(cfg.min_samples(), 1.0);
}

TP_TEST(signal_assessment_json_round_trip) {
  signal::Config cfg = default_cfg();
  Value resp = response_of({
      evidence_row("ml", "a", "samples", 60), evidence_row("ml", "a", "age", 12),
      evidence_row("ml", "b", "age", 5000),
  });
  signal::Assessment a = signal::assess(
      resp, {candidate("ml", "a"), candidate("ml", "b"), candidate("ml", "c")}, cfg, 42);
  signal::Assessment back = signal::assessment_from_json(signal::assessment_to_json(a));
  TP_CHECK_EQ(back.cycle, a.cycle);
  TP_CHECK_EQ(back.coverage_ratio, a.coverage_ratio);
  TP_CHECK_EQ(back.brownout, a.brownout);
  TP_CHECK_EQ(back.min_coverage, a.min_coverage);
  TP_CHECK_EQ(back.pods.size(), a.pods.size());
  for (size_t i = 0; i < a.pods.size(); ++i) {
    TP_CHECK_EQ(back.pods[i].ns, a.pods[i].ns);
    TP_CHECK_EQ(back.pods[i].pod, a.pods[i].pod);
    TP_CHECK(back.pods[i].verdict == a.pods[i].verdict);
    TP_CHECK_EQ(back.pods[i].has_samples, a.pods[i].has_samples);
    TP_CHECK_EQ(back.pods[i].has_age, a.pods[i].has_age);
    TP_CHECK_EQ(back.pods[i].sample_count, a.pods[i].sample_count);
    TP_CHECK_EQ(back.pods[i].last_age_s, a.pods[i].last_age_s);
  }
  // the serialized dump is stable through a second round-trip
  TP_CHECK_EQ(signal::assessment_to_json(back).dump(), signal::assessment_to_json(a).dump());
}

TP_TEST(signal_veto_reasons_and_details) {
  signal::Config cfg = default_cfg();
  signal::PodSignal p;
  p.verdict = signal::Verdict::Stale;
  p.last_age_s = 4000;
  p.has_age = true;
  TP_CHECK(signal::veto_reason(p.verdict) == tpupruner::audit::Reason::SignalStale);
  TP_CHECK(signal::veto_detail(p, cfg).find("--signal-max-age=300") != std::string::npos);
  p.verdict = signal::Verdict::Gappy;
  TP_CHECK(signal::veto_reason(p.verdict) == tpupruner::audit::Reason::SignalGappy);
  TP_CHECK(signal::veto_detail(p, cfg).find("--signal-scrape-interval=30") != std::string::npos);
  p.verdict = signal::Verdict::Absent;
  TP_CHECK(signal::veto_reason(p.verdict) == tpupruner::audit::Reason::SignalAbsent);
  TP_CHECK(!signal::veto_detail(p, cfg).empty());

  signal::Assessment a;
  a.coverage_ratio = 0.25;
  std::string why = signal::brownout_detail(a, cfg);
  TP_CHECK(why.find("0.250") != std::string::npos);
  TP_CHECK(why.find("--signal-min-coverage=0.900") != std::string::npos);
}

TP_TEST(signal_registry_publish_and_render) {
  signal::reset_for_test();
  TP_CHECK_EQ(signal::render_metrics(false), std::string(""));  // absent before publish
  TP_CHECK(!signal::signals_json().find("enabled")->as_bool());

  signal::Config cfg = default_cfg();
  Value resp = response_of({
      evidence_row("ml", "a", "samples", 60), evidence_row("ml", "a", "age", 10),
  });
  signal::Assessment healthy = signal::assess(resp, {candidate("ml", "a")}, cfg, 1);
  signal::publish(healthy, cfg);
  std::string body = signal::render_metrics(false);
  TP_CHECK(body.find("tpu_pruner_signal_coverage_ratio 1\n") != std::string::npos);
  TP_CHECK(body.find("tpu_pruner_signal_pods{verdict=\"healthy\"} 1") != std::string::npos);
  TP_CHECK(body.find("tpu_pruner_signal_brownouts_total 0") != std::string::npos);
  TP_CHECK(body.find("tpu_pruner_pod_signal_age_seconds_bucket{le=\"15\"} 1") !=
           std::string::npos);

  signal::Assessment browned =
      signal::assess(resp, {candidate("ml", "a"), candidate("ml", "gone")}, cfg, 2);
  TP_CHECK(browned.brownout);
  signal::publish(browned, cfg);
  signal::publish(browned, cfg);  // two browned-out cycles
  body = signal::render_metrics(false);
  TP_CHECK(body.find("tpu_pruner_signal_brownouts_total 2") != std::string::npos);
  TP_CHECK(body.find("tpu_pruner_signal_pods{verdict=\"absent\"} 1") != std::string::npos);

  // OpenMetrics negotiation strips _total from the counter TYPE line
  std::string om = signal::render_metrics(true);
  TP_CHECK(om.find("# TYPE tpu_pruner_signal_brownouts counter") != std::string::npos);

  Value served = signal::signals_json();
  TP_CHECK(served.find("enabled")->as_bool());
  TP_CHECK_EQ(served.find("brownouts_total")->as_int(), 2);
  TP_CHECK(served.at_path("thresholds.min_samples") != nullptr);
  signal::reset_for_test();
}

TP_TEST(signal_evidence_query_covers_every_schema) {
  query::QueryArgs gmp;
  std::string q = query::build_evidence_query(gmp);
  TP_CHECK(q.find("signal_stat") != std::string::npos);
  TP_CHECK(q.find("count_over_time(tensorcore_utilization") != std::string::npos);
  TP_CHECK(q.find("timestamp(tensorcore_duty_cycle") != std::string::npos);

  query::QueryArgs gke;
  gke.metric_schema = "gke-system";
  gke.namespace_regex = "ml-.*";
  std::string gq = query::build_evidence_query(gke);
  TP_CHECK(gq.find("kubernetes_io:node_accelerator_tensorcore_utilization") !=
           std::string::npos);
  TP_CHECK(gq.find("> bool 0") != std::string::npos);  // join mask, not request_count×stat
  TP_CHECK(gq.find("group_left") != std::string::npos);
  TP_CHECK(gq.find("exported_namespace =~ \"ml-.*\"") != std::string::npos);

  query::QueryArgs gpu;
  gpu.device = "gpu";
  std::string pq = query::build_evidence_query(gpu);
  TP_CHECK(pq.find("DCGM_FI_PROF_GR_ENGINE_ACTIVE") != std::string::npos);

  bool threw = false;
  query::QueryArgs bad;
  bad.metric_schema = "nope";
  try {
    query::build_evidence_query(bad);
  } catch (const std::exception&) {
    threw = true;
  }
  TP_CHECK(threw);
}

TP_TEST(signal_reason_codes_registered) {
  auto codes = tpupruner::audit::all_reason_codes();
  for (const char* code :
       {"SIGNAL_STALE", "SIGNAL_GAPPY", "SIGNAL_ABSENT", "SIGNAL_BROWNOUT"}) {
    bool found = false;
    for (const std::string& c : codes) {
      if (c == code) found = true;
    }
    TP_CHECK(found);
    TP_CHECK(tpupruner::audit::reason_from_name(code).has_value());
  }
}
