// Protobuf wire-format units for the OTLP/gRPC transport (otlp_grpc.cpp).
// Golden bytes are hand-computed from the protobuf encoding rules so the
// writer is checked against the spec, not against itself.
#include "testing.hpp"

#include "../src/otlp_grpc.hpp"

using tpupruner::log::Counter;
using tpupruner::otlp::FinishedSpan;
namespace pb = tpupruner::otlp_grpc::pb;

namespace {

std::string hex(const std::string& s) {
  static const char* d = "0123456789abcdef";
  std::string out;
  for (unsigned char c : s) {
    out.push_back(d[c >> 4]);
    out.push_back(d[c & 0xf]);
  }
  return out;
}

// Minimal generic protobuf reader: returns (field, wiretype, payload)
// triples of one message level. Independent re-implementation of the wire
// rules used to cross-check the writer.
struct Field {
  int number;
  int wire;
  uint64_t varint = 0;
  std::string bytes;
};

std::vector<Field> parse(const std::string& buf) {
  std::vector<Field> out;
  size_t i = 0;
  auto varint = [&]() {
    uint64_t v = 0;
    int shift = 0;
    while (i < buf.size()) {
      uint8_t b = static_cast<uint8_t>(buf[i++]);
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    return v;
  };
  while (i < buf.size()) {
    uint64_t tag = varint();
    Field f;
    f.number = static_cast<int>(tag >> 3);
    f.wire = static_cast<int>(tag & 7);
    if (f.wire == 0) {
      f.varint = varint();
    } else if (f.wire == 1) {
      for (int k = 0; k < 8; ++k) f.varint |= static_cast<uint64_t>(static_cast<uint8_t>(buf[i++])) << (8 * k);
    } else if (f.wire == 2) {
      uint64_t len = varint();
      f.bytes = buf.substr(i, len);
      i += len;
    }
    out.push_back(std::move(f));
  }
  return out;
}

const Field* find(const std::vector<Field>& fs, int number) {
  for (const Field& f : fs)
    if (f.number == number) return &f;
  return nullptr;
}

}  // namespace

TP_TEST(pb_varint_golden) {
  std::string out;
  pb::put_varint(out, 0);
  pb::put_varint(out, 1);
  pb::put_varint(out, 127);
  pb::put_varint(out, 128);
  pb::put_varint(out, 300);
  TP_CHECK_EQ(hex(out), "00017f8001ac02");
}

TP_TEST(pb_fields_golden) {
  std::string out;
  pb::put_varint_field(out, 1, 150);       // tag 0x08, varint 0x9601
  TP_CHECK_EQ(hex(out), "089601");
  out.clear();
  pb::put_bytes_field(out, 2, "testing");  // tag 0x12, len 7
  TP_CHECK_EQ(hex(out), "120774657374696e67");
  out.clear();
  pb::put_fixed64_field(out, 3, 0x0102030405060708ull);  // tag 0x19, LE bytes
  TP_CHECK_EQ(hex(out), "190807060504030201");
}

TP_TEST(metrics_request_shape) {
  std::map<std::string, Counter> counters;
  counters["query_successes"] = Counter{7, false};
  counters["query_returned_candidates"] = Counter{42, true};
  std::string req = tpupruner::otlp_grpc::encode_metrics_request(counters, 1000, 2000);

  auto top = parse(req);
  const Field* rm = find(top, 1);  // resource_metrics
  TP_CHECK(rm && rm->wire == 2);
  auto rm_fields = parse(rm->bytes);
  const Field* resource = find(rm_fields, 1);
  TP_CHECK(resource != nullptr);
  // Resource.attributes[0] = KeyValue{service.name, tpu-pruner}
  auto res_fields = parse(resource->bytes);
  auto kv = parse(find(res_fields, 1)->bytes);
  TP_CHECK_EQ(find(kv, 1)->bytes, "service.name");
  TP_CHECK_EQ(parse(find(kv, 2)->bytes)[0].bytes, "tpu-pruner");

  const Field* sm = find(rm_fields, 2);  // scope_metrics
  TP_CHECK(sm != nullptr);
  auto sm_fields = parse(sm->bytes);
  // two metrics, map-ordered: query_returned_candidates (gauge) first
  std::vector<const Field*> metrics;
  for (const Field& f : sm_fields)
    if (f.number == 2) metrics.push_back(&f);
  TP_CHECK_EQ(metrics.size(), static_cast<size_t>(2));

  auto m0 = parse(metrics[0]->bytes);
  TP_CHECK_EQ(find(m0, 1)->bytes, "tpu_pruner.query_returned_candidates");
  const Field* gauge = find(m0, 5);
  TP_CHECK(gauge != nullptr);          // gauge kind
  TP_CHECK(find(m0, 7) == nullptr);    // not a sum
  auto dp0 = parse(find(parse(gauge->bytes), 1)->bytes);
  TP_CHECK_EQ(find(dp0, 2)->varint, static_cast<uint64_t>(1000));  // start (fixed64)
  TP_CHECK_EQ(find(dp0, 3)->varint, static_cast<uint64_t>(2000));  // now
  TP_CHECK_EQ(find(dp0, 6)->varint, static_cast<uint64_t>(42));    // as_int

  auto m1 = parse(metrics[1]->bytes);
  TP_CHECK_EQ(find(m1, 1)->bytes, "tpu_pruner.query_successes");
  const Field* sum = find(m1, 7);
  TP_CHECK(sum != nullptr);
  auto sum_fields = parse(sum->bytes);
  TP_CHECK_EQ(find(sum_fields, 2)->varint, static_cast<uint64_t>(2));  // CUMULATIVE
  TP_CHECK_EQ(find(sum_fields, 3)->varint, static_cast<uint64_t>(1));  // monotonic
  auto dp1 = parse(find(sum_fields, 1)->bytes);
  TP_CHECK_EQ(find(dp1, 6)->varint, static_cast<uint64_t>(7));
}

TP_TEST(traces_request_shape) {
  FinishedSpan fs;
  fs.name = "cycle";
  fs.trace_id = "0102030405060708090a0b0c0d0e0f10";
  fs.span_id = "1112131415161718";
  fs.parent_span_id = "";
  fs.start_nanos = 111;
  fs.end_nanos = 222;
  fs.str_attrs = {{"mode", "scale-down"}};
  fs.int_attrs = {{"candidates", 5}};
  fs.error = true;
  fs.error_message = "boom";

  std::string req = tpupruner::otlp_grpc::encode_traces_request({fs});
  auto rs = parse(find(parse(req), 1)->bytes);     // resource_spans
  auto ss = parse(find(rs, 2)->bytes);             // scope_spans
  auto span = parse(find(ss, 2)->bytes);           // spans[0]
  TP_CHECK_EQ(hex(find(span, 1)->bytes), "0102030405060708090a0b0c0d0e0f10");
  TP_CHECK_EQ(hex(find(span, 2)->bytes), "1112131415161718");
  TP_CHECK(find(span, 4) == nullptr);  // no parent -> field omitted
  TP_CHECK_EQ(find(span, 5)->bytes, "cycle");
  TP_CHECK_EQ(find(span, 7)->varint, static_cast<uint64_t>(111));
  TP_CHECK_EQ(find(span, 8)->varint, static_cast<uint64_t>(222));
  // two attributes (one string, one int)
  int attrs = 0;
  for (const Field& f : span)
    if (f.number == 9) ++attrs;
  TP_CHECK_EQ(attrs, 2);
  auto status = parse(find(span, 15)->bytes);
  TP_CHECK_EQ(find(status, 2)->bytes, "boom");
  TP_CHECK_EQ(find(status, 3)->varint, static_cast<uint64_t>(2));
}

// ── HPACK response-path decoder (otlp_grpc.cpp hpack_decode) ──────────────

using HpackHeaders = std::vector<std::tuple<std::string, std::string, bool>>;

TP_TEST(hpack_literal_without_indexing) {
  // the fake collector's exact shape: 0x00, len-prefixed raw strings
  std::string block("\x00\x07:status\x03""200\x00\x0bgrpc-status\x01""0", 28);
  HpackHeaders h;
  TP_CHECK(tpupruner::otlp_grpc::hpack_decode_for_test(block, h));
  TP_CHECK_EQ(h.size(), static_cast<size_t>(2));
  TP_CHECK_EQ(std::get<0>(h[0]), ":status");
  TP_CHECK_EQ(std::get<1>(h[0]), "200");
  TP_CHECK_EQ(std::get<0>(h[1]), "grpc-status");
  TP_CHECK_EQ(std::get<1>(h[1]), "0");
  TP_CHECK(!std::get<2>(h[1]));
}

TP_TEST(hpack_static_indexed_and_name_index) {
  // 0x88 = indexed static 8 (:status 200); 0x48 = literal incremental
  // with static name index 8 (:status) + raw value "404"
  std::string block("\x88\x48\x03""404", 6);
  HpackHeaders h;
  TP_CHECK(tpupruner::otlp_grpc::hpack_decode_for_test(block, h));
  TP_CHECK_EQ(h.size(), static_cast<size_t>(2));
  TP_CHECK_EQ(std::get<0>(h[0]), ":status");
  TP_CHECK_EQ(std::get<1>(h[0]), "200");
  TP_CHECK_EQ(std::get<0>(h[1]), ":status");
  TP_CHECK_EQ(std::get<1>(h[1]), "404");
}

TP_TEST(hpack_huffman_value_flagged_opaque) {
  // literal new name "x", value huffman-flagged (0x83 = H bit + len 3);
  // \x30\x31\x32 decodes part-way ("i0G3") but ends on a 0 padding bit —
  // invalid per RFC 7541 §5.2, so the value must stay opaque/flagged
  std::string block("\x00\x01x\x83\x30\x31\x32", 7);
  HpackHeaders h;
  TP_CHECK(tpupruner::otlp_grpc::hpack_decode_for_test(block, h));
  TP_CHECK_EQ(h.size(), static_cast<size_t>(1));
  TP_CHECK_EQ(std::get<0>(h[0]), "x");
  TP_CHECK(std::get<2>(h[0]));  // flagged, not decoded
}

TP_TEST(huffman_rfc7541_appendix_c_vectors) {
  // the RFC's own request/response examples pin the whole code table
  auto dec = [](std::string_view in) {
    std::string out;
    TP_CHECK(tpupruner::otlp_grpc::huffman_decode_for_test(in, out));
    return out;
  };
  TP_CHECK_EQ(dec("\xf1\xe3\xc2\xe5\xf2\x3a\x6b\xa0\xab\x90\xf4\xff"),
              "www.example.com");                          // C.4.1
  TP_CHECK_EQ(dec("\xa8\xeb\x10\x64\x9c\xbf"), "no-cache");  // C.4.2
  TP_CHECK_EQ(dec("\x25\xa8\x49\xe9\x5b\xa9\x7d\x7f"), "custom-key");
  TP_CHECK_EQ(dec("\x25\xa8\x49\xe9\x5b\xb8\xe8\xb4\xbf"), "custom-value");
  TP_CHECK_EQ(dec("\x64\x02"), "302");                       // C.6.1
  TP_CHECK_EQ(dec("\xae\xc3\x77\x1a\x4b"), "private");       // C.6.1
  TP_CHECK_EQ(dec(std::string(
                  "\x9d\x29\xad\x17\x18\x63\xc7\x8f\x0b\x97\xc8\xe9\xae"
                  "\x82\xae\x43\xd3", 17)),
              "https://www.example.com");                    // C.6.1
  TP_CHECK_EQ(dec(std::string(
                  "\xd0\x7a\xbe\x94\x10\x54\xd4\x44\xa8\x20\x05\x95\x04"
                  "\x0b\x81\x66\xe0\x82\xa6\x2d\x1b\xff", 22)),
              "Mon, 21 Oct 2013 20:13:21 GMT");              // C.6.1
}

TP_TEST(huffman_invalid_rejected) {
  std::string out;
  // EOS (30 one-bits) inside the string is a decoding error
  TP_CHECK(!tpupruner::otlp_grpc::huffman_decode_for_test(
      std::string("\xff\xff\xff\xff", 4), out));
  // 'a' followed by 11 one-bits: padding must be < 8 bits
  out.clear();
  TP_CHECK(!tpupruner::otlp_grpc::huffman_decode_for_test(
      std::string("\x1f\xff", 2), out));
  // empty input decodes to the empty string
  out.clear();
  TP_CHECK(tpupruner::otlp_grpc::huffman_decode_for_test("", out));
  TP_CHECK_EQ(out, "");
}

TP_TEST(hpack_huffman_coded_trailer_name_decoded) {
  // the grpc-go shape this decoder exists for: literal with the NAME
  // huffman-coded ("grpc-status", 11 raw -> 8 coded bytes) and the
  // 1-byte value "0" raw. Before huffman decoding landed, the name
  // surfaced as "<huffman>" and every real collector export misread.
  std::string name_huff("\x9a\xca\xc8\xb2\x12\x34\xda\x8f", 8);
  std::string block = std::string("\x00\x88", 2) + name_huff +
                      std::string("\x01""0", 2);
  HpackHeaders h;
  TP_CHECK(tpupruner::otlp_grpc::hpack_decode_for_test(block, h));
  TP_CHECK_EQ(h.size(), static_cast<size_t>(1));
  TP_CHECK_EQ(std::get<0>(h[0]), "grpc-status");
  TP_CHECK_EQ(std::get<1>(h[0]), "0");
  TP_CHECK(!std::get<2>(h[0]));
}

TP_TEST(hpack_dynamic_size_update_skipped) {
  // 0x20 = table size update to 0, then one literal
  std::string block("\x20\x00\x01x\x01y", 6);
  HpackHeaders h;
  TP_CHECK(tpupruner::otlp_grpc::hpack_decode_for_test(block, h));
  TP_CHECK_EQ(h.size(), static_cast<size_t>(1));
  TP_CHECK_EQ(std::get<0>(h[0]), "x");
  TP_CHECK_EQ(std::get<1>(h[0]), "y");
}

TP_TEST(hpack_malformed_rejected_not_crash) {
  HpackHeaders h;
  // truncated length prefix
  TP_CHECK(!tpupruner::otlp_grpc::hpack_decode_for_test(std::string("\x00\x7f", 2), h));
  // string length past end of block
  TP_CHECK(!tpupruner::otlp_grpc::hpack_decode_for_test(std::string("\x00\x10x", 3), h));
  // unterminated multi-byte integer
  TP_CHECK(!tpupruner::otlp_grpc::hpack_decode_for_test(
      std::string("\x7f\x80\x80\x80\x80\x80\x80\x80\x80\x80\x80", 11), h));
  // empty block is valid (no headers)
  HpackHeaders h2;
  TP_CHECK(tpupruner::otlp_grpc::hpack_decode_for_test("", h2));
  TP_CHECK_EQ(h2.size(), static_cast<size_t>(0));
}
