// Trace-span machinery units (reference analog: the #[tracing::instrument]
// spans exported under the `otel` feature, gpu-pruner main.rs:194-221).
#include "testing.hpp"

#include "../src/otlp.hpp"

using tpupruner::otlp::FinishedSpan;
using tpupruner::otlp::Span;
using tpupruner::otlp::SpanContext;

namespace {

// RAII recording toggle so a failing test can't poison the others.
struct Recording {
  Recording() {
    tpupruner::otlp::set_recording_for_test(true);
    tpupruner::otlp::drain_spans_for_test();
  }
  ~Recording() {
    tpupruner::otlp::set_recording_for_test(false);
    tpupruner::otlp::drain_spans_for_test();
  }
};

}  // namespace

TP_TEST(span_disabled_records_nothing) {
  tpupruner::otlp::drain_spans_for_test();
  {
    Span s("noop");
    s.attr("k", std::string("v"));
  }
  TP_CHECK(tpupruner::otlp::drain_spans_for_test().empty());
}

TP_TEST(span_ids_and_timing) {
  Recording rec;
  {
    Span s("cycle");
  }
  auto spans = tpupruner::otlp::drain_spans_for_test();
  TP_CHECK_EQ(spans.size(), 1u);
  const FinishedSpan& fs = spans[0];
  TP_CHECK_EQ(fs.name, "cycle");
  TP_CHECK_EQ(fs.trace_id.size(), 32u);  // 16-byte trace id
  TP_CHECK_EQ(fs.span_id.size(), 16u);   // 8-byte span id
  TP_CHECK(fs.parent_span_id.empty());   // root span
  TP_CHECK(fs.end_nanos >= fs.start_nanos);
  TP_CHECK(fs.start_nanos > 1000000000ll * 1000000000ll);  // post-2001 wall clock
  TP_CHECK(!fs.error);
}

TP_TEST(span_child_inherits_trace_and_parents) {
  Recording rec;
  {
    Span parent("run_query_and_scale");
    Span child("find_root_object", &parent.context());
    TP_CHECK_EQ(child.context().trace_id, parent.context().trace_id);
    TP_CHECK(child.context().span_id != parent.context().span_id);
  }
  auto spans = tpupruner::otlp::drain_spans_for_test();
  TP_CHECK_EQ(spans.size(), 2u);  // child finishes first (reverse destruction)
  const FinishedSpan& child = spans[0];
  const FinishedSpan& parent = spans[1];
  TP_CHECK_EQ(child.name, "find_root_object");
  TP_CHECK_EQ(child.trace_id, parent.trace_id);
  TP_CHECK_EQ(child.parent_span_id, parent.span_id);
}

TP_TEST(span_attrs_and_error_status) {
  Recording rec;
  {
    Span s("scale");
    s.attr("kind", std::string("JobSet"));
    s.attr("shutdown_events", static_cast<int64_t>(7));
    s.set_error("patch failed");
  }
  auto spans = tpupruner::otlp::drain_spans_for_test();
  TP_CHECK_EQ(spans.size(), 1u);
  const FinishedSpan& fs = spans[0];
  TP_CHECK_EQ(fs.str_attrs.size(), 1u);
  TP_CHECK_EQ(fs.str_attrs[0].first, "kind");
  TP_CHECK_EQ(fs.str_attrs[0].second, "JobSet");
  TP_CHECK_EQ(fs.int_attrs.size(), 1u);
  TP_CHECK_EQ(fs.int_attrs[0].second, 7);
  TP_CHECK(fs.error);
  TP_CHECK_EQ(fs.error_message, "patch failed");
}

TP_TEST(span_buffer_caps_and_drains) {
  Recording rec;
  for (int i = 0; i < 5000; ++i) {
    Span s("burst");
  }
  auto spans = tpupruner::otlp::drain_spans_for_test();
  TP_CHECK_EQ(spans.size(), 4096u);  // cap, excess dropped not blocked
  TP_CHECK(tpupruner::otlp::drain_spans_for_test().empty());
}
