// Minimal native test harness for tpu-pruner's C++ units (the reference uses
// `cargo test` in-crate tests; this plays the same role for the C++ build).
#pragma once

#include <cstdio>
#include <functional>
#include <sstream>
#include <string>
#include <vector>

namespace tptest {

struct Case {
  const char* name;
  std::function<void()> fn;
};

inline std::vector<Case>& registry() {
  static std::vector<Case> r;
  return r;
}

struct Registrar {
  Registrar(const char* name, std::function<void()> fn) {
    registry().push_back({name, std::move(fn)});
  }
};

struct Failure {
  std::string msg;
};

#define TP_TEST(name)                                             \
  static void tptest_fn_##name();                                 \
  static ::tptest::Registrar tptest_reg_##name(#name, tptest_fn_##name); \
  static void tptest_fn_##name()

#define TP_CHECK(cond)                                                          \
  do {                                                                          \
    if (!(cond)) {                                                              \
      std::ostringstream oss_;                                                  \
      oss_ << __FILE__ << ":" << __LINE__ << ": check failed: " #cond;          \
      throw ::tptest::Failure{oss_.str()};                                      \
    }                                                                           \
  } while (0)

#define TP_CHECK_EQ(a, b)                                                       \
  do {                                                                          \
    auto va_ = (a);                                                             \
    auto vb_ = (b);                                                             \
    if (!(va_ == vb_)) {                                                        \
      std::ostringstream oss_;                                                  \
      oss_ << __FILE__ << ":" << __LINE__ << ": expected " #a " == " #b         \
           << "  (lhs=" << va_ << ", rhs=" << vb_ << ")";                       \
      throw ::tptest::Failure{oss_.str()};                                      \
    }                                                                           \
  } while (0)

inline int run_all(int argc, char** argv) {
  std::string filter = argc > 1 ? argv[1] : "";
  int failed = 0, ran = 0;
  for (const Case& c : registry()) {
    if (!filter.empty() && std::string(c.name).find(filter) == std::string::npos) continue;
    ++ran;
    try {
      c.fn();
      printf("ok      %s\n", c.name);
    } catch (const Failure& f) {
      ++failed;
      printf("FAILED  %s\n        %s\n", c.name, f.msg.c_str());
    } catch (const std::exception& e) {
      ++failed;
      printf("FAILED  %s\n        exception: %s\n", c.name, e.what());
    }
  }
  printf("%d tests, %d failed\n", ran, failed);
  return failed == 0 ? 0 : 1;
}

}  // namespace tptest
