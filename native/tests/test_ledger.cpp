// Workload utilization ledger unit tests: cycle integration math, the
// pause/resume lifecycle, the top-K + "_other" cardinality rollup, event
// history bounding, and the JSONL checkpoint round trip.
#include "tpupruner/ledger.hpp"

#include <unistd.h>

#include <cstdio>
#include <string>
#include <vector>

#include "testing.hpp"
#include "tpupruner/json.hpp"

namespace ledger = tpupruner::ledger;
using tpupruner::json::Value;

namespace {

ledger::Observation obs(const std::string& name, int64_t chips) {
  return {"Deployment", "ml", name, chips};
}

const Value* workload(const Value& doc, const std::string& key) {
  for (const Value& w : doc.find("workloads")->as_array()) {
    if (w.get_string("workload") == key) return &w;
  }
  return nullptr;
}

double num(const Value& v, const char* k) {
  const Value* x = v.find(k);
  return x && x->is_number() ? x->as_double() : -1;
}

size_t count_series(const std::string& text, const std::string& family) {
  // sample lines start with `family{` (labelled) or `family ` (bare)
  size_t n = 0, pos = 0;
  while ((pos = text.find("\n" + family, pos)) != std::string::npos) {
    char next = text[pos + 1 + family.size()];
    if (next == '{' || next == ' ') ++n;
    pos += family.size();
  }
  return n;
}

}  // namespace

TP_TEST(ledger_integrates_idle_active_and_reclaimed) {
  ledger::reset_for_test();
  // cycle 1: first sighting — nothing accrues, streak starts
  ledger::observe_cycle(1, 1000, {obs("a", 4)});
  // cycle 2 (+10s): still idle → idle_seconds
  ledger::observe_cycle(2, 1010, {obs("a", 4)});
  Value doc = ledger::workloads_json();
  const Value* a = workload(doc, "Deployment/ml/a");
  TP_CHECK(a != nullptr);
  TP_CHECK_EQ(num(*a, "idle_seconds"), 10.0);
  TP_CHECK_EQ(num(*a, "idle_streak_cycles"), 2.0);
  TP_CHECK_EQ(a->get_string("state"), std::string("idle"));

  // cycle 3 (+5s): absent from the idle set → active, streak resets
  ledger::observe_cycle(3, 1015, {});
  doc = ledger::workloads_json();
  a = workload(doc, "Deployment/ml/a");
  TP_CHECK_EQ(num(*a, "active_seconds"), 5.0);
  TP_CHECK_EQ(num(*a, "idle_streak_cycles"), 0.0);
  TP_CHECK_EQ(a->get_string("state"), std::string("active"));

  // idle again, then paused: reclaimed accrues at chips-at-pause x dt,
  // idle time stops (series outliving the pods must not double-count)
  ledger::observe_cycle(4, 1020, {obs("a", 4)});
  ledger::record_pause(4, "Deployment", "ml", "a", "SCALED");
  ledger::observe_cycle(5, 1030, {obs("a", 4)});
  ledger::observe_cycle(6, 1040, {});
  doc = ledger::workloads_json();
  a = workload(doc, "Deployment/ml/a");
  TP_CHECK_EQ(num(*a, "reclaimed_chip_seconds"), 80.0);  // 4 chips x 20s
  TP_CHECK_EQ(num(*a, "idle_seconds"), 15.0);            // 10 + 5 (cycle 4)
  TP_CHECK_EQ(a->get_string("state"), std::string("paused"));
  TP_CHECK_EQ(num(*a, "pauses"), 1.0);

  // resume closes the reclaim window; idle accrual resumes on observation
  ledger::record_resume(6, "Deployment", "ml", "a", "external");
  ledger::observe_cycle(7, 1050, {obs("a", 4)});
  doc = ledger::workloads_json();
  a = workload(doc, "Deployment/ml/a");
  TP_CHECK_EQ(num(*a, "reclaimed_chip_seconds"), 80.0);  // frozen
  TP_CHECK_EQ(num(*a, "resumes"), 1.0);
  TP_CHECK_EQ(num(*a, "idle_seconds"), 25.0);
}

TP_TEST(ledger_repatch_of_paused_root_is_noop) {
  ledger::reset_for_test();
  ledger::observe_cycle(1, 1000, {obs("a", 4)});
  ledger::record_pause(1, "Deployment", "ml", "a", "SCALED");
  // watch-cache-off re-patches land SCALED every cycle; the pause count
  // and the savings clock must not restart
  ledger::record_pause(2, "Deployment", "ml", "a", "SCALED");
  ledger::record_pause(3, "Deployment", "ml", "a", "ALREADY_PAUSED");
  Value doc = ledger::workloads_json();
  const Value* a = workload(doc, "Deployment/ml/a");
  TP_CHECK_EQ(num(*a, "pauses"), 1.0);
  // resume without a pause is equally inert
  ledger::record_resume(3, "Deployment", "ml", "b", "external");
  TP_CHECK(workload(ledger::workloads_json(), "Deployment/ml/b") == nullptr);
}

TP_TEST(ledger_rollup_serves_topk_plus_other_and_sums) {
  ledger::reset_for_test();
  // 5 workloads, chips 1..5; two cycles so idle_seconds accrue
  std::vector<ledger::Observation> fleet;
  for (int i = 1; i <= 5; ++i) fleet.push_back(obs("w" + std::to_string(i), i));
  ledger::observe_cycle(1, 1000, fleet);
  ledger::observe_cycle(2, 1010, fleet);
  std::string text = "\n" + ledger::render_metrics(/*top_k=*/2, false);

  // exactly K + _other series per family
  TP_CHECK_EQ(count_series(text, "tpu_pruner_workload_idle_seconds_total"), 3u);
  TP_CHECK_EQ(count_series(text, "tpu_pruner_workload_reclaimed_chip_seconds_total"), 3u);
  TP_CHECK_EQ(count_series(text, "tpu_pruner_workload_chips"), 3u);
  TP_CHECK_EQ(count_series(text, "tpu_pruner_workloads_tracked"), 1u);
  // top-K is by chips: w5 and w4 get their own series
  TP_CHECK(text.find("{workload=\"Deployment/ml/w5\"} 10") != std::string::npos);
  TP_CHECK(text.find("{workload=\"Deployment/ml/w4\"} 10") != std::string::npos);
  // the rollup preserves totals: 3 remaining workloads x 10s idle,
  // 1+2+3 chips
  TP_CHECK(text.find("tpu_pruner_workload_idle_seconds_total{workload=\"_other\"} 30")
           != std::string::npos);
  TP_CHECK(text.find("tpu_pruner_workload_chips{workload=\"_other\",state=\"_other\"} 6")
           != std::string::npos);
  TP_CHECK(text.find("tpu_pruner_workloads_tracked 5") != std::string::npos);

  // at or below K every workload is named and no rollup appears
  std::string all = "\n" + ledger::render_metrics(/*top_k=*/5, false);
  TP_CHECK_EQ(count_series(all, "tpu_pruner_workload_idle_seconds_total"), 5u);
  TP_CHECK(all.find("\"_other\"") == std::string::npos);

  // OpenMetrics form: counter families are typed WITHOUT the _total
  // suffix (the classic form keeps the full sample name)
  std::string om = ledger::render_metrics(2, true);
  TP_CHECK(om.find("# TYPE tpu_pruner_workload_idle_seconds counter") != std::string::npos);
  TP_CHECK(om.find("# TYPE tpu_pruner_workload_idle_seconds_total counter") == std::string::npos);
  TP_CHECK(text.find("# TYPE tpu_pruner_workload_idle_seconds_total counter") != std::string::npos);
}

TP_TEST(ledger_event_history_is_bounded) {
  ledger::reset_for_test();
  ledger::observe_cycle(1, 1000, {obs("flappy", 4)});
  for (uint64_t c = 0; c < 100; ++c) {
    ledger::record_pause(c, "Deployment", "ml", "flappy", "SCALED");
    ledger::record_resume(c, "Deployment", "ml", "flappy", "external");
  }
  Value doc = ledger::workloads_json();
  const Value* a = workload(doc, "Deployment/ml/flappy");
  TP_CHECK_EQ(num(*a, "pauses"), 100.0);
  TP_CHECK_EQ(num(*a, "resumes"), 100.0);
  TP_CHECK(a->find("events")->as_array().size() <= 32);
}

TP_TEST(ledger_checkpoint_roundtrip_restores_totals) {
  std::string path = "/tmp/tp_test_ledger_" + std::to_string(::getpid()) + ".jsonl";
  std::remove(path.c_str());
  ledger::reset_for_test();
  ledger::set_ledger_file(path);
  ledger::observe_cycle(1, 1000, {obs("a", 4), obs("b", 8)});
  ledger::observe_cycle(2, 1010, {obs("a", 4), obs("b", 8)});
  ledger::record_pause(2, "Deployment", "ml", "a", "SCALED");
  ledger::observe_cycle(3, 1025, {obs("b", 8)});
  Value before = ledger::workloads_json();

  // a fresh process restores the checkpoint and reproduces the totals
  // exactly — its first cycle integrates nothing
  ledger::reset_for_test();
  ledger::set_ledger_file(path);
  Value after = ledger::workloads_json();
  TP_CHECK_EQ(num(*after.find("totals"), "reclaimed_chip_seconds"),
              num(*before.find("totals"), "reclaimed_chip_seconds"));
  TP_CHECK_EQ(num(*after.find("totals"), "idle_seconds"),
              num(*before.find("totals"), "idle_seconds"));
  const Value* a = workload(after, "Deployment/ml/a");
  TP_CHECK_EQ(a->get_string("state"), std::string("paused"));
  TP_CHECK_EQ(num(*a, "reclaimed_chip_seconds"), 60.0);  // 4 chips x 15s
  TP_CHECK_EQ(num(*a, "pauses"), 1.0);
  // the restored clock starts fresh: cycle 1 of the new process adds 0
  ledger::observe_cycle(1, 5000, {obs("b", 8)});
  Value again = ledger::workloads_json();
  TP_CHECK_EQ(num(*again.find("totals"), "reclaimed_chip_seconds"), 60.0);
  // ...and the next cycle accrues from the new baseline
  ledger::observe_cycle(2, 5010, {obs("b", 8)});
  again = ledger::workloads_json();
  TP_CHECK_EQ(num(*again.find("totals"), "reclaimed_chip_seconds"), 100.0);
  ledger::reset_for_test();
  std::remove(path.c_str());
}
