// Audit-trail + histogram registry units (the in-process halves of the
// decision audit layer; the e2e behavior rides tests/test_audit_trail.py).
#include "testing.hpp"
#include "tpupruner/audit.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/log.hpp"

namespace audit = tpupruner::audit;
namespace log_ = tpupruner::log;
using tpupruner::json::Value;

namespace {

audit::DecisionRecord make_record(uint64_t cycle, const std::string& pod) {
  audit::DecisionRecord r;
  r.cycle = cycle;
  r.ns = "ml";
  r.pod = pod;
  r.reason = audit::Reason::DryRun;
  r.action = "none";
  return r;
}

}  // namespace

TP_TEST(audit_reason_codes_unique_and_stable) {
  auto codes = audit::all_reason_codes();
  TP_CHECK(codes.size() >= 20);
  for (size_t i = 0; i < codes.size(); ++i) {
    TP_CHECK(!codes[i].empty() && codes[i] != "?");
    for (size_t j = i + 1; j < codes.size(); ++j) TP_CHECK(codes[i] != codes[j]);
  }
  TP_CHECK_EQ(codes.front(), std::string("SCALED"));
  TP_CHECK_EQ(codes.back(), std::string("SLICE_SHARED_BUSY"));
}

TP_TEST(audit_ring_serves_and_filters) {
  audit::reset_for_test();
  uint64_t cycle = audit::begin_cycle();
  audit::record(make_record(cycle, "a"));
  audit::record(make_record(cycle, "b"));

  Value all = audit::decisions_json("");
  TP_CHECK_EQ(all.find("decisions")->as_array().size(), size_t{2});
  Value one = audit::decisions_json("pod=ml/a");
  TP_CHECK_EQ(one.find("decisions")->as_array().size(), size_t{1});
  TP_CHECK_EQ(one.find("decisions")->as_array()[0].get_string("pod"), std::string("a"));
  Value none = audit::decisions_json("namespace=other");
  TP_CHECK_EQ(none.find("decisions")->as_array().size(), size_t{0});
  audit::reset_for_test();
}

TP_TEST(audit_pending_finalize_applies_verdict) {
  audit::reset_for_test();
  uint64_t cycle = audit::begin_cycle();
  audit::record_pending(make_record(cycle, "a"), "Deployment:uid1");
  audit::record_pending(make_record(cycle, "b"), "Deployment:uid1");
  // not visible until finalized
  TP_CHECK_EQ(audit::decisions_json("").find("decisions")->as_array().size(), size_t{0});

  audit::finalize(cycle, "Deployment:uid1", audit::Reason::Scaled, "scale_down");
  Value out = audit::decisions_json("");
  TP_CHECK_EQ(out.find("decisions")->as_array().size(), size_t{2});
  for (const Value& d : out.find("decisions")->as_array()) {
    TP_CHECK_EQ(d.get_string("reason"), std::string("SCALED"));
    TP_CHECK_EQ(d.get_string("action"), std::string("scale_down"));
  }
  // unknown identity is a no-op, not a crash
  audit::finalize(cycle, "nope", audit::Reason::Scaled, "scale_down");
  audit::reset_for_test();
}

TP_TEST(audit_shutdown_drain_lands_pending) {
  audit::reset_for_test();
  uint64_t cycle = audit::begin_cycle();
  audit::record_pending(make_record(cycle, "a"), "JobSet:uid2");
  audit::finalize_all_pending(audit::Reason::ShutdownAborted);
  Value out = audit::decisions_json("");
  TP_CHECK_EQ(out.find("decisions")->as_array().size(), size_t{1});
  TP_CHECK_EQ(out.find("decisions")->as_array()[0].get_string("reason"),
              std::string("SHUTDOWN_ABORTED"));
  audit::reset_for_test();
}

TP_TEST(histogram_observe_buckets_sum_count) {
  log_::histograms_reset_for_test();
  log_::histogram_observe("t_seconds", "query", 0.003, "abc");
  log_::histogram_observe("t_seconds", "query", 0.02, "");
  log_::histogram_observe("t_seconds", "query", 1000.0, "");  // over the top bound

  auto snap = log_::histograms_snapshot();
  const auto& h = snap.at("t_seconds").at("query");
  TP_CHECK_EQ(h.count, uint64_t{3});
  TP_CHECK(h.sum > 1000.0 && h.sum < 1000.1);
  TP_CHECK_EQ(h.buckets.size(), h.bounds.size() + 1);
  uint64_t total = 0;
  for (uint64_t b : h.buckets) total += b;
  TP_CHECK_EQ(total, uint64_t{3});
  TP_CHECK_EQ(h.buckets.back(), uint64_t{1});  // the +Inf overflow landed alone
  // 0.003 falls in the le=0.005 bucket (le is an inclusive upper bound)
  size_t idx = 0;
  while (idx < h.bounds.size() && h.bounds[idx] < 0.003) ++idx;
  TP_CHECK_EQ(h.buckets[idx], uint64_t{1});
  TP_CHECK(h.exemplars[idx].set);
  TP_CHECK_EQ(h.exemplars[idx].trace_id, std::string("abc"));
  log_::histograms_reset_for_test();
}

TP_TEST(decision_record_json_shape) {
  audit::DecisionRecord r = make_record(7, "worker-0");
  r.signal_metric = "tensorcore/duty_cycle";
  r.signal_value = 0.0;
  r.has_signal = true;
  r.lookback_s = 2100;
  r.owner_chain = {"Pod/ml/worker-0", "Job/ml/j", "JobSet/ml/slice"};
  r.root_kind = "JobSet";
  r.root_ns = "ml";
  r.root_name = "slice";
  r.trace_id = "cafe";
  Value v = r.to_json();
  TP_CHECK_EQ(v.find("cycle")->as_int(), int64_t{7});
  TP_CHECK_EQ(v.get_string("reason"), std::string("DRY_RUN"));
  TP_CHECK_EQ(v.find("signal")->get_string("metric"), std::string("tensorcore/duty_cycle"));
  TP_CHECK_EQ(v.find("owner_chain")->as_array().size(), size_t{3});
  TP_CHECK_EQ(v.find("root")->get_string("kind"), std::string("JobSet"));
  TP_CHECK_EQ(v.get_string("trace_id"), std::string("cafe"));
  // absent optionals stay absent (no "signal" when has_signal is false)
  audit::DecisionRecord bare = make_record(1, "x");
  TP_CHECK(!bare.to_json().find("signal"));
  TP_CHECK(!bare.to_json().find("root"));
}
