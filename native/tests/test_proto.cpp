// Binary wire protocol units (native/src/proto.cpp): the hand-rolled
// varint/length-delimited decoder for the runtime.Unknown envelope, the
// Pod-subset schema, the watch-frame scan, and the Prometheus exposition
// — plus the truncation/byte-flip sweeps (the fuzzer-invariant pattern:
// decode either succeeds or throws a typed ParseError, never crashes;
// `just asan-proto` runs this file under AddressSanitizer) and the fused
// decode → journal_touch → store-upsert path under concurrency (`just
// tsan-wire` runs it under ThreadSanitizer).
#include "testing.hpp"

#include <string>
#include <thread>
#include <vector>

#include "tpupruner/informer.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/k8s.hpp"
#include "tpupruner/metrics.hpp"
#include "tpupruner/proto.hpp"

namespace proto = tpupruner::proto;
namespace informer = tpupruner::informer;
namespace k8s = tpupruner::k8s;
using tpupruner::json::ParseError;
using tpupruner::json::Value;

namespace {

// ── tiny encoder (the C++ twin of tpu_pruner/testing/wire_proto.py) ──

std::string enc_varint(uint64_t n) {
  std::string out;
  while (true) {
    uint8_t b = n & 0x7F;
    n >>= 7;
    if (n) out.push_back(static_cast<char>(b | 0x80));
    else {
      out.push_back(static_cast<char>(b));
      return out;
    }
  }
}

std::string enc_tag(uint32_t field, uint32_t wt) { return enc_varint((field << 3) | wt); }

std::string enc_ld(uint32_t field, const std::string& data) {
  return enc_tag(field, 2) + enc_varint(data.size()) + data;
}

std::string enc_str(uint32_t field, const std::string& s) { return enc_ld(field, s); }

std::string enc_unknown(const std::string& api_version, const std::string& kind,
                        const std::string& raw) {
  std::string tm;
  if (!api_version.empty()) tm += enc_str(1, api_version);
  if (!kind.empty()) tm += enc_str(2, kind);
  return std::string("k8s\x00", 4) + enc_ld(1, tm) + enc_ld(2, raw);
}

// metadata {name, namespace, uid, resourceVersion, labels{app:demo},
// ownerReferences[{kind,name,uid,apiVersion,controller}]}, spec
// {containers[{name, resources{requests/limits google.com/tpu=4}}]},
// status {phase Running}.
std::string enc_demo_pod() {
  std::string meta = enc_str(1, "pod-0") + enc_str(3, "ml") + enc_str(5, "uid-0") +
                     enc_str(6, "41");
  meta += enc_ld(11, enc_str(1, "app") + enc_str(2, "demo"));
  std::string owner = enc_str(1, "ReplicaSet") + enc_str(3, "rs-0") + enc_str(4, "uid-rs") +
                      enc_str(5, "apps/v1") + enc_tag(6, 0) + enc_varint(1);
  meta += enc_ld(13, owner);
  std::string quantity = enc_ld(2, enc_str(1, "4"));
  std::string requests = enc_ld(2, enc_str(1, "google.com/tpu") + quantity);
  std::string limits = enc_ld(1, enc_str(1, "google.com/tpu") + quantity);
  std::string container = enc_str(1, "main") + enc_ld(8, limits + requests);
  std::string spec = enc_ld(2, container);
  std::string status = enc_str(1, "Running");
  return enc_ld(1, meta) + enc_ld(2, spec) + enc_ld(3, status);
}

std::string enc_demo_list() {
  std::string list_meta = enc_str(2, "41");  // resourceVersion
  return enc_unknown("v1", "PodList", enc_ld(1, list_meta) + enc_ld(2, enc_demo_pod()));
}

std::string enc_watch_frame(const std::string& type) {
  std::string inner = enc_unknown("v1", "Pod", enc_demo_pod());
  std::string we = enc_str(1, type) + enc_ld(2, enc_ld(1, inner));
  return enc_unknown("v1", "WatchEvent", we);
}

}  // namespace

// ── decode correctness ──────────────────────────────────────────────────

TP_TEST(proto_pod_materializes_like_its_json_form) {
  Value pod = proto::object_to_value(enc_demo_pod(), "v1", "Pod");
  Value expect = Value::parse(R"({
    "apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "pod-0", "namespace": "ml", "uid": "uid-0",
                 "resourceVersion": "41", "labels": {"app": "demo"},
                 "ownerReferences": [{"apiVersion": "apps/v1", "kind": "ReplicaSet",
                                      "name": "rs-0", "uid": "uid-rs",
                                      "controller": true}]},
    "spec": {"containers": [{"name": "main",
              "resources": {"limits": {"google.com/tpu": "4"},
                            "requests": {"google.com/tpu": "4"}}}]},
    "status": {"phase": "Running"}})");
  TP_CHECK_EQ(pod.dump(), expect.dump());
  // the chip accounting reads straight through the materialized form
  TP_CHECK_EQ(tpupruner::core::pod_chip_count(pod), int64_t{4});
}

TP_TEST(proto_list_scan_extracts_keys_in_one_pass) {
  proto::ListPagePtr page = proto::parse_list(enc_demo_list());
  TP_CHECK_EQ(page->api_version, std::string("v1"));
  TP_CHECK_EQ(page->kind, std::string("Pod"));
  TP_CHECK_EQ(page->resource_version, std::string("41"));
  TP_CHECK_EQ(page->items.size(), size_t{1});
  const proto::ObjectRef& ref = page->items[0];
  TP_CHECK_EQ(ref.ns, std::string("ml"));
  TP_CHECK_EQ(ref.name, std::string("pod-0"));
  TP_CHECK_EQ(ref.fp, proto::fingerprint(enc_demo_pod()));
  Value pod = proto::object_to_value(
      std::string_view(page->body.data() + ref.off, ref.len), page->api_version, page->kind);
  TP_CHECK_EQ(pod.at_path("metadata.name")->as_string(), std::string("pod-0"));
}

TP_TEST(proto_watch_frame_single_scan) {
  proto::WatchEventPtr ev = proto::parse_watch_event(enc_watch_frame("MODIFIED"));
  TP_CHECK_EQ(ev->type, std::string("MODIFIED"));
  TP_CHECK(ev->has_object);
  TP_CHECK_EQ(ev->ns, std::string("ml"));
  TP_CHECK_EQ(ev->name, std::string("pod-0"));
  TP_CHECK_EQ(ev->resource_version, std::string("41"));
  TP_CHECK_EQ(ev->fp, proto::fingerprint(enc_demo_pod()));
  Value pod = proto::object_to_value(
      std::string_view(ev->body.data() + ev->obj_off, ev->obj_len), ev->api_version, ev->kind);
  TP_CHECK_EQ(pod.at_path("metadata.namespace")->as_string(), std::string("ml"));
}

TP_TEST(proto_error_event_carries_status_code) {
  std::string status = enc_str(3, "too old resource version") + enc_tag(6, 0) + enc_varint(410);
  std::string inner = enc_unknown("v1", "Status", status);
  std::string we = enc_str(1, "ERROR") + enc_ld(2, enc_ld(1, inner));
  proto::WatchEventPtr ev = proto::parse_watch_event(enc_unknown("v1", "WatchEvent", we));
  TP_CHECK_EQ(ev->type, std::string("ERROR"));
  TP_CHECK_EQ(ev->error_code, int64_t{410});
  TP_CHECK_EQ(ev->error_message, std::string("too old resource version"));
}

TP_TEST(proto_rejects_missing_magic_and_bad_list_kind) {
  bool threw = false;
  try {
    proto::parse_list("xyz" + enc_demo_list());
  } catch (const ParseError&) {
    threw = true;
  }
  TP_CHECK(threw);
  threw = false;
  try {
    proto::parse_list(enc_unknown("v1", "Pod", enc_demo_pod()));  // not a *List
  } catch (const ParseError&) {
    threw = true;
  }
  TP_CHECK(threw);
}

// ── truncation / byte-flip sweeps (fuzzer-invariant pattern) ────────────

namespace {

// Decode must either succeed or throw ParseError; anything else —
// another exception type, a crash, an OOB read (ASan) — fails.
template <typename Fn>
void sweep(const std::string& body, Fn&& decode) {
  for (size_t cut = 0; cut <= body.size(); ++cut) {
    try {
      decode(body.substr(0, cut));
    } catch (const ParseError&) {
    }
  }
  for (size_t i = 0; i < body.size(); ++i) {
    std::string mutated = body;
    mutated[i] = static_cast<char>(mutated[i] ^ 0xFF);
    try {
      decode(mutated);
    } catch (const ParseError&) {
    }
  }
}

}  // namespace

TP_TEST(proto_truncation_and_byteflip_sweep_list) {
  sweep(enc_demo_list(), [](const std::string& b) { proto::parse_list(b); });
}

TP_TEST(proto_truncation_and_byteflip_sweep_watch) {
  sweep(enc_watch_frame("ADDED"), [](const std::string& b) { proto::parse_watch_event(b); });
}

TP_TEST(proto_truncation_and_byteflip_sweep_prom) {
  std::string series = enc_ld(1, enc_str(1, "exported_pod") + enc_str(2, "pod-0")) +
                       enc_ld(1, enc_str(1, "exported_namespace") + enc_str(2, "ml")) +
                       enc_ld(1, enc_str(1, "exported_container") + enc_str(2, "main")) +
                       enc_str(2, "1754300000.25") + enc_str(3, "0.0");
  std::string body = enc_str(1, "success") + enc_ld(4, series);
  sweep(body, [](const std::string& b) { proto::parse_prom_vector(b); });
  // and the full body must actually decode
  proto::PromVector v = proto::parse_prom_vector(body);
  TP_CHECK_EQ(v.result.size(), size_t{1});
  auto decoded = tpupruner::metrics::decode_instant_vector(v, "tpu", "gmp");
  TP_CHECK_EQ(decoded.samples.size(), size_t{1});
  TP_CHECK_EQ(decoded.samples[0].name, std::string("pod-0"));
}

// ── canonical body reconstruction (python json.dumps fidelity) ──────────

TP_TEST(proto_prom_canonical_body_matches_python_dumps) {
  proto::PromVector v;
  v.status = "success";
  proto::PromSeries s;
  s.labels = {{"exported_pod", "pod-0"}, {"exported_namespace", "ml"}};
  s.ts_text = "1754300000.25";
  s.value_text = "0.0";
  v.result.push_back(s);
  TP_CHECK_EQ(proto::prom_canonical_body(v),
              std::string("{\"status\": \"success\", \"data\": {\"resultType\": \"vector\", "
                          "\"result\": [{\"metric\": {\"exported_pod\": \"pod-0\", "
                          "\"exported_namespace\": \"ml\"}, \"value\": [1754300000.25, "
                          "\"0.0\"]}]}}"));
  proto::PromVector empty;
  empty.status = "success";
  TP_CHECK_EQ(proto::prom_canonical_body(empty),
              std::string("{\"status\": \"success\", \"data\": {\"resultType\": \"vector\", "
                          "\"result\": []}}"));
}

TP_TEST(proto_python_json_escape_matches_ensure_ascii) {
  auto esc = [](std::string_view in) {
    std::string out;
    proto::python_json_escape(out, in);
    return out;
  };
  TP_CHECK_EQ(esc("plain"), std::string("plain"));
  TP_CHECK_EQ(esc("a\"b\\c"), std::string("a\\\"b\\\\c"));
  TP_CHECK_EQ(esc("\n\t\r\b\f"), std::string("\\n\\t\\r\\b\\f"));
  TP_CHECK_EQ(esc(std::string("\x01", 1)), std::string("\\u0001"));
  TP_CHECK_EQ(esc("caf\xc3\xa9"), std::string("caf\\u00e9"));          // é
  TP_CHECK_EQ(esc("\xf0\x9f\x98\x80"), std::string("\\ud83d\\ude00"));  // 😀 pair
}

// ── the fused path: decode → fingerprint → journal_touch → upsert ──────

namespace {

const k8s::Client& offline_client() {
  static k8s::Client client = [] {
    k8s::Config cfg;
    cfg.api_url = "http://127.0.0.1:1";  // never dialed by apply_* units
    return k8s::Client(std::move(cfg));
  }();
  return client;
}

}  // namespace

TP_TEST(proto_fused_apply_journals_and_stores_without_materializing) {
  informer::Reflector r(offline_client(), *informer::spec_for("pods"));
  r.enable_dirty_journal();
  proto::WatchEventPtr ev = proto::parse_watch_event(enc_watch_frame("ADDED"));
  TP_CHECK(r.apply_event_proto(ev));
  const std::string path = "/api/v1/namespaces/ml/pods/pod-0";
  std::vector<std::string> dirty;
  bool all = false;
  r.drain_dirty(dirty, all);
  TP_CHECK(!all);
  TP_CHECK_EQ(dirty.size(), size_t{1});
  TP_CHECK_EQ(dirty[0], path);
  // the store answers with the materialized twin of the JSON form
  auto got = r.get(path);
  TP_CHECK(got.has_value());
  TP_CHECK_EQ(got->at_path("metadata.resourceVersion")->as_string(), std::string("41"));
  TP_CHECK_EQ(tpupruner::core::pod_chip_count(*got), int64_t{4});
  // DELETED erases and journals again
  proto::WatchEventPtr del = proto::parse_watch_event(enc_watch_frame("DELETED"));
  TP_CHECK(r.apply_event_proto(del));
  dirty.clear();
  r.drain_dirty(dirty, all);
  TP_CHECK_EQ(dirty.size(), size_t{1});
  TP_CHECK(!r.get(path).has_value());
}

TP_TEST(proto_fused_store_keeps_fingerprint_until_materialized) {
  informer::Store store;
  std::string pod_bytes = enc_demo_pod();
  auto body = std::make_shared<const std::string>(pod_bytes);
  uint64_t fp = proto::fingerprint(pod_bytes);
  store.upsert_proto("/api/v1/namespaces/ml/pods/pod-0", body, 0, body->size(), "v1", "Pod",
                     fp);
  TP_CHECK_EQ(store.proto_fingerprint("/api/v1/namespaces/ml/pods/pod-0"), fp);
  TP_CHECK(store.get("/api/v1/namespaces/ml/pods/pod-0").has_value());
}

TP_TEST(proto_fused_journal_concurrent_apply_and_drain_is_race_free) {
  // The TSan target (`just tsan-wire`): reflector threads apply fused
  // events while the producer drains the journal — exactly the
  // concurrency the incremental engine rides every warm cycle.
  informer::Reflector r(offline_client(), *informer::spec_for("pods"));
  r.enable_dirty_journal();
  std::thread applier([&] {
    for (int i = 0; i < 500; ++i) {
      proto::WatchEventPtr ev =
          proto::parse_watch_event(enc_watch_frame(i % 2 ? "MODIFIED" : "ADDED"));
      r.apply_event_proto(ev);
    }
  });
  std::thread reader([&] {
    for (int i = 0; i < 200; ++i) {
      r.get("/api/v1/namespaces/ml/pods/pod-0");
    }
  });
  size_t drained = 0;
  bool all = false;
  for (int i = 0; i < 200; ++i) {
    std::vector<std::string> dirty;
    r.drain_dirty(dirty, all);
    drained += dirty.size();
  }
  applier.join();
  reader.join();
  std::vector<std::string> dirty;
  r.drain_dirty(dirty, all);
  drained += dirty.size();
  TP_CHECK_EQ(drained, size_t{500});
}
