// FetchCache single-flight semantics (native/include/tpupruner/walker.hpp).
// The cache sits under the concurrent resolve fan-out: every pod of a
// slice demands the same Job→JobSet chain, so correctness here decides
// both the API-call count and WHICH owner gets scaled (a poisoned miss
// would demote a Deployment to its ReplicaSet). Exercised under TSan via
// `just test-tsan`.
#include "testing.hpp"

#include <atomic>
#include <chrono>
#include <stdexcept>
#include <thread>
#include <vector>

#include "tpupruner/walker.hpp"

using tpupruner::json::Value;
using tpupruner::walker::FetchCache;

TP_TEST(fetch_cache_single_flight_one_fetch_for_concurrent_callers) {
  FetchCache cache;
  std::atomic<int> fetches{0};
  auto slow_fetch = [&]() -> FetchCache::Entry {
    fetches.fetch_add(1);
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    return Value::parse(R"({"metadata":{"name":"dep"}})");
  };
  std::vector<std::thread> threads;
  std::atomic<int> ok{0};
  for (int i = 0; i < 8; ++i) {
    threads.emplace_back([&] {
      FetchCache::Entry e = cache.get_or_fetch("apis/.../dep", slow_fetch);
      if (e && e->at_path("metadata.name")->as_string() == "dep") ok.fetch_add(1);
    });
  }
  for (auto& t : threads) t.join();
  TP_CHECK_EQ(fetches.load(), 1);  // everyone else blocked on the leader
  TP_CHECK_EQ(ok.load(), 8);
}

TP_TEST(fetch_cache_miss_is_cached_too) {
  FetchCache cache;
  std::atomic<int> fetches{0};
  auto fetch_404 = [&]() -> FetchCache::Entry {
    fetches.fetch_add(1);
    return std::nullopt;  // 404: remembered for the cycle
  };
  TP_CHECK(!cache.get_or_fetch("k", fetch_404).has_value());
  TP_CHECK(!cache.get_or_fetch("k", fetch_404).has_value());
  TP_CHECK_EQ(fetches.load(), 1);
}

TP_TEST(fetch_cache_leader_failure_not_cached_waiters_retry) {
  FetchCache cache;
  std::atomic<int> attempts{0};
  auto flaky = [&]() -> FetchCache::Entry {
    if (attempts.fetch_add(1) == 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(30));
      throw std::runtime_error("transient 500");
    }
    return Value::parse(R"({"ok":true})");
  };
  std::atomic<int> got{0}, threw{0};
  std::vector<std::thread> threads;
  for (int i = 0; i < 6; ++i) {
    threads.emplace_back([&] {
      try {
        if (cache.get_or_fetch("k", flaky)) got.fetch_add(1);
      } catch (const std::runtime_error&) {
        threw.fetch_add(1);  // only the failing leader itself rethrows
      }
    });
  }
  for (auto& t : threads) t.join();
  // the first leader failed (and threw to its caller); a waiter became the
  // new leader, succeeded, and the rest got its entry — exactly 2 attempts
  TP_CHECK_EQ(attempts.load(), 2);
  TP_CHECK_EQ(threw.load(), 1);
  TP_CHECK_EQ(got.load(), 5);
  // and the success IS cached now
  TP_CHECK(cache.get_or_fetch("k", flaky).has_value());
  TP_CHECK_EQ(attempts.load(), 2);
}

TP_TEST(fetch_cache_seed_prevents_fetch_and_first_writer_wins) {
  FetchCache cache;
  cache.seed("k", Value::parse(R"({"v":1})"));
  cache.seed("k", Value::parse(R"({"v":2})"));  // no-op: first writer wins
  std::atomic<int> fetches{0};
  auto fetch = [&]() -> FetchCache::Entry {
    fetches.fetch_add(1);
    return std::nullopt;
  };
  FetchCache::Entry e = cache.get_or_fetch("k", fetch);
  TP_CHECK(e.has_value());
  TP_CHECK_EQ(e->find("v")->as_int(), 1);
  TP_CHECK_EQ(fetches.load(), 0);
}
