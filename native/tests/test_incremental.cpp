// Differential reconcile engine units (tpupruner/incremental.hpp) — the
// dirty-set planner and memoized decision cache behind --incremental.
// What is pinned here:
//   - the three invalidation sources (watch events via pod map + object
//     reverse index, sample-fingerprint diffs, timer/config edges) each
//     dirty exactly the affected units;
//   - relist / untrusted-store / journal-overflow degrade to a FULL
//     recompute, never to a silently stale cache;
//   - the actuation state machine: an enqueued unit stays dirty until the
//     consumer reports a cacheable no-op, and anything that mutated the
//     cluster recomputes next cycle (the overlap-deferral bug class);
//   - wave-2 invalidation hands back a cached unit's members when a
//     recomputed pod resolves into it;
//   - the cache is written by the producer and updated by concurrent
//     consumers — the TSan tier (just tsan-incremental) runs these tests
//     to prove the locking.
#include "testing.hpp"

#include <thread>
#include <vector>

#include "tpupruner/incremental.hpp"
#include "tpupruner/metrics.hpp"

namespace incremental = tpupruner::incremental;
namespace metrics = tpupruner::metrics;
using tpupruner::audit::Reason;
using tpupruner::core::PodMetricSample;
using tpupruner::informer::ClusterCache;

namespace {

PodMetricSample sample(const std::string& ns, const std::string& name, double value = 0.0) {
  PodMetricSample s;
  s.ns = ns;
  s.name = name;
  s.container = "main";
  s.node_type = "tpu-v5-lite-podslice";
  s.accelerator = "tpu-v5-lite-podslice";
  s.value = value;
  return s;
}

incremental::Unit unit_for(const std::string& key,
                           const std::vector<PodMetricSample>& pods,
                           const std::string& object_path = "") {
  incremental::Unit u;
  u.key = key;
  for (const PodMetricSample& p : pods) {
    u.members.emplace_back(p.ns + "/" + p.name, metrics::sample_fingerprint(p));
  }
  if (!object_path.empty()) u.objects.emplace_back(object_path, std::nullopt);
  return u;
}

// A fresh enabled engine seeded with `units` via a full-recompute commit.
void seed(incremental::Engine& e, std::vector<incremental::Unit> units) {
  e.configure(true, 42);
  incremental::Engine::Plan full;
  full.active = true;
  full.full = true;
  e.commit_cycle(full, std::move(units));
}

}  // namespace

TP_TEST(incremental_quiesced_cluster_serves_everything_from_cache) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "a"), sample("ml", "b")};
  seed(e, {unit_for("Deployment/uid:1", pods, "/apis/apps/v1/namespaces/ml/deployments/d")});
  ClusterCache::DirtyDrain drain;  // no events
  auto plan = e.plan_cycle(pods, drain, 1000, true);
  TP_CHECK(plan.active);
  TP_CHECK(!plan.full);
  TP_CHECK_EQ(plan.recompute.size(), size_t(0));
  TP_CHECK_EQ(plan.hits, size_t(2));
  TP_CHECK_EQ(plan.cached.size(), size_t(1));
}

TP_TEST(incremental_sample_change_dirties_pod_and_unit) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "a"), sample("ml", "b")};
  seed(e, {unit_for("Deployment/uid:1", pods)});
  std::vector<PodMetricSample> next = pods;
  next[0].value = 0.5;  // the sample diff — fingerprint flips
  auto plan = e.plan_cycle(next, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK(!plan.full);
  // The dirty pod drags its whole unit (sibling included) into recompute.
  TP_CHECK_EQ(plan.recompute.size(), size_t(2));
  TP_CHECK_EQ(plan.hits, size_t(0));
  TP_CHECK_EQ(plan.dirty_units.size(), size_t(1));
  TP_CHECK_EQ(plan.dirty_units[0], std::string("Deployment/uid:1"));
}

TP_TEST(incremental_new_and_absent_pods_dirty) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "a")};
  seed(e, {unit_for("Deployment/uid:1", pods)});
  // New pod: recomputes (and may wave-2 into a cached root later).
  std::vector<PodMetricSample> with_new = {sample("ml", "a"), sample("ml", "new")};
  auto plan = e.plan_cycle(with_new, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.recompute.size(), size_t(1));
  TP_CHECK_EQ(with_new[plan.recompute[0]].name, std::string("new"));
  TP_CHECK_EQ(plan.hits, size_t(1));
  // Absent member: the unit is dirty even though no present pod changed.
  auto plan2 = e.plan_cycle({}, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan2.cached.size(), size_t(0));
}

TP_TEST(incremental_watch_event_dirties_via_pod_map_and_object_index) {
  incremental::Engine e;
  std::vector<PodMetricSample> a = {sample("ml", "a")};
  std::vector<PodMetricSample> b = {sample("ml", "b")};
  incremental::Unit ua = unit_for("Deployment/uid:1", a, "/apis/apps/v1/namespaces/ml/deployments/da");
  incremental::Unit ub = unit_for("Deployment/uid:2", b, "/apis/apps/v1/namespaces/ml/deployments/db");
  seed(e, {ua, ub});
  std::vector<PodMetricSample> all = {sample("ml", "a"), sample("ml", "b")};

  // Pod event → unit 1 dirty via the pod→unit map.
  ClusterCache::DirtyDrain pod_ev;
  pod_ev.paths.push_back("/api/v1/namespaces/ml/pods/a");
  auto plan = e.plan_cycle(all, pod_ev, 1000, true);
  TP_CHECK(plan.dirty_units == (std::vector<std::string>{"Deployment/uid:1"}));
  TP_CHECK_EQ(plan.hits, size_t(1));

  // Owner event → unit 2 dirty via the consulted-object reverse index.
  ClusterCache::DirtyDrain owner_ev;
  owner_ev.paths.push_back("/apis/apps/v1/namespaces/ml/deployments/db");
  plan = e.plan_cycle(all, owner_ev, 1000, true);
  TP_CHECK(plan.dirty_units == (std::vector<std::string>{"Deployment/uid:2"}));

  // Unrelated event → nothing dirties.
  ClusterCache::DirtyDrain other;
  other.paths.push_back("/apis/apps/v1/namespaces/elsewhere/deployments/x");
  plan = e.plan_cycle(all, other, 1000, true);
  TP_CHECK_EQ(plan.dirty_units.size(), size_t(0));
  TP_CHECK_EQ(plan.hits, size_t(2));
}

TP_TEST(incremental_relist_and_untrusted_store_force_full_recompute) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "a")};
  seed(e, {unit_for("Deployment/uid:1", pods)});
  ClusterCache::DirtyDrain relist;
  relist.all = true;
  auto plan = e.plan_cycle(pods, relist, 1000, true);
  TP_CHECK(plan.full);
  TP_CHECK_EQ(plan.recompute.size(), size_t(1));
  TP_CHECK_EQ(plan.cached.size(), size_t(0));
  // Unsynced store: the journal can't vouch for object freshness.
  seed(e, {unit_for("Deployment/uid:1", pods)});
  plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, false);
  TP_CHECK(plan.full);
}

TP_TEST(incremental_timer_unit_self_dirties_at_deadline) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "young")};
  incremental::Unit u = unit_for("pod:ml/young", pods);
  u.deadline_unix = 500;
  seed(e, {u});
  auto before = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 499, true);
  TP_CHECK_EQ(before.hits, size_t(1));
  auto at = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 500, true);
  TP_CHECK_EQ(at.hits, size_t(0));
  TP_CHECK_EQ(at.recompute.size(), size_t(1));
}

TP_TEST(incremental_never_cache_units_recompute_every_cycle) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("tpu-jobs", "host-0")};
  incremental::Unit u = unit_for("JobSet/uid:7", pods);
  u.never_cache = true;  // transients, GET-fallback pods, unparsed timers
  seed(e, {u});
  auto plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
  TP_CHECK_EQ(plan.recompute.size(), size_t(1));
}

TP_TEST(incremental_enqueued_unit_stays_dirty_until_noop_reported) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "a")};
  seed(e, {unit_for("Deployment/uid:1", pods)});
  // Enqueued, no outcome yet → dirty (a deferral or in-flight actuation
  // must never be served from cache on the following cycle).
  e.mark_enqueued(7, "Deployment/uid:1");
  auto plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
  // A mutating outcome (SCALED) keeps it dirty.
  seed(e, {unit_for("Deployment/uid:1", pods)});
  e.mark_enqueued(8, "Deployment/uid:1");
  e.record_actuation_outcome(8, "Deployment/uid:1", Reason::Scaled, "scale_down", "");
  plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
  // A verified no-op makes it cacheable, and the verdict rides the unit.
  seed(e, {unit_for("Deployment/uid:1", pods)});
  e.mark_enqueued(9, "Deployment/uid:1");
  e.record_actuation_outcome(9, "Deployment/uid:1", Reason::AlreadyPaused, "none",
                             "root already at its paused state");
  plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(1));
  const incremental::Unit* cached = plan.cached.at("Deployment/uid:1");
  TP_CHECK(cached->actuation == incremental::Unit::Actuation::Noop);
  TP_CHECK(cached->noop_reason == Reason::AlreadyPaused);
  // A stale outcome (wrong cycle) is ignored.
  seed(e, {unit_for("Deployment/uid:1", pods)});
  e.mark_enqueued(10, "Deployment/uid:1");
  e.record_actuation_outcome(3, "Deployment/uid:1", Reason::AlreadyPaused, "none", "");
  plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
}

TP_TEST(incremental_group_verdict_gates_caching) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("tpu-jobs", "host-0"),
                                       sample("tpu-jobs", "host-1")};
  incremental::Unit u = unit_for("JobSet/uid:7", pods);
  u.group_verdict = incremental::Unit::GroupVerdict::Unknown;
  u.group_ns = "tpu-jobs";
  seed(e, {u});
  // Unknown verdict (never verified / gate failed / not fully idle):
  // the unit re-gates — and re-resolves — every cycle.
  auto plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
  // A verified all-idle verdict makes it cacheable...
  seed(e, {u});
  e.record_group_verdict("JobSet/uid:7", true);
  plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(2));
  // ...until ANY pod event lands in the group's namespace (the gate's
  // LIST covers pods the candidate set cannot see).
  ClusterCache::DirtyDrain ns_event;
  ns_event.paths.push_back("/api/v1/namespaces/tpu-jobs/pods/some-other-pod");
  plan = e.plan_cycle(pods, ns_event, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
  // A pod event elsewhere leaves the verdict standing.
  seed(e, {u});
  e.record_group_verdict("JobSet/uid:7", true);
  ClusterCache::DirtyDrain other_ns;
  other_ns.paths.push_back("/api/v1/namespaces/elsewhere/pods/p");
  plan = e.plan_cycle(pods, other_ns, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(2));
  // A failed/not-idle verdict resets to Unknown — never sticky.
  e.record_group_verdict("JobSet/uid:7", false);
  plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
}

TP_TEST(incremental_wave2_invalidation_returns_members) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "a"), sample("ml", "b")};
  seed(e, {unit_for("Deployment/uid:1", pods)});
  std::vector<PodMetricSample> with_new = {sample("ml", "a"), sample("ml", "b"),
                                           sample("ml", "joiner")};
  auto plan = e.plan_cycle(with_new, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(2));
  // The joiner's walk resolved into the cached root: its siblings come
  // back for re-walking and the unit stops serving.
  auto members = e.invalidate_unit(plan, "Deployment/uid:1");
  TP_CHECK_EQ(members.size(), size_t(2));
  TP_CHECK_EQ(plan.hits, size_t(0));
  TP_CHECK_EQ(plan.cached.size(), size_t(0));
  // Second invalidation is a no-op.
  TP_CHECK_EQ(e.invalidate_unit(plan, "Deployment/uid:1").size(), size_t(0));
}

TP_TEST(incremental_config_edge_clears_cache) {
  incremental::Engine e;
  std::vector<PodMetricSample> pods = {sample("ml", "a")};
  seed(e, {unit_for("Deployment/uid:1", pods)});
  e.configure(true, 43);  // flag fingerprint changed
  auto plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(0));
  TP_CHECK_EQ(e.unit_count(), size_t(0));
}

TP_TEST(incremental_commit_drops_vanished_units_and_reindexes) {
  incremental::Engine e;
  std::vector<PodMetricSample> a = {sample("ml", "a")};
  std::vector<PodMetricSample> b = {sample("ml", "b")};
  seed(e, {unit_for("Deployment/uid:1", a), unit_for("Deployment/uid:2", b)});
  TP_CHECK_EQ(e.unit_count(), size_t(2));
  // Next cycle only unit 2 is present and clean; unit 1's pod vanished.
  auto plan = e.plan_cycle(b, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan.hits, size_t(1));
  e.commit_cycle(plan, {});
  TP_CHECK_EQ(e.unit_count(), size_t(1));
  // The vanished pod's map entry is gone: it re-registers as new.
  auto plan2 = e.plan_cycle(a, ClusterCache::DirtyDrain{}, 1000, true);
  TP_CHECK_EQ(plan2.recompute.size(), size_t(1));
}

TP_TEST(incremental_pod_key_of_path_parses_only_pod_paths) {
  TP_CHECK_EQ(incremental::pod_key_of_path("/api/v1/namespaces/ml/pods/a"),
              std::string("ml/a"));
  TP_CHECK_EQ(incremental::pod_key_of_path("/apis/apps/v1/namespaces/ml/deployments/d"),
              std::string(""));
  TP_CHECK_EQ(incremental::pod_key_of_path("/api/v1/namespaces/ml/configmaps/c"),
              std::string(""));
  TP_CHECK_EQ(incremental::pod_key_of_path("/api/v1/namespaces/ml/pods/a/status"),
              std::string(""));
}

TP_TEST(incremental_sample_fingerprint_field_sensitivity) {
  PodMetricSample s = sample("ml", "a", 0.0);
  uint64_t base = metrics::sample_fingerprint(s);
  TP_CHECK_EQ(metrics::sample_fingerprint(s), base);  // stable
  PodMetricSample v = s;
  v.value = 0.25;
  TP_CHECK(metrics::sample_fingerprint(v) != base);
  PodMetricSample acc = s;
  acc.accelerator = "tpu-v4-podslice";
  TP_CHECK(metrics::sample_fingerprint(acc) != base);
  // Field-delimited: ("ab","c") vs ("a","bc") must not collide.
  PodMetricSample x = sample("ml", "ab");
  x.container = "c";
  PodMetricSample y = sample("ml", "a");
  y.container = "bc";
  TP_CHECK(metrics::sample_fingerprint(x) != metrics::sample_fingerprint(y));
}

TP_TEST(incremental_concurrent_consumers_and_planner_race_free) {
  // The cache is written by the producer (plan/commit) while consumer
  // threads report actuation outcomes — the TSan tier runs this test to
  // prove the engine's locking (just tsan-incremental).
  incremental::Engine e;
  std::vector<PodMetricSample> pods;
  std::vector<incremental::Unit> units;
  for (int i = 0; i < 16; ++i) {
    PodMetricSample p = sample("ml", "p" + std::to_string(i));
    pods.push_back(p);
    units.push_back(unit_for("Deployment/uid:" + std::to_string(i), {p}));
  }
  seed(e, units);
  std::vector<std::thread> consumers;
  for (int t = 0; t < 4; ++t) {
    consumers.emplace_back([&e, t] {
      for (int i = 0; i < 200; ++i) {
        std::string key = "Deployment/uid:" + std::to_string((t * 53 + i) % 16);
        e.record_actuation_outcome(1, key, Reason::AlreadyPaused, "none", "noop");
      }
    });
  }
  for (int cycle = 0; cycle < 50; ++cycle) {
    auto plan = e.plan_cycle(pods, ClusterCache::DirtyDrain{}, 1000 + cycle, true);
    // Recompute dirty units (what the daemon's resolve stage would do):
    // commit a fresh unit for every pod not served from cache.
    std::vector<incremental::Unit> fresh;
    for (size_t idx : plan.recompute) {
      fresh.push_back(unit_for("Deployment/uid:" + std::to_string(idx), {pods[idx]}));
    }
    e.commit_cycle(plan, std::move(fresh));
    for (int i = 0; i < 4; ++i) {
      e.mark_enqueued(1, "Deployment/uid:" + std::to_string(i));
    }
  }
  for (std::thread& t : consumers) t.join();
  TP_CHECK_EQ(e.unit_count(), size_t(16));
}
