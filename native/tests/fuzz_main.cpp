// Deterministic mutation fuzzer for the untrusted-input surfaces.
//
// The daemon parses three kinds of bytes it does not control: Prometheus
// query responses, K8s API responses (both through json::Value::parse →
// decode_instant_vector / check_eligibility / the owner walk's shape
// probing), and RFC 3339 timestamps from object metadata. The reference
// gets memory safety from Rust; this tier compensates in C++ (SURVEY.md §5
// "race detection/sanitizers": the build adds what the reference lacks).
//
// No libFuzzer in this toolchain (g++ only), so this is a self-contained
// corpus-mutation loop with a fixed-seed xorshift PRNG — fully
// deterministic for a given (iterations, seed) pair, so CI failures
// reproduce exactly. Run under ASan/UBSan (build-asan); it is
// single-threaded, so TSan adds nothing here.
//
// Invariants checked per iteration:
//   1. parse() either returns a Value or throws json::ParseError — any
//      other exception type or a crash is a bug;
//   2. parse → dump → parse round-trips to an equal Value;
//   3. decode_instant_vector / check_eligibility on arbitrary parsed JSON
//      throw nothing worse than std::runtime_error (their documented
//      failure mode) — shape probing must never crash on hostile shapes;
//   4. util::parse_rfc3339 never throws on any byte string.
//
// Usage: tpupruner_fuzz [iterations] [seed]   (defaults: 50000, 0xC0FFEE)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "../src/otlp_grpc.hpp"
#include "tpupruner/core.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/metrics.hpp"
#include "tpupruner/util.hpp"

namespace {

using tpupruner::json::ParseError;
using tpupruner::json::Value;

struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 1) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
  size_t below(size_t n) { return n ? static_cast<size_t>(next() % n) : 0; }
};

// Seed corpus: the real wire shapes plus near-miss malformations.
const std::vector<std::string>& seeds() {
  static const std::vector<std::string> kSeeds = {
      // Prometheus instant vector (the decode_instant_vector contract)
      R"({"status":"success","data":{"resultType":"vector","result":[
        {"metric":{"pod":"trainer-0","namespace":"ml","container":"main",
         "modelName":"tpu-v5e","nodeType":"ct5lp-hightpu-4t"},
         "value":[1722249000.123,"0"]},
        {"metric":{"exported_pod":"t-1","exported_namespace":"ml"},
         "value":[1722249000.123,"0.0"]}]}})",
      R"({"status":"error","errorType":"bad_data","error":"parse error"})",
      // Pod object (eligibility + walker shape probing)
      R"({"apiVersion":"v1","kind":"Pod","metadata":{"name":"w-0",
        "namespace":"tpu","creationTimestamp":"2026-07-28T10:00:00Z",
        "labels":{"jobset.sigs.k8s.io/jobset-name":"slice-a"},
        "ownerReferences":[{"kind":"Job","name":"slice-a-workers-0",
          "uid":"u1","apiVersion":"batch/v1"}]},
        "spec":{"containers":[{"name":"m","resources":{"requests":
          {"google.com/tpu":"4"}}}]},"status":{"phase":"Running"}})",
      // List envelope
      R"({"kind":"PodList","apiVersion":"v1","items":[]})",
      // Numbers, escapes, unicode, nesting
      R"([0,-1,1e308,-2.5e-308,18446744073709551615,
          " 😀\\\"\n",[[[[{"k":[null,true,false]}]]]]])",
      R"({"value":[1722249000.123,"NaN"]})",
      "[",
      "{\"a\":",
      "\"unterminated",
      "nul",
      "0x10",
      "2026-07-28T10:00:00Z",
      "2026-07-28T10:00:00.123456+05:30",
      // HPACK header blocks (invariant 5, the OTLP/gRPC response path):
      // literal-without-indexing :status 200 + grpc-status 0 (the fake
      // collector's shape), static-indexed :status 200 (0x88), literal
      // with incremental indexing + huffman flag, multi-byte prefix int.
      std::string("\x00\x07:status\x03""200\x00\x0bgrpc-status\x01""0", 28),
      std::string("\x88\x00\x0bgrpc-status\x01""0", 16),
      std::string("\x40\x0bgrpc-status\x83\x30\x31\x32", 17),
      std::string("\x7f\x80\x01zzzzzz", 9),
  };
  return kSeeds;
}

std::string mutate(const std::string& base, Rng& rng) {
  std::string out = base;
  // Bias mutation count low: heavy stacks almost always break the JSON
  // grammar, starving the post-parse invariants (round-trip, decoders);
  // 1-2 mutations keep roughly a third of derived inputs parseable.
  size_t n_mut = 1 + rng.below(rng.below(4) == 0 ? 8 : 2);
  for (size_t m = 0; m < n_mut && !out.empty(); ++m) {
    switch (rng.below(6)) {
      case 0:  // byte flip
        out[rng.below(out.size())] ^= static_cast<char>(1u << rng.below(8));
        break;
      case 1:  // insert random byte
        out.insert(out.begin() + rng.below(out.size() + 1),
                   static_cast<char>(rng.next() & 0xFF));
        break;
      case 2: {  // delete span
        size_t at = rng.below(out.size());
        out.erase(at, 1 + rng.below(4));
        break;
      }
      case 3: {  // duplicate span
        size_t at = rng.below(out.size());
        size_t len = std::min(out.size() - at, 1 + rng.below(8));
        out.insert(at, out.substr(at, len));
        break;
      }
      case 4:  // truncate
        out.resize(rng.below(out.size() + 1));
        break;
      case 5: {  // splice a fragment of another seed
        const std::string& other = seeds()[rng.below(seeds().size())];
        size_t at = rng.below(other.size());
        size_t len = std::min(other.size() - at, 1 + rng.below(16));
        out.insert(rng.below(out.size() + 1), other.substr(at, len));
        break;
      }
    }
  }
  return out;
}

int run(uint64_t iterations, uint64_t seed) {
  Rng rng(seed);
  uint64_t parsed = 0, rejected = 0;
  // Working corpus: starts at the seeds and grows with inputs that parsed,
  // so mutations compound on interesting (valid-shape) ancestors.
  std::vector<std::string> corpus = seeds();
  const size_t kMaxCorpus = 4096;
  for (uint64_t i = 0; i < iterations; ++i) {
    std::string input = mutate(corpus[rng.below(corpus.size())], rng);

    // invariant 4: timestamp parser is total
    (void)tpupruner::util::parse_rfc3339(input);

    // invariant 5: the HPACK response decoder is total on arbitrary
    // server-controlled bytes (otlp_grpc.cpp; the OTLP/gRPC response
    // path) — false on malformed input, never a crash or a throw
    {
      std::vector<std::tuple<std::string, std::string, bool>> headers;
      (void)tpupruner::otlp_grpc::hpack_decode_for_test(input, headers);
    }

    Value v;
    bool value_parsed = true;
    try {
      v = Value::parse(input);
      ++parsed;
      if (corpus.size() < kMaxCorpus) corpus.push_back(input);
    } catch (const ParseError&) {
      ++rejected;
      value_parsed = false;
    }
    // invariant 6: the arena/zero-copy Doc parser accepts and rejects
    // EXACTLY the inputs Value::parse does, and on acceptance produces an
    // identical tree — the transport hot path's decode-parity contract on
    // arbitrary bytes, not just the recorded corpus.
    {
      tpupruner::json::DocPtr doc;
      bool doc_parsed = true;
      try {
        doc = tpupruner::json::Doc::parse(input);
      } catch (const ParseError&) {
        doc_parsed = false;
      }
      if (doc_parsed != value_parsed) {
        std::fprintf(stderr,
                     "DOC/VALUE ACCEPT DIVERGENCE (iter %llu, seed %llu, doc=%d value=%d):\n%s\n",
                     static_cast<unsigned long long>(i), static_cast<unsigned long long>(seed),
                     doc_parsed ? 1 : 0, value_parsed ? 1 : 0, input.c_str());
        return 1;
      }
      if (doc_parsed && doc->to_value() != v) {
        std::fprintf(stderr, "DOC/VALUE TREE DIVERGENCE (iter %llu, seed %llu):\n%s\n",
                     static_cast<unsigned long long>(i), static_cast<unsigned long long>(seed),
                     input.c_str());
        return 1;
      }
    }
    if (!value_parsed) continue;  // invariant 1 satisfied: documented rejection
    // invariant 2: round-trip stability
    std::string dumped = v.dump();
    Value v2 = Value::parse(dumped);  // must not throw: we produced it
    if (v != v2) {
      std::fprintf(stderr, "ROUND-TRIP DIVERGENCE (iter %llu, seed %llu):\n%s\n→\n%s\n",
                   static_cast<unsigned long long>(i), static_cast<unsigned long long>(seed),
                   input.c_str(), dumped.c_str());
      return 1;
    }
    // invariant 3: decoders tolerate hostile shapes
    try {
      (void)tpupruner::metrics::decode_instant_vector(v, (i & 1) ? "tpu" : "gpu");
    } catch (const std::runtime_error&) {
    }
    (void)tpupruner::core::check_eligibility(v, 1722249000, 2100);
  }
  std::fprintf(stderr, "fuzz ok: %llu iterations (%llu parsed, %llu rejected)\n",
               static_cast<unsigned long long>(iterations),
               static_cast<unsigned long long>(parsed),
               static_cast<unsigned long long>(rejected));
  return 0;
}

}  // namespace

namespace {

// Strict numeric argv parse: trailing garbage or a zero value would
// otherwise silently turn the CI fuzz run into a 0-iteration no-op.
uint64_t parse_arg(const char* s, const char* what, bool allow_zero) {
  char* end = nullptr;
  uint64_t v = std::strtoull(s, &end, 10);
  if (end == s || *end != '\0' || (!allow_zero && v == 0)) {
    std::fprintf(stderr, "tpupruner_fuzz: invalid %s '%s'\n", what, s);
    std::exit(2);
  }
  return v;
}

}  // namespace

int main(int argc, char** argv) {
  uint64_t iterations = argc > 1 ? parse_arg(argv[1], "iteration count", false) : 50000;
  uint64_t seed = argc > 2 ? parse_arg(argv[2], "seed", true) : 0xC0FFEE;
  return run(iterations, seed);
}
