// Action provenance traces (native/src/trace.cpp): the per-evaluation
// span-tree engine behind --trace on. The parity contract (every hook a
// no-op while off), the bounded retention ring, the SLO engine's
// breach-pinning, and the lock discipline between producer begins,
// consumer actuation ends, and serving-thread index reads are what the
// daemon's byte-identity and /debug/traces surfaces lean on.
#include "testing.hpp"

#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include "tpupruner/json.hpp"
#include "tpupruner/trace.hpp"

namespace trace = tpupruner::trace;
using tpupruner::json::Value;

namespace {

// Drive one evaluation through its whole lifecycle: begin (root
// backdated by lag_ms), one query phase span, `acts` actuation spans
// (ended BEFORE arm — the incremental fast path enqueues first, so
// pre-arm credit is load-bearing), then arm to seal.
std::string complete_trace(uint64_t cycle, int64_t lag_ms, int acts) {
  std::string id = trace::begin(cycle, "dirty", lag_ms, "");
  trace::add_phase_span(cycle, "query", 0.0001);
  for (int i = 0; i < acts; ++i) {
    trace::actuation_begin(cycle, "ml/dep-" + std::to_string(i));
    trace::actuation_end(cycle, "SCALED", false, "");
  }
  trace::arm(cycle, static_cast<size_t>(acts));
  return id;
}

std::vector<std::string> span_names(const Value& doc) {
  std::vector<std::string> names;
  if (const Value* tree = doc.find("span_tree"); tree && tree->is_array()) {
    for (const Value& s : tree->as_array()) names.push_back(s.get_string("name"));
  }
  return names;
}

bool contains(const std::vector<std::string>& names, const std::string& want) {
  for (const auto& n : names)
    if (n == want) return true;
  return false;
}

struct TraceOffAtExit {
  ~TraceOffAtExit() {
    trace::configure(false, 0);
    trace::reset_for_test();
  }
};

}  // namespace

TP_TEST(trace_off_every_hook_is_noop) {
  trace::reset_for_test();
  trace::configure(false, 0);
  TP_CHECK_EQ(trace::begin(1, "cycle", 0, ""), std::string());
  TP_CHECK_EQ(trace::trace_id_of(1), std::string());
  TP_CHECK_EQ(trace::traceparent(1), std::string());
  trace::add_phase_span(1, "query", 0.1);
  trace::actuation_begin(1, "ml/x");
  trace::thread_retry_event("kube_patch", "429", 0.1);
  trace::actuation_end(1, "SCALED", false, "");
  trace::arm(1, 1);
  TP_CHECK(trace::capsule_stamp(1).is_null());
  // "" keeps the /metrics scrape byte-identical with tracing off.
  TP_CHECK_EQ(trace::render_metrics(false), std::string());
  TP_CHECK_EQ(trace::render_metrics(true), std::string());
}

TP_TEST(trace_span_tree_has_phases_actuation_and_retry_events) {
  TraceOffAtExit off;
  trace::reset_for_test();
  trace::configure(true, 0);
  std::string id = trace::begin(42, "probe", 7, "");
  TP_CHECK_EQ(id.size(), static_cast<size_t>(32));
  TP_CHECK_EQ(trace::trace_id_of(42), id);
  // The traceparent carries this trace's id, so fake_prom header
  // assertions and histogram exemplars join on the retained tree.
  TP_CHECK(trace::traceparent(42).find(id) != std::string::npos);
  trace::add_phase_span(42, "query", 0.002);
  trace::add_phase_span(42, "decode", 0.001);
  trace::actuation_begin(42, "ml/dep-0");
  trace::thread_retry_event("kube_patch", "429", 0.25);
  trace::actuation_end(42, "SCALED", false, "");
  trace::arm(42, 1);

  std::string body = trace::trace_json(id);
  TP_CHECK(!body.empty());
  Value doc = Value::parse(body);
  TP_CHECK_EQ(doc.get_string("trace_id"), id);
  TP_CHECK_EQ(doc.get_string("trigger"), std::string("probe"));
  const Value* root = doc.find("root");
  TP_CHECK(root != nullptr);
  TP_CHECK_EQ(root->get_string("name"), std::string("evaluate"));
  TP_CHECK_EQ(root->find("ingress_lag_ms")->as_int(), static_cast<int64_t>(7));

  auto names = span_names(doc);
  TP_CHECK(contains(names, "query"));
  TP_CHECK(contains(names, "decode"));
  TP_CHECK(contains(names, "actuate"));
  for (const Value& s : doc.find("span_tree")->as_array()) {
    if (s.get_string("name") != "actuate") continue;
    TP_CHECK_EQ(s.find("attrs")->get_string("identity"), std::string("ml/dep-0"));
    const Value* events = s.find("events");
    TP_CHECK(events != nullptr && events->is_array());
    TP_CHECK_EQ(events->as_array().size(), static_cast<size_t>(1));
    const Value& ev = events->as_array()[0];
    TP_CHECK_EQ(ev.get_string("name"), std::string("retry"));
    TP_CHECK_EQ(ev.find("attrs")->get_string("endpoint"), std::string("kube_patch"));
    TP_CHECK_EQ(ev.find("attrs")->get_string("cause"), std::string("429"));
    TP_CHECK_EQ(ev.find("attrs")->find("backoff_ms")->as_int(),
                static_cast<int64_t>(250));
    // Every child parents to the evaluation root — one tree, no orphans.
    TP_CHECK_EQ(s.get_string("parent_span_id"), root->get_string("span_id"));
  }
}

TP_TEST(trace_arm_zero_seals_with_no_actuation_spans) {
  // Dry-run / SIGNAL_STALE / BROWNOUT evaluations still trace, with zero
  // actuation spans — the chaos join test keys on this shape.
  TraceOffAtExit off;
  trace::reset_for_test();
  trace::configure(true, 0);
  std::string id = trace::begin(7, "timer", 0, "");
  trace::add_phase_span(7, "query", 0.001);
  trace::arm(7, 0);
  Value doc = Value::parse(trace::trace_json(id));
  TP_CHECK_EQ(doc.find("actuations")->as_int(), static_cast<int64_t>(0));
  TP_CHECK(!contains(span_names(doc), "actuate"));
  TP_CHECK(!doc.find("breached")->as_bool());
}

TP_TEST(trace_capsule_stamp_carries_spans_so_far) {
  TraceOffAtExit off;
  trace::reset_for_test();
  trace::configure(true, 0);
  std::string id = trace::begin(9, "anti_entropy", 0, "");
  trace::add_phase_span(9, "query", 0.001);
  Value stamp = trace::capsule_stamp(9);
  TP_CHECK(stamp.is_object());
  TP_CHECK_EQ(stamp.get_string("trace_id"), id);
  TP_CHECK_EQ(stamp.get_string("trigger"), std::string("anti_entropy"));
  const Value* spans = stamp.find("spans");
  TP_CHECK(spans != nullptr && spans->is_array());
  TP_CHECK_EQ(spans->as_array().size(), static_cast<size_t>(1));
  TP_CHECK_EQ(spans->as_array()[0].get_string("name"), std::string("query"));
  // Offsets are root-relative (normalized) — the offline waterfall and
  // byte-identity normalization both depend on that, not wall clocks.
  TP_CHECK(spans->as_array()[0].find("end_us")->as_int() >=
           spans->as_array()[0].find("start_us")->as_int());
  trace::arm(9, 0);
  // Sealed → no longer open; the stamp is only for the recording cycle.
  TP_CHECK(trace::capsule_stamp(9).is_null());
}

TP_TEST(trace_ring_bounded_and_eviction_counted) {
  TraceOffAtExit off;
  trace::reset_for_test();
  trace::configure(true, 0);
  for (uint64_t c = 1; c <= 300; ++c) complete_trace(c, 0, 0);
  Value idx = trace::index_json();
  TP_CHECK_EQ(idx.find("completed_total")->as_int(), static_cast<int64_t>(300));
  TP_CHECK(idx.find("retained")->as_int() <= 256);
  TP_CHECK_EQ(idx.find("evicted_total")->as_int(), static_cast<int64_t>(44));
  // The index body is capped; the ring itself holds more.
  TP_CHECK(idx.find("traces")->as_array().size() <= static_cast<size_t>(50));
}

TP_TEST(trace_slo_breach_pins_past_ring_eviction) {
  TraceOffAtExit off;
  trace::reset_for_test();
  trace::configure(true, 100);  // 100 ms detect→action budget
  // Root backdated 5 s: the actuation's root-relative latency breaches.
  std::string bad = complete_trace(1, 5000, 1);
  // Flood the ring well past kRingCap with fast (good) evaluations.
  for (uint64_t c = 2; c <= 300; ++c) complete_trace(c, 0, 1);

  std::string body = trace::trace_json(bad);
  TP_CHECK(!body.empty());  // survived 299 completions behind it
  Value doc = Value::parse(body);
  TP_CHECK(doc.find("breached")->as_bool());
  TP_CHECK(doc.find("pinned")->as_bool());
  TP_CHECK(doc.find("worst_actuation_ms")->as_double() >= 100.0);

  Value slo = trace::slo_summary();
  TP_CHECK(slo.find("enabled")->as_bool());
  TP_CHECK_EQ(slo.find("slo_ms")->as_int(), static_cast<int64_t>(100));
  TP_CHECK_EQ(slo.find("bad")->as_int(), static_cast<int64_t>(1));
  TP_CHECK_EQ(slo.find("good")->as_int(), static_cast<int64_t>(299));
  TP_CHECK_EQ(slo.find("breaches")->as_int(), static_cast<int64_t>(1));
  TP_CHECK(slo.find("burn_ratio")->as_double() > 0.0);
  // Worst-first: the 5 s breach outranks every sub-ms good trace.
  TP_CHECK_EQ(slo.find("worst")->as_array()[0].get_string("trace_id"), bad);

  std::string metrics = trace::render_metrics(false);
  TP_CHECK(metrics.find("tpu_pruner_slo_breaches_total 1") != std::string::npos);
  TP_CHECK(metrics.find("tpu_pruner_trace_pinned 1") != std::string::npos);
}

TP_TEST(trace_concurrent_begin_end_export_eviction) {
  // Producer begins + consumer actuation ends + serving-thread index and
  // tree reads, all racing ring eviction — the tsan-trace tier runs this
  // under ThreadSanitizer.
  TraceOffAtExit off;
  trace::reset_for_test();
  trace::configure(true, 50);
  std::atomic<bool> stop{false};
  std::thread reader([&stop] {
    while (!stop.load()) {
      (void)trace::index_json();
      (void)trace::slo_summary();
      (void)trace::render_metrics(true);
    }
  });
  std::vector<std::thread> workers;
  for (int t = 0; t < 4; ++t) {
    workers.emplace_back([t] {
      for (int i = 0; i < 200; ++i) {
        const uint64_t cycle = static_cast<uint64_t>(t) * 1000 + i + 1;
        std::string id = trace::begin(cycle, "dirty", i % 3, "");
        trace::add_phase_span(cycle, "query", 0.0001);
        trace::actuation_begin(cycle, "ml/dep");
        trace::thread_retry_event("kube_patch", "429", 0.01);
        trace::actuation_end(cycle, "SCALED", false, "");
        trace::arm(cycle, 1);
        (void)trace::trace_json(id);
      }
    });
  }
  for (auto& w : workers) w.join();
  stop.store(true);
  reader.join();
  Value idx = trace::index_json();
  TP_CHECK_EQ(idx.find("completed_total")->as_int(), static_cast<int64_t>(800));
  TP_CHECK(idx.find("retained")->as_int() <= 256 + 64);
}
