// Shared HTTP/2 transport (native/include/tpupruner/h2.hpp): wire
// primitives (frame headers, HPACK literal encode / decode, huffman) and
// the multiplexing client against a scripted in-process h2 server —
// negotiation, stream multiplexing on ONE connection, HTTP/1.1 fallback,
// GOAWAY retry, and the per-stream idle deadline. The Python tier drives
// the same client end-to-end through the daemon against the fakes'
// h2-speaking servers; `just tsan-transport` runs these under TSan (the
// client's IO thread + caller threads share the connection state).
#include "testing.hpp"

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tpupruner/h2.hpp"
#include "tpupruner/http.hpp"

namespace h2 = tpupruner::h2;
namespace http = tpupruner::http;

namespace {

// ── scripted server plumbing ────────────────────────────────────────────

ssize_t read_some(int fd, char* buf, size_t n) { return ::recv(fd, buf, n, 0); }

bool read_exact(int fd, char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = read_some(fd, buf + off, n - off);
    if (r <= 0) return false;
    off += static_cast<size_t>(r);
  }
  return true;
}

void write_all(int fd, const std::string& s) {
  size_t off = 0;
  while (off < s.size()) {
    ssize_t w = ::send(fd, s.data() + off, s.size() - off, MSG_NOSIGNAL);
    if (w <= 0) return;
    off += static_cast<size_t>(w);
  }
}

struct Listener {
  int fd = -1;
  int port = 0;

  Listener() {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    ::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    socklen_t len = sizeof(addr);
    ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
    port = ntohs(addr.sin_port);
    ::listen(fd, 8);
  }
  ~Listener() {
    if (fd >= 0) ::close(fd);
  }
  int accept() { return ::accept(fd, nullptr, nullptr); }
  std::string url(const std::string& path) const {
    return "http://127.0.0.1:" + std::to_string(port) + path;
  }
};

struct Frame {
  uint8_t type = 0, flags = 0;
  uint32_t stream = 0;
  std::string payload;
};

bool read_frame(int fd, Frame& f) {
  char h[9];
  if (!read_exact(fd, h, 9)) return false;
  size_t len = (static_cast<uint8_t>(h[0]) << 16) | (static_cast<uint8_t>(h[1]) << 8) |
               static_cast<uint8_t>(h[2]);
  f.type = static_cast<uint8_t>(h[3]);
  f.flags = static_cast<uint8_t>(h[4]);
  f.stream = ((static_cast<uint8_t>(h[5]) & 0x7f) << 24) | (static_cast<uint8_t>(h[6]) << 16) |
             (static_cast<uint8_t>(h[7]) << 8) | static_cast<uint8_t>(h[8]);
  f.payload.resize(len);
  return len == 0 || read_exact(fd, f.payload.data(), len);
}

// Consume the client preface + its SETTINGS, answer with our SETTINGS.
bool h2_handshake(int fd) {
  char preface[24];
  if (!read_exact(fd, preface, 24)) return false;
  if (std::string(preface, 24) != h2::kClientPreface) return false;
  write_all(fd, h2::frame_header(0, h2::kFrameSettings, 0, 0));
  return true;
}

// Minimal 200-with-body response on `stream`.
void respond_200(int fd, uint32_t stream, const std::string& body) {
  std::string hb;
  h2::hpack_literal(hb, ":status", "200");
  h2::hpack_literal(hb, "content-type", "text/plain");
  write_all(fd, h2::frame_header(hb.size(), h2::kFrameHeaders, h2::kFlagEndHeaders, stream) + hb);
  write_all(fd, h2::frame_header(body.size(), h2::kFrameData, h2::kFlagEndStream, stream) + body);
}

}  // namespace

// ── wire primitives ─────────────────────────────────────────────────────

TP_TEST(h2_frame_header_layout) {
  std::string h = h2::frame_header(0x01020304 & 0xffffff, h2::kFrameData,
                                   h2::kFlagEndStream, 5);
  TP_CHECK_EQ(h.size(), 9u);
  TP_CHECK_EQ(static_cast<uint8_t>(h[0]), 0x02);
  TP_CHECK_EQ(static_cast<uint8_t>(h[1]), 0x03);
  TP_CHECK_EQ(static_cast<uint8_t>(h[2]), 0x04);
  TP_CHECK_EQ(static_cast<uint8_t>(h[3]), h2::kFrameData);
  TP_CHECK_EQ(static_cast<uint8_t>(h[4]), h2::kFlagEndStream);
  TP_CHECK_EQ(static_cast<uint8_t>(h[8]), 5);
}

TP_TEST(h2_hpack_literal_roundtrip) {
  std::string block;
  h2::hpack_literal(block, ":status", "200");
  h2::hpack_literal(block, "content-type", "application/json");
  std::string big(300, 'x');  // exercises the multi-byte length prefix
  h2::hpack_literal(block, "x-big", big);
  std::vector<h2::Header> out;
  TP_CHECK(h2::hpack_decode(block, out));
  TP_CHECK_EQ(out.size(), 3u);
  TP_CHECK_EQ(out[0].name, ":status");
  TP_CHECK_EQ(out[0].value, "200");
  TP_CHECK_EQ(out[1].value, "application/json");
  TP_CHECK_EQ(out[2].value, big);
}

TP_TEST(h2_hpack_decode_static_indexed) {
  // 0x82 = indexed ":method: GET", 0x88 = ":status: 200" (RFC 7541 A).
  std::string block = "\x82\x88";
  std::vector<h2::Header> out;
  TP_CHECK(h2::hpack_decode(block, out));
  TP_CHECK_EQ(out.size(), 2u);
  TP_CHECK_EQ(out[0].name, ":method");
  TP_CHECK_EQ(out[0].value, "GET");
  TP_CHECK_EQ(out[1].name, ":status");
  TP_CHECK_EQ(out[1].value, "200");
}

TP_TEST(h2_huffman_decode_rfc_vector) {
  // RFC 7541 C.4.1: "www.example.com" huffman-coded.
  const unsigned char coded[] = {0xf1, 0xe3, 0xc2, 0xe5, 0xf2, 0x3a,
                                 0x6b, 0xa0, 0xab, 0x90, 0xf4, 0xff};
  std::string out;
  TP_CHECK(h2::huffman_decode(
      std::string_view(reinterpret_cast<const char*>(coded), sizeof(coded)), out));
  TP_CHECK_EQ(out, "www.example.com");
}

TP_TEST(h2_settings_payload_shape) {
  std::string s = h2::settings_payload(0);
  TP_CHECK_EQ(s.size(), 12u);  // HEADER_TABLE_SIZE + ENABLE_PUSH
  std::string w = h2::settings_payload(1 << 20);
  TP_CHECK_EQ(w.size(), 18u);  // + INITIAL_WINDOW_SIZE
  TP_CHECK_EQ(static_cast<uint8_t>(w[13]), 0x04);  // id 0x0004
}

TP_TEST(h2_mode_parse_and_default) {
  TP_CHECK(h2::mode_from_string("auto") == h2::Mode::Auto);
  TP_CHECK(h2::mode_from_string("h2") == h2::Mode::H2);
  TP_CHECK(h2::mode_from_string("http1") == h2::Mode::Http1);
  TP_CHECK_EQ(std::string(h2::mode_name(h2::Mode::H2)), "h2");
  bool threw = false;
  try {
    h2::mode_from_string("spdy");
  } catch (const std::exception&) {
    threw = true;
  }
  TP_CHECK(threw);
  h2::Mode prev = h2::default_mode();
  h2::set_default_mode(h2::Mode::Http1);
  TP_CHECK(h2::default_mode() == h2::Mode::Http1);
  h2::set_default_mode(prev);
}

TP_TEST(h2_transport_metric_families_nonempty) {
  auto families = h2::transport_metric_families();
  TP_CHECK(families.size() >= 5);
  std::string text = h2::render_transport_metrics(false);
  for (const std::string& f : families) {
    TP_CHECK(text.find(f) != std::string::npos);
  }
}

// ── the multiplexing client vs scripted servers ─────────────────────────

TP_TEST(h2_transport_cleartext_prior_knowledge) {
  Listener lst;
  std::thread server([&] {
    int fd = lst.accept();
    if (fd < 0 || !h2_handshake(fd)) return;
    Frame f;
    while (read_frame(fd, f)) {
      if (f.type == h2::kFrameHeaders) {
        respond_200(fd, f.stream, "hello-h2");
        break;
      }
    }
    // Drain until the client hangs up so close_notify ordering never races.
    while (read_frame(fd, f)) {
    }
    ::close(fd);
  });
  {
    // Scoped: the transport's destructor hangs up the connection, which is
    // what lets the server's drain loop (and join below) finish.
    h2::Transport t(h2::Mode::Auto);
    http::Request req;
    req.url = lst.url("/ping");
    req.timeout_ms = 3000;
    http::Response resp = t.request(req);
    TP_CHECK_EQ(resp.status, 200);
    TP_CHECK_EQ(resp.body, "hello-h2");
    TP_CHECK_EQ(t.protocol_for(req.url), "h2");
  }
  server.join();
}

TP_TEST(h2_transport_concurrent_streams_one_connection) {
  Listener lst;
  std::atomic<int> accepts{0};
  std::thread server([&] {
    int fd = lst.accept();
    if (fd < 0) return;
    ++accepts;
    if (!h2_handshake(fd)) return;
    int served = 0;
    Frame f;
    while (served < 2 && read_frame(fd, f)) {
      if (f.type == h2::kFrameHeaders) {
        respond_200(fd, f.stream, "s" + std::to_string(f.stream));
        ++served;
      }
    }
    while (read_frame(fd, f)) {
    }
    ::close(fd);
  });
  std::string b1, b2;
  {
    h2::Transport t(h2::Mode::Auto);
    std::thread c1([&] {
      http::Request req;
      req.url = lst.url("/a");
      req.timeout_ms = 3000;
      b1 = t.request(req).body;
    });
    std::thread c2([&] {
      http::Request req;
      req.url = lst.url("/b");
      req.timeout_ms = 3000;
      b2 = t.request(req).body;
    });
    c1.join();
    c2.join();
  }
  server.join();
  TP_CHECK_EQ(accepts.load(), 1);
  TP_CHECK(!b1.empty() && b1[0] == 's');
  TP_CHECK(!b2.empty() && b2[0] == 's');
  TP_CHECK(b1 != b2);  // two distinct streams, one connection
}

TP_TEST(h2_transport_falls_back_to_http1) {
  Listener lst;
  std::thread server([&] {
    // Connection 1: the prior-knowledge probe. Answer the preface like any
    // HTTP/1.1 server would: an error line.
    int fd = lst.accept();
    if (fd >= 0) {
      char buf[512];
      (void)read_some(fd, buf, sizeof(buf));
      write_all(fd, "HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n\r\n");
      ::close(fd);
    }
    // Connection 2: the fallback HTTP/1.1 request.
    fd = lst.accept();
    if (fd >= 0) {
      char buf[2048];
      (void)read_some(fd, buf, sizeof(buf));
      write_all(fd, "HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok");
      ::close(fd);
    }
  });
  h2::Transport t(h2::Mode::Auto);
  http::Request req;
  req.url = lst.url("/h1");
  req.timeout_ms = 3000;
  http::Response resp = t.request(req);
  TP_CHECK_EQ(resp.status, 200);
  TP_CHECK_EQ(resp.body, "ok");
  TP_CHECK_EQ(t.protocol_for(req.url), "http1");
  // The endpoint is remembered: a second request goes straight to http1
  // (connection 2's socket is gone, so the pooled client redials — the
  // server thread already exited; just assert the memo stuck).
  server.join();
}

TP_TEST(h2_transport_goaway_retries_on_fresh_connection) {
  Listener lst;
  std::thread server([&] {
    // Connection 1: GOAWAY(last_stream=0) as soon as a request arrives —
    // "not processed, retry elsewhere".
    int fd = lst.accept();
    if (fd >= 0 && h2_handshake(fd)) {
      Frame f;
      while (read_frame(fd, f)) {
        if (f.type == h2::kFrameHeaders) {
          std::string p(8, '\0');  // last_stream=0, error NO_ERROR
          write_all(fd, h2::frame_header(8, h2::kFrameGoaway, 0, 0) + p);
          break;
        }
      }
      ::close(fd);
    }
    // Connection 2: serve the retried request.
    fd = lst.accept();
    if (fd >= 0 && h2_handshake(fd)) {
      Frame f;
      while (read_frame(fd, f)) {
        if (f.type == h2::kFrameHeaders) {
          respond_200(fd, f.stream, "retried");
          break;
        }
      }
      while (read_frame(fd, f)) {
      }
      ::close(fd);
    }
  });
  uint64_t retries_before = h2::counters().retries.load();
  {
    h2::Transport t(h2::Mode::Auto);
    http::Request req;
    req.url = lst.url("/goaway");
    req.timeout_ms = 3000;
    http::Response resp = t.request(req);
    TP_CHECK_EQ(resp.status, 200);
    TP_CHECK_EQ(resp.body, "retried");
    TP_CHECK(h2::counters().retries.load() > retries_before);
  }
  server.join();
}

TP_TEST(h2_transport_stream_idle_deadline) {
  Listener lst;
  std::atomic<bool> stop{false};
  std::thread server([&] {
    int fd = lst.accept();
    if (fd < 0) return;
    if (!h2_handshake(fd)) {
      ::close(fd);
      return;
    }
    // Swallow everything and never answer: the client's per-stream idle
    // deadline — not the server — must end the request.
    Frame f;
    while (!stop.load() && read_frame(fd, f)) {
    }
    ::close(fd);
  });
  bool threw = false;
  std::string msg;
  {
    h2::Transport t(h2::Mode::Auto);
    http::Request req;
    req.url = lst.url("/stall");
    req.timeout_ms = 300;
    try {
      (void)t.request(req);
    } catch (const std::exception& e) {
      threw = true;
      msg = e.what();
    }
    stop.store(true);
  }  // transport teardown closes the connection → server recv sees EOF
  TP_CHECK(threw);
  TP_CHECK(msg.find("idle") != std::string::npos || msg.find("deadline") != std::string::npos);
  server.join();
}
