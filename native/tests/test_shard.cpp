// Sharded reconcile-engine primitives (native/include/tpupruner/shard.hpp).
// These pin the determinism contract the daemon's merge stage relies on:
// placement is a pure function of (key, shard count) — stable across
// runs, builds and platforms — and the worker pool runs every task
// exactly once, reusing its threads across calls.
#include "testing.hpp"

#include <atomic>
#include <set>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "tpupruner/shard.hpp"

namespace shard = tpupruner::shard;

TP_TEST(shard_stable_hash_pinned_values) {
  // FNV-1a 64 test vectors: a drifting hash would silently re-place every
  // root and break cross-build capsule byte-identity, so the exact values
  // are pinned (the empty string is the FNV offset basis).
  TP_CHECK_EQ(shard::stable_hash(""), 14695981039346656037ULL);
  TP_CHECK_EQ(shard::stable_hash("a"), 12638187200555641996ULL);
  TP_CHECK_EQ(shard::stable_hash("Deployment/ml-0/dep-0"),
              shard::stable_hash("Deployment/ml-0/dep-0"));
  TP_CHECK(shard::stable_hash("Deployment/ml-0/dep-0") !=
           shard::stable_hash("Deployment/ml-0/dep-1"));
}

TP_TEST(shard_of_same_key_same_shard) {
  for (int i = 0; i < 100; ++i) {
    std::string key = "JobSet/tpu-jobs/slice-" + std::to_string(i);
    size_t first = shard::shard_of(key, 8);
    TP_CHECK(first < 8);
    for (int repeat = 0; repeat < 3; ++repeat) {
      TP_CHECK_EQ(shard::shard_of(key, 8), first);
    }
  }
}

TP_TEST(shard_of_degenerate_counts) {
  TP_CHECK_EQ(shard::shard_of("anything", 0), size_t{0});
  TP_CHECK_EQ(shard::shard_of("anything", 1), size_t{0});
}

TP_TEST(shard_of_spreads_roots) {
  // Not a distribution-quality proof — just a guard against a
  // constant-output regression (everything hashing to shard 0 would
  // silently serialize the engine).
  std::set<size_t> seen;
  for (int i = 0; i < 64; ++i) {
    seen.insert(shard::shard_of("Deployment/ml/dep-" + std::to_string(i), 8));
  }
  TP_CHECK(seen.size() >= 4);
}

TP_TEST(shard_resolve_count_clamps) {
  TP_CHECK_EQ(shard::resolve_shard_count(1), size_t{1});
  TP_CHECK_EQ(shard::resolve_shard_count(8), size_t{8});
  TP_CHECK_EQ(shard::resolve_shard_count(100000), shard::kMaxShards);
  size_t auto_count = shard::resolve_shard_count(0);
  TP_CHECK(auto_count >= 1);
  TP_CHECK(auto_count <= shard::kAutoMaxShards);
}

TP_TEST(shard_pool_runs_every_task_once) {
  shard::Pool pool(4);
  std::vector<std::atomic<int>> hits(100);
  for (auto& h : hits) h.store(0);
  pool.run(hits.size(), [&](size_t i) { hits[i].fetch_add(1); });
  for (auto& h : hits) TP_CHECK_EQ(h.load(), 1);
  // Reuse across calls: the same pool must serve a second, larger batch.
  std::atomic<int> total{0};
  pool.run(257, [&](size_t) { total.fetch_add(1); });
  TP_CHECK_EQ(total.load(), 257);
  pool.run(0, [&](size_t) { total.fetch_add(1); });  // no-op, must not hang
  TP_CHECK_EQ(total.load(), 257);
}

TP_TEST(shard_pool_rethrows_first_error) {
  shard::Pool pool(3);
  std::atomic<int> ran{0};
  bool threw = false;
  try {
    pool.run(16, [&](size_t i) {
      ran.fetch_add(1);
      if (i == 5) throw std::runtime_error("boom");
    });
  } catch (const std::runtime_error& e) {
    threw = std::string(e.what()) == "boom";
  }
  TP_CHECK(threw);
  // Every index was still handed out (a throwing task must not wedge the
  // remaining indices or the next run).
  std::atomic<int> again{0};
  pool.run(8, [&](size_t) { again.fetch_add(1); });
  TP_CHECK_EQ(again.load(), 8);
}

TP_TEST(shard_pool_concurrent_callers_from_global) {
  // The process-wide pool accessor returns a working pool and resizes on
  // a different requested width.
  shard::Pool& p4 = shard::pool(4);
  TP_CHECK_EQ(p4.size(), size_t{4});
  std::atomic<int> n{0};
  p4.run(32, [&](size_t) { n.fetch_add(1); });
  TP_CHECK_EQ(n.load(), 32);
  shard::Pool& p2 = shard::pool(2);
  TP_CHECK_EQ(p2.size(), size_t{2});
}
