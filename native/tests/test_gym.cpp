// Policy-gym units: the right-size math shared by the daemon, the replay
// engine and the simulator; policy-spec parsing; and a two-capsule
// simulate() pass over handcrafted corpus evidence.
#include <string>

#include "testing.hpp"
#include "tpupruner/core.hpp"
#include "tpupruner/gym.hpp"
#include "tpupruner/json.hpp"

namespace gym = tpupruner::gym;
namespace core = tpupruner::core;
using tpupruner::json::Value;

namespace {

Value deployment_with_replicas(int64_t replicas) {
  Value obj = Value::object();
  Value spec = Value::object();
  spec.set("replicas", Value(replicas));
  obj.set("spec", std::move(spec));
  return obj;
}

}  // namespace

TP_TEST(right_size_plan_partial_idle_scales_to_ceil_busy_over_threshold) {
  // R=4, 2 idle pods observed (8 chips) → busy=2; τ=0.8 → N=ceil(2.5)=3,
  // freeing one replica's worth of chips (8/2 = 4 per replica).
  gym::RightSizePlan p = gym::right_size_plan(core::Kind::Deployment,
                                              deployment_with_replicas(4), 2, 8, 0.8);
  TP_CHECK(p.applicable);
  TP_CHECK(!p.held);
  TP_CHECK_EQ(p.current_replicas, int64_t{4});
  TP_CHECK_EQ(p.busy_replicas, int64_t{2});
  TP_CHECK_EQ(p.target_replicas, int64_t{3});
  TP_CHECK_EQ(p.freed_chips, int64_t{4});
  TP_CHECK(p.detail.find("right-sized from 4 to 3 replicas") != std::string::npos);
  TP_CHECK(p.detail.find("freed 4 chips") != std::string::npos);
}

TP_TEST(right_size_plan_holds_when_no_smaller_count_satisfies_threshold) {
  // R=2, 1 idle → busy=1; τ=0.25 → ceil(4)=4 >= R: held, nothing freed.
  gym::RightSizePlan p = gym::right_size_plan(core::Kind::Deployment,
                                              deployment_with_replicas(2), 1, 4, 0.25);
  TP_CHECK(p.applicable);
  TP_CHECK(p.held);
  TP_CHECK_EQ(p.target_replicas, int64_t{2});
  TP_CHECK_EQ(p.freed_chips, int64_t{0});
  TP_CHECK(p.detail.find("right-size held at 2 replicas") != std::string::npos);
}

TP_TEST(right_size_plan_fully_idle_and_single_replica_keep_classic_pause) {
  // busy == 0 (all replicas idle): scale-to-zero frees everything.
  TP_CHECK(!gym::right_size_plan(core::Kind::Deployment, deployment_with_replicas(2), 2, 8,
                                 0.8).applicable);
  // R <= 1: right-sizing IS scale-to-zero.
  TP_CHECK(!gym::right_size_plan(core::Kind::Deployment, deployment_with_replicas(1), 0, 0,
                                 0.8).applicable);
  // No replica knob on the object at all.
  TP_CHECK(!gym::right_size_plan(core::Kind::Deployment, Value::object(), 0, 0, 0.8)
                .applicable);
  // Kinds without a replica knob (JobSet suspend, Notebook annotation).
  TP_CHECK(!gym::right_size_plan(core::Kind::JobSet, deployment_with_replicas(4), 2, 8, 0.8)
                .applicable);
}

TP_TEST(right_size_plan_inference_service_uses_predictor_min_replicas) {
  Value isvc = Value::object();
  Value predictor = Value::object();
  predictor.set("minReplicas", Value(int64_t{3}));
  Value spec = Value::object();
  spec.set("predictor", std::move(predictor));
  isvc.set("spec", std::move(spec));
  gym::RightSizePlan p = gym::right_size_plan(core::Kind::InferenceService, isvc, 1, 4, 0.9);
  // busy=2 → ceil(2/0.9)=3 >= R: held.
  TP_CHECK(p.applicable);
  TP_CHECK(p.held);
  gym::RightSizePlan p2 = gym::right_size_plan(core::Kind::InferenceService, isvc, 2, 8, 0.9);
  // busy=1 → N=2 < 3: frees one replica (4 chips).
  TP_CHECK(p2.applicable && !p2.held);
  TP_CHECK_EQ(p2.target_replicas, int64_t{2});
  TP_CHECK_EQ(p2.freed_chips, int64_t{4});
}

TP_TEST(right_size_plan_rejects_bad_threshold) {
  bool threw = false;
  try {
    gym::right_size_plan(core::Kind::Deployment, deployment_with_replicas(4), 2, 8, 0.0);
  } catch (const std::exception&) {
    threw = true;
  }
  TP_CHECK(threw);
}

TP_TEST(policy_spec_parsing_round_trips_every_kind) {
  Value b = gym::parse_policy_spec("baseline");
  TP_CHECK_EQ(b.get_string("kind"), std::string("baseline"));
  TP_CHECK_EQ(b.get_string("name"), std::string("baseline"));

  Value s = gym::parse_policy_spec("sweep:lookback=10m,grace=60");
  TP_CHECK_EQ(s.get_string("kind"), std::string("sweep"));
  TP_CHECK_EQ(s.find("what_if")->get_string("lookback"), std::string("10m"));
  TP_CHECK_EQ(s.find("what_if")->get_string("grace"), std::string("60"));

  Value r = gym::parse_policy_spec("right-size:threshold=0.5");
  TP_CHECK_EQ(r.get_string("kind"), std::string("right_size"));
  TP_CHECK(r.find("threshold")->as_double() == 0.5);

  Value h = gym::parse_policy_spec("hysteresis:pause_after=5");
  TP_CHECK_EQ(h.get_string("kind"), std::string("hysteresis"));
  TP_CHECK_EQ(h.find("pause_after")->as_int(), int64_t{5});

  TP_CHECK_EQ(gym::default_policies().as_array().size(), size_t{3});
}

TP_TEST(policy_spec_parsing_rejects_malformed_specs) {
  for (const char* bad : {"bogus", "sweep", "sweep:novalue", "right-size:threshold=2",
                          "hysteresis:pause_after=0", "baseline:x=1",
                          "right-size:unknown=1"}) {
    bool threw = false;
    try {
      gym::parse_policy_spec(bad);
    } catch (const std::exception&) {
      threw = true;
    }
    TP_CHECK(threw);
  }
}

namespace {

// A minimal self-consistent capsule: one old idle pod resolving to a
// 2-replica Deployment, fully idle → the baseline pauses it.
Value mini_capsule(uint64_t cycle, int64_t now, bool observed_idle) {
  Value cap = Value::object();
  cap.set("id", Value("cycle-" + std::to_string(now) + "-" + std::to_string(cycle)));
  cap.set("cycle", Value(static_cast<int64_t>(cycle)));
  cap.set("ts_unix", Value(now));
  cap.set("now_unix", Value(now));

  Value qa = Value::object();
  qa.set("device", Value("tpu"));
  qa.set("duration", Value(int64_t{30}));
  qa.set("metric_schema", Value("gmp"));
  Value cfg = Value::object();
  cfg.set("query_args", std::move(qa));
  cfg.set("run_mode", Value("scale-down"));
  cfg.set("lookback_s", Value(int64_t{2100}));
  cfg.set("grace_s", Value(int64_t{300}));
  cap.set("config", std::move(cfg));
  cap.set("query", Value("(q)"));

  Value result = Value::array();
  if (observed_idle) {
    Value metric = Value::object();
    metric.set("exported_pod", Value("p0"));
    metric.set("exported_namespace", Value("ml"));
    metric.set("exported_container", Value("main"));
    metric.set("accelerator_type", Value("tpu-v5-lite-podslice"));
    metric.set("node_type", Value("tpu-v5-lite-podslice"));
    Value series = Value::object();
    series.set("metric", std::move(metric));
    Value value = Value::array();
    value.push_back(Value(static_cast<int64_t>(now)));
    value.push_back(Value("0"));
    series.set("value", std::move(value));
    result.push_back(std::move(series));
  }
  Value data = Value::object();
  data.set("resultType", Value("vector"));
  data.set("result", std::move(result));
  Value body = Value::object();
  body.set("status", Value("success"));
  body.set("data", std::move(data));
  Value prom = Value::object();
  prom.set("body", Value(body.dump()));
  cap.set("prom", std::move(prom));

  Value pods = Value::object();
  Value resolutions = Value::object();
  if (observed_idle) {
    Value pod = Value::object();
    Value meta = Value::object();
    meta.set("name", Value("p0"));
    meta.set("namespace", Value("ml"));
    meta.set("creationTimestamp", Value("2020-01-01T00:00:00Z"));
    pod.set("metadata", std::move(meta));
    Value status = Value::object();
    status.set("phase", Value("Running"));
    pod.set("status", std::move(status));
    Value resources = Value::object();
    Value requests = Value::object();
    requests.set("google.com/tpu", Value("4"));
    resources.set("requests", std::move(requests));
    Value container = Value::object();
    container.set("name", Value("main"));
    container.set("resources", std::move(resources));
    Value containers = Value::array();
    containers.push_back(std::move(container));
    Value spec = Value::object();
    spec.set("containers", std::move(containers));
    pod.set("spec", std::move(spec));

    Value ev = Value::object();
    ev.set("present", Value(true));
    ev.set("pod", std::move(pod));
    pods.set("ml/p0", std::move(ev));

    Value res = Value::object();
    Value chain = Value::array();
    chain.push_back(Value("Pod/ml/p0"));
    chain.push_back(Value("Deployment/ml/serve"));
    res.set("chain", std::move(chain));
    Value root = Value::object();
    root.set("kind", Value("Deployment"));
    root.set("namespace", Value("ml"));
    root.set("name", Value("serve"));
    res.set("root", std::move(root));
    res.set("identity", Value("Deployment/uid:d1"));
    resolutions.set("ml/p0", std::move(res));
  }
  cap.set("pods", std::move(pods));
  cap.set("resolutions", std::move(resolutions));
  cap.set("objects", Value::object());
  cap.set("vetoed_roots", Value::array());
  cap.set("vetoed_namespaces", Value::object());
  cap.set("root_flags", Value::object());
  cap.set("decisions", Value::array());

  Value obs = Value::array();
  if (observed_idle) {
    Value o = Value::object();
    o.set("kind", Value("Deployment"));
    o.set("namespace", Value("ml"));
    o.set("name", Value("serve"));
    o.set("chips", Value(int64_t{4}));
    o.set("pods", Value(int64_t{1}));
    obs.push_back(std::move(o));
  }
  Value led = Value::object();
  led.set("now_unix", Value(now));
  led.set("observations", std::move(obs));
  cap.set("ledger", std::move(led));
  return cap;
}

}  // namespace

TP_TEST(gym_simulate_integrates_reclaim_and_detects_false_pause) {
  // Cycle 1: idle → the baseline pauses Deployment/ml/serve (4 chips).
  // Cycle 2 (+60s): busy (no evidence row) within the
  // regret window → resume + ONE false pause, after accruing 4×60
  // reclaimed chip-seconds for the paused minute.
  Value capsules = Value::array();
  capsules.push_back(mini_capsule(1, 1700000000, true));
  capsules.push_back(mini_capsule(2, 1700000060, false));
  Value payload = Value::object();
  payload.set("capsules", std::move(capsules));
  Value policies = Value::array();
  policies.push_back(Value("baseline"));
  payload.set("policies", std::move(policies));
  payload.set("regret_window_s", Value(int64_t{600}));

  Value out = gym::simulate(payload);
  TP_CHECK_EQ(out.find("cycles")->as_int(), int64_t{2});
  const Value& p = out.find("policies")->as_array()[0];
  TP_CHECK_EQ(p.get_string("kind"), std::string("baseline"));
  TP_CHECK(p.find("reclaimed_chip_seconds")->as_double() == 240.0);
  TP_CHECK_EQ(p.find("false_pauses")->as_int(), int64_t{1});
  TP_CHECK_EQ(p.find("pauses")->as_int(), int64_t{1});
  TP_CHECK_EQ(p.find("resumes")->as_int(), int64_t{1});
  TP_CHECK_EQ(out.find("winner")->get_string("kind"), std::string("baseline"));
}

TP_TEST(gym_simulate_regret_window_bounds_false_pauses) {
  // Same corpus, but the busy evidence lands OUTSIDE a 30s regret
  // window: the pause still resumes (churn) but is not a false pause.
  Value capsules = Value::array();
  capsules.push_back(mini_capsule(1, 1700000000, true));
  capsules.push_back(mini_capsule(2, 1700000060, false));
  Value payload = Value::object();
  payload.set("capsules", std::move(capsules));
  Value policies = Value::array();
  policies.push_back(Value("baseline"));
  payload.set("policies", std::move(policies));
  payload.set("regret_window_s", Value(int64_t{30}));

  Value out = gym::simulate(payload);
  const Value& p = out.find("policies")->as_array()[0];
  TP_CHECK_EQ(p.find("false_pauses")->as_int(), int64_t{0});
  TP_CHECK_EQ(p.find("resumes")->as_int(), int64_t{1});
}

TP_TEST(gym_simulate_rejects_empty_and_malformed_payloads) {
  bool threw = false;
  try {
    gym::simulate(Value::object());
  } catch (const std::exception&) {
    threw = true;
  }
  TP_CHECK(threw);
}
