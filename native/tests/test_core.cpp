// C++-level domain tests (reference: gpu-pruner/src/lib.rs:578-998).
// The fuller port of the reference's domain suite lives in
// tests/test_domain.py, driving this same code through the C API.
#include "testing.hpp"
#include "tpupruner/core.hpp"
#include "tpupruner/metrics.hpp"
#include "tpupruner/query.hpp"

using namespace tpupruner;
using core::Kind;
using json::Value;

namespace {
core::ScaleTarget make_target(Kind k, const char* name, const char* ns, const char* uid) {
  Value obj = Value::object();
  Value meta = Value::object();
  meta.set("name", Value(name));
  meta.set("namespace", Value(ns));
  if (uid) meta.set("uid", Value(uid));
  obj.set("metadata", std::move(meta));
  return core::ScaleTarget{k, std::move(obj)};
}
}  // namespace

TP_TEST(enabled_resources_parsing) {
  auto all = core::parse_enabled_resources("drsinjl");
  TP_CHECK_EQ(all, core::kAllResources);
  TP_CHECK(core::parse_enabled_resources("drsinj") != core::kAllResources);
  TP_CHECK_EQ(core::parse_enabled_resources("l"), core::flag(Kind::LeaderWorkerSet));
  auto just_n = core::parse_enabled_resources("n");
  TP_CHECK(just_n & core::flag(Kind::Notebook));
  TP_CHECK(!(just_n & core::flag(Kind::Deployment)));
  TP_CHECK_EQ(core::parse_enabled_resources(""), 0);
  TP_CHECK_EQ(core::parse_enabled_resources("xdqz"), core::flag(Kind::Deployment));
  TP_CHECK_EQ(core::parse_enabled_resources("dddd"), core::parse_enabled_resources("d"));
  TP_CHECK_EQ(core::parse_enabled_resources("j"), core::flag(Kind::JobSet));
}

TP_TEST(target_identity_uid_based) {
  auto a = make_target(Kind::Deployment, "d", "ns", "uid-1");
  auto b = make_target(Kind::Deployment, "other-name", "ns", "uid-1");
  auto c = make_target(Kind::Deployment, "d", "ns", "uid-2");
  auto d = make_target(Kind::ReplicaSet, "d", "ns", "uid-1");
  TP_CHECK(a == b);   // same uid → equal despite names
  TP_CHECK(!(a == c));  // different uid
  TP_CHECK(!(a == d));  // different variant, same uid (lib.rs:774-778)
}

TP_TEST(target_identity_uidless_fallback) {
  auto a = make_target(Kind::Deployment, "d", "ns", nullptr);
  auto b = make_target(Kind::Deployment, "d", "ns", nullptr);
  auto c = make_target(Kind::Deployment, "d2", "ns", nullptr);
  TP_CHECK(a == b);
  TP_CHECK(!(a == c));
}

TP_TEST(dedup_targets_mixed) {
  std::vector<core::ScaleTarget> in;
  in.push_back(make_target(Kind::Deployment, "d1", "ns", "uid-d"));
  in.push_back(make_target(Kind::ReplicaSet, "r1", "ns", "uid-r"));
  in.push_back(make_target(Kind::StatefulSet, "s1", "ns", "uid-s"));
  in.push_back(make_target(Kind::InferenceService, "i1", "ns", "uid-i"));
  in.push_back(make_target(Kind::Notebook, "n1", "ns", "uid-n"));
  in.push_back(make_target(Kind::JobSet, "j1", "ns", "uid-j"));
  in.push_back(make_target(Kind::Deployment, "d1", "ns", "uid-d"));  // dup
  auto out = core::dedup_targets(std::move(in));
  TP_CHECK_EQ(out.size(), size_t(6));
  TP_CHECK_EQ(out[0].name(), std::string("d1"));  // first-seen order preserved
}

TP_TEST(event_generation_fields) {
  auto t = make_target(Kind::Notebook, "tpu-test", "ml-ns", "nb-uid-1");
  core::EventOptions opts;
  opts.device = "tpu";
  opts.reporting_instance = "pruner-pod-0";
  opts.now_unix = 1785312000;
  Value e = core::generate_scale_event(t, opts);

  TP_CHECK_EQ(e.at_path("involvedObject.name")->as_string(), std::string("tpu-test"));
  TP_CHECK_EQ(e.at_path("involvedObject.namespace")->as_string(), std::string("ml-ns"));
  TP_CHECK_EQ(e.at_path("involvedObject.kind")->as_string(), std::string("Notebook"));
  TP_CHECK_EQ(e.at_path("involvedObject.uid")->as_string(), std::string("nb-uid-1"));
  TP_CHECK_EQ(e.at_path("involvedObject.apiVersion")->as_string(), std::string("kubeflow.org/v1"));
  TP_CHECK_EQ(e.get_string("action"), std::string("scale_down"));
  TP_CHECK_EQ(e.get_string("type"), std::string("Normal"));
  TP_CHECK_EQ(e.get_string("reason"), std::string("Pod ml-ns::tpu-test was not using TPU"));
  TP_CHECK_EQ(e.get_string("reportingComponent"), std::string("tpu-pruner"));
  TP_CHECK_EQ(e.get_string("reportingInstance"), std::string("pruner-pod-0"));
  TP_CHECK(e.at_path("metadata.name")->as_string().starts_with("tpupruner-"));
  TP_CHECK_EQ(e.at_path("metadata.namespace")->as_string(), std::string("ml-ns"));
  TP_CHECK_EQ(e.get_string("firstTimestamp"), std::string("2026-07-29T08:00:00Z"));
  TP_CHECK_EQ(e.get_string("lastTimestamp"), std::string("2026-07-29T08:00:00Z"));
  TP_CHECK(!e.get_string("eventTime").empty());
}

TP_TEST(event_names_unique) {
  auto t = make_target(Kind::Deployment, "d", "ns", nullptr);
  Value e1 = core::generate_scale_event(t);
  Value e2 = core::generate_scale_event(t);
  TP_CHECK(e1.at_path("metadata.name")->as_string() != e2.at_path("metadata.name")->as_string());
}

TP_TEST(eligibility_gates) {
  int64_t now = 1785312000;
  int64_t lookback = 30 * 60 + 300;

  Value pending = Value::parse(R"({"metadata":{"creationTimestamp":"2026-07-01T00:00:00Z"},
                                   "status":{"phase":"Pending"}})");
  TP_CHECK(core::check_eligibility(pending, now, lookback) == core::Eligibility::Pending);

  Value no_ts = Value::parse(R"({"metadata":{},"status":{"phase":"Running"}})");
  TP_CHECK(core::check_eligibility(no_ts, now, lookback) == core::Eligibility::NoCreationTs);

  Value young = Value::parse(R"({"metadata":{"creationTimestamp":"2026-07-29T07:45:00Z"},
                                 "status":{"phase":"Running"}})");
  TP_CHECK(core::check_eligibility(young, now, lookback) == core::Eligibility::TooYoung);

  // created exactly at the boundary is still too young (>= in main.rs:508)
  Value boundary = Value::parse(R"({"metadata":{"creationTimestamp":"2026-07-29T07:25:00Z"},
                                    "status":{"phase":"Running"}})");
  TP_CHECK(core::check_eligibility(boundary, now, lookback) == core::Eligibility::TooYoung);

  Value old_pod = Value::parse(R"({"metadata":{"creationTimestamp":"2026-07-29T07:24:59Z"},
                                   "status":{"phase":"Running"}})");
  TP_CHECK(core::check_eligibility(old_pod, now, lookback) == core::Eligibility::Eligible);

  Value bad_ts = Value::parse(R"({"metadata":{"creationTimestamp":"not-a-time"}})");
  TP_CHECK(core::check_eligibility(bad_ts, now, lookback) == core::Eligibility::BadTimestamp);
}

TP_TEST(query_tpu_shape) {
  query::QueryArgs a;
  a.device = "tpu";
  a.duration_min = 45;
  a.hbm_threshold = 0.05;
  std::string q = query::build_idle_query(a);
  TP_CHECK(q.find("max_over_time(") != std::string::npos);
  TP_CHECK(q.find("avg_over_time(") == std::string::npos);
  TP_CHECK(q.find("tensorcore_utilization") != std::string::npos);
  TP_CHECK(q.find("tensorcore_duty_cycle") != std::string::npos);
  TP_CHECK(q.find("/ 100") != std::string::npos);
  TP_CHECK(q.find("[45m]") != std::string::npos);
  TP_CHECK(q.find("== 0") != std::string::npos);
  TP_CHECK(q.find("unless on (exported_pod, exported_namespace)") != std::string::npos);
  TP_CHECK(q.find("hbm_memory_bandwidth_utilization") != std::string::npos);
  TP_CHECK(q.find(">= 0.05") != std::string::npos);
  TP_CHECK(q.find("gke_tpu_accelerator") != std::string::npos);
}

TP_TEST(query_gpu_shape) {
  query::QueryArgs a;
  a.device = "gpu";
  a.duration_min = 30;
  a.power_threshold = 150.0;
  std::string q = query::build_idle_query(a);
  TP_CHECK(q.find("DCGM_FI_PROF_GR_ENGINE_ACTIVE") != std::string::npos);
  TP_CHECK(q.find("DCGM_FI_DEV_GPU_UTIL") != std::string::npos);
  TP_CHECK(q.find("DCGM_FI_DEV_POWER_USAGE") != std::string::npos);
  TP_CHECK(q.find(">= 150") != std::string::npos);
  TP_CHECK(q.find("node_dmi_info") != std::string::npos);
}

TP_TEST(decode_samples_basic) {
  Value resp = Value::parse(R"({
    "status": "success",
    "data": {"resultType": "vector", "result": [
      {"metric": {"exported_pod": "p1", "exported_namespace": "ns", "exported_container": "c",
                  "accelerator_type": "tpu-v5-lite-podslice", "node_type": "ct5lp-hightpu-4t"},
       "value": [1785312000, "0"]},
      {"metric": {"exported_pod": "p1", "exported_namespace": "ns", "exported_container": "c",
                  "accelerator_id": "1"},
       "value": [1785312000, "0"]},
      {"metric": {"pod": "p2", "namespace": "ns2", "container": "c2"},
       "value": [1785312000, "0"]}
    ]}
  })");
  auto r = metrics::decode_instant_vector(resp, "tpu");
  TP_CHECK_EQ(r.num_series, size_t(3));
  TP_CHECK_EQ(r.samples.size(), size_t(2));  // p1 deduped across chips
  TP_CHECK_EQ(r.samples[0].accelerator, std::string("tpu-v5-lite-podslice"));
  TP_CHECK_EQ(r.samples[1].name, std::string("p2"));  // native label fallback
  TP_CHECK_EQ(r.samples[1].accelerator, std::string("unknown"));
}

TP_TEST(decode_gpu_requires_model_name) {
  Value resp = Value::parse(R"({
    "status": "success",
    "data": {"resultType": "vector", "result": [
      {"metric": {"exported_pod": "p1", "exported_namespace": "ns", "exported_container": "c"},
       "value": [1785312000, "0"]}
    ]}
  })");
  auto r = metrics::decode_instant_vector(resp, "gpu");
  TP_CHECK_EQ(r.samples.size(), size_t(0));
  TP_CHECK_EQ(r.errors.size(), size_t(1));
  TP_CHECK(r.errors[0].find("modelName") != std::string::npos);
}

TP_TEST(decode_rejects_non_vector) {
  Value resp = Value::parse(R"({"status":"success","data":{"resultType":"matrix","result":[]}})");
  bool threw = false;
  try {
    metrics::decode_instant_vector(resp, "tpu");
  } catch (const std::runtime_error&) {
    threw = true;
  }
  TP_CHECK(threw);
}
