// tpu-pruner: small shared utilities (time, ids, strings, files).
//
// Covers the reference's uses of jiff (Timestamp::now, SignedDuration —
// main.rs:413-414, lib.rs:391-402) and uuid (event names, lib.rs:390,412)
// without external dependencies.
#pragma once

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <optional>
#include <string>
#include <string_view>
#include <thread>
#include <vector>

namespace tpupruner::util {

// Unix epoch seconds (wall clock, UTC).
int64_t now_unix();

// Unix epoch nanoseconds (wall clock; OTLP span/metric timestamps).
int64_t now_unix_nanos();

// Monotonic seconds (steady clock; staleness windows immune to NTP steps).
int64_t mono_secs();

// RFC 4648 base64 (no line breaks) — Proxy-Authorization: Basic credentials.
std::string base64_encode(std::string_view in);

// Process-wide graceful-shutdown flag: the daemon's SIGTERM/SIGINT
// handler stores the signal number here; interruptible waits (daemon
// interval sleep, k8s 429-retry sleep) poll it so shutdown latency stays
// bounded even mid-backoff. Function-local static — call once before
// installing signal handlers so the handler never hits first-init.
std::atomic<int>& shutdown_flag();

// Format epoch seconds (+ optional subsecond digits of `nanos`) as RFC 3339
// UTC, e.g. "2026-07-29T07:47:45Z" / "2026-07-29T07:47:45.123456Z".
std::string format_rfc3339(int64_t unix_secs, int64_t nanos = 0, int subsec_digits = 0);

// Current time as RFC 3339 with microsecond precision (K8s MicroTime shape).
std::string now_rfc3339_micro();
// Current time as RFC 3339 with second precision (K8s Time shape).
std::string now_rfc3339();

// Parse RFC 3339 (e.g. K8s creationTimestamp "2026-07-29T07:47:45Z",
// fractional seconds and numeric offsets accepted). Returns epoch seconds.
std::optional<int64_t> parse_rfc3339(std::string_view s);

// 32 hex chars from the system CSPRNG, like uuid::Uuid::new_v4().as_simple()
// in the reference (lib.rs:390, 412).
std::string random_hex32();

std::vector<std::string> split(std::string_view s, char sep);
std::string to_lower(std::string_view s);
bool starts_with(std::string_view s, std::string_view prefix);
std::string trim(std::string_view s);

std::optional<std::string> read_file(const std::string& path);

// Getenv as optional<string>.
std::optional<std::string> env(const char* name);

// URL-encode for application/x-www-form-urlencoded bodies / query strings.
std::string url_encode(std::string_view s);
// Inverse: %XX → byte; malformed escapes pass through verbatim.
std::string url_decode(std::string_view s);

// Run fn(i) for i in [0, n) from min(workers, n) threads pulling indices
// off a shared counter, then join. The daemon's fan-out idiom (reference:
// buffer_unordered, main.rs:530).
template <typename Fn>
void fan_out(size_t workers, size_t n, Fn&& fn) {
  workers = std::min(workers, n);
  if (workers == 0) return;
  std::atomic<size_t> next{0};
  auto worker_fn = [&] {
    while (true) {
      size_t i = next.fetch_add(1);
      if (i >= n) break;
      fn(i);
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(workers);
  for (size_t i = 0; i < workers; ++i) threads.emplace_back(worker_fn);
  for (std::thread& t : threads) t.join();
}

}  // namespace tpupruner::util
