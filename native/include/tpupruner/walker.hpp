// Owner-reference resolution: pod → root scalable object.
//
// Reference analog: find_root_object (gpu-pruner/src/lib.rs:437-513):
//   kserve label shortcut → InferenceService;
//   ownerRef ReplicaSet → (Deployment | ReplicaSet);
//   ownerRef StatefulSet → (Notebook | StatefulSet);
//   unknown kinds ignored; error when nothing matches.
//
// TPU-native addition (SURVEY.md §7.3): ownerRef Job → JobSet — the owner
// chain of every multi-host GKE TPU slice pod — plus the slice-completeness
// gate: a JobSet may only be suspended when EVERY tpu-requesting pod of the
// slice is in the idle set (a partially idle slice means the workload is
// alive and mid-collective; suspending it would kill healthy hosts).
#pragma once

#include <functional>
#include <set>
#include <string>

#include "tpupruner/core.hpp"
#include "tpupruner/k8s.hpp"

namespace tpupruner::walker {

// Resolve the root scalable object for a pod (fetched Pod JSON).
// Throws std::runtime_error("no scalable root object ...") when the pod has
// no recognized owner chain — callers log-and-skip (main.rs:517-527).
core::ScaleTarget find_root_object(const k8s::Client& client, const json::Value& pod);

// Key "ns/pod" set of idle pods discovered this cycle.
using IdlePodSet = std::set<std::string>;
inline std::string pod_key(const std::string& ns, const std::string& name) {
  return ns + "/" + name;
}

// True when every pod of `jobset` that requests google.com/tpu resources is
// present in `idle`. Lists the JobSet's pods via the
// jobset.sigs.k8s.io/jobset-name label.
bool jobset_fully_idle(const k8s::Client& client, const core::ScaleTarget& jobset,
                       const IdlePodSet& idle);

// True when any container of the pod requests google.com/tpu (requests or
// limits) — the resource-model filter for slice membership.
bool pod_requests_tpu(const json::Value& pod);

}  // namespace tpupruner::walker
