// Owner-reference resolution: pod → root scalable object.
//
// Reference analog: find_root_object (gpu-pruner/src/lib.rs:437-513):
//   kserve label shortcut → InferenceService;
//   ownerRef ReplicaSet → (Deployment | ReplicaSet);
//   ownerRef StatefulSet → (Notebook | StatefulSet);
//   unknown kinds ignored; error when nothing matches.
//
// TPU-native addition (SURVEY.md §7.3): ownerRef Job → JobSet — the owner
// chain of every multi-host GKE TPU slice pod — plus the slice-completeness
// gate: a JobSet may only be suspended when EVERY tpu-requesting pod of the
// slice is in the idle set (a partially idle slice means the workload is
// alive and mid-collective; suspending it would kill healthy hosts).
#pragma once

#include <condition_variable>
#include <functional>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "tpupruner/core.hpp"
#include "tpupruner/informer.hpp"
#include "tpupruner/k8s.hpp"

namespace tpupruner::walker {

// Per-cycle memoization of owner fetches. Every pod of a multi-host slice
// shares the same Job → JobSet chain and every pod of a Deployment shares
// its ReplicaSet, so the reference's refetch-per-pod pattern (lib.rs:465,
// 485) costs O(pods) API calls where O(owners) suffices. Entries live for
// one evaluation cycle — the same staleness window the reference already
// tolerates for in-flight objects. Thread-safe.
class FetchCache {
 public:
  // nullopt-cached misses are remembered too (404s repeat per cycle).
  using Entry = std::optional<json::Value>;
  Entry get_or_fetch(const std::string& key, const std::function<Entry()>& fetch);

  // Pre-populate an entry (batched-LIST prefetch). First writer wins: a
  // seed never overwrites a fetched or previously seeded entry.
  void seed(const std::string& key, Entry entry);

  // Every completed entry (fetched or seeded, cached misses included) —
  // the flight recorder's owner-object snapshot. In-flight and failed
  // flights are skipped.
  std::vector<std::pair<std::string, Entry>> snapshot();

 private:
  struct Flight {
    std::mutex m;
    std::condition_variable cv;
    bool done = false;
    bool failed = false;  // leader threw; waiters retry instead of caching
    Entry entry;
  };
  std::mutex mutex_;
  std::unordered_map<std::string, std::shared_ptr<Flight>> map_;
};

// Batched owner-chain prefetch: scan the (already fetched, eligible) pods'
// labels and ownerReferences, and for every owner collection demanded by
// more than `threshold` distinct names, issue ONE namespace-collection
// LIST and seed the results into `cache` — so the subsequent per-pod
// find_root_object walks hit memory instead of the API server. Two waves:
//   wave 1: Pod → {ReplicaSet, StatefulSet, Job, kserve/LWS label roots}
//   wave 2: listed wave-1 objects → {Deployment, Notebook, JobSet, LWS}
// The reference pays 1-3 GETs per candidate pod (main.rs:444-446); with
// batching an N-pod reclaim cycle costs O(namespaces × kinds) LISTs.
// Collections at or below the threshold keep per-object GETs (a LIST
// returns the whole collection — not worth it for a handful of owners).
// LIST failures degrade to the unbatched path. Returns #LISTs issued.
size_t prefetch_owner_chains(const k8s::Client& client, FetchCache& cache,
                             const std::vector<const json::Value*>& pods,
                             int64_t threshold, size_t concurrency);

// Object source for the owner walk: API object path → object (nullopt =
// absent/404). May throw for transport errors — each hop handles that the
// way the live walk does (mid-chain fetches are best-effort, root fetches
// propagate). The live walk wraps client+cache+store into one of these;
// the flight-recorder replay wraps a capsule's recorded object snapshot,
// so the SAME walk code runs online and offline.
using ObjectFetcher = std::function<std::optional<json::Value>(const std::string&)>;

// The walk itself, over an abstract object source. Throws
// std::runtime_error("no scalable root object ...") when the pod has no
// recognized owner chain. `chain_out` (optional) receives the resolved
// hops as "Kind/ns/name" strings, pod first.
core::ScaleTarget find_root_object_from(const ObjectFetcher& fetch, const json::Value& pod,
                                        std::vector<std::string>* chain_out = nullptr);

// The live read-through chain (per-cycle cache → watch store → GET) as an
// ObjectFetcher — exactly what find_root_object wraps. Exposed so the
// incremental reconcile engine can interpose a tracing fetcher and record
// which object paths a pod's walk consulted (the watch-event reverse
// index and the cached capsule object snapshot both need the per-pod
// list). The returned fetcher borrows `client`/`cache`/`store`: they must
// outlive it (one resolve stage).
ObjectFetcher live_fetcher(const k8s::Client& client, FetchCache* cache,
                           const informer::ClusterCache* store);

// Resolve the root scalable object for a pod (fetched Pod JSON).
// Throws std::runtime_error("no scalable root object ...") when the pod has
// no recognized owner chain — callers log-and-skip (main.rs:517-527).
// `cache` (optional) memoizes owner fetches within an evaluation cycle.
// `watch_cache` (optional) makes the per-cycle cache a READ-THROUGH view of
// the watch-backed cluster store: each owner fetch consults the store
// first and only falls back to a live GET on a miss (store unsynced,
// resource unwatched, or object genuinely absent — absence is never
// negative-cached, so a lagging watch costs an API call, not correctness).
// `chain_out` (optional) receives the resolved hops as "Kind/ns/name"
// strings, pod first — the DecisionRecord.owner_chain audit field.
core::ScaleTarget find_root_object(const k8s::Client& client, const json::Value& pod,
                                   FetchCache* cache = nullptr,
                                   const informer::ClusterCache* watch_cache = nullptr,
                                   std::vector<std::string>* chain_out = nullptr);

// Key "ns/pod" set of idle pods discovered this cycle. Unordered: only
// membership is ever asked (the group gates), and at fleet scale the
// per-cycle inserts sit on the reconcile hot path.
using IdlePodSet = std::unordered_set<std::string>;
inline std::string pod_key(const std::string& ns, const std::string& name) {
  return ns + "/" + name;
}

// True when every pod of the group that requests google.com/tpu resources
// is present in `idle`. Applies to the two multi-host group kinds: JobSet
// (pods labelled jobset.sigs.k8s.io/jobset-name) and LeaderWorkerSet
// (pods labelled leaderworkerset.sigs.k8s.io/name).
bool group_fully_idle(const k8s::Client& client, const core::ScaleTarget& group,
                      const IdlePodSet& idle);

// Batch form: ONE set-based-selector LIST per (namespace, group kind)
// instead of one LIST per group — at reclaim scale the per-slice LISTs
// dominate the gate. Returns keep flags aligned with `groups`; entries the
// LIST failed for are kept=false (safe side). Non-group kinds in `groups`
// are rejected with keep=false.
std::vector<char> groups_fully_idle(const k8s::Client& client,
                                    const std::vector<const core::ScaleTarget*>& groups,
                                    const IdlePodSet& idle);

// True when any container of the pod requests google.com/tpu (requests or
// limits) — the resource-model filter for slice membership.
bool pod_requests_tpu(const json::Value& pod);

}  // namespace tpupruner::walker
