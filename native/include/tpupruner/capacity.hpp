// Capacity observatory: the live free-capacity inventory behind
// /debug/capacity, the tpu_pruner_capacity_* metric families, the fourth
// delta-journaled fleet surface, and the replayable defragmentation
// report.
//
// Pruning's chief output — freed TPU capacity — was invisible: the
// ledger knows what was reclaimed, but nothing published what is free
// RIGHT NOW, where, and in what shape. Shape matters because multi-host
// slices are only schedulable whole (MLPerf TPU-pod scaling, arxiv
// 1909.09756): 3 idle chips scattered across three 4-chip slices are
// worth far less than one whole free slice. ParvaGPU (arxiv 2409.14447)
// treats reclaimed accelerator capacity as supply to be packed; this
// module is the supply ledger for that view.
//
// Everything observable is derived from a canonical, order-normalized
// Inputs record (nodes with their node-pool/slice-topology labels, TPU
// pod placements with idleness + owning root, freed ledger accounts).
// build() is a PURE function of Inputs — the daemon stamps the result
// with its cluster identity and republishes per evaluation; the recorder
// stamps {inputs, doc} into the flight capsule so `analyze
// --capacity-report` can recompute the document bit-for-bit and score
// consolidation with the gym's dt-integration ledger math.
//
// Slice semantics (one GKE node-pool == one TPU slice):
//   whole_free     zero occupied chips — schedulable as a whole slice
//   partial_idle   occupied, but some occupied chips belong to idle roots
//                  (or some capacity is unallocated) — the defrag signal
//   busy           every chip accounted to non-idle tenants, none free
//   consolidatable partial_idle AND every occupied chip belongs to idle
//                  roots: pausing/right-sizing its tenants frees the
//                  WHOLE slice.
//
// The slice-topology group gate (satellite of the same PR) rides on the
// same Inputs: a root whose idle pods share a slice with a BUSY tenant
// is spared (audit reason SLICE_SHARED_BUSY) — evicting it would
// fragment a slice that cannot become whole anyway.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::capacity {

// The FIXED audit detail for SLICE_SHARED_BUSY — shared verbatim by the
// daemon gate and capsule replay so replayed outcomes are byte-identical.
inline constexpr const char* kSliceSharedBusyDetail =
    "slice has busy co-tenants (slice gate)";

// One TPU node (slice host) observed via /api/v1/nodes.
struct NodeFact {
  std::string name;
  std::string pool;      // cloud.google.com/gke-nodepool → slice identity
  std::string topology;  // cloud.google.com/gke-tpu-topology ("" unknown)
  int64_t chips = 0;     // allocatable google.com/tpu
};

// One TPU-requesting pod placed on a node.
struct PlacementFact {
  std::string pod;   // "ns/name"
  std::string node;  // spec.nodeName ("" unscheduled → ignored by build)
  int64_t chips = 0;
  bool idle = false;  // member of this evaluation's idle+eligible set
  std::string root;   // owning root "Kind/ns/name" ("" unresolved)
};

// One ledger account whose capacity is currently freed by an actuation.
struct FreedFact {
  std::string kind, ns, name;
  int64_t chips = 0;
  std::string state;  // "paused" | "right_sized"
};

struct Inputs {
  std::vector<NodeFact> nodes;
  std::vector<PlacementFact> placements;
  std::vector<FreedFact> freed;
};

// Canonical JSON round-trip for Inputs (the capsule "capacity.inputs"
// stamp). inputs_json SORTS each section (nodes by name, placements by
// pod, freed by kind/ns/name), so the stamp — and everything derived
// from it — is independent of informer shard count and wire format.
json::Value inputs_json(const Inputs& in);
Inputs inputs_from_json(const json::Value& v);

// The inventory document: {"schema", "slices": [...], "totals": {...},
// "freed": {...}} — pure, deterministic, no cluster/cycle stamps (the
// daemon layers identity on the published copy).
json::Value build(const Inputs& in);

// Slice-topology group gate: the sorted, de-duplicated roots that must
// be HELD because at least one of their idle pods shares a slice
// (node-pool) with a busy TPU tenant.
std::vector<std::string> shared_busy_roots(const Inputs& in);

// ── the daemon's published document (process-wide, thread-safe) ──
// null until the first publish; reset_for_test clears.
void set_current(json::Value doc);
json::Value current();
bool enabled();
void set_enabled(bool on);
void reset_for_test();

// Prometheus text for one inventory document (all gauges, so the
// OpenMetrics flag only matters for future counter families).
std::string render_metrics(const json::Value& doc, bool openmetrics);

// Canonical tpu_pruner_capacity_* family list (docs drift guard, capi).
std::vector<std::string> metric_families();

// Defragmentation report over an ARRAY of capsule capacity stamps
// [{"cycle", "now_unix", "inputs", "doc"}, ...] (any order; sorted by
// cycle internally). Recomputes every document from its inputs —
// byte-level drift against the recorded doc is reported per cycle — and
// dt-integrates consolidation potential across the window with the
// gym's ledger math (dt = now - previous stamp's now; the first stamp
// integrates nothing). The moves section lists, from the LAST stamp,
// the pause/right-size actions that would free each consolidatable
// slice whole. Throws std::runtime_error on malformed stamps.
json::Value report(const json::Value& stamps);

}  // namespace tpupruner::capacity
