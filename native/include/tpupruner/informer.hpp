// Informer-style List+Watch cluster cache (store + reflector).
//
// Reference analog: client-go's Reflector/Store pair (the machinery behind
// every Kubernetes controller), which the reference binary — and this
// rebuild until now — deliberately lacked: the watch-free client re-LISTs
// candidate pods and re-GETs owner chains every cycle, so steady-state
// API-server cost scales with CLUSTER SIZE (~7.5k calls per cycle on the
// r05 bench's 4,416-pod cluster) instead of with CHURN. The cache LISTs
// each resource once, then holds a streaming `watch=true` connection and
// applies ADDED/MODIFIED/DELETED/BOOKMARK events under resourceVersion
// ordering; a `410 Gone` (apiserver compacted past our resourceVersion)
// triggers a full relist with jittered backoff.
//
// Safety contract (the part that lets the daemon trust a cache):
//   - A store only answers (`get` returns a value) while its watch loop is
//     SYNCED: listed at least once AND no un-relisted 410/error streak.
//     Everything else returns nullopt and the caller falls back to the
//     watch-free GET — graceful degradation is the miss path, not a mode.
//   - On 410 (events were missed) the store is marked UNSYNCED BEFORE the
//     relist starts, so no concurrent cycle can actuate from pre-compaction
//     state — asserted by tests: no stale-object patch after a relist.
//   - Lookup misses are never negative-cached: an absent object still GETs,
//     so a lagging watch can only cost an API call, never skip the
//     tpu-pruner.dev/skip annotation check.
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <thread>
#include <vector>

#include "tpupruner/compact.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/k8s.hpp"
#include "tpupruner/proto.hpp"

namespace tpupruner::informer {

// One watched resource: the cluster-scoped LIST+WATCH endpoint plus the
// pieces needed to rebuild per-object paths ("<prefix>namespaces/<ns>/
// <plural>/<name>") — the same keys k8s::Client's path builders produce,
// so walker/daemon lookups need no translation layer.
struct ResourceSpec {
  std::string list_path;  // e.g. "/api/v1/pods", "/apis/apps/v1/replicasets"
  std::string prefix;     // e.g. "/api/v1/", "/apis/apps/v1/"
  std::string plural;     // e.g. "pods"
};

// Spec for a well-known plural ("pods", "replicasets", "jobs", "jobsets",
// ...); nullopt for unknown names.
std::optional<ResourceSpec> spec_for(std::string_view plural);
// The daemon's full watch set: pods + every owner/root kind it resolves.
std::vector<ResourceSpec> daemon_specs();

struct ResourceStats {
  bool synced = false;
  uint64_t objects = 0;
  uint64_t adds = 0;
  uint64_t updates = 0;
  uint64_t deletes = 0;
  uint64_t bookmarks = 0;
  uint64_t relists = 0;
  // Relist REQUESTS (ERROR/410 events, watch-failure streaks) after
  // coalescing: a 410 that lands while a relist is already in flight is
  // absorbed, not queued — `relists` counts LISTs actually applied.
  uint64_t relist_requests = 0;
  uint64_t watch_failures = 0;
  std::string resource_version;
  // Approximate retained bytes of this resource's store entries.
  uint64_t store_bytes = 0;
  // Last cold LIST→synced wall for this resource (negative: none yet).
  double cold_sync_seconds = -1.0;
};

// Thread-safe object store for one resource. Values share JSON nodes
// (json::Value is COW), so get() copies are pointer-sized.
//
// Zero-copy mode (json::zero_copy_enabled): entries are (DocPtr, node)
// references into the LIST-page / watch-event arenas instead of Value
// trees — a 100k-pod LIST never materializes 100k maps-of-shared-ptrs.
// get() materializes a Value on demand, so only the objects a cycle
// actually touches (candidates, owner chains) ever pay tree construction.
class Store {
 public:
  // Either a materialized Value, an arena (Doc, node) reference, a
  // packed compact record (--compact-store on), or — on the binary wire
  // path — a protobuf slice into a shared page/frame buffer. All four
  // materialize to IDENTICAL Values on get().
  struct Entry {
    // Exact (non-compact) representations, out-of-line: a million-pod
    // compact store pays 32 inline bytes per entry instead of ~216 —
    // the Exact block is allocated only for entries that actually hold
    // a Value tree, an arena node or a proto slice (or memoize one on
    // first read).
    struct Exact {
      json::Value value;
      json::DocPtr doc;
      uint32_t node = 0;
      // Proto-backed entry (--wire proto): raw object bytes inside a
      // LIST page / watch frame (aliased shared_ptr keeps the buffer
      // alive), materialized lazily via proto::object_to_value.
      std::shared_ptr<const std::string> pbody;
      size_t poff = 0, plen = 0;
      std::string papi, pkind;
    };
    std::unique_ptr<Exact> exact;
    // Fused-path fingerprint over the object's wire bytes.
    uint64_t pfp = 0;
    // Compact-store entry: a packed interned record the upsert decoded
    // straight into — no page buffer, Doc arena or Value tree retained.
    // Materializes lazily (and memoizes into `exact`) via
    // PodRecord::to_value.
    std::shared_ptr<const compact::PodRecord> rec;

    Exact& ex() {
      if (!exact) exact = std::make_unique<Exact>();
      return *exact;
    }
  };

  std::optional<json::Value> get(const std::string& object_path) const;
  bool contains(const std::string& object_path) const;
  size_t size() const;
  // Swap in a full LIST snapshot (relist semantics: objects deleted while
  // the watch was down vanish here).
  void replace(std::map<std::string, json::Value> objects);
  void replace_entries(std::map<std::string, Entry> objects);
  void upsert(const std::string& object_path, json::Value object);
  void upsert_doc(const std::string& object_path, json::DocPtr doc, uint32_t node);
  // Binary wire path: store the raw protobuf object slice (no tree of any
  // kind is built until some cycle actually reads the object).
  void upsert_proto(const std::string& object_path, std::shared_ptr<const std::string> body,
                    size_t off, size_t len, std::string api_version, std::string kind,
                    uint64_t fp);
  // The stored entry's fused-path fingerprint (0 for non-proto entries /
  // absent paths) — the native wire tests assert single-pass decode
  // against it.
  uint64_t proto_fingerprint(const std::string& object_path) const;
  void erase(const std::string& object_path);

  ~Store();
  // Resource identity for the store gauges (pods feed
  // tpu_pruner_store_pods) and the compact-record upsert gate. Called
  // once by the owning Reflector before any entry lands.
  void configure(std::string plural);
  // Approximate retained bytes across entries (the per-store slice of
  // tpu_pruner_store_bytes).
  uint64_t retained_bytes() const;
  // Entry cost estimator shared with the cold-sync snapshot builder.
  static size_t entry_cost(const std::string& path, const Entry& e);

 private:
  // Re-point this store's contribution to the process-wide gauges after
  // a mutation (caller holds mutex_; const because get() memoization
  // shifts representation cost under a const API).
  void settle_gauges(int64_t bytes_delta, int64_t object_delta) const;

  // Accounted single-entry insert/overwrite shared by the upsert_* paths.
  void put(const std::string& object_path, Entry e);

  bool pods_ = false;
  mutable std::mutex mutex_;
  // mutable: get() memoizes an arena entry's materialized Value in place
  // (logically const — the entry's content is unchanged, only its
  // representation).
  mutable std::map<std::string, Entry> objects_;
  mutable size_t bytes_ = 0;
};

// List+watch driver for one resource, owning its Store and worker thread.
// Exposed (rather than folded into ClusterCache) for unit tests: apply_*
// methods are the pure event-application core the reflector thread drives.
class Reflector {
 public:
  Reflector(const k8s::Client& kube, ResourceSpec spec);
  ~Reflector();

  void start();
  void stop();  // signal + join; bounded by the watch read poll (~250ms)

  bool synced() const { return synced_.load(); }
  std::optional<json::Value> get(const std::string& object_path) const;
  ResourceStats stats() const;
  const ResourceSpec& spec() const { return spec_; }
  // Monotonic seconds of the last applied LIST or watch event (bookmarks
  // count: they prove the stream is live). 0 = never.
  int64_t last_activity_mono() const { return last_activity_mono_.load(); }

  // ── pure event application (unit-testable without a server) ──
  // Apply one watch event {type, object}. Returns false when the event
  // demands a relist (ERROR status, e.g. code 410). Relist requests are
  // COALESCED: an ERROR/410 arriving while a relist is already pending
  // (LIST in flight) marks nothing new — apply_list services and clears
  // the pending flag — so a 410 storm can never stack relists. Safe to
  // call concurrently with apply_list (the relist window is exactly when
  // a late watch event can still race the fresh LIST).
  bool apply_event(const json::Value& event);
  // Zero-copy sibling: the event Doc's object subtree is stored as an
  // arena reference (the event Doc stays alive while its object is in the
  // store). Semantics identical to apply_event.
  bool apply_event_doc(const json::DocPtr& event);
  // Binary-wire sibling — the FUSED path: the frame was decoded in one
  // scan (type + object slice + store key + fingerprint, proto.cpp); this
  // applies journal_touch and the store upsert from those fields with no
  // intermediate Value/Doc ever built. Semantics identical to
  // apply_event: same journal marks, same stats, same relist requests.
  bool apply_event_proto(const proto::WatchEventPtr& event);
  // Apply a LIST result (replace + resourceVersion adoption); services
  // any pending relist request.
  void apply_list(const json::Value& list);
  // Snapshot-level core shared by apply_list and the paginated zero-copy
  // LIST path in run(): swaps the store and adopts `rv`.
  void apply_list_snapshot(std::map<std::string, Store::Entry> snapshot, std::string rv);
  // Object path for an arena-doc object node ("" when metadata is missing).
  std::string object_path_of_doc(const json::Doc::Node& object) const;
  // True while a requested relist has not yet been serviced by apply_list.
  bool relist_pending() const { return relist_pending_.load(); }
  // Object path for an object of this resource (empty when metadata is
  // missing — such objects are ignored, never half-keyed).
  std::string object_path_of(const json::Value& object) const;

  // ── dirty journal (incremental reconcile, incremental.hpp) ──
  // When enabled, every applied ADDED/MODIFIED/DELETED event appends its
  // object path to a per-reflector journal and every LIST snapshot
  // (initial sync or relist — events may have been missed) marks the
  // journal GLOBALLY dirty. drain_dirty() moves the journal out under the
  // lock; the journal is bounded (overflow degrades to globally dirty,
  // never to a silently dropped invalidation). Off by default: without a
  // drain the journal would grow for the life of the process.
  void enable_dirty_journal();
  // Event fan-out (--reconcile event): invoked (outside the journal lock)
  // after every journal mark — the dispatcher's wake signal, carrying the
  // monotonic ms the event was decoded (the trigger-ingress stamp the
  // trace engine backdates its root span to). Must be set BEFORE start()
  // (read lock-free on the reflector thread) and must not call back into
  // the reflector; a notify is a hint to drain, not a payload.
  void set_dirty_notify(std::function<void(int64_t arrival_mono_ms)> notify);
  // const: drains a logically-external queue (the cycle holds the cache
  // by const pointer); journal state is mutable under its own mutex.
  void drain_dirty(std::vector<std::string>& paths, bool& all) const;
  // Cumulative journal-cap overflows (each degraded one drain to
  // globally dirty) — the churn-storm instrumentation.
  uint64_t journal_overflows() const;

 private:
  void run();  // thread body: relist loop wrapping the watch loop
  // Cold LIST→synced: fetches pages on a helper thread while this thread
  // decodes+keys them (compact mode fans item decode out over a shard
  // pool), then swaps the snapshot in. Throws on fetch/decode failure.
  void cold_sync(bool wire_proto, bool zero_copy);
  void bump_watch_failure(const std::string& why);
  void journal_touch(const std::string& path);  // dirty-journal append
  void journal_all();                           // dirty-journal global mark
  // Mark a relist request; returns false when one was already pending
  // (the request is coalesced, not stacked).
  bool request_relist(const std::string& why);
  std::string resource_version() const;

  const k8s::Client& kube_;
  ResourceSpec spec_;
  Store store_;
  std::atomic<bool> synced_{false};
  std::atomic<bool> stop_{false};
  std::atomic<bool> relist_pending_{false};
  std::atomic<int64_t> last_activity_mono_{0};
  // Last cold LIST→synced wall (seconds; negative until the first sync).
  std::atomic<double> cold_sync_secs_{-1.0};
  // Dirty journal: touched object paths since the last drain. Guarded by
  // dirty_mutex_; journal_enabled_ is set once before start() (daemon
  // startup) and read on every event, so it is atomic.
  std::atomic<bool> journal_enabled_{false};
  std::function<void(int64_t)> dirty_notify_;  // set before start(); see setter
  mutable std::mutex dirty_mutex_;
  mutable std::vector<std::string> dirty_paths_;
  mutable bool dirty_all_ = false;
  mutable uint64_t journal_overflows_ = 0;
  std::thread thread_;
  mutable std::mutex stats_mutex_;
  ResourceStats stats_;
  // Watch resume point. Guarded by stats_mutex_: apply_event and
  // apply_list may run concurrently around a relist (a straggling watch
  // frame vs the fresh LIST), and both touch it.
  std::string resource_version_;
};

// The daemon-facing facade: one Reflector per watched resource, lookups
// routed by object path shape.
class ClusterCache {
 public:
  ClusterCache(const k8s::Client& kube, std::vector<ResourceSpec> specs);
  ~ClusterCache();

  void start();
  void stop();

  // Block until every resource has completed its initial LIST, up to
  // timeout_ms. Returns whether full sync was reached (callers proceed
  // either way — unsynced resources just miss).
  bool wait_synced(int timeout_ms) const;

  // Cached object for a namespaced object path, or nullopt when the path's
  // resource is unwatched/unsynced or the object is absent. Callers MUST
  // treat nullopt as "ask the API server", never as a 404.
  std::optional<json::Value> get(const std::string& object_path) const;

  bool all_synced() const;
  // True when the pods resource specifically is synced (the resolve
  // phase's gate for skipping its namespace pod LISTs).
  bool pods_synced() const;

  // Worst-resource staleness: seconds since the least-recently-active
  // reflector applied a LIST or watch event (bookmarks count). Feeds the
  // tpu_pruner_informer_staleness_seconds gauge — a watch stream that went
  // quiet without erroring shows up here long before a relist fires.
  int64_t staleness_secs() const;

  // Aggregate + per-resource stats (capi/tests/metrics).
  json::Value stats_json() const;

  // ── dirty journal (incremental reconcile) ──
  // Enable journaling on every reflector (call before start()).
  void enable_dirty_journal();
  // Event fan-out: wake `notify` after any reflector journals a mark
  // (--reconcile event's watch-plane trigger), passing the monotonic ms
  // the event was decoded. Call before start().
  void set_dirty_notify(std::function<void(int64_t arrival_mono_ms)> notify);
  // Everything touched since the last drain, across all resources.
  // `all == true` means at least one resource relisted (or its journal
  // overflowed) — events may have been missed, so the caller must treat
  // the WHOLE world as dirty, not just `paths`.
  struct DirtyDrain {
    bool all = false;
    std::vector<std::string> paths;
    uint64_t overflows_total = 0;  // cumulative journal-cap overflows
  };
  DirtyDrain drain_dirty() const;

 private:
  const Reflector* route(const std::string& object_path) const;

  std::vector<std::unique_ptr<Reflector>> reflectors_;
  // Monotonic second start() ran: a reflector that never applied anything
  // is as stale as the CACHE is old — without this anchor it would report
  // the steady clock's epoch distance (machine uptime), i.e. garbage.
  std::atomic<int64_t> start_mono_{0};
};

// The per-reflector dirty-journal bound (paths retained before a drain
// degrades to globally dirty) — exported so the bench's churn-storm
// phase can assert the served journal-depth gauge stays under it.
size_t dirty_journal_cap();

}  // namespace tpupruner::informer
