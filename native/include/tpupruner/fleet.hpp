// Fleet federation: per-cluster identity, exposition stamping, and the
// hub's merge math.
//
// One daemon prunes one cluster, but the north star is a fleet of them —
// and every observability surface built so far (metrics, DecisionRecords,
// the workload ledger, flight capsules, the /debug endpoints) was blind
// to WHICH cluster it came from, so N ledgers could not merge and a
// browned-out cluster could hide inside a fleet average. This module is
// the federation layer's foundation, in three parts:
//
//   1. Identity: a process-wide cluster name (--cluster-name; default
//      resolved by resolve_cluster_name's heuristic) that every exporter
//      stamps — a `cluster` label on every /metrics sample line (the
//      stamp_exposition choke point in metrics_http), a "cluster" key in
//      every /debug/* JSON payload, every DecisionRecord, every ledger
//      checkpoint line, and every flight capsule.
//   2. Merge math: aggregate() folds N member snapshots (each member's
//      /debug/{workloads,signals,decisions} documents plus reachability
//      facts) into the fleet view — per-cluster sections, fleet totals
//      that provably sum, per-cluster-MINIMUM coverage (never the mean:
//      one cluster's dead scrapes must surface even when the fleet looks
//      healthy), and explicit UNREACHABLE rows for members gone dark
//      (never a silent drop from the average). Pure function — the
//      native unit tier drives it directly.
//   3. The hub shell (hub.cpp) polls members and serves the view at
//      /debug/fleet/* plus tpu_pruner_fleet_* metric families.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::fleet {

// ── cluster identity ──
// Process-wide cluster name; "default" until set. Thread-safe.
void set_cluster_name(const std::string& name);
std::string cluster_name();

// Resolution heuristic for the --cluster-name default, first hit wins:
//   1. the flag value itself (non-empty),
//   2. $TPU_PRUNER_CLUSTER_NAME,
//   3. the in-cluster serviceaccount namespace file,
//   4. $POD_NAMESPACE,
//   5. the kubeconfig's `current-context:`,
//   6. "default".
std::string resolve_cluster_name(const std::string& flag_value);

// ── exposition stamping (the metric-label drift guard's choke point) ──
// Insert `cluster="<cluster>"` into the label set of EVERY sample line of
// a Prometheus text exposition (comments and blank lines untouched;
// lines already carrying a cluster label — the hub's per-member rows —
// are left verbatim, so stamping is idempotent). Applied once, at the
// serving boundary (metrics_http::render_exposition), so no renderer can
// ship an unlabelled family.
std::string stamp_exposition(const std::string& body, const std::string& cluster);

// ── hub merge math ──
// Everything the hub learned about one member daemon: the parsed /debug
// documents from its last successful poll plus reachability facts.
struct MemberSnapshot {
  std::string url;          // member base URL (http://host:port)
  std::string cluster;      // from the member's payloads; url fallback
  bool reachable = false;   // the LAST poll round succeeded
  bool ever_reached = false;
  int64_t staleness_s = -1; // seconds since the last successful poll; -1 = never
  std::string last_error;   // last poll failure ("" when none)
  uint64_t polls = 0, failures = 0;
  uint64_t backoffs = 0;    // poll rounds skipped by the failure backoff
  std::string via;          // parent hub URL when expanded from a rollup ("" direct)
  json::Value workloads;    // member /debug/workloads (null until first success)
  json::Value signals;      // member /debug/signals
  json::Value decisions;    // member /debug/decisions
  json::Value capacity;     // member /debug/capacity (null: not running --capacity)
  json::Value slo;          // member SLO summary, the "slo" key of
                            // /debug/traces (null: not running --trace)
};

// The /debug/fleet/* documents plus the fleet metric families'
// exposition text, derived from one poll round's snapshots.
struct FleetView {
  json::Value workloads;  // /debug/fleet/workloads
  json::Value signals;    // /debug/fleet/signals
  json::Value decisions;  // /debug/fleet/decisions
  json::Value capacity;   // /debug/fleet/capacity (free-TPU supply map)
  json::Value slo;        // /debug/fleet/slo (detect→action burn + worst traces)
  json::Value clusters;   // /debug/fleet/clusters
  std::string metrics_text;        // classic exposition
  std::string metrics_openmetrics; // OpenMetrics TYPE naming
};

// Member status for the clusters table and the metric rows:
//   OK           reachable and fresh (staleness within stale_after_s)
//   PENDING      never polled successfully, never failed (startup)
//   UNREACHABLE  gone dark — failed polls, or last success too old
// Semantics the view guarantees:
//   - fleet workload totals = the SUM of every member's own last-known
//     /debug/workloads totals (cached data from an unreachable member is
//     kept and flagged, never silently dropped);
//   - fleet coverage = the per-cluster MINIMUM: OK members with the
//     signal guard on contribute their coverage_ratio, UNREACHABLE
//     members contribute 0.0 (a dark cluster's evidence health is
//     unknown, which is the opposite of healthy), guard-off members
//     contribute nothing;
//   - every member yields exactly one row in every document.
// Hub-of-hubs: a member whose /debug documents carry `"rollup": true` is
// itself a hub (region → global). aggregate() EXPANDS such members into
// their per-cluster leaves before merging, so a parent hub's view over
// two child hubs is byte-identical (workloads/signals/decisions documents
// and fleet_totals) to one hub over all leaves directly. Semantics:
//   - stale propagation: a child hub gone dark forces every one of its
//     last-known leaves UNREACHABLE — a dark REGION pins the fleet
//     coverage minimum to 0 globally, never the mean;
//   - disjointness: the same cluster name surfacing from two different
//     members is a topology error — flagged in `duplicate_clusters` on
//     the signals + clusters documents and pinning coverage_min to 0;
//   - the clusters table keeps leaf rows (each stamped `via` = the child
//     hub's URL) plus a `hubs` section for the child hubs themselves.
FleetView aggregate(const std::vector<MemberSnapshot>& members, int64_t stale_after_s,
                    size_t decisions_per_member = 100);

// The hub's own member-compatible /debug/{workloads,signals,decisions}
// documents (`"rollup": true` + per-cluster sections), so a hub can be a
// --member of a parent hub and its journal can delta-serve them.
json::Value rollup_workloads(const FleetView& view, const std::string& hub_cluster);
json::Value rollup_signals(const FleetView& view, const std::string& hub_cluster);
json::Value rollup_decisions(const FleetView& view, const std::string& hub_cluster);
json::Value rollup_capacity(const FleetView& view, const std::string& hub_cluster);
json::Value rollup_slo(const FleetView& view, const std::string& hub_cluster);

// Status string for one member snapshot ("OK" | "PENDING" |
// "UNREACHABLE") — the same derivation aggregate() applies, exposed so
// the hub's change-gated merge can notice a staleness-driven transition
// without re-running the whole merge.
const char* member_status(const MemberSnapshot& m, int64_t stale_after_s);

// The tpu_pruner_fleet_* family names the hub serves (docs drift guard,
// via capi — includes the fleet_merge_seconds histogram the hub's poll
// loop observes through the log registry).
std::vector<std::string> hub_metric_families();

void reset_for_test();

}  // namespace tpupruner::fleet

namespace tpupruner::hub {

// `tpu-pruner hub` entry point (hub.cpp): parse the hub flag surface
// (--member, --metrics-port, --poll-interval, --stale-after,
// --member-timeout-ms, --cluster-name, --log-format), poll every member's
// /debug/{workloads,signals,decisions}, and serve the merged fleet view
// (fleet::aggregate) at /debug/fleet/* plus tpu_pruner_fleet_* metric
// families until SIGTERM/SIGINT. argv excludes the "hub" token. Returns
// the process exit code (2 on flag errors).
int run(int argc, char** argv);
std::string usage();

}  // namespace tpupruner::hub
