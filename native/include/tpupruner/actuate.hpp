// Scale actuators: the non-destructive "pause" per kind.
//
// Reference analog: Scaler::scale (gpu-pruner/src/lib.rs:337-427, 515-576).
// Ordering contract preserved: the K8s Event is posted FIRST and its
// failure only logged (lib.rs:340-349) — the audit trail must not block the
// action, and the action must not be skipped because auditing failed.
//
// Patch shapes:
//   Deployment/ReplicaSet/StatefulSet → /scale subresource merge-patch
//     {"spec":{"replicas":0}}                           (lib.rs:517-525)
//   Notebook → annotation kubeflow-resource-stopped=<now RFC3339>
//     (Kubeflow's stop contract)                        (lib.rs:529-549)
//   InferenceService → {"spec":{"predictor":{"minReplicas":0}}} so KServe
//     drains and auto-rescales on traffic               (lib.rs:553-576)
//   JobSet → {"spec":{"suspend":true}} — the idiomatic pause for multi-host
//     TPU slices: JobSet deletes child Jobs' pods, freeing every chip in
//     the slice, and resume is a single unsuspend       (TPU-native, new)
#pragma once

#include <string>

#include "tpupruner/core.hpp"
#include "tpupruner/k8s.hpp"

namespace tpupruner::actuate {

struct ScaleOptions {
  std::string device = "tpu";  // event reason text
  // Test injection; production uses wall clock / $POD_NAME.
  std::optional<int64_t> now_unix;
  std::string reporting_instance;
};

// Emit the Event (failure logged only), then apply the per-kind patch.
// Throws std::runtime_error when the PATCH itself fails — the caller counts
// scale_failures and continues (main.rs:347-353).
void scale_to_zero(const k8s::Client& client, const core::ScaleTarget& target,
                   const ScaleOptions& opts = {});

}  // namespace tpupruner::actuate
