// Scale actuators: the non-destructive "pause" per kind.
//
// Reference analog: Scaler::scale (gpu-pruner/src/lib.rs:337-427, 515-576).
// Ordering contract preserved: the K8s Event is posted FIRST and its
// failure only logged (lib.rs:340-349) — the audit trail must not block the
// action, and the action must not be skipped because auditing failed.
//
// Patch shapes:
//   Deployment/ReplicaSet/StatefulSet → /scale subresource merge-patch
//     {"spec":{"replicas":0}}                           (lib.rs:517-525)
//   Notebook → annotation kubeflow-resource-stopped=<now RFC3339>
//     (Kubeflow's stop contract)                        (lib.rs:529-549)
//   InferenceService → {"spec":{"predictor":{"minReplicas":0}}} so KServe
//     drains and auto-rescales on traffic               (lib.rs:553-576)
//   JobSet → {"spec":{"suspend":true}} — the idiomatic pause for multi-host
//     TPU slices: JobSet deletes child Jobs' pods, freeing every chip in
//     the slice, and resume is a single unsuspend       (TPU-native, new)
#pragma once

#include <string>

#include "tpupruner/core.hpp"
#include "tpupruner/k8s.hpp"

namespace tpupruner::actuate {

struct ScaleOptions {
  std::string device = "tpu";  // event reason text
  // Test injection; production uses wall clock / $POD_NAME.
  std::optional<int64_t> now_unix;
  std::string reporting_instance;
  // Skip (no Event, no PATCH) when the target's resolved object already
  // shows its paused state. Only safe when the resolved object is FRESH —
  // the daemon enables it with --watch-cache=on, where objects come from
  // the watch-backed store (or a live GET); the watch-free mode keeps the
  // re-patch-every-cycle behavior (idempotent, and the parity contract).
  bool skip_if_already_paused = false;
  // Exemplar trace id for the per-actuation latency histogram
  // (tpu_pruner_scale_patch_seconds) — the consumer's `scale` span.
  std::string trace_id;
};

// True when the target object already carries its kind's paused state:
// replicas==0 (Deployment/ReplicaSet/StatefulSet/LeaderWorkerSet),
// suspend==true (JobSet), kubeflow-resource-stopped annotation (Notebook),
// predictor.minReplicas==0 (InferenceService).
bool already_paused(const core::ScaleTarget& target);

// Emit the Event (failure logged only), then apply the per-kind patch.
// Returns false when skip_if_already_paused elided the actuation, true
// when the patch was applied. Throws std::runtime_error when the PATCH
// itself fails — the caller counts scale_failures and continues
// (main.rs:347-353).
bool scale_to_zero(const k8s::Client& client, const core::ScaleTarget& target,
                   const ScaleOptions& opts = {});

// Replica right-sizing (--right-size on, gym.hpp): partial scale-down to
// `replicas` for the replica-knob kinds — /scale merge-patch for
// Deployment/ReplicaSet/StatefulSet/LeaderWorkerSet,
// spec.predictor.minReplicas for InferenceService. Same Event-first
// contract as scale_to_zero. Returns false when skip_if_already_paused
// elided the patch (the object already shows <= replicas); throws on an
// unsupported kind — the caller gates on gym::right_size_plan.
bool scale_to_replicas(const k8s::Client& client, const core::ScaleTarget& target,
                       int64_t replicas, const ScaleOptions& opts = {});

}  // namespace tpupruner::actuate
