// tpu-pruner idle-workload query builders.
//
// Reference analog: gpu-pruner/src/query.promql.j2 (rendered once at startup,
// main.rs:280-282). The reference renders a Jinja template; here the same
// query semantics are produced by native builders with a backend seam
// (SURVEY.md §7.2): one source per device class.
//
// Shared query shape (the reference's contract, asserted by its template
// tests at main.rs:572-740):
//   - peak (max_over_time), never average, over the lookback window;
//   - a primary utilization metric with a normalized (/100) fallback,
//     combined with `or`;
//   - optional node-type enrichment join with a bare fallback (`or`) so
//     series still match when the node-info metric is absent;
//   - `== 0` idle predicate on the peak;
//   - an optional corroborating `unless` clause that rescues workloads the
//     utilization metric misses (GPU: peak power draw >= threshold W;
//     TPU: peak HBM bandwidth utilization >= threshold);
//   - honor_labels switch between native (pod/namespace/container) and
//     Prometheus-prefixed (exported_*) label names.
//
// TPU source specifics: `tensorcore_utilization` (0-1, v5e+) is the primary
// signal with `tensorcore_duty_cycle` (0-100, all generations) as the /100
// fallback — mirroring DCGM_FI_PROF_GR_ENGINE_ACTIVE vs DCGM_FI_DEV_GPU_UTIL.
// Node-type enrichment joins `kube_node_labels` on the node label and lifts
// `cloud.google.com/gke-tpu-accelerator` into `node_type` (the analog of the
// node_dmi_info product_name join). Metric names are overridable because GMP
// relabeling differs across clusters.
//
// Two TPU schemas (metric_schema):
//   - "gmp": pod-labeled series, the shape a self-managed exporter or a
//     relabeling GMP pipeline produces (the profile above).
//   - "gke-system": the stock GKE system-metric schema as served by the
//     Cloud Monitoring PromQL API. TPU utilization surfaces there as
//     `kubernetes_io:node_accelerator_tensorcore_utilization` /
//     `…_duty_cycle` / `…_memory_bandwidth_utilization` on the k8s_node
//     monitored resource — node-scoped labels (node_name, accelerator_id,
//     make, model), NO pod/namespace/container labels. Node idleness is
//     computed first (max over the node's chips of each chip's window
//     peak), then attributed to pods with a many-to-one
//     `* on (node_name) group_left(model)` join — pods, from
//     kube-state-metrics' `kube_pod_container_resource_requests`
//     restricted to `resource="google_com_tpu"`, are the many side, so
//     any number of TPU-requesting pods (and containers) per node is
//     legal: shared single-host nodes make every TPU pod on an idle node
//     a candidate, and one busy chip rescues them all. The resource
//     selector keeps non-TPU sidecar/daemonset pods out of the join. The
//     accelerator-type filter matches the `model` metric label; namespace
//     filters apply on the join side (the node series carry none).
//     honor_labels keeps its meaning on the join: GMP-managed KSM collides
//     the `namespace` metric label with the prometheus_target resource
//     label, so stock GMP serves it as `exported_namespace` (default);
//     honor-labels pipelines keep the bare name.
#pragma once

#include <optional>
#include <string>

#include "tpupruner/json.hpp"

namespace tpupruner::query {

struct QueryArgs {
  std::string device = "tpu";  // "tpu" | "gpu"
  int64_t duration_min = 30;   // lookback window (reference -t/--duration)

  std::string namespace_regex;    // pattern pushed into every selector
  // Negative namespace match (ns !~ "..."). A separate flag because RE2
  // (PromQL's regex engine) has no negative lookahead — exclusion is not
  // expressible through the include pattern. No reference analog.
  std::string namespace_exclude_regex;
  std::string model_regex;        // GPU model filter (DCGM modelName)
  std::string accelerator_regex;  // TPU accelerator-type filter

  std::optional<double> power_threshold;  // GPU corroboration, watts
  std::optional<double> hbm_threshold;    // TPU corroboration, HBM bw util (0-1)

  bool honor_labels = false;

  // TPU query schema: "gmp" (pod-labeled series) or "gke-system" (stock
  // GKE node-scoped system metrics + pod-attribution join). The CLI's
  // "auto" resolves before this struct is built (cli::to_query_args).
  std::string metric_schema = "gmp";

  // TPU metric-name overrides (GMP export names vary by cluster config).
  // Under metric_schema=="gke-system" these defaults are remapped to the
  // Cloud Monitoring PromQL forms (kubernetes_io:node_accelerator_*)
  // unless explicitly overridden.
  std::string tensorcore_metric = "tensorcore_utilization";
  std::string duty_cycle_metric = "tensorcore_duty_cycle";
  std::string hbm_metric = "hbm_memory_bandwidth_utilization";

  // gke-system pod-attribution join (kube-state-metrics). join_resource
  // selects TPU-requesting containers; empty disables the resource
  // selector — the override metric must then itself be limited to
  // TPU-requesting pods, or every daemonset pod on an idle node becomes
  // a candidate (docs/OPERATIONS.md).
  std::string join_metric = "kube_pod_container_resource_requests";
  std::string join_resource = "google_com_tpu";
};

// Build the instant-query PromQL for the configured source.
std::string build_idle_query(const QueryArgs& args);

// Build the companion *evidence* query (the signal-quality watchdog's
// second per-cycle query, signal.hpp): instead of asking "which pods are
// idle?" it asks "how trustworthy is the utilization signal itself?" —
// per pod, the sample coverage over the lookback window
// (count_over_time) and the age of the newest sample (time() −
// timestamp()). The two statistics ride ONE instant query, distinguished
// by a synthetic `signal_stat` label ("samples" | "age") stamped with
// label_replace, so a cycle costs exactly one extra round-trip. Shares
// the idle query's selectors, schema switch (gmp pod-labeled series vs
// gke-system node-scoped series joined onto pods) and honor_labels
// handling, so the evidence always covers exactly the series the idle
// verdict was computed from.
std::string build_evidence_query(const QueryArgs& args);

// JSON round-trip for QueryArgs. One shape shared by three consumers: the
// capi payload (tp_build_query), the flight-recorder capsule's config
// fingerprint, and the replay engine's what-if re-render — so a capsule's
// recorded query is always re-buildable from its own config. Keys are the
// capi names (device, duration, namespace, namespace_exclude, model_name,
// accelerator_type, power_threshold, hbm_threshold, honor_labels,
// metric_schema, join_metric, join_resource, tensorcore_metric,
// duty_cycle_metric, hbm_metric); absent keys keep defaults.
json::Value args_to_json(const QueryArgs& args);
QueryArgs args_from_json(const json::Value& v);

}  // namespace tpupruner::query
