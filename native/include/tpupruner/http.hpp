// Minimal blocking HTTP/1.1 client over POSIX sockets.
//
// Reference analog: the reqwest-backed clients (gpu-pruner/src/lib.rs:240-282
// for Prometheus; kube's hyper client for the API server). This image ships
// no libcurl/OpenSSL headers, so transport is hand-rolled: plain HTTP
// natively, HTTPS through a dlopen()'d OpenSSL 3 shim (tls.cpp) — the
// system libssl.so.3 exists even though its headers don't.
//
// Scope matches the reference's needs exactly: request/response with
// bearer-token headers, TLS skip/verify modes and a custom CA bundle
// (TlsMode, lib.rs:233-238, 248-271), content-length and chunked bodies.
// Persistent connections: requests default to HTTP/1.1 keep-alive with a
// per-client connection pool (keyed host:port), because the owner walk
// issues 1-3 API calls per candidate pod (main.rs:444-446) and paying a
// TCP+TLS handshake for each one dominates the resolve fan-out at scale.
// A request on a stale pooled connection (server closed it) is retried
// once on a fresh connection iff no response bytes were received.
#pragma once

#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

namespace tpupruner::http {

enum class TlsMode { Skip, Verify };

struct Url {
  std::string scheme;  // "http" | "https"
  std::string host;
  int port = 80;
  std::string target;  // path + query, always starts with '/'
};

std::optional<Url> parse_url(std::string_view url);

// Connected TCP socket (blocking, TCP_NODELAY, SO_RCV/SNDTIMEO set to
// timeout_ms) or throws — the dial path shared with the h2 transport.
int connect_tcp(const std::string& host, int port, int timeout_ms);

// True when the process's proxy environment (HTTPS_PROXY/HTTP_PROXY/
// NO_PROXY) routes this URL through an egress proxy. The h2 transport
// keeps proxied endpoints on the HTTP/1.1 client.
bool proxy_in_use(const Url& url);

struct Request {
  std::string method = "GET";
  std::string url;
  std::vector<std::pair<std::string, std::string>> headers;
  std::string body;
  int timeout_ms = 30000;
};

struct Response {
  int status = 0;
  std::string body;
  std::map<std::string, std::string> headers;  // keys lowercased
};

namespace detail {
struct Conn;  // pooled transport (fd + optional TLS session)
}

// ── W3C trace-context propagation ──
// Outbound requests carry a `traceparent` header so the daemon's OTLP
// spans correlate with server-side traces (apiserver audit logs, managed
// Prometheus request logs). Resolution order per request: an explicit
// traceparent in Request.headers wins, then the calling thread's value
// (consumer actuations propagate their own `scale` span), then the
// client's default (the producer sets the cycle trace at cycle start).
// Empty string everywhere → no header, zero cost.
void set_thread_traceparent(std::string tp);  // "" clears
const std::string& thread_traceparent();

class Client {
 public:
  explicit Client(TlsMode tls_mode = TlsMode::Verify, std::string ca_file = "");
  ~Client();
  Client(Client&&) noexcept;
  Client& operator=(Client&&) = delete;

  // Throws std::runtime_error on transport/TLS errors; HTTP error statuses
  // are returned, not thrown. Thread-safe; idle connections are pooled and
  // reused across calls.
  Response request(const Request& req) const;

  // Streaming request for long-lived bodies (K8s `watch=true`). Always a
  // FRESH connection (never pooled; never returned to the pool): a watch
  // monopolizes its socket for minutes. Status + headers come back in the
  // Response (its body stays empty); decoded body bytes — chunked,
  // content-length, or close-delimited framing — are handed to on_data as
  // they arrive, regardless of status (error bodies stream too, so callers
  // can collect the apiserver's Status JSON). on_data returning false ends
  // the stream early. `abort` (optional) is polled ~4x/s while waiting for
  // data; returning true closes the connection and returns — the reflector
  // shutdown path, bounded regardless of req.timeout_ms (which still caps
  // each individual socket wait).
  // `on_headers` (optional) fires once after the status line + headers
  // parse, before any body byte — callers branch on status without
  // waiting for the stream to end.
  Response request_stream(const Request& req,
                          const std::function<bool(const char*, size_t)>& on_data,
                          const std::function<bool()>& abort = nullptr,
                          const std::function<void(const Response&)>& on_headers = nullptr) const;

  // Default `traceparent` attached to every request without an explicit or
  // thread-scoped one (see set_thread_traceparent above). The daemon sets
  // the cycle's trace context here each cycle; "" clears. Const because
  // the shared k8s client is held by const& throughout the pipeline.
  void set_default_traceparent(std::string tp) const;

 private:
  Response request_once(const Request& req, const Url& url, bool allow_reuse) const;
  std::string resolved_traceparent(const Request& req) const;

  TlsMode tls_mode_;
  std::string ca_file_;
  mutable std::mutex pool_mutex_;
  mutable std::multimap<std::string, std::unique_ptr<detail::Conn>> pool_;
  mutable std::mutex traceparent_mutex_;
  mutable std::string default_traceparent_;
};

}  // namespace tpupruner::http
