// Differential reconcile engine: dirty-set invalidation + a memoized
// per-root decision cache (the ISSUE 10 perf tentpole).
//
// PRs 8–9 made warm-cycle *API traffic* O(churn): with a synced watch
// store, a quiesced 50k-pod cluster costs a handful of API calls per
// cycle. But the CPU spent per cycle still scaled with the candidate set
// — every cycle re-ran acquire → eligibility → owner walk → record
// construction → enqueue → consumer no-op over thousands of pods whose
// inputs had not changed since the previous cycle. This module makes the
// warm-cycle CPU itself O(churn): a pod whose decoded Prometheus samples
// are identical to last cycle's, whose Pod object and every owner object
// its walk consulted saw no watch event, and whose decision carries no
// armed timer, is CLEAN — and the per-root decision cache replays its
// DecisionRecords, scale target, ledger observation and flight-capsule
// evidence verbatim (re-stamped with the current cycle id/ts) instead of
// recomputing them.
//
// Invalidation fuses three sources into per-unit dirty marks (a unit is a
// resolved root, or a rootless candidate pod):
//   1. informer watch events — the dirty journal (informer.hpp) maps
//      ADDED/MODIFIED/DELETED object paths onto units via the pod→unit
//      map and the consulted-object reverse index; a relist (events may
//      have been missed) or an unsynced store is GLOBALLY dirty.
//   2. Prometheus sample diffing — metrics::sample_fingerprint over the
//      decoded samples; a new, absent, or changed sample dirties the pod
//      and its unit. Signal-guard verdict flips ride the same diff: a
//      vetoed pod leaves the post-veto candidate set (absent ⇒ dirty),
//      and a recovered one re-enters it (new ⇒ dirty).
//   3. config/clock edges — a config-fingerprint change clears the cache
//      outright; timer-armed units (BELOW_MIN_AGE pods waiting out the
//      lookback window) self-dirty at their deadline, never silently
//      staying stale.
//
// What is deliberately NEVER cached (correctness before hit ratio):
//   - units whose evidence came from a live GET fallback (store miss) or
//     whose cycle saw a fetch error / namespace veto: transients self-heal
//     by recomputation;
//   - units whose last actuation mutated the cluster (SCALED,
//     RIGHT_SIZED, SCALE_FAILED) or has not reported back yet;
//   - per-cycle cross-root verdicts (breaker deferrals, brownouts,
//     namespace vetoes, right-size plans): those gates re-run every cycle
//     over the MERGED target set (cached + recomputed), so the caps stay
//     per-cycle properties and a deferral is never served from cache.
//     The group all-idle gate caches only a VERIFIED all-idle verdict,
//     invalidated by any pod watch event in the group's namespace (see
//     Unit::GroupVerdict).
//
// The byte-identity contract: with --incremental on, audit JSONL,
// /debug/decisions, flight capsules, ledger integration and
// `analyze --replay` are byte-identical to --incremental off at every
// shard count (volatile clock/trace fields aside, plus the capsule's
// "incremental" provenance stamp, which records the dirty set and cache
// hits so a replay can re-derive the same view offline).
#pragma once

#include <cstdint>
#include <map>
#include <mutex>
#include <optional>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "tpupruner/audit.hpp"
#include "tpupruner/core.hpp"
#include "tpupruner/informer.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/ledger.hpp"

namespace tpupruner::incremental {

// Per-pod acquisition + walk evidence, cached so a clean unit can replay
// its flight-capsule contributions (recorder::record_pod /
// record_resolution) without touching the store.
struct PodEvidence {
  std::string key;  // "ns/name"
  bool has_pod = false;
  json::Value pod;  // as consulted (COW — shares nodes with the store)
  bool store_missed = false;
  bool walked = false;  // reached the owner walk (eligible or opted out)
  std::vector<std::string> chain;
  std::string root_kind, root_ns, root_name, identity, walk_error;
};

// One cacheable unit: the per-root (or per-rootless-pod) slice of a
// cycle's resolve output, plus everything needed to re-stamp its records
// and capsule evidence into a later cycle.
struct Unit {
  std::string key;  // root identity, or "pod:<ns>/<name>" for rootless pods
  // Contributing candidate pods with their sample fingerprints.
  std::vector<std::pair<std::string, uint64_t>> members;
  std::vector<PodEvidence> evidence;
  // Records terminal at the resolve stage (ineligible pods, failed walks).
  std::vector<audit::DecisionRecord> decided;
  // Per-pod records that resolved to this unit's root; their verdict is
  // joined against the per-cycle gate outcomes, exactly like freshly
  // resolved records.
  std::vector<audit::DecisionRecord> resolved;
  bool has_target = false;
  core::ScaleTarget target;  // object included (COW)
  bool vetoed_root = false;  // an annotated member pod vetoes this root
  std::vector<std::string> idle_pods;  // "ns/name" members that were idle+eligible
  bool has_obs = false;
  ledger::Observation obs;
  // Owner/root object paths this unit's walks consulted (404 misses
  // included) — the capsule object snapshot AND the watch-event reverse
  // index both come from this list.
  std::vector<std::pair<std::string, std::optional<json::Value>>> objects;
  // Invalidation state.
  bool never_cache = false;   // transients: recompute every cycle
  int64_t deadline_unix = 0;  // self-dirty at this unix time (0 = no timer)
  // Group-kind roots (JobSet/LWS): the all-idle gate's verdict depends on
  // pods OUTSIDE the candidate set, so it is cached only as IDLE (a
  // verified all-idle LIST) and invalidated by ANY pod watch event in the
  // root's namespace; Unknown (never verified, gate failed, or group not
  // fully idle) recomputes — and re-gates — every cycle.
  enum class GroupVerdict : uint8_t { NotGroup, Unknown, Idle };
  GroupVerdict group_verdict = GroupVerdict::NotGroup;
  std::string group_ns;  // root namespace (group units only)
  // Actuation state machine. Only a unit whose last enqueue came back as a
  // cacheable no-op (ALREADY_PAUSED / KIND_DISABLED) may skip the queue;
  // anything that mutated the cluster — or has not reported back yet —
  // recomputes next cycle.
  enum class Actuation : uint8_t { None, InFlight, Noop, Mutated };
  Actuation actuation = Actuation::None;
  uint64_t actuation_cycle = 0;
  audit::Reason noop_reason = audit::Reason::AlreadyPaused;
  std::string noop_action, noop_detail;
};

class Engine {
 public:
  // Enable/disable and (re)key the cache. A fingerprint change (any
  // decision-affecting flag) clears every cached unit — config edges are
  // invalidation source 3.
  void configure(bool enabled, uint64_t config_fingerprint);
  bool enabled() const;

  // One cycle's differential plan: which candidate samples must recompute
  // and which units serve from cache.
  struct Plan {
    bool active = false;  // engine enabled and the planner ran this cycle
    bool full = false;    // global dirty: every candidate recomputes
    std::vector<size_t> recompute;         // indices into the sample vector
    std::vector<std::string> dirty_units;  // unit keys being recomputed
    // Units served from cache this cycle. Pointers stay valid until
    // commit_cycle: only the producer thread inserts/erases units, and
    // consumers only touch actuation fields.
    std::map<std::string, const Unit*> cached;
    size_t hits = 0;        // cached pods (not units)
    size_t pods_total = 0;  // candidate pods this cycle
  };

  // Fuse the invalidation sources against the post-veto candidate set.
  // `store_trusted` must be false whenever the watch store cannot vouch
  // for object freshness (not fully synced) — the plan degrades to a full
  // recompute rather than serving possibly-stale decisions.
  Plan plan_cycle(const std::vector<core::PodMetricSample>& samples,
                  const informer::ClusterCache::DirtyDrain& drain, int64_t now_unix,
                  bool store_trusted);

  // Wave-2 invalidation: a recomputed pod's walk resolved to `unit_key`,
  // which the plan had marked clean (e.g. a new pod joined a cached
  // root). Drops the unit from the cache-served set and returns its
  // member pod keys so the caller re-walks them too. Empty when the unit
  // was not being served from cache.
  std::vector<std::string> invalidate_unit(Plan& plan, const std::string& unit_key);

  // Replace the dirty units (and drop vanished ones) with this cycle's
  // freshly built units; cached units carry forward untouched. Under
  // plan.full the whole cache is rebuilt.
  void commit_cycle(const Plan& plan, std::vector<Unit> fresh_units);

  // Producer: the group all-idle gate verified this unit's group as fully
  // idle this cycle — the verdict may serve from cache until a pod event
  // lands in the group's namespace. `fully_idle == false` resets to
  // Unknown (re-gate every cycle; a failed LIST must not stick).
  void record_group_verdict(const std::string& unit_key, bool fully_idle);
  // Producer: a unit's target entered the scale queue this cycle — its
  // outcome is unknown until the consumer reports back, so it recomputes
  // next cycle unless record_actuation_outcome lands a cacheable no-op.
  void mark_enqueued(uint64_t cycle, const std::string& unit_key);
  // Consumer: the actuation outcome for a unit enqueued this cycle.
  void record_actuation_outcome(uint64_t cycle, const std::string& unit_key,
                                audit::Reason reason, const std::string& action,
                                const std::string& detail);

  // The capsule provenance stamp: {"enabled", "full", "pods",
  // "cache_hits", "hit_ratio", "dirty_units"} — how this cycle's view was
  // assembled, so an offline replay (which always recomputes in full) can
  // attribute any drift to a specific dirty set.
  json::Value provenance_json(const Plan& plan) const;

  // Timer-armed units: (unit key, deadline_unix) for every cached unit
  // whose verdict flips by clock alone (BELOW_MIN_AGE pods leaving the
  // lookback window). The event dispatcher (--reconcile event) arms these
  // in its timer wheel so the flip re-evaluates at the deadline instead of
  // waiting out the anti-entropy interval; the cycle engine never calls
  // this (unit_dirty_locked self-dirties on the same clock). Sorted by
  // key for deterministic scheduling order.
  std::vector<std::pair<std::string, int64_t>> pending_deadlines() const;

  size_t unit_count() const;
  void reset();

 private:
  bool unit_dirty_locked(const Unit& u, int64_t now_unix,
                         const std::unordered_map<std::string, size_t>& present) const;
  void index_unit_locked(const Unit& u);
  void unindex_unit_locked(const Unit& u);

  mutable std::mutex mutex_;
  bool enabled_ = false;
  uint64_t config_fp_ = 0;
  std::unordered_map<std::string, Unit> units_;
  std::unordered_map<std::string, std::string> pod_unit_;  // pod key → unit key
  std::unordered_map<std::string, uint64_t> pod_fp_;       // pod key → sample fp
  // Consulted object path → unit keys (watch-event reverse index).
  std::unordered_map<std::string, std::set<std::string>> path_units_;
  // Namespace → group-unit keys (pod-event invalidation of gate verdicts).
  std::unordered_map<std::string, std::set<std::string>> ns_groups_;
};

// Process-wide engine (daemon lifetime; reset_for_test between tests).
Engine& engine();

// "ns/name" for an informer pods path ("/api/v1/namespaces/<ns>/pods/<n>"),
// empty for any other resource path.
std::string pod_key_of_path(const std::string& path);

// Per-cycle gauges for /metrics (absent until the first incremental cycle
// publishes, like the signal families):
//   tpu_pruner_incremental_cache_hit_ratio   gauge (cached pods / candidates)
//   tpu_pruner_incremental_cached_pods       gauge
//   tpu_pruner_incremental_dirty_pods        gauge
//   tpu_pruner_incremental_full_recomputes_total  counter
//   tpu_pruner_incremental_journal_depth     gauge (dirty paths drained at plan)
//   tpu_pruner_incremental_journal_overflows_total  counter (cap hits)
//   tpu_pruner_incremental_cache_units       gauge (bounded by
//                                            TPU_PRUNER_INCREMENTAL_CACHE_CAP)
//   tpu_pruner_incremental_cache_evictions_total    counter
void publish_metrics(const Engine::Plan& plan);
std::string render_metrics(bool openmetrics);
std::vector<std::string> metric_families();

void reset_for_test();

}  // namespace tpupruner::incremental
