// Daemon runtime: producer/consumer with a bounded queue.
//
// Reference analog: main() orchestration (gpu-pruner/src/main.rs:273-375):
//   - query task (producer): optional interval tick → rebuild Prometheus
//     client (fresh token each cycle) → run the query pipeline → reset or
//     bump the consecutive-failure budget, exiting after >5 failures;
//   - scale-down task (consumer): enabled-kind filter → scale, counting
//     successes/failures;
//   - bounded channel of 100 between them.
// Tokio tasks become two std::threads; the channel becomes a
// condvar-bounded queue. The daemon stays stateless across cycles
// (SURVEY.md §5 checkpoint/resume: idempotency substitutes for resume).
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <string>

#include "tpupruner/cli.hpp"
#include "tpupruner/informer.hpp"
#include "tpupruner/k8s.hpp"

namespace tpupruner::daemon {

struct CycleStats {
  size_t num_series = 0;       // raw series from the query
  size_t num_pods = 0;         // unique (pod, ns)
  size_t shutdown_events = 0;  // deduped root objects surviving gates
  uint64_t api_calls = 0;      // K8s API requests issued during the cycle
};

// Consumer instruction attached to each enqueued target. target_replicas
// 0 = the classic scale-to-zero pause; > 0 = a right-size patch
// (--right-size on, gym.hpp) to that replica count, crediting
// freed_chips to the ledger as partial reclaim and landing a RIGHT_SIZED
// DecisionRecord with `detail`.
struct ScalePlan {
  int64_t target_replicas = 0;
  int64_t freed_chips = 0;
  std::string detail;
};

// One evaluation cycle (reference: run_query_and_scale, main.rs:390-570).
// `enqueue` receives each surviving target plus the id of the cycle that
// produced it — under --overlap the producer may already be preparing the
// NEXT cycle while this one's targets enqueue, so the consumer must never
// infer the cycle from a global counter (enabled-kind filtering stays
// consumer-side, as in the reference; `enabled` is used only so the
// --max-scale-per-cycle budget counts actionable targets, not ones the
// consumer will skip). Throws on query failure (feeds the failure budget).
// `watch_cache` (nullable): the informer store pod acquisition and the
// owner walk read through (--watch-cache=on); unsynced resources degrade
// to the watch-free GET/LIST path per lookup. The multi-host group gate
// deliberately KEEPS its fresh LIST either way: it is the last check
// before suspending every host of a slice, and a store lookup would
// re-widen the new-pod race the fresh LIST exists to close.
// `evidence_query` ("" with --signal-guard off) is the signal-quality
// watchdog's second per-cycle query (query::build_evidence_query): its
// assessment vetoes unhealthy-signal candidates and can brown out the
// whole cycle's scale-downs (signal.hpp).
CycleStats run_cycle(const cli::Cli& args, const std::string& query, const k8s::Client& kube,
                     core::ResourceSet enabled,
                     const std::function<void(core::ScaleTarget, ScalePlan, uint64_t)>& enqueue,
                     const informer::ClusterCache* watch_cache = nullptr,
                     const std::string& evidence_query = "");

// Full daemon: spawns the two threads, joins them, returns the process
// exit code (0 normal, 1 after failure-budget exhaustion).
int run(const cli::Cli& args);

// Failure budget: consecutive failures tolerated before exit (>5,
// main.rs:317-320).
constexpr int kMaxConsecutiveFailures = 5;
constexpr size_t kQueueCapacity = 100;

}  // namespace tpupruner::daemon
