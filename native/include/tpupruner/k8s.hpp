// Minimal Kubernetes REST client.
//
// Reference analog: the kube-rs Client (gpu-pruner/src/main.rs:333, 411) —
// typed-binding-free: the reference only ever GETs single objects, LISTs
// pods by label, PATCHes, and POSTs Events (SURVEY.md §7 "hard parts" #2),
// and CR objects are handled as JSON (§2 #10). One deliberate extension
// beyond the reference: a streaming `watch()` verb, the transport under
// the informer-style cluster cache (informer.hpp / --watch-cache=on).
// Config inference order:
//   1. env: KUBE_API_URL (+ KUBE_TOKEN / KUBE_TOKEN_FILE / KUBE_CA_FILE /
//      KUBE_TLS_SKIP) — also the hermetic-test seam;
//   2. in-cluster: KUBERNETES_SERVICE_HOST/PORT + mounted SA token and CA;
//   3. kubeconfig scan: current cluster server + user token (token auth
//      only; exec/client-cert auth is out of scope and errors clearly).
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <optional>
#include <stdexcept>
#include <string>

#include "tpupruner/core.hpp"
#include "tpupruner/h2.hpp"
#include "tpupruner/http.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/proto.hpp"

namespace tpupruner::k8s {

// Non-2xx API-server response. Subclasses runtime_error so existing broad
// handlers keep working; `status` lets CAS callers (leader election) tell a
// genuine 409 conflict from a transient transport/server failure.
struct ApiError : std::runtime_error {
  int status;
  ApiError(int status_code, const std::string& what)
      : std::runtime_error(what), status(status_code) {}
};

struct Config {
  std::string api_url;   // e.g. https://10.0.0.1:443
  std::string token;     // bearer; empty for anonymous (tests)
  std::string ca_file;   // PEM bundle for the API server
  bool tls_skip = false;
  int timeout_ms = 15000;

  // Throws std::runtime_error with the probed locations when nothing works.
  static Config infer();
};

class Client {
 public:
  explicit Client(Config config);

  const Config& config() const { return config_; }

  // retry_throttle on the verbs below: honor 429 + Retry-After with a
  // bounded wait (API Priority & Fairness). Leader-election traffic
  // passes false — blocking a renew attempt for seconds inside the
  // elector would widen the dual-leadership window past the
  // lease-duration bound its grace logic promises; a 429 there must
  // surface immediately and ride that grace window instead.

  // GET that treats 404 as nullopt (reference get_opt, main.rs:453).
  std::optional<json::Value> get_opt(const std::string& path,
                                     bool retry_throttle = true) const;
  // GET that throws on any non-2xx.
  json::Value get(const std::string& path) const;
  // LIST with an urlencoded labelSelector; returns the List object.
  // `limit` > 0 requests server-side pagination (`limit=N` per page) and
  // the client transparently follows `metadata.continue` until the
  // collection is complete — the informer's initial LIST passes a page
  // size so a 100k-object collection never materializes as one response.
  json::Value list(const std::string& path, const std::string& label_selector,
                   int64_t limit = 0) const;
  // Paginated LIST delivering each page as an arena Doc (the zero-copy
  // informer path): `on_page` receives every page in order; the caller
  // extracts items/continue-free metadata itself. Returns the LAST page's
  // metadata.resourceVersion — the newest snapshot version a watch may
  // legally resume from. Same limit/continue/429 semantics as list().
  std::string list_pages(const std::string& path, const std::string& label_selector,
                         int64_t limit,
                         const std::function<void(const json::DocPtr&)>& on_page) const;
  // application/merge-patch+json PATCH (reference Patch::Merge).
  json::Value patch_merge(const std::string& path, const json::Value& body,
                          bool retry_throttle = true) const;
  json::Value post(const std::string& path, const json::Value& body,
                   bool retry_throttle = true) const;

  // ── watch (the informer transport) ──
  struct WatchOptions {
    // Start point: events strictly after this version stream; empty asks
    // the server for "current state onward" (informers always pass the
    // version of their LIST snapshot).
    std::string resource_version;
    bool bookmarks = true;       // allowWatchBookmarks=true
    int read_timeout_ms = 90000;  // per-socket-wait cap, not a stream cap
    std::function<bool()> abort;  // polled ~4x/s while idle; true = hang up
  };
  // Long-lived streaming GET `path?watch=true&...`. Decodes the
  // newline-delimited event frames and hands each {type, object} JSON to
  // on_event; returning false ends the watch cleanly. Returns when the
  // server closes the stream (routine — re-watch from the last seen
  // resourceVersion). Throws ApiError on a non-200 response — 410 Gone is
  // the relist signal — and runtime_error on transport failures.
  void watch(const std::string& path, const WatchOptions& opts,
             const std::function<bool(const json::Value&)>& on_event) const;
  // Zero-copy sibling: each newline-delimited event frame is parsed as its
  // own arena Doc (strings view into the frame buffer) instead of a Value
  // tree. Framing, error, and abort semantics identical to watch().
  void watch_doc(const std::string& path, const WatchOptions& opts,
                 const std::function<bool(const json::DocPtr&)>& on_event) const;

  // ── binary wire path (--wire proto|auto; proto.hpp) ──
  // One LIST page in whichever representation the server negotiated:
  // exactly one of pb (application/vnd.kubernetes.protobuf) or doc
  // (JSON, served after a refusal) is set.
  struct WirePage {
    json::DocPtr doc;
    proto::ListPagePtr pb;
  };
  // list_pages with content negotiation: requests
  // `application/vnd.kubernetes.protobuf, application/json` and decodes
  // whichever comes back, counting negotiation fallbacks. Pagination,
  // 429 and error semantics identical to list_pages(); returns the last
  // page's resourceVersion.
  std::string list_pages_wire(const std::string& path, const std::string& label_selector,
                              int64_t limit,
                              const std::function<void(const WirePage&)>& on_page) const;

  // One watch event in whichever representation the stream negotiated.
  struct WireWatchEvent {
    json::DocPtr doc;
    proto::WatchEventPtr pb;
  };
  // watch with content negotiation: requests the `;stream=watch` protobuf
  // variant; a protobuf stream arrives as 4-byte big-endian
  // length-delimited runtime.Unknown(WatchEvent) frames (k8s's
  // LengthDelimitedFramer), a JSON stream as the usual newline-delimited
  // events. Error/abort semantics identical to watch().
  void watch_wire(const std::string& path, const WatchOptions& opts,
                  const std::function<bool(const WireWatchEvent&)>& on_event) const;

  // Transport protocol negotiated for the API server endpoint
  // ("h2" | "http1" | "unknown") — surfaced in /debug and logs.
  std::string transport_protocol() const { return http_.protocol_for(config_.api_url); }

  // Monotonic count of API requests issued through this client (watch
  // connections count once). Feeds the per-cycle call accounting the
  // daemon logs and the bench asserts on.
  uint64_t api_calls() const { return api_calls_.load(); }

  // W3C trace-context propagation: every subsequent request carries this
  // `traceparent` (consumer threads may override per-thread via
  // http::set_thread_traceparent). The daemon stamps the cycle span's
  // context here at cycle start so apiserver audit logs join the OTLP
  // trace. "" clears.
  void set_traceparent(const std::string& tp) const { http_.set_default_traceparent(tp); }

  // ── path builders ──
  static std::string pod_path(const std::string& ns, const std::string& name);
  static std::string pods_path(const std::string& ns);
  static std::string events_path(const std::string& ns);
  // Object path for a scalable kind (CRs included).
  static std::string object_path(core::Kind kind, const std::string& ns,
                                 const std::string& name);
  // Collection path for a scalable kind (object_path minus the name) —
  // the LIST endpoint used by batched owner-chain prefetch.
  static std::string collection_path(core::Kind kind, const std::string& ns);
  // batch/v1 Job paths (Jobs are walked through, never scaled, so Job is
  // not a core::Kind — see walker.cpp Pod→Job→JobSet chain).
  static std::string jobs_path(const std::string& ns);
  static std::string job_path(const std::string& ns, const std::string& name);
  // /scale subresource path (Deployment/ReplicaSet/StatefulSet).
  static std::string scale_path(core::Kind kind, const std::string& ns,
                                const std::string& name);

 private:
  json::Value request_json(const std::string& method, const std::string& path,
                           const std::string& body, const std::string& content_type,
                           int* status_out, bool retry_throttle = true,
                           json::DocPtr* doc_out = nullptr) const;
  // Issue one request with the 429/Retry-After handling every verb
  // shares; the response comes back raw (any content type).
  http::Response issue(http::Request& req, const std::string& method,
                       const std::string& path, bool retry_throttle) const;
  void watch_impl(const std::string& path, const WatchOptions& opts,
                  const std::function<bool(std::string_view)>& on_line) const;

  Config config_;
  // The shared multiplexing transport (ALPN h2 with transparent HTTP/1.1
  // fallback): every verb of this client — LIST pages, watch streams,
  // owner GETs, scale PATCHes — rides ONE connection per endpoint as
  // concurrent streams when the server speaks h2.
  h2::Transport http_;
  mutable std::atomic<uint64_t> api_calls_{0};
};

}  // namespace tpupruner::k8s
