// tpu-pruner core domain model.
//
// Reference analog: gpu-pruner/src/lib.rs:36-135 (ScaleKind, ResourceKind,
// get_enabled_resources), lib.rs:188-202 & 287-335 (Meta), lib.rs:389-427
// (event generation), and the eligibility gates inlined in
// gpu-pruner/src/main.rs:452-510. Pure, cluster-free, fully unit-testable
// (reference tests: lib.rs:578-998).
//
// TPU-first deltas vs the reference:
// - two extra scalable kinds for GKE multi-host TPU topologies: JobSet
//   (jobset.x-k8s.io, flag 'j') for training slices and LeaderWorkerSet
//   (leaderworkerset.x-k8s.io, flag 'l') for multi-host serving groups.
// - involvedObject apiVersions are the full group/version strings (the
//   reference emits bare "v1"/"v1beta1" for the CR kinds, lib.rs:313-314).
// - event text is device-aware ("was not using TPU" / "... GPU").
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <string_view>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::core {

// ── scalable kinds ────────────────────────────────────────────────────────

enum class Kind : uint8_t {
  Deployment,
  ReplicaSet,
  StatefulSet,
  InferenceService,
  Notebook,
  JobSet,
  LeaderWorkerSet,
};

constexpr int kNumKinds = 7;

// Bitflag set over Kind (reference: bitflags ResourceKind, lib.rs:96-105).
using ResourceSet = uint8_t;
constexpr ResourceSet flag(Kind k) { return static_cast<ResourceSet>(1u << static_cast<int>(k)); }
constexpr ResourceSet kAllResources = (1u << kNumKinds) - 1;

// Parse "drsinjl" flag chars; unknown characters are silently ignored
// (reference: get_enabled_resources, lib.rs:116-129).
ResourceSet parse_enabled_resources(std::string_view flags);

std::string_view kind_name(Kind k);         // "Deployment", ..., "JobSet"
std::optional<Kind> kind_from_name(std::string_view name);
std::string_view api_version(Kind k);       // "apps/v1", "kubeflow.org/v1", ...
std::string_view api_group(Kind k);         // "" for core/apps..., group for CRs
std::string_view plural(Kind k);            // REST path segment, e.g. "jobsets"

// ── scale targets ─────────────────────────────────────────────────────────

// A root scalable object selected for scale-down. Holds the fetched object
// as semi-structured JSON rather than typed CRD bindings (SURVEY.md §2 #10:
// "do not hand-port 31k lines").
struct ScaleTarget {
  Kind kind;
  json::Value object;  // at minimum {"metadata": {...}}

  std::string name() const;
  std::optional<std::string> ns() const;
  std::optional<std::string> uid() const;
  std::optional<std::string> resource_version() const;

  // Identity for dedup: (kind, uid) when uid is present — the reference's
  // uid-based Eq/Hash (lib.rs:45-82) — falling back to (kind, ns, name) for
  // objects without uid so distinct uid-less objects stay distinct.
  std::string identity() const;
  bool operator==(const ScaleTarget& other) const { return identity() == other.identity(); }
};

// Drop duplicate targets, preserving first-seen order (reference:
// HashSet<ScaleKind> collect at main.rs:534).
std::vector<ScaleTarget> dedup_targets(std::vector<ScaleTarget> targets);

// ── event generation ──────────────────────────────────────────────────────

struct EventOptions {
  std::string device = "tpu";               // "tpu" | "gpu" — reason text
  std::string reporting_instance;           // default: $POD_NAME or "tpu-pruner"
  std::optional<int64_t> now_unix;          // test injection; default wall clock
};

// Build the v1 Event posted before any scale action (reference:
// generate_scale_event, lib.rs:389-427). Name "tpupruner-<32 hex>",
// action "scale_down", type "Normal", reason
// "Pod <ns>::<name> was not using TPU|GPU".
json::Value generate_scale_event(const ScaleTarget& target, const EventOptions& opts = {});

// ── eligibility policy ────────────────────────────────────────────────────

enum class Eligibility : uint8_t {
  Eligible,
  Pending,        // pod phase == "Pending" (main.rs:473-483)
  NoCreationTs,   // missing creationTimestamp (main.rs:485-492)
  TooYoung,       // created within lookback+grace (main.rs:494-510)
  BadTimestamp,   // creationTimestamp unparseable
  OptedOut,       // annotated tpu-pruner.dev/skip=true (no reference analog)
};

std::string_view eligibility_name(Eligibility e);

// Operator opt-out valve (beyond reference parity). On a ROOT object:
// authoritative, the target is never pruned. On a POD: effective whenever
// the pod is in the idle candidate set — it vetoes the pod's resolved
// root for EVERY kind (a sibling pod of the same Deployment must not
// scale the shared root away) and is excluded from the idle set so a
// group kind (JobSet/LWS) containing it fails the all-idle slice gate;
// an unresolvable root fails closed on the namespace for the cycle. A
// BUSY annotated pod is absent from the idle query results, so its
// annotation can't be seen that cycle — root annotation is the standing
// guarantee.
constexpr std::string_view kSkipAnnotation = "tpu-pruner.dev/skip";

bool is_opted_out(const json::Value& object);

// Apply the per-pod gates from main.rs:452-510 to a Pod object.
// `lookback_secs` = duration*60 + grace_period (main.rs:413-414).
Eligibility check_eligibility(const json::Value& pod, int64_t now_unix, int64_t lookback_secs);

// Accelerator chips the pod reserves: per container max(requests, limits)
// of google.com/tpu (device=tpu) or nvidia.com/gpu (device=gpu), summed.
// 0 for pods with no accelerator resources — the workload-ledger's
// per-root chip accounting input.
int64_t pod_chip_count(const json::Value& pod, std::string_view device = "tpu");

// ── metric samples ────────────────────────────────────────────────────────

// One decoded Prometheus series (reference: PodMetricData, lib.rs:136-145).
// `accelerator` generalizes the reference's gpu_model: DCGM `modelName` for
// GPUs; the GKE TPU accelerator type (e.g. "tpu-v5-lite-podslice") for TPUs.
struct PodMetricSample {
  std::string name;
  std::string ns;
  std::string container;
  std::string node_type;
  std::string accelerator;
  double value = 0.0;
};

}  // namespace tpupruner::core
