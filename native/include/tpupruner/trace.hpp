// Action provenance traces (--trace on): per-evaluation causal span trees
// plus a detect→action SLO engine.
//
// PR 16 collapsed detect→scaledown latency to tens of milliseconds, but
// the only view into that path was aggregate histograms — when one action
// takes 2 s instead of 100 ms nothing says *which phase, shard, retry, or
// debounce extension* ate the budget. This module is the measurement
// substrate: every evaluation builds ONE span tree rooted at trigger
// ingress (watch-event arrival / probe sample flip / timer expiry /
// anti-entropy tick), with child spans for debounce wait, query, decode,
// signal, per-shard resolve, merge, cross-root gates, and one span per
// actuation patch carrying its retry/backoff ticks as span events.
//
// Completed traces land in a bounded in-memory ring served at
// /debug/traces (index + SLO summary) and /debug/traces/<id> (full tree);
// when the OTLP exporter is live every sealed tree is also converted to
// otlp::FinishedSpan records (events included) and rides the existing
// TraceService export. The trace id doubles as the W3C traceparent /
// histogram-exemplar id, so an exemplar on detect_to_action_seconds now
// resolves to a real retained trace.
//
// SLO engine: --slo-detect-to-action-ms N judges every actuation's
// root-relative latency, feeds good/bad budget counters and a burn-ratio
// gauge, and PINS every breaching trace past normal ring eviction so the
// evidence for a 3am "why was this slow" survives the storm that caused
// it. The hub rolls per-member burn + worst traces into /debug/fleet/slo.
//
// Parity contract: with --trace off every entry point is a no-op and the
// flag is excluded from the audit/capsule config fingerprint — audit
// JSONL, capsules, ledger and `analyze --replay` are byte-identical with
// tracing on and off (pinned by tests at shards 1 and 8 × both reconcile
// modes). The capsule gains a normalized "trace" stamp only when tracing
// is on; byte-identity comparisons normalize that key away like
// "incremental" and "reconcile".
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::trace {

// Timestamped point event inside a span — mirrors otlp::SpanEvent without
// coupling the public header to the exporter's internals.
struct Event {
  int64_t time_nanos = 0;
  std::string name;
  std::vector<std::pair<std::string, std::string>> str_attrs;
  std::vector<std::pair<std::string, int64_t>> int_attrs;
};

// One child span in an evaluation's tree. span_id is assigned by the
// engine when the span attaches; parent defaults to the trace root.
struct Span {
  std::string name;
  int64_t start_nanos = 0, end_nanos = 0;
  std::vector<std::pair<std::string, std::string>> str_attrs;
  std::vector<std::pair<std::string, int64_t>> int_attrs;
  std::vector<Event> events;
  bool error = false;
  std::string error_message;
};

// ── lifecycle / configuration ──
// `on` gates every hook below (all no-ops while off, zero contention on
// the hot path beyond one relaxed atomic load). `slo_ms` > 0 arms the
// detect→action SLO engine; 0 disables it.
void configure(bool on, int64_t slo_ms);
bool enabled();
int64_t slo_ms();

// ── per-evaluation capture (keyed by audit cycle id) ──
// Open the evaluation's trace. `trigger` names the ingress (dirty /
// probe / timer / anti_entropy / cycle); the root span is backdated by
// `ingress_lag_ms` so it starts at trigger arrival, not evaluation start.
// `hint_trace_id` (32 hex) reuses the OTLP cycle span's trace id when the
// exporter is live — exemplars, headers, and the retained tree then all
// share one id; "" mints a fresh id. Returns the trace id ("" while off).
std::string begin(uint64_t cycle, const std::string& trigger, int64_t ingress_lag_ms,
                  const std::string& hint_trace_id);

// The trace id / W3C traceparent of an open (or just-sealed) evaluation;
// "" when unknown or off. The traceparent carries the ROOT span id, so
// fake_prom/fake_k8s header assertions join on the same id the exemplars
// carry.
std::string trace_id_of(uint64_t cycle);
std::string traceparent(uint64_t cycle);

// Attach a finished child span verbatim (shard resolves, debounce wait).
void add_span(uint64_t cycle, Span span);
// Convenience for the observe_phase call sites: a span that ENDED now and
// lasted `seconds`, parented to the root.
void add_phase_span(uint64_t cycle, const std::string& name, double seconds);

// ── actuation spans (consumer threads) ──
// An actuation span is assembled in a thread-local between begin and end
// so retry hooks (backoff::record_retry → thread_retry_event) append
// LOCK-FREE from anywhere inside the patch attempt; the span only touches
// the engine mutex once, at actuation_end.
void actuation_begin(uint64_t cycle, const std::string& identity);
// Appends a retry/backoff event to the thread's open actuation span.
// Safe to call unconditionally — a no-op when no actuation is open (e.g.
// informer relist retries on the reflector thread).
void thread_retry_event(const std::string& endpoint, const std::string& cause,
                        double backoff_seconds);
// Close the span: `outcome` ∈ {scaled, right_sized, noop, error, ...};
// `error` marks span status. Decrements the pending-actuation count and
// seals the trace when the last one lands. Also feeds the SLO engine with
// the actuation's root-relative latency.
void actuation_end(uint64_t cycle, const std::string& outcome, bool error,
                   const std::string& error_message);

// Arm the trace for `expected` actuations; 0 seals immediately with zero
// actuation spans (dry-run, no-candidate, SIGNAL_STALE / BROWNOUT veto
// evaluations). Actuations that ended BEFORE arm (the incremental fast
// path enqueues first) are credited at arm time, like recorder::arm.
void arm(uint64_t cycle, size_t expected);

// Normalized capsule stamp for the open trace ({trace_id, trigger,
// root_start_nanos, spans-so-far}) — recorded via recorder::record_trace
// at arm time so `analyze --trace <flight-dir>` renders waterfalls
// offline. Null while off/unknown.
json::Value capsule_stamp(uint64_t cycle);

// ── serving ──
// /debug/traces body: {"traces": [recent, newest first, capped], "slo":
// slo_summary(), "retained": N, "pinned": N, "enabled": true}.
json::Value index_json();
// Full tree by trace id ("" when not retained).
std::string trace_json(const std::string& id);
// {"enabled", "slo_ms", "good", "bad", "breaches", "burn_ratio",
// "worst": [{trace_id, cycle, trigger, root_ms}...]} — embedded in the
// index doc; the hub folds it into /debug/fleet/slo.
json::Value slo_summary();

// ── metrics ──
// Canonical native family list (tpu_pruner_trace_* / tpu_pruner_slo_*),
// exported through the C API so tests/test_docs_drift.py holds
// docs/OPERATIONS.md to the real set.
const std::vector<std::string>& metric_families();
// Prometheus text exposition; appended to /metrics by the daemon's
// extra-metrics provider ("" while off, so the scrape is byte-identical).
std::string render_metrics(bool openmetrics);

void reset_for_test();

}  // namespace tpupruner::trace
