// Sharded reconcile-engine primitives: stable root-keyed shard placement
// plus a persistent worker pool.
//
// The reconcile cycle used to be a serial phase chain whose resolve stage
// fanned work out with per-call thread spawns (util::fan_out) and folded
// every result under one mutex — at the 100k-pod bench scale the fold
// mutex and thread churn become the ceiling, and the nondeterministic
// fold order made byte-level audit/capsule comparisons across
// configurations impossible. This module provides the two pieces the
// sharded engine in daemon.cpp builds on:
//
//   - stable_hash / shard_of: placement keyed by the RESOLVED ROOT's
//     identity, so every pod of one root folds on one shard and per-root
//     state (group gates, right-size plans, ledger accounts) stays
//     single-writer per shard. FNV-1a, not std::hash: placement must be
//     identical across runs, builds and platforms — capsule replay and
//     the --shards 1 vs N byte-identity contract depend on it.
//
//   - Pool: a persistent worker pool with fan_out semantics. The daemon
//     runs one pool for the life of the process (sized by --shards)
//     instead of spawning threads per phase per cycle; the policy gym's
//     capsule replay loop reuses the same pool.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <string_view>
#include <thread>
#include <vector>

namespace tpupruner::shard {

// FNV-1a 64-bit over the key bytes. Deliberately not std::hash (its value
// is implementation-defined and may differ across libstdc++ versions) —
// shard placement is part of the engine's determinism contract.
uint64_t stable_hash(std::string_view key);

// Shard index for a key. num_shards == 0 is treated as 1 (everything on
// shard 0). Same key + same shard count → same shard, always.
size_t shard_of(std::string_view key, size_t num_shards);

// --shards resolution: values >= 1 are clamped to [1, kMaxShards]; 0
// ("auto", the default) resolves to hardware_concurrency clamped to
// [1, kAutoMaxShards] — past ~8 shards the per-cycle fold is merge-bound
// on the clusters the bench models, so auto stays conservative and the
// flag allows explicit wider counts.
constexpr size_t kMaxShards = 64;
constexpr size_t kAutoMaxShards = 8;
size_t resolve_shard_count(int64_t flag);

// Persistent worker pool: run(n, fn) has util::fan_out semantics (fn(i)
// for i in [0, n), all workers pulling off a shared counter, blocking
// until every index completed) but reuses the same threads across calls.
// The first exception thrown by fn is captured and rethrown from run()
// (fan_out would std::terminate). run() is not reentrant — a task must
// not call run() on its own pool.
class Pool {
 public:
  explicit Pool(size_t workers);
  ~Pool();
  Pool(const Pool&) = delete;
  Pool& operator=(const Pool&) = delete;

  size_t size() const { return threads_.size(); }
  void run(size_t n_tasks, const std::function<void(size_t)>& fn);

 private:
  void worker_loop();

  std::mutex mutex_;
  std::condition_variable work_cv_;   // workers wait for a new generation
  std::condition_variable done_cv_;   // run() waits for completion
  uint64_t generation_ = 0;           // bumped per run() call
  size_t n_tasks_ = 0;
  size_t next_ = 0;                   // next index to hand out
  size_t active_ = 0;                 // workers still inside fn
  const std::function<void(size_t)>* fn_ = nullptr;
  std::exception_ptr first_error_;
  bool stop_ = false;
  std::vector<std::thread> threads_;
};

// Process-wide pool for the reconcile engine. The first caller sizes it;
// a later call with a DIFFERENT size tears the old pool down and builds a
// fresh one (the daemon uses one constant size for the process lifetime —
// resizing exists for tests and the gym, which may run with their own
// shard counts).
Pool& pool(size_t workers);

}  // namespace tpupruner::shard
