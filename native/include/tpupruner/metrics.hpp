// Metric-sample decoding: Prometheus instant-query response → pod samples.
//
// Reference analog: PodMetricData + TryFrom<&InstantVector>
// (gpu-pruner/src/lib.rs:136-187) and the per-cycle series dedup
// (main.rs:416-437). Pure JSON-in/structs-out; the HTTP client lives in
// http.hpp / prom.hpp.
#pragma once

#include <string>
#include <vector>

#include "tpupruner/core.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/proto.hpp"

namespace tpupruner::metrics {

struct DecodeResult {
  std::vector<core::PodMetricSample> samples;  // unique by (pod, namespace)
  size_t num_series = 0;                       // raw series count pre-dedup
  std::vector<std::string> errors;             // per-series decode failures
};

// Decode {"status":"success","data":{"resultType":"vector","result":[...]}}.
// Tolerates both native and exported_* label names (lib.rs:161-175).
// device == "gpu" requires the DCGM modelName label (hard error per series,
// lib.rs:180-183); device == "tpu" reads accelerator_type/node_type labels
// with a `model` fallback (the gke-system accelerator series' metric
// label) before "unknown" (GKE label enrichment may be disabled).
// schema == "gke-system" additionally tolerates a missing container label
// ("unknown"): rows are node-keyed there and the container name only
// arrives via the KSM join, which a kube_pod_info-style --join-metric
// override doesn't carry. Under "gmp" a missing container stays a hard
// per-series error, as in the reference.
// Throws std::runtime_error when the response is not a success/vector
// payload (the reference panics via into_vector().expect, main.rs:405-409 —
// here it is a typed error feeding the daemon's failure budget).
DecodeResult decode_instant_vector(const json::Value& response, const std::string& device,
                                   const std::string& schema = "gmp");

// Zero-copy sibling walking the arena Doc directly — no Value tree is ever
// built for the (potentially multi-megabyte) matrix. Samples, dedup order,
// per-series error strings, and throw behavior are IDENTICAL to the Value
// overload on the same bytes (pinned by the decode-parity corpus tests;
// flight-recorder replay re-decodes capsule bytes through the Value path).
DecodeResult decode_instant_vector(const json::Doc& response, const std::string& device,
                                   const std::string& schema = "gmp");

// Binary-wire sibling (--wire proto): the fused protobuf decode already
// produced per-series label lists and exact value text (proto.hpp); this
// overload applies the SAME label-fallback / dedup / per-series-error
// semantics to them. Samples, order, error strings, and throw behavior
// are identical to the JSON overloads on the equivalent body — pinned by
// the wire parity corpus.
DecodeResult decode_instant_vector(const proto::PromVector& response, const std::string& device,
                                   const std::string& schema = "gmp");

// Sample-diff fingerprint (the incremental reconcile engine's
// invalidation source 2): FNV-1a over every decoded field of the sample —
// the entirety of what one candidate feeds into the decision pipeline, so
// equal fingerprints mean the pod's Prometheus evidence cannot change the
// cycle's output. Byte-equal raw series always decode to equal samples;
// decode-equal is strictly tighter (label reordering or whitespace churn
// in the response body never false-dirties a pod). Identical across the
// Value and Doc decode paths by construction: both produce the same
// PodMetricSample.
uint64_t sample_fingerprint(const core::PodMetricSample& sample);

}  // namespace tpupruner::metrics
