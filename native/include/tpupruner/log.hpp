// Structured logging + operational counters.
//
// Reference analog: tracing-subscriber with three formats (json / default /
// pretty, main.rs:128-134, 176-192), EnvFilter level directives via RUST_LOG
// (main.rs:159-173 — e.g. `gpu_pruner=debug,hyper=error` to silence wire
// noise), and tracing-field counters that the OTEL layer turns into metrics
// (main.rs:300-321, 349-365). Here: same three formats on stderr, the same
// directive grammar via TPU_PRUNER_LOG (or RUST_LOG for drop-in
// familiarity) — `debug`, `walker=debug,http=error`, `info,http=trace`,
// `off` — and a process-wide counter registry with the reference's six
// counter names, exposed over the optional /metrics endpoint instead of
// OTLP push.
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <string_view>
#include <vector>

namespace tpupruner::log {

// Off is a threshold-only sentinel (nothing logs AT Off).
enum class Level : uint8_t { Trace = 0, Debug, Info, Warn, Error, Off };
enum class Format : uint8_t { Default, Json, Pretty };

void init(Format format);
// Global default level after directive parsing (bare tokens in the spec).
Level threshold();
// Effective level for one module: exact `module=level` directive, else the
// global default. Modules are flat names (walker, http, daemon, leader,
// otlp, auth, actuate, metrics, query), not Rust-style paths.
Level threshold_for(std::string_view module);

void write(Level level, const std::string& msg);
void write(Level level, std::string_view module, const std::string& msg);

inline void trace(const std::string& msg) { write(Level::Trace, msg); }
inline void debug(const std::string& msg) { write(Level::Debug, msg); }
inline void info(const std::string& msg) { write(Level::Info, msg); }
inline void warn(const std::string& msg) { write(Level::Warn, msg); }
inline void error(const std::string& msg) { write(Level::Error, msg); }

// Module-tagged variants; the module lands in the `target` field
// (tpu_pruner::<module>) and selects its filter directive.
inline void trace(std::string_view m, const std::string& msg) { write(Level::Trace, m, msg); }
inline void debug(std::string_view m, const std::string& msg) { write(Level::Debug, m, msg); }
inline void info(std::string_view m, const std::string& msg) { write(Level::Info, m, msg); }
inline void warn(std::string_view m, const std::string& msg) { write(Level::Warn, m, msg); }
inline void error(std::string_view m, const std::string& msg) { write(Level::Error, m, msg); }

// ── cycle stamping ──
// Monotonic cycle id appended to every log line (json: a "cycle" field;
// default/pretty: a trailing " cycle=N") so logs join against
// DecisionRecord.cycle without timestamp guessing. The producer sets the
// process-wide id at cycle start (audit::begin_cycle); consumer threads —
// which may still be actuating cycle N while the producer runs N+1 — pin
// their own lines with the thread override. 0 = unstamped.
void set_cycle(uint64_t cycle);              // process-wide (producer)
void set_thread_cycle(uint64_t cycle);       // per-thread override; 0 clears

// Counters (reference names, main.rs:300-365):
//   query_successes, query_failures, scale_successes, scale_failures,
//   query_returned_candidates, query_returned_shutdown_events
// The call site fixes the metric kind, mirroring the reference's
// monotonic_counter.* vs counter.* tracing-field prefixes: counter_add
// registers a monotonic cumulative sum, counter_set a last-value gauge.
struct Counter {
  uint64_t value = 0;
  bool gauge = false;
};
void counter_add(const std::string& name, uint64_t delta);
void counter_set(const std::string& name, uint64_t value);
std::map<std::string, Counter> counters_snapshot();
void counters_reset_for_test();

// ── histograms ──
// Prometheus-histogram registry for phase latencies: fixed buckets, one
// optional label value per family (the label name is always "phase"; ""
// renders unlabelled). Each bucket remembers its latest exemplar trace id
// — /metrics serves them under the OpenMetrics negotiation so histogram
// points link back to the cycle's OTLP trace.
struct HistogramSnapshot {
  struct Exemplar {
    std::string trace_id;
    double value = 0;
    int64_t ts_unix = 0;
    bool set = false;
  };
  std::vector<double> bounds;      // upper bounds, excludes +Inf
  std::vector<uint64_t> buckets;   // per-bucket (NON-cumulative); size bounds+1
  std::vector<Exemplar> exemplars; // aligned with buckets
  double sum = 0;
  uint64_t count = 0;
};
void histogram_observe(const std::string& family, const std::string& phase,
                       double value, const std::string& exemplar_trace_id = "");
// family → phase label value → snapshot
std::map<std::string, std::map<std::string, HistogramSnapshot>> histograms_snapshot();
void histograms_reset_for_test();

}  // namespace tpupruner::log
