// Structured logging + operational counters.
//
// Reference analog: tracing-subscriber with three formats (json / default /
// pretty, main.rs:128-134, 176-192), level filtering via RUST_LOG
// (main.rs:173), and tracing-field counters that the OTEL layer turns into
// metrics (main.rs:300-321, 349-365). Here: same three formats on stderr,
// level via TPU_PRUNER_LOG (or RUST_LOG for drop-in familiarity), and a
// process-wide counter registry with the reference's six counter names —
// exposed over the optional /metrics endpoint instead of OTLP push.
#pragma once

#include <cstdint>
#include <map>
#include <string>

namespace tpupruner::log {

enum class Level : uint8_t { Trace = 0, Debug, Info, Warn, Error };
enum class Format : uint8_t { Default, Json, Pretty };

void init(Format format);
// Level resolution: TPU_PRUNER_LOG → RUST_LOG → "info".
Level threshold();

void write(Level level, const std::string& msg);

inline void trace(const std::string& msg) { write(Level::Trace, msg); }
inline void debug(const std::string& msg) { write(Level::Debug, msg); }
inline void info(const std::string& msg) { write(Level::Info, msg); }
inline void warn(const std::string& msg) { write(Level::Warn, msg); }
inline void error(const std::string& msg) { write(Level::Error, msg); }

// Counters (reference names, main.rs:300-365):
//   query_successes, query_failures, scale_successes, scale_failures,
//   query_returned_candidates, query_returned_shutdown_events
// The call site fixes the metric kind, mirroring the reference's
// monotonic_counter.* vs counter.* tracing-field prefixes: counter_add
// registers a monotonic cumulative sum, counter_set a last-value gauge.
struct Counter {
  uint64_t value = 0;
  bool gauge = false;
};
void counter_add(const std::string& name, uint64_t delta);
void counter_set(const std::string& name, uint64_t value);
std::map<std::string, Counter> counters_snapshot();
void counters_reset_for_test();

}  // namespace tpupruner::log
