// Policy gym: offline replay simulator for auto-scaling policies.
//
// The flight recorder (recorder.hpp) made single cycles replayable and
// `--what-if` flips one knob at a time; the ledger (ledger.hpp) defined
// the money math. The gym composes them into a KIS-S-style simulator
// (arxiv 2507.07932): replay a *stream* of cycle capsules — a recorded
// --flight-dir corpus or a synthetic trace (tpu_pruner/testing/trace_gen)
// — against N candidate policies side by side in ONE pass over the
// stream, scoring each with the ledger's own integration math:
//
//   reclaimed chip-hours   chips × time a policy kept roots scaled down
//                          (the ledger's dt-integration, bit-for-bit for
//                          the baseline policy on the recording run's own
//                          capsules — asserted by tests/test_gym.py),
//   false pauses           a pause whose root shows busy evidence within
//                          --regret-window seconds (the workload was
//                          needed; the pause cost a cold restart),
//   actuation churn        pause + resume events (each is an API patch
//                          and a workload disruption).
//
// Policies are first-class (PolicySpec):
//   baseline               the daemon's current config, replayed verbatim
//   sweep:<k=v,...>        a what-if overlay (lookback, grace, run_mode,
//                          max_scale_per_cycle, ...) applied every cycle
//   right-size[:threshold=T]
//                          scale partially idle replica-knob roots to the
//                          smallest replica count whose projected duty
//                          cycle stays under T instead of all-or-nothing
//                          zero (the batching-vs-multi-tenancy tradeoff,
//                          arxiv 2308.13803)
//   hysteresis[:pause_after=K]
//                          per-root streak state: only pause after K
//                          consecutive candidate cycles (flapping guard)
//
// The winner's config is emitted as a ready-to-apply daemon flag line.
// The right-size policy is promoted into the daemon behind
// `--right-size on|off` (off = exact decision parity); right_size_plan()
// below is the ONE implementation of that math, shared by the daemon
// (run_cycle), the replay engine (recorder::replay re-derives
// RIGHT_SIZED / RIGHT_SIZE_HELD offline) and the simulator.
//
// Counterfactual honesty: a corpus recorded in scale-down mode carries
// evidence shadows — once the live daemon paused a root, later capsules
// hold no busy/idle evidence for it, so false-pause detection is
// suppressed for live-paused roots (tracked from the capsules' own
// actuation records). Corpora recorded in dry-run mode are evidence-
// complete and are the recommended gym input; `assume_scale_down`
// (default on) then scores every policy as if it had been acting.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "tpupruner/core.hpp"
#include "tpupruner/json.hpp"

namespace tpupruner::gym {

// ── replica right-sizing (shared daemon / replay / simulator math) ──

struct RightSizePlan {
  // False when the kind has no replica knob, the root object carries no
  // replica count, R <= 1, or every replica is idle — all of which keep
  // the classic scale-to-zero pause (exact baseline behavior).
  bool applicable = false;
  int64_t current_replicas = 0;
  int64_t busy_replicas = 0;    // replicas NOT observed idle this cycle
  int64_t target_replicas = 0;  // N = min(R, ceil(busy / threshold))
  int64_t freed_chips = 0;      // chips_per_replica × (R − N)
  bool held = false;            // N >= R: nothing to shrink this cycle
  std::string detail;           // deterministic audit/replay detail string
};

// The right-size decision for one resolved root: scale to the smallest
// replica count N whose projected per-replica duty cycle — busy_replicas
// (each conservatively assumed fully busy) redistributed over N replicas
// — stays under `threshold`. `idle_pods`/`idle_chips` are the cycle's
// observed idle evidence for the root (the ledger observation); replica
// counts come from the root object (spec.replicas, or
// spec.predictor.minReplicas for InferenceService). Pure and
// deterministic: the daemon, the offline replay and the gym all call
// exactly this.
RightSizePlan right_size_plan(core::Kind kind, const json::Value& root_object,
                              int64_t idle_pods, int64_t idle_chips,
                              double threshold);

// ── policy specs ──

// Parse a CLI policy spec string into the structured form simulate()
// takes: "baseline", "sweep:lookback=10m,grace=60",
// "right-size[:threshold=0.8]", "hysteresis[:pause_after=3]". Throws
// std::runtime_error on malformed specs (unknown kinds/keys surface on
// replay). The spec string itself becomes the policy name.
json::Value parse_policy_spec(const std::string& spec);

// The default 3-policy panel (baseline, right-size:threshold=0.8,
// hysteresis:pause_after=3) used when no --policy is given.
json::Value default_policies();

// ── the simulator ──
// payload:
//   {"capsules": [<capsule JSON>...],        // any order; sorted by cycle
//    "policies": ["baseline", {...}, ...],   // spec strings or objects
//    "regret_window_s": 600,                 // false-pause window
//    "assume_scale_down": true,              // score dry-run corpora as
//                                            // if run_mode=scale-down
//    "false_pause_penalty_chip_hours": 1.0,  // scoring weights
//    "churn_penalty_chip_hours": 0.01}
// Returns {"cycles", "policies": [{name, kind, reclaimed_chip_seconds,
// reclaimed_chip_hours, false_pauses, pauses, resumes, actuation_churn,
// right_size_applied, right_size_held, score, flag_line}...],
// "winner": {...}, "regret_window_s", "assume_scale_down"}. Throws on
// malformed capsules or policy specs.
json::Value simulate(const json::Value& payload);

// `tpu-pruner gym` entry point (flag surface: --flight-dir, --capsule,
// --policy, --regret-window, --as-recorded, --false-pause-penalty,
// --churn-penalty). Human table on stderr, one JSON document on stdout.
int run_cli(int argc, char** argv);

}  // namespace tpupruner::gym
