// CLI flag surface.
//
// Reference analog: struct Cli (gpu-pruner/src/main.rs:46-119) — all 15
// reference flags are kept (same names, shorts, defaults) so a gpu-pruner
// deployment manifest ports by changing the binary name; TPU-native flags
// are added (--device, --accelerator-type, --hbm-threshold, metric-name
// overrides, --metrics-port). The reference serializes Cli into the Jinja
// context (main.rs:281); here Cli maps onto query::QueryArgs the same way.
#pragma once

#include <optional>
#include <stdexcept>
#include <string>

#include "tpupruner/core.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/query.hpp"

namespace tpupruner::cli {

struct CliError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Raised by parse() on -h/--help; carries the usage text.
struct HelpRequested : std::runtime_error {
  using std::runtime_error::runtime_error;
};

struct Cli {
  // ── reference flags (main.rs:46-119) ──
  int64_t duration = 30;                  // -t, minutes of no activity
  bool daemon_mode = false;               // -d
  std::string enabled_resources = "drsinjl";  // -e (ref default "drsin" + JobSet/LWS)
  int64_t check_interval = 180;           // -c, seconds (daemon mode)
  std::string ns_regex;                   // -n, namespace pattern
  std::string ns_exclude_regex;           // --namespace-exclude (ns !~; RE2 has no lookahead)
  int64_t grace_period = 300;             // -g, seconds
  std::string model_name;                 // -m, GPU model pattern (device=gpu)
  std::optional<double> power_threshold;  // --power-threshold, watts
  bool honor_labels = false;              // --honor-labels
  std::string run_mode = "dry-run";       // -r {scale-down, dry-run}
  std::string prometheus_url;             // --prometheus-url (required for run)
  std::string prometheus_token;           // --prometheus-token
  std::string prometheus_tls_mode = "verify";  // {skip, verify}
  std::string prometheus_tls_cert;        // --prometheus-tls-cert
  std::string log_format = "default";     // -l {json, default, pretty}

  // ── TPU-native flags ──
  std::string device = "tpu";             // --device {tpu, gpu}
  std::string accelerator_type;           // --accelerator-type pattern (device=tpu)
  std::optional<double> hbm_threshold;    // --hbm-threshold, HBM bw util 0-1
  // --metric-schema {auto, gmp, gke-system}; parse() resolves "auto" →
  // gke-system when --gcp-project is set (the Cloud Monitoring PromQL API
  // is the only plane serving kubernetes_io:node_accelerator_* names),
  // gmp otherwise — so this field is always concrete after parse().
  std::string metric_schema = "auto";
  std::string tensorcore_metric;          // --tensorcore-metric override
  std::string duty_cycle_metric;          // --duty-cycle-metric override
  std::string hbm_metric;                 // --hbm-metric override
  std::string join_metric;                // --join-metric override (gke-system)
  // --join-resource (gke-system): KSM resource selector; "none" disables.
  std::string join_resource;
  int64_t max_scale_per_cycle = 0;        // --max-scale-per-cycle (0 = unlimited)
  // --watch-cache {on, off}: informer-style List+Watch cluster cache. "on"
  // serves pod acquisition and the owner walk from a watch-backed store
  // (steady-state API cost scales with churn, not cluster size); "off"
  // keeps the watch-free GET/LIST client — the parity mode.
  std::string watch_cache = "off";
  int64_t max_cycles = 0;                 // --max-cycles (daemon mode; 0 = unlimited)
  // --cycle-deadline: abort a cycle wedged past N x max(check-interval,
  // 1 s) at the next phase boundary with audit reason CYCLE_TIMEOUT
  // (watchdog.hpp). 0 = off (the default; opt-in hardening).
  int64_t cycle_deadline = 0;
  int64_t resolve_concurrency = 10;       // --resolve-concurrency (ref: fixed 10)
  int64_t resolve_batch_threshold = 8;    // --resolve-batch-threshold (0 = off)
  int64_t scale_concurrency = 8;          // --scale-concurrency (ref: serial consumer)
  // --shards: reconcile-engine shard count (shard.hpp). Candidates walk
  // shard-parallel (per-shard owner cache, read-through to the informer
  // store) and fold keyed by resolved-root hash, then merge in stable
  // order — every count produces byte-identical decisions. 1 = the
  // serial engine; 0 (default) = auto: hardware_concurrency clamped to 8.
  int64_t shards = 0;
  // --overlap {on, off}: pipeline adjacent cycles — cycle N+1's
  // query+decode+signal phases run on a helper thread while cycle N
  // resolves and its actuations drain (bounded two-cycle handoff; the
  // breaker, brownout and --max-scale-per-cycle caps still apply per
  // cycle). "off" (default) keeps the strictly serial producer loop.
  std::string overlap = "off";
  // --reconcile {cycle, event}: reconcile engine inversion. "cycle"
  // (default) is the polling loop: evaluate everything every
  // --check-interval seconds. "event" turns the engine into a streaming
  // dataflow — informer dirty-journal notifications, Prometheus
  // sample-fingerprint flips and timer-wheel deadline expiries each
  // trigger an evaluation within milliseconds, while the old cycle
  // survives only as a periodic full anti-entropy pass every
  // --check-interval seconds (the informer relist analog). Every
  // evaluation runs the same prepare/finish pipeline a polled cycle
  // does, so audit/capsules/ledger/replay stay byte-identical on
  // quiesced and replayed-churn corpora. Requires --watch-cache on
  // (events come from the watch plane). Cross-evaluation gates
  // (--max-scale-per-cycle) become sliding-window token buckets over
  // one --check-interval with the same DEFERRED audit code.
  std::string reconcile = "cycle";
  // --sample-interval-ms (event mode): cadence of the cheap Prometheus
  // probe query whose decoded-sample fingerprint flip triggers an
  // evaluation — the detection path that decouples detect→action
  // latency from --check-interval. Ignored under --reconcile cycle.
  int64_t sample_interval_ms = 500;
  // --pause-after K: hysteresis promoted from the gym policy — a root
  // must be observed idle-and-actionable on K CONSECUTIVE evaluations
  // before the pause lands (audit code HYSTERESIS_HOLD while the streak
  // builds; any non-idle evaluation resets it). 1 (default) = exact
  // parity with the pre-hysteresis daemon. Event mode wants K>1 so a
  // flap-triggered evaluation cannot actuate on one sample.
  int64_t pause_after = 1;
  // --incremental {on, off}: differential reconcile engine
  // (incremental.hpp). "on" fuses watch-event, sample-diff and
  // config/clock invalidation into per-root dirty marks and serves clean
  // roots from a memoized decision cache, making warm-cycle CPU O(churn);
  // requires --watch-cache on (the dirty journal is watch-driven). "off"
  // (default) keeps the full per-cycle recompute — exact output parity
  // either way (audit JSONL, capsules, ledger, replay are byte-identical).
  std::string incremental = "off";
  // --transport: shared h2 transport mode (auto = ALPN/prior-knowledge
  // negotiation with transparent HTTP/1.1 fallback; http1 = parity escape
  // hatch). --zero-copy-json: arena decode at the LIST/watch and
  // Prometheus-matrix call sites (off = Value::parse everywhere).
  std::string transport = "auto";
  std::string zero_copy_json = "on";
  // --wire: wire FORMAT for the pods list+watch and the Prometheus
  // instant queries (proto.hpp). "proto" negotiates
  // application/vnd.kubernetes.protobuf (+ the Prometheus protobuf
  // exposition) with per-request JSON fallback and fuses watch-event
  // decode into the incremental engine's dirty journal; "auto" asks once
  // per endpoint and remembers a refusal; "json" (default) never asks —
  // exact output parity (audit/capsules/ledger/replay byte-identical).
  std::string wire = "json";
  // --compact-store: pods LIST/watch decode straight into packed,
  // string-interned PodRecords (compact.hpp) instead of pinning LIST
  // pages / JSON arenas per entry. Materialization back to a Value is
  // byte-identical (pinned over the wire-parity corpus); "off" is the
  // exact-parity escape hatch that keeps the PR 9/11 representations.
  std::string compact_store = "on";
  int metrics_port = -1;                  // --metrics-port: -1 disabled (flag "0" maps
                                          // here too), 0 ephemeral (flag "auto"), else port
  // --cluster-name: fleet identity stamped on every exported surface (a
  // `cluster` label on every /metrics sample, a "cluster" key in every
  // /debug/* payload, DecisionRecord, ledger checkpoint line, and flight
  // capsule). "" → fleet::resolve_cluster_name's heuristic
  // ($TPU_PRUNER_CLUSTER_NAME, in-cluster namespace, $POD_NAMESPACE,
  // kubeconfig current-context, "default").
  std::string cluster_name;
  std::string audit_log;                  // --audit-log: JSONL DecisionRecord sink ("" = off)
  std::string ledger_file;                // --ledger-file: JSONL workload-ledger checkpoint ("" = off)
  int64_t ledger_top_k = 10;              // --ledger-top-k: /metrics workload label cardinality bound
  std::string flight_dir;                 // --flight-dir: cycle flight-recorder capsule ring ("" = off)
  int64_t flight_keep = 64;               // --flight-keep: capsules retained in the on-disk ring
  // --signal-guard {on, off}: signal-quality watchdog (signal.hpp). "on"
  // runs a second per-cycle evidence query (per-pod sample coverage +
  // last-sample age), vetoes unhealthy-signal pods with SIGNAL_* reason
  // codes, and defers every scale-down under a fleet brownout. "off"
  // (default) keeps exact decision parity with the pre-watchdog daemon.
  std::string signal_guard = "off";
  int64_t signal_scrape_interval = 30;    // --signal-scrape-interval: expected scrape cadence, s
  int64_t signal_max_age = 300;           // --signal-max-age: STALE threshold, s
  double signal_min_coverage = 0.9;       // --signal-min-coverage: brownout floor, 0-1
  // --right-size {on, off}: replica right-sizing (gym.hpp). "on" scales
  // partially idle replica-knob roots (Deployment/ReplicaSet/StatefulSet/
  // LWS/InferenceService) to the smallest replica count whose projected
  // per-replica duty cycle stays under --right-size-threshold, instead of
  // the all-or-nothing scale-to-zero; audit codes RIGHT_SIZED /
  // RIGHT_SIZE_HELD, partial reclaim in the ledger (freed chips × time).
  // "off" (default) keeps exact decision parity.
  std::string right_size = "off";
  double right_size_threshold = 0.8;      // --right-size-threshold: duty ceiling, (0-1]
  // --capacity {on, off}: the capacity observatory (capacity.hpp). "on"
  // lists nodes + TPU pod placements each evaluation and publishes the
  // free-capacity inventory (/debug/capacity, tpu_pruner_capacity_*
  // families, the fourth delta surface, capsule capacity stamps). "off"
  // (default) keeps the API call pattern and every artifact byte-exact.
  std::string capacity = "off";
  // --slice-gate {on, off}: slice-topology group gate — an idle root
  // whose pods share a TPU slice (node-pool) with a busy tenant is held
  // (SLICE_SHARED_BUSY) instead of evicted. Implies the same node/pod
  // listing as --capacity. "off" (default) keeps exact decision parity.
  std::string slice_gate = "off";
  // --trace {on, off}: action provenance traces (trace.hpp). "on" builds
  // one causal span tree per evaluation (rooted at trigger ingress, with
  // per-phase / per-shard / per-actuation children) retained in a bounded
  // ring at /debug/traces and exported over OTLP when the exporter is
  // live. "off" (default) keeps audit/capsule/ledger output byte-exact;
  // the flag never enters the config fingerprint.
  std::string trace = "off";
  // --slo-detect-to-action-ms: detect→action latency objective. > 0 arms
  // the SLO engine (tpu_pruner_slo_* counters + burn ratio), judges every
  // actuation's root-relative latency, and pins breaching traces past
  // normal ring eviction. Requires --trace on. 0 (default) disables.
  int64_t slo_detect_to_action_ms = 0;
  std::string otlp_endpoint;              // --otlp-endpoint (default: $OTEL_EXPORTER_OTLP_ENDPOINT)
  std::string gcp_project;                // --gcp-project (Cloud Monitoring PromQL API)
  std::string monitoring_endpoint = "https://monitoring.googleapis.com";  // --monitoring-endpoint
  std::string notify_webhook;             // --notify-webhook (POST per pause; Slack-compatible)
  bool print_query = false;               // --print-query: render the query and exit
  bool leader_elect = false;              // --leader-elect (HA; requires daemon mode)
  std::string lease_namespace;            // --lease-namespace (default: $POD_NAMESPACE or "tpu-pruner")
  std::string lease_name = "tpu-pruner";  // --lease-name
  int64_t lease_duration = 15;            // --lease-duration seconds

  bool dry_run() const { return run_mode != "scale-down"; }
};

// Parse argv (past any subcommand). Throws CliError on unknown flags, bad
// values, or missing required flags; HelpRequested on -h/--help.
Cli parse(int argc, char** argv);

std::string usage();

query::QueryArgs to_query_args(const Cli& cli);
log::Format log_format_of(const Cli& cli);

// The concrete metric schema ("gmp" | "gke-system") for a Cli whose
// metric_schema may still read "auto" (hand-built values; parse() output
// is always concrete). Single point of truth — the daemon's decoder and
// to_query_args both resolve through here so query build and decode can
// never disagree.
std::string resolved_schema(const Cli& cli);

// Effective PromQL base URL: --prometheus-url verbatim, or (GKE-native)
// the Cloud Monitoring PromQL API for --gcp-project —
// <monitoring-endpoint>/v1/projects/<p>/location/global/prometheus — to
// which prom::Client appends /api/v1/query. Auth rides the same bearer
// chain (Workload Identity metadata-server tokens in-cluster).
std::string prometheus_base(const Cli& cli);

}  // namespace tpupruner::cli
