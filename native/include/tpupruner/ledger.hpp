// Workload utilization ledger: per-root idle/active accounting and
// reclaimed chip-hour attribution.
//
// The audit trail (audit.hpp) answers "why was pod X touched"; the ledger
// answers the question operators budget against: "how much TPU time did
// each workload waste, and how much did the pruner reclaim?" For every
// root object the walker resolves, a continuously-updated account keyed by
// kind/namespace/name integrates per-cycle duty-cycle observations into
// cumulative idle-seconds and active-seconds, tracks the current idle
// streak, keeps a bounded history of scale events (paused/resumed, by
// whom, at which cycle, with the audit reason code), and derives
// reclaimed chip-seconds — chips × time the root spent scaled-to-zero
// after the pruner paused it.
//
// Exposed three ways: bounded-cardinality metric families on /metrics
// (top-K by chips + one "_other" rollup so label cardinality never scales
// with fleet size), a /debug/workloads JSON snapshot on the metrics port,
// and an optional JSONL checkpoint (--ledger-file) written at cycle end
// and reloaded at startup so savings survive restarts and leader
// failover. `python -m tpu_pruner.analyze --fleet-report` consumes the
// file or the endpoint and renders the per-namespace savings report.
//
// Accounting semantics (deliberately conservative):
//   - Integration is cycle-driven: dt = time since the previous cycle of
//     THIS process. The first cycle after a (re)start integrates nothing,
//     so a reloaded checkpoint's cumulative totals are reproduced exactly
//     before any new evidence lands.
//   - A paused account accrues reclaimed chip-seconds (chips-at-pause ×
//     dt) and nothing else; observations while paused (metric series that
//     outlive the pods) never double-count as idle time.
//   - Resume detection is informer-driven: a paused root whose stored
//     object no longer shows its kind's paused state was resumed
//     externally. Without --watch-cache the account stays paused until
//     the pruner itself re-pauses the root (a no-op on the ledger).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::ledger {

// One cycle's evidence for one root: the root identity plus the chips its
// observed idle pods reserve (summed per root by the caller). `pods` is
// the contributing idle-pod count — the ledger itself only integrates
// chips, but the right-size planner (gym.hpp) and the flight capsule's
// ledger stamp ride the same struct.
struct Observation {
  std::string kind, ns, name;
  int64_t chips = 0;
  int64_t pods = 0;
};

// A currently-paused account (kind/ns/name), for the daemon's informer
// resume sweep.
struct PausedRoot {
  std::string kind, ns, name;
};

// Optional JSONL checkpoint ("" disables). Setting a non-empty path loads
// any existing checkpoint into the registry (accounts merge over whatever
// is already tracked) before enabling the per-cycle rewrite.
void set_ledger_file(const std::string& path);

// Fold one cycle's idle-root observations into the registry:
//   observed & not paused  → idle_seconds += dt, idle streak advances
//   tracked but unobserved → active_seconds += dt, idle streak resets
//   paused (either way)    → reclaimed_chip_seconds += chips_at_pause × dt
// dt = now_unix − previous observe_cycle's now_unix (0 on the first call
// of the process). Writes the checkpoint when a ledger file is set.
void observe_cycle(uint64_t cycle, int64_t now_unix,
                   const std::vector<Observation>& idle_roots);

// The consumer landed (or confirmed) a pause on this root. No-op when the
// account is already marked paused — watch-cache-off re-patches of an
// already-paused root must not inflate the pause count. `reason` is the
// audit reason code (SCALED / ALREADY_PAUSED).
void record_pause(uint64_t cycle, const std::string& kind, const std::string& ns,
                  const std::string& name, const std::string& reason);

// A right-size patch landed (--right-size on): the root kept its busy
// replicas and freed `freed_chips` worth of idle ones. The account enters
// the "right_sized" state — partial reclaim accrues as freed_chips × dt,
// exactly like a pause accrues chips_when_paused × dt. Repeated
// right-sizes of the same root (progressive consolidation) ACCUMULATE
// freed chips; a later full pause upgrades the account in record_pause.
// No-op when the account is already fully paused.
void record_right_size(uint64_t cycle, const std::string& kind, const std::string& ns,
                       const std::string& name, int64_t freed_chips);

// A paused root came back (informer saw it leave its paused state, or a
// test drives the transition directly). No-op when not marked paused.
// `actor` is "external" for operator resumes.
void record_resume(uint64_t cycle, const std::string& kind, const std::string& ns,
                   const std::string& name, const std::string& actor);

// Accounts currently marked paused — the daemon's per-cycle informer
// resume sweep iterates these.
// Rewrite the checkpoint now if throttled record_* writes left it dirty
// (record_pause and friends rewrite at most once per second — a
// fleet-scale actuation drain would otherwise spend O(pauses x accounts)
// re-serializing the whole file). The daemon calls this at shutdown so
// the final drain's tail is never lost; observe_cycle flushes every
// cycle in steady state.
void flush();

std::vector<PausedRoot> paused_roots();

// Accounts whose capacity is currently freed by an actuation (fully
// paused OR right-sized), with the chips basis their reclaim accrues at
// (chips_when_paused) — the capacity observatory's "freed" section.
// Sorted by account key (the registry map order).
struct FreedAccount {
  std::string kind, ns, name;
  int64_t chips = 0;   // the reclaim basis: freed chips x dt accrues
  std::string state;   // "paused" | "right_sized"
};
std::vector<FreedAccount> freed_accounts();

// /debug/workloads body: {"workloads": [...], "tracked": N, "totals":
// {...}}. `query_string` supports ns=<namespace> (alias namespace=) and
// sort=reclaimed|idle|chips (descending; default reclaimed).
json::Value workloads_json(const std::string& query_string = "");

// Prometheus text for the ledger's metric families, bounded to the top-K
// accounts by chips plus one "_other" rollup series per family (totals
// across served series always equal the full-fleet totals):
//   tpu_pruner_workload_idle_seconds_total{workload=...}            counter
//   tpu_pruner_workload_reclaimed_chip_seconds_total{workload=...}  counter
//   tpu_pruner_workload_chips{workload=...,state=idle|active|paused} gauge
//   tpu_pruner_workloads_tracked                                    gauge
// `openmetrics` switches counter TYPE lines to the OpenMetrics family
// form (name without the _total suffix).
std::string render_metrics(int top_k, bool openmetrics);

// The family names served above, for the docs drift guard (capi).
std::vector<std::string> metric_families();

void reset_for_test();

}  // namespace tpupruner::ledger
