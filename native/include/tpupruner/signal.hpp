// Signal-quality watchdog: per-pod evidence provenance + fleet brownout
// guard.
//
// The whole pruner rests on one inference — "zero peak duty cycle over
// the lookback ⇒ idle" — but a broken scrape, metric-plane ingestion
// lag, or an absent metric family produces EXACTLY the same query result
// as a truly idle fleet. The watchdog makes the daemon observe the
// health of its own evidence, not just the evidence: each cycle a second
// *evidence query* (query::build_evidence_query) asks the metric plane
// for per-pod sample coverage (count_over_time over the lookback) and
// last-sample age (time() − timestamp()), and assess() folds both
// against the cycle's candidate set into one per-pod verdict:
//
//   HEALTHY  fresh samples, adequate coverage — evidence trustworthy
//   STALE    newest sample older than --signal-max-age (ingestion lag /
//            dead scrape; the "idle" reading is a memory, not a fact)
//   GAPPY    fewer than half the samples the scrape interval implies
//            over the window (flapping scrape; peaks can hide in gaps)
//   ABSENT   the candidate appears in the idle result but the evidence
//            query has no coverage or freshness row for it at all
//            (metric family missing / relabeling dropped the series)
//
// Behind --signal-guard on (off = exact decision parity with the
// pre-watchdog daemon), unhealthy-signal pods are vetoed with dedicated
// audit reason codes (SIGNAL_STALE / SIGNAL_GAPPY / SIGNAL_ABSENT), the
// ledger consequently never integrates idle-seconds from untrustworthy
// evidence (vetoed pods never reach resolution), and a fleet-wide
// *brownout* — healthy coverage below --signal-min-coverage — defers
// EVERY scale-down of the cycle (reason SIGNAL_BROWNOUT), the way the
// blast-radius breaker defers its overflow. The assessment is exported
// three ways: /metrics families (signal_coverage_ratio, signal_pods by
// verdict, signal_brownouts_total, pod_signal_age_seconds histogram),
// the /debug/signals JSON endpoint, and a stamp in the flight-recorder
// capsule so replay reproduces every verdict bit-for-bit offline.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "tpupruner/audit.hpp"
#include "tpupruner/core.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/proto.hpp"

namespace tpupruner::signal {

enum class Verdict : uint8_t { Healthy, Stale, Gappy, Absent };

const char* verdict_name(Verdict v);  // "healthy" | "stale" | "gappy" | "absent"

// Assessment thresholds (CLI: --signal-scrape-interval, --signal-max-age,
// --signal-min-coverage; window_s is the evidence query's lookback —
// duration minutes, the count_over_time range).
struct Config {
  int64_t scrape_interval_s = 30;
  int64_t max_age_s = 300;
  double min_coverage = 0.9;
  int64_t window_s = 1800;

  // GAPPY floor: below half the samples a healthy scrape would land in
  // the window, coverage is too thin to trust a zero-peak reading.
  double min_samples() const {
    double expected = scrape_interval_s > 0
                          ? static_cast<double>(window_s) / static_cast<double>(scrape_interval_s)
                          : 1.0;
    return expected * 0.5 < 1.0 ? 1.0 : expected * 0.5;
  }
};

// One candidate pod's evidence health.
struct PodSignal {
  std::string ns, pod;
  double sample_count = 0.0;  // count_over_time over the window
  double last_age_s = 0.0;    // seconds since the newest sample
  bool has_samples = false;   // a "samples" evidence row existed
  bool has_age = false;       // an "age" evidence row existed
  Verdict verdict = Verdict::Absent;
};

// The cycle's whole evidence-health picture.
struct Assessment {
  uint64_t cycle = 0;
  double coverage_ratio = 1.0;  // healthy candidates / all candidates (1.0 when none)
  bool brownout = false;        // coverage below Config::min_coverage
  double min_coverage = 0.9;    // threshold the brownout was judged against
  std::vector<PodSignal> pods;  // one entry per candidate, candidate order

  size_t count(Verdict v) const;
};

// Decode one evidence-query response (instant vector with the synthetic
// signal_stat label) against the cycle's candidate set and derive the
// per-pod verdicts + fleet coverage. Throws on a non-success response
// (an unanswerable evidence query feeds the failure budget like the idle
// query — no evidence is itself a signal-quality fact the guard must not
// paper over).
Assessment assess(const json::Value& evidence_response,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle);
// Zero-copy sibling walking the arena Doc directly; verdicts, ordering,
// and throw behavior identical to the Value overload on the same bytes
// (replay re-derives from capsule bytes via the Value path — bit-for-bit
// holds only because these two agree).
Assessment assess(const json::Doc& evidence_response,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle);
// Binary-wire sibling (--wire proto): folds the fused protobuf decode's
// series (proto.hpp) with the same label chain and row semantics; replay
// re-derives from the capsule's canonical JSON body via the Value path —
// bit-for-bit holds only because all three agree.
Assessment assess(const proto::PromVector& evidence_response,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle);

// The audit reason code a verdict vetoes with (Healthy has none — do not
// call it for healthy pods).
audit::Reason veto_reason(Verdict v);

// Deterministic detail strings, shared verbatim by the daemon and the
// flight-recorder replay so replayed DecisionRecords match bit-for-bit.
std::string veto_detail(const PodSignal& p, const Config& cfg);
std::string brownout_detail(const Assessment& a, const Config& cfg);

// JSON round-trip: the flight-recorder capsule stamp and the
// /debug/signals payload share this shape.
json::Value assessment_to_json(const Assessment& a);
Assessment assessment_from_json(const json::Value& v);

// ── process-wide export registry ──
// publish() installs the latest assessment (and folds it into the
// cumulative brownout counter + age histogram); the /metrics extra
// provider and /debug/signals read it back.
void publish(const Assessment& a, const Config& cfg);

// /debug/signals body: {"enabled", "cycle", "coverage_ratio", "brownout",
// "brownouts_total", "thresholds", "pods" (verdict counts), "details"}.
// {"enabled": false} before the first publish (guard off).
json::Value signals_json();

// Prometheus exposition for the signal families ("" before the first
// publish, so --signal-guard off serves no signal series — the absent-
// not-zero convention the informer families follow):
//   tpu_pruner_signal_coverage_ratio                  gauge
//   tpu_pruner_signal_pods{verdict=...}               gauge
//   tpu_pruner_signal_brownouts_total                 counter
//   tpu_pruner_pod_signal_age_seconds                 histogram
std::string render_metrics(bool openmetrics);

// The family names served above (docs drift guard, via capi).
std::vector<std::string> metric_families();

void reset_for_test();

}  // namespace tpupruner::signal
