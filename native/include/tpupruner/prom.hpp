// Prometheus HTTP API client (instant queries).
//
// Reference analog: prometheus_http_query::Client built per cycle with
// bearer auth + TLS modes (gpu-pruner/src/lib.rs:240-282, main.rs:296,
// 377-388). Works against vanilla Prometheus, Thanos query frontends, and
// the GKE managed-Prometheus query endpoint (all speak /api/v1/query).
#pragma once

#include <mutex>
#include <string>

#include "tpupruner/h2.hpp"
#include "tpupruner/http.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/proto.hpp"

namespace tpupruner::prom {

class Client {
 public:
  Client(std::string base_url, std::string bearer_token,
         http::TlsMode tls_mode = http::TlsMode::Verify, std::string ca_file = "",
         int timeout_ms = 30000);

  // POST /api/v1/query (form-encoded). Returns the decoded JSON response
  // body; throws std::runtime_error on transport errors or non-2xx status.
  // `raw_body` (optional) receives the VERBATIM 2xx response text before
  // parsing — the flight recorder stores it so a replay decodes exactly
  // the bytes the daemon received, not a re-serialization.
  json::Value instant_query(const std::string& promql, std::string* raw_body = nullptr) const;

  // Zero-copy sibling: the 2xx response body moves into an arena Doc
  // (labels/values are string_views into it) instead of a Value tree —
  // the warm cycle's matrix decode walks the Doc directly. Same error
  // semantics as instant_query; `raw_body` still receives a verbatim copy
  // (the flight recorder's contract).
  json::DocPtr instant_query_doc(const std::string& promql,
                                 std::string* raw_body = nullptr) const;

  // ── binary wire path (--wire proto|auto; proto.hpp) ──
  // The negotiated instant-query result: exactly one representation is
  // populated. Under the protobuf exposition the samples are decoded in
  // the SAME pass that reads the body (no Doc/Value is ever built), and
  // `raw_body` receives the canonical JSON reconstruction — byte-identical
  // to what the JSON wire would have delivered for the same data, which
  // is what keeps flight capsules wire-format independent.
  struct WireVector {
    bool proto = false;
    proto::PromVector pv;   // proto: fused label/timestamp/value series
    json::DocPtr doc;       // JSON + zero-copy on
    json::Value response;   // JSON + zero-copy off
  };
  // POST /api/v1/query asking `application/x-protobuf, application/json`
  // (when the wire mode wants proto; plain JSON otherwise), decoding
  // whichever content type comes back. Error semantics identical to
  // instant_query.
  WireVector instant_query_wire(const std::string& promql,
                                std::string* raw_body = nullptr) const;

  // Transport protocol negotiated for the Prometheus endpoint
  // ("h2" | "http1" | "unknown").
  std::string transport_protocol() const { return http_.protocol_for(base_url_ + "/"); }

  // W3C trace-context propagation onto the query requests (the daemon
  // stamps each cycle's span context; managed-Prometheus request logs
  // then join the OTLP trace). "" clears.
  void set_traceparent(const std::string& tp) const { http_.set_default_traceparent(tp); }

  // Refresh the bearer token (SA projections and metadata-server tokens
  // rotate): the daemon refreshes per cycle while KEEPING the client — and
  // its warm multiplexed connection — alive across cycles.
  void set_token(std::string token) const {
    std::lock_guard<std::mutex> lock(token_mutex_);
    token_ = std::move(token);
  }

 private:
  http::Response query_once(const std::string& promql,
                            std::string_view accept = "application/json") const;

  std::string base_url_;
  mutable std::mutex token_mutex_;
  mutable std::string token_;
  // Shared multiplexing transport: the per-cycle idleness + evidence query
  // pair is issued as two concurrent streams on ONE h2 connection (or two
  // pooled HTTP/1.1 sockets after fallback).
  h2::Transport http_;
  int timeout_ms_;
};

}  // namespace tpupruner::prom
