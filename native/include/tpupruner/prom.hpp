// Prometheus HTTP API client (instant queries).
//
// Reference analog: prometheus_http_query::Client built per cycle with
// bearer auth + TLS modes (gpu-pruner/src/lib.rs:240-282, main.rs:296,
// 377-388). Works against vanilla Prometheus, Thanos query frontends, and
// the GKE managed-Prometheus query endpoint (all speak /api/v1/query).
#pragma once

#include <string>

#include "tpupruner/http.hpp"
#include "tpupruner/json.hpp"

namespace tpupruner::prom {

class Client {
 public:
  Client(std::string base_url, std::string bearer_token,
         http::TlsMode tls_mode = http::TlsMode::Verify, std::string ca_file = "",
         int timeout_ms = 30000);

  // POST /api/v1/query (form-encoded). Returns the decoded JSON response
  // body; throws std::runtime_error on transport errors or non-2xx status.
  // `raw_body` (optional) receives the VERBATIM 2xx response text before
  // parsing — the flight recorder stores it so a replay decodes exactly
  // the bytes the daemon received, not a re-serialization.
  json::Value instant_query(const std::string& promql, std::string* raw_body = nullptr) const;

  // W3C trace-context propagation onto the query requests (the daemon
  // stamps each cycle's span context; managed-Prometheus request logs
  // then join the OTLP trace). "" clears.
  void set_traceparent(const std::string& tp) const { http_.set_default_traceparent(tp); }

 private:
  std::string base_url_;
  std::string token_;
  http::Client http_;
  int timeout_ms_;
};

}  // namespace tpupruner::prom
