// Unified seeded retry/backoff policy (PR 15 chaos tier).
//
// Before this module the daemon had three ad-hoc retry mechanisms with
// three independently-tuned jitter formulas: the k8s 429 loop
// (k8s.cpp issue()), the stale keep-alive retry (http.cpp), and the
// informer relist/watch backoff (informer.cpp backoff_sleep). All three
// now route through one Policy so the chaos harness can (a) reason about
// worst-case stall time with a single cap, and (b) reseed the jitter for
// deterministic fault-schedule replay via TPU_PRUNER_BACKOFF_SEED.
//
// Every retry — wherever it happens — is counted into one labeled
// family, tpu_pruner_retries_total{endpoint,cause}, and every backoff
// wait lands in the tpu_pruner_backoff_seconds histogram, both rendered
// by render_metrics() onto /metrics (drift-guarded against
// docs/OPERATIONS.md through metric_families()).
#pragma once

#include <atomic>
#include <cstdint>
#include <string>
#include <vector>

namespace tpupruner::backoff {

// Deterministic jittered delay computation. All jitter is a pure
// function of (seed, key) — no RNG state — so a retry storm replays
// byte-identically under the same seed, which is what lets the chaos
// tier compare a faulted run against an undisturbed control run.
struct Policy {
  int64_t cap_ms = 10000;   // worst case per attempt (matches the
                            // documented 10 s bound of the old 429 loop)
  int64_t jitter_ms = 500;  // deterministic per-key spread, breaks
                            // lockstep wake across workers/reflectors
  uint64_t seed = 0;        // 0 = legacy hash (bit-identical to the
                            // pre-unification formulas)

  // Per-key jitter in [0, jitter_ms).
  int64_t jitter(const std::string& key) const;

  // Exponential schedule: min(500ms << min(attempt,5), cap_ms) plus
  // jitter over (key, attempt) — the informer relist/watch formula.
  int64_t exp_delay_ms(const std::string& key, int attempt) const;

  // Server-hinted schedule (Retry-After): the hint is capped at
  // cap_ms - jitter_ms BEFORE the jitter is added, never after —
  // capping the sum would collapse every long Retry-After to an
  // identical cap_ms, recreating exactly the lockstep wake the jitter
  // exists to break. The k8s 429 formula.
  int64_t hinted_delay_ms(const std::string& key, int64_t hint_ms) const;
};

// Process-wide policy. Seeded once from TPU_PRUNER_BACKOFF_SEED (decimal
// uint64; absent/invalid = 0 = legacy behavior).
const Policy& policy();

// Parse an RFC 7231 Retry-After header into a wait hint in ms:
// delta-seconds clamped to [1, 10] BEFORE the *1000 multiply (a hostile
// proxy can send a delta that fits int64 but overflows once scaled),
// or the HTTP-date form relative to now. Unparseable → 1000 ms.
int64_t parse_retry_after_ms(const std::string& header);

// Chunked, interruptible wait (the daemon's 100 ms sleep convention):
// polls util::shutdown_flag() and, when given, *stop every chunk.
// Returns false when interrupted before the full wait elapsed.
bool sleep_interruptible(int64_t wait_ms, const std::atomic<bool>* stop = nullptr);

// Account one retry: bumps tpu_pruner_retries_total{endpoint,cause} and
// observes the backoff wait (seconds; 0.0 for immediate retries like the
// stale keep-alive replay) into tpu_pruner_backoff_seconds.
void record_retry(const std::string& endpoint, const std::string& cause,
                  double backoff_seconds);

// Canonical native family list served by render_metrics, exported
// through the C API so tests/test_docs_drift.py can hold
// docs/OPERATIONS.md to the real set.
const std::vector<std::string>& metric_families();

// Prometheus text exposition for the retry/backoff families; appended to
// /metrics by the daemon's extra-metrics provider.
std::string render_metrics(bool openmetrics);

// Test hook: zero the counters/histogram (native units only).
void reset_for_test();

}  // namespace tpupruner::backoff
