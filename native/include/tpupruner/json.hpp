// tpu-pruner: minimal JSON value, parser, and serializer.
//
// The reference (wseaton/gpu-pruner) leans on serde_json for three jobs:
// decoding Prometheus instant-vector responses (lib.rs:153-187), building
// merge-patch bodies (lib.rs:521, 536-545, 563-572), and constructing K8s
// Event objects (lib.rs:389-427). This module provides the same capability
// natively: a small immutable-ish DOM with strict RFC 8259 parsing and
// deterministic serialization. CR objects (Notebook, InferenceService,
// JobSet) are handled as semi-structured Values rather than 31k lines of
// generated bindings (SURVEY.md §2 #10).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <string_view>
#include <vector>

namespace tpupruner::json {

class Value;
using Array = std::vector<Value>;
// std::map keeps key order deterministic for serialization and tests.
using Object = std::map<std::string, Value, std::less<>>;

enum class Type : uint8_t { Null, Bool, Int, Double, String, Array, Object };

class ParseError : public std::runtime_error {
 public:
  ParseError(const std::string& msg, size_t offset)
      : std::runtime_error(msg + " at offset " + std::to_string(offset)),
        offset_(offset) {}
  size_t offset() const { return offset_; }

 private:
  size_t offset_;
};

class Value {
 public:
  Value() : type_(Type::Null) {}
  Value(std::nullptr_t) : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(int i) : type_(Type::Int), int_(i) {}
  Value(int64_t i) : type_(Type::Int), int_(i) {}
  Value(uint64_t i) : type_(Type::Int), int_(static_cast<int64_t>(i)) {}
  Value(double d) : type_(Type::Double), dbl_(d) {}
  Value(const char* s) : type_(Type::String), str_(std::make_shared<std::string>(s)) {}
  Value(std::string s) : type_(Type::String), str_(std::make_shared<std::string>(std::move(s))) {}
  Value(std::string_view s) : type_(Type::String), str_(std::make_shared<std::string>(s)) {}
  Value(Array a) : type_(Type::Array), arr_(std::make_shared<Array>(std::move(a))) {}
  Value(Object o) : type_(Type::Object), obj_(std::make_shared<Object>(std::move(o))) {}

  static Value array() { return Value(Array{}); }
  static Value object() { return Value(Object{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_bool() const { return type_ == Type::Bool; }
  bool is_number() const { return type_ == Type::Int || type_ == Type::Double; }
  bool is_string() const { return type_ == Type::String; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool() const { expect(Type::Bool); return bool_; }
  int64_t as_int() const {
    if (type_ == Type::Double) return static_cast<int64_t>(dbl_);
    expect(Type::Int);
    return int_;
  }
  double as_double() const {
    if (type_ == Type::Int) return static_cast<double>(int_);
    expect(Type::Double);
    return dbl_;
  }
  const std::string& as_string() const { expect(Type::String); return *str_; }

  const Array& as_array() const { expect(Type::Array); return *arr_; }
  Array& as_array() { expect(Type::Array); return mutable_arr(); }
  const Object& as_object() const { expect(Type::Object); return *obj_; }
  Object& as_object() { expect(Type::Object); return mutable_obj(); }

  // Object lookup: returns nullptr when absent or when *this is not an object.
  const Value* find(std::string_view key) const {
    if (type_ != Type::Object) return nullptr;
    auto it = obj_->find(key);
    return it == obj_->end() ? nullptr : &it->second;
  }

  // Dotted-path lookup, e.g. at_path("metadata.ownerReferences").
  const Value* at_path(std::string_view path) const;

  // String at key, or fallback when absent/not a string.
  std::string get_string(std::string_view key, std::string_view fallback = "") const {
    const Value* v = find(key);
    return (v && v->is_string()) ? v->as_string() : std::string(fallback);
  }

  // Mutating object set (copy-on-write).
  Value& set(std::string key, Value v) {
    expect(Type::Object);
    mutable_obj()[std::move(key)] = std::move(v);
    return *this;
  }
  Value& push_back(Value v) {
    expect(Type::Array);
    mutable_arr().push_back(std::move(v));
    return *this;
  }

  bool operator==(const Value& other) const;
  bool operator!=(const Value& other) const { return !(*this == other); }

  // Serialize. indent < 0 → compact, otherwise pretty with that indent.
  std::string dump(int indent = -1) const;

  static Value parse(std::string_view text);

 private:
  void expect(Type t) const {
    if (type_ != t) throw std::runtime_error("json: wrong type access");
  }
  Array& mutable_arr() {
    if (arr_.use_count() > 1) arr_ = std::make_shared<Array>(*arr_);
    return *arr_;
  }
  Object& mutable_obj() {
    if (obj_.use_count() > 1) obj_ = std::make_shared<Object>(*obj_);
    return *obj_;
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double dbl_ = 0.0;
  std::shared_ptr<std::string> str_;
  std::shared_ptr<Array> arr_;
  std::shared_ptr<Object> obj_;
};

// Escape a string for embedding in JSON output (without surrounding quotes).
std::string escape(std::string_view s);

// ── arena / zero-copy document ──────────────────────────────────────────
//
// Value::parse builds a shared_ptr-per-string, map-node-per-member DOM —
// fine for config blobs, pathological for the transport hot path, where a
// warm cycle decodes megabytes of pod LIST pages and Prometheus matrices
// per cycle. Doc is the opt-in alternative: one flat preorder node arena
// over an OWNED response buffer, strings as string_views into that buffer
// (escaped strings decode once into a side arena), numbers resolved with
// EXACTLY Value::parse's grammar and int/double rules. Grammar, depth
// limit, duplicate-key semantics (last wins) and error behavior match
// Value::parse — pinned by the decode-parity corpus tests — so a tree
// built via Doc::to_value() is indistinguishable from Value::parse(text).
//
// Consumers hold a DocPtr (shared ownership of buffer + arena) and walk
// Node cursors; the informer store keeps (DocPtr, node) pairs and
// materializes a Value only for the objects a cycle actually touches.
class Doc;
using DocPtr = std::shared_ptr<const Doc>;

// Process-wide opt-in for the Doc-based decode path at the transport hot
// call sites (informer LIST pages + watch events, the Prometheus
// idleness/evidence matrices). Default ON — parity with Value::parse is a
// tested invariant, not a risk — with $TPU_PRUNER_ZERO_COPY_JSON=off /
// `--zero-copy-json off` as the measured-comparison escape hatch.
bool zero_copy_enabled();
void set_zero_copy(bool on);

class Doc {
 public:
  // Parses `body`, taking ownership (nodes view into it). Throws
  // ParseError exactly where Value::parse(body) would.
  static DocPtr parse(std::string body);

  Doc() = default;
  // Releases the node arena into the recycled-arena pool (below).
  ~Doc();
  Doc(const Doc&) = delete;
  Doc& operator=(const Doc&) = delete;

  // Lightweight cursor: (doc, node index). Valid while the Doc lives.
  class Node {
   public:
    Type type() const;
    bool is_null() const { return type() == Type::Null; }
    bool is_bool() const { return type() == Type::Bool; }
    bool is_number() const { return type() == Type::Int || type() == Type::Double; }
    bool is_string() const { return type() == Type::String; }
    bool is_array() const { return type() == Type::Array; }
    bool is_object() const { return type() == Type::Object; }

    bool as_bool() const;
    int64_t as_int() const;      // Value::as_int semantics (Double truncates)
    double as_double() const;    // Value::as_double semantics (Int widens)
    std::string_view as_sv() const;  // string payload, escapes decoded
    std::string as_string() const { return std::string(as_sv()); }

    // Direct children of an array/object (0 otherwise).
    size_t size() const;
    // child(i) walks siblings from the first child — O(i). Hot loops must
    // step with first_child()/next_sibling() instead (O(1) each); the
    // caller bounds the walk by size().
    Node child(size_t i) const;                              // array element i
    std::pair<std::string_view, Node> member(size_t i) const;  // object member i
    Node first_child() const { return Node(doc_, idx_ + 1); }
    Node next_sibling() const;
    std::string_view key() const;  // member key ("" for array elements)

    // Object lookup; like Value::parse's duplicate-key handling, the LAST
    // occurrence of a repeated key wins. nullopt when absent or non-object.
    std::optional<Node> find(std::string_view key) const;
    std::optional<Node> at_path(std::string_view path) const;
    std::string_view get_string(std::string_view key,
                                std::string_view fallback = "") const;

    // Materialize this subtree as a regular Value (identical to what
    // Value::parse would have produced for the same bytes).
    Value to_value() const;

    // Stable handle for re-deriving this node later from a held DocPtr
    // (the informer store keeps (doc, index) pairs): doc->node(index).
    uint32_t index() const { return idx_; }

   private:
    friend class Doc;
    Node(const Doc* doc, uint32_t idx) : doc_(doc), idx_(idx) {}
    const Doc* doc_;
    uint32_t idx_;
  };

  Node root() const { return Node(this, 0); }
  Node node(uint32_t index) const { return Node(this, index); }
  Value to_value() const { return root().to_value(); }
  const std::string& body() const { return body_; }
  size_t node_count() const { return nodes_.size(); }

 private:
  friend class Node;
  friend struct DocParser;  // json.cpp's arena-emitting parser
  struct Rep {
    Type type = Type::Null;
    // Subtree extent: children of a container start at (self+1); the next
    // sibling of node i is nodes_[i].end — one uint32 buys full traversal
    // of the preorder arena without per-child pointers.
    uint32_t end = 0;
    uint32_t count = 0;  // direct children (containers)
    union {
      bool b;
      int64_t i;
      double d;
    };
    // String payload / member key: (offset, len) into body_ or, when the
    // source contained escapes, into decoded_ (flagged).
    uint32_t str_off = 0, str_len = 0;
    uint32_t key_off = 0, key_len = 0;
    bool str_decoded = false, key_decoded = false, has_key = false;
    Rep() : i(0) {}
  };
  std::string_view str_of(const Rep& r) const {
    return std::string_view((r.str_decoded ? decoded_ : body_).data() + r.str_off, r.str_len);
  }
  std::string_view key_of(const Rep& r) const {
    return std::string_view((r.key_decoded ? decoded_ : body_).data() + r.key_off, r.key_len);
  }

  // Recycled-arena hooks (json.cpp): parse draws a pooled node vector,
  // the destructor returns it if the pool budget allows.
  static std::vector<Rep> take_arena();
  static void recycle_arena(std::vector<Rep>&& arena);
  static std::mutex& arena_mutex();
  static std::vector<std::vector<Rep>>& arena_pool();

  std::string body_;     // the response buffer (owned; nodes view into it)
  std::string decoded_;  // side arena for escape-decoded strings
  std::vector<Rep> nodes_;
};

// ── recycled Doc arenas ─────────────────────────────────────────────────
//
// A warm informer cycle parses and drops hundreds of page-sized Docs; the
// node arenas are identical-shaped allocations, so destroyed Docs donate
// their arena capacity to a bounded process-wide pool that Doc::parse
// draws from. The pooled capacity is capped by $TPU_PRUNER_DOC_ARENA_MB
// (default 32; 0 disables recycling) — the daemon's steady-state Doc
// allocation cost becomes O(budget), not O(pages parsed).
struct DocArenaStats {
  uint64_t reuses = 0;   // parses served from the pool
  uint64_t returns = 0;  // arenas accepted back into the pool
  uint64_t drops = 0;    // arenas freed because the pool was at budget
  uint64_t pooled_bytes = 0;
};
DocArenaStats doc_arena_stats();

}  // namespace tpupruner::json
