// tpu-pruner: minimal JSON value, parser, and serializer.
//
// The reference (wseaton/gpu-pruner) leans on serde_json for three jobs:
// decoding Prometheus instant-vector responses (lib.rs:153-187), building
// merge-patch bodies (lib.rs:521, 536-545, 563-572), and constructing K8s
// Event objects (lib.rs:389-427). This module provides the same capability
// natively: a small immutable-ish DOM with strict RFC 8259 parsing and
// deterministic serialization. CR objects (Notebook, InferenceService,
// JobSet) are handled as semi-structured Values rather than 31k lines of
// generated bindings (SURVEY.md §2 #10).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <string_view>
#include <vector>

namespace tpupruner::json {

class Value;
using Array = std::vector<Value>;
// std::map keeps key order deterministic for serialization and tests.
using Object = std::map<std::string, Value, std::less<>>;

enum class Type : uint8_t { Null, Bool, Int, Double, String, Array, Object };

class ParseError : public std::runtime_error {
 public:
  ParseError(const std::string& msg, size_t offset)
      : std::runtime_error(msg + " at offset " + std::to_string(offset)),
        offset_(offset) {}
  size_t offset() const { return offset_; }

 private:
  size_t offset_;
};

class Value {
 public:
  Value() : type_(Type::Null) {}
  Value(std::nullptr_t) : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(int i) : type_(Type::Int), int_(i) {}
  Value(int64_t i) : type_(Type::Int), int_(i) {}
  Value(uint64_t i) : type_(Type::Int), int_(static_cast<int64_t>(i)) {}
  Value(double d) : type_(Type::Double), dbl_(d) {}
  Value(const char* s) : type_(Type::String), str_(std::make_shared<std::string>(s)) {}
  Value(std::string s) : type_(Type::String), str_(std::make_shared<std::string>(std::move(s))) {}
  Value(std::string_view s) : type_(Type::String), str_(std::make_shared<std::string>(s)) {}
  Value(Array a) : type_(Type::Array), arr_(std::make_shared<Array>(std::move(a))) {}
  Value(Object o) : type_(Type::Object), obj_(std::make_shared<Object>(std::move(o))) {}

  static Value array() { return Value(Array{}); }
  static Value object() { return Value(Object{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_bool() const { return type_ == Type::Bool; }
  bool is_number() const { return type_ == Type::Int || type_ == Type::Double; }
  bool is_string() const { return type_ == Type::String; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool() const { expect(Type::Bool); return bool_; }
  int64_t as_int() const {
    if (type_ == Type::Double) return static_cast<int64_t>(dbl_);
    expect(Type::Int);
    return int_;
  }
  double as_double() const {
    if (type_ == Type::Int) return static_cast<double>(int_);
    expect(Type::Double);
    return dbl_;
  }
  const std::string& as_string() const { expect(Type::String); return *str_; }

  const Array& as_array() const { expect(Type::Array); return *arr_; }
  Array& as_array() { expect(Type::Array); return mutable_arr(); }
  const Object& as_object() const { expect(Type::Object); return *obj_; }
  Object& as_object() { expect(Type::Object); return mutable_obj(); }

  // Object lookup: returns nullptr when absent or when *this is not an object.
  const Value* find(std::string_view key) const {
    if (type_ != Type::Object) return nullptr;
    auto it = obj_->find(key);
    return it == obj_->end() ? nullptr : &it->second;
  }

  // Dotted-path lookup, e.g. at_path("metadata.ownerReferences").
  const Value* at_path(std::string_view path) const;

  // String at key, or fallback when absent/not a string.
  std::string get_string(std::string_view key, std::string_view fallback = "") const {
    const Value* v = find(key);
    return (v && v->is_string()) ? v->as_string() : std::string(fallback);
  }

  // Mutating object set (copy-on-write).
  Value& set(std::string key, Value v) {
    expect(Type::Object);
    mutable_obj()[std::move(key)] = std::move(v);
    return *this;
  }
  Value& push_back(Value v) {
    expect(Type::Array);
    mutable_arr().push_back(std::move(v));
    return *this;
  }

  bool operator==(const Value& other) const;
  bool operator!=(const Value& other) const { return !(*this == other); }

  // Serialize. indent < 0 → compact, otherwise pretty with that indent.
  std::string dump(int indent = -1) const;

  static Value parse(std::string_view text);

 private:
  void expect(Type t) const {
    if (type_ != t) throw std::runtime_error("json: wrong type access");
  }
  Array& mutable_arr() {
    if (arr_.use_count() > 1) arr_ = std::make_shared<Array>(*arr_);
    return *arr_;
  }
  Object& mutable_obj() {
    if (obj_.use_count() > 1) obj_ = std::make_shared<Object>(*obj_);
    return *obj_;
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double dbl_ = 0.0;
  std::shared_ptr<std::string> str_;
  std::shared_ptr<Array> arr_;
  std::shared_ptr<Object> obj_;
};

// Escape a string for embedding in JSON output (without surrounding quotes).
std::string escape(std::string_view s);

}  // namespace tpupruner::json
