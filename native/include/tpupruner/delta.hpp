// Delta federation protocol: the member-side change journal behind
// /debug/delta and the hub-side cursor/apply state machine.
//
// PR 10 made the daemon's warm cycle O(churn) with a dirty journal; the
// fleet layer never got the same treatment — `tpu-pruner hub` re-polled
// every member's FULL /debug/{workloads,signals,decisions} snapshot every
// interval, so hub cost grew as O(members x fleet-size) even when nothing
// changed. This module applies the daemon's own trick at the fleet layer:
//
//   Member side (Journal): each cycle end the daemon snapshots its three
//   debug surfaces and journals row-level changes under a process-wide
//   monotonic epoch — the same epoch discipline the ledger's checkpoint
//   lines already carry, extended to every surface. A hub polls
//   /debug/delta?since=<epoch>&gen=<generation> and receives only what
//   changed; a quiesced member answers with a ~100-byte header. The
//   journal's change log is BOUNDED (TPU_PRUNER_DELTA_JOURNAL_CAP, def
//   4096 row-changes): a cursor that has aged out of the window — or a
//   generation mismatch after a member restart — forces a clean
//   full-snapshot resync carried inline in the same response, mirroring
//   the informer's 410→relist semantics (and like the informer's
//   coalescing rules, deltas are latest-state per key: N changes to one
//   row between polls ship once).
//
//   Hub side (DeltaState + apply_delta): a per-member cursor plus the row
//   maps needed to reconstruct each member's debug documents EXACTLY as a
//   full-snapshot poll would have parsed them — merged fleet views are
//   byte-identical across --fleet-delta on|off by construction.
//
// The journal is LAZY: it costs nothing (no per-cycle render/diff) until
// the first /debug/delta request activates it, so a daemon that is not
// federated never pays for the protocol.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::delta {

// The journaled surfaces, in canonical order. "capacity" (PR 18) is only
// present on daemons running --capacity on; members without it simply
// never journal the surface, and hubs merge whatever subset arrives.
inline constexpr const char* kSurfaces[] = {"workloads", "signals", "decisions",
                                            "capacity"};

// Current-document providers (the same renderers the /debug endpoints
// serve). A null provider means the surface is absent for this process.
struct Renderers {
  std::function<json::Value()> workloads;
  std::function<json::Value()> signals;
  std::function<json::Value()> decisions;
  std::function<json::Value()> capacity;
};

class Journal {
 public:
  Journal();

  void set_renderers(Renderers r);
  // Change-log bound (row-changes retained). Also read from
  // $TPU_PRUNER_DELTA_JOURNAL_CAP at construction; this overrides.
  void set_log_cap(size_t cap);

  // True once a /debug/delta request has been seen: the daemon only
  // renders + diffs its surfaces per cycle while someone is listening.
  bool active() const;

  // Snapshot the surfaces through the renderers and journal the changes
  // under a fresh epoch (one epoch per publish that changed anything).
  // Cheap no-op until active(). Thread-safe; wakes long-pollers.
  void publish();

  // Serve one /debug/delta request. `query` is the raw query string
  // (since=<epoch>&gen=<generation>&wait_ms=<ms>); `abort` is polled
  // ~5x/s while long-polling (server shutdown seam). Activates the
  // journal (and self-primes from the renderers) on first use.
  std::string handle_request(const std::string& query,
                             const std::function<bool()>& abort);

  // Release any long-poll waiters immediately (daemon shutdown).
  void wake_all();

  uint64_t epoch() const;
  std::string generation() const;

  void reset_for_test();

 private:
  struct WorkloadsState {
    bool have = false;
    uint64_t meta_epoch = 0;
    uint64_t meta_fp = 0;
    json::Value meta;                               // doc minus "workloads"
    std::map<std::string, uint64_t> row_epoch;      // key → epoch last changed
    std::map<std::string, uint64_t> row_fp;         // key → row fingerprint
    std::map<std::string, json::Value> rows;        // key → row (latest)
    std::map<std::string, uint64_t> removed;        // key → epoch removed
  };
  struct SignalsState {
    bool have = false;
    uint64_t doc_epoch = 0;
    uint64_t fp = 0;
    json::Value doc;
  };
  // The capacity inventory ships whole-document-on-change like signals:
  // the document is small (one row per slice) and its totals are
  // cross-coupled, so row-level diffing buys nothing.
  struct CapacityState {
    bool have = false;
    uint64_t doc_epoch = 0;
    uint64_t fp = 0;
    json::Value doc;
  };
  struct DecisionsState {
    bool have = false;
    int64_t capacity = 0;
    int64_t dropped = 0;
    uint64_t appended_total = 0;                    // dropped + ring length
    uint64_t meta_epoch = 0;
    uint64_t meta_fp = 0;
    json::Value meta;                               // doc minus "decisions"
    std::deque<std::pair<uint64_t, json::Value>> ring;  // (epoch, record)
  };

  void publish_locked();
  void note_change_locked(uint64_t epoch);
  std::string build_response_locked(int64_t since, bool resync, bool first);
  json::Value full_docs_locked() const;

  mutable std::mutex mutex_;
  std::condition_variable cv_;
  Renderers renderers_;
  std::string gen_;
  uint64_t epoch_ = 0;
  // Oldest `since` the change log can still answer; a smaller cursor has
  // aged out of the window and must resync.
  uint64_t min_since_ = 0;
  size_t log_cap_ = 4096;
  std::deque<uint64_t> log_;  // epoch per retained row-change (bound bookkeeping)
  bool active_ = false;
  bool primed_ = false;
  WorkloadsState wl_;
  SignalsState sig_;
  DecisionsState dec_;
  CapacityState cap_;
};

// Process-wide journal (the daemon's). The hub builds its own instance
// for its rollup surfaces.
Journal& journal();

// ── hub side ──

// A member's three debug documents as the hub holds them.
struct MemberDocs {
  json::Value workloads, signals, decisions, capacity;
};

// Per-member delta cursor + reconstruction state.
struct DeltaState {
  bool primed = false;     // a full snapshot (resync or first poll) landed
  std::string gen;
  uint64_t epoch = 0;
  // workloads reconstruction
  json::Value wl_meta;
  std::map<std::string, json::Value> wl_rows;  // key → row
  // decisions reconstruction (ring semantics)
  std::deque<json::Value> dec_ring;
  int64_t dec_capacity = 0;
  int64_t dec_dropped = 0;
  json::Value signals;
  json::Value capacity;
};

// Result of applying one /debug/delta response.
struct ApplyResult {
  bool ok = false;       // response parsed and applied
  bool resync = false;   // the member forced (or served) a full snapshot
  bool changed = false;  // any surface changed (epoch advanced or resync)
};

// Apply one parsed /debug/delta response body to the member state and
// rebuild `out` — documents EQUAL to what a full-snapshot poll of the
// member would have parsed (fleet::aggregate consumes either
// interchangeably). Malformed responses return ok=false and leave the
// state untouched; the caller falls back to snapshot polling.
ApplyResult apply_delta(DeltaState& st, const json::Value& resp, MemberDocs& out);

// The hub-side query string for the next poll given the member state
// ("since=-1" before the first snapshot). wait_ms==0 omits the long-poll
// parameter (plain poll).
std::string cursor_query(const DeltaState& st, int64_t wait_ms);

}  // namespace tpupruner::delta
