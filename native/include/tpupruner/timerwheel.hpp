// Hierarchical timer wheel + sliding-window token buckets — the event
// engine's time plane.
//
// Under --reconcile cycle, per-root deadlines (min-age expiry, lookback
// boundaries, anti-entropy) are implicit: every cycle re-scans everything,
// so "check again later" costs a full recompute per interval. Event mode
// has no periodic re-scan to hide behind, so deadlines become explicit
// entries in a hierarchical wheel (the kernel-timer shape: O(1) schedule/
// cancel, expiries cascade down levels as time advances) and the
// dispatcher sleeps until the earliest of {watch event, sample probe,
// next timer}. Cross-evaluation gates (--max-scale-per-cycle) become
// sliding-window token buckets: the same budget the per-cycle breaker
// enforced, measured over one --check-interval window instead of one
// cycle, with the same DEFERRED audit reason.
//
// Both structures are deterministic given the injected clock (callers
// pass now_ms; nothing here reads the wall clock) so the simulator seam
// (capi tp_timerwheel_sim) can drive them from tests byte-for-byte.
#pragma once

#include <cstdint>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::timerwheel {

// ── hierarchical wheel ──
//
// kLevels levels of kSlots slots each; level 0 slots span kTickMs, each
// higher level spans kSlots x the level below. An entry lands in the
// coarsest level whose horizon contains it and cascades toward level 0 as
// advance() moves the clock, so a far-future deadline costs one slot hop
// per level, not a per-tick re-sort. Keys are caller identities (root
// paths); re-scheduling a key replaces its previous deadline.
class Wheel {
 public:
  static constexpr int kLevels = 4;
  static constexpr int kSlots = 64;
  static constexpr int64_t kTickMs = 64;

  explicit Wheel(int64_t origin_ms = 0);

  // Arm (or re-arm) `key` to fire at `due_ms`. A due time at or before
  // the current clock fires on the next advance().
  void schedule(const std::string& key, int64_t due_ms);
  // Disarm; false when the key was not scheduled.
  bool cancel(const std::string& key);
  // Move the clock to now_ms and collect every entry whose deadline
  // passed, ordered by (due_ms, key) so expiry order is deterministic
  // regardless of slot layout.
  std::vector<std::string> advance(int64_t now_ms);
  // Earliest armed deadline, or -1 when the wheel is empty — the
  // dispatcher's sleep bound.
  int64_t next_due() const;
  size_t size() const;
  // /debug/timers: clock, per-level occupancy, lifetime counters.
  json::Value stats_json() const;

 private:
  struct Entry {
    int64_t due_ms = 0;
    int level = 0;
    int slot = 0;
  };
  // Place an entry into the right (level, slot) for its distance from
  // the current clock. Caller holds the lock.
  void place(const std::string& key, int64_t due_ms);

  mutable std::mutex mu_;
  int64_t now_ms_ = 0;
  std::unordered_map<std::string, Entry> entries_;
  // slots_[level][slot] → keys parked there (unsorted; advance sorts).
  std::vector<std::vector<std::vector<std::string>>> slots_;
  uint64_t scheduled_total_ = 0;
  uint64_t fired_total_ = 0;
  uint64_t cancelled_total_ = 0;
  uint64_t cascades_total_ = 0;
};

// ── sliding-window token bucket ──
//
// Exact sliding-window log (not a leaky-bucket approximation): a grant
// timestamp ages out of the window after window_ms, so "at most N pauses
// per --check-interval" holds over EVERY window position — strictly
// tighter than the per-cycle breaker it replaces, never looser.
class TokenBucket {
 public:
  // capacity 0 = unlimited (mirrors --max-scale-per-cycle 0).
  TokenBucket(int64_t capacity, int64_t window_ms);

  // Take one token at now_ms; false when the window is saturated.
  bool try_acquire(int64_t now_ms);
  // Tokens still grantable at now_ms (INT64_MAX when unlimited).
  int64_t available(int64_t now_ms) const;
  json::Value stats_json() const;

 private:
  void expire(int64_t now_ms) const;

  mutable std::mutex mu_;
  int64_t capacity_;
  int64_t window_ms_;
  mutable std::vector<int64_t> grants_;  // in-window grant times, oldest first
  uint64_t granted_total_ = 0;
  uint64_t denied_total_ = 0;
};

}  // namespace tpupruner::timerwheel
