// Lease-based leader election (coordination.k8s.io/v1).
//
// No reference analog — the reference runs a single replica and relies on
// crash-only restarts. With --leader-elect, operators can run 2+ replicas
// for fast failover: exactly one runs evaluation cycles; standbys renew
// their candidacy and take over when the holder's lease expires.
//
// Semantics (the standard K8s leader-election recipe, client-go style):
// - the Lease object's spec.holderIdentity names the leader;
// - the holder renews spec.renewTime every leaseDuration/3;
// - a candidate takes over iff the lease RECORD (holder, renewTime) has
//   remained unchanged for > leaseDuration by the candidate's own
//   monotonic clock — never by comparing the holder's wall-clock
//   renewTime against the local wall clock, which cross-replica skew
//   would break — using a resourceVersion-preconditioned patch so racing
//   candidates can't both win (the API server 409s the loser);
// - a leader that cannot reach the API server demotes itself once
//   leaseDuration passes without a successful renew (a standby will have
//   taken over by then), bounding dual-leadership to one lease window;
// - losing the lease mid-cycle lets the cycle finish: every action is an
//   idempotent patch, so a brief dual-leader overlap is harmless
//   (duplicate Events at worst) — the same argument as stateless resume.
#pragma once

#include <atomic>
#include <chrono>
#include <optional>
#include <string>
#include <thread>

#include "tpupruner/k8s.hpp"

namespace tpupruner::leader {

struct Options {
  std::string lease_ns = "tpu-pruner";   // --lease-namespace
  std::string lease_name = "tpu-pruner"; // --lease-name
  std::string identity;                  // default: $POD_NAME or host-pid
  int64_t lease_duration_s = 15;
};

class Elector {
 public:
  // Starts the renew thread immediately; is_leader() flips as acquisition
  // succeeds/fails. `client` must outlive the Elector.
  Elector(const k8s::Client& client, Options opts);
  ~Elector();  // stops the thread; best-effort lease release when leading

  bool is_leader() const { return is_leader_.load(); }
  const std::string& identity() const { return opts_.identity; }

  // One acquisition/renewal attempt (exposed for tests; the thread calls
  // this every lease_duration/3). Returns the new leadership state.
  bool try_acquire_or_renew();

 private:
  void release();

  const k8s::Client& client_;
  Options opts_;
  std::string lease_path_;
  std::atomic<bool> is_leader_{false};
  std::atomic<bool> stop_{false};
  // Local (monotonic) observation of the remote record, client-go style:
  // expiry is judged by how long the record stayed unchanged on OUR clock.
  std::string observed_record_;
  std::chrono::steady_clock::time_point observed_at_{};
  // Last successful acquire/renew on our clock — the self-demotion deadline.
  std::optional<std::chrono::steady_clock::time_point> last_renew_ok_;
  std::thread thread_;
};

}  // namespace tpupruner::leader
