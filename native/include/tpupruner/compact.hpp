// Compact interned pod store (PR 14).
//
// The informer's three entry representations (materialized Value, arena
// Doc node, aliased proto slice) all retain far more bytes per pod than
// the walker/actuator/ledger/capsule path ever reads. Behind
// `--compact-store on` the store decodes pods straight into a packed
// PodRecord: interned refs for the fleet-repeated strings (namespaces,
// kinds, apiVersions, owner-ref kinds, label/annotation/resource keys,
// node names), one per-record byte blob for everything else, presence
// bits for every optional field. Materialization back to a json::Value
// is lazy, memoized, and byte-identical to what the JSON/proto decode
// paths would have produced — a record is only built when the object
// conforms to the decoder subset exactly, so `dump()` of the
// materialized Value equals `dump()` of the original parse.
#pragma once

#include <atomic>
#include <cstdint>
#include <optional>
#include <string>
#include <string_view>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::compact {

// ── process-wide toggle ──
//
// Same contract as proto::wire_mode/json::zero_copy: lazily initialized
// from $TPU_PRUNER_COMPACT_STORE (on|off, default on — parity with the
// exact representations is a tested invariant, not a risk), overridden
// by the daemon's --compact-store flag before any client is constructed.
bool enabled();
void set_enabled(bool on);

// ── intern table ──
//
// Thread-safe, append-only, FNV-sharded. Ids are stable for the process
// lifetime (records hold them forever), so there is no erase. intern()
// and str() are safe to call concurrently from the cold-sync pool
// workers and the watch threads.
class Interner {
 public:
  uint32_t intern(std::string_view s);
  // The returned view points at an immutable, never-moved string and
  // stays valid for the process lifetime.
  std::string_view str(uint32_t id) const;
  uint64_t count() const { return count_.load(std::memory_order_relaxed); }
  uint64_t bytes() const { return bytes_.load(std::memory_order_relaxed); }

  Interner();
  ~Interner();
  Interner(const Interner&) = delete;
  Interner& operator=(const Interner&) = delete;

 private:
  static constexpr size_t kShards = 16;
  struct Shard;  // compact.cpp: mutex + map + stable string deque
  Shard* shards_;
  std::atomic<uint64_t> count_{0};
  std::atomic<uint64_t> bytes_{0};
};

Interner& interner();

// ── packed pod record ──

// (offset, length) into PodRecord::blob.
struct Str {
  uint32_t off = 0;
  uint32_t len = 0;
};

// One label/resource-map entry: interned key AND value — label values
// (app names, zones, template hashes) and resource quantities repeat
// across the fleet as much as their keys do.
struct KV {
  uint32_t key = 0;
  uint32_t val = 0;
};

// One annotation entry: interned key, blob value. Annotation values are
// frequently per-object-unique (applied configs, checksums) and the
// intern table never frees, so they stay record-local and die with the
// record.
struct AnnKV {
  uint32_t key = 0;
  Str value;
};

struct OwnerRec {
  enum : uint8_t {
    kKind = 1u << 0,
    kName = 1u << 1,
    kUid = 1u << 2,
    kApiVersion = 1u << 3,
    kController = 1u << 4,
    kControllerVal = 1u << 5,
    kBlockOwnerDeletion = 1u << 6,
    kBlockOwnerDeletionVal = 1u << 7,
  };
  uint8_t present = 0;
  uint32_t kind = 0;         // interned
  uint32_t api_version = 0;  // interned
  Str name, uid;
};

struct ContainerRec {
  enum : uint8_t {
    kName = 1u << 0,
    kImage = 1u << 1,
    kResources = 1u << 2,
    kLimits = 1u << 3,
    kRequests = 1u << 4,
  };
  uint8_t present = 0;
  Str name, image;
  std::vector<KV> limits, requests;  // key = interned resource name
};

struct PodRecord {
  enum : uint32_t {
    kApiVersion = 1u << 0,
    kKind = 1u << 1,
    kMetadata = 1u << 2,
    kSpec = 1u << 3,
    kStatus = 1u << 4,
    kName = 1u << 5,
    kGenerateName = 1u << 6,
    kNamespace = 1u << 7,
    kSelfLink = 1u << 8,
    kUid = 1u << 9,
    kResourceVersion = 1u << 10,
    kCreationTs = 1u << 11,
    kLabels = 1u << 12,
    kAnnotations = 1u << 13,
    kOwners = 1u << 14,
    kContainers = 1u << 15,
    kNodeName = 1u << 16,
    kPhase = 1u << 17,
    kMessage = 1u << 18,
    kReason = 1u << 19,
  };
  uint32_t present = 0;
  // Interned refs (valid only when the matching presence bit is set).
  uint32_t ns = 0, api_version = 0, kind = 0, node_name = 0;
  // Inline strings (blob slices).
  Str name, generate_name, self_link, uid, resource_version, creation_ts,
      phase, message, reason;
  std::vector<KV> labels;
  std::vector<AnnKV> annotations;
  std::vector<OwnerRec> owners;
  std::vector<ContainerRec> containers;
  // Reserved TPU+GPU chips summed over containers (max of request/limit
  // per container, matching core's "either alone reserves" rule).
  uint32_t chips = 0;
  std::string blob;

  std::string_view view(const Str& s) const {
    return std::string_view(blob.data() + s.off, s.len);
  }
  Str append(std::string_view s) {
    Str out{static_cast<uint32_t>(blob.size()), static_cast<uint32_t>(s.size())};
    blob.append(s.data(), s.size());
    return out;
  }

  // Materialize the exact Value the JSON/proto decode of the source
  // object would have produced (construction mirrors
  // proto::object_to_value field-for-field; json::Object sorts keys, so
  // dump() is deterministic).
  json::Value to_value() const;
  // Approximate retained heap bytes (struct + blob + vectors).
  size_t bytes() const;
  // Drop slack capacity after building (records live for a long time).
  void shrink();
  // Post-build pass shared by both builders: compute `chips` from the
  // container resource maps and shrink slack capacity.
  void finish();
};

// Build a record from a materialized Value. Returns nullopt when the
// object falls outside the decoder subset (any unknown key, non-string
// scalar, null, nested structure the record cannot carry) — the caller
// keeps the exact representation instead. Round-trip is exact by
// construction for every accepted object.
std::optional<PodRecord> record_from_value(const json::Value& v);

// Build a record straight from a protobuf object payload (the slice a
// LIST page / watch frame carries). Mirrors proto::object_to_value
// byte-for-byte; throws json::ParseError exactly where it would.
// Implemented in proto.cpp (shares the wire Reader).
PodRecord record_from_proto(std::string_view bytes, const std::string& api_version,
                            const std::string& kind);

// ── store gauges / cold-sync telemetry ──
//
// The informer updates these process-wide aggregates; the daemon's
// /metrics provider renders them. Kept here (not in informer state) so
// rendering needs no back-reference into live caches.
void add_store_bytes(int64_t delta);
void add_store_pods(int64_t delta);
uint64_t store_bytes();
uint64_t store_pods();
// Record one cold LIST→synced duration for `resource` (plural).
void note_cold_sync(const std::string& resource, double seconds, uint64_t objects);
// Last cold-sync duration for `resource`, or negative when none yet.
double last_cold_sync_seconds(const std::string& resource);

// Canonical family list + Prometheus exposition (text or OpenMetrics),
// appended to the daemon's /metrics by the extra-metrics provider.
std::vector<std::string> store_metric_families();
std::string render_store_metrics(bool openmetrics);

// Test hook: clears the toggle cache and the store gauges (NOT the
// intern table — ids embedded in live records must stay valid).
void reset_for_test();

}  // namespace tpupruner::compact
