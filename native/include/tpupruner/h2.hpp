// Shared HTTP/2 transport layer.
//
// PR 1 grew a working h2 framing + HPACK stack inside otlp_grpc.cpp for
// the gRPC exporter; until now the daemon's HOT traffic — the informer's
// LIST+watch streams, the per-cycle idleness+evidence query pair, and
// consumer scale patches — still rode one-request-per-connection-ish
// HTTP/1.1 (http.cpp). This header factors that layer out into two
// surfaces:
//
//   1. Wire primitives (frame headers, HPACK literal encode, HPACK +
//      huffman decode) shared by the multiplexing client below AND by
//      otlp_grpc.cpp's single-stream gRPC state machine (rebased onto
//      these instead of its private copies).
//
//   2. h2::Transport — a drop-in replacement for http::Client that
//      multiplexes every request to one endpoint over ONE connection as
//      concurrent h2 streams (per-stream idle deadlines, GOAWAY /
//      dead-connection retry), with transparent HTTP/1.1 fallback:
//        - https: ALPN-negotiated ({"h2","http/1.1"} offered; the
//          server's pick decides),
//        - cleartext http: prior-knowledge probe (client preface +
//          SETTINGS; a peer that answers with anything but an h2
//          SETTINGS frame is remembered as http1 and the request is
//          re-issued through the pooled HTTP/1.1 client).
//      Mode::Http1 bypasses h2 entirely — the exact-parity escape hatch
//      behind the daemon's `--transport http1`.
//
// Reference analog: hyper's auto-negotiating client pool under kube-rs /
// reqwest — one h2 connection per host carrying watches and GETs
// side by side — which the hand-rolled HTTP/1.1 client could not express.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <string_view>
#include <vector>

#include "tpupruner/http.hpp"

namespace tpupruner::h2 {

// ── wire primitives (shared with otlp_grpc.cpp) ─────────────────────────

// Frame types / flags (RFC 7540 §6, §4.1).
constexpr uint8_t kFrameData = 0x0, kFrameHeaders = 0x1, kFrameRst = 0x3,
                  kFrameSettings = 0x4, kFramePing = 0x6, kFrameGoaway = 0x7,
                  kFrameWindowUpdate = 0x8, kFrameContinuation = 0x9;
constexpr uint8_t kFlagEndStream = 0x1, kFlagAck = 0x1, kFlagEndHeaders = 0x4,
                  kFlagPadded = 0x8, kFlagPriority = 0x20;

constexpr const char* kClientPreface = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

// 9-byte frame header.
std::string frame_header(size_t len, uint8_t type, uint8_t flags, uint32_t stream);

// HPACK "literal header field without indexing — new name", both strings
// raw (huffman bit 0). Always legal regardless of table state (RFC 7541
// §6.2.2); names must already be lowercase.
void hpack_literal(std::string& out, std::string_view name, std::string_view value);

struct Header {
  std::string name, value;
  bool huffman_value = false;  // huffman-coded AND undecodable (opaque)
};

// Decode one HPACK header block (static table + literals; dynamic-table
// references are tolerated as unknowns — we advertise table size 0).
// Returns false on malformed input.
bool hpack_decode(std::string_view block, std::vector<Header>& out);

// RFC 7541 §5.2 huffman string decode. False on decoding errors.
bool huffman_decode(std::string_view in, std::string& out);

// A SETTINGS payload: {HEADER_TABLE_SIZE: 0, ENABLE_PUSH: 0} plus the
// given INITIAL_WINDOW_SIZE when > 0 (0 keeps the protocol default).
std::string settings_payload(uint32_t initial_window);

// ── process-wide transport counters ─────────────────────────────────────
// Bumped by both this client and http.cpp's pooled HTTP/1.1 client, and
// served as /metrics families (render_transport_metrics) so the bench can
// read connections_opened before/after a warm cycle.
struct TransportCounters {
  std::atomic<uint64_t> h2_connections{0};     // h2 connections established
  std::atomic<uint64_t> http1_connections{0};  // HTTP/1.1 connections opened
  std::atomic<uint64_t> h2_streams_total{0};   // h2 request streams opened
  std::atomic<int64_t> streams_active{0};      // h2 streams currently open
  std::atomic<uint64_t> h2_fallbacks{0};       // endpoints demoted to http1
  std::atomic<uint64_t> retries{0};            // GOAWAY/dead-conn h2 retries
};
TransportCounters& counters();

// Canonical transport family names served on /metrics — the docs
// drift-guard joins this list against OPERATIONS.md.
std::vector<std::string> transport_metric_families();
// Exposition text for those families (extra-metrics provider shape).
std::string render_transport_metrics(bool openmetrics);

// ── the multiplexing client ─────────────────────────────────────────────

enum class Mode { Auto, H2, Http1 };
// "auto" | "h2" | "http1"; throws std::runtime_error on anything else.
Mode mode_from_string(const std::string& s);
const char* mode_name(Mode m);

// Process-wide default for clients constructed without an explicit mode
// (k8s::Client, prom::Client). Initialized lazily from
// $TPU_PRUNER_TRANSPORT (auto|h2|http1; default auto); the daemon's
// `--transport` flag overrides it at startup, before any client exists.
Mode default_mode();
void set_default_mode(Mode m);

namespace detail {
class Conn;  // one multiplexed h2 connection (internal)
}

class Transport {
 public:
  explicit Transport(Mode mode, http::TlsMode tls_mode = http::TlsMode::Verify,
                     std::string ca_file = "");
  ~Transport();
  Transport(Transport&&) noexcept;
  Transport& operator=(Transport&&) = delete;

  // Same contract as http::Client::request — HTTP statuses returned,
  // transport errors thrown — but requests to an h2 endpoint share one
  // connection as concurrent streams. req.timeout_ms is a per-stream
  // IDLE deadline over h2 (reset by any frame for the stream), matching
  // the HTTP/1.1 client's per-socket-wait semantics.
  http::Response request(const http::Request& req) const;

  // Streaming request (K8s watch shape; see http::Client::request_stream
  // for the callback contract). Over h2 the stream multiplexes onto the
  // endpoint's shared connection instead of monopolizing a socket —
  // the point of this refactor.
  http::Response request_stream(
      const http::Request& req, const std::function<bool(const char*, size_t)>& on_data,
      const std::function<bool()>& abort = nullptr,
      const std::function<void(const http::Response&)>& on_headers = nullptr) const;

  void set_default_traceparent(std::string tp) const;

  // Protocol this transport is using for the URL's endpoint:
  // "h2" | "http1" | "unknown" (not yet contacted).
  std::string protocol_for(const std::string& url) const;

  Mode mode() const { return mode_; }

 private:
  struct Endpoint;
  std::shared_ptr<Endpoint> endpoint_for(const std::string& key) const;
  std::string resolved_traceparent(const http::Request& req) const;
  http::Response dispatch(const http::Request& req,
                          const std::function<bool(const char*, size_t)>* on_data,
                          const std::function<bool()>* abort,
                          const std::function<void(const http::Response&)>* on_headers) const;

  Mode mode_;
  http::TlsMode tls_mode_;
  std::string ca_file_;
  http::Client http1_;  // fallback + Mode::Http1 path (owns its own pool)
  mutable std::mutex mutex_;
  mutable std::map<std::string, std::shared_ptr<Endpoint>> endpoints_;
  mutable std::mutex traceparent_mutex_;
  mutable std::string default_traceparent_;
};

}  // namespace tpupruner::h2
