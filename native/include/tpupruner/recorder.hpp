// Cycle flight recorder + deterministic replay / what-if engine.
//
// The audit trail (audit.hpp) made the *outputs* of each cycle queryable
// and the ledger (ledger.hpp) made their cost visible — but the *inputs*
// died with the cycle: once a reconcile ends, the raw Prometheus evidence,
// the watch-store objects the owner walk consulted, and the config that
// produced a scale-down are gone, so a 3am "why did you pause my JobSet?"
// can only be answered from derived records, and a threshold change can
// only be validated live. The recorder captures one self-contained
// CycleCapsule per cycle:
//
//   - the rendered PromQL and the VERBATIM Prometheus response body,
//   - a config fingerprint (query args, lookback, run mode, enabled
//     kinds, breaker limit, watch-cache mode),
//   - per-candidate pod evidence (the Pod JSON as consulted, store-miss /
//     fetch-error facts) and per-pod owner-walk results,
//   - the owner/root objects the walk touched (the FetchCache snapshot),
//   - cycle facts that are cluster state, not config: veto sets, group
//     all-idle verdicts, breaker deferrals, consumer actuation outcomes,
//   - and the final DecisionRecords (captured via the audit sink).
//
// Capsules persist to a bounded on-disk ring (--flight-dir, --flight-keep;
// atomic tmp+rename writes; the index is rebuilt from the directory on
// restart) and are served at /debug/cycles (index) and /debug/cycles/<id>
// (full capsule) on the metrics port.
//
// replay() re-runs decode → eligibility → owner walk → target gates
// purely from capsule contents — zero network — and asserts the replayed
// decisions reproduce the recorded ones bit-for-bit (reason codes, roots,
// actions). A what-if overlay ({"lookback": "10m", ...}) re-decides under
// altered config and reports exactly which decisions flip. Facts that
// depend on cluster state the capsule can't re-derive (veto sets, group
// verdicts, actuation results) are held fixed; what-if flips that reach
// actuation are reported as predicted.
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

#include "tpupruner/json.hpp"
#include "tpupruner/ledger.hpp"

namespace tpupruner::recorder {

// ── lifecycle / configuration ──
// Enable the on-disk ring. `dir` is created when missing; existing
// cycle-*.json capsules are reloaded into the index (then pruned to
// `keep`). "" disables capture entirely — every hook becomes a no-op.
void configure(const std::string& dir, int keep);
bool enabled();

// Static per-run context: the config fingerprint (see capsule schema in
// recorder.cpp), the rendered idle query, and (with --signal-guard on)
// the rendered evidence query — identical for every cycle of the process.
void set_run_context(json::Value config, std::string query, std::string evidence_query = "");

// ── per-cycle capture hooks (all no-ops while disabled) ──
// Opens the cycle's capsule; also drops any stale capsule of an earlier
// cycle that never reached arm() (a failed query leaves one behind).
void begin_cycle(uint64_t cycle, int64_t ts_unix);
void record_prom_body(uint64_t cycle, const std::string& body);
// The signal watchdog's VERBATIM evidence-query response body — replay
// re-derives every per-pod verdict from these bytes, bit-for-bit.
void record_evidence_body(uint64_t cycle, const std::string& body);
// The derived assessment (signal::assessment_to_json) — stamped for
// forensics (`analyze --signal-report <capsule>`); replay recomputes it
// from the evidence body rather than trusting the stamp.
void record_signal(uint64_t cycle, json::Value assessment);
// The eligibility clock resolve_pods used (util::now_unix at resolve
// start) — replay feeds it back into core::check_eligibility.
void record_resolve_now(uint64_t cycle, int64_t now_unix);
// Per-candidate pod acquisition evidence. `pod` nullptr = absent;
// `fetch_error` non-empty = the GET threw (namespace veto follows).
void record_pod(uint64_t cycle, const std::string& key, const json::Value* pod,
                bool store_missed, const std::string& fetch_error);
// Per-pod owner-walk result: either a resolved root or the walk error.
void record_resolution(uint64_t cycle, const std::string& key,
                       const std::vector<std::string>& chain,
                       const std::string& root_kind, const std::string& root_ns,
                       const std::string& root_name, const std::string& identity,
                       const std::string& error);
// One owner/root object the walk consulted (FetchCache snapshot entry);
// nullptr records a cached miss (404) explicitly.
void record_object(uint64_t cycle, const std::string& path, const json::Value* object);
// The cycle's ledger feed, verbatim: the clock and per-root observations
// passed to ledger::observe_cycle. The policy gym integrates savings from
// exactly these inputs, so its baseline policy reproduces the live
// ledger's reclaimed chip-seconds bit-for-bit on the recording run's own
// capsules.
void record_ledger(uint64_t cycle, int64_t now_unix,
                   const std::vector<ledger::Observation>& observations);
// Differential-engine provenance: the cycle's dirty set + cache-hit
// counts (incremental::Engine::provenance_json). Pure metadata — replay
// recomputes in full and never reads it; byte-identity comparisons
// between --incremental modes normalize the "incremental" key away.
void record_incremental(uint64_t cycle, json::Value provenance);
// Capacity observatory stamp (--capacity on): the canonical {inputs, doc}
// pair — inputs via capacity::inputs_json (order-normalized), doc the
// PURE capacity::build output (no cluster/cycle keys). `analyze
// --capacity-report` recomputes doc from inputs and flags byte drift.
void record_capacity(uint64_t cycle, json::Value stamp);
// Event-engine provenance (--reconcile event): which trigger (dirty watch
// burst, sample-flip probe, timer-wheel expiry, anti-entropy pass) opened
// this logical capsule. Pure metadata like the incremental stamp — replay
// never reads it, and byte-identity comparisons between --reconcile modes
// normalize the "reconcile" key away. Never written in cycle mode, so
// cycle-mode capsules are byte-identical to pre-event builds.
void record_reconcile(uint64_t cycle, json::Value info);
// Normalized action-provenance trace stamp (--trace on): {trace_id,
// trigger, root_start_nanos, spans-so-far} from trace::capsule_stamp —
// the trace-id ↔ capsule cross-link `analyze --trace` joins on. Pure
// provenance like the incremental/reconcile stamps: replay never reads
// it, cross-mode byte-identity diffs normalize the key away, and it is
// never written with --trace off (capsules stay byte-identical).
void record_trace(uint64_t cycle, json::Value stamp);
// Cycle facts: fail-closed veto sets, per-root gate flags, breaker stamp.
void record_vetoes(uint64_t cycle, const std::vector<std::string>& vetoed_roots,
                   const std::vector<std::pair<std::string, std::string>>& vetoed_namespaces);
// `flag` ∈ {"root_opted_out", "group_not_idle", "slice_shared_busy",
// "hysteresis_hold", "deferred", "signal_brownout"}.
void flag_root(uint64_t cycle, const std::string& identity, const char* flag);
void record_breaker(uint64_t cycle, int64_t limit, size_t actionable, size_t deferred);
void record_stats(uint64_t cycle, size_t num_series, size_t num_pods,
                  size_t shutdown_events);
// Final DecisionRecord (verbatim JSON) — wired as the audit record sink.
void record_decision(uint64_t cycle, json::Value decision);
// Arm the capsule for `expected` consumer actuations; 0 seals immediately
// (dry-run / no-candidate cycles). Each counting record_actuation
// decrements and the last one seals (writes the capsule to the ring);
// consumer outcomes that land BEFORE arm() are credited at arm time (the
// incremental fast path enqueues first, emits cached records, then
// arms). `counts_toward_seal = false` stamps an outcome without touching
// the seal count — the producer-side cached no-op replay.
void arm(uint64_t cycle, size_t expected);
void record_actuation(uint64_t cycle, const std::string& identity,
                      const std::string& reason, const std::string& action,
                      const std::string& detail, bool counts_toward_seal = true);
// Shutdown flush: seal every armed capsule still waiting on a drained
// queue (its dropped targets' SHUTDOWN_ABORTED records are already in).
void seal_all();

// ── serving ──
// /debug/cycles body: {"capsules": [{id, cycle, ts, decisions,
// scale_downs, breaker_tripped}...], "dir": ..., "keep": N}, oldest first.
json::Value index_json();
// Full capsule JSON text by id ("" when unknown / traversal-unsafe).
std::string capsule_body(const std::string& id);

// ── replay ──
// Re-decide a capsule offline. `what_if` is an object of config overrides
// (values as strings or numbers): lookback (duration, e.g. "30m"/"600s"/
// seconds), duration (minutes), grace (seconds), run_mode, enabled_resources,
// max_scale_per_cycle, hbm_threshold (re-renders the query only — the
// recorded response can't be re-queried offline), signal_min_coverage
// (re-judges the fleet brownout from the recorded evidence), signal_guard
// ("off" replays a guarded capsule without the watchdog; "on" requires a
// recorded evidence body). Empty object = pure replay. Returns {match,
// replayed, recorded, drift, flips, query_changed, replay_query, actions};
// throws on a malformed capsule or unknown key.
json::Value replay(const json::Value& capsule, const json::Value& what_if);

void reset_for_test();

}  // namespace tpupruner::recorder
