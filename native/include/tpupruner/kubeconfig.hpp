// Shared minimal kubeconfig scan (token-auth users only).
//
// The reference gets full kubeconfig semantics from kube-rs
// (lib.rs:212-223); here the daemon only needs the cluster server URL and
// a bearer token, so one line-scanner serves both the auth chain
// (auth.cpp) and K8s config inference (k8s.cpp). Exec plugins and client
// certificates are intentionally unsupported — in-cluster SA auth and
// env-based config are the production paths.
#pragma once

#include <optional>
#include <string>

namespace tpupruner::kubeconfig {

struct Info {
  std::string server;  // first `server:` value
  std::string token;   // first `token:` value, or contents of `tokenFile:`
  std::string current_context;  // `current-context:` value (cluster-name heuristic)
  bool tls_skip = false;
};

// Scan $KUBECONFIG (or ~/.kube/config). nullopt when the file is missing
// or contains no server.
std::optional<Info> scan();

}  // namespace tpupruner::kubeconfig
