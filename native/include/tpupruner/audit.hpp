// Decision audit trail: one DecisionRecord per candidate pod per cycle.
//
// The daemon's only audit surface until now was a K8s Event per actuation
// plus counters — "why was pod X paused at 14:02, and why was pod Y NOT?"
// had no queryable answer. Every pipeline gate now lands a DecisionRecord
// carrying the observed signal, the lookback window, the resolved owner
// chain, the verdict and a stable machine-readable reason code. Records
// live in a bounded in-process ring buffer served as JSON at
// /debug/decisions (metrics port) and are appended as JSONL to the
// optional --audit-log file; `python -m tpu_pruner.analyze --explain
// <ns>/<pod>` consumes either. Deliberate non-actuations are first-class:
// a pod that was NOT touched gets a record saying exactly which gate
// stopped it.
#pragma once

#include <cstdint>
#include <functional>
#include <optional>
#include <string>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::audit {

// Stable machine-readable reason codes. Every code here must be documented
// in docs/OPERATIONS.md — tests/test_docs_drift.py fails on undocumented
// codes, so the list can only grow together with its runbook entry.
enum class Reason : uint8_t {
  Scaled,               // SCALED: pause patch landed
  DryRun,               // DRY_RUN: would have paused (run-mode dry-run)
  AlreadyPaused,        // ALREADY_PAUSED: root already at paused state (no-op)
  ScaleFailed,          // SCALE_FAILED: actuation threw (see detail)
  KindDisabled,         // KIND_DISABLED: root kind not in --enabled-resources
  NoScalableOwner,      // NO_SCALABLE_OWNER: owner walk found no scalable root
  PodGone,              // POD_GONE: in the metric plane, 404 in the cluster
  WatchCacheMiss,       // WATCH_CACHE_MISS: absent from the synced watch
                        // store AND from the live GET fallback
  FetchError,           // FETCH_ERROR: pod GET failed (namespace vetoed)
  PendingPod,           // PENDING_POD: pod phase is still Pending
  NoCreationTimestamp,  // NO_CREATION_TIMESTAMP
  BadCreationTimestamp, // BAD_CREATION_TIMESTAMP
  BelowMinAge,          // BELOW_MIN_AGE: created within the lookback window
  OptedOut,             // OPTED_OUT: pod carries tpu-pruner.dev/skip=true
  RootOptedOut,         // ROOT_OPTED_OUT: root object carries the annotation
  VetoedByAnnotatedPod, // VETOED_BY_ANNOTATED_POD: sibling pod's annotation
  NamespaceVetoed,      // NAMESPACE_VETOED: fail-closed veto (see detail)
  GroupNotIdle,         // GROUP_NOT_IDLE: JobSet/LWS gate found active hosts
  Deferred,             // DEFERRED: over --max-scale-per-cycle this cycle
  ShutdownAborted,      // SHUTDOWN_ABORTED: enqueued but daemon shut down
  // Signal-quality watchdog vetoes (signal.hpp, --signal-guard on): the
  // EVIDENCE was untrustworthy, not the workload busy.
  SignalStale,          // SIGNAL_STALE: newest sample older than --signal-max-age
  SignalGappy,          // SIGNAL_GAPPY: sample coverage below the scrape-interval floor
  SignalAbsent,         // SIGNAL_ABSENT: no evidence series for the candidate at all
  SignalBrownout,       // SIGNAL_BROWNOUT: fleet coverage below --signal-min-coverage;
                        // every scale-down of the cycle deferred
  // Replica right-sizing (--right-size on, gym.hpp): partially idle
  // replica-knob roots scale to N instead of all-or-nothing zero.
  RightSized,           // RIGHT_SIZED: partial scale-down patch landed (R → N replicas)
  RightSizeHeld,        // RIGHT_SIZE_HELD: projected duty cycle stays over the
                        // threshold at every lower replica count — no action
  // Cycle watchdog (--cycle-deadline, watchdog.hpp): the CYCLE was
  // abandoned at a phase boundary, not a judgment on the workload.
  CycleTimeout,         // CYCLE_TIMEOUT: cycle blew past --cycle-deadline;
                        // pending records landed unactuated
  // Hysteresis (--pause-after K, promoted from the gym policy): the root
  // IS idle and actionable, but its consecutive-idle streak has not
  // reached K evaluations yet — the flap damper, not a veto.
  HysteresisHold,       // HYSTERESIS_HOLD: idle streak below --pause-after
  // Slice-topology group gate (--slice-gate, capacity.hpp): the root IS
  // idle, but one of its idle pods shares a TPU slice (node-pool) with a
  // busy tenant — evicting it would fragment a slice that cannot become
  // whole anyway.
  SliceSharedBusy,      // SLICE_SHARED_BUSY: idle pods share a slice with a busy tenant
};

const char* reason_name(Reason r);
// Inverse of reason_name (the flight-recorder replay engine rebuilds
// DecisionRecords from recorded actuation outcomes); nullopt for unknown.
std::optional<Reason> reason_from_name(std::string_view name);
// Every code, in enum order (capi → drift-guard test).
std::vector<std::string> all_reason_codes();

struct DecisionRecord {
  uint64_t cycle = 0;
  int64_t ts_unix = 0;
  std::string ns, pod;
  // Observed signal from the idle query's instant vector (the joined
  // max-over-window utilization — 0 for every row the `== 0` query
  // returns). HBM corroboration acts as an `unless` clause: rescued pods
  // never appear, so no per-pod HBM value exists to record.
  std::string signal_metric;
  double signal_value = 0.0;
  bool has_signal = false;
  std::string accelerator;
  int64_t lookback_s = 0;
  std::vector<std::string> owner_chain;  // "Kind/ns/name" hops, pod first
  std::string root_kind, root_ns, root_name;
  Reason reason = Reason::DryRun;
  std::string action;  // "scale_down" | "none"
  std::string detail;  // free-text context (error messages, veto causes)
  std::string trace_id;  // cycle trace id (OTLP correlation); may be empty

  json::Value to_json() const;
};

// ── cycle lifecycle ──
// Monotonic process-wide cycle counter; also stamps log lines (log.cpp)
// so logs join against DecisionRecord.cycle without timestamp guessing.
uint64_t begin_cycle();
uint64_t current_cycle();

// ── recording ──
// Optional JSONL sink (--audit-log). "" disables. Lines are appended and
// flushed per record; failures are log-only (telemetry never kills cycles).
void set_audit_log(const std::string& path);

// Optional extra sink invoked (under the registry lock) for EVERY record
// that lands in the ring — the single choke point record(), finalize()
// and finalize_all_pending() all pass through. The flight recorder hangs
// its per-cycle capsule capture here; the sink must not call back into
// audit. nullptr clears.
void set_record_sink(std::function<void(const DecisionRecord&)> sink);

// Final record: ring buffer + JSONL.
void record(DecisionRecord rec);
// Record whose verdict awaits the actuation consumer: held pending under
// (cycle, root identity) until finalize() moves it to the ring.
void record_pending(DecisionRecord rec, const std::string& root_identity);
// Resolve every pending record of (cycle, root identity).
void finalize(uint64_t cycle, const std::string& root_identity, Reason reason,
              const std::string& action, const std::string& detail = "");
// Shutdown drain: resolve whatever is still pending.
void finalize_all_pending(Reason reason);

// ── actuate-phase tracker ──
// The actuate phase is asynchronous (consumer pool); observe ONE histogram
// sample per cycle when the last enqueued target of the cycle completes,
// so every phase's _count advances in lockstep. expected==0 observes 0s
// immediately (dry-run / no-candidate cycles). Also sets the per-cycle
// noop gauge when the drain completes.
void arm_actuation(uint64_t cycle, size_t expected, const std::string& trace_id);
void actuation_done(uint64_t cycle, bool was_noop);

// ── serving ──
// Ring-buffer contents as {"decisions": [...], "dropped": N, "capacity": N},
// oldest first. `query_string` supports namespace=<ns>&pod=<name> and the
// combined pod=<ns>/<name> form (the /debug/decisions URL surface).
json::Value decisions_json(const std::string& query_string = "");

void reset_for_test();

}  // namespace tpupruner::audit
