// Binary wire protocol: hand-rolled protobuf decode for the two hot
// conversations.
//
// PRs 8-10 made the warm cycle transport-cheap (one h2 connection per
// endpoint) and CPU-cheap (O(churn) incremental reconcile); the remaining
// wall is the wire FORMAT — every watch event and Prometheus matrix still
// arrives as JSON and re-parses into a tree/arena before it touches the
// dirty journal. Real apiservers speak `application/vnd.kubernetes.protobuf`
// for exactly this reason, and this module adds that path end to end
// behind `--wire proto|json|auto` (json = exact output parity):
//
//   - a varint/length-delimited decoder for the runtime.Unknown envelope
//     (the `k8s\0` magic), meta/v1 WatchEvent frames, and the subset of
//     core/v1 PodList/Pod the informer, walker and actuator actually read
//     (metadata name/namespace/uid/resourceVersion/labels/ownerReferences,
//     spec containers + accelerator resource requests, status.phase) —
//     no protobuf library, mirroring the hand-rolled h2/HPACK approach;
//   - watch-event decode FUSED into the incremental engine: one scan per
//     frame extracts the store key + resourceVersion, fingerprints the raw
//     object bytes, journal-touches and upserts a lazily-materialized
//     entry — no intermediate json::Value or Doc is ever built for the
//     99% of objects a cycle never looks at;
//   - a Prometheus protobuf exposition for the idleness and evidence
//     instant queries (label/timestamp/value series carrying the EXACT
//     decimal text of the JSON form, so flight capsules can store a
//     canonical JSON body byte-identical to what `--wire json` records).
//
// Field numbers follow the real k8s.io generated.proto messages (TypeMeta
// apiVersion=1/kind=2, Unknown typeMeta=1/raw=2, ObjectMeta name=1/
// namespace=3/uid=5/resourceVersion=6/creationTimestamp=8/labels=11/
// annotations=12/ownerReferences=13, PodList metadata=1/items=2, ...) so
// the decoder is honest about the upstream schema; unknown fields are
// skipped by wire type, never rejected. The hermetic fakes encode the
// SAME subset (tpu_pruner/testing/wire_proto.py) and fall back to JSON
// for any object outside it, which is what keeps audit JSONL, capsules,
// ledger checkpoints and `analyze --replay` byte-identical across
// `--wire` modes.
//
// Scope: protobuf is negotiated for the PODS list+watch (the dominant
// collection — real apiservers refuse protobuf for CRs anyway, and the
// owner kinds here include four CRs) and the Prometheus instant queries.
// Owner GETs, scale patches and the other informer resources stay JSON.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <string_view>
#include <utility>
#include <vector>

#include "tpupruner/json.hpp"

namespace tpupruner::proto {

// ── wire mode (process-wide, like json::zero_copy / h2::default_mode) ──
enum class WireMode : uint8_t { Json, Proto, Auto };

// "json" | "proto" | "auto"; throws std::runtime_error on anything else.
WireMode wire_mode_from_string(const std::string& s);
const char* wire_mode_name(WireMode m);

// Initialized lazily from $TPU_PRUNER_WIRE (default json — the exact
// parity mode); the daemon's `--wire` flag overrides at startup before
// any client exists.
WireMode wire_mode();
void set_wire_mode(WireMode m);

// Should the NEXT eligible request ask for protobuf? Proto: always.
// Auto: until the endpoint refuses once (sticky per-process fallback).
// Json: never.
bool k8s_proto_wanted();
bool prom_proto_wanted();
// A proto-accepting request came back as JSON: count the fallback and —
// under Auto — remember the refusal so we stop asking.
void note_k8s_fallback();
void note_prom_fallback();

// ── content types ──
constexpr std::string_view kK8sProtoContentType = "application/vnd.kubernetes.protobuf";
constexpr std::string_view kK8sProtoAccept =
    "application/vnd.kubernetes.protobuf, application/json";
constexpr std::string_view kK8sProtoWatchAccept =
    "application/vnd.kubernetes.protobuf;stream=watch, application/json";
constexpr std::string_view kPromProtoContentType = "application/x-protobuf";
constexpr std::string_view kPromProtoAccept = "application/x-protobuf, application/json";

// True when the (lowercased) Content-Type names the protobuf form.
bool is_k8s_proto(std::string_view content_type);
bool is_prom_proto(std::string_view content_type);

// ── process-wide wire counters (served as tpu_pruner_wire_* families) ──
struct WireCounters {
  std::atomic<uint64_t> k8s_proto_bytes{0};   // LIST/watch bytes decoded as proto
  std::atomic<uint64_t> k8s_json_bytes{0};    // ... as JSON (same call sites)
  std::atomic<uint64_t> prom_proto_bytes{0};  // query bytes decoded as proto
  std::atomic<uint64_t> prom_json_bytes{0};
  std::atomic<uint64_t> negotiation_fallbacks{0};  // proto asked, JSON served
  std::atomic<uint64_t> fused_events{0};  // watch events through the fused path
};
WireCounters& counters();

// Canonical family names served on /metrics (docs drift guard, via capi):
//   tpu_pruner_wire_bytes_decoded_total{endpoint,content_type}  counter
//   tpu_pruner_wire_negotiation_fallbacks_total                 counter
//   tpu_pruner_wire_fused_decode_events_total                   counter
//   tpu_pruner_wire_mode{mode}                                  gauge (1)
std::vector<std::string> wire_metric_families();
std::string render_wire_metrics(bool openmetrics);

// FNV-1a64 over raw bytes (the fused-path object fingerprint; same
// constants as shard::stable_hash / metrics::sample_fingerprint).
uint64_t fingerprint(std::string_view bytes);

// ── Kubernetes decode ───────────────────────────────────────────────────
// All parse_* functions throw json::ParseError (offset = byte position)
// on truncated or malformed input — the same typed error the JSON path
// raises, so callers and the fuzzer-invariant tests treat both wires
// uniformly.

// One object inside a LIST page: a byte range into the page body plus the
// store key fields scanned in the same pass (never a materialized tree).
struct ObjectRef {
  size_t off = 0, len = 0;   // object message bytes within the page body
  std::string ns, name;      // metadata.namespace / metadata.name
  uint64_t fp = 0;           // fingerprint over the object bytes
};

// A decoded LIST page: raw body (owned; ObjectRefs view into it), the
// items' TypeMeta, and the ListMeta fields the pagination loop reads.
struct ListPage {
  std::string body;
  std::string api_version, kind;  // per-ITEM type (e.g. "v1", "Pod")
  std::string resource_version, continue_token;
  std::vector<ObjectRef> items;
};
using ListPagePtr = std::shared_ptr<const ListPage>;
ListPagePtr parse_list(std::string body);

// A decoded watch frame (one length-delimited runtime.Unknown(WatchEvent)).
// For ADDED/MODIFIED/DELETED/BOOKMARK the object slice + scanned key
// fields are populated; for ERROR the embedded Status code/message are.
struct WatchEvent {
  std::string body;  // raw frame (owned; the object slice views into it)
  std::string type;  // "ADDED" | "MODIFIED" | "DELETED" | "BOOKMARK" | "ERROR" | ...
  std::string api_version, kind;  // embedded object's TypeMeta ("" when absent)
  size_t obj_off = 0, obj_len = 0;
  bool has_object = false;
  std::string ns, name, resource_version;
  uint64_t fp = 0;
  int64_t error_code = 0;     // ERROR events: Status.code
  std::string error_message;  // ERROR events: Status.message
};
using WatchEventPtr = std::shared_ptr<const WatchEvent>;
WatchEventPtr parse_watch_event(std::string frame);

// Materialize an object slice (the Pod-subset schema) as a json::Value
// IDENTICAL to parsing the JSON representation of the same object —
// json::Object is key-sorted, so field order never matters. api_version /
// kind are stamped as the "apiVersion"/"kind" members when non-empty
// (protobuf items carry TypeMeta out of band).
json::Value object_to_value(std::string_view bytes, const std::string& api_version,
                            const std::string& kind);

// ── Prometheus decode ───────────────────────────────────────────────────

// One series of the instant-vector exposition. Labels preserve wire
// order; ts_text/value_text carry the EXACT decimal tokens of the JSON
// form so the canonical body reconstruction is byte-faithful.
struct PromSeries {
  std::vector<std::pair<std::string, std::string>> labels;
  std::string ts_text;     // JSON number token, e.g. "1754300123.456789"
  std::string value_text;  // sample value string, e.g. "0.0"
};

// QueryResponse message: status=1, errorType=2, error=3,
// result=4 repeated Series{label=1 repeated Label{name=1,value=2},
// ts_text=2, value_text=3}.
struct PromVector {
  std::string status;  // "success" | "error"
  std::string error_type, error;
  std::vector<PromSeries> result;
};
PromVector parse_prom_vector(std::string_view body);

// Canonical JSON reconstruction of the vector — byte-identical to
// Python's `json.dumps({"status": ..., "data": {"resultType": "vector",
// "result": [...]}})` with default separators and ensure_ascii (what
// fake_prom and real Prometheus emit for the same data), so a flight
// capsule recorded under `--wire proto` stores exactly the body
// `--wire json` would have recorded.
std::string prom_canonical_body(const PromVector& v);

// Python-compatible JSON string escape (ensure_ascii: non-ASCII and
// control characters as \uXXXX with lowercase hex, surrogate pairs for
// non-BMP) — exposed for the canonical-body unit tests.
void python_json_escape(std::string& out, std::string_view s);

void reset_for_test();  // counters + sticky fallbacks

}  // namespace tpupruner::proto
