// Per-cycle deadline watchdog (--cycle-deadline, PR 15 chaos tier).
//
// A wedged phase — an apiserver that accepts connections but never
// finishes a LIST page, a Prometheus that trickles bytes forever —
// previously stalled the producer loop until the transport timeout
// fired, and a pathological sequence of slow-but-not-dead calls could
// stretch one cycle far past the check interval with no audit trail.
// The watchdog bounds a whole cycle: armed at cycle start with deadline
// N x check-interval, checked at every phase boundary (the
// observe_phase choke points in daemon.cpp), and when breached the
// cycle is abandoned by throwing CycleTimeout BEFORE the next phase's
// side effects — pending audit rows land with reason CYCLE_TIMEOUT,
// tpu_pruner_cycle_timeouts_total ticks, and the incremental engine is
// reset so the next cycle recomputes from a globally-dirty state.
//
// Checks happen only at phase boundaries, never mid-I/O: each network
// call is already bounded by its own transport timeout, so a boundary
// check is reached within one transport timeout of the breach — the
// watchdog turns "slow forever" into "bounded, audited abort" without
// the races of cross-thread I/O cancellation.
#pragma once

#include <cstdint>
#include <stdexcept>
#include <string>

namespace tpupruner::watchdog {

// Thrown from check() at a phase boundary once the armed deadline has
// passed. Caught specifically by the daemon run loop (before its
// generic failure handler) to do the CYCLE_TIMEOUT bookkeeping.
struct CycleTimeout : std::runtime_error {
  explicit CycleTimeout(const std::string& what) : std::runtime_error(what) {}
};

// Set the per-cycle deadline; 0 disables (the default — the flag is
// opt-in). Thread-safe, callable at any time.
void configure(int64_t deadline_ms);
int64_t deadline_ms();

// Arm/disarm around one producer cycle. Disarmed, check() never throws.
void arm();
void disarm();

// True when armed, enabled, and the deadline has elapsed.
bool expired();

// Phase-boundary probe: throws CycleTimeout naming the phase when
// expired(). No-op otherwise.
void check(const char* phase);

}  // namespace tpupruner::watchdog
