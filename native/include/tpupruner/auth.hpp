// Bearer-token resolution chain for the metric plane.
//
// Reference analog: get_prometheus_token (gpu-pruner/src/lib.rs:205-231):
//   PROMETHEUS_TOKEN env → kube config token_file → kube config token →
//   `oc whoami -t` subprocess.
//
// TPU-native chain (GKE managed Prometheus / Cloud Monitoring auth):
//   explicit --prometheus-token flag
//   → PROMETHEUS_TOKEN env
//   → in-cluster ServiceAccount token file
//   → kubeconfig user token / tokenFile
//   → GCE metadata server access token (Workload Identity / ADC path)
//   → `gcloud auth print-access-token` subprocess (operator-laptop path)
//   → `oc whoami -t` subprocess (the reference's literal last resort,
//     kept for drop-in --device=gpu use on OpenShift).
// Subprocess steps run under a native 5 s deadline (fork/exec + poll, no
// coreutils `timeout` dependency) so a wedged CLI (e.g. oc logged into an
// unreachable cluster) can't stall every cycle's client rebuild.
// Every step is overridable for hermetic tests (env vars below).
#pragma once

#include <optional>
#include <string>

namespace tpupruner::auth {

struct TokenOptions {
  std::string explicit_token;  // from the CLI flag; wins when non-empty
  // Env overrides honored (mainly for tests):
  //   PROMETHEUS_TOKEN            — token value (reference parity, lib.rs:206)
  //   TPU_PRUNER_SA_TOKEN_FILE    — in-cluster SA token path override
  //   KUBECONFIG                  — kubeconfig path ("~/.kube/config" default)
  //   GCE_METADATA_HOST           — metadata server host:port override
  //   TPU_PRUNER_DISABLE_GCLOUD   — skip the gcloud subprocess fallback
  //   TPU_PRUNER_DISABLE_OC       — skip the oc subprocess fallback
  bool allow_metadata_server = true;
  bool allow_gcloud = true;
  bool allow_oc = true;  // own gate — oc is not a gcloud concern
  int metadata_timeout_ms = 2000;
  int subprocess_timeout_ms = 5000;  // native deadline for gcloud/oc
};

// Returns a bearer token, or nullopt when every source comes up empty.
// Never throws: each failed source falls through to the next.
std::optional<std::string> get_bearer_token(const TokenOptions& opts = {});

// Individual sources (exposed for tests).
std::optional<std::string> token_from_sa_file();
std::optional<std::string> token_from_kubeconfig();
std::optional<std::string> token_from_metadata_server(int timeout_ms);
std::optional<std::string> token_from_gcloud(int timeout_ms = 5000);
// Reference last resort, lib.rs:225-230.
std::optional<std::string> token_from_oc(int timeout_ms = 5000);

}  // namespace tpupruner::auth
