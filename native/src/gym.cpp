#include "tpupruner/gym.hpp"

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <filesystem>
#include <map>
#include <set>
#include <stdexcept>
#include <string>
#include <vector>

#include "tpupruner/k8s.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/recorder.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::gym {

namespace fs = std::filesystem;
using json::Value;

namespace {

std::string fmt_g(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%g", v);
  return buf;
}

double round3(double v) { return std::round(v * 1000.0) / 1000.0; }

}  // namespace

// ── right-size math (the ONE implementation: daemon + replay + gym) ──

RightSizePlan right_size_plan(core::Kind kind, const Value& root_object,
                              int64_t idle_pods, int64_t idle_chips, double threshold) {
  if (!(threshold > 0.0 && threshold <= 1.0)) {
    throw std::runtime_error("right-size threshold must be in (0, 1]");
  }
  RightSizePlan p;
  const Value* replicas = nullptr;
  switch (kind) {
    case core::Kind::Deployment:
    case core::Kind::ReplicaSet:
    case core::Kind::StatefulSet:
    case core::Kind::LeaderWorkerSet:
      replicas = root_object.at_path("spec.replicas");
      break;
    case core::Kind::InferenceService:
      // minReplicas is the knob the pruner owns for KServe; treat it as
      // the root's floor replica count (the classic pause sets it to 0).
      replicas = root_object.at_path("spec.predictor.minReplicas");
      break;
    default:
      return p;  // no replica knob (JobSet suspend, Notebook annotation)
  }
  if (!replicas || !replicas->is_number()) return p;
  const int64_t r = replicas->as_int();
  if (r <= 1) return p;  // right-sizing a single replica IS scale-to-zero
  const int64_t busy = r - idle_pods;
  if (busy <= 0) return p;  // fully idle: the classic pause frees everything
  p.applicable = true;
  p.current_replicas = r;
  p.busy_replicas = busy;
  // Smallest N whose projected per-replica duty cycle — busy replicas,
  // each conservatively assumed fully busy, consolidated onto N — stays
  // under the threshold: N = ceil(busy / threshold), clamped to R.
  int64_t n = static_cast<int64_t>(std::ceil(static_cast<double>(busy) / threshold));
  p.held = n >= r;
  p.target_replicas = p.held ? r : n;
  const int64_t chips_per_replica = idle_pods > 0 ? idle_chips / idle_pods : 0;
  p.freed_chips = (r - p.target_replicas) * chips_per_replica;
  if (p.held) {
    p.detail = "right-size held at " + std::to_string(r) + " replicas (" +
               std::to_string(busy) + " busy over threshold " + fmt_g(threshold) + ")";
  } else {
    p.detail = "right-sized from " + std::to_string(r) + " to " +
               std::to_string(p.target_replicas) + " replicas (" + std::to_string(busy) +
               " busy, threshold " + fmt_g(threshold) + ", freed " +
               std::to_string(p.freed_chips) + " chips)";
  }
  return p;
}

// ── policy specs ──

Value parse_policy_spec(const std::string& spec) {
  std::string head = spec, rest;
  if (auto colon = spec.find(':'); colon != std::string::npos) {
    head = spec.substr(0, colon);
    rest = spec.substr(colon + 1);
  }
  auto kv_pairs = [&] {
    std::vector<std::pair<std::string, std::string>> out;
    for (const std::string& pair : util::split(rest, ',')) {
      std::string t = util::trim(pair);
      if (t.empty()) continue;
      auto eq = t.find('=');
      if (eq == std::string::npos) {
        throw std::runtime_error("policy spec '" + spec + "': expected key=value, got '" + t +
                                 "'");
      }
      out.push_back({t.substr(0, eq), t.substr(eq + 1)});
    }
    return out;
  };
  auto num = [&](const std::string& key, const std::string& v) {
    try {
      size_t idx = 0;
      double d = std::stod(v, &idx);
      if (idx != v.size()) throw std::invalid_argument("trailing");
      return d;
    } catch (const std::exception&) {
      throw std::runtime_error("policy spec '" + spec + "': invalid number for " + key);
    }
  };

  Value p = Value::object();
  p.set("name", Value(spec));
  if (head == "baseline") {
    if (!util::trim(rest).empty()) {
      throw std::runtime_error("policy spec '" + spec + "': baseline takes no parameters");
    }
    p.set("kind", Value("baseline"));
  } else if (head == "sweep") {
    Value what_if = Value::object();
    for (auto& [k, v] : kv_pairs()) what_if.set(k, Value(v));
    if (what_if.as_object().empty()) {
      throw std::runtime_error("policy spec '" + spec + "': sweep needs at least one key=value");
    }
    p.set("kind", Value("sweep"));
    p.set("what_if", std::move(what_if));
  } else if (head == "right-size" || head == "right_size") {
    double threshold = 0.8;
    for (auto& [k, v] : kv_pairs()) {
      if (k == "threshold") threshold = num(k, v);
      else throw std::runtime_error("policy spec '" + spec + "': unknown key " + k);
    }
    if (!(threshold > 0.0 && threshold <= 1.0)) {
      throw std::runtime_error("policy spec '" + spec + "': threshold must be in (0, 1]");
    }
    p.set("kind", Value("right_size"));
    p.set("threshold", Value(threshold));
  } else if (head == "hysteresis") {
    int64_t pause_after = 3;
    for (auto& [k, v] : kv_pairs()) {
      if (k == "pause_after") pause_after = static_cast<int64_t>(num(k, v));
      else throw std::runtime_error("policy spec '" + spec + "': unknown key " + k);
    }
    if (pause_after < 1) {
      throw std::runtime_error("policy spec '" + spec + "': pause_after must be >= 1");
    }
    p.set("kind", Value("hysteresis"));
    p.set("pause_after", Value(pause_after));
  } else {
    throw std::runtime_error(
        "unknown policy kind '" + head +
        "' (expected baseline, sweep:<k=v,...>, right-size[:threshold=T], "
        "hysteresis[:pause_after=K])");
  }
  return p;
}

Value default_policies() {
  Value out = Value::array();
  out.push_back(parse_policy_spec("baseline"));
  out.push_back(parse_policy_spec("right-size:threshold=0.8"));
  out.push_back(parse_policy_spec("hysteresis:pause_after=3"));
  return out;
}

// ── the simulator ──

namespace {

// One cycle's ledger evidence for one root (the exact observe_cycle input).
struct Obs {
  std::string kind, ns, name;
  int64_t chips = 0;
  int64_t pods = 0;
};

// Evidence per capsule: the recorded "ledger" block when present (new
// capsules — guarantees the baseline integration is driven by the exact
// inputs the live ledger saw), else reconstructed from resolutions + pod
// evidence exactly the way resolve_pods builds ledger_obs.
std::map<std::string, Obs> capsule_observations(const Value& capsule, const std::string& device) {
  std::map<std::string, Obs> out;
  if (const Value* led = capsule.find("ledger")) {
    if (const Value* obs = led->find("observations"); obs && obs->is_array()) {
      for (const Value& o : obs->as_array()) {
        Obs x;
        x.kind = o.get_string("kind");
        x.ns = o.get_string("namespace");
        x.name = o.get_string("name");
        if (const Value* c = o.find("chips"); c && c->is_number()) x.chips = c->as_int();
        if (const Value* n = o.find("pods"); n && n->is_number()) x.pods = n->as_int();
        out[x.kind + "/" + x.ns + "/" + x.name] = std::move(x);
      }
      return out;
    }
  }
  std::set<std::string> opted_out;
  if (const Value* decs = capsule.find("decisions"); decs && decs->is_array()) {
    for (const Value& d : decs->as_array()) {
      if (d.get_string("reason") == "OPTED_OUT") {
        opted_out.insert(d.get_string("namespace") + "/" + d.get_string("pod"));
      }
    }
  }
  const Value* res = capsule.find("resolutions");
  const Value* pods = capsule.find("pods");
  if (!res || !res->is_object()) return out;
  for (const auto& [key, r] : res->as_object()) {
    const Value* root = r.find("root");
    if (!root || opted_out.count(key)) continue;
    Obs& x = out[root->get_string("kind") + "/" + root->get_string("namespace") + "/" +
                 root->get_string("name")];
    if (x.kind.empty()) {
      x.kind = root->get_string("kind");
      x.ns = root->get_string("namespace");
      x.name = root->get_string("name");
    }
    x.pods += 1;
    const Value* ev = pods ? pods->find(key) : nullptr;
    if (const Value* pod = ev ? ev->find("pod") : nullptr) {
      x.chips += core::pod_chip_count(*pod, device);
    }
  }
  return out;
}

int64_t capsule_ledger_now(const Value& capsule) {
  if (const Value* led = capsule.find("ledger")) {
    if (const Value* n = led->find("now_unix"); n && n->is_number()) return n->as_int();
  }
  if (const Value* n = capsule.find("now_unix"); n && n->is_number()) return n->as_int();
  if (const Value* n = capsule.find("ts_unix"); n && n->is_number()) return n->as_int();
  throw std::runtime_error("gym: capsule carries no usable clock");
}

struct Policy {
  std::string name;
  std::string kind;  // baseline | sweep | right_size | hysteresis
  Value what_if = Value::object();
  double threshold = 0.8;
  int64_t pause_after = 1;  // >1 only for hysteresis
};

// Per-policy virtual ledger account — observe_cycle's state machine with
// a virtual pause bit and a candidate streak for hysteresis.
struct VAccount {
  int64_t chips = 0;  // latest observed idle chips (ledger a.chips analog)
  uint64_t first_seen = 0;
  bool paused = false;
  bool right_sized = false;
  int64_t freed_chips = 0;  // chips_when_paused analog
  int64_t paused_at = 0;
  double reclaimed = 0, idle_s = 0, active_s = 0;
  uint64_t streak = 0;  // consecutive candidate cycles (hysteresis)
};

struct PolicyState {
  Policy spec;
  std::map<std::string, VAccount> accounts;
  uint64_t pauses = 0, resumes = 0, false_pauses = 0;
  uint64_t right_size_applied = 0, right_size_held = 0;
};

Policy policy_from_json(const Value& v) {
  Policy p;
  p.name = v.get_string("name");
  p.kind = v.get_string("kind");
  if (p.name.empty()) p.name = p.kind;
  if (p.kind == "baseline") {
  } else if (p.kind == "sweep") {
    const Value* w = v.find("what_if");
    if (!w || !w->is_object() || w->as_object().empty()) {
      throw std::runtime_error("gym: sweep policy '" + p.name + "' needs a what_if object");
    }
    p.what_if = *w;
  } else if (p.kind == "right_size") {
    if (const Value* t = v.find("threshold"); t && t->is_number()) p.threshold = t->as_double();
    if (!(p.threshold > 0.0 && p.threshold <= 1.0)) {
      throw std::runtime_error("gym: right_size threshold must be in (0, 1]");
    }
    p.what_if.set("right_size", Value("on"));
    p.what_if.set("right_size_threshold", Value(p.threshold));
  } else if (p.kind == "hysteresis") {
    if (const Value* k = v.find("pause_after"); k && k->is_number()) p.pause_after = k->as_int();
    if (p.pause_after < 1) throw std::runtime_error("gym: pause_after must be >= 1");
    if (const Value* w = v.find("what_if"); w && w->is_object()) p.what_if = *w;
  } else {
    throw std::runtime_error("gym: unknown policy kind '" + p.kind + "'");
  }
  return p;
}

std::string flag_line_of(const Policy& p) {
  if (p.kind == "baseline") {
    return "# baseline: the daemon's current configuration (no flag changes)";
  }
  if (p.kind == "right_size") {
    return "--right-size on --right-size-threshold " + fmt_g(p.threshold);
  }
  if (p.kind == "hysteresis") {
    return "# hysteresis (pause_after=" + std::to_string(p.pause_after) +
           ") is a gym-only policy today; nearest production guard: --max-scale-per-cycle";
  }
  std::string flags, comments;
  for (const auto& [k, v] : p.what_if.as_object()) {
    std::string val = v.is_string() ? v.as_string() : v.dump();
    if (k == "duration") flags += " -t " + val;
    else if (k == "grace") flags += " -g " + val;
    else if (k == "run_mode") flags += " --run-mode " + val;
    else if (k == "enabled_resources") flags += " -e " + val;
    else if (k == "max_scale_per_cycle") flags += " --max-scale-per-cycle " + val;
    else if (k == "hbm_threshold") flags += " --hbm-threshold " + val;
    else if (k == "signal_min_coverage") flags += " --signal-min-coverage " + val;
    else if (k == "signal_guard") flags += " --signal-guard " + val;
    else if (k == "right_size") flags += " --right-size " + val;
    else if (k == "right_size_threshold") flags += " --right-size-threshold " + val;
    else if (k == "lookback") comments += "  # lookback=" + val + " derives from -t (min) + -g (sec)";
    else comments += "  # " + k + "=" + val;
  }
  std::string out = util::trim(flags + comments);
  return out.empty() ? "# (no flag changes)" : out;
}

}  // namespace

Value simulate(const Value& payload) {
  const Value* caps_v = payload.find("capsules");
  if (!caps_v || !caps_v->is_array() || caps_v->as_array().empty()) {
    throw std::runtime_error("gym: missing or empty capsules");
  }

  std::vector<PolicyState> policies;
  {
    Value specs = default_policies();
    if (const Value* pol = payload.find("policies"); pol && pol->is_array() &&
        !pol->as_array().empty()) {
      specs = *pol;
    }
    for (const Value& s : specs.as_array()) {
      PolicyState st;
      st.spec = policy_from_json(s.is_string() ? parse_policy_spec(s.as_string()) : s);
      policies.push_back(std::move(st));
    }
  }

  int64_t regret_window_s = 600;
  if (const Value* r = payload.find("regret_window_s"); r && r->is_number()) {
    regret_window_s = r->as_int();
  }
  bool assume_scale_down = true;
  if (const Value* a = payload.find("assume_scale_down"); a && a->is_bool()) {
    assume_scale_down = a->as_bool();
  }
  double fp_penalty = 1.0, churn_penalty = 0.01;
  if (const Value* v = payload.find("false_pause_penalty_chip_hours"); v && v->is_number()) {
    fp_penalty = v->as_double();
  }
  if (const Value* v = payload.find("churn_penalty_chip_hours"); v && v->is_number()) {
    churn_penalty = v->as_double();
  }
  // Synthetic corpora recorded back-to-back (--check-interval 0) carry
  // near-zero wall-clock dt between capsules; an assumed interval scores
  // them at their LOGICAL cadence instead (0 = use the capsules' own
  // ledger clocks — the bit-for-bit parity mode).
  int64_t assume_interval_s = 0;
  if (const Value* v = payload.find("assume_interval_s"); v && v->is_number()) {
    assume_interval_s = v->as_int();
    if (assume_interval_s < 0) throw std::runtime_error("gym: assume_interval_s must be >= 0");
  }

  // Chronological order: cycle number first, capsule id as tiebreak.
  std::vector<const Value*> capsules;
  for (const Value& c : caps_v->as_array()) capsules.push_back(&c);
  std::sort(capsules.begin(), capsules.end(), [](const Value* a, const Value* b) {
    int64_t ca = 0, cb = 0;
    if (const Value* v = a->find("cycle"); v && v->is_number()) ca = v->as_int();
    if (const Value* v = b->find("cycle"); v && v->is_number()) cb = v->as_int();
    if (ca != cb) return ca < cb;
    return a->get_string("id") < b->get_string("id");
  });

  // Effective what-if per policy (assume_scale_down injects run_mode
  // without polluting the policy's flag line).
  std::vector<Value> effective_what_if;
  for (const PolicyState& st : policies) {
    Value w = st.spec.what_if;
    if (assume_scale_down && !w.find("run_mode")) w.set("run_mode", Value("scale-down"));
    effective_what_if.push_back(std::move(w));
  }

  // Roots the LIVE daemon paused (actuation reasons in the capsules'
  // decisions): their later absence from the idle evidence is a shadow,
  // not a busy signal — false-pause detection must skip them.
  std::set<std::string> live_paused;

  int64_t prev_now = 0;
  bool first = true;
  uint64_t cycles = 0;
  for (const Value* capsule : capsules) {
    ++cycles;
    const std::string device =
        capsule->at_path("config.query_args") ? capsule->at_path("config.query_args")->get_string("device", "tpu") : "tpu";
    const int64_t now_clock = capsule_ledger_now(*capsule);
    const int64_t now = assume_interval_s > 0
                            ? (first ? now_clock : prev_now + assume_interval_s)
                            : now_clock;
    const double dt = (!first && now > prev_now) ? static_cast<double>(now - prev_now) : 0.0;

    std::map<std::string, Obs> observed = capsule_observations(*capsule, device);

    // Replay the capsule once per DISTINCT overlay (baseline + hysteresis
    // usually share one replay), then extract each policy's wanted set.
    std::map<std::string, Value> replay_cache;
    for (size_t pi = 0; pi < policies.size(); ++pi) {
      PolicyState& st = policies[pi];
      const std::string cache_key = effective_what_if[pi].dump();
      auto cached = replay_cache.find(cache_key);
      if (cached == replay_cache.end()) {
        try {
          cached = replay_cache
                       .emplace(cache_key, recorder::replay(*capsule, effective_what_if[pi]))
                       .first;
        } catch (const std::exception& e) {
          throw std::runtime_error("gym: capsule " + capsule->get_string("id", "<unnamed>") +
                                   ", policy '" + st.spec.name + "': " + e.what());
        }
      }
      const Value& replayed = *cached->second.find("replayed");

      // Wanted-pause roots this cycle under this policy, split full vs
      // right-size partial; held roots are counted for the report.
      std::map<std::string, bool> wanted;  // ledger key → is_right_size
      std::set<std::string> held_roots;
      for (const Value& rec : replayed.as_array()) {
        const Value* root = rec.find("root");
        if (!root) continue;
        const std::string key = root->get_string("kind") + "/" +
                                root->get_string("namespace") + "/" + root->get_string("name");
        const std::string reason = rec.get_string("reason");
        if (reason == "RIGHT_SIZE_HELD") held_roots.insert(key);
        if (rec.get_string("action") != "scale_down") continue;
        bool rs = reason == "RIGHT_SIZED";
        auto it = wanted.find(key);
        if (it == wanted.end()) wanted.emplace(key, rs);
        else it->second = it->second && rs;
      }
      st.right_size_held += held_roots.size();

      // ── ledger integration (observe_cycle's state machine, verbatim) ──
      for (const auto& [key, o] : observed) {
        VAccount& a = st.accounts[key];
        if (a.first_seen == 0) a.first_seen = cycles;
        a.chips = o.chips;
      }
      std::vector<std::string> resumed;
      for (auto& [key, a] : st.accounts) {
        const bool was_observed = observed.count(key) != 0;
        if (a.first_seen == cycles && !a.paused) continue;  // new: nothing spans yet
        if (a.paused) {
          a.reclaimed += static_cast<double>(a.freed_chips) * dt;
          // Busy evidence against a virtual pause: the root left the idle
          // set while its pods still exist in the corpus — the workload
          // was needed. Right-sized roots keep their busy replicas, so
          // busy evidence is expected, not a regret signal.
          if (!was_observed && !a.right_sized && !live_paused.count(key)) {
            resumed.push_back(key);
          }
        } else if (was_observed) {
          a.idle_s += dt;
        } else {
          a.active_s += dt;
        }
      }
      for (const std::string& key : resumed) {
        VAccount& a = st.accounts[key];
        a.paused = false;
        a.freed_chips = 0;
        ++st.resumes;
        if (now - a.paused_at <= regret_window_s) ++st.false_pauses;
      }

      // ── hysteresis streaks, then this cycle's pauses ──
      for (auto& [key, a] : st.accounts) {
        a.streak = wanted.count(key) ? a.streak + 1 : 0;
      }
      for (const auto& [key, is_rs] : wanted) {
        VAccount& a = st.accounts[key];
        if (a.streak < static_cast<uint64_t>(st.spec.pause_after)) continue;
        if (is_rs) {
          if (a.paused) continue;  // virtual right-size applies once
          const Obs* o = observed.count(key) ? &observed.at(key) : nullptr;
          auto kind = core::kind_from_name(util::split(key, '/')[0]);
          RightSizePlan plan;
          if (kind && o) {
            const Value* objects = capsule->find("objects");
            const Value* root_obj =
                objects ? objects->find(k8s::Client::object_path(*kind, o->ns, o->name))
                        : nullptr;
            if (root_obj && !root_obj->is_null()) {
              plan = right_size_plan(*kind, *root_obj, o->pods, o->chips, st.spec.threshold);
            }
          }
          if (!plan.applicable || plan.held) continue;  // evidence too thin: hold
          a.paused = true;
          a.right_sized = true;
          a.freed_chips = plan.freed_chips;
          a.paused_at = now;
          ++st.pauses;
          ++st.right_size_applied;
        } else {
          if (a.paused && !a.right_sized) continue;
          if (a.paused && a.right_sized) {
            // Full pause upgrades a virtual right-size: everything the
            // idle evidence covers is now freed (conservative: observed
            // idle chips, the same figure record_pause would take).
            a.right_sized = false;
            a.freed_chips = a.chips;
            ++st.pauses;
            continue;
          }
          a.paused = true;
          a.right_sized = false;
          a.freed_chips = a.chips;
          a.paused_at = now;
          ++st.pauses;
        }
      }
    }

    // Evidence shadows start AFTER the cycle that actually paused a root.
    if (const Value* decs = capsule->find("decisions"); decs && decs->is_array()) {
      for (const Value& d : decs->as_array()) {
        const std::string reason = d.get_string("reason");
        if (reason != "SCALED" && reason != "ALREADY_PAUSED" && reason != "RIGHT_SIZED") {
          continue;
        }
        if (const Value* root = d.find("root")) {
          live_paused.insert(root->get_string("kind") + "/" + root->get_string("namespace") +
                             "/" + root->get_string("name"));
        }
      }
    }
    prev_now = now;
    first = false;
  }

  // ── scoring ──
  Value out_policies = Value::array();
  double best_score = 0;
  size_t best_index = 0;
  for (size_t pi = 0; pi < policies.size(); ++pi) {
    PolicyState& st = policies[pi];
    double reclaimed = 0, idle_s = 0, active_s = 0;
    for (const auto& [key, a] : st.accounts) {
      reclaimed += a.reclaimed;
      idle_s += a.idle_s;
      active_s += a.active_s;
    }
    const uint64_t churn = st.pauses + st.resumes;
    const double score = reclaimed / 3600.0 - fp_penalty * static_cast<double>(st.false_pauses) -
                         churn_penalty * static_cast<double>(churn);
    if (pi == 0 || score > best_score) {
      best_score = score;
      best_index = pi;
    }
    Value p = Value::object();
    p.set("name", Value(st.spec.name));
    p.set("kind", Value(st.spec.kind));
    if (st.spec.kind == "sweep") p.set("what_if", st.spec.what_if);
    if (st.spec.kind == "right_size") p.set("threshold", Value(st.spec.threshold));
    if (st.spec.kind == "hysteresis") p.set("pause_after", Value(st.spec.pause_after));
    p.set("reclaimed_chip_seconds", Value(round3(reclaimed)));
    p.set("reclaimed_chip_hours", Value(round3(reclaimed / 3600.0)));
    p.set("idle_seconds", Value(round3(idle_s)));
    p.set("active_seconds", Value(round3(active_s)));
    p.set("false_pauses", Value(static_cast<int64_t>(st.false_pauses)));
    p.set("pauses", Value(static_cast<int64_t>(st.pauses)));
    p.set("resumes", Value(static_cast<int64_t>(st.resumes)));
    p.set("actuation_churn", Value(static_cast<int64_t>(churn)));
    p.set("right_size_applied", Value(static_cast<int64_t>(st.right_size_applied)));
    p.set("right_size_held", Value(static_cast<int64_t>(st.right_size_held)));
    p.set("tracked_workloads", Value(static_cast<int64_t>(st.accounts.size())));
    p.set("score", Value(round3(score)));
    p.set("flag_line", Value(flag_line_of(st.spec)));
    out_policies.push_back(std::move(p));
  }

  Value out = Value::object();
  out.set("cycles", Value(static_cast<int64_t>(cycles)));
  out.set("regret_window_s", Value(regret_window_s));
  out.set("assume_scale_down", Value(assume_scale_down));
  if (assume_interval_s > 0) out.set("assume_interval_s", Value(assume_interval_s));
  out.set("winner", out_policies.as_array()[best_index]);
  out.set("policies", std::move(out_policies));
  return out;
}

// ── CLI shell: `tpu-pruner gym` ──

namespace {

const char kGymUsage[] = R"(tpu-pruner gym — offline policy simulator over flight-recorder capsules

Replays a capsule corpus against N candidate policies in one pass and
scores each with the ledger's own math: reclaimed chip-hours vs false
pauses (a pause whose root shows busy evidence within the regret window)
vs actuation churn. Human table on stderr, one JSON document on stdout.

USAGE:
  tpu-pruner gym --flight-dir <DIR> [FLAGS]
  tpu-pruner gym --capsule <FILE> [--capsule <FILE>...] [FLAGS]

FLAGS:
      --flight-dir <DIR>       load every cycle-*.json capsule in DIR
      --capsule <FILE>         load one capsule file (repeatable)
      --policy <SPEC>          policy to score (repeatable); specs:
                                 baseline
                                 sweep:<key=value,...>   (what-if keys)
                                 right-size[:threshold=0.8]
                                 hysteresis[:pause_after=3]
                               default: baseline, right-size:threshold=0.8,
                               hysteresis:pause_after=3
      --regret-window <SEC>    a pause whose root shows busy evidence
                               within this window counts as a false pause
                               [default: 600]
      --as-recorded            score run modes exactly as recorded (a
                               dry-run corpus then reclaims nothing);
                               default scores every policy as if
                               run_mode=scale-down
      --assume-interval <SEC>  score cycles SEC seconds apart instead of
                               using the capsules' own clocks — for
                               synthetic corpora recorded back-to-back
                               (--check-interval 0), whose wall-clock dt
                               is near zero [default: 0 = capsule clocks]
      --false-pause-penalty <CHIP_HOURS>
                               score penalty per false pause [default: 1]
      --churn-penalty <CHIP_HOURS>
                               score penalty per pause/resume actuation
                               [default: 0.01]
  -h, --help                   print this help
)";

}  // namespace

int run_cli(int argc, char** argv) {
  std::string flight_dir;
  std::vector<std::string> capsule_paths, policy_specs;
  int64_t regret_window_s = 600;
  int64_t assume_interval_s = 0;
  bool as_recorded = false;
  double fp_penalty = 1.0, churn_penalty = 0.01;

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto value = [&]() -> std::string {
      if (i + 1 >= argc) throw std::runtime_error(arg + " requires a value");
      return argv[++i];
    };
    if (arg == "-h" || arg == "--help") {
      std::fprintf(stdout, "%s", kGymUsage);
      return 0;
    } else if (arg == "--flight-dir") {
      flight_dir = value();
    } else if (arg == "--capsule") {
      capsule_paths.push_back(value());
    } else if (arg == "--policy") {
      policy_specs.push_back(value());
    } else if (arg == "--regret-window") {
      regret_window_s = std::stoll(value());
    } else if (arg == "--assume-interval") {
      assume_interval_s = std::stoll(value());
    } else if (arg == "--as-recorded") {
      as_recorded = true;
    } else if (arg == "--false-pause-penalty") {
      fp_penalty = std::stod(value());
    } else if (arg == "--churn-penalty") {
      churn_penalty = std::stod(value());
    } else {
      std::fprintf(stderr, "gym: unknown flag %s\n%s", arg.c_str(), kGymUsage);
      return 2;
    }
  }

  std::vector<std::string> files;
  if (!flight_dir.empty()) {
    std::error_code ec;
    std::vector<std::string> found;
    for (const auto& entry : fs::directory_iterator(flight_dir, ec)) {
      std::string name = entry.path().filename().string();
      if (name.rfind("cycle-", 0) == 0 && name.size() > 5 &&
          name.substr(name.size() - 5) == ".json") {
        found.push_back(entry.path().string());
      }
    }
    if (ec) {
      std::fprintf(stderr, "gym: cannot read --flight-dir %s: %s\n", flight_dir.c_str(),
                   ec.message().c_str());
      return 1;
    }
    std::sort(found.begin(), found.end());
    files.insert(files.end(), found.begin(), found.end());
  }
  files.insert(files.end(), capsule_paths.begin(), capsule_paths.end());
  if (files.empty()) {
    std::fprintf(stderr, "gym: no capsules (--flight-dir or --capsule required)\n%s", kGymUsage);
    return 2;
  }

  Value capsules = Value::array();
  for (const std::string& f : files) {
    auto text = util::read_file(f);
    if (!text) {
      std::fprintf(stderr, "gym: cannot read capsule %s\n", f.c_str());
      return 1;
    }
    try {
      capsules.push_back(Value::parse(*text));
    } catch (const std::exception& e) {
      std::fprintf(stderr, "gym: unparseable capsule %s: %s\n", f.c_str(), e.what());
      return 1;
    }
  }

  Value payload = Value::object();
  payload.set("capsules", std::move(capsules));
  if (!policy_specs.empty()) {
    Value pol = Value::array();
    for (const std::string& s : policy_specs) pol.push_back(Value(s));
    payload.set("policies", std::move(pol));
  }
  payload.set("regret_window_s", Value(regret_window_s));
  payload.set("assume_scale_down", Value(!as_recorded));
  if (assume_interval_s > 0) payload.set("assume_interval_s", Value(assume_interval_s));
  payload.set("false_pause_penalty_chip_hours", Value(fp_penalty));
  payload.set("churn_penalty_chip_hours", Value(churn_penalty));

  Value out;
  try {
    out = simulate(payload);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "gym: %s\n", e.what());
    return 1;
  }

  std::fprintf(stderr, "policy gym: %lld capsule cycle(s), %zu policy(ies), regret window %llds\n\n",
               static_cast<long long>(out.find("cycles")->as_int()),
               out.find("policies")->as_array().size(),
               static_cast<long long>(regret_window_s));
  std::fprintf(stderr, "%-36s %14s %12s %7s %6s %8s\n", "policy", "reclaimed", "false", "churn",
               "held", "score");
  std::fprintf(stderr, "%-36s %14s %12s %7s %6s %8s\n", "", "chip-hrs", "pauses", "", "", "");
  for (const Value& p : out.find("policies")->as_array()) {
    std::fprintf(stderr, "%-36s %14.3f %12lld %7lld %6lld %8.3f\n",
                 p.get_string("name").c_str(), p.find("reclaimed_chip_hours")->as_double(),
                 static_cast<long long>(p.find("false_pauses")->as_int()),
                 static_cast<long long>(p.find("actuation_churn")->as_int()),
                 static_cast<long long>(p.find("right_size_held")->as_int()),
                 p.find("score")->as_double());
  }
  const Value* winner = out.find("winner");
  std::fprintf(stderr, "\nwinner: %s\napply with: %s\n", winner->get_string("name").c_str(),
               winner->get_string("flag_line").c_str());
  std::fprintf(stdout, "%s\n", out.dump().c_str());
  return 0;
}

}  // namespace tpupruner::gym
