#include "tpupruner/signal.hpp"

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <map>
#include <mutex>
#include <stdexcept>

#include "tpupruner/fleet.hpp"

namespace tpupruner::signal {

using json::Value;

namespace {

std::string fmt_value(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%.9g", v);
  return buf;
}

std::string fmt_ratio(double v) {
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%.3f", v);
  return buf;
}

// Label lookup with the exported_*/native fallback chain, mirroring
// metrics.cpp's decoder — the evidence query rides the same scrape
// pipeline as the idle query, so its labels wear the same prefixes.
const std::string* label(const Value& metric, const char* exported, const char* native) {
  const Value* v = metric.find(exported);
  if (v && v->is_string()) return &v->as_string();
  v = metric.find(native);
  if (v && v->is_string()) return &v->as_string();
  return nullptr;
}

// Evidence-age histogram: ages span a healthy scrape interval (tens of
// seconds) to a dead exporter (hours), so the ladder is wider and coarser
// than the phase-latency buckets in log.cpp.
constexpr double kAgeBounds[] = {15, 30, 60, 120, 300, 600, 1800, 3600, 14400, 86400};
constexpr size_t kAgeBuckets = sizeof(kAgeBounds) / sizeof(kAgeBounds[0]) + 1;

struct Registry {
  std::mutex mutex;
  bool published = false;
  Assessment latest;
  Config cfg;
  uint64_t brownouts_total = 0;
  uint64_t age_buckets[kAgeBuckets] = {};
  double age_sum = 0;
  uint64_t age_count = 0;
};

Registry& reg() {
  static Registry r;
  return r;
}

}  // namespace

const char* verdict_name(Verdict v) {
  switch (v) {
    case Verdict::Healthy: return "healthy";
    case Verdict::Stale: return "stale";
    case Verdict::Gappy: return "gappy";
    case Verdict::Absent: return "absent";
  }
  return "?";
}

size_t Assessment::count(Verdict v) const {
  size_t n = 0;
  for (const PodSignal& p : pods) {
    if (p.verdict == v) ++n;
  }
  return n;
}

namespace {

// Per-pod statistics folded out of the evidence response. After the
// query's `sum by`/`max by` there is one row per (pod, stat); duplicates
// are tolerated anyway (chip-level rows from a permissive fake or a
// non-aggregating override) by summing coverage and keeping the freshest
// age.
struct Stats {
  double samples = 0;
  double age = 0;
  bool has_samples = false, has_age = false;
};

void fold_row(std::map<std::string, Stats>& by_pod, const std::string& key,
              std::string_view stat, double x) {
  Stats& s = by_pod[key];
  if (stat == "samples") {
    s.samples += x;
    s.has_samples = true;
  } else if (stat == "age") {
    s.age = s.has_age ? std::min(s.age, x) : x;
    s.has_age = true;
  }
}

// Verdict derivation shared by the Value and Doc folds.
Assessment derive(std::map<std::string, Stats>&& by_pod,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle);

}  // namespace

Assessment assess(const Value& evidence_response,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle) {
  const Value* status = evidence_response.find("status");
  if (!status || !status->is_string() || status->as_string() != "success") {
    throw std::runtime_error("evidence query failed: " +
                             evidence_response.get_string("error", "unknown error"));
  }
  const Value* result = evidence_response.at_path("data.result");
  if (!result || !result->is_array()) {
    throw std::runtime_error("malformed evidence response: missing data.result");
  }

  std::map<std::string, Stats> by_pod;
  for (const Value& series : result->as_array()) {
    const Value* metric = series.find("metric");
    if (!metric || !metric->is_object()) continue;
    const std::string* pod = label(*metric, "exported_pod", "pod");
    const std::string* ns = label(*metric, "exported_namespace", "namespace");
    if (!pod || !ns) continue;
    std::string stat = metric->get_string("signal_stat");
    const Value* value = series.find("value");
    if (!value || !value->is_array() || value->as_array().size() != 2) continue;
    const Value& v = value->as_array()[1];
    double x = 0;
    try {
      x = v.is_string() ? std::stod(v.as_string()) : v.as_double();
    } catch (const std::exception&) {
      continue;
    }
    fold_row(by_pod, *ns + "/" + *pod, stat, x);
  }
  return derive(std::move(by_pod), candidates, cfg, cycle);
}

Assessment assess(const json::Doc& evidence_response,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle) {
  json::Doc::Node root = evidence_response.root();
  auto status = root.find("status");
  if (!status || !status->is_string() || status->as_sv() != "success") {
    throw std::runtime_error("evidence query failed: " +
                             std::string(root.get_string("error", "unknown error")));
  }
  auto result = root.at_path("data.result");
  if (!result || !result->is_array()) {
    throw std::runtime_error("malformed evidence response: missing data.result");
  }

  std::map<std::string, Stats> by_pod;
  json::Doc::Node series = result->first_child();
  for (size_t i = 0; i < result->size(); ++i, series = series.next_sibling()) {
    auto metric = series.find("metric");
    if (!metric || !metric->is_object()) continue;
    auto label_of = [&](const char* exported,
                        const char* native) -> std::optional<std::string_view> {
      if (auto v = metric->find(exported); v && v->is_string()) return v->as_sv();
      if (auto v = metric->find(native); v && v->is_string()) return v->as_sv();
      return std::nullopt;
    };
    auto pod = label_of("exported_pod", "pod");
    auto ns = label_of("exported_namespace", "namespace");
    if (!pod || !ns) continue;
    std::string_view stat = metric->get_string("signal_stat");
    auto value = series.find("value");
    if (!value || !value->is_array() || value->size() != 2) continue;
    json::Doc::Node v = value->child(1);
    double x = 0;
    try {
      x = v.is_string() ? std::stod(std::string(v.as_sv())) : v.as_double();
    } catch (const std::exception&) {
      continue;
    }
    fold_row(by_pod, std::string(*ns) + "/" + std::string(*pod), stat, x);
  }
  return derive(std::move(by_pod), candidates, cfg, cycle);
}

Assessment assess(const proto::PromVector& evidence_response,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle) {
  if (evidence_response.status != "success") {
    throw std::runtime_error(
        "evidence query failed: " +
        (evidence_response.error.empty() ? "unknown error" : evidence_response.error));
  }
  std::map<std::string, Stats> by_pod;
  for (const proto::PromSeries& series : evidence_response.result) {
    auto label_of = [&](std::string_view exported,
                        std::string_view native) -> const std::string* {
      const std::string* native_hit = nullptr;
      for (const auto& [name, value] : series.labels) {
        if (name == exported) return &value;
        if (!native_hit && name == native) native_hit = &value;
      }
      return native_hit;
    };
    const std::string* pod = label_of("exported_pod", "pod");
    const std::string* ns = label_of("exported_namespace", "namespace");
    if (!pod || !ns) continue;
    std::string stat;
    for (const auto& [name, value] : series.labels) {
      if (name == "signal_stat") {
        stat = value;
        break;
      }
    }
    double x = 0;
    try {
      x = std::stod(series.value_text);
    } catch (const std::exception&) {
      continue;
    }
    fold_row(by_pod, *ns + "/" + *pod, stat, x);
  }
  return derive(std::move(by_pod), candidates, cfg, cycle);
}

namespace {

Assessment derive(std::map<std::string, Stats>&& by_pod,
                  const std::vector<core::PodMetricSample>& candidates, const Config& cfg,
                  uint64_t cycle) {
  Assessment out;
  out.cycle = cycle;
  out.min_coverage = cfg.min_coverage;
  const double min_samples = cfg.min_samples();
  size_t healthy = 0;
  for (const core::PodMetricSample& c : candidates) {
    PodSignal p;
    p.ns = c.ns;
    p.pod = c.name;
    auto it = by_pod.find(c.ns + "/" + c.name);
    if (it != by_pod.end()) {
      p.sample_count = it->second.samples;
      p.last_age_s = it->second.age;
      p.has_samples = it->second.has_samples;
      p.has_age = it->second.has_age;
    }
    if (!p.has_samples && !p.has_age) {
      p.verdict = Verdict::Absent;
    } else if (p.has_age && p.last_age_s > static_cast<double>(cfg.max_age_s)) {
      p.verdict = Verdict::Stale;
    } else if (p.has_samples && p.sample_count < min_samples) {
      p.verdict = Verdict::Gappy;
    } else {
      p.verdict = Verdict::Healthy;
      ++healthy;
    }
    out.pods.push_back(std::move(p));
  }
  out.coverage_ratio =
      candidates.empty() ? 1.0
                         : static_cast<double>(healthy) / static_cast<double>(candidates.size());
  out.brownout = !candidates.empty() && out.coverage_ratio < cfg.min_coverage;
  return out;
}

}  // namespace

audit::Reason veto_reason(Verdict v) {
  switch (v) {
    case Verdict::Stale: return audit::Reason::SignalStale;
    case Verdict::Gappy: return audit::Reason::SignalGappy;
    case Verdict::Absent: return audit::Reason::SignalAbsent;
    case Verdict::Healthy: break;
  }
  return audit::Reason::SignalAbsent;
}

std::string veto_detail(const PodSignal& p, const Config& cfg) {
  switch (p.verdict) {
    case Verdict::Stale:
      return "newest sample " + fmt_value(p.last_age_s) + "s old, over --signal-max-age=" +
             std::to_string(cfg.max_age_s) + "s (the idle reading is a memory, not a fact)";
    case Verdict::Gappy:
      return "only " + fmt_value(p.sample_count) + " samples over the " +
             std::to_string(cfg.window_s) + "s window, below the " + fmt_value(cfg.min_samples()) +
             " floor (--signal-scrape-interval=" + std::to_string(cfg.scrape_interval_s) + "s)";
    case Verdict::Absent:
      return "no evidence series for this pod (metric family absent or dropped by relabeling)";
    case Verdict::Healthy:
      break;
  }
  return "";
}

std::string brownout_detail(const Assessment& a, const Config& cfg) {
  return "signal brownout: healthy evidence coverage " + fmt_ratio(a.coverage_ratio) +
         " below --signal-min-coverage=" + fmt_ratio(cfg.min_coverage) +
         "; all scale-downs deferred this cycle";
}

json::Value assessment_to_json(const Assessment& a) {
  Value v = Value::object();
  v.set("cycle", Value(static_cast<int64_t>(a.cycle)));
  v.set("coverage_ratio", Value(a.coverage_ratio));
  v.set("brownout", Value(a.brownout));
  v.set("min_coverage", Value(a.min_coverage));
  Value counts = Value::object();
  for (Verdict verdict : {Verdict::Healthy, Verdict::Stale, Verdict::Gappy, Verdict::Absent}) {
    counts.set(verdict_name(verdict), Value(static_cast<int64_t>(a.count(verdict))));
  }
  v.set("pods", std::move(counts));
  Value details = Value::array();
  for (const PodSignal& p : a.pods) {
    Value d = Value::object();
    d.set("namespace", Value(p.ns));
    d.set("pod", Value(p.pod));
    d.set("verdict", Value(std::string(verdict_name(p.verdict))));
    if (p.has_samples) d.set("sample_count", Value(p.sample_count));
    if (p.has_age) d.set("last_age_s", Value(p.last_age_s));
    details.push_back(std::move(d));
  }
  v.set("details", std::move(details));
  return v;
}

Assessment assessment_from_json(const json::Value& v) {
  Assessment a;
  if (const Value* x = v.find("cycle"); x && x->is_number())
    a.cycle = static_cast<uint64_t>(x->as_int());
  if (const Value* x = v.find("coverage_ratio"); x && x->is_number())
    a.coverage_ratio = x->as_double();
  if (const Value* x = v.find("brownout"); x && x->is_bool()) a.brownout = x->as_bool();
  if (const Value* x = v.find("min_coverage"); x && x->is_number())
    a.min_coverage = x->as_double();
  if (const Value* details = v.find("details"); details && details->is_array()) {
    for (const Value& d : details->as_array()) {
      PodSignal p;
      p.ns = d.get_string("namespace");
      p.pod = d.get_string("pod");
      std::string verdict = d.get_string("verdict");
      for (Verdict candidate :
           {Verdict::Healthy, Verdict::Stale, Verdict::Gappy, Verdict::Absent}) {
        if (verdict == verdict_name(candidate)) p.verdict = candidate;
      }
      if (const Value* x = d.find("sample_count"); x && x->is_number()) {
        p.sample_count = x->as_double();
        p.has_samples = true;
      }
      if (const Value* x = d.find("last_age_s"); x && x->is_number()) {
        p.last_age_s = x->as_double();
        p.has_age = true;
      }
      a.pods.push_back(std::move(p));
    }
  }
  return a;
}

void publish(const Assessment& a, const Config& cfg) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  r.published = true;
  r.latest = a;
  r.cfg = cfg;
  if (a.brownout) ++r.brownouts_total;
  for (const PodSignal& p : a.pods) {
    if (!p.has_age) continue;
    size_t idx = std::lower_bound(std::begin(kAgeBounds), std::end(kAgeBounds), p.last_age_s) -
                 std::begin(kAgeBounds);
    ++r.age_buckets[idx];
    r.age_sum += p.last_age_s;
    ++r.age_count;
  }
}

json::Value signals_json() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  if (!r.published) {
    Value v = Value::object();
    v.set("cluster", Value(fleet::cluster_name()));
    v.set("enabled", Value(false));
    v.set("hint", Value("run the daemon with --signal-guard on to assess evidence health"));
    return v;
  }
  Value v = assessment_to_json(r.latest);
  v.set("cluster", Value(fleet::cluster_name()));
  v.set("enabled", Value(true));
  v.set("brownouts_total", Value(static_cast<int64_t>(r.brownouts_total)));
  Value thresholds = Value::object();
  thresholds.set("scrape_interval_s", Value(r.cfg.scrape_interval_s));
  thresholds.set("max_age_s", Value(r.cfg.max_age_s));
  thresholds.set("min_coverage", Value(r.cfg.min_coverage));
  thresholds.set("window_s", Value(r.cfg.window_s));
  thresholds.set("min_samples", Value(r.cfg.min_samples()));
  v.set("thresholds", std::move(thresholds));
  return v;
}

std::string render_metrics(bool openmetrics) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  // Absent-not-zero, like the informer families: before the first
  // assessment (guard off) these series would read "no coverage, never
  // brownouted" — a dashboard would misread silence as health.
  if (!r.published) return "";

  auto family = [&](const std::string& name, const char* type, const std::string& help) {
    std::string fam = name;
    if (openmetrics && std::string(type) == "counter" && fam.size() > 6 &&
        fam.compare(fam.size() - 6, 6, "_total") == 0) {
      fam = fam.substr(0, fam.size() - 6);
    }
    return "# HELP " + fam + " " + help + "\n# TYPE " + fam + " " + type + "\n";
  };

  std::string body;
  body += family("tpu_pruner_signal_coverage_ratio", "gauge",
                 "Fraction of last cycle's candidate pods whose evidence is healthy");
  body += "tpu_pruner_signal_coverage_ratio " + fmt_value(r.latest.coverage_ratio) + "\n";

  body += family("tpu_pruner_signal_pods", "gauge",
                 "Last cycle's candidate pods by evidence verdict "
                 "(healthy|stale|gappy|absent)");
  for (Verdict v : {Verdict::Healthy, Verdict::Stale, Verdict::Gappy, Verdict::Absent}) {
    body += "tpu_pruner_signal_pods{verdict=\"" + std::string(verdict_name(v)) + "\"} " +
            std::to_string(r.latest.count(v)) + "\n";
  }

  body += family("tpu_pruner_signal_brownouts_total", "counter",
                 "Cycles whose scale-downs were all deferred because healthy evidence "
                 "coverage fell below --signal-min-coverage");
  body += "tpu_pruner_signal_brownouts_total " + std::to_string(r.brownouts_total) + "\n";

  body += family("tpu_pruner_pod_signal_age_seconds", "histogram",
                 "Age of each candidate pod's newest utilization sample, per cycle");
  uint64_t cum = 0;
  for (size_t i = 0; i < kAgeBuckets; ++i) {
    cum += r.age_buckets[i];
    std::string le = i < kAgeBuckets - 1 ? fmt_value(kAgeBounds[i]) : "+Inf";
    body += "tpu_pruner_pod_signal_age_seconds_bucket{le=\"" + le + "\"} " +
            std::to_string(cum) + "\n";
  }
  body += "tpu_pruner_pod_signal_age_seconds_sum " + fmt_value(r.age_sum) + "\n";
  body += "tpu_pruner_pod_signal_age_seconds_count " + std::to_string(r.age_count) + "\n";
  return body;
}

std::vector<std::string> metric_families() {
  return {
      "tpu_pruner_signal_coverage_ratio",
      "tpu_pruner_signal_pods",
      "tpu_pruner_signal_brownouts_total",
      "tpu_pruner_pod_signal_age_seconds",
  };
}

void reset_for_test() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  r.published = false;
  r.latest = Assessment{};
  r.cfg = Config{};
  r.brownouts_total = 0;
  std::fill(std::begin(r.age_buckets), std::end(r.age_buckets), 0);
  r.age_sum = 0;
  r.age_count = 0;
}

}  // namespace tpupruner::signal
