#include "tpupruner/http.hpp"

#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <stdexcept>

#include "tls.hpp"
#include "tpupruner/backoff.hpp"
#include "tpupruner/h2.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::http {

namespace {
[[noreturn]] void fail(const std::string& msg) { throw std::runtime_error("http: " + msg); }
}  // namespace

namespace detail {

// One live connection: owned fd, optional TLS session, leftover read buffer.
struct Conn {
  int fd = -1;
  std::unique_ptr<tls::Conn> tls_conn;
  bool reused = false;  // came from the pool (stale-retry eligibility)

  ~Conn() {
    tls_conn.reset();  // TLS shutdown before close
    if (fd >= 0) ::close(fd);
  }

  size_t read(char* buf, size_t n) {
    if (tls_conn) return tls_conn->read(buf, n);
    ssize_t rc = ::recv(fd, buf, n, 0);
    if (rc < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) fail("read timeout");
      fail(std::string("read: ") + std::strerror(errno));
    }
    return static_cast<size_t>(rc);
  }

  void write_all(const char* buf, size_t n) {
    if (tls_conn) {
      tls_conn->write_all(buf, n);
      return;
    }
    size_t off = 0;
    while (off < n) {
      ssize_t rc = ::send(fd, buf + off, n - off, MSG_NOSIGNAL);
      if (rc < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) fail("write timeout");
        fail(std::string("write: ") + std::strerror(errno));
      }
      off += static_cast<size_t>(rc);
    }
  }

  void set_timeout(int timeout_ms) {
    struct timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }
};

}  // namespace detail

namespace {

using detail::Conn;

int connect_with_timeout(const std::string& host, int port, int timeout_ms) {
  struct addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  int rc = ::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res);
  if (rc != 0) fail("resolve " + host + ": " + gai_strerror(rc));
  std::unique_ptr<addrinfo, decltype(&freeaddrinfo)> res_guard(res, freeaddrinfo);

  std::string last_err = "no addresses";
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    int fd = ::socket(ai->ai_family, ai->ai_socktype | SOCK_NONBLOCK, ai->ai_protocol);
    if (fd < 0) continue;
    rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      struct pollfd pfd{fd, POLLOUT, 0};
      rc = ::poll(&pfd, 1, timeout_ms);
      if (rc == 1) {
        int err = 0;
        socklen_t len = sizeof(err);
        ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err == 0) rc = 0;
        else {
          last_err = std::strerror(err);
          rc = -1;
        }
      } else {
        last_err = rc == 0 ? "connect timeout" : std::strerror(errno);
        rc = -1;
      }
    } else if (rc != 0) {
      last_err = std::strerror(errno);
    }
    if (rc == 0) {
      int nodelay = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nodelay, sizeof(nodelay));
      // Install socket timeouts BEFORE any TLS handshake runs on this fd —
      // SSL_connect on a blocking socket would otherwise hang forever on a
      // black-holed peer.
      struct timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      int flags = ::fcntl(fd, F_GETFL, 0);
      ::fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
      return fd;
    }
    ::close(fd);
  }
  fail("connect " + host + ":" + port_s + ": " + last_err);
}

// Largest response the client will buffer. Far above any real Prometheus
// vector or K8s LIST this daemon sees, but finite: a hostile or broken
// server advertising a multi-terabyte content-length / chunk size must
// produce a transport error, not an OOM kill.
constexpr size_t kMaxResponseBytes = 256u << 20;  // 256 MiB

// Thrown (and caught inside request_stream) when the caller's abort
// predicate fires mid-stream — an orderly local hang-up, not an error.
struct StreamAborted : std::runtime_error {
  StreamAborted() : std::runtime_error("stream aborted by caller") {}
};

// Incremental reader with buffering for header/line parsing.
struct Reader {
  Conn& conn;
  std::string buf{};
  size_t pos = 0;
  bool eof = false;
  bool got_bytes = false;  // any response bytes at all (stale-retry signal)
  // Streaming mode: polled ~4x/s while the socket is idle so a watch
  // shutdown never waits out the full read timeout.
  std::function<bool()> abort_check{};

  bool fill() {
    if (eof) return false;
    if (abort_check) {
      // TLS may hold decrypted bytes a raw-fd poll can't see.
      while (!(conn.tls_conn && conn.tls_conn->pending())) {
        struct pollfd pfd{conn.fd, POLLIN, 0};
        int rc = ::poll(&pfd, 1, 250);
        if (rc > 0) break;
        if (rc < 0 && errno != EINTR) fail(std::string("poll: ") + std::strerror(errno));
        if (abort_check()) throw StreamAborted();
      }
    }
    // Cap the UNCONSUMED tail, not the lifetime stream: consumed bytes are
    // trimmed below, so a legal body of exactly kMaxResponseBytes passes
    // while a hostile one fails before buffering past ~the cap.
    if (buf.size() - pos > kMaxResponseBytes) {
      fail("response exceeds " + std::to_string(kMaxResponseBytes) + " bytes");
    }
    if (pos > (1u << 20)) {  // trim consumed prefix; keeps peak ≈ cap, not 2x
      buf.erase(0, pos);
      pos = 0;
    }
    char chunk[16384];
    size_t n = conn.read(chunk, sizeof(chunk));
    if (n == 0) {
      eof = true;
      return false;
    }
    got_bytes = true;
    buf.append(chunk, n);
    return true;
  }

  std::string read_line() {
    while (true) {
      size_t nl = buf.find('\n', pos);
      if (nl != std::string::npos) {
        std::string line = buf.substr(pos, nl - pos);
        pos = nl + 1;
        if (!line.empty() && line.back() == '\r') line.pop_back();
        return line;
      }
      if (!fill()) fail("unexpected EOF in response");
    }
  }

  std::string read_exact(size_t n) {
    if (n > kMaxResponseBytes) {
      fail("declared body size " + std::to_string(n) + " exceeds " +
           std::to_string(kMaxResponseBytes) + " bytes");
    }
    while (buf.size() - pos < n) {
      if (!fill()) fail("unexpected EOF in body");
    }
    std::string out = buf.substr(pos, n);
    pos += n;
    return out;
  }

  std::string read_to_eof() {
    while (fill()) {
    }
    std::string out = buf.substr(pos);
    pos = buf.size();
    return out;
  }

  bool drained() const { return pos >= buf.size(); }
};

struct StaleConnection : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// ── egress proxy (HTTPS_PROXY / HTTP_PROXY / NO_PROXY) ──
//
// The reference inherits this de-facto env contract from reqwest
// (lib.rs:240-282 builds on its defaults): https targets honor
// HTTPS_PROXY, http targets HTTP_PROXY, NO_PROXY lists bypass hosts
// ("*" = bypass all; entries match exact host or domain suffix, string-
// wise like curl — "127.0.0.1" does not match "localhost"). https is
// tunneled with CONNECT; http is forwarded in absolute-form. Only
// http:// proxies are supported (TLS *to* the proxy is rare enough that
// reqwest gates it behind a non-default feature too).
struct ProxyTarget {
  std::string host;
  int port = 80;
  std::string basic_auth;  // full header value, e.g. "Basic dXNlcjpwdw=="
};

bool no_proxy_match(const std::string& host, const std::string& list) {
  std::string h = util::to_lower(host);
  for (const std::string& raw : util::split(list, ',')) {
    std::string e = util::to_lower(util::trim(raw));
    if (e.empty()) continue;
    if (e == "*") return true;
    if (!e.empty() && e.front() == '.') e.erase(0, 1);
    // strip a :port suffix (but leave IPv6 literals alone)
    if (size_t colon = e.rfind(':');
        colon != std::string::npos && e.find(':') == colon) {
      e.resize(colon);
    }
    if (h == e) return true;
    if (h.size() > e.size() && h[h.size() - e.size() - 1] == '.' &&
        h.compare(h.size() - e.size(), e.size(), e) == 0) {
      return true;
    }
  }
  return false;
}

ProxyTarget parse_proxy_spec(const std::string& spec) {
  std::string s = spec;
  if (s.find("://") == std::string::npos) s = "http://" + s;
  // Only plaintext-HTTP proxies: https:// (TLS to the proxy) and socks5://
  // would silently speak the wrong protocol to that port, turning every
  // cycle into opaque transport errors — fail loudly instead. Schemes are
  // case-insensitive (RFC 3986), so HTTP://… must pass.
  if (util::to_lower(s.substr(0, 7)) != "http://") {
    fail("unsupported proxy scheme in " + spec + " (only http:// proxies are supported)");
  }
  // split out userinfo before parse_url (which doesn't model it)
  ProxyTarget out;
  std::string rest = s.substr(7);
  if (size_t slash = rest.find('/'); slash != std::string::npos) rest.resize(slash);
  if (size_t at = rest.rfind('@'); at != std::string::npos) {
    // Percent-decode first (curl/reqwest semantics): a password containing
    // '@' or ':' MUST be %-encoded in the URL, and the Basic credentials
    // carry the decoded form.
    out.basic_auth = "Basic " + util::base64_encode(util::url_decode(rest.substr(0, at)));
    rest = rest.substr(at + 1);
  }
  auto parsed = parse_url("http://" + rest + "/");
  if (!parsed) fail("invalid proxy url in environment: " + spec);
  out.host = parsed->host;
  out.port = parsed->port;
  return out;
}

// Env is fixed for the process lifetime, so the whole proxy config —
// getenv, URL parse, credential encoding — is computed exactly once
// (thread-safe static init); per-request work is one NO_PROXY string
// match. A malformed proxy URL throws on first use and retries on the
// next call (function-local static init semantics), staying loud.
struct ProxyEnv {
  std::optional<ProxyTarget> https_proxy, http_proxy;
  std::string no_proxy;
};

const ProxyEnv& proxy_env() {
  static const ProxyEnv env = [] {
    auto env2 = [](const char* upper, const char* lower) -> std::optional<std::string> {
      if (auto v = util::env(upper); v && !v->empty()) return v;
      if (auto v = util::env(lower); v && !v->empty()) return v;
      return std::nullopt;
    };
    ProxyEnv out;
    out.no_proxy = env2("NO_PROXY", "no_proxy").value_or("");
    if (auto s = env2("HTTPS_PROXY", "https_proxy")) out.https_proxy = parse_proxy_spec(*s);
    if (auto s = env2("HTTP_PROXY", "http_proxy")) out.http_proxy = parse_proxy_spec(*s);
    return out;
  }();
  return env;
}

std::optional<ProxyTarget> proxy_for(const Url& url) {
  // The GCE metadata server is link-local: no egress proxy can ever reach
  // it, and google-auth/gcloud always bypass proxies for it. Without this,
  // HTTPS_PROXY would break Workload Identity token minting in-cluster.
  if (url.host == "metadata.google.internal" || url.host == "169.254.169.254") {
    return std::nullopt;
  }
  const ProxyEnv& env = proxy_env();
  const std::optional<ProxyTarget>& proxy =
      url.scheme == "https" ? env.https_proxy : env.http_proxy;
  if (!proxy) return std::nullopt;
  if (!env.no_proxy.empty() && no_proxy_match(url.host, env.no_proxy)) return std::nullopt;
  return proxy;
}

// Issues CONNECT on a fresh proxy connection and validates the 200 before
// the TLS handshake rides the tunnel.
void establish_tunnel(Conn& conn, const Url& target, const ProxyTarget& proxy,
                      int timeout_ms) {
  std::string authority = target.host + ":" + std::to_string(target.port);
  std::string creq = "CONNECT " + authority + " HTTP/1.1\r\nHost: " + authority + "\r\n";
  if (!proxy.basic_auth.empty()) creq += "Proxy-Authorization: " + proxy.basic_auth + "\r\n";
  creq += "\r\n";
  conn.set_timeout(timeout_ms);
  conn.write_all(creq.data(), creq.size());
  Reader reader{conn};
  std::string status_line = reader.read_line();
  size_t sp = status_line.find(' ');
  int code = sp == std::string::npos ? 0 : std::atoi(status_line.c_str() + sp + 1);
  while (!reader.read_line().empty()) {
  }
  if (code != 200) {
    fail("proxy CONNECT " + authority + " via " + proxy.host + ":" +
         std::to_string(proxy.port) + " → " + status_line);
  }
  // Safe to hand the fd to TLS: the server end of the tunnel cannot have
  // sent bytes yet (TLS servers speak only after ClientHello), so the
  // reader buffer is empty past the proxy headers.
}

// Serialized request line + headers + body. Through an http proxy,
// plain-http requests go out in absolute-form (RFC 9112 §3.2.2) so the
// proxy knows the upstream; tunneled https and direct connections keep
// origin-form.
std::string build_request_message(const Request& req, const Url& url,
                                  const std::optional<ProxyTarget>& proxy,
                                  const std::string& traceparent = "") {
  std::string request_target = url.target;
  if (proxy && url.scheme == "http") {
    request_target = "http://" + url.host +
                     (url.port != 80 ? ":" + std::to_string(url.port) : "") + url.target;
  }
  std::string msg = req.method + " " + request_target + " HTTP/1.1\r\n";
  msg += "Host: " + url.host +
         (url.port != (url.scheme == "https" ? 443 : 80) ? ":" + std::to_string(url.port) : "") +
         "\r\n";
  if (proxy && url.scheme == "http" && !proxy->basic_auth.empty()) {
    msg += "Proxy-Authorization: " + proxy->basic_auth + "\r\n";
  }
  bool has_ua = false;
  bool has_traceparent = false;
  for (const auto& [k, v] : req.headers) {
    msg += k + ": " + v + "\r\n";
    std::string lk = util::to_lower(k);
    if (lk == "user-agent") has_ua = true;
    if (lk == "traceparent") has_traceparent = true;
  }
  if (!has_ua) msg += "User-Agent: tpu-pruner/0.1\r\n";
  if (!has_traceparent && !traceparent.empty()) msg += "traceparent: " + traceparent + "\r\n";
  if (!req.body.empty() || req.method == "POST" || req.method == "PATCH" || req.method == "PUT") {
    msg += "Content-Length: " + std::to_string(req.body.size()) + "\r\n";
  }
  msg += "\r\n";
  msg += req.body;
  return msg;
}

// Header block into resp.headers (keys lowercased), up to the blank line.
void read_headers(Reader& reader, Response& resp) {
  while (true) {
    std::string line = reader.read_line();
    if (line.empty()) break;
    size_t colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = util::to_lower(util::trim(line.substr(0, colon)));
    resp.headers[key] = util::trim(line.substr(colon + 1));
  }
}

// Fresh (non-pooled) connection to `url`, via `proxy` when set, TLS
// attached for https — the connect path request_once uses on a pool miss,
// shared with the streaming entry point.
std::unique_ptr<Conn> open_fresh_conn(const Url& url, const std::optional<ProxyTarget>& proxy,
                                      int timeout_ms, TlsMode tls_mode,
                                      const std::string& ca_file) {
  auto conn = std::make_unique<Conn>();
  if (proxy) {
    conn->fd = connect_with_timeout(proxy->host, proxy->port, timeout_ms);
    if (url.scheme == "https") {
      establish_tunnel(*conn, url, *proxy, timeout_ms);
    }
  } else {
    conn->fd = connect_with_timeout(url.host, url.port, timeout_ms);
  }
  if (url.scheme == "https") {
    conn->tls_conn = std::make_unique<tls::Conn>(conn->fd, url.host,
                                                 tls_mode == TlsMode::Verify, ca_file);
  }
  h2::counters().http1_connections.fetch_add(1, std::memory_order_relaxed);
  return conn;
}

}  // namespace

int connect_tcp(const std::string& host, int port, int timeout_ms) {
  return connect_with_timeout(host, port, timeout_ms);
}

bool proxy_in_use(const Url& url) { return proxy_for(url).has_value(); }

std::optional<Url> parse_url(std::string_view url) {
  Url out;
  size_t scheme_end = url.find("://");
  if (scheme_end == std::string_view::npos) return std::nullopt;
  out.scheme = std::string(url.substr(0, scheme_end));
  if (out.scheme != "http" && out.scheme != "https") return std::nullopt;
  out.port = out.scheme == "https" ? 443 : 80;

  std::string_view rest = url.substr(scheme_end + 3);
  size_t path_start = rest.find('/');
  std::string_view authority =
      path_start == std::string_view::npos ? rest : rest.substr(0, path_start);
  out.target = path_start == std::string_view::npos ? "/" : std::string(rest.substr(path_start));
  if (authority.empty()) return std::nullopt;

  if (authority.front() == '[') {  // IPv6 literal
    size_t close = authority.find(']');
    if (close == std::string_view::npos) return std::nullopt;
    out.host = std::string(authority.substr(1, close - 1));
    if (close + 1 < authority.size() && authority[close + 1] == ':') {
      out.port = std::atoi(std::string(authority.substr(close + 2)).c_str());
    }
  } else {
    size_t colon = authority.rfind(':');
    if (colon != std::string_view::npos) {
      out.host = std::string(authority.substr(0, colon));
      out.port = std::atoi(std::string(authority.substr(colon + 1)).c_str());
    } else {
      out.host = std::string(authority);
    }
  }
  if (out.host.empty() || out.port <= 0 || out.port > 65535) return std::nullopt;
  return out;
}

Client::Client(TlsMode tls_mode, std::string ca_file)
    : tls_mode_(tls_mode), ca_file_(std::move(ca_file)) {}

Client::~Client() = default;

Client::Client(Client&& other) noexcept
    : tls_mode_(other.tls_mode_), ca_file_(std::move(other.ca_file_)) {
  std::lock_guard<std::mutex> lock(other.pool_mutex_);
  pool_ = std::move(other.pool_);
  std::lock_guard<std::mutex> tp_lock(other.traceparent_mutex_);
  default_traceparent_ = std::move(other.default_traceparent_);
}

namespace {
thread_local std::string t_traceparent;
}  // namespace

void set_thread_traceparent(std::string tp) { t_traceparent = std::move(tp); }
const std::string& thread_traceparent() { return t_traceparent; }

void Client::set_default_traceparent(std::string tp) const {
  std::lock_guard<std::mutex> lock(traceparent_mutex_);
  default_traceparent_ = std::move(tp);
}

std::string Client::resolved_traceparent(const Request& req) const {
  for (const auto& [k, v] : req.headers) {
    if (util::to_lower(k) == "traceparent") return "";  // explicit header wins
  }
  if (!t_traceparent.empty()) return t_traceparent;
  std::lock_guard<std::mutex> lock(traceparent_mutex_);
  return default_traceparent_;
}

Response Client::request(const Request& req) const {
  auto url = parse_url(req.url);
  if (!url) fail("invalid url: " + req.url);
  // POST is the one non-idempotent method this client carries (Event
  // creation); it always goes out on a fresh connection so a stale pooled
  // socket can never force the replay-or-fail dilemma (RFC 9110 §9.2.2
  // permits automatic retry only for idempotent requests). GET/PATCH
  // (merge-patches here: replicas=0, suspend=true) are safe to replay.
  bool reuse_ok = req.method != "POST";
  try {
    return request_once(req, *url, reuse_ok);
  } catch (const StaleConnection& e) {
    // The pooled connection died between requests (idle timeout on the
    // server side — clean FIN or ECONNRESET before any response byte). A
    // single retry on a fresh connection is safe for these idempotent
    // methods; surfacing it as a cycle error would turn routine server
    // idle-timeouts into failure-budget ticks.
    h2::counters().retries.fetch_add(1, std::memory_order_relaxed);
    // Immediate replay (no wait): still accounted through the unified
    // backoff telemetry so tpu_pruner_retries_total covers every retry
    // in the process, not just the delayed ones.
    backoff::record_retry("transport", "stale_conn", 0.0);
    log::debug("http", "retrying " + req.method + " " + url->host + ":" +
                           std::to_string(url->port) + url->target +
                           " on a fresh connection (stale keep-alive socket: " + e.what() + ")");
    return request_once(req, *url, /*allow_reuse=*/false);
  }
}

Response Client::request_once(const Request& req, const Url& url, bool allow_reuse) const {
  const std::string pool_key = url.scheme + "://" + url.host + ":" + std::to_string(url.port);
  std::optional<ProxyTarget> proxy = proxy_for(url);

  std::unique_ptr<Conn> conn;
  if (allow_reuse) {
    std::lock_guard<std::mutex> lock(pool_mutex_);
    auto it = pool_.find(pool_key);
    if (it != pool_.end()) {
      conn = std::move(it->second);
      conn->reused = true;
      pool_.erase(it);
    }
  }
  if (!conn) {
    conn = open_fresh_conn(url, proxy, req.timeout_ms, tls_mode_, ca_file_);
  }
  conn->set_timeout(req.timeout_ms);
  std::string msg = build_request_message(req, url, proxy, resolved_traceparent(req));

  // Wire log under its own module so production debugging can do
  // `TPU_PRUNER_LOG=info,http=trace` (or the inverse: silence it with
  // http=error) — the reference's hyper/reqwest EnvFilter noise story
  // (main.rs:159-170). Never logs bodies: they can carry bearer tokens.
  // Gated up front: hundreds of requests per cycle must not pay the
  // string building just to have write() drop it.
  const bool wire_trace = log::threshold_for("http") <= log::Level::Trace;
  if (wire_trace) {
    log::trace("http", req.method + " " + url.scheme + "://" + url.host + ":" +
                           std::to_string(url.port) + url.target + " body=" +
                           std::to_string(req.body.size()) + "B" +
                           (conn->reused ? " (pooled)" : " (fresh)"));
  }

  Reader reader{*conn};
  try {
    conn->write_all(msg.data(), msg.size());
  } catch (const std::exception& e) {
    if (conn->reused) throw StaleConnection(e.what());
    throw;
  }

  // ── read response ──
  Response resp;
  try {
    std::string status_line = reader.read_line();
    auto sp1 = status_line.find(' ');
    if (sp1 == std::string::npos) fail("malformed status line: " + status_line);
    resp.status = std::atoi(status_line.c_str() + sp1 + 1);
    if (resp.status < 100 || resp.status > 599) fail("bad status in: " + status_line);
  } catch (const std::exception& e) {
    // EOF/reset before any bytes on a reused connection → stale.
    if (conn->reused && !reader.got_bytes) throw StaleConnection(e.what());
    throw;
  }
  read_headers(reader, resp);

  bool keep_alive = true;
  if (auto c = resp.headers.find("connection"); c != resp.headers.end()) {
    keep_alive = util::to_lower(c->second).find("close") == std::string::npos;
  }

  bool body_expected = !(req.method == "HEAD" || resp.status == 204 || resp.status == 304);
  if (body_expected) {
    auto te = resp.headers.find("transfer-encoding");
    if (te != resp.headers.end() &&
        util::to_lower(te->second).find("chunked") != std::string::npos) {
      while (true) {
        std::string size_line = reader.read_line();
        size_t semi = size_line.find(';');
        if (semi != std::string::npos) size_line.resize(semi);
        size_t chunk_size = 0;
        try {
          chunk_size = static_cast<size_t>(std::stoul(util::trim(size_line), nullptr, 16));
        } catch (const std::exception&) {
          fail("bad chunk size: " + size_line);
        }
        if (chunk_size == 0) break;
        resp.body += reader.read_exact(chunk_size);
        if (resp.body.size() > kMaxResponseBytes) {
          fail("chunked response exceeds " + std::to_string(kMaxResponseBytes) + " bytes");
        }
        reader.read_line();  // CRLF after chunk data
      }
      // Trailers until blank line; the body is already complete, so a
      // server closing without the final CRLF is tolerated (the connection
      // just isn't reusable).
      try {
        while (true) {
          std::string line = reader.read_line();
          if (line.empty()) break;
        }
      } catch (const std::exception&) {
        keep_alive = false;
      }
    } else if (auto cl = resp.headers.find("content-length"); cl != resp.headers.end()) {
      size_t n = 0;
      try {
        n = static_cast<size_t>(std::stoul(cl->second));
      } catch (const std::exception&) {
        fail("bad content-length: " + cl->second);
      }
      resp.body = reader.read_exact(n);
    } else {
      // Close-delimited body: connection is not reusable afterwards.
      resp.body = reader.read_to_eof();
      keep_alive = false;
    }
  }

  if (wire_trace) {
    log::trace("http", "→ " + std::to_string(resp.status) + ", " +
                           std::to_string(resp.body.size()) + "B");
  }

  // Return the connection to the pool only when the response framing left
  // it exactly at a message boundary.
  if (keep_alive && reader.drained() && !reader.eof) {
    std::lock_guard<std::mutex> lock(pool_mutex_);
    if (pool_.count(pool_key) < 32) {
      conn->reused = false;
      pool_.emplace(pool_key, std::move(conn));
    }
  }
  return resp;
}

Response Client::request_stream(const Request& req,
                                const std::function<bool(const char*, size_t)>& on_data,
                                const std::function<bool()>& abort,
                                const std::function<void(const Response&)>& on_headers) const {
  auto url = parse_url(req.url);
  if (!url) fail("invalid url: " + req.url);
  std::optional<ProxyTarget> proxy = proxy_for(*url);
  std::unique_ptr<Conn> conn =
      open_fresh_conn(*url, proxy, req.timeout_ms, tls_mode_, ca_file_);
  conn->set_timeout(req.timeout_ms);

  std::string msg = build_request_message(req, *url, proxy, resolved_traceparent(req));
  conn->write_all(msg.data(), msg.size());

  Response resp;
  Reader reader{*conn};
  std::string status_line = reader.read_line();
  auto sp1 = status_line.find(' ');
  if (sp1 == std::string::npos) fail("malformed status line: " + status_line);
  resp.status = std::atoi(status_line.c_str() + sp1 + 1);
  if (resp.status < 100 || resp.status > 599) fail("bad status in: " + status_line);
  read_headers(reader, resp);
  if (on_headers) on_headers(resp);
  // Arm the abort poll only for the body: headers arrive promptly, bodies
  // (watch streams) idle for arbitrary stretches.
  reader.abort_check = abort;

  // Deliver consumed-and-decoded body bytes; returns false to stop.
  auto deliver = [&](const char* data, size_t n) { return n == 0 || on_data(data, n); };
  bool body_expected = !(req.method == "HEAD" || resp.status == 204 || resp.status == 304);
  try {
    if (!body_expected) return resp;
    auto te = resp.headers.find("transfer-encoding");
    if (te != resp.headers.end() &&
        util::to_lower(te->second).find("chunked") != std::string::npos) {
      while (true) {
        std::string size_line = reader.read_line();
        size_t semi = size_line.find(';');
        if (semi != std::string::npos) size_line.resize(semi);
        size_t chunk_size = 0;
        try {
          chunk_size = static_cast<size_t>(std::stoul(util::trim(size_line), nullptr, 16));
        } catch (const std::exception&) {
          fail("bad chunk size: " + size_line);
        }
        if (chunk_size == 0) break;
        std::string chunk = reader.read_exact(chunk_size);
        reader.read_line();  // CRLF after chunk data
        if (!deliver(chunk.data(), chunk.size())) return resp;
      }
      // Trailers are tolerated like request_once: the body is complete.
      try {
        while (!reader.read_line().empty()) {
        }
      } catch (const std::exception&) {
      }
    } else if (auto cl = resp.headers.find("content-length"); cl != resp.headers.end()) {
      size_t n = 0;
      try {
        n = static_cast<size_t>(std::stoul(cl->second));
      } catch (const std::exception&) {
        fail("bad content-length: " + cl->second);
      }
      size_t remaining = n;
      while (remaining > 0) {
        // Drain buffered bytes first, then read socket-sized pieces —
        // never buffer the whole declared length (a watch would OOM).
        if (reader.drained() && !reader.fill()) fail("unexpected EOF in body");
        size_t take = std::min(remaining, reader.buf.size() - reader.pos);
        std::string piece = reader.read_exact(take);
        remaining -= take;
        if (!deliver(piece.data(), piece.size())) return resp;
      }
    } else {
      // Close-delimited: stream until EOF.
      while (true) {
        if (reader.drained() && !reader.fill()) break;
        size_t take = reader.buf.size() - reader.pos;
        std::string piece = reader.read_exact(take);
        if (!deliver(piece.data(), piece.size())) return resp;
      }
    }
  } catch (const StreamAborted&) {
    // Caller asked to stop; the connection just closes.
  }
  return resp;
}

}  // namespace tpupruner::http
