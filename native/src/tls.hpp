// dlopen()-based OpenSSL 3 shim (internal).
//
// The image ships /lib/x86_64-linux-gnu/libssl.so.3 but no development
// headers, so the handful of functions a TLS client needs are declared here
// by ABI and resolved at runtime. If libssl cannot be loaded, https URLs
// fail with a clear error while plain http (the hermetic test path and
// many in-cluster Prometheus endpoints) keeps working.
#pragma once

#include <memory>
#include <string>
#include <vector>

namespace tpupruner::tls {

// True when libssl.so.3/libcrypto.so.3 resolved.
bool available();

// One TLS client session over an already-connected socket fd.
// Construction performs the handshake; throws std::runtime_error on
// failure (including certificate verification when verify=true, and a
// missing/different ALPN selection when `alpn` is non-empty — gRPC
// servers require a negotiated "h2", RFC 7301).
class Conn {
 public:
  Conn(int fd, const std::string& sni_host, bool verify, const std::string& ca_file,
       const std::string& alpn = "");
  // Multi-protocol ALPN offer (RFC 7301 preference order). When
  // `require_alpn` the handshake fails unless the server selects one of
  // the offered protocols; otherwise a no-selection handshake succeeds
  // and alpn_selected() reads "" — the shared-transport client offers
  // {"h2","http/1.1"} this way and branches on the answer.
  Conn(int fd, const std::string& sni_host, bool verify, const std::string& ca_file,
       const std::vector<std::string>& alpn_protos, bool require_alpn);
  ~Conn();
  Conn(const Conn&) = delete;
  Conn& operator=(const Conn&) = delete;

  // The server's ALPN selection ("" when none was negotiated).
  const std::string& alpn_selected() const { return alpn_selected_; }

  // Return >0 bytes, 0 on orderly close, throw on error.
  size_t read(char* buf, size_t n);
  // Timeout-tolerant read for pollers: a socket-timeout (SO_RCVTIMEO
  // expiring mid-wait) or a retryable WANT_READ returns WouldBlock with
  // got=0 instead of throwing — the h2 IO loop reads with a short
  // timeout and must tell "nothing arrived yet" from a dead session.
  enum class IoStatus { Data, WouldBlock, Eof };
  IoStatus read_nb(char* buf, size_t n, size_t& got);
  // Decrypted bytes already buffered in the session (SSL_pending) — a
  // poll() on the raw fd can report "nothing to read" while a previous
  // record still holds deliverable plaintext; streaming readers must
  // check this before waiting on the socket.
  bool pending() const;
  void write_all(const char* buf, size_t n);

 private:
  void init(int fd, const std::string& sni_host, bool verify, const std::string& ca_file,
            const std::vector<std::string>& alpn_protos, bool require_alpn);

  void* ctx_ = nullptr;  // SSL_CTX*
  void* ssl_ = nullptr;  // SSL*
  std::string alpn_selected_;
};

}  // namespace tpupruner::tls
