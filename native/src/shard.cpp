#include "tpupruner/shard.hpp"

#include <algorithm>
#include <memory>
#include <mutex>

namespace tpupruner::shard {

uint64_t stable_hash(std::string_view key) {
  // FNV-1a 64-bit (public-domain constants). Stable across platforms by
  // construction — byte-wise, no word-size or endianness dependence.
  uint64_t h = 14695981039346656037ULL;
  for (unsigned char c : key) {
    h ^= c;
    h *= 1099511628211ULL;
  }
  return h;
}

size_t shard_of(std::string_view key, size_t num_shards) {
  if (num_shards <= 1) return 0;
  return static_cast<size_t>(stable_hash(key) % num_shards);
}

size_t resolve_shard_count(int64_t flag) {
  if (flag >= 1) {
    return std::min<size_t>(static_cast<size_t>(flag), kMaxShards);
  }
  size_t hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 1;  // hardware_concurrency may legally answer "unknown"
  return std::clamp<size_t>(hw, 1, kAutoMaxShards);
}

Pool::Pool(size_t workers) {
  workers = std::max<size_t>(workers, 1);
  threads_.reserve(workers);
  for (size_t i = 0; i < workers; ++i) {
    threads_.emplace_back(&Pool::worker_loop, this);
  }
}

Pool::~Pool() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stop_ = true;
  }
  work_cv_.notify_all();
  for (std::thread& t : threads_) t.join();
}

void Pool::run(size_t n_tasks, const std::function<void(size_t)>& fn) {
  if (n_tasks == 0) return;
  std::unique_lock<std::mutex> lock(mutex_);
  ++generation_;
  n_tasks_ = n_tasks;
  next_ = 0;
  active_ = 0;
  fn_ = &fn;
  first_error_ = nullptr;
  work_cv_.notify_all();
  done_cv_.wait(lock, [&] { return next_ >= n_tasks_ && active_ == 0; });
  fn_ = nullptr;
  if (first_error_) std::rethrow_exception(first_error_);
}

void Pool::worker_loop() {
  uint64_t seen_generation = 0;
  std::unique_lock<std::mutex> lock(mutex_);
  while (true) {
    work_cv_.wait(lock, [&] { return stop_ || generation_ != seen_generation; });
    if (stop_) return;
    seen_generation = generation_;
    while (next_ < n_tasks_) {
      size_t i = next_++;
      ++active_;
      lock.unlock();
      try {
        (*fn_)(i);
      } catch (...) {
        lock.lock();
        if (!first_error_) first_error_ = std::current_exception();
        --active_;
        continue;
      }
      lock.lock();
      --active_;
    }
    if (active_ == 0) done_cv_.notify_all();
  }
}

Pool& pool(size_t workers) {
  static std::mutex m;
  static std::unique_ptr<Pool> p;
  std::lock_guard<std::mutex> lock(m);
  if (!p || p->size() != std::max<size_t>(workers, 1)) {
    p = std::make_unique<Pool>(workers);
  }
  return *p;
}

}  // namespace tpupruner::shard
