#include "tpupruner/backoff.hpp"

#include <algorithm>
#include <chrono>
#include <cmath>
#include <ctime>
#include <functional>
#include <iomanip>
#include <map>
#include <mutex>
#include <sstream>
#include <thread>

#include "tpupruner/trace.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::backoff {

namespace {

// splitmix64 finalizer: mixes the seed into the key hash so two seeds
// produce decorrelated jitter sequences while staying a pure function.
uint64_t mix(uint64_t h, uint64_t seed) {
  uint64_t z = h + seed * 0x9e3779b97f4a7c15ULL;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

struct Telemetry {
  std::mutex mu;
  // (endpoint, cause) → retry count. A flat map: the label space is
  // tiny and bounded by call sites, not by input.
  std::map<std::pair<std::string, std::string>, uint64_t> retries;
  // Fixed-bucket histogram of backoff waits, seconds.
  static constexpr double kBuckets[] = {0.05, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0};
  uint64_t bucket_counts[7] = {0, 0, 0, 0, 0, 0, 0};
  uint64_t count = 0;
  double sum = 0.0;
};

Telemetry& telemetry() {
  static Telemetry t;
  return t;
}

// Render a double the way Prometheus clients do: shortest round-trip
// form, no trailing noise for whole numbers.
std::string fmt(double v) {
  if (v == static_cast<int64_t>(v)) return std::to_string(static_cast<int64_t>(v));
  std::ostringstream ss;
  ss << v;
  return ss.str();
}

}  // namespace

int64_t Policy::jitter(const std::string& key) const {
  if (jitter_ms <= 0) return 0;
  uint64_t h = std::hash<std::string>{}(key);
  // seed == 0 preserves the legacy formula bit-for-bit: the informer
  // and 429 jitters were plain hash(key) % 500 before unification, and
  // existing tests (and byte-identity replay baselines) depend on it.
  if (seed != 0) h = mix(h, seed);
  return static_cast<int64_t>(h % static_cast<uint64_t>(jitter_ms));
}

int64_t Policy::exp_delay_ms(const std::string& key, int attempt) const {
  int64_t base = std::min<int64_t>(500LL << std::min(attempt, 5), cap_ms);
  return base + jitter(key + std::to_string(attempt));
}

int64_t Policy::hinted_delay_ms(const std::string& key, int64_t hint_ms) const {
  return std::min<int64_t>(hint_ms, cap_ms - jitter_ms) + jitter(key);
}

const Policy& policy() {
  static Policy p = [] {
    Policy out;
    if (auto s = util::env("TPU_PRUNER_BACKOFF_SEED")) {
      try {
        out.seed = static_cast<uint64_t>(std::stoull(*s));
      } catch (const std::exception&) {
        // invalid seed → legacy behavior; the chaos harness always sets
        // a well-formed decimal, operators normally leave it unset
      }
    }
    return out;
  }();
  return p;
}

int64_t parse_retry_after_ms(const std::string& header) {
  try {
    // cap the seconds BEFORE the multiply: a hostile/broken proxy can
    // send a delta that fits int64 but overflows once *1000 (UB, and
    // the negative product would skip the wait entirely)
    return std::clamp<int64_t>(std::stoll(header), 1, 10) * 1000;
  } catch (const std::exception&) {
    // RFC 7231 also allows the HTTP-date form ("Wed, 21 Oct 2015
    // 07:28:00 GMT"); apiservers send delta-seconds, but an
    // intermediary proxy may rewrite it.
    std::tm tm{};
    std::istringstream ss(header);
    ss >> std::get_time(&tm, "%a, %d %b %Y %H:%M:%S");
    if (!ss.fail()) {
      std::time_t when = timegm(&tm);
      std::time_t now = std::time(nullptr);
      if (when > now) return static_cast<int64_t>(when - now) * 1000;
    }
  }
  return 1000;
}

bool sleep_interruptible(int64_t wait_ms, const std::atomic<bool>* stop) {
  for (int64_t waited = 0; waited < wait_ms; waited += 100) {
    if (util::shutdown_flag().load()) return false;
    if (stop && stop->load()) return false;
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  }
  return !util::shutdown_flag().load() && !(stop && stop->load());
}

void record_retry(const std::string& endpoint, const std::string& cause,
                  double backoff_seconds) {
  {
    Telemetry& t = telemetry();
    std::lock_guard<std::mutex> lock(t.mu);
    ++t.retries[{endpoint, cause}];
    ++t.count;
    t.sum += backoff_seconds;
    for (size_t i = 0; i < 7; ++i) {
      if (backoff_seconds <= Telemetry::kBuckets[i]) ++t.bucket_counts[i];
    }
  }
  // Provenance traces: a retry inside an actuation patch lands as a span
  // event on that actuation's span. No-op when no actuation is open on
  // this thread (informer relists, evidence queries) or with --trace off.
  trace::thread_retry_event(endpoint, cause, backoff_seconds);
}

const std::vector<std::string>& metric_families() {
  static const std::vector<std::string> families = {
      "tpu_pruner_retries_total",
      "tpu_pruner_backoff_seconds",
  };
  return families;
}

std::string render_metrics(bool openmetrics) {
  Telemetry& t = telemetry();
  std::lock_guard<std::mutex> lock(t.mu);
  std::string out;
  out += "# HELP tpu_pruner_retries_total Requests retried through the unified "
         "backoff policy, by endpoint and cause\n";
  // OpenMetrics reserves the `counter` type for suffix-transformed
  // names; keep the 0.0.4-compatible rendering the other families use.
  out += "# TYPE tpu_pruner_retries_total " +
         std::string(openmetrics ? "unknown" : "counter") + "\n";
  if (t.retries.empty()) {
    out += "tpu_pruner_retries_total 0\n";
  } else {
    for (const auto& [key, n] : t.retries) {
      out += "tpu_pruner_retries_total{endpoint=\"" + key.first + "\",cause=\"" +
             key.second + "\"} " + std::to_string(n) + "\n";
    }
  }
  out += "# HELP tpu_pruner_backoff_seconds Backoff wait before each retry, "
         "seconds\n";
  out += "# TYPE tpu_pruner_backoff_seconds histogram\n";
  uint64_t cumulative = 0;
  for (size_t i = 0; i < 7; ++i) {
    cumulative = t.bucket_counts[i];
    out += "tpu_pruner_backoff_seconds_bucket{le=\"" + fmt(Telemetry::kBuckets[i]) +
           "\"} " + std::to_string(cumulative) + "\n";
  }
  out += "tpu_pruner_backoff_seconds_bucket{le=\"+Inf\"} " + std::to_string(t.count) +
         "\n";
  out += "tpu_pruner_backoff_seconds_sum " + fmt(t.sum) + "\n";
  out += "tpu_pruner_backoff_seconds_count " + std::to_string(t.count) + "\n";
  return out;
}

void reset_for_test() {
  Telemetry& t = telemetry();
  std::lock_guard<std::mutex> lock(t.mu);
  t.retries.clear();
  for (auto& b : t.bucket_counts) b = 0;
  t.count = 0;
  t.sum = 0.0;
}

}  // namespace tpupruner::backoff
