#include "tpupruner/query.hpp"

#include <sstream>
#include <stdexcept>

namespace tpupruner::query {

namespace {

// Label names switch on honor_labels exactly as in the reference template
// (query.promql.j2:1-7): honorLabels scrape configs keep the exporter's own
// pod/namespace/container labels; default Prometheus configs prefix them.
struct Labels {
  std::string pod, ns, container;
  explicit Labels(bool honor)
      : pod(honor ? "pod" : "exported_pod"),
        ns(honor ? "namespace" : "exported_namespace"),
        container(honor ? "container" : "exported_container") {}
};

std::string fmt_threshold(double v) {
  std::ostringstream oss;
  oss << v;
  return oss.str();
}

// Escape a user-supplied regex for embedding in a double-quoted PromQL
// string literal (Go string escape rules): backslashes and quotes double.
std::string promql_string_escape(std::string_view s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '\\' || c == '"') out.push_back('\\');
    out.push_back(c);
  }
  return out;
}

// The reference's Jinja `{% if args.power_threshold %}` treats 0 as falsy
// (query.promql.j2:36): a zero threshold means "no corroboration clause",
// never an always-true `>= 0` clause.
bool threshold_set(const std::optional<double>& t) { return t && *t != 0.0; }

// One metric selector: {<pod> != "", <ns> =~ "...", <extra> =~ "..."}.
std::string selector(const Labels& l, const QueryArgs& a, const std::string& extra_label,
                     const std::string& extra_regex) {
  std::string s = "{\n      " + l.pod + " != \"\"";
  if (!a.namespace_regex.empty())
    s += ", " + l.ns + " =~ \"" + promql_string_escape(a.namespace_regex) + "\"";
  if (!a.namespace_exclude_regex.empty())
    s += ", " + l.ns + " !~ \"" + promql_string_escape(a.namespace_exclude_regex) + "\"";
  if (!extra_label.empty() && !extra_regex.empty())
    s += ", " + extra_label + " =~ \"" + promql_string_escape(extra_regex) + "\"";
  s += "\n    }";
  return s;
}

std::string window(const QueryArgs& a) {
  return "[" + std::to_string(a.duration_min) + "m]";
}

// The shared skeleton: enriched-or-bare idle block, == 0 predicate, optional
// unless corroboration (query.promql.j2:23-44 semantics).
std::string assemble(const std::string& idle_block, const std::string& group_labels,
                     const std::string& enrich_join, const std::string& unless_clause) {
  std::string q = "(\n  " + idle_block + " " + enrich_join + "\n  or on (" + group_labels +
                  ")\n  " + idle_block + "\n)\n== 0";
  if (!unless_clause.empty()) q += "\n" + unless_clause;
  return q;
}

std::string build_tpu_query(const QueryArgs& a) {
  Labels l(a.honor_labels);
  // Per-chip series keyed by node + chip id + accelerator type; summed per
  // (pod, chip) the same way the reference sums per (pod, gpu).
  std::string group_labels = "node, " + l.container + ", " + l.pod + ", " + l.ns +
                             ", accelerator_id, accelerator_type";
  std::string sel = selector(l, a, "accelerator_type", a.accelerator_regex);

  std::string idle_block = "sum by (" + group_labels + ") (\n    max_over_time(" +
                           a.tensorcore_metric + sel + window(a) + ")\n    or\n    max_over_time(" +
                           a.duty_cycle_metric + sel + window(a) + ") / 100\n)";

  // Enrichment: lift the GKE TPU accelerator node label into node_type via
  // kube_node_labels (kube-state-metrics), joined on the node label — the
  // TPU analog of the reference's node_dmi_info/product_name join.
  std::string enrich_join =
      "* on (node) group_left(node_type) (\n"
      "    label_replace(\n"
      "      kube_node_labels{label_cloud_google_com_gke_tpu_accelerator != \"\"},\n"
      "      \"node_type\", \"$1\", \"label_cloud_google_com_gke_tpu_accelerator\", \"(.+)\"\n"
      "    )\n"
      "  )";

  std::string unless_clause;
  if (threshold_set(a.hbm_threshold)) {
    // HBM traffic corroboration: a workload streaming from HBM is not idle
    // even if tensorcore peak reads zero (infeed-bound phases, host
    // offload). Analog of the reference's power clause (query.promql.j2:36-44).
    unless_clause = "unless on (" + l.pod + ", " + l.ns + ")\n(\n  max_over_time(" + a.hbm_metric +
                    selector(l, a, "", "") + window(a) + ") >= " + fmt_threshold(*a.hbm_threshold) +
                    "\n)";
  }
  return assemble(idle_block, group_labels, enrich_join, unless_clause);
}

// Stock-GKE system-metric schema (Cloud Monitoring PromQL API). The
// de-facto contract this builder encodes, pinned by the gke-system tier of
// tests/test_query_template.py the way main.rs:572-740 pins the DCGM shape:
//   - node idleness first: kubernetes_io:node_accelerator_tensorcore_
//     utilization (0-1, v4+) `or` kubernetes_io:node_accelerator_duty_cycle
//     (percent, all gens) / 100, peak over the lookback window, then
//     `max by (node_name, model)` over the node's chips — a node is idle
//     only when EVERY chip's peak over the window is zero. One row per
//     node (a GKE node exposes exactly one accelerator model, so keeping
//     `model` in the grouping does not split rows; it exists to be
//     carried onto pods by group_left below).
//   - pod attribution with pods as the MANY side: the KSM requests metric
//     filtered to resource="google_com_tpu" (its `node` label lifted into
//     node_name to align join keys), aggregated per (node_name, pod, <ns>,
//     container), `> 0` to drop degenerate zero-quantity requests, then
//     `* on (node_name) group_left (model)` onto the node-idleness row.
//     Many-to-one is the point: any number of TPU-requesting pods per node
//     — shared single-host nodes (e.g. fractional ct5lp-hightpu-8t pools)
//     and pods splitting requests across containers — render a LEGAL
//     query. A fully-idle node makes every TPU pod on it a candidate; one
//     busy chip (node peak > 0) rescues them all. Round-3 shipped the
//     opposite direction (one pod per node or per-cycle many-to-many
//     failure, crash-looping the daemon on legitimate shared-node
//     topologies); node-scoped metrics cannot distinguish pods, so
//     node-level attribution is the honest structure.
//   - == 0 idle predicate AFTER the join: only pod-attributed nodes are
//     candidates (an idle node with no TPU pod has nothing to prune).
//     The joined value is request_count x node_peak: zero exactly when
//     the node is idle.
//   - `unless on (node_name)` HBM-bandwidth corroboration: any chip on
//     the node moving HBM traffic rescues all of the node's pods.
std::string build_tpu_gke_system_query(const QueryArgs& a) {
  Labels l(a.honor_labels);
  // Remap bare GMP default names to the Cloud Monitoring forms; explicit
  // overrides pass through (the gke-system schema has no bare names, so
  // an untouched default would return zero rows on a stock cluster).
  auto effective = [](const std::string& configured, const char* gmp_default,
                     const char* gke_name) {
    return configured == gmp_default ? std::string(gke_name) : configured;
  };
  std::string tensorcore =
      effective(a.tensorcore_metric, "tensorcore_utilization",
                "kubernetes_io:node_accelerator_tensorcore_utilization");
  std::string duty = effective(a.duty_cycle_metric, "tensorcore_duty_cycle",
                               "kubernetes_io:node_accelerator_duty_cycle");
  std::string hbm = effective(a.hbm_metric, "hbm_memory_bandwidth_utilization",
                              "kubernetes_io:node_accelerator_memory_bandwidth_utilization");

  // Accelerator-series selector: model filter only (node-scoped series
  // carry no pod/namespace labels to filter on).
  std::string accel_sel;
  if (!a.accelerator_regex.empty()) {
    accel_sel = "{model =~ \"" + promql_string_escape(a.accelerator_regex) + "\"}";
  }

  // Join-side selector: TPU-resource restriction + the namespace filters.
  std::string join_sel = "{";
  bool first = true;
  auto add = [&](const std::string& clause) {
    if (!first) join_sel += ", ";
    join_sel += clause;
    first = false;
  };
  if (!a.join_resource.empty())
    add("resource = \"" + promql_string_escape(a.join_resource) + "\"");
  if (!a.namespace_regex.empty())
    add(l.ns + " =~ \"" + promql_string_escape(a.namespace_regex) + "\"");
  if (!a.namespace_exclude_regex.empty())
    add(l.ns + " !~ \"" + promql_string_escape(a.namespace_exclude_regex) + "\"");
  join_sel += "}";
  if (join_sel == "{}") join_sel.clear();

  // PromQL gotcha: comparison binds looser than *, so the > 0 guard needs
  // explicit parens or `pods > 0 * node_idle` parses as `pods > (0 * ...)`.
  std::string pods_block = "(\n    max by (node_name, pod, " + l.ns +
                           ", container) (\n      label_replace(\n        " + a.join_metric +
                           join_sel + ",\n        \"node_name\", \"$1\", \"node\", \"(.+)\"\n"
                           "      )\n    ) > 0\n  )";

  std::string node_idle = "max by (node_name, model) (\n    max_over_time(" + tensorcore +
                          accel_sel + window(a) + ")\n    or\n    max_over_time(" + duty +
                          accel_sel + window(a) + ") / 100\n  )";

  std::string q = "(\n  " + pods_block + "\n  * on (node_name) group_left (model)\n  " +
                  node_idle + "\n)\n== 0";
  if (threshold_set(a.hbm_threshold)) {
    q += "\nunless on (node_name)\n(\n  max_over_time(" + hbm + accel_sel + window(a) +
         ") >= " + fmt_threshold(*a.hbm_threshold) + "\n)";
  }
  return q;
}

std::string build_gpu_query(const QueryArgs& a) {
  Labels l(a.honor_labels);
  std::string group_labels =
      "Hostname, " + l.container + ", " + l.pod + ", " + l.ns + ", gpu, modelName";
  std::string sel = selector(l, a, "modelName", a.model_regex);

  std::string idle_block =
      "sum by (" + group_labels + ") (\n    max_over_time(DCGM_FI_PROF_GR_ENGINE_ACTIVE" + sel +
      window(a) + ")\n    or\n    max_over_time(DCGM_FI_DEV_GPU_UTIL" + sel + window(a) +
      ") / 100\n)";

  std::string enrich_join =
      "* on (Hostname) group_left(node_type) (\n"
      "    label_replace(\n"
      "      label_replace(node_dmi_info,\n"
      "        \"Hostname\", \"$1\", \"instance\", \"(.+)\"\n"
      "      ),\n"
      "      \"node_type\", \"$1\", \"product_name\", \"(.+)\"\n"
      "    )\n"
      "  )";

  std::string unless_clause;
  if (threshold_set(a.power_threshold)) {
    unless_clause = "unless on (" + l.pod + ", " + l.ns +
                    ")\n(\n  max_over_time(DCGM_FI_DEV_POWER_USAGE" + selector(l, a, "", "") +
                    window(a) + ") >= " + fmt_threshold(*a.power_threshold) + "\n)";
  }
  return assemble(idle_block, group_labels, enrich_join, unless_clause);
}

// Stamp a constant-valued synthetic label onto every series of `expr`:
// label_replace with an empty source label ("" always exists, as the
// empty string) and an empty anchored regex (matches exactly "").
std::string stamp_stat(const std::string& expr, const char* stat) {
  return "label_replace(\n  " + expr + ",\n  \"signal_stat\", \"" + std::string(stat) +
         "\", \"\", \"\"\n)";
}

// gmp evidence: per-pod coverage + freshness over the same selectors the
// idle query uses. `or` between the two metric variants keeps the primary
// (tensorcore) statistic where both exist, like the idle block.
std::string build_evidence_query_podlabeled(const QueryArgs& a, const std::string& primary,
                                            const std::string& fallback,
                                            const std::string& extra_label,
                                            const std::string& extra_regex) {
  Labels l(a.honor_labels);
  std::string sel = selector(l, a, extra_label, extra_regex);
  std::string group = l.pod + ", " + l.ns;
  std::string samples = "sum by (" + group + ") (\n    count_over_time(" + primary + sel +
                        window(a) + ")\n    or\n    count_over_time(" + fallback + sel +
                        window(a) + ")\n  )";
  std::string age = "time()\n  - max by (" + group + ") (\n    timestamp(" + primary + sel +
                    ")\n    or\n    timestamp(" + fallback + sel + ")\n  )";
  return "(\n" + stamp_stat(samples, "samples") + "\n)\nor\n(\n" + stamp_stat(age, "age") + "\n)";
}

// gke-system evidence: coverage/freshness are node-scoped facts (the
// accelerator series carry no pod labels); attribute them to pods with
// the SAME many-to-one KSM join the idle query uses, masked to 1 with a
// `> bool 0` so the joined value stays the node statistic, not
// request_count × statistic.
std::string build_evidence_query_gke_system(const QueryArgs& a) {
  Labels l(a.honor_labels);
  auto effective = [](const std::string& configured, const char* gmp_default,
                     const char* gke_name) {
    return configured == gmp_default ? std::string(gke_name) : configured;
  };
  std::string tensorcore =
      effective(a.tensorcore_metric, "tensorcore_utilization",
                "kubernetes_io:node_accelerator_tensorcore_utilization");
  std::string duty = effective(a.duty_cycle_metric, "tensorcore_duty_cycle",
                               "kubernetes_io:node_accelerator_duty_cycle");
  std::string accel_sel;
  if (!a.accelerator_regex.empty()) {
    accel_sel = "{model =~ \"" + promql_string_escape(a.accelerator_regex) + "\"}";
  }
  std::string join_sel = "{";
  bool first = true;
  auto add = [&](const std::string& clause) {
    if (!first) join_sel += ", ";
    join_sel += clause;
    first = false;
  };
  if (!a.join_resource.empty())
    add("resource = \"" + promql_string_escape(a.join_resource) + "\"");
  if (!a.namespace_regex.empty())
    add(l.ns + " =~ \"" + promql_string_escape(a.namespace_regex) + "\"");
  if (!a.namespace_exclude_regex.empty())
    add(l.ns + " !~ \"" + promql_string_escape(a.namespace_exclude_regex) + "\"");
  join_sel += "}";
  if (join_sel == "{}") join_sel.clear();

  std::string pods_mask = "(\n    max by (node_name, pod, " + l.ns +
                          ", container) (\n      label_replace(\n        " + a.join_metric +
                          join_sel + ",\n        \"node_name\", \"$1\", \"node\", \"(.+)\"\n"
                          "      )\n    ) > bool 0\n  )";
  std::string node_samples = "sum by (node_name) (\n    count_over_time(" + tensorcore +
                             accel_sel + window(a) + ")\n    or\n    count_over_time(" + duty +
                             accel_sel + window(a) + ")\n  )";
  std::string node_age = "time()\n  - max by (node_name) (\n    timestamp(" + tensorcore +
                         accel_sel + ")\n    or\n    timestamp(" + duty + accel_sel + ")\n  )";
  std::string samples =
      pods_mask + "\n  * on (node_name) group_left\n  " + node_samples;
  std::string age = pods_mask + "\n  * on (node_name) group_left\n  (\n  " + node_age + "\n  )";
  return "(\n" + stamp_stat(samples, "samples") + "\n)\nor\n(\n" + stamp_stat(age, "age") + "\n)";
}

}  // namespace

std::string build_evidence_query(const QueryArgs& args) {
  if (args.metric_schema != "gmp" && args.metric_schema != "gke-system") {
    throw std::invalid_argument("unknown metric schema: " + args.metric_schema +
                                " (expected gmp|gke-system)");
  }
  if (args.device == "gpu") {
    if (args.metric_schema == "gke-system") {
      throw std::invalid_argument("--metric-schema=gke-system requires --device=tpu");
    }
    return build_evidence_query_podlabeled(args, "DCGM_FI_PROF_GR_ENGINE_ACTIVE",
                                           "DCGM_FI_DEV_GPU_UTIL", "modelName",
                                           args.model_regex);
  }
  if (args.device == "tpu") {
    if (args.metric_schema == "gke-system") return build_evidence_query_gke_system(args);
    return build_evidence_query_podlabeled(args, args.tensorcore_metric, args.duty_cycle_metric,
                                           "accelerator_type", args.accelerator_regex);
  }
  throw std::invalid_argument("unknown device: " + args.device + " (expected tpu|gpu)");
}

std::string build_idle_query(const QueryArgs& args) {
  if (args.metric_schema != "gmp" && args.metric_schema != "gke-system") {
    throw std::invalid_argument("unknown metric schema: " + args.metric_schema +
                                " (expected gmp|gke-system)");
  }
  if (args.device == "gpu") {
    if (args.metric_schema == "gke-system") {
      // The node_accelerator metrics do cover GPUs, but the DCGM profile is
      // the reference-parity path; refuse rather than emit a half-schema.
      throw std::invalid_argument("--metric-schema=gke-system requires --device=tpu");
    }
    return build_gpu_query(args);
  }
  if (args.device == "tpu") {
    return args.metric_schema == "gke-system" ? build_tpu_gke_system_query(args)
                                              : build_tpu_query(args);
  }
  throw std::invalid_argument("unknown device: " + args.device + " (expected tpu|gpu)");
}

json::Value args_to_json(const QueryArgs& a) {
  json::Value v = json::Value::object();
  v.set("device", json::Value(a.device));
  v.set("duration", json::Value(a.duration_min));
  if (!a.namespace_regex.empty()) v.set("namespace", json::Value(a.namespace_regex));
  if (!a.namespace_exclude_regex.empty())
    v.set("namespace_exclude", json::Value(a.namespace_exclude_regex));
  if (!a.model_regex.empty()) v.set("model_name", json::Value(a.model_regex));
  if (!a.accelerator_regex.empty())
    v.set("accelerator_type", json::Value(a.accelerator_regex));
  if (a.power_threshold) v.set("power_threshold", json::Value(*a.power_threshold));
  if (a.hbm_threshold) v.set("hbm_threshold", json::Value(*a.hbm_threshold));
  v.set("honor_labels", json::Value(a.honor_labels));
  v.set("metric_schema", json::Value(a.metric_schema));
  v.set("join_metric", json::Value(a.join_metric));
  v.set("join_resource", json::Value(a.join_resource));
  v.set("tensorcore_metric", json::Value(a.tensorcore_metric));
  v.set("duty_cycle_metric", json::Value(a.duty_cycle_metric));
  v.set("hbm_metric", json::Value(a.hbm_metric));
  return v;
}

QueryArgs args_from_json(const json::Value& v) {
  QueryArgs a;
  if (const json::Value* x = v.find("device"); x && x->is_string()) a.device = x->as_string();
  if (const json::Value* x = v.find("duration"); x && x->is_number()) a.duration_min = x->as_int();
  if (const json::Value* x = v.find("namespace"); x && x->is_string())
    a.namespace_regex = x->as_string();
  if (const json::Value* x = v.find("namespace_exclude"); x && x->is_string())
    a.namespace_exclude_regex = x->as_string();
  if (const json::Value* x = v.find("model_name"); x && x->is_string())
    a.model_regex = x->as_string();
  if (const json::Value* x = v.find("accelerator_type"); x && x->is_string())
    a.accelerator_regex = x->as_string();
  if (const json::Value* x = v.find("power_threshold"); x && x->is_number())
    a.power_threshold = x->as_double();
  if (const json::Value* x = v.find("hbm_threshold"); x && x->is_number())
    a.hbm_threshold = x->as_double();
  if (const json::Value* x = v.find("honor_labels"); x && x->is_bool())
    a.honor_labels = x->as_bool();
  if (const json::Value* x = v.find("metric_schema"); x && x->is_string())
    a.metric_schema = x->as_string();
  if (const json::Value* x = v.find("join_metric"); x && x->is_string())
    a.join_metric = x->as_string();
  if (const json::Value* x = v.find("join_resource"); x && x->is_string())
    a.join_resource = x->as_string();
  if (const json::Value* x = v.find("tensorcore_metric"); x && x->is_string())
    a.tensorcore_metric = x->as_string();
  if (const json::Value* x = v.find("duty_cycle_metric"); x && x->is_string())
    a.duty_cycle_metric = x->as_string();
  if (const json::Value* x = v.find("hbm_metric"); x && x->is_string())
    a.hbm_metric = x->as_string();
  return a;
}

}  // namespace tpupruner::query
