#include "tpupruner/kubeconfig.hpp"

#include "tpupruner/util.hpp"

namespace tpupruner::kubeconfig {

namespace {
std::string strip_quotes(std::string v) {
  if (v.size() >= 2 && ((v.front() == '"' && v.back() == '"') ||
                        (v.front() == '\'' && v.back() == '\''))) {
    return v.substr(1, v.size() - 2);
  }
  return v;
}
}  // namespace

std::optional<Info> scan() {
  std::string path;
  if (auto kc = util::env("KUBECONFIG")) {
    path = *kc;
  } else if (auto home = util::env("HOME")) {
    path = *home + "/.kube/config";
  } else {
    return std::nullopt;
  }
  auto content = util::read_file(path);
  if (!content) return std::nullopt;

  Info info;
  for (const std::string& raw : util::split(*content, '\n')) {
    std::string line = util::trim(raw);
    if (info.server.empty() && util::starts_with(line, "server:")) {
      info.server = strip_quotes(util::trim(line.substr(7)));
    }
    if (info.token.empty() && util::starts_with(line, "token:")) {
      info.token = strip_quotes(util::trim(line.substr(6)));
    }
    if (info.token.empty() && util::starts_with(line, "tokenFile:")) {
      if (auto tf = util::read_file(strip_quotes(util::trim(line.substr(10))))) {
        info.token = util::trim(*tf);
      }
    }
    if (info.current_context.empty() && util::starts_with(line, "current-context:")) {
      info.current_context = strip_quotes(util::trim(line.substr(16)));
    }
    if (line == "insecure-skip-tls-verify: true") info.tls_skip = true;
  }
  if (info.server.empty()) return std::nullopt;
  return info;
}

}  // namespace tpupruner::kubeconfig
