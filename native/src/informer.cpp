#include "tpupruner/informer.hpp"

#include "tpupruner/backoff.hpp"

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <deque>
#include <functional>
#include <thread>

#include "tpupruner/log.hpp"
#include "tpupruner/shard.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::informer {

using json::Value;

std::optional<ResourceSpec> spec_for(std::string_view plural) {
  static const std::map<std::string, ResourceSpec, std::less<>> kSpecs = [] {
    std::map<std::string, ResourceSpec, std::less<>> out;
    auto add = [&](const std::string& prefix, const std::string& p) {
      out[p] = ResourceSpec{prefix + p, prefix, p};
    };
    add("/api/v1/", "pods");
    add("/apis/apps/v1/", "replicasets");
    add("/apis/apps/v1/", "deployments");
    add("/apis/apps/v1/", "statefulsets");
    add("/apis/batch/v1/", "jobs");
    add("/apis/jobset.x-k8s.io/v1alpha2/", "jobsets");
    add("/apis/leaderworkerset.x-k8s.io/v1/", "leaderworkersets");
    add("/apis/kubeflow.org/v1/", "notebooks");
    add("/apis/serving.kserve.io/v1beta1/", "inferenceservices");
    return out;
  }();
  auto it = kSpecs.find(plural);
  if (it == kSpecs.end()) return std::nullopt;
  return it->second;
}

std::vector<ResourceSpec> daemon_specs() {
  // Pods plus every kind the owner walk can touch: the walk must be able
  // to resolve a full chain (Pod → RS → Deployment, Pod → Job → JobSet,
  // label shortcuts to LWS/InferenceService) without leaving the cache.
  std::vector<ResourceSpec> out;
  for (const char* p : {"pods", "replicasets", "deployments", "statefulsets", "jobs",
                        "jobsets", "leaderworkersets", "notebooks", "inferenceservices"}) {
    out.push_back(*spec_for(p));
  }
  return out;
}

// ── Store ──

namespace {

// Rough retained-bytes walk over a materialized Value (shared_ptr blocks
// + container nodes + string payloads). An estimate, not an audit: the
// gauge it feeds compares representations, it does not bill the heap.
size_t value_cost(const Value& v) {
  switch (v.type()) {
    case json::Type::String:
      return 48 + v.as_string().size();
    case json::Type::Array: {
      size_t n = 56;
      for (const Value& c : v.as_array()) n += sizeof(Value) + value_cost(c);
      return n;
    }
    case json::Type::Object: {
      size_t n = 56;
      for (const auto& [k, c] : v.as_object()) {
        n += 64 + k.size() + sizeof(Value) + value_cost(c);
      }
      return n;
    }
    default:
      return 0;
  }
}

// Flat per-entry share of a LIST-page / watch-event Doc arena. The real
// cost is shared across every entry of the page; a fixed prior keeps the
// estimator O(1) (a pod subtree is ~40 nodes at ~48 bytes each, plus its
// slice of the page body).
constexpr size_t kDocEntryShare = 2048;

}  // namespace

size_t Store::entry_cost(const std::string& path, const Entry& e) {
  size_t n = path.size() + 96;  // key + map node overhead
  if (e.rec) {
    n += e.rec->bytes();
    return n;
  }
  if (!e.exact) return n;  // empty entry (no allocation on const reads)
  const Entry::Exact& x = *e.exact;
  n += sizeof(Entry::Exact);
  if (x.pbody) {
    // Counted by slice: after the page-retention copy-out the slice IS
    // the allocation; an aliased small frame undercounts only its header.
    n += x.plen + x.papi.size() + x.pkind.size() + 64;
  } else if (x.doc) {
    n += kDocEntryShare;
  } else {
    n += value_cost(x.value);
  }
  return n;
}

void Store::configure(std::string plural) { pods_ = (plural == "pods"); }

void Store::settle_gauges(int64_t bytes_delta, int64_t object_delta) const {
  bytes_ = static_cast<size_t>(static_cast<int64_t>(bytes_) + bytes_delta);
  compact::add_store_bytes(bytes_delta);
  if (pods_ && object_delta != 0) compact::add_store_pods(object_delta);
}

Store::~Store() {
  std::lock_guard<std::mutex> lock(mutex_);
  settle_gauges(-static_cast<int64_t>(bytes_), -static_cast<int64_t>(objects_.size()));
  objects_.clear();
}

uint64_t Store::retained_bytes() const {
  std::lock_guard<std::mutex> lock(mutex_);
  return bytes_;
}

std::optional<Value> Store::get(const std::string& object_path) const {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = objects_.find(object_path);
  if (it == objects_.end()) return std::nullopt;
  Entry& e = it->second;
  size_t before = entry_cost(object_path, e);
  if (e.rec) {
    // Compact entry: materialize the packed record, then MEMOIZE — same
    // contract as the arena/proto arms below, and byte-identical to them
    // by the record builders' strict-subset rule.
    e.ex().value = e.rec->to_value();
    e.rec.reset();
  } else if (e.exact && e.exact->doc) {
    // Arena-backed entry: materialize on demand, then MEMOIZE — a warm
    // cycle re-reads the same candidate pods and owner objects every
    // interval, and re-building the tree each time put the conversion in
    // the resolve hot path. Only the objects a cycle touches pay (once);
    // the other 99k pods stay flat arena nodes. The doc stays referenced
    // so sibling entries of the same LIST page / watch event are
    // unaffected.
    e.exact->value = e.exact->doc->node(e.exact->node).to_value();
    e.exact->doc.reset();
  } else if (e.exact && e.exact->pbody) {
    // Proto-backed entry (--wire proto): same memoized-materialization
    // contract, from the raw protobuf slice. Produces a Value identical
    // to parsing the object's JSON form (pinned by the wire parity
    // corpus), so every consumer downstream is wire-format blind.
    Entry::Exact& x = *e.exact;
    x.value = proto::object_to_value(
        std::string_view(x.pbody->data() + x.poff, x.plen), x.papi, x.pkind);
    x.pbody.reset();
  }
  size_t after = entry_cost(object_path, e);
  if (after != before) {
    settle_gauges(static_cast<int64_t>(after) - static_cast<int64_t>(before), 0);
  }
  return e.exact ? e.exact->value : Value();  // COW copy: shares nodes, pointer-sized
}

bool Store::contains(const std::string& object_path) const {
  std::lock_guard<std::mutex> lock(mutex_);
  return objects_.count(object_path) > 0;
}

size_t Store::size() const {
  std::lock_guard<std::mutex> lock(mutex_);
  return objects_.size();
}

void Store::replace(std::map<std::string, Value> objects) {
  std::map<std::string, Entry> entries;
  for (auto& [path, v] : objects) {
    entries[path].ex().value = std::move(v);
  }
  replace_entries(std::move(entries));
}

void Store::replace_entries(std::map<std::string, Entry> objects) {
  size_t total = 0;
  for (const auto& [path, e] : objects) total += entry_cost(path, e);
  std::lock_guard<std::mutex> lock(mutex_);
  int64_t bytes_delta = static_cast<int64_t>(total) - static_cast<int64_t>(bytes_);
  int64_t object_delta =
      static_cast<int64_t>(objects.size()) - static_cast<int64_t>(objects_.size());
  objects_ = std::move(objects);
  settle_gauges(bytes_delta, object_delta);
}

void Store::put(const std::string& object_path, Entry e) {
  size_t cost = entry_cost(object_path, e);
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = objects_.find(object_path);
  int64_t bytes_delta = static_cast<int64_t>(cost);
  int64_t object_delta = 1;
  if (it != objects_.end()) {
    bytes_delta -= static_cast<int64_t>(entry_cost(object_path, it->second));
    object_delta = 0;
    it->second = std::move(e);
  } else {
    objects_.emplace(object_path, std::move(e));
  }
  settle_gauges(bytes_delta, object_delta);
}

void Store::upsert(const std::string& object_path, Value object) {
  Entry e;
  if (pods_ && compact::enabled()) {
    // Decode straight into a packed record when the object conforms to
    // the decoder subset; a non-conformant pod keeps its exact Value.
    if (auto rec = compact::record_from_value(object)) {
      e.rec = std::make_shared<const compact::PodRecord>(std::move(*rec));
    }
  }
  if (!e.rec) e.ex().value = std::move(object);
  put(object_path, std::move(e));
}

void Store::upsert_doc(const std::string& object_path, json::DocPtr doc, uint32_t node) {
  Entry e;
  if (pods_ && compact::enabled()) {
    // Compact mode must not pin the event/page Doc: conforming pods pack
    // into a record, the rest materialize an owned Value immediately.
    Value v = doc->node(node).to_value();
    if (auto rec = compact::record_from_value(v)) {
      e.rec = std::make_shared<const compact::PodRecord>(std::move(*rec));
    } else {
      e.ex().value = std::move(v);
    }
  } else {
    Entry::Exact& x = e.ex();
    x.doc = std::move(doc);
    x.node = node;
  }
  put(object_path, std::move(e));
}

void Store::upsert_proto(const std::string& object_path, std::shared_ptr<const std::string> body,
                         size_t off, size_t len, std::string api_version, std::string kind,
                         uint64_t fp) {
  Entry e;
  e.pfp = fp;
  if (pods_ && compact::enabled()) {
    try {
      compact::PodRecord rec = compact::record_from_proto(
          std::string_view(body->data() + off, len), api_version, kind);
      e.rec = std::make_shared<const compact::PodRecord>(std::move(rec));
    } catch (const json::ParseError&) {
      // Malformed payload: keep the raw bytes (copied out, never pinning
      // the frame) so the error still surfaces at get(), exactly where
      // the lazy decode would have thrown.
      Entry::Exact& x = e.ex();
      x.pbody = std::make_shared<const std::string>(body->data() + off, len);
      x.poff = 0;
      x.plen = len;
      x.papi = std::move(api_version);
      x.pkind = std::move(kind);
    }
  }
  if (!e.rec && !e.exact) {
    Entry::Exact& x = e.ex();
    x.pbody = std::move(body);
    x.poff = off;
    x.plen = len;
    x.papi = std::move(api_version);
    x.pkind = std::move(kind);
  }
  put(object_path, std::move(e));
}

uint64_t Store::proto_fingerprint(const std::string& object_path) const {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = objects_.find(object_path);
  return it == objects_.end() ? 0 : it->second.pfp;
}

void Store::erase(const std::string& object_path) {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = objects_.find(object_path);
  if (it == objects_.end()) return;
  settle_gauges(-static_cast<int64_t>(entry_cost(object_path, it->second)), -1);
  objects_.erase(it);
}

// ── Reflector ──

// Page size for the initial/relist LIST (limit/continue). 500 is the
// client-go pager default: big enough that a 4k-pod cluster still lists
// in a handful of round-trips, small enough that a 100k-pod LIST never
// materializes as one response on either end.
constexpr int64_t kListPageLimit = 500;

// Dirty-journal bound: past this many undrained paths the journal
// degrades to globally dirty. A cycle interval's worth of churn is
// normally a few hundred events; hitting the cap means the consumer
// stopped draining (or the cluster is churning at relist scale), and a
// full recompute is the honest answer either way.
constexpr size_t kDirtyJournalCap = 65536;

void Reflector::enable_dirty_journal() { journal_enabled_.store(true); }

// Event-arrival stamp for the dirty-notify fan-out: monotonic ms at decode
// time, the same clock the daemon's detect→action plane runs on.
int64_t arrival_mono_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void Reflector::set_dirty_notify(std::function<void(int64_t)> notify) {
  // Pre-start() only: the reflector thread reads this without a lock
  // (thread creation is the happens-before edge).
  dirty_notify_ = std::move(notify);
}

void Reflector::drain_dirty(std::vector<std::string>& paths, bool& all) const {
  std::lock_guard<std::mutex> lock(dirty_mutex_);
  if (dirty_all_) all = true;
  dirty_all_ = false;
  for (std::string& p : dirty_paths_) paths.push_back(std::move(p));
  dirty_paths_.clear();
}

void Reflector::journal_touch(const std::string& path) {
  if (!journal_enabled_.load(std::memory_order_relaxed)) return;
  {
    std::lock_guard<std::mutex> lock(dirty_mutex_);
    if (dirty_all_) {
      // already globally dirty; paths are redundant — but the mark still
      // notifies below: the dispatcher may not have drained yet.
    } else if (dirty_paths_.size() >= kDirtyJournalCap) {
      dirty_paths_.clear();
      dirty_all_ = true;
      ++journal_overflows_;
    } else {
      dirty_paths_.push_back(path);
    }
  }
  if (dirty_notify_) dirty_notify_(arrival_mono_ms());  // outside the lock: wake, don't hold
}

uint64_t Reflector::journal_overflows() const {
  std::lock_guard<std::mutex> lock(dirty_mutex_);
  return journal_overflows_;
}

size_t dirty_journal_cap() { return kDirtyJournalCap; }

void Reflector::journal_all() {
  if (!journal_enabled_.load(std::memory_order_relaxed)) return;
  {
    std::lock_guard<std::mutex> lock(dirty_mutex_);
    dirty_paths_.clear();
    dirty_all_ = true;
  }
  if (dirty_notify_) dirty_notify_(arrival_mono_ms());
}

Reflector::Reflector(const k8s::Client& kube, ResourceSpec spec)
    : kube_(kube), spec_(std::move(spec)) {
  store_.configure(spec_.plural);
}

Reflector::~Reflector() { stop(); }

void Reflector::start() {
  if (thread_.joinable()) return;
  stop_.store(false);
  thread_ = std::thread(&Reflector::run, this);
}

void Reflector::stop() {
  stop_.store(true);
  if (thread_.joinable()) thread_.join();
}

std::optional<Value> Reflector::get(const std::string& object_path) const {
  return store_.get(object_path);
}

ResourceStats Reflector::stats() const {
  ResourceStats out;
  {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    out = stats_;
  }
  out.synced = synced_.load();
  out.objects = store_.size();
  out.store_bytes = store_.retained_bytes();
  out.cold_sync_seconds = cold_sync_secs_.load();
  return out;
}

std::string Reflector::object_path_of(const Value& object) const {
  const Value* ns = object.at_path("metadata.namespace");
  const Value* name = object.at_path("metadata.name");
  if (!ns || !ns->is_string() || !name || !name->is_string()) return "";
  return spec_.prefix + "namespaces/" + ns->as_string() + "/" + spec_.plural + "/" +
         name->as_string();
}

std::string Reflector::resource_version() const {
  std::lock_guard<std::mutex> lock(stats_mutex_);
  return resource_version_;
}

bool Reflector::request_relist(const std::string& why) {
  if (relist_pending_.exchange(true)) {
    // A relist is already in flight — coalesce, never stack: two LISTs
    // for one gap would double the apiserver cost of every compaction
    // and re-unsync the store right after it recovered.
    log::debug("informer", "watch " + spec_.list_path + " relist request (" + why +
               ") coalesced into the in-flight relist");
    return false;
  }
  std::lock_guard<std::mutex> lock(stats_mutex_);
  ++stats_.relist_requests;
  return true;
}

void Reflector::apply_list(const Value& list) {
  std::map<std::string, Store::Entry> snapshot;
  if (const Value* items = list.find("items"); items && items->is_array()) {
    for (const Value& item : items->as_array()) {
      std::string path = object_path_of(item);
      if (!path.empty()) snapshot[std::move(path)].ex().value = item;
    }
  }
  std::string rv;
  if (const Value* v = list.at_path("metadata.resourceVersion"); v && v->is_string()) {
    rv = v->as_string();
  }
  apply_list_snapshot(std::move(snapshot), std::move(rv));
}

void Reflector::apply_list_snapshot(std::map<std::string, Store::Entry> snapshot,
                                    std::string rv) {
  // A LIST snapshot means the watch stream could not be trusted (initial
  // sync, 410, failure streak) — events may have been missed, so the
  // incremental engine must treat everything as changed.
  journal_all();
  store_.replace_entries(std::move(snapshot));
  {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
    ++stats_.relists;  // counts the initial LIST too: relists == LISTs issued
    stats_.resource_version = rv;
  }
  // The fresh snapshot services any pending relist request — a 410 that
  // arrived while this LIST was in flight is satisfied by it, not queued.
  relist_pending_.store(false);
  synced_.store(true);
  last_activity_mono_.store(util::mono_secs());
  log::counter_add("informer_relists", 1);
}

std::string Reflector::object_path_of_doc(const json::Doc::Node& object) const {
  auto ns = object.at_path("metadata.namespace");
  auto name = object.at_path("metadata.name");
  if (!ns || !ns->is_string() || !name || !name->is_string()) return "";
  return spec_.prefix + "namespaces/" + std::string(ns->as_sv()) + "/" + spec_.plural + "/" +
         std::string(name->as_sv());
}

bool Reflector::apply_event(const Value& event) {
  std::string type = event.get_string("type");
  const Value* object = event.find("object");

  if (type == "ERROR") {
    // The in-band relist signal: {"type":"ERROR","object":<Status>}, most
    // commonly code 410 after apiserver compaction. Any ERROR means the
    // stream can no longer be trusted — relist regardless of code. A 410
    // arriving while a relist LIST is already in flight coalesces into it
    // (request_relist) instead of queueing a second relist.
    int64_t code = 0;
    if (object) {
      if (const Value* c = object->find("code"); c && c->is_number()) code = c->as_int();
    }
    if (request_relist("ERROR event code " + std::to_string(code))) {
      log::warn("informer", "watch " + spec_.list_path + " ERROR event (code " +
                std::to_string(code) + "); relisting");
    }
    return false;
  }

  std::string rv;
  if (object) {
    if (const Value* v = object->at_path("metadata.resourceVersion"); v && v->is_string()) {
      rv = v->as_string();
    }
  }

  if (type == "BOOKMARK") {
    // Progress marker only: no object payload beyond metadata. Advancing
    // the resume point here is what keeps a relist after a quiet period
    // from replaying (or 410ing on) long-compacted history.
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.bookmarks;
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "ADDED" || type == "MODIFIED") {
    if (!object) return true;
    std::string path = object_path_of(*object);
    if (path.empty()) return true;
    bool existed = store_.get(path).has_value();
    journal_touch(path);
    store_.upsert(path, *object);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++(existed ? stats_.updates : stats_.adds);
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "DELETED") {
    if (!object) return true;
    std::string path = object_path_of(*object);
    if (path.empty()) return true;
    journal_touch(path);
    store_.erase(path);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.deletes;
    if (!rv.empty()) stats_.resource_version = rv;
  } else {
    log::debug("informer", "ignoring unknown watch event type: " + type);
    return true;
  }
  if (!rv.empty()) {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
  }
  last_activity_mono_.store(util::mono_secs());
  return true;
}

bool Reflector::apply_event_doc(const json::DocPtr& event) {
  json::Doc::Node root = event->root();
  std::string type(root.get_string("type"));
  std::optional<json::Doc::Node> object = root.find("object");

  if (type == "ERROR") {
    int64_t code = 0;
    if (object) {
      if (auto c = object->find("code"); c && c->is_number()) code = c->as_int();
    }
    if (request_relist("ERROR event code " + std::to_string(code))) {
      log::warn("informer", "watch " + spec_.list_path + " ERROR event (code " +
                std::to_string(code) + "); relisting");
    }
    return false;
  }

  std::string rv;
  if (object) {
    if (auto v = object->at_path("metadata.resourceVersion"); v && v->is_string()) {
      rv = std::string(v->as_sv());
    }
  }

  if (type == "BOOKMARK") {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.bookmarks;
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "ADDED" || type == "MODIFIED") {
    if (!object) return true;
    std::string path = object_path_of_doc(*object);
    if (path.empty()) return true;
    bool existed = store_.contains(path);
    // The event Doc rides into the store: the object stays arena-flat
    // until some cycle actually looks it up.
    journal_touch(path);
    store_.upsert_doc(path, event, object->index());
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++(existed ? stats_.updates : stats_.adds);
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "DELETED") {
    if (!object) return true;
    std::string path = object_path_of_doc(*object);
    if (path.empty()) return true;
    journal_touch(path);
    store_.erase(path);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.deletes;
    if (!rv.empty()) stats_.resource_version = rv;
  } else {
    log::debug("informer", "ignoring unknown watch event type: " + type);
    return true;
  }
  if (!rv.empty()) {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
  }
  last_activity_mono_.store(util::mono_secs());
  return true;
}

bool Reflector::apply_event_proto(const proto::WatchEventPtr& event) {
  const std::string& type = event->type;

  if (type == "ERROR") {
    if (request_relist("ERROR event code " + std::to_string(event->error_code))) {
      log::warn("informer", "watch " + spec_.list_path + " ERROR event (code " +
                std::to_string(event->error_code) + "); relisting");
    }
    return false;
  }

  const std::string& rv = event->resource_version;

  if (type == "BOOKMARK") {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.bookmarks;
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "ADDED" || type == "MODIFIED") {
    if (!event->has_object) return true;
    if (event->ns.empty() || event->name.empty()) return true;
    std::string path =
        spec_.prefix + "namespaces/" + event->ns + "/" + spec_.plural + "/" + event->name;
    bool existed = store_.contains(path);
    // The FUSED path: the frame's single decode scan already produced the
    // key, the object byte range and its fingerprint — journal mark and
    // store write happen here with no Value/Doc in between. The frame
    // buffer rides into the store via an aliasing shared_ptr; the object
    // materializes only if some cycle actually reads it.
    journal_touch(path);
    store_.upsert_proto(path,
                        std::shared_ptr<const std::string>(event, &event->body),
                        event->obj_off, event->obj_len, event->api_version, event->kind,
                        event->fp);
    proto::counters().fused_events.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++(existed ? stats_.updates : stats_.adds);
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "DELETED") {
    if (!event->has_object) return true;
    if (event->ns.empty() || event->name.empty()) return true;
    std::string path =
        spec_.prefix + "namespaces/" + event->ns + "/" + spec_.plural + "/" + event->name;
    journal_touch(path);
    store_.erase(path);
    proto::counters().fused_events.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.deletes;
    if (!rv.empty()) stats_.resource_version = rv;
  } else {
    log::debug("informer", "ignoring unknown watch event type: " + type);
    return true;
  }
  if (!rv.empty()) {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
  }
  last_activity_mono_.store(util::mono_secs());
  return true;
}

namespace {

// Stop-responsive jittered sleep via the unified backoff::Policy:
// exponential base capped at 10 s, plus a deterministic per-path offset
// so a fleet of reflectors knocked over by one apiserver hiccup does not
// relist in lockstep (the same rationale as the 429 path in k8s.cpp).
// `cause` distinguishes relist from watch retries in
// tpu_pruner_retries_total.
void backoff_sleep(const std::string& path, int attempt, const std::atomic<bool>& stop,
                   const char* cause) {
  int64_t wait_ms = backoff::policy().exp_delay_ms(path, attempt);
  backoff::record_retry("k8s", cause, static_cast<double>(wait_ms) / 1000.0);
  backoff::sleep_interruptible(wait_ms, &stop);
}

}  // namespace

namespace {

// Satellite: LIST page bodies above this threshold never ride into the
// store via aliasing shared_ptr slices — one live pod must not pin a
// whole page. Tunable for the regression test; 64 KiB keeps small-page
// zero-copy behavior intact.
size_t page_retain_limit() {
  static const size_t limit = [] {
    long v = 64 * 1024;
    if (auto e = util::env("TPU_PRUNER_PAGE_RETAIN_BYTES")) {
      char* end = nullptr;
      long parsed = std::strtol(e->c_str(), &end, 10);
      if (end && *end == '\0' && parsed >= 0) v = parsed;
    }
    return static_cast<size_t>(v);
  }();
  return limit;
}

// Cold-sync decode pool. Informer-owned: shard::Pool::run is
// single-client, and the process-wide shard::pool() belongs to the
// daemon's reconcile loop, which a mid-run relist would race. The mutex
// serializes fan-out across reflectors (capi sessions can run several
// pods reflectors at once). TPU_PRUNER_SYNC_WORKERS pins the pool size
// (the bench's shard-curve sweep); default = hardware concurrency.
shard::Pool& sync_pool() {
  static shard::Pool pool([] {
    if (auto e = util::env("TPU_PRUNER_SYNC_WORKERS")) {
      char* end = nullptr;
      long v = std::strtol(e->c_str(), &end, 10);
      if (end && *end == '\0' && v >= 1) return static_cast<size_t>(v);
    }
    return shard::resolve_shard_count(-1);
  }());
  return pool;
}

// TPU_PRUNER_SYNC_PIPELINE=off falls back to the serial fetch→decode
// LIST (page N fully decoded before page N+1 is requested) — the
// pre-pipeline shape, kept as the bench's before/after baseline and an
// escape hatch.
bool sync_pipeline_enabled() {
  static const bool on = [] {
    auto e = util::env("TPU_PRUNER_SYNC_PIPELINE");
    if (e) return *e != "off";
    // Auto: overlapping fetch with decode needs a second core — on a
    // 1-core host the fetcher thread only steals time from the decoder
    // (measured ~40% slower), so default to the serial shape there.
    return std::thread::hardware_concurrency() > 1;
  }();
  return on;
}

std::mutex& sync_pool_mutex() {
  static std::mutex m;
  return m;
}

}  // namespace

void Reflector::cold_sync(bool wire_proto, bool zero_copy) {
  const auto t0 = std::chrono::steady_clock::now();
  if (!wire_proto && !zero_copy) {
    // Legacy single-response LIST (zero-copy off): Value trees, no pages
    // to pipeline.
    apply_list(kube_.list(spec_.list_path, "", kListPageLimit));
  } else {
    // Pipelined paginated LIST: a fetcher thread pulls page N+1 while
    // this thread decodes and keys page N. Keyed upserts into the
    // snapshot map are order-independent, and apply_list_snapshot marks
    // the journal globally dirty — incremental semantics are untouched.
    struct Page {
      proto::ListPagePtr pb;
      json::DocPtr doc;
    };
    std::string rv;
    const bool compact_pods = spec_.plural == "pods" && compact::enabled();
    std::map<std::string, Store::Entry> snapshot;

    // Build one (path, entry) pair for a protobuf item.
    auto wire_entry = [&](const proto::ListPagePtr& pb,
                          const std::shared_ptr<const std::string>& body, bool copy_out,
                          const proto::ObjectRef& ref) {
      Store::Entry e;
      e.pfp = ref.fp;
      if (compact_pods) {
        try {
          e.rec = std::make_shared<const compact::PodRecord>(compact::record_from_proto(
              std::string_view(body->data() + ref.off, ref.len), pb->api_version, pb->kind));
        } catch (const json::ParseError&) {
          // Keep the raw bytes (copied out) so the malformed payload
          // still throws at get(), where the lazy decode would have.
          Store::Entry::Exact& x = e.ex();
          x.pbody = std::make_shared<const std::string>(body->data() + ref.off, ref.len);
          x.plen = ref.len;
          x.papi = pb->api_version;
          x.pkind = pb->kind;
        }
      } else if (copy_out) {
        Store::Entry::Exact& x = e.ex();
        x.pbody = std::make_shared<const std::string>(body->data() + ref.off, ref.len);
        x.plen = ref.len;
        x.papi = pb->api_version;
        x.pkind = pb->kind;
      } else {
        Store::Entry::Exact& x = e.ex();
        x.pbody = body;
        x.poff = ref.off;
        x.plen = ref.len;
        x.papi = pb->api_version;
        x.pkind = pb->kind;
      }
      return e;
    };

    // Build one (path, entry) pair for an arena-Doc item node.
    auto doc_entry = [&](const json::DocPtr& doc, uint32_t node) {
      Store::Entry e;
      if (compact_pods) {
        Value v = doc->node(node).to_value();
        if (auto rec = compact::record_from_value(v)) {
          e.rec = std::make_shared<const compact::PodRecord>(std::move(*rec));
        } else {
          e.ex().value = std::move(v);
        }
      } else {
        Store::Entry::Exact& x = e.ex();
        x.doc = doc;
        x.node = node;
      }
      return e;
    };

    auto decode_page = [&](const Page& page) {
      if (page.pb) {
        // Each protobuf page was scanned ONCE (item ranges + store keys +
        // fingerprints). Compact mode decodes items straight into packed
        // records; otherwise entries reference the page buffer — copied
        // out per item above the retention threshold so one live pod
        // cannot pin a large page.
        const auto& pb = page.pb;
        auto body = std::shared_ptr<const std::string>(pb, &pb->body);
        const bool copy_out = pb->body.size() > page_retain_limit();
        const size_t n = pb->items.size();
        const size_t workers = compact_pods ? std::min(sync_pool().size(), n) : 1;
        if (workers > 1) {
          std::vector<std::vector<std::pair<std::string, Store::Entry>>> partial(workers);
          std::lock_guard<std::mutex> pool_lock(sync_pool_mutex());
          sync_pool().run(workers, [&](size_t w) {
            for (size_t i = w; i < n; i += workers) {
              const proto::ObjectRef& ref = pb->items[i];
              if (ref.ns.empty() || ref.name.empty()) continue;
              std::string path = spec_.prefix + "namespaces/" + ref.ns + "/" + spec_.plural +
                                 "/" + ref.name;
              partial[w].emplace_back(std::move(path), wire_entry(pb, body, copy_out, ref));
            }
          });
          for (auto& vec : partial) {
            for (auto& [path, e] : vec) snapshot[std::move(path)] = std::move(e);
          }
        } else {
          for (const proto::ObjectRef& ref : pb->items) {
            if (ref.ns.empty() || ref.name.empty()) continue;
            std::string path =
                spec_.prefix + "namespaces/" + ref.ns + "/" + spec_.plural + "/" + ref.name;
            snapshot[std::move(path)] = wire_entry(pb, body, copy_out, ref);
          }
        }
      } else if (page.doc) {
        // Zero-copy JSON page: the snapshot holds (page, node) references
        // (compact mode packs pods into records instead and releases the
        // page arena).
        auto items = page.doc->root().find("items");
        if (!items || !items->is_array()) return;
        std::vector<uint32_t> nodes;
        nodes.reserve(items->size());
        json::Doc::Node item = items->first_child();
        for (size_t i = 0; i < items->size(); ++i, item = item.next_sibling()) {
          nodes.push_back(item.index());
        }
        const size_t workers = compact_pods ? std::min(sync_pool().size(), nodes.size()) : 1;
        if (workers > 1) {
          std::vector<std::vector<std::pair<std::string, Store::Entry>>> partial(workers);
          std::lock_guard<std::mutex> pool_lock(sync_pool_mutex());
          sync_pool().run(workers, [&](size_t w) {
            for (size_t i = w; i < nodes.size(); i += workers) {
              std::string path = object_path_of_doc(page.doc->node(nodes[i]));
              if (path.empty()) continue;
              partial[w].emplace_back(std::move(path), doc_entry(page.doc, nodes[i]));
            }
          });
          for (auto& vec : partial) {
            for (auto& [path, e] : vec) snapshot[std::move(path)] = std::move(e);
          }
        } else {
          for (uint32_t node : nodes) {
            std::string path = object_path_of_doc(page.doc->node(node));
            if (!path.empty()) snapshot[std::move(path)] = doc_entry(page.doc, node);
          }
        }
      }
    };

    if (!sync_pipeline_enabled()) {
      // Serial baseline: decode page N before requesting N+1 (decode
      // errors propagate straight out of the pager callback).
      if (wire_proto) {
        rv = kube_.list_pages_wire(
            spec_.list_path, "", kListPageLimit,
            [&](const k8s::Client::WirePage& page) { decode_page(Page{page.pb, page.doc}); });
      } else {
        rv = kube_.list_pages(spec_.list_path, "", kListPageLimit,
                              [&](const json::DocPtr& page) { decode_page(Page{nullptr, page}); });
      }
    } else {
      constexpr size_t kMaxQueuedPages = 4;
      std::mutex qmu;
      std::condition_variable qcv;
      std::deque<Page> queue;
      bool fetch_done = false;
      std::exception_ptr fetch_err;
      std::exception_ptr decode_err;
      auto push = [&](Page page) {
        std::unique_lock<std::mutex> lock(qmu);
        qcv.wait(lock, [&] { return queue.size() < kMaxQueuedPages; });
        queue.push_back(std::move(page));
        qcv.notify_all();
      };
      std::thread fetcher([&] {
        try {
          if (wire_proto) {
            rv = kube_.list_pages_wire(
                spec_.list_path, "", kListPageLimit,
                [&](const k8s::Client::WirePage& page) { push(Page{page.pb, page.doc}); });
          } else {
            rv = kube_.list_pages(spec_.list_path, "", kListPageLimit,
                                  [&](const json::DocPtr& page) { push(Page{nullptr, page}); });
          }
        } catch (...) {
          std::lock_guard<std::mutex> lock(qmu);
          fetch_err = std::current_exception();
        }
        {
          std::lock_guard<std::mutex> lock(qmu);
          fetch_done = true;
        }
        qcv.notify_all();
      });
      while (true) {
        Page page;
        {
          std::unique_lock<std::mutex> lock(qmu);
          qcv.wait(lock, [&] { return !queue.empty() || fetch_done; });
          if (queue.empty()) break;
          page = std::move(queue.front());
          queue.pop_front();
          qcv.notify_all();
        }
        if (decode_err) continue;  // keep draining so the fetcher can finish
        try {
          decode_page(page);
        } catch (...) {
          decode_err = std::current_exception();
        }
      }
      fetcher.join();
      if (fetch_err) std::rethrow_exception(fetch_err);
      if (decode_err) std::rethrow_exception(decode_err);
    }
    apply_list_snapshot(std::move(snapshot), std::move(rv));
  }
  double secs = std::chrono::duration<double>(std::chrono::steady_clock::now() - t0).count();
  cold_sync_secs_.store(secs);
  compact::note_cold_sync(spec_.plural, secs, store_.size());
}

void Reflector::run() {
  int list_failures = 0;
  // Latched once per reflector lifetime: flipping the process-wide toggle
  // mid-watch must not mix decode paths within one stream.
  const bool zero_copy = json::zero_copy_enabled();
  while (!stop_.load()) {
    // Binary wire path (--wire proto|auto): negotiated per LIST/watch
    // attempt — under auto a refused endpoint flips k8s_proto_wanted()
    // off and the next attempt stops asking. Pods only: the owner kinds
    // include four CRs, which real apiservers serve as JSON anyway.
    const bool wire_proto = spec_.plural == "pods" && proto::k8s_proto_wanted();
    try {
      // Paginated initial LIST (limit/continue): a 100k-pod cluster
      // arrives in kListPageLimit-object chunks instead of one giant
      // response the apiserver (or this process) has to materialize at
      // once — the same chunking client-go's pager applies. PR 14: the
      // fetch and the decode of successive pages now overlap
      // (cold_sync's pipeline), and compact mode fans item decode out
      // over the informer's shard pool.
      cold_sync(wire_proto, zero_copy);
    } catch (const std::exception& e) {
      synced_.store(false);
      log::warn("informer", "LIST " + spec_.list_path + " failed: " + std::string(e.what()));
      backoff_sleep(spec_.list_path, ++list_failures, stop_, "relist");
      continue;
    }
    list_failures = 0;
    log::debug("informer", "synced " + spec_.list_path + " (" +
               std::to_string(store_.size()) + " objects at rv " + resource_version() + ")");

    int watch_failures = 0;
    bool relist = false;
    while (!stop_.load() && !relist) {
      k8s::Client::WatchOptions wopts;
      wopts.resource_version = resource_version();
      wopts.abort = [this] { return stop_.load(); };
      try {
        if (wire_proto) {
          kube_.watch_wire(spec_.list_path, wopts, [&](const k8s::Client::WireWatchEvent& ev) {
            bool ok = ev.pb ? apply_event_proto(ev.pb) : apply_event_doc(ev.doc);
            if (!ok) {
              relist = true;
              return false;
            }
            watch_failures = 0;
            return !stop_.load();
          });
        } else if (zero_copy) {
          kube_.watch_doc(spec_.list_path, wopts, [&](const json::DocPtr& ev) {
            if (!apply_event_doc(ev)) {
              relist = true;
              return false;
            }
            watch_failures = 0;
            return !stop_.load();
          });
        } else {
          kube_.watch(spec_.list_path, wopts, [&](const Value& ev) {
            if (!apply_event(ev)) {
              relist = true;
              return false;
            }
            watch_failures = 0;
            return !stop_.load();
          });
        }
        // Clean server close: routine — re-watch from the last seen rv.
      } catch (const k8s::ApiError& e) {
        if (e.status == 410) {
          if (request_relist("watch HTTP 410")) {
            log::info("informer", "watch " + spec_.list_path +
                      " got 410 Gone (compacted past rv " + resource_version() +
                      "); relisting");
          }
          relist = true;
        } else {
          ++watch_failures;
          bump_watch_failure(e.what());
          backoff_sleep(spec_.list_path, watch_failures, stop_, "watch");
        }
      } catch (const std::exception& e) {
        ++watch_failures;
        bump_watch_failure(e.what());
        backoff_sleep(spec_.list_path, watch_failures, stop_, "watch");
      }
      if (watch_failures >= 3 && !relist) {
        // The watch cannot hold; events may have been missed while flapping.
        // Treat like a 410: stop serving, then rebuild from a fresh LIST.
        request_relist("watch failure streak");
        relist = true;
      }
    }
    if (relist && !stop_.load()) {
      // CRITICAL ORDER: unsync BEFORE the relist LIST goes out. Between
      // the missed events and the fresh snapshot the store may describe
      // deleted or replaced objects; a concurrent cycle must fall back to
      // live GETs rather than actuate from that state (the no-stale-patch
      // guarantee the tests pin).
      synced_.store(false);
    }
  }
}

void Reflector::bump_watch_failure(const std::string& why) {
  {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.watch_failures;
  }
  log::counter_add("informer_watch_failures", 1);
  log::warn("informer", "watch " + spec_.list_path + " failed: " + why);
}

// ── ClusterCache ──

ClusterCache::ClusterCache(const k8s::Client& kube, std::vector<ResourceSpec> specs) {
  reflectors_.reserve(specs.size());
  for (ResourceSpec& spec : specs) {
    reflectors_.push_back(std::make_unique<Reflector>(kube, std::move(spec)));
  }
}

ClusterCache::~ClusterCache() { stop(); }

void ClusterCache::start() {
  start_mono_.store(util::mono_secs());
  for (auto& r : reflectors_) r->start();
}

void ClusterCache::stop() {
  // Signal everyone first, then join: stops overlap instead of serializing
  // nine 250ms-bounded poll exits.
  for (auto& r : reflectors_) r->stop();
}

bool ClusterCache::all_synced() const {
  for (const auto& r : reflectors_) {
    if (!r->synced()) return false;
  }
  return !reflectors_.empty();
}

bool ClusterCache::pods_synced() const {
  for (const auto& r : reflectors_) {
    if (r->spec().plural == "pods") return r->synced();
  }
  return false;
}

bool ClusterCache::wait_synced(int timeout_ms) const {
  int64_t deadline = util::mono_secs() * 1000 + timeout_ms;
  while (!all_synced()) {
    if (util::mono_secs() * 1000 >= deadline) return false;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  return true;
}

const Reflector* ClusterCache::route(const std::string& object_path) const {
  for (const auto& r : reflectors_) {
    const ResourceSpec& s = r->spec();
    std::string ns_prefix = s.prefix + "namespaces/";
    if (!util::starts_with(object_path, ns_prefix)) continue;
    // Expect "<ns>/<plural>/<name>" past the prefix.
    std::vector<std::string> parts =
        util::split(object_path.substr(ns_prefix.size()), '/');
    if (parts.size() == 3 && parts[1] == s.plural && !parts[2].empty()) return r.get();
  }
  return nullptr;
}

std::optional<Value> ClusterCache::get(const std::string& object_path) const {
  const Reflector* r = route(object_path);
  if (!r || !r->synced()) return std::nullopt;
  return r->get(object_path);
}

int64_t ClusterCache::staleness_secs() const {
  int64_t now = util::mono_secs();
  int64_t started = start_mono_.load();
  int64_t worst = 0;
  for (const auto& r : reflectors_) {
    int64_t last = r->last_activity_mono();
    // A reflector that never applied anything is as stale as the CACHE is
    // old. Anchor to start() — the raw steady clock reads as machine
    // uptime here, which served a garbage gauge whenever a resource never
    // managed its first LIST (e.g. a denied `watch`/`list` RBAC verb).
    int64_t age = last == 0 ? (started ? now - started : 0) : now - last;
    worst = std::max(worst, age);
  }
  return worst;
}

void ClusterCache::enable_dirty_journal() {
  for (auto& r : reflectors_) r->enable_dirty_journal();
}

void ClusterCache::set_dirty_notify(std::function<void(int64_t)> notify) {
  for (auto& r : reflectors_) r->set_dirty_notify(notify);
}

ClusterCache::DirtyDrain ClusterCache::drain_dirty() const {
  DirtyDrain out;
  for (auto& r : reflectors_) {
    r->drain_dirty(out.paths, out.all);
    out.overflows_total += r->journal_overflows();
  }
  return out;
}

Value ClusterCache::stats_json() const {
  Value resources = Value::object();
  bool synced = !reflectors_.empty();
  uint64_t objects = 0;
  uint64_t store_bytes = 0;
  for (const auto& r : reflectors_) {
    ResourceStats s = r->stats();
    synced = synced && s.synced;
    objects += s.objects;
    store_bytes += s.store_bytes;
    Value rs = Value::object();
    rs.set("synced", Value(s.synced));
    rs.set("objects", Value(static_cast<int64_t>(s.objects)));
    rs.set("adds", Value(static_cast<int64_t>(s.adds)));
    rs.set("updates", Value(static_cast<int64_t>(s.updates)));
    rs.set("deletes", Value(static_cast<int64_t>(s.deletes)));
    rs.set("bookmarks", Value(static_cast<int64_t>(s.bookmarks)));
    rs.set("relists", Value(static_cast<int64_t>(s.relists)));
    rs.set("relist_requests", Value(static_cast<int64_t>(s.relist_requests)));
    rs.set("watch_failures", Value(static_cast<int64_t>(s.watch_failures)));
    rs.set("resource_version", Value(s.resource_version));
    rs.set("store_bytes", Value(static_cast<int64_t>(s.store_bytes)));
    if (s.cold_sync_seconds >= 0) rs.set("cold_sync_seconds", Value(s.cold_sync_seconds));
    resources.set(r->spec().list_path, std::move(rs));
  }
  Value out = Value::object();
  out.set("synced", Value(synced));
  out.set("objects", Value(static_cast<int64_t>(objects)));
  out.set("store_bytes", Value(static_cast<int64_t>(store_bytes)));
  out.set("resources", std::move(resources));
  return out;
}

}  // namespace tpupruner::informer
