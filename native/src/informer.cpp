#include "tpupruner/informer.hpp"

#include <algorithm>
#include <chrono>
#include <functional>

#include "tpupruner/log.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::informer {

using json::Value;

std::optional<ResourceSpec> spec_for(std::string_view plural) {
  static const std::map<std::string, ResourceSpec, std::less<>> kSpecs = [] {
    std::map<std::string, ResourceSpec, std::less<>> out;
    auto add = [&](const std::string& prefix, const std::string& p) {
      out[p] = ResourceSpec{prefix + p, prefix, p};
    };
    add("/api/v1/", "pods");
    add("/apis/apps/v1/", "replicasets");
    add("/apis/apps/v1/", "deployments");
    add("/apis/apps/v1/", "statefulsets");
    add("/apis/batch/v1/", "jobs");
    add("/apis/jobset.x-k8s.io/v1alpha2/", "jobsets");
    add("/apis/leaderworkerset.x-k8s.io/v1/", "leaderworkersets");
    add("/apis/kubeflow.org/v1/", "notebooks");
    add("/apis/serving.kserve.io/v1beta1/", "inferenceservices");
    return out;
  }();
  auto it = kSpecs.find(plural);
  if (it == kSpecs.end()) return std::nullopt;
  return it->second;
}

std::vector<ResourceSpec> daemon_specs() {
  // Pods plus every kind the owner walk can touch: the walk must be able
  // to resolve a full chain (Pod → RS → Deployment, Pod → Job → JobSet,
  // label shortcuts to LWS/InferenceService) without leaving the cache.
  std::vector<ResourceSpec> out;
  for (const char* p : {"pods", "replicasets", "deployments", "statefulsets", "jobs",
                        "jobsets", "leaderworkersets", "notebooks", "inferenceservices"}) {
    out.push_back(*spec_for(p));
  }
  return out;
}

// ── Store ──

std::optional<Value> Store::get(const std::string& object_path) const {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = objects_.find(object_path);
  if (it == objects_.end()) return std::nullopt;
  Entry& e = it->second;
  if (e.doc) {
    // Arena-backed entry: materialize on demand, then MEMOIZE — a warm
    // cycle re-reads the same candidate pods and owner objects every
    // interval, and re-building the tree each time put the conversion in
    // the resolve hot path. Only the objects a cycle touches pay (once);
    // the other 99k pods stay flat arena nodes. The doc stays referenced
    // so sibling entries of the same LIST page / watch event are
    // unaffected.
    e.value = e.doc->node(e.node).to_value();
    e.doc.reset();
  } else if (e.pbody) {
    // Proto-backed entry (--wire proto): same memoized-materialization
    // contract, from the raw protobuf slice. Produces a Value identical
    // to parsing the object's JSON form (pinned by the wire parity
    // corpus), so every consumer downstream is wire-format blind.
    e.value = proto::object_to_value(
        std::string_view(e.pbody->data() + e.poff, e.plen), e.papi, e.pkind);
    e.pbody.reset();
  }
  return e.value;  // COW copy: shares nodes, pointer-sized
}

bool Store::contains(const std::string& object_path) const {
  std::lock_guard<std::mutex> lock(mutex_);
  return objects_.count(object_path) > 0;
}

size_t Store::size() const {
  std::lock_guard<std::mutex> lock(mutex_);
  return objects_.size();
}

void Store::replace(std::map<std::string, Value> objects) {
  std::map<std::string, Entry> entries;
  for (auto& [path, v] : objects) {
    entries[path].value = std::move(v);
  }
  replace_entries(std::move(entries));
}

void Store::replace_entries(std::map<std::string, Entry> objects) {
  std::lock_guard<std::mutex> lock(mutex_);
  objects_ = std::move(objects);
}

void Store::upsert(const std::string& object_path, Value object) {
  std::lock_guard<std::mutex> lock(mutex_);
  objects_[object_path] = Entry{std::move(object), nullptr, 0};
}

void Store::upsert_doc(const std::string& object_path, json::DocPtr doc, uint32_t node) {
  std::lock_guard<std::mutex> lock(mutex_);
  objects_[object_path] = Entry{Value(), std::move(doc), node};
}

void Store::upsert_proto(const std::string& object_path, std::shared_ptr<const std::string> body,
                         size_t off, size_t len, std::string api_version, std::string kind,
                         uint64_t fp) {
  std::lock_guard<std::mutex> lock(mutex_);
  Entry e;
  e.pbody = std::move(body);
  e.poff = off;
  e.plen = len;
  e.papi = std::move(api_version);
  e.pkind = std::move(kind);
  e.pfp = fp;
  objects_[object_path] = std::move(e);
}

uint64_t Store::proto_fingerprint(const std::string& object_path) const {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = objects_.find(object_path);
  return it == objects_.end() ? 0 : it->second.pfp;
}

void Store::erase(const std::string& object_path) {
  std::lock_guard<std::mutex> lock(mutex_);
  objects_.erase(object_path);
}

// ── Reflector ──

// Page size for the initial/relist LIST (limit/continue). 500 is the
// client-go pager default: big enough that a 4k-pod cluster still lists
// in a handful of round-trips, small enough that a 100k-pod LIST never
// materializes as one response on either end.
constexpr int64_t kListPageLimit = 500;

// Dirty-journal bound: past this many undrained paths the journal
// degrades to globally dirty. A cycle interval's worth of churn is
// normally a few hundred events; hitting the cap means the consumer
// stopped draining (or the cluster is churning at relist scale), and a
// full recompute is the honest answer either way.
constexpr size_t kDirtyJournalCap = 65536;

void Reflector::enable_dirty_journal() { journal_enabled_.store(true); }

void Reflector::drain_dirty(std::vector<std::string>& paths, bool& all) const {
  std::lock_guard<std::mutex> lock(dirty_mutex_);
  if (dirty_all_) all = true;
  dirty_all_ = false;
  for (std::string& p : dirty_paths_) paths.push_back(std::move(p));
  dirty_paths_.clear();
}

void Reflector::journal_touch(const std::string& path) {
  if (!journal_enabled_.load(std::memory_order_relaxed)) return;
  std::lock_guard<std::mutex> lock(dirty_mutex_);
  if (dirty_all_) return;  // already globally dirty; paths are redundant
  if (dirty_paths_.size() >= kDirtyJournalCap) {
    dirty_paths_.clear();
    dirty_all_ = true;
    ++journal_overflows_;
    return;
  }
  dirty_paths_.push_back(path);
}

uint64_t Reflector::journal_overflows() const {
  std::lock_guard<std::mutex> lock(dirty_mutex_);
  return journal_overflows_;
}

size_t dirty_journal_cap() { return kDirtyJournalCap; }

void Reflector::journal_all() {
  if (!journal_enabled_.load(std::memory_order_relaxed)) return;
  std::lock_guard<std::mutex> lock(dirty_mutex_);
  dirty_paths_.clear();
  dirty_all_ = true;
}

Reflector::Reflector(const k8s::Client& kube, ResourceSpec spec)
    : kube_(kube), spec_(std::move(spec)) {}

Reflector::~Reflector() { stop(); }

void Reflector::start() {
  if (thread_.joinable()) return;
  stop_.store(false);
  thread_ = std::thread(&Reflector::run, this);
}

void Reflector::stop() {
  stop_.store(true);
  if (thread_.joinable()) thread_.join();
}

std::optional<Value> Reflector::get(const std::string& object_path) const {
  return store_.get(object_path);
}

ResourceStats Reflector::stats() const {
  ResourceStats out;
  {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    out = stats_;
  }
  out.synced = synced_.load();
  out.objects = store_.size();
  return out;
}

std::string Reflector::object_path_of(const Value& object) const {
  const Value* ns = object.at_path("metadata.namespace");
  const Value* name = object.at_path("metadata.name");
  if (!ns || !ns->is_string() || !name || !name->is_string()) return "";
  return spec_.prefix + "namespaces/" + ns->as_string() + "/" + spec_.plural + "/" +
         name->as_string();
}

std::string Reflector::resource_version() const {
  std::lock_guard<std::mutex> lock(stats_mutex_);
  return resource_version_;
}

bool Reflector::request_relist(const std::string& why) {
  if (relist_pending_.exchange(true)) {
    // A relist is already in flight — coalesce, never stack: two LISTs
    // for one gap would double the apiserver cost of every compaction
    // and re-unsync the store right after it recovered.
    log::debug("informer", "watch " + spec_.list_path + " relist request (" + why +
               ") coalesced into the in-flight relist");
    return false;
  }
  std::lock_guard<std::mutex> lock(stats_mutex_);
  ++stats_.relist_requests;
  return true;
}

void Reflector::apply_list(const Value& list) {
  std::map<std::string, Store::Entry> snapshot;
  if (const Value* items = list.find("items"); items && items->is_array()) {
    for (const Value& item : items->as_array()) {
      std::string path = object_path_of(item);
      if (!path.empty()) snapshot[std::move(path)].value = item;
    }
  }
  std::string rv;
  if (const Value* v = list.at_path("metadata.resourceVersion"); v && v->is_string()) {
    rv = v->as_string();
  }
  apply_list_snapshot(std::move(snapshot), std::move(rv));
}

void Reflector::apply_list_snapshot(std::map<std::string, Store::Entry> snapshot,
                                    std::string rv) {
  // A LIST snapshot means the watch stream could not be trusted (initial
  // sync, 410, failure streak) — events may have been missed, so the
  // incremental engine must treat everything as changed.
  journal_all();
  store_.replace_entries(std::move(snapshot));
  {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
    ++stats_.relists;  // counts the initial LIST too: relists == LISTs issued
    stats_.resource_version = rv;
  }
  // The fresh snapshot services any pending relist request — a 410 that
  // arrived while this LIST was in flight is satisfied by it, not queued.
  relist_pending_.store(false);
  synced_.store(true);
  last_activity_mono_.store(util::mono_secs());
  log::counter_add("informer_relists", 1);
}

std::string Reflector::object_path_of_doc(const json::Doc::Node& object) const {
  auto ns = object.at_path("metadata.namespace");
  auto name = object.at_path("metadata.name");
  if (!ns || !ns->is_string() || !name || !name->is_string()) return "";
  return spec_.prefix + "namespaces/" + std::string(ns->as_sv()) + "/" + spec_.plural + "/" +
         std::string(name->as_sv());
}

bool Reflector::apply_event(const Value& event) {
  std::string type = event.get_string("type");
  const Value* object = event.find("object");

  if (type == "ERROR") {
    // The in-band relist signal: {"type":"ERROR","object":<Status>}, most
    // commonly code 410 after apiserver compaction. Any ERROR means the
    // stream can no longer be trusted — relist regardless of code. A 410
    // arriving while a relist LIST is already in flight coalesces into it
    // (request_relist) instead of queueing a second relist.
    int64_t code = 0;
    if (object) {
      if (const Value* c = object->find("code"); c && c->is_number()) code = c->as_int();
    }
    if (request_relist("ERROR event code " + std::to_string(code))) {
      log::warn("informer", "watch " + spec_.list_path + " ERROR event (code " +
                std::to_string(code) + "); relisting");
    }
    return false;
  }

  std::string rv;
  if (object) {
    if (const Value* v = object->at_path("metadata.resourceVersion"); v && v->is_string()) {
      rv = v->as_string();
    }
  }

  if (type == "BOOKMARK") {
    // Progress marker only: no object payload beyond metadata. Advancing
    // the resume point here is what keeps a relist after a quiet period
    // from replaying (or 410ing on) long-compacted history.
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.bookmarks;
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "ADDED" || type == "MODIFIED") {
    if (!object) return true;
    std::string path = object_path_of(*object);
    if (path.empty()) return true;
    bool existed = store_.get(path).has_value();
    journal_touch(path);
    store_.upsert(path, *object);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++(existed ? stats_.updates : stats_.adds);
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "DELETED") {
    if (!object) return true;
    std::string path = object_path_of(*object);
    if (path.empty()) return true;
    journal_touch(path);
    store_.erase(path);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.deletes;
    if (!rv.empty()) stats_.resource_version = rv;
  } else {
    log::debug("informer", "ignoring unknown watch event type: " + type);
    return true;
  }
  if (!rv.empty()) {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
  }
  last_activity_mono_.store(util::mono_secs());
  return true;
}

bool Reflector::apply_event_doc(const json::DocPtr& event) {
  json::Doc::Node root = event->root();
  std::string type(root.get_string("type"));
  std::optional<json::Doc::Node> object = root.find("object");

  if (type == "ERROR") {
    int64_t code = 0;
    if (object) {
      if (auto c = object->find("code"); c && c->is_number()) code = c->as_int();
    }
    if (request_relist("ERROR event code " + std::to_string(code))) {
      log::warn("informer", "watch " + spec_.list_path + " ERROR event (code " +
                std::to_string(code) + "); relisting");
    }
    return false;
  }

  std::string rv;
  if (object) {
    if (auto v = object->at_path("metadata.resourceVersion"); v && v->is_string()) {
      rv = std::string(v->as_sv());
    }
  }

  if (type == "BOOKMARK") {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.bookmarks;
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "ADDED" || type == "MODIFIED") {
    if (!object) return true;
    std::string path = object_path_of_doc(*object);
    if (path.empty()) return true;
    bool existed = store_.contains(path);
    // The event Doc rides into the store: the object stays arena-flat
    // until some cycle actually looks it up.
    journal_touch(path);
    store_.upsert_doc(path, event, object->index());
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++(existed ? stats_.updates : stats_.adds);
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "DELETED") {
    if (!object) return true;
    std::string path = object_path_of_doc(*object);
    if (path.empty()) return true;
    journal_touch(path);
    store_.erase(path);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.deletes;
    if (!rv.empty()) stats_.resource_version = rv;
  } else {
    log::debug("informer", "ignoring unknown watch event type: " + type);
    return true;
  }
  if (!rv.empty()) {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
  }
  last_activity_mono_.store(util::mono_secs());
  return true;
}

bool Reflector::apply_event_proto(const proto::WatchEventPtr& event) {
  const std::string& type = event->type;

  if (type == "ERROR") {
    if (request_relist("ERROR event code " + std::to_string(event->error_code))) {
      log::warn("informer", "watch " + spec_.list_path + " ERROR event (code " +
                std::to_string(event->error_code) + "); relisting");
    }
    return false;
  }

  const std::string& rv = event->resource_version;

  if (type == "BOOKMARK") {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.bookmarks;
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "ADDED" || type == "MODIFIED") {
    if (!event->has_object) return true;
    if (event->ns.empty() || event->name.empty()) return true;
    std::string path =
        spec_.prefix + "namespaces/" + event->ns + "/" + spec_.plural + "/" + event->name;
    bool existed = store_.contains(path);
    // The FUSED path: the frame's single decode scan already produced the
    // key, the object byte range and its fingerprint — journal mark and
    // store write happen here with no Value/Doc in between. The frame
    // buffer rides into the store via an aliasing shared_ptr; the object
    // materializes only if some cycle actually reads it.
    journal_touch(path);
    store_.upsert_proto(path,
                        std::shared_ptr<const std::string>(event, &event->body),
                        event->obj_off, event->obj_len, event->api_version, event->kind,
                        event->fp);
    proto::counters().fused_events.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++(existed ? stats_.updates : stats_.adds);
    if (!rv.empty()) stats_.resource_version = rv;
  } else if (type == "DELETED") {
    if (!event->has_object) return true;
    if (event->ns.empty() || event->name.empty()) return true;
    std::string path =
        spec_.prefix + "namespaces/" + event->ns + "/" + spec_.plural + "/" + event->name;
    journal_touch(path);
    store_.erase(path);
    proto::counters().fused_events.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.deletes;
    if (!rv.empty()) stats_.resource_version = rv;
  } else {
    log::debug("informer", "ignoring unknown watch event type: " + type);
    return true;
  }
  if (!rv.empty()) {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    resource_version_ = rv;
  }
  last_activity_mono_.store(util::mono_secs());
  return true;
}

namespace {

// Stop-responsive jittered sleep: exponential base capped at 10 s, plus a
// deterministic per-path offset so a fleet of reflectors knocked over by
// one apiserver hiccup does not relist in lockstep (the same rationale as
// the 429 path in k8s.cpp).
void backoff_sleep(const std::string& path, int attempt, const std::atomic<bool>& stop) {
  int64_t base = std::min<int64_t>(500LL << std::min(attempt, 5), 10000);
  int64_t jitter =
      static_cast<int64_t>(std::hash<std::string>{}(path + std::to_string(attempt)) % 500);
  int64_t wait_ms = base + jitter;
  for (int64_t waited = 0; waited < wait_ms && !stop.load(); waited += 100) {
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  }
}

}  // namespace

void Reflector::run() {
  int list_failures = 0;
  // Latched once per reflector lifetime: flipping the process-wide toggle
  // mid-watch must not mix decode paths within one stream.
  const bool zero_copy = json::zero_copy_enabled();
  while (!stop_.load()) {
    // Binary wire path (--wire proto|auto): negotiated per LIST/watch
    // attempt — under auto a refused endpoint flips k8s_proto_wanted()
    // off and the next attempt stops asking. Pods only: the owner kinds
    // include four CRs, which real apiservers serve as JSON anyway.
    const bool wire_proto = spec_.plural == "pods" && proto::k8s_proto_wanted();
    try {
      // Paginated initial LIST (limit/continue): a 100k-pod cluster
      // arrives in kListPageLimit-object chunks instead of one giant
      // response the apiserver (or this process) has to materialize at
      // once — the same chunking client-go's pager applies.
      if (wire_proto) {
        // Each protobuf page was scanned ONCE (item ranges + store keys +
        // fingerprints); entries reference the page buffer and stay
        // un-materialized until a cycle looks them up. JSON fallback
        // pages take the arena-Doc shape.
        std::map<std::string, Store::Entry> snapshot;
        std::string rv = kube_.list_pages_wire(
            spec_.list_path, "", kListPageLimit, [&](const k8s::Client::WirePage& page) {
              if (page.pb) {
                auto body = std::shared_ptr<const std::string>(page.pb, &page.pb->body);
                for (const proto::ObjectRef& ref : page.pb->items) {
                  if (ref.ns.empty() || ref.name.empty()) continue;
                  std::string path = spec_.prefix + "namespaces/" + ref.ns + "/" +
                                     spec_.plural + "/" + ref.name;
                  Store::Entry e;
                  e.pbody = body;
                  e.poff = ref.off;
                  e.plen = ref.len;
                  e.papi = page.pb->api_version;
                  e.pkind = page.pb->kind;
                  e.pfp = ref.fp;
                  snapshot[std::move(path)] = std::move(e);
                }
              } else if (page.doc) {
                auto items = page.doc->root().find("items");
                if (!items || !items->is_array()) return;
                json::Doc::Node item = items->first_child();
                for (size_t i = 0; i < items->size(); ++i, item = item.next_sibling()) {
                  std::string path = object_path_of_doc(item);
                  if (!path.empty()) {
                    snapshot[std::move(path)] = Store::Entry{Value(), page.doc, item.index()};
                  }
                }
              }
            });
        apply_list_snapshot(std::move(snapshot), std::move(rv));
      } else if (zero_copy) {
        // Zero-copy: each page body becomes an arena Doc; the snapshot
        // holds (page, node) references and the pods stay un-materialized
        // until a cycle looks them up.
        std::map<std::string, Store::Entry> snapshot;
        std::string rv =
            kube_.list_pages(spec_.list_path, "", kListPageLimit, [&](const json::DocPtr& page) {
              auto items = page->root().find("items");
              if (!items || !items->is_array()) return;
              json::Doc::Node item = items->first_child();
              for (size_t i = 0; i < items->size(); ++i, item = item.next_sibling()) {
                std::string path = object_path_of_doc(item);
                if (!path.empty()) {
                  snapshot[std::move(path)] = Store::Entry{Value(), page, item.index()};
                }
              }
            });
        apply_list_snapshot(std::move(snapshot), std::move(rv));
      } else {
        apply_list(kube_.list(spec_.list_path, "", kListPageLimit));
      }
    } catch (const std::exception& e) {
      synced_.store(false);
      log::warn("informer", "LIST " + spec_.list_path + " failed: " + std::string(e.what()));
      backoff_sleep(spec_.list_path, ++list_failures, stop_);
      continue;
    }
    list_failures = 0;
    log::debug("informer", "synced " + spec_.list_path + " (" +
               std::to_string(store_.size()) + " objects at rv " + resource_version() + ")");

    int watch_failures = 0;
    bool relist = false;
    while (!stop_.load() && !relist) {
      k8s::Client::WatchOptions wopts;
      wopts.resource_version = resource_version();
      wopts.abort = [this] { return stop_.load(); };
      try {
        if (wire_proto) {
          kube_.watch_wire(spec_.list_path, wopts, [&](const k8s::Client::WireWatchEvent& ev) {
            bool ok = ev.pb ? apply_event_proto(ev.pb) : apply_event_doc(ev.doc);
            if (!ok) {
              relist = true;
              return false;
            }
            watch_failures = 0;
            return !stop_.load();
          });
        } else if (zero_copy) {
          kube_.watch_doc(spec_.list_path, wopts, [&](const json::DocPtr& ev) {
            if (!apply_event_doc(ev)) {
              relist = true;
              return false;
            }
            watch_failures = 0;
            return !stop_.load();
          });
        } else {
          kube_.watch(spec_.list_path, wopts, [&](const Value& ev) {
            if (!apply_event(ev)) {
              relist = true;
              return false;
            }
            watch_failures = 0;
            return !stop_.load();
          });
        }
        // Clean server close: routine — re-watch from the last seen rv.
      } catch (const k8s::ApiError& e) {
        if (e.status == 410) {
          if (request_relist("watch HTTP 410")) {
            log::info("informer", "watch " + spec_.list_path +
                      " got 410 Gone (compacted past rv " + resource_version() +
                      "); relisting");
          }
          relist = true;
        } else {
          ++watch_failures;
          bump_watch_failure(e.what());
          backoff_sleep(spec_.list_path, watch_failures, stop_);
        }
      } catch (const std::exception& e) {
        ++watch_failures;
        bump_watch_failure(e.what());
        backoff_sleep(spec_.list_path, watch_failures, stop_);
      }
      if (watch_failures >= 3 && !relist) {
        // The watch cannot hold; events may have been missed while flapping.
        // Treat like a 410: stop serving, then rebuild from a fresh LIST.
        request_relist("watch failure streak");
        relist = true;
      }
    }
    if (relist && !stop_.load()) {
      // CRITICAL ORDER: unsync BEFORE the relist LIST goes out. Between
      // the missed events and the fresh snapshot the store may describe
      // deleted or replaced objects; a concurrent cycle must fall back to
      // live GETs rather than actuate from that state (the no-stale-patch
      // guarantee the tests pin).
      synced_.store(false);
    }
  }
}

void Reflector::bump_watch_failure(const std::string& why) {
  {
    std::lock_guard<std::mutex> lock(stats_mutex_);
    ++stats_.watch_failures;
  }
  log::counter_add("informer_watch_failures", 1);
  log::warn("informer", "watch " + spec_.list_path + " failed: " + why);
}

// ── ClusterCache ──

ClusterCache::ClusterCache(const k8s::Client& kube, std::vector<ResourceSpec> specs) {
  reflectors_.reserve(specs.size());
  for (ResourceSpec& spec : specs) {
    reflectors_.push_back(std::make_unique<Reflector>(kube, std::move(spec)));
  }
}

ClusterCache::~ClusterCache() { stop(); }

void ClusterCache::start() {
  start_mono_.store(util::mono_secs());
  for (auto& r : reflectors_) r->start();
}

void ClusterCache::stop() {
  // Signal everyone first, then join: stops overlap instead of serializing
  // nine 250ms-bounded poll exits.
  for (auto& r : reflectors_) r->stop();
}

bool ClusterCache::all_synced() const {
  for (const auto& r : reflectors_) {
    if (!r->synced()) return false;
  }
  return !reflectors_.empty();
}

bool ClusterCache::pods_synced() const {
  for (const auto& r : reflectors_) {
    if (r->spec().plural == "pods") return r->synced();
  }
  return false;
}

bool ClusterCache::wait_synced(int timeout_ms) const {
  int64_t deadline = util::mono_secs() * 1000 + timeout_ms;
  while (!all_synced()) {
    if (util::mono_secs() * 1000 >= deadline) return false;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  return true;
}

const Reflector* ClusterCache::route(const std::string& object_path) const {
  for (const auto& r : reflectors_) {
    const ResourceSpec& s = r->spec();
    std::string ns_prefix = s.prefix + "namespaces/";
    if (!util::starts_with(object_path, ns_prefix)) continue;
    // Expect "<ns>/<plural>/<name>" past the prefix.
    std::vector<std::string> parts =
        util::split(object_path.substr(ns_prefix.size()), '/');
    if (parts.size() == 3 && parts[1] == s.plural && !parts[2].empty()) return r.get();
  }
  return nullptr;
}

std::optional<Value> ClusterCache::get(const std::string& object_path) const {
  const Reflector* r = route(object_path);
  if (!r || !r->synced()) return std::nullopt;
  return r->get(object_path);
}

int64_t ClusterCache::staleness_secs() const {
  int64_t now = util::mono_secs();
  int64_t started = start_mono_.load();
  int64_t worst = 0;
  for (const auto& r : reflectors_) {
    int64_t last = r->last_activity_mono();
    // A reflector that never applied anything is as stale as the CACHE is
    // old. Anchor to start() — the raw steady clock reads as machine
    // uptime here, which served a garbage gauge whenever a resource never
    // managed its first LIST (e.g. a denied `watch`/`list` RBAC verb).
    int64_t age = last == 0 ? (started ? now - started : 0) : now - last;
    worst = std::max(worst, age);
  }
  return worst;
}

void ClusterCache::enable_dirty_journal() {
  for (auto& r : reflectors_) r->enable_dirty_journal();
}

ClusterCache::DirtyDrain ClusterCache::drain_dirty() const {
  DirtyDrain out;
  for (auto& r : reflectors_) {
    r->drain_dirty(out.paths, out.all);
    out.overflows_total += r->journal_overflows();
  }
  return out;
}

Value ClusterCache::stats_json() const {
  Value resources = Value::object();
  bool synced = !reflectors_.empty();
  uint64_t objects = 0;
  for (const auto& r : reflectors_) {
    ResourceStats s = r->stats();
    synced = synced && s.synced;
    objects += s.objects;
    Value rs = Value::object();
    rs.set("synced", Value(s.synced));
    rs.set("objects", Value(static_cast<int64_t>(s.objects)));
    rs.set("adds", Value(static_cast<int64_t>(s.adds)));
    rs.set("updates", Value(static_cast<int64_t>(s.updates)));
    rs.set("deletes", Value(static_cast<int64_t>(s.deletes)));
    rs.set("bookmarks", Value(static_cast<int64_t>(s.bookmarks)));
    rs.set("relists", Value(static_cast<int64_t>(s.relists)));
    rs.set("relist_requests", Value(static_cast<int64_t>(s.relist_requests)));
    rs.set("watch_failures", Value(static_cast<int64_t>(s.watch_failures)));
    rs.set("resource_version", Value(s.resource_version));
    resources.set(r->spec().list_path, std::move(rs));
  }
  Value out = Value::object();
  out.set("synced", Value(synced));
  out.set("objects", Value(static_cast<int64_t>(objects)));
  out.set("resources", std::move(resources));
  return out;
}

}  // namespace tpupruner::informer
