#include "tpupruner/log.hpp"

#include <cstdio>
#include <mutex>

#include "tpupruner/json.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::log {

namespace {

std::mutex g_mutex;
Format g_format = Format::Default;
Level g_threshold = Level::Info;
bool g_initialized = false;
std::map<std::string, Counter> g_counters;

Level parse_level(const std::string& s) {
  std::string l = util::to_lower(s);
  if (l == "trace") return Level::Trace;
  if (l == "debug") return Level::Debug;
  if (l == "info") return Level::Info;
  if (l == "warn" || l == "warning") return Level::Warn;
  if (l == "error") return Level::Error;
  return Level::Info;
}

const char* level_name(Level l) {
  switch (l) {
    case Level::Trace: return "TRACE";
    case Level::Debug: return "DEBUG";
    case Level::Info: return "INFO";
    case Level::Warn: return "WARN";
    case Level::Error: return "ERROR";
  }
  return "?";
}

const char* level_color(Level l) {
  switch (l) {
    case Level::Trace: return "\x1b[90m";
    case Level::Debug: return "\x1b[36m";
    case Level::Info: return "\x1b[32m";
    case Level::Warn: return "\x1b[33m";
    case Level::Error: return "\x1b[31m";
  }
  return "";
}

void ensure_init() {
  if (g_initialized) return;
  if (auto lv = util::env("TPU_PRUNER_LOG")) g_threshold = parse_level(*lv);
  else if (auto lv2 = util::env("RUST_LOG")) g_threshold = parse_level(*lv2);
  g_initialized = true;
}

}  // namespace

void init(Format format) {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_format = format;
  g_initialized = false;
  ensure_init();
}

Level threshold() {
  std::lock_guard<std::mutex> lock(g_mutex);
  ensure_init();
  return g_threshold;
}

void write(Level level, const std::string& msg) {
  std::lock_guard<std::mutex> lock(g_mutex);
  ensure_init();
  if (level < g_threshold) return;
  std::string ts = util::now_rfc3339_micro();
  switch (g_format) {
    case Format::Json: {
      json::Value v = json::Value::object();
      v.set("timestamp", json::Value(ts));
      v.set("level", json::Value(util::to_lower(level_name(level))));
      v.set("fields", json::Value(json::Object{{"message", json::Value(msg)}}));
      v.set("target", json::Value("tpu_pruner"));
      std::fprintf(stderr, "%s\n", v.dump().c_str());
      break;
    }
    case Format::Pretty:
      std::fprintf(stderr, "  %s%s\x1b[0m %s\n    \x1b[90mat %s\x1b[0m\n",
                   level_color(level), level_name(level), msg.c_str(), ts.c_str());
      break;
    case Format::Default:
      std::fprintf(stderr, "%s %5s tpu_pruner: %s\n", ts.c_str(), level_name(level), msg.c_str());
      break;
  }
  std::fflush(stderr);
}

void counter_add(const std::string& name, uint64_t delta) {
  std::lock_guard<std::mutex> lock(g_mutex);
  Counter& c = g_counters[name];
  c.value += delta;
  c.gauge = false;
}

void counter_set(const std::string& name, uint64_t value) {
  std::lock_guard<std::mutex> lock(g_mutex);
  Counter& c = g_counters[name];
  c.value = value;
  c.gauge = true;
}

std::map<std::string, Counter> counters_snapshot() {
  std::lock_guard<std::mutex> lock(g_mutex);
  return g_counters;
}

void counters_reset_for_test() {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_counters.clear();
}

}  // namespace tpupruner::log
