#include "tpupruner/log.hpp"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <mutex>

#include "tpupruner/json.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::log {

namespace {

std::mutex g_mutex;
Format g_format = Format::Default;
Level g_threshold = Level::Info;
bool g_initialized = false;
std::map<std::string, Counter> g_counters;
std::map<std::string, Level, std::less<>> g_module_levels;

// Cycle stamping: process-wide id set by the producer, thread override for
// consumers still draining an earlier cycle. Lock-free reads — log lines
// are emitted from every thread.
std::atomic<uint64_t> g_cycle{0};
thread_local uint64_t t_cycle = 0;

uint64_t effective_cycle() { return t_cycle ? t_cycle : g_cycle.load(std::memory_order_relaxed); }

// Histogram registry. Phase latencies span ~1ms (decode on a small fleet)
// to tens of seconds (a slow-API cycle), hence the wide log-ish ladder.
constexpr double kHistBounds[] = {0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                                  0.1,   0.25,   0.5,   1,    2.5,   5,
                                  10,    30,     60};
std::map<std::string, std::map<std::string, HistogramSnapshot>> g_histograms;

Level parse_level(const std::string& s) {
  std::string l = util::to_lower(s);
  if (l == "trace") return Level::Trace;
  if (l == "debug") return Level::Debug;
  if (l == "info") return Level::Info;
  if (l == "warn" || l == "warning") return Level::Warn;
  if (l == "error") return Level::Error;
  if (l == "off" || l == "none") return Level::Off;
  return Level::Info;
}

// EnvFilter directive grammar (reference main.rs:173 semantics): a comma-
// separated list where a bare level sets the global default and
// `module=level` overrides one module. Unknown level words fall back to
// info rather than erroring — a typo'd filter must not kill the daemon.
void parse_directives(const std::string& spec) {
  for (const std::string& raw : util::split(spec, ',')) {
    std::string token = util::trim(raw);
    if (token.empty()) continue;
    size_t eq = token.find('=');
    if (eq == std::string::npos) {
      g_threshold = parse_level(token);
    } else {
      std::string module = util::trim(token.substr(0, eq));
      if (!module.empty()) g_module_levels[module] = parse_level(token.substr(eq + 1));
    }
  }
}

const char* level_name(Level l) {
  switch (l) {
    case Level::Trace: return "TRACE";
    case Level::Debug: return "DEBUG";
    case Level::Info: return "INFO";
    case Level::Warn: return "WARN";
    case Level::Error: return "ERROR";
    case Level::Off: break;  // threshold-only; nothing logs AT Off
  }
  return "?";
}

const char* level_color(Level l) {
  switch (l) {
    case Level::Trace: return "\x1b[90m";
    case Level::Debug: return "\x1b[36m";
    case Level::Info: return "\x1b[32m";
    case Level::Warn: return "\x1b[33m";
    case Level::Error: return "\x1b[31m";
    case Level::Off: break;
  }
  return "";
}

void ensure_init() {
  if (g_initialized) return;
  g_module_levels.clear();
  if (auto lv = util::env("TPU_PRUNER_LOG")) parse_directives(*lv);
  else if (auto lv2 = util::env("RUST_LOG")) parse_directives(*lv2);
  g_initialized = true;
}

Level threshold_for_locked(std::string_view module) {
  if (!module.empty()) {
    auto it = g_module_levels.find(module);
    if (it != g_module_levels.end()) return it->second;
  }
  return g_threshold;
}

}  // namespace

void init(Format format) {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_format = format;
  g_initialized = false;
  ensure_init();
}

Level threshold() {
  std::lock_guard<std::mutex> lock(g_mutex);
  ensure_init();
  return g_threshold;
}

Level threshold_for(std::string_view module) {
  std::lock_guard<std::mutex> lock(g_mutex);
  ensure_init();
  return threshold_for_locked(module);
}

void write(Level level, const std::string& msg) { write(level, std::string_view(), msg); }

void write(Level level, std::string_view module, const std::string& msg) {
  std::lock_guard<std::mutex> lock(g_mutex);
  ensure_init();
  if (level < threshold_for_locked(module)) return;
  std::string target = "tpu_pruner";
  if (!module.empty()) target += "::" + std::string(module);
  std::string ts = util::now_rfc3339_micro();
  uint64_t cycle = effective_cycle();
  switch (g_format) {
    case Format::Json: {
      json::Value v = json::Value::object();
      v.set("timestamp", json::Value(ts));
      v.set("level", json::Value(util::to_lower(level_name(level))));
      v.set("fields", json::Value(json::Object{{"message", json::Value(msg)}}));
      v.set("target", json::Value(target));
      if (cycle) v.set("cycle", json::Value(static_cast<int64_t>(cycle)));
      std::fprintf(stderr, "%s\n", v.dump().c_str());
      break;
    }
    case Format::Pretty:
      std::fprintf(stderr, "  %s%s\x1b[0m %s%s\n    \x1b[90mat %s %s\x1b[0m\n",
                   level_color(level), level_name(level), msg.c_str(),
                   cycle ? (" cycle=" + std::to_string(cycle)).c_str() : "",
                   target.c_str(), ts.c_str());
      break;
    case Format::Default:
      if (cycle) {
        std::fprintf(stderr, "%s %5s %s: %s cycle=%llu\n", ts.c_str(), level_name(level),
                     target.c_str(), msg.c_str(), static_cast<unsigned long long>(cycle));
      } else {
        std::fprintf(stderr, "%s %5s %s: %s\n", ts.c_str(), level_name(level), target.c_str(),
                     msg.c_str());
      }
      break;
  }
  std::fflush(stderr);
}

void set_cycle(uint64_t cycle) { g_cycle.store(cycle, std::memory_order_relaxed); }
void set_thread_cycle(uint64_t cycle) { t_cycle = cycle; }

void counter_add(const std::string& name, uint64_t delta) {
  std::lock_guard<std::mutex> lock(g_mutex);
  Counter& c = g_counters[name];
  c.value += delta;
  c.gauge = false;
}

void counter_set(const std::string& name, uint64_t value) {
  std::lock_guard<std::mutex> lock(g_mutex);
  Counter& c = g_counters[name];
  c.value = value;
  c.gauge = true;
}

std::map<std::string, Counter> counters_snapshot() {
  std::lock_guard<std::mutex> lock(g_mutex);
  return g_counters;
}

void counters_reset_for_test() {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_counters.clear();
}

void histogram_observe(const std::string& family, const std::string& phase, double value,
                       const std::string& exemplar_trace_id) {
  std::lock_guard<std::mutex> lock(g_mutex);
  HistogramSnapshot& h = g_histograms[family][phase];
  if (h.bounds.empty()) {
    h.bounds.assign(std::begin(kHistBounds), std::end(kHistBounds));
    h.buckets.assign(h.bounds.size() + 1, 0);
    h.exemplars.assign(h.bounds.size() + 1, {});
  }
  size_t idx = std::lower_bound(h.bounds.begin(), h.bounds.end(), value) - h.bounds.begin();
  ++h.buckets[idx];
  if (!exemplar_trace_id.empty()) {
    h.exemplars[idx] = {exemplar_trace_id, value, util::now_unix(), true};
  }
  h.sum += value;
  ++h.count;
}

std::map<std::string, std::map<std::string, HistogramSnapshot>> histograms_snapshot() {
  std::lock_guard<std::mutex> lock(g_mutex);
  return g_histograms;
}

void histograms_reset_for_test() {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_histograms.clear();
}

}  // namespace tpupruner::log
