#include "tpupruner/timerwheel.hpp"

#include <algorithm>
#include <climits>

namespace tpupruner::timerwheel {

Wheel::Wheel(int64_t origin_ms) : now_ms_(origin_ms) {
  slots_.resize(kLevels);
  for (auto& level : slots_) level.resize(kSlots);
}

void Wheel::place(const std::string& key, int64_t due_ms) {
  // Distance in level-0 ticks decides the level: each level l covers
  // kSlots^(l+1) ticks. Past-due entries park in the current level-0
  // slot so the next advance() collects them.
  int64_t delta = due_ms > now_ms_ ? due_ms - now_ms_ : 0;
  int64_t ticks = delta / kTickMs;
  int level = 0;
  int64_t span = kSlots;  // ticks covered by level 0
  while (level < kLevels - 1 && ticks >= span) {
    ++level;
    span *= kSlots;
  }
  // Slot within the level: absolute tick index scaled to the level's
  // granularity, modulo the ring.
  int64_t level_tick = kTickMs;
  for (int l = 0; l < level; ++l) level_tick *= kSlots;
  int slot = static_cast<int>((due_ms / level_tick) % kSlots);
  slots_[level][slot].push_back(key);
  entries_[key] = Entry{due_ms, level, slot};
}

void Wheel::schedule(const std::string& key, int64_t due_ms) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(key);
  if (it != entries_.end()) {
    auto& parked = slots_[it->second.level][it->second.slot];
    parked.erase(std::remove(parked.begin(), parked.end(), key), parked.end());
    entries_.erase(it);
  }
  place(key, due_ms);
  ++scheduled_total_;
}

bool Wheel::cancel(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(key);
  if (it == entries_.end()) return false;
  auto& parked = slots_[it->second.level][it->second.slot];
  parked.erase(std::remove(parked.begin(), parked.end(), key), parked.end());
  entries_.erase(it);
  ++cancelled_total_;
  return true;
}

std::vector<std::string> Wheel::advance(int64_t now_ms) {
  std::lock_guard<std::mutex> lock(mu_);
  if (now_ms < now_ms_) now_ms = now_ms_;
  std::vector<std::pair<int64_t, std::string>> fired;
  // Tick walk with cascade — the O(1)-amortized common case. A clock
  // jump wider than a few level-0 laps (first advance after construction,
  // injected test clocks) skips the walk; the due-sweep below fires
  // whatever the skipped cascades would have, with identical ordering.
  if (now_ms - now_ms_ <= kTickMs * kSlots * 4) {
    while (now_ms_ < now_ms) {
      int64_t step = std::min<int64_t>(kTickMs, now_ms - now_ms_);
      int slot0 = static_cast<int>((now_ms_ / kTickMs) % kSlots);
      // Collect the current level-0 slot before moving off it.
      auto due_here = std::move(slots_[0][slot0]);
      slots_[0][slot0].clear();
      for (auto& key : due_here) {
        auto it = entries_.find(key);
        if (it == entries_.end()) continue;
        if (it->second.due_ms <= now_ms) {
          fired.emplace_back(it->second.due_ms, key);
          entries_.erase(it);
          ++fired_total_;
        } else {
          // Same ring slot, later lap: re-park for a future pass.
          slots_[0][slot0].push_back(key);
        }
      }
      now_ms_ += step;
      // Lap boundary on level l → cascade the matching slot of level
      // l+1 down: its entries re-place against the advanced clock,
      // landing in finer levels (or firing via the sweep below).
      int64_t level_tick = kTickMs;
      for (int l = 0; l + 1 < kLevels; ++l) {
        level_tick *= kSlots;
        if (now_ms_ % level_tick != 0) break;
        int slot = static_cast<int>((now_ms_ / level_tick) % kSlots);
        auto cascading = std::move(slots_[l + 1][slot]);
        slots_[l + 1][slot].clear();
        for (auto& key : cascading) {
          auto it = entries_.find(key);
          if (it == entries_.end()) continue;
          int64_t due = it->second.due_ms;
          entries_.erase(it);
          place(key, due);
          ++cascades_total_;
        }
      }
    }
  } else {
    now_ms_ = now_ms;
  }
  // Sweep: anything armed at/before now fires even if its slot was
  // never walked (huge jumps, schedule-in-the-past).
  for (auto it = entries_.begin(); it != entries_.end();) {
    if (it->second.due_ms <= now_ms) {
      auto& parked = slots_[it->second.level][it->second.slot];
      parked.erase(std::remove(parked.begin(), parked.end(), it->first),
                   parked.end());
      fired.emplace_back(it->second.due_ms, it->first);
      ++fired_total_;
      it = entries_.erase(it);
    } else {
      ++it;
    }
  }
  std::sort(fired.begin(), fired.end());
  std::vector<std::string> out;
  out.reserve(fired.size());
  for (auto& [due, key] : fired) out.push_back(std::move(key));
  return out;
}

int64_t Wheel::next_due() const {
  std::lock_guard<std::mutex> lock(mu_);
  int64_t best = -1;
  for (const auto& [key, e] : entries_) {
    if (best < 0 || e.due_ms < best) best = e.due_ms;
  }
  return best;
}

size_t Wheel::size() const {
  std::lock_guard<std::mutex> lock(mu_);
  return entries_.size();
}

json::Value Wheel::stats_json() const {
  std::lock_guard<std::mutex> lock(mu_);
  json::Value v = json::Value::object();
  v.set("now_ms", json::Value(now_ms_));
  v.set("entries", json::Value(static_cast<int64_t>(entries_.size())));
  v.set("levels", json::Value(static_cast<int64_t>(kLevels)));
  v.set("slots_per_level", json::Value(static_cast<int64_t>(kSlots)));
  v.set("tick_ms", json::Value(kTickMs));
  json::Value per_level = json::Value::array();
  for (int l = 0; l < kLevels; ++l) {
    int64_t occupied = 0;
    for (const auto& slot : slots_[l]) occupied += static_cast<int64_t>(slot.size());
    per_level.push_back(json::Value(occupied));
  }
  v.set("entries_per_level", std::move(per_level));
  int64_t best = -1;
  for (const auto& [key, e] : entries_) {
    if (best < 0 || e.due_ms < best) best = e.due_ms;
  }
  v.set("next_due_ms", json::Value(best));
  v.set("scheduled_total", json::Value(static_cast<int64_t>(scheduled_total_)));
  v.set("fired_total", json::Value(static_cast<int64_t>(fired_total_)));
  v.set("cancelled_total", json::Value(static_cast<int64_t>(cancelled_total_)));
  v.set("cascades_total", json::Value(static_cast<int64_t>(cascades_total_)));
  return v;
}

TokenBucket::TokenBucket(int64_t capacity, int64_t window_ms)
    : capacity_(capacity), window_ms_(window_ms < 1 ? 1 : window_ms) {}

void TokenBucket::expire(int64_t now_ms) const {
  auto first_live = std::lower_bound(grants_.begin(), grants_.end(),
                                     now_ms - window_ms_ + 1);
  grants_.erase(grants_.begin(), first_live);
}

bool TokenBucket::try_acquire(int64_t now_ms) {
  std::lock_guard<std::mutex> lock(mu_);
  if (capacity_ <= 0) {  // unlimited, but still counted for stats
    ++granted_total_;
    return true;
  }
  expire(now_ms);
  if (static_cast<int64_t>(grants_.size()) >= capacity_) {
    ++denied_total_;
    return false;
  }
  grants_.push_back(now_ms);
  ++granted_total_;
  return true;
}

int64_t TokenBucket::available(int64_t now_ms) const {
  std::lock_guard<std::mutex> lock(mu_);
  if (capacity_ <= 0) return INT64_MAX;
  expire(now_ms);
  return capacity_ - static_cast<int64_t>(grants_.size());
}

json::Value TokenBucket::stats_json() const {
  std::lock_guard<std::mutex> lock(mu_);
  json::Value v = json::Value::object();
  v.set("capacity", json::Value(capacity_));
  v.set("window_ms", json::Value(window_ms_));
  v.set("in_window", json::Value(static_cast<int64_t>(grants_.size())));
  v.set("granted_total", json::Value(static_cast<int64_t>(granted_total_)));
  v.set("denied_total", json::Value(static_cast<int64_t>(denied_total_)));
  return v;
}

}  // namespace tpupruner::timerwheel
