#include "tpupruner/proto.hpp"

#include <algorithm>
#include <cstdio>
#include <ctime>
#include <stdexcept>

#include "tpupruner/compact.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::proto {

using json::ParseError;
using json::Value;

// ── wire mode ───────────────────────────────────────────────────────────

namespace {

std::atomic<int> g_mode{-1};  // -1 = not yet initialized from the env
std::atomic<bool> g_k8s_refused{false};
std::atomic<bool> g_prom_refused{false};

WireMode env_mode() {
  if (auto v = util::env("TPU_PRUNER_WIRE")) {
    try {
      return wire_mode_from_string(*v);
    } catch (const std::exception&) {
      // A typo'd env var must not silently change the wire format.
      return WireMode::Json;
    }
  }
  return WireMode::Json;
}

}  // namespace

WireMode wire_mode_from_string(const std::string& s) {
  if (s == "json") return WireMode::Json;
  if (s == "proto") return WireMode::Proto;
  if (s == "auto") return WireMode::Auto;
  throw std::runtime_error("proto: unknown wire mode '" + s + "' (json|proto|auto)");
}

const char* wire_mode_name(WireMode m) {
  switch (m) {
    case WireMode::Json: return "json";
    case WireMode::Proto: return "proto";
    case WireMode::Auto: return "auto";
  }
  return "?";
}

WireMode wire_mode() {
  int m = g_mode.load(std::memory_order_relaxed);
  if (m < 0) {
    m = static_cast<int>(env_mode());
    g_mode.store(m, std::memory_order_relaxed);
  }
  return static_cast<WireMode>(m);
}

void set_wire_mode(WireMode m) { g_mode.store(static_cast<int>(m)); }

bool k8s_proto_wanted() {
  WireMode m = wire_mode();
  if (m == WireMode::Proto) return true;
  return m == WireMode::Auto && !g_k8s_refused.load(std::memory_order_relaxed);
}

bool prom_proto_wanted() {
  WireMode m = wire_mode();
  if (m == WireMode::Proto) return true;
  return m == WireMode::Auto && !g_prom_refused.load(std::memory_order_relaxed);
}

void note_k8s_fallback() {
  counters().negotiation_fallbacks.fetch_add(1, std::memory_order_relaxed);
  if (wire_mode() == WireMode::Auto) g_k8s_refused.store(true, std::memory_order_relaxed);
}

void note_prom_fallback() {
  counters().negotiation_fallbacks.fetch_add(1, std::memory_order_relaxed);
  if (wire_mode() == WireMode::Auto) g_prom_refused.store(true, std::memory_order_relaxed);
}

bool is_k8s_proto(std::string_view content_type) {
  return content_type.substr(0, kK8sProtoContentType.size()) == kK8sProtoContentType;
}

bool is_prom_proto(std::string_view content_type) {
  return content_type.substr(0, kPromProtoContentType.size()) == kPromProtoContentType;
}

// ── counters / metrics ──────────────────────────────────────────────────

WireCounters& counters() {
  static WireCounters c;
  return c;
}

std::vector<std::string> wire_metric_families() {
  return {"tpu_pruner_wire_bytes_decoded_total", "tpu_pruner_wire_negotiation_fallbacks_total",
          "tpu_pruner_wire_fused_decode_events_total", "tpu_pruner_wire_mode"};
}

std::string render_wire_metrics(bool openmetrics) {
  WireCounters& c = counters();
  std::string out;
  auto counter = [&](const std::string& name, const std::string& help,
                     const std::string& body) {
    out += "# HELP " + name + " " + help + "\n";
    // OpenMetrics reserves `counter` for suffix-transformed names; keep
    // the 0.0.4-compatible rendering the transport families use.
    out += "# TYPE " + name + " " + (openmetrics ? "unknown" : "counter") + "\n";
    out += body;
  };
  auto row = [](const char* ep, const char* ct, uint64_t v) {
    return std::string("tpu_pruner_wire_bytes_decoded_total{endpoint=\"") + ep +
           "\",content_type=\"" + ct + "\"} " + std::to_string(v) + "\n";
  };
  counter("tpu_pruner_wire_bytes_decoded_total",
          "Response bytes decoded at the hot call sites (informer LIST/watch, Prometheus "
          "instant queries), by endpoint and negotiated content type",
          row("k8s", "protobuf", c.k8s_proto_bytes.load()) +
              row("k8s", "json", c.k8s_json_bytes.load()) +
              row("prom", "protobuf", c.prom_proto_bytes.load()) +
              row("prom", "json", c.prom_json_bytes.load()));
  counter("tpu_pruner_wire_negotiation_fallbacks_total",
          "Requests that asked for protobuf and were answered with JSON (under --wire auto "
          "the endpoint is then remembered as JSON-only)",
          "tpu_pruner_wire_negotiation_fallbacks_total " +
              std::to_string(c.negotiation_fallbacks.load()) + "\n");
  counter("tpu_pruner_wire_fused_decode_events_total",
          "Watch events decoded through the fused single-pass path (decode -> fingerprint "
          "-> journal_touch -> store upsert, no intermediate tree)",
          "tpu_pruner_wire_fused_decode_events_total " + std::to_string(c.fused_events.load()) +
              "\n");
  out += "# HELP tpu_pruner_wire_mode Selected wire mode (--wire); the labeled mode is 1\n";
  out += "# TYPE tpu_pruner_wire_mode gauge\n";
  out += std::string("tpu_pruner_wire_mode{mode=\"") + wire_mode_name(wire_mode()) + "\"} 1\n";
  return out;
}

uint64_t fingerprint(std::string_view bytes) {
  uint64_t h = 0xcbf29ce484222325ull;
  for (unsigned char b : bytes) {
    h ^= b;
    h *= 0x100000001b3ull;
  }
  return h;
}

void reset_for_test() {
  WireCounters& c = counters();
  c.k8s_proto_bytes = 0;
  c.k8s_json_bytes = 0;
  c.prom_proto_bytes = 0;
  c.prom_json_bytes = 0;
  c.negotiation_fallbacks = 0;
  c.fused_events = 0;
  g_k8s_refused = false;
  g_prom_refused = false;
}

// ── protobuf wire primitives ────────────────────────────────────────────
//
// Only the three wire types the schema uses: varint (0), length-delimited
// (2), and (skipped) fixed64/fixed32 (1/5). Every read is bounds-checked
// against the slice; violations throw json::ParseError with the absolute
// byte offset — the same typed error the JSON decoders raise, pinned by
// the truncation/garbage sweep tests.

namespace {

struct Reader {
  std::string_view data;
  size_t pos = 0;    // position within `data`
  size_t base = 0;   // absolute offset of data[0] (error reporting)

  [[noreturn]] void fail(const std::string& msg) const {
    throw ParseError("proto: " + msg, base + pos);
  }
  bool done() const { return pos >= data.size(); }

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (true) {
      if (pos >= data.size()) fail("truncated varint");
      if (shift >= 64) fail("varint overflow");
      uint8_t b = static_cast<uint8_t>(data[pos++]);
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
  }

  // (field number, wire type)
  std::pair<uint32_t, uint32_t> tag() {
    uint64_t t = varint();
    uint32_t field = static_cast<uint32_t>(t >> 3);
    uint32_t wt = static_cast<uint32_t>(t & 7);
    if (field == 0) fail("field number 0");
    return {field, wt};
  }

  std::string_view bytes() {
    uint64_t len = varint();
    if (len > data.size() - pos) fail("length-delimited field overruns buffer");
    std::string_view out = data.substr(pos, len);
    pos += len;
    return out;
  }

  // Sub-reader over a length-delimited field, carrying absolute offsets.
  Reader message() {
    size_t at = pos;
    std::string_view b = bytes();
    return Reader{b, 0, base + at + (pos - at - b.size())};
  }

  void skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0: varint(); return;
      case 1:
        if (data.size() - pos < 8) fail("truncated fixed64");
        pos += 8;
        return;
      case 2: bytes(); return;
      case 5:
        if (data.size() - pos < 4) fail("truncated fixed32");
        pos += 4;
        return;
      default: fail("unsupported wire type " + std::to_string(wire_type));
    }
  }
};

constexpr char kMagic[4] = {0x6b, 0x38, 0x73, 0x00};  // "k8s\0"

// runtime.Unknown envelope past the magic: typeMeta=1 {apiVersion=1,
// kind=2}, raw=2. Returns the raw slice; offsets stay absolute.
struct Envelope {
  std::string api_version, kind;
  std::string_view raw;
  size_t raw_off = 0;  // absolute offset of raw within the original buffer
};

Envelope parse_unknown(std::string_view buf, size_t base) {
  if (buf.size() < 4 || std::string_view(buf.data(), 4) != std::string_view(kMagic, 4)) {
    throw ParseError("proto: missing k8s protobuf magic prefix", base);
  }
  Reader r{buf.substr(4), 0, base + 4};
  Envelope env;
  while (!r.done()) {
    auto [field, wt] = r.tag();
    if (field == 1 && wt == 2) {
      Reader tm = r.message();
      while (!tm.done()) {
        auto [f2, w2] = tm.tag();
        if (f2 == 1 && w2 == 2) env.api_version = std::string(tm.bytes());
        else if (f2 == 2 && w2 == 2) env.kind = std::string(tm.bytes());
        else tm.skip(w2);
      }
    } else if (field == 2 && wt == 2) {
      size_t at = r.pos;
      env.raw = r.bytes();
      env.raw_off = base + 4 + at + (r.pos - at - env.raw.size());
    } else {
      r.skip(wt);
    }
  }
  return env;
}

// Shallow ObjectMeta scan: name (1), namespace (3), resourceVersion (6).
// One pass, no allocation beyond the three strings — the fused path's
// store-key extraction.
void scan_meta(Reader meta, std::string* name, std::string* ns, std::string* rv) {
  while (!meta.done()) {
    auto [f, w] = meta.tag();
    if (f == 1 && w == 2) *name = std::string(meta.bytes());
    else if (f == 3 && w == 2) *ns = std::string(meta.bytes());
    else if (f == 6 && w == 2) *rv = std::string(meta.bytes());
    else meta.skip(w);
  }
}

// Object scan for the key fields: field 1 = ObjectMeta.
void scan_object(Reader obj, std::string* name, std::string* ns, std::string* rv) {
  while (!obj.done()) {
    auto [f, w] = obj.tag();
    if (f == 1 && w == 2) scan_meta(obj.message(), name, ns, rv);
    else obj.skip(w);
  }
}

std::string rfc3339(int64_t seconds) {
  std::time_t t = static_cast<std::time_t>(seconds);
  std::tm tm{};
  gmtime_r(&t, &tm);
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%04d-%02d-%02dT%02d:%02d:%02dZ", tm.tm_year + 1900,
                tm.tm_mon + 1, tm.tm_mday, tm.tm_hour, tm.tm_min, tm.tm_sec);
  return buf;
}

// meta/v1 Time: seconds=1 (varint, zigzag NOT used upstream — plain
// int64), nanos=2. Rendered in the compact RFC3339 form the fakes (and
// apiservers) emit in JSON.
Value time_to_value(Reader t) {
  int64_t seconds = 0;
  while (!t.done()) {
    auto [f, w] = t.tag();
    if (f == 1 && w == 0) seconds = static_cast<int64_t>(t.varint());
    else t.skip(w);
  }
  return Value(rfc3339(seconds));
}

// map<string,string> entry {key=1, value=2} folded into `obj`.
void map_entry_into(Reader e, Value& obj) {
  std::string key, value;
  while (!e.done()) {
    auto [f, w] = e.tag();
    if (f == 1 && w == 2) key = std::string(e.bytes());
    else if (f == 2 && w == 2) value = std::string(e.bytes());
    else e.skip(w);
  }
  obj.set(std::move(key), Value(std::move(value)));
}

// map<string,Quantity> entry {key=1, value=Quantity{string=1}}.
void quantity_entry_into(Reader e, Value& obj) {
  std::string key, value;
  while (!e.done()) {
    auto [f, w] = e.tag();
    if (f == 1 && w == 2) key = std::string(e.bytes());
    else if (f == 2 && w == 2) {
      Reader q = e.message();
      while (!q.done()) {
        auto [f2, w2] = q.tag();
        if (f2 == 1 && w2 == 2) value = std::string(q.bytes());
        else q.skip(w2);
      }
    } else e.skip(w);
  }
  obj.set(std::move(key), Value(std::move(value)));
}

// OwnerReference: kind=1, name=3, uid=4, apiVersion=5, controller=6,
// blockOwnerDeletion=7 (the real generated.proto numbering).
Value owner_ref_to_value(Reader o) {
  Value out = Value::object();
  while (!o.done()) {
    auto [f, w] = o.tag();
    if (f == 1 && w == 2) out.set("kind", Value(std::string(o.bytes())));
    else if (f == 3 && w == 2) out.set("name", Value(std::string(o.bytes())));
    else if (f == 4 && w == 2) out.set("uid", Value(std::string(o.bytes())));
    else if (f == 5 && w == 2) out.set("apiVersion", Value(std::string(o.bytes())));
    else if (f == 6 && w == 0) out.set("controller", Value(o.varint() != 0));
    else if (f == 7 && w == 0) out.set("blockOwnerDeletion", Value(o.varint() != 0));
    else o.skip(w);
  }
  return out;
}

Value object_meta_to_value(Reader m) {
  Value out = Value::object();
  Value labels, annotations, owners;
  while (!m.done()) {
    auto [f, w] = m.tag();
    if (f == 1 && w == 2) out.set("name", Value(std::string(m.bytes())));
    else if (f == 2 && w == 2) out.set("generateName", Value(std::string(m.bytes())));
    else if (f == 3 && w == 2) out.set("namespace", Value(std::string(m.bytes())));
    else if (f == 4 && w == 2) out.set("selfLink", Value(std::string(m.bytes())));
    else if (f == 5 && w == 2) out.set("uid", Value(std::string(m.bytes())));
    else if (f == 6 && w == 2) out.set("resourceVersion", Value(std::string(m.bytes())));
    else if (f == 8 && w == 2) out.set("creationTimestamp", time_to_value(m.message()));
    else if (f == 11 && w == 2) {
      if (!labels.is_object()) labels = Value::object();
      map_entry_into(m.message(), labels);
    } else if (f == 12 && w == 2) {
      if (!annotations.is_object()) annotations = Value::object();
      map_entry_into(m.message(), annotations);
    } else if (f == 13 && w == 2) {
      if (!owners.is_array()) owners = Value::array();
      owners.push_back(owner_ref_to_value(m.message()));
    } else m.skip(w);
  }
  if (labels.is_object()) out.set("labels", std::move(labels));
  if (annotations.is_object()) out.set("annotations", std::move(annotations));
  if (owners.is_array()) out.set("ownerReferences", std::move(owners));
  return out;
}

// ResourceRequirements: limits=1 map, requests=2 map.
Value resources_to_value(Reader r) {
  Value out = Value::object();
  Value limits, requests;
  while (!r.done()) {
    auto [f, w] = r.tag();
    if (f == 1 && w == 2) {
      if (!limits.is_object()) limits = Value::object();
      quantity_entry_into(r.message(), limits);
    } else if (f == 2 && w == 2) {
      if (!requests.is_object()) requests = Value::object();
      quantity_entry_into(r.message(), requests);
    } else r.skip(w);
  }
  if (limits.is_object()) out.set("limits", std::move(limits));
  if (requests.is_object()) out.set("requests", std::move(requests));
  return out;
}

// Container: name=1, image=2, resources=8.
Value container_to_value(Reader c) {
  Value out = Value::object();
  while (!c.done()) {
    auto [f, w] = c.tag();
    if (f == 1 && w == 2) out.set("name", Value(std::string(c.bytes())));
    else if (f == 2 && w == 2) out.set("image", Value(std::string(c.bytes())));
    else if (f == 8 && w == 2) out.set("resources", resources_to_value(c.message()));
    else c.skip(w);
  }
  return out;
}

// PodSpec: containers=2, nodeName=10.
Value pod_spec_to_value(Reader s) {
  Value out = Value::object();
  Value containers;
  while (!s.done()) {
    auto [f, w] = s.tag();
    if (f == 2 && w == 2) {
      if (!containers.is_array()) containers = Value::array();
      containers.push_back(container_to_value(s.message()));
    } else if (f == 10 && w == 2) {
      out.set("nodeName", Value(std::string(s.bytes())));
    } else s.skip(w);
  }
  if (containers.is_array()) out.set("containers", std::move(containers));
  return out;
}

// PodStatus: phase=1, message=3, reason=4.
Value pod_status_to_value(Reader s) {
  Value out = Value::object();
  while (!s.done()) {
    auto [f, w] = s.tag();
    if (f == 1 && w == 2) out.set("phase", Value(std::string(s.bytes())));
    else if (f == 3 && w == 2) out.set("message", Value(std::string(s.bytes())));
    else if (f == 4 && w == 2) out.set("reason", Value(std::string(s.bytes())));
    else s.skip(w);
  }
  return out;
}

// meta/v1 Status (ERROR watch events): status=2, message=3, reason=4,
// code=6.
void scan_status(Reader s, int64_t* code, std::string* message) {
  while (!s.done()) {
    auto [f, w] = s.tag();
    if (f == 3 && w == 2) *message = std::string(s.bytes());
    else if (f == 6 && w == 0) *code = static_cast<int64_t>(s.varint());
    else s.skip(w);
  }
}

}  // namespace

Value object_to_value(std::string_view bytes, const std::string& api_version,
                      const std::string& kind) {
  Value out = Value::object();
  if (!api_version.empty()) out.set("apiVersion", Value(api_version));
  if (!kind.empty()) out.set("kind", Value(kind));
  Reader r{bytes, 0, 0};
  while (!r.done()) {
    auto [f, w] = r.tag();
    if (f == 1 && w == 2) out.set("metadata", object_meta_to_value(r.message()));
    else if (f == 2 && w == 2) out.set("spec", pod_spec_to_value(r.message()));
    else if (f == 3 && w == 2) out.set("status", pod_status_to_value(r.message()));
    else r.skip(w);
  }
  return out;
}

ListPagePtr parse_list(std::string body) {
  auto page = std::make_shared<ListPage>();
  page->body = std::move(body);
  Envelope env = parse_unknown(page->body, 0);
  // Envelope TypeMeta names the LIST type ("v1"/"PodList"); items are the
  // element type. A list kind without the List suffix is malformed.
  if (env.kind.size() <= 4 || env.kind.substr(env.kind.size() - 4) != "List") {
    throw ParseError("proto: list envelope kind '" + env.kind + "' lacks List suffix", 0);
  }
  page->api_version = env.api_version;
  page->kind = env.kind.substr(0, env.kind.size() - 4);
  Reader list{env.raw, 0, env.raw_off};
  while (!list.done()) {
    auto [f, w] = list.tag();
    if (f == 1 && w == 2) {
      // ListMeta: selfLink=1, resourceVersion=2, continue=3.
      Reader meta = list.message();
      while (!meta.done()) {
        auto [f2, w2] = meta.tag();
        if (f2 == 2 && w2 == 2) page->resource_version = std::string(meta.bytes());
        else if (f2 == 3 && w2 == 2) page->continue_token = std::string(meta.bytes());
        else meta.skip(w2);
      }
    } else if (f == 2 && w == 2) {
      std::string_view item = list.bytes();
      ObjectRef ref;
      // Offsets are relative to page->body (env.raw views into it).
      ref.off = static_cast<size_t>(item.data() - page->body.data());
      ref.len = item.size();
      std::string rv_unused;
      scan_object(Reader{item, 0, ref.off}, &ref.name, &ref.ns, &rv_unused);
      ref.fp = fingerprint(item);
      page->items.push_back(std::move(ref));
    } else {
      list.skip(w);
    }
  }
  return page;
}

WatchEventPtr parse_watch_event(std::string frame) {
  auto ev = std::make_shared<WatchEvent>();
  ev->body = std::move(frame);
  Envelope env = parse_unknown(ev->body, 0);
  // env.raw is the meta/v1 WatchEvent message: type=1, object=2
  // (RawExtension{raw=1} holding a nested Unknown-wrapped object).
  Reader we{env.raw, 0, env.raw_off};
  std::string_view raw_ext;
  size_t raw_ext_off = 0;
  while (!we.done()) {
    auto [f, w] = we.tag();
    if (f == 1 && w == 2) ev->type = std::string(we.bytes());
    else if (f == 2 && w == 2) {
      Reader re = we.message();
      while (!re.done()) {
        auto [f2, w2] = re.tag();
        if (f2 == 1 && w2 == 2) {
          size_t at = re.pos;
          raw_ext = re.bytes();
          raw_ext_off = re.base + at + (re.pos - at - raw_ext.size());
        } else re.skip(w2);
      }
    } else we.skip(w);
  }
  if (!raw_ext.empty()) {
    Envelope inner = parse_unknown(raw_ext, raw_ext_off);
    ev->api_version = inner.api_version;
    ev->kind = inner.kind;
    ev->has_object = true;
    ev->obj_off = static_cast<size_t>(inner.raw.data() - ev->body.data());
    ev->obj_len = inner.raw.size();
    std::string_view obj = inner.raw;
    if (ev->type == "ERROR") {
      scan_status(Reader{obj, 0, ev->obj_off}, &ev->error_code, &ev->error_message);
    } else {
      scan_object(Reader{obj, 0, ev->obj_off}, &ev->name, &ev->ns, &ev->resource_version);
      ev->fp = fingerprint(obj);
    }
  }
  return ev;
}

// ── Prometheus ──────────────────────────────────────────────────────────

PromVector parse_prom_vector(std::string_view body) {
  PromVector out;
  Reader r{body, 0, 0};
  while (!r.done()) {
    auto [f, w] = r.tag();
    if (f == 1 && w == 2) out.status = std::string(r.bytes());
    else if (f == 2 && w == 2) out.error_type = std::string(r.bytes());
    else if (f == 3 && w == 2) out.error = std::string(r.bytes());
    else if (f == 4 && w == 2) {
      Reader s = r.message();
      PromSeries series;
      while (!s.done()) {
        auto [f2, w2] = s.tag();
        if (f2 == 1 && w2 == 2) {
          Reader l = s.message();
          std::string name, value;
          while (!l.done()) {
            auto [f3, w3] = l.tag();
            if (f3 == 1 && w3 == 2) name = std::string(l.bytes());
            else if (f3 == 2 && w3 == 2) value = std::string(l.bytes());
            else l.skip(w3);
          }
          series.labels.emplace_back(std::move(name), std::move(value));
        } else if (f2 == 2 && w2 == 2) series.ts_text = std::string(s.bytes());
        else if (f2 == 3 && w2 == 2) series.value_text = std::string(s.bytes());
        else s.skip(w2);
      }
      out.result.push_back(std::move(series));
    } else r.skip(w);
  }
  if (out.status.empty()) {
    throw ParseError("proto: prometheus response carries no status field", body.size());
  }
  return out;
}

void python_json_escape(std::string& out, std::string_view s) {
  // Mirrors CPython's json.dumps default (ensure_ascii=True): the two-char
  // shortcuts, \uXXXX with lowercase hex for other control chars and ALL
  // non-ASCII, surrogate pairs for non-BMP code points.
  auto u16 = [&](unsigned cp) {
    char buf[8];
    std::snprintf(buf, sizeof(buf), "\\u%04x", cp & 0xFFFF);
    out += buf;
  };
  size_t i = 0;
  while (i < s.size()) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    if (c == '"') { out += "\\\""; ++i; }
    else if (c == '\\') { out += "\\\\"; ++i; }
    else if (c == '\n') { out += "\\n"; ++i; }
    else if (c == '\t') { out += "\\t"; ++i; }
    else if (c == '\r') { out += "\\r"; ++i; }
    else if (c == '\b') { out += "\\b"; ++i; }
    else if (c == '\f') { out += "\\f"; ++i; }
    else if (c < 0x20) { u16(c); ++i; }
    else if (c < 0x80) { out.push_back(static_cast<char>(c)); ++i; }
    else {
      // Decode one UTF-8 sequence; invalid bytes degrade to U+FFFD the
      // way a lenient re-encoder would (label values on this path are
      // produced by our own fakes, so this is a never-taken safety net).
      unsigned cp = 0xFFFD;
      size_t n = 1;
      if ((c & 0xE0) == 0xC0 && i + 1 < s.size()) {
        cp = (c & 0x1F) << 6 | (s[i + 1] & 0x3F);
        n = 2;
      } else if ((c & 0xF0) == 0xE0 && i + 2 < s.size()) {
        cp = (c & 0x0F) << 12 | (s[i + 1] & 0x3F) << 6 | (s[i + 2] & 0x3F);
        n = 3;
      } else if ((c & 0xF8) == 0xF0 && i + 3 < s.size()) {
        cp = (c & 0x07) << 18 | (s[i + 1] & 0x3F) << 12 | (s[i + 2] & 0x3F) << 6 |
             (s[i + 3] & 0x3F);
        n = 4;
      }
      if (cp >= 0x10000) {
        unsigned v = cp - 0x10000;
        u16(0xD800 + (v >> 10));
        u16(0xDC00 + (v & 0x3FF));
      } else {
        u16(cp);
      }
      i += n;
    }
  }
}

std::string prom_canonical_body(const PromVector& v) {
  // Byte-faithful reconstruction of Python's json.dumps with DEFAULT
  // separators (", " / ": ") over the dict shapes fake_prom (and a real
  // Prometheus) builds, in their construction order.
  std::string out;
  out.reserve(64 + v.result.size() * 160);
  if (v.status != "success") {
    out += "{\"status\": \"";
    python_json_escape(out, v.status);
    out += "\", \"errorType\": \"";
    python_json_escape(out, v.error_type);
    out += "\", \"error\": \"";
    python_json_escape(out, v.error);
    out += "\"}";
    return out;
  }
  out += "{\"status\": \"success\", \"data\": {\"resultType\": \"vector\", \"result\": [";
  bool first_series = true;
  for (const PromSeries& s : v.result) {
    if (!first_series) out += ", ";
    first_series = false;
    out += "{\"metric\": {";
    bool first_label = true;
    for (const auto& [name, value] : s.labels) {
      if (!first_label) out += ", ";
      first_label = false;
      out += '"';
      python_json_escape(out, name);
      out += "\": \"";
      python_json_escape(out, value);
      out += '"';
    }
    out += "}, \"value\": [";
    out += s.ts_text;
    out += ", \"";
    python_json_escape(out, s.value_text);
    out += "\"]}";
  }
  out += "]}}";
  return out;
}

}  // namespace tpupruner::proto

// ── compact::record_from_proto ──────────────────────────────────────────
//
// Lives here (not compact.cpp) so it can share the wire Reader and
// rfc3339 with the Value decoders above. The builder mirrors
// object_to_value FIELD-FOR-FIELD — same field numbers, same lazy
// sub-object creation, same last-wins scalar rule — so a record's
// to_value() is byte-identical to the lazy decode it replaces. The
// decode-parity corpus (test_compact.cpp + tests/test_compact_store.py)
// pins that equivalence.
namespace tpupruner::compact {

// Reaches the anonymous-namespace helpers of tpupruner::proto (same TU).
using namespace tpupruner::proto;

namespace {

Str record_time(PodRecord& r, Reader t) {
  int64_t seconds = 0;
  while (!t.done()) {
    auto [f, w] = t.tag();
    if (f == 1 && w == 0) seconds = static_cast<int64_t>(t.varint());
    else t.skip(w);
  }
  return r.append(rfc3339(seconds));
}

void record_map_entry(Reader e, std::vector<KV>& out) {
  std::string key, value;
  while (!e.done()) {
    auto [f, w] = e.tag();
    if (f == 1 && w == 2) key = std::string(e.bytes());
    else if (f == 2 && w == 2) value = std::string(e.bytes());
    else e.skip(w);
  }
  out.push_back(KV{interner().intern(key), interner().intern(value)});
}

void record_ann_entry(PodRecord& r, Reader e, std::vector<AnnKV>& out) {
  std::string key, value;
  while (!e.done()) {
    auto [f, w] = e.tag();
    if (f == 1 && w == 2) key = std::string(e.bytes());
    else if (f == 2 && w == 2) value = std::string(e.bytes());
    else e.skip(w);
  }
  out.push_back(AnnKV{interner().intern(key), r.append(value)});
}

void record_quantity_entry(Reader e, std::vector<KV>& out) {
  std::string key, value;
  while (!e.done()) {
    auto [f, w] = e.tag();
    if (f == 1 && w == 2) key = std::string(e.bytes());
    else if (f == 2 && w == 2) {
      Reader q = e.message();
      while (!q.done()) {
        auto [f2, w2] = q.tag();
        if (f2 == 1 && w2 == 2) value = std::string(q.bytes());
        else q.skip(w2);
      }
    } else e.skip(w);
  }
  out.push_back(KV{interner().intern(key), interner().intern(value)});
}

OwnerRec record_owner(PodRecord& r, Reader o) {
  OwnerRec out;
  while (!o.done()) {
    auto [f, w] = o.tag();
    if (f == 1 && w == 2) {
      out.kind = interner().intern(o.bytes());
      out.present |= OwnerRec::kKind;
    } else if (f == 3 && w == 2) {
      out.name = r.append(o.bytes());
      out.present |= OwnerRec::kName;
    } else if (f == 4 && w == 2) {
      out.uid = r.append(o.bytes());
      out.present |= OwnerRec::kUid;
    } else if (f == 5 && w == 2) {
      out.api_version = interner().intern(o.bytes());
      out.present |= OwnerRec::kApiVersion;
    } else if (f == 6 && w == 0) {
      out.present |= OwnerRec::kController;
      if (o.varint() != 0) out.present |= OwnerRec::kControllerVal;
      else out.present &= static_cast<uint8_t>(~OwnerRec::kControllerVal);
    } else if (f == 7 && w == 0) {
      out.present |= OwnerRec::kBlockOwnerDeletion;
      if (o.varint() != 0) out.present |= OwnerRec::kBlockOwnerDeletionVal;
      else out.present &= static_cast<uint8_t>(~OwnerRec::kBlockOwnerDeletionVal);
    } else {
      o.skip(w);
    }
  }
  return out;
}

void record_meta(PodRecord& r, Reader m) {
  // A repeated metadata field replaces the whole sub-object (last wins),
  // exactly as object_to_value's out.set("metadata", ...) does.
  r.present &= ~(PodRecord::kName | PodRecord::kGenerateName | PodRecord::kNamespace |
                 PodRecord::kSelfLink | PodRecord::kUid | PodRecord::kResourceVersion |
                 PodRecord::kCreationTs | PodRecord::kLabels | PodRecord::kAnnotations |
                 PodRecord::kOwners);
  r.labels.clear();
  r.annotations.clear();
  r.owners.clear();
  r.present |= PodRecord::kMetadata;
  while (!m.done()) {
    auto [f, w] = m.tag();
    if (f == 1 && w == 2) {
      r.name = r.append(m.bytes());
      r.present |= PodRecord::kName;
    } else if (f == 2 && w == 2) {
      r.generate_name = r.append(m.bytes());
      r.present |= PodRecord::kGenerateName;
    } else if (f == 3 && w == 2) {
      r.ns = interner().intern(m.bytes());
      r.present |= PodRecord::kNamespace;
    } else if (f == 4 && w == 2) {
      r.self_link = r.append(m.bytes());
      r.present |= PodRecord::kSelfLink;
    } else if (f == 5 && w == 2) {
      r.uid = r.append(m.bytes());
      r.present |= PodRecord::kUid;
    } else if (f == 6 && w == 2) {
      r.resource_version = r.append(m.bytes());
      r.present |= PodRecord::kResourceVersion;
    } else if (f == 8 && w == 2) {
      r.creation_ts = record_time(r, m.message());
      r.present |= PodRecord::kCreationTs;
    } else if (f == 11 && w == 2) {
      record_map_entry(m.message(), r.labels);
      r.present |= PodRecord::kLabels;
    } else if (f == 12 && w == 2) {
      record_ann_entry(r, m.message(), r.annotations);
      r.present |= PodRecord::kAnnotations;
    } else if (f == 13 && w == 2) {
      r.owners.push_back(record_owner(r, m.message()));
      r.present |= PodRecord::kOwners;
    } else {
      m.skip(w);
    }
  }
}

ContainerRec record_container(PodRecord& r, Reader c) {
  ContainerRec out;
  while (!c.done()) {
    auto [f, w] = c.tag();
    if (f == 1 && w == 2) {
      out.name = r.append(c.bytes());
      out.present |= ContainerRec::kName;
    } else if (f == 2 && w == 2) {
      out.image = r.append(c.bytes());
      out.present |= ContainerRec::kImage;
    } else if (f == 8 && w == 2) {
      // Repeated resources replaces (container_to_value sets the key).
      out.present |= ContainerRec::kResources;
      out.present &= static_cast<uint8_t>(~(ContainerRec::kLimits | ContainerRec::kRequests));
      out.limits.clear();
      out.requests.clear();
      Reader res = c.message();
      while (!res.done()) {
        auto [f2, w2] = res.tag();
        if (f2 == 1 && w2 == 2) {
          record_quantity_entry(res.message(), out.limits);
          out.present |= ContainerRec::kLimits;
        } else if (f2 == 2 && w2 == 2) {
          record_quantity_entry(res.message(), out.requests);
          out.present |= ContainerRec::kRequests;
        } else {
          res.skip(w2);
        }
      }
    } else {
      c.skip(w);
    }
  }
  return out;
}

void record_spec(PodRecord& r, Reader s) {
  r.present &= ~(PodRecord::kContainers | PodRecord::kNodeName);
  r.containers.clear();
  r.present |= PodRecord::kSpec;
  while (!s.done()) {
    auto [f, w] = s.tag();
    if (f == 2 && w == 2) {
      r.containers.push_back(record_container(r, s.message()));
      r.present |= PodRecord::kContainers;
    } else if (f == 10 && w == 2) {
      r.node_name = interner().intern(s.bytes());
      r.present |= PodRecord::kNodeName;
    } else {
      s.skip(w);
    }
  }
}

void record_status(PodRecord& r, Reader s) {
  r.present &= ~(PodRecord::kPhase | PodRecord::kMessage | PodRecord::kReason);
  r.present |= PodRecord::kStatus;
  while (!s.done()) {
    auto [f, w] = s.tag();
    if (f == 1 && w == 2) {
      r.phase = r.append(s.bytes());
      r.present |= PodRecord::kPhase;
    } else if (f == 3 && w == 2) {
      r.message = r.append(s.bytes());
      r.present |= PodRecord::kMessage;
    } else if (f == 4 && w == 2) {
      r.reason = r.append(s.bytes());
      r.present |= PodRecord::kReason;
    } else {
      s.skip(w);
    }
  }
}

}  // namespace

PodRecord record_from_proto(std::string_view bytes, const std::string& api_version,
                            const std::string& kind) {
  PodRecord r;
  if (!api_version.empty()) {
    r.api_version = interner().intern(api_version);
    r.present |= PodRecord::kApiVersion;
  }
  if (!kind.empty()) {
    r.kind = interner().intern(kind);
    r.present |= PodRecord::kKind;
  }
  Reader rd{bytes, 0, 0};
  while (!rd.done()) {
    auto [f, w] = rd.tag();
    if (f == 1 && w == 2) record_meta(r, rd.message());
    else if (f == 2 && w == 2) record_spec(r, rd.message());
    else if (f == 3 && w == 2) record_status(r, rd.message());
    else rd.skip(w);
  }
  r.finish();
  return r;
}

}  // namespace tpupruner::compact
