// Minimal Prometheus /metrics exposition server (internal).
//
// The reference pushes its six operational counters over OTLP when built
// with the `otel` feature (main.rs:138-155, 194-271). Pull-based /metrics
// is the idiomatic GKE shape (PodMonitoring scrapes it), so the daemon
// serves the same counter names as a text exposition instead.
#pragma once

#include <atomic>
#include <functional>
#include <mutex>
#include <thread>

namespace tpupruner::metrics_http {

class Server {
 public:
  // Binds 0.0.0.0:port; throws std::runtime_error when the bind fails.
  explicit Server(int port);
  ~Server();
  int port() const { return port_; }

  // Liveness seam: when set, /healthz answers 503 while the probe returns
  // false. The daemon wires a cycle-staleness check here so a wedged
  // producer loop (stuck cycle, deadlocked consumer) fails the kubelet
  // probe — process death alone K8s already handles; hangs it cannot see.
  void set_health_probe(std::function<bool()> probe);

 private:
  void serve();
  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stop_{false};
  std::function<bool()> probe_;
  std::mutex probe_mutex_;
  std::thread thread_;
};

}  // namespace tpupruner::metrics_http
