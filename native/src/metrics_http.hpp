// Minimal Prometheus /metrics + debug HTTP server (internal).
//
// The reference pushes its six operational counters over OTLP when built
// with the `otel` feature (main.rs:138-155, 194-271). Pull-based /metrics
// is the idiomatic GKE shape (PodMonitoring scrapes it), so the daemon
// serves the counter names as a text exposition instead — now alongside
// phase-latency histograms (with OTLP trace-id exemplars under OpenMetrics
// content negotiation), a /readyz informer-sync probe distinct from the
// /healthz liveness stamp, and the /debug/decisions audit-trail endpoint.
#pragma once

#include <atomic>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace tpupruner::metrics_http {

class Server {
 public:
  // Binds 0.0.0.0:port; throws std::runtime_error when the bind fails.
  // The socket listens (so port() is final and concurrent binds lose)
  // but no request is ANSWERED until start().
  explicit Server(int port);
  ~Server();
  int port() const { return port_; }

  // Launch the accept loop and log the "serving /metrics on port" line.
  // Callers register every provider BEFORE start(): a request racing the
  // registration window would otherwise 404 — and a hub whose first
  // /debug/delta poll lands in that window demotes the member to
  // snapshot polling for good (it reads 404 as "unsupported").
  void start();

  // Liveness seam: when set, /healthz answers 503 while the probe returns
  // false. The daemon wires a cycle-staleness check here so a wedged
  // producer loop (stuck cycle, deadlocked consumer) fails the kubelet
  // probe — process death alone K8s already handles; hangs it cannot see.
  void set_health_probe(std::function<bool()> probe);

  // Readiness seam (/readyz): reflects informer sync state — a daemon
  // whose watch cache is mid-relist is alive (healthz 200) but should not
  // be Ready until lookups serve from the store again. Unset → always 200.
  void set_ready_probe(std::function<bool()> probe);

  // /debug/decisions provider: receives the raw query string ("pod=ns/x")
  // and returns the JSON body. Unset → 404.
  void set_decisions_provider(std::function<std::string(const std::string&)> provider);

  // /debug/workloads provider (the workload-ledger snapshot): receives the
  // raw query string ("ns=…&sort=reclaimed") and returns the JSON body.
  // Unset → 404.
  void set_workloads_provider(std::function<std::string(const std::string&)> provider);

  // /debug/cycles provider (the flight-recorder capsule ring): receives
  // the capsule id ("" = the index) and returns the JSON body — an empty
  // return means "no such capsule" (404). Unset → 404 for both routes.
  void set_cycles_provider(std::function<std::string(const std::string&)> provider);

  // /debug/traces provider (the action-provenance trace ring): receives
  // the trace id ("" = the index + SLO summary) and returns the JSON body
  // — an empty return means "no such trace" (404). Unset → 404 with a
  // hint that the ring exists under --trace on.
  void set_traces_provider(std::function<std::string(const std::string&)> provider);

  // /debug/signals provider (the signal-quality watchdog's latest
  // evidence assessment). Unset → 404.
  void set_signals_provider(std::function<std::string()> provider);

  // /debug/capacity provider (the capacity observatory's free-TPU
  // inventory). Unset → 404 with a hint that the surface exists under
  // --capacity on.
  void set_capacity_provider(std::function<std::string()> provider);

  // /debug/fleet/* provider (the federation hub's merged views): receives
  // the subpath ("workloads" | "signals" | "decisions" | "capacity" |
  // "slo" | "clusters") and
  // the raw query string, returns the JSON body — an empty return means
  // "no such view" (404). Unset → 404 with a hint that the routes are
  // served by `tpu-pruner hub`.
  void set_fleet_provider(
      std::function<std::string(const std::string&, const std::string&)> provider);

  // /debug/timers provider (the event engine's time plane: timer-wheel
  // occupancy + token-bucket gate windows). Unset → 404 with a hint that
  // the surface exists under --reconcile event.
  void set_timers_provider(std::function<std::string()> provider);

  // /debug/delta provider (the delta-federation change journal): receives
  // the raw query string ("since=…&gen=…&wait_ms=…") and an abort
  // predicate (true once the server is stopping) the provider must poll
  // while long-polling. Runs on the connection's own thread, so a held
  // request blocks nobody else. Unset → 404.
  void set_delta_provider(
      std::function<std::string(const std::string&, const std::function<bool()>&)>
          provider);

  // Extra /metrics families rendered outside the counter/histogram
  // registries (the ledger's bounded-cardinality workload series). The
  // provider returns ready-made exposition text (HELP/TYPE included);
  // the bool argument is the OpenMetrics negotiation.
  void set_extra_metrics_provider(std::function<std::string(bool)> provider);

 private:
  void serve();
  // One accepted connection: sequential HTTP/1.1 keep-alive requests until
  // the peer closes, an error, or server stop. Runs on its own thread so
  // a long-poll (/debug/delta?wait_ms=…) or a hub holding a persistent
  // connection never blocks the accept loop or other clients.
  void handle_connection(int fd);
  std::string render_exposition(bool openmetrics) const;

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stop_{false};
  std::function<bool()> probe_;
  std::function<bool()> ready_probe_;
  std::function<std::string(const std::string&)> decisions_provider_;
  std::function<std::string(const std::string&)> workloads_provider_;
  std::function<std::string(const std::string&)> cycles_provider_;
  std::function<std::string(const std::string&)> traces_provider_;
  std::function<std::string()> signals_provider_;
  std::function<std::string()> capacity_provider_;
  std::function<std::string()> timers_provider_;
  std::function<std::string(const std::string&, const std::string&)> fleet_provider_;
  std::function<std::string(const std::string&, const std::function<bool()>&)>
      delta_provider_;
  std::function<std::string(bool)> extra_metrics_provider_;
  mutable std::mutex probe_mutex_;
  std::thread thread_;
  // Connection threads: swept as they finish, joined at shutdown.
  struct Conn {
    std::thread thread;
    std::atomic<bool> done{false};
  };
  std::mutex conns_mutex_;
  std::vector<std::unique_ptr<Conn>> conns_;
};

}  // namespace tpupruner::metrics_http
