#include "tpupruner/leader.hpp"

#include <unistd.h>

#include <chrono>

#include "tpupruner/json.hpp"
#include "tpupruner/k8s.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::leader {

using json::Value;

namespace {

std::string lease_collection(const std::string& ns) {
  return "/apis/coordination.k8s.io/v1/namespaces/" + ns + "/leases";
}

// MicroTime per the Lease schema (RFC 3339 with 6 fractional digits).
std::string micro_time(int64_t unix_secs) {
  return util::format_rfc3339(unix_secs, 0, 6);
}

Value lease_spec(const std::string& holder, int64_t duration_s,
                 std::optional<int64_t> acquire_unix, int64_t renew_unix,
                 std::optional<int64_t> transitions) {
  Value spec = Value::object();
  spec.set("holderIdentity", Value(holder));
  spec.set("leaseDurationSeconds", Value(duration_s));
  if (acquire_unix) spec.set("acquireTime", Value(micro_time(*acquire_unix)));
  spec.set("renewTime", Value(micro_time(renew_unix)));
  if (transitions) spec.set("leaseTransitions", Value(*transitions));
  return spec;
}

}  // namespace

Elector::Elector(const k8s::Client& client, Options opts)
    : client_(client), opts_(std::move(opts)) {
  if (opts_.identity.empty()) {
    if (auto pn = util::env("POD_NAME")) {
      opts_.identity = *pn;
    } else {
      char host[256] = "tpu-pruner";
      ::gethostname(host, sizeof(host) - 1);
      opts_.identity = std::string(host) + "-" + std::to_string(::getpid());
    }
  }
  lease_path_ = lease_collection(opts_.lease_ns) + "/" + opts_.lease_name;

  thread_ = std::thread([this] {
    // First attempt immediately, then every leaseDuration/3 (the client-go
    // renew cadence), polling stop_ in short chunks so shutdown is fast.
    while (!stop_.load()) {
      bool was = is_leader_.load();
      bool now = false;
      try {
        now = try_acquire_or_renew();
      } catch (const std::exception& e) {
        log::warn("leader", std::string("leader election attempt failed: ") + e.what());
        // Transport errors: a leader keeps leading only until the lease
        // would have expired anyway — past that, a standby has taken over,
        // so self-demote to bound dual-leadership to one lease window. A
        // candidate just retries.
        auto deadline = std::chrono::seconds(opts_.lease_duration_s);
        now = was && last_renew_ok_ &&
              std::chrono::steady_clock::now() - *last_renew_ok_ < deadline;
        if (was && !now) {
          log::warn("leader", "leader election: could not renew within the lease duration, "
                    "self-demoting");
        }
      }
      if (now != was) {
        log::info("leader", now ? "leader election: acquired lease " + opts_.lease_ns + "/" +
                            opts_.lease_name + " as " + opts_.identity
                      : "leader election: lost lease " + opts_.lease_ns + "/" +
                            opts_.lease_name);
      }
      is_leader_.store(now);
      int64_t wait_ms = opts_.lease_duration_s * 1000 / 3;
      for (int64_t waited = 0; waited < wait_ms && !stop_.load(); waited += 100) {
        std::this_thread::sleep_for(std::chrono::milliseconds(100));
      }
    }
  });
}

Elector::~Elector() {
  stop_.store(true);
  if (thread_.joinable()) thread_.join();
  if (is_leader_.load()) release();
}

bool Elector::try_acquire_or_renew() {
  int64_t now = util::now_unix();
  auto mono_now = std::chrono::steady_clock::now();
  std::optional<Value> lease = client_.get_opt(lease_path_, /*retry_throttle=*/false);

  if (!lease) {
    // No lease yet: create it. A racing candidate's create wins with 201;
    // the loser's POST 409s (AlreadyExists) → return false, retried next
    // tick. Non-409 failures throw into the renew loop's grace window.
    Value body = Value::object();
    body.set("apiVersion", Value("coordination.k8s.io/v1"));
    body.set("kind", Value("Lease"));
    Value meta = Value::object();
    meta.set("name", Value(opts_.lease_name));
    meta.set("namespace", Value(opts_.lease_ns));
    body.set("metadata", std::move(meta));
    body.set("spec", lease_spec(opts_.identity, opts_.lease_duration_s, now, now, 1));
    try {
      client_.post(lease_collection(opts_.lease_ns), body, /*retry_throttle=*/false);
      last_renew_ok_ = mono_now;
      return true;
    } catch (const k8s::ApiError& e) {
      if (e.status == 409) return false;  // lost the creation race
      throw;  // transport/server failure → renew loop's grace window
    }
  }

  std::string rv;
  if (const Value* v = lease->at_path("metadata.resourceVersion"); v && v->is_string()) {
    rv = v->as_string();
  }
  std::string holder;
  if (const Value* h = lease->at_path("spec.holderIdentity"); h && h->is_string()) {
    holder = h->as_string();
  }
  int64_t duration = opts_.lease_duration_s;
  if (const Value* d = lease->at_path("spec.leaseDurationSeconds"); d && d->is_number()) {
    duration = d->as_int();
  }
  std::string renew_str;
  if (const Value* r = lease->at_path("spec.renewTime"); r && r->is_string()) {
    renew_str = r->as_string();
  }

  // Local-observation expiry (client-go semantics): the holder's renewTime
  // is another machine's wall clock, so never compare it against ours —
  // skew > leaseDuration would let a standby steal a live lease. Instead,
  // the record (holder, renewTime) must stay UNCHANGED for > leaseDuration
  // on our monotonic clock before it counts as expired.
  std::string record = holder + "\x1f" + renew_str;
  if (record != observed_record_) {
    observed_record_ = record;
    observed_at_ = mono_now;
  }

  if (holder == opts_.identity) {
    // Renew. No precondition needed: only the holder writes renewTime
    // while the lease is live, and a takeover after expiry bumps
    // resourceVersion, which would make a stale holder's next renew a
    // plain overwrite — so guard with the precondition anyway.
    Value patch = Value::object();
    Value meta = Value::object();
    meta.set("resourceVersion", Value(rv));
    patch.set("metadata", std::move(meta));
    patch.set("spec", lease_spec(opts_.identity, opts_.lease_duration_s, std::nullopt, now,
                                 std::nullopt));
    try {
      client_.patch_merge(lease_path_, patch, /*retry_throttle=*/false);
      last_renew_ok_ = mono_now;
      return true;
    } catch (const k8s::ApiError& e) {
      // Only a genuine CAS conflict proves someone took over; a 5xx or
      // timeout mid-renew must flow to the loop's leaseDuration grace
      // window instead of demoting the leader on one API blip.
      if (e.status == 409) return false;
      throw;
    }
  }

  bool expired = !holder.empty() &&
                 mono_now - observed_at_ > std::chrono::seconds(duration);
  if (holder.empty() || renew_str.empty() || expired) {
    // Takeover, CAS-guarded by resourceVersion so exactly one racing
    // candidate wins (the API server 409s the rest).
    int64_t transitions = 0;
    if (const Value* t = lease->at_path("spec.leaseTransitions"); t && t->is_number()) {
      transitions = t->as_int();
    }
    Value patch = Value::object();
    Value meta = Value::object();
    meta.set("resourceVersion", Value(rv));
    patch.set("metadata", std::move(meta));
    patch.set("spec", lease_spec(opts_.identity, opts_.lease_duration_s, now, now,
                                 transitions + 1));
    try {
      client_.patch_merge(lease_path_, patch, /*retry_throttle=*/false);
      last_renew_ok_ = mono_now;
      return true;
    } catch (const k8s::ApiError& e) {
      if (e.status == 409) return false;  // lost the takeover race
      throw;
    }
  }
  return false;  // live lease held by someone else
}

void Elector::release() {
  // Best-effort: clearing holderIdentity lets a standby take over at its
  // next tick instead of waiting out the lease (client-go releaseOnCancel).
  // Guarded: re-read the lease and only release if WE still hold it, with
  // the resourceVersion precondition — a stale ex-leader (demoted during a
  // partition) must not clear the current leader's claim.
  try {
    std::optional<Value> lease = client_.get_opt(lease_path_, /*retry_throttle=*/false);
    if (!lease) return;
    const Value* h = lease->at_path("spec.holderIdentity");
    if (!h || !h->is_string() || h->as_string() != opts_.identity) return;
    const Value* rv = lease->at_path("metadata.resourceVersion");
    Value patch = Value::object();
    if (rv && rv->is_string()) {
      Value meta = Value::object();
      meta.set("resourceVersion", Value(rv->as_string()));
      patch.set("metadata", std::move(meta));
    }
    Value spec = Value::object();
    spec.set("holderIdentity", Value(""));
    patch.set("spec", std::move(spec));
    client_.patch_merge(lease_path_, patch, /*retry_throttle=*/false);
  } catch (const std::exception& e) {
    log::debug("leader", std::string("lease release failed (will expire instead): ") + e.what());
  }
}

}  // namespace tpupruner::leader
