// OTLP/HTTP metrics exporter (internal).
//
// Reference analog: the optional `otel` cargo feature (gpu-pruner
// main.rs:138-155, 194-271) pushing the six tracing-field counters over
// OTLP gRPC, configured purely by OTEL_* env vars (README.md:79-98).
// Here: the same counters pushed as OTLP/HTTP JSON (the spec's JSON
// encoding of ExportMetricsServiceRequest) on a periodic background
// thread. Enabled by OTEL_EXPORTER_OTLP_ENDPOINT (or the CLI flag);
// interval from OTEL_METRIC_EXPORT_INTERVAL (ms, default 15000).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <string>
#include <thread>

namespace tpupruner::otlp {

class Exporter {
 public:
  // `endpoint` is the OTLP base (e.g. http://collector:4318); metrics go
  // to <endpoint>/v1/metrics.
  Exporter(std::string endpoint, int interval_ms);
  ~Exporter();  // final flush, then stop

  // One export now (also used for the shutdown flush). Returns false and
  // logs on failure; the daemon never fails because telemetry did.
  bool export_once();

 private:
  void loop();
  std::string endpoint_;
  int interval_ms_;
  std::atomic<bool> stop_{false};
  std::mutex mutex_;
  std::condition_variable cv_;
  std::thread thread_;
  int64_t start_unix_nanos_;
};

}  // namespace tpupruner::otlp
