// OTLP/HTTP metrics + trace exporter (internal).
//
// Reference analog: the optional `otel` cargo feature (gpu-pruner
// main.rs:138-155, 194-271) pushing OTLP gRPC span and metric exports —
// the six tracing-field counters plus the #[tracing::instrument] spans on
// the pipeline and actuators (main.rs:390; lib.rs:338, 388, 436, 516, 528,
// 552) — configured purely by OTEL_* env vars (README.md:79-98).
// Here: the same counters and spans pushed as OTLP/HTTP JSON (the spec's
// JSON encoding of ExportMetricsServiceRequest / ExportTraceServiceRequest)
// on a periodic background thread. Enabled by OTEL_EXPORTER_OTLP_ENDPOINT
// (or the CLI flag); interval from OTEL_METRIC_EXPORT_INTERVAL (ms,
// default 15000).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace tpupruner::otlp {

// ── Trace spans ──────────────────────────────────────────────────────────
//
// Recording is process-global and off by default; the Exporter switches it
// on for its lifetime, so instrumented code pays one relaxed atomic load
// when telemetry is disabled. Finished spans land in a bounded buffer
// (drops counted) drained by each export.

struct SpanContext {
  std::string trace_id;  // 32 hex chars
  std::string span_id;   // 16 hex chars
};

// Timestamped point event inside a span (OTLP Span.events) — e.g. one
// retry/backoff tick inside an actuation span.
struct SpanEvent {
  int64_t time_nanos = 0;
  std::string name;
  std::vector<std::pair<std::string, std::string>> str_attrs;
  std::vector<std::pair<std::string, int64_t>> int_attrs;
};

struct FinishedSpan {
  std::string name;
  std::string trace_id, span_id, parent_span_id;
  int64_t start_nanos = 0, end_nanos = 0;
  std::vector<std::pair<std::string, std::string>> str_attrs;
  std::vector<std::pair<std::string, int64_t>> int_attrs;
  std::vector<SpanEvent> events;
  bool error = false;
  std::string error_message;
};

// RAII span: starts at construction, finishes (and is buffered) at
// destruction. A default-constructed parent starts a new trace.
class Span {
 public:
  explicit Span(std::string name, const SpanContext* parent = nullptr);
  ~Span();
  Span(const Span&) = delete;
  Span& operator=(const Span&) = delete;

  void attr(std::string key, std::string value);
  void attr(std::string key, int64_t value);
  void set_error(std::string message);
  const SpanContext& context() const { return ctx_; }

 private:
  bool enabled_;
  FinishedSpan rec_;
  SpanContext ctx_;
};

bool recording();                        // true while an Exporter is live
void set_recording_for_test(bool on);    // test hook
std::vector<FinishedSpan> drain_spans_for_test();

// Buffer an externally-assembled finished span (the trace engine seals
// whole span trees at once, with ids and timestamps of its own). No-op
// unless recording — same gate as the RAII Span.
void buffer_finished_span(FinishedSpan&& span);

// W3C trace-context header value ("00-<trace>-<span>-01") for a span
// context, or "" when the context is empty (recording off) — callers hand
// it to http::Client::set_default_traceparent / set_thread_traceparent so
// outbound Prometheus and K8s API requests correlate with the OTLP trace.
std::string traceparent(const SpanContext& ctx);

class Exporter {
 public:
  // `endpoint` is the OTLP base (e.g. http://collector:4318); metrics go
  // to <endpoint>/v1/metrics. Signal-specific OTEL env vars are honored
  // per the spec (and the reference's documented config, README.md:79-98):
  // OTEL_EXPORTER_OTLP_{METRICS,TRACES}_ENDPOINT override the full URL for
  // that signal (used verbatim, no /v1/* appended), and
  // OTEL_{METRICS,TRACES}_EXPORTER=none disables the signal.
  Exporter(std::string endpoint, int interval_ms);
  ~Exporter();  // final flush, then stop

  // Single point of truth for OTLP activation: resolves the CLI flag plus
  // the OTEL_* env shape (base endpoint, signal-specific endpoints,
  // exporter=none switches, export interval) and returns nullptr when no
  // signal would be active. Set-but-empty env vars count as unset, here
  // and in the per-signal resolution alike.
  static std::unique_ptr<Exporter> from_config(const std::string& cli_endpoint);

  // One export now (also used for the shutdown flush). Returns false and
  // logs on failure; the daemon never fails because telemetry did.
  bool export_once();

 private:
  void loop();
  bool export_metrics(int64_t now_nanos);
  bool export_traces();
  bool post(const std::string& url, const std::string& body_json,
            const std::vector<std::pair<std::string, std::string>>& headers,
            const std::string& ca_file);
  bool grpc_post(const std::string& url, const char* path, const std::string& proto,
                 const std::vector<std::pair<std::string, std::string>>& headers,
                 const std::string& ca_file);
  std::string metrics_url_, traces_url_;  // empty = signal disabled
  bool metrics_grpc_ = false, traces_grpc_ = false;  // OTLP/gRPC transport
  // OTEL_EXPORTER_OTLP[_SIGNAL]_HEADERS: auth/routing headers for managed
  // collectors, applied on both transports.
  std::vector<std::pair<std::string, std::string>> metrics_headers_, traces_headers_;
  // CA bundle for TLS endpoints, per signal (OTEL spec
  // OTEL_EXPORTER_OTLP[_SIGNAL]_CERTIFICATE); empty = system trust store.
  std::string metrics_ca_, traces_ca_;
  int interval_ms_;
  std::atomic<bool> stop_{false};
  std::mutex mutex_;
  std::condition_variable cv_;
  std::thread thread_;
  int64_t start_unix_nanos_;
};

}  // namespace tpupruner::otlp
