#pragma once

#include <string>

namespace tpupruner::querytest {

// Run one ad-hoc query, print a label table, write a CSV. Returns exit code.
int run(const std::string& promql, const std::string& url,
        const std::string& csv_path = "output.csv");

}  // namespace tpupruner::querytest
