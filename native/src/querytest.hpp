#pragma once

#include <string>

namespace tpupruner::querytest {

// Run one ad-hoc query, print a label table, write a CSV. Returns exit code.
int run(const std::string& promql, const std::string& url,
        const std::string& csv_path = "output.csv");

// `querytest --wire proto|json <promql> <url>`: fetch ONE raw instant-query
// response in the requested content type (proto = the same
// application/x-protobuf negotiation the daemon's --wire proto uses) and
// hex-dump it with the negotiated Content-Type — the debugging tool for
// wire negotiation against real endpoints. Returns exit code.
int run_wire(const std::string& promql, const std::string& url, const std::string& wire);

}  // namespace tpupruner::querytest
