// tpu-pruner daemon entry point (reference analog: gpu-pruner/src/main.rs:273).
// Grows subcommands: default daemon/single-shot run, plus `querytest`
// (reference: gpu-pruner/src/bin/querytest.rs).
#include <cstdio>

int main(int argc, char** argv) {
  (void)argc;
  (void)argv;
  std::fprintf(stderr, "tpu-pruner: daemon not wired yet (scaffolding build)\n");
  return 2;
}
