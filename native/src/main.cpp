// tpu-pruner entry point.
//
// Reference analog: gpu-pruner/src/main.rs:273-375 (main) plus the separate
// querytest binary (src/bin/querytest.rs) — folded in as a subcommand so
// the container image stays single-binary.
#include <csignal>
#include <cstdio>
#include <cstring>
#include <exception>

#include "querytest.hpp"
#include "tpupruner/cli.hpp"
#include "tpupruner/daemon.hpp"
#include "tpupruner/fleet.hpp"
#include "tpupruner/gym.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/query.hpp"

int main(int argc, char** argv) {
  using namespace tpupruner;

  // A reset pooled connection (GMP frontends close idle HTTPS links; the
  // transport deliberately retries stale keep-alive sockets) must surface
  // as a write error, not a process-killing SIGPIPE — OpenSSL writes via
  // SSL_set_fd bypass MSG_NOSIGNAL. Process-wide, covers every subcommand.
  std::signal(SIGPIPE, SIG_IGN);

  if (argc >= 2 && (std::strcmp(argv[1], "--version") == 0 ||
                    std::strcmp(argv[1], "-V") == 0 ||
                    std::strcmp(argv[1], "version") == 0)) {
    std::fprintf(stdout, "tpu-pruner %s (%s)\n", TP_VERSION, TP_GIT_REV);
    return 0;
  }

  if (argc >= 2 && std::strcmp(argv[1], "hub") == 0) {
    // Fleet federation hub: poll N member daemons, serve the merged view
    // (per-cluster ledgers, per-cluster-minimum coverage, UNREACHABLE
    // rows) at /debug/fleet/* + tpu_pruner_fleet_* families.
    try {
      return hub::run(argc - 1, argv + 1);
    } catch (const std::exception& e) {
      std::fprintf(stderr, "hub: %s\n", e.what());
      return 1;
    }
  }

  if (argc >= 2 && std::strcmp(argv[1], "gym") == 0) {
    // Policy gym: replay a flight-recorder capsule corpus against N
    // candidate policies (baseline, sweeps, right-size, hysteresis) and
    // score reclaimed chip-hours vs false pauses vs actuation churn.
    log::init(log::Format::Default);
    try {
      return gym::run_cli(argc - 1, argv + 1);
    } catch (const std::exception& e) {
      std::fprintf(stderr, "gym: %s\n", e.what());
      return 1;
    }
  }

  if (argc >= 2 && std::strcmp(argv[1], "querytest") == 0) {
    const bool wire_form = argc == 6 && std::strcmp(argv[2], "--wire") == 0;
    if (argc != 4 && !wire_form) {
      std::fprintf(stderr,
                   "usage: tpu-pruner querytest <promql> <prometheus-url>\n"
                   "       tpu-pruner querytest --evidence <prometheus-url>\n"
                   "       tpu-pruner querytest --wire proto|json <promql> <prometheus-url>\n"
                   "  --evidence renders and runs the signal watchdog's evidence query\n"
                   "  (per-pod sample coverage + last-sample age; default TPU/gmp args)\n"
                   "  --wire fetches ONE raw response in the chosen content type and\n"
                   "  hex-dumps it (debugging protobuf negotiation against real endpoints)\n");
      return 2;
    }
    log::init(log::Format::Default);
    try {
      if (wire_form) {
        // Raw-wire debugging: what does this endpoint actually answer
        // when asked for the protobuf exposition?
        return querytest::run_wire(argv[4], argv[5], argv[3]);
      }
      if (std::strcmp(argv[2], "--evidence") == 0) {
        // Ad-hoc evidence-health check: the same query --signal-guard on
        // issues per cycle, runnable standalone before enabling the guard.
        std::string evidence = query::build_evidence_query(query::QueryArgs{});
        std::fprintf(stderr, "evidence query:\n%s\n", evidence.c_str());
        return querytest::run(evidence, argv[3]);
      }
      return querytest::run(argv[2], argv[3]);
    } catch (const std::exception& e) {
      std::fprintf(stderr, "querytest: %s\n", e.what());
      return 1;
    }
  }

  cli::Cli args;
  try {
    args = cli::parse(argc, argv);
  } catch (const cli::HelpRequested& e) {
    std::fprintf(stdout, "%s\n", e.what());
    return 0;
  } catch (const cli::CliError& e) {
    std::fprintf(stderr, "%s\n", e.what());
    return 2;
  }

  if (args.print_query) {
    try {
      std::fprintf(stdout, "%s\n", query::build_idle_query(cli::to_query_args(args)).c_str());
      return 0;
    } catch (const std::exception& e) {
      std::fprintf(stderr, "%s\n", e.what());
      return 2;
    }
  }

  log::init(cli::log_format_of(args));
  try {
    return daemon::run(args);
  } catch (const std::exception& e) {
    log::error(std::string("fatal: ") + e.what());
    return 1;
  }
}
