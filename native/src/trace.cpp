#include "tpupruner/trace.hpp"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <deque>
#include <memory>
#include <mutex>
#include <unordered_map>

#include "otlp.hpp"
#include "tpupruner/fleet.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::trace {

using json::Value;

namespace {

std::atomic<bool> g_enabled{false};
std::atomic<int64_t> g_slo_ms{0};

// Ring sizes: 256 recent traces (~a few KB each) bounds steady-state RSS;
// 64 pinned SLO breaches survive past normal eviction so breach evidence
// outlives the storm that caused it. Index serves the newest 50 so the
// hub's per-member poll stays bounded.
constexpr size_t kRingCap = 256;
constexpr size_t kPinnedCap = 64;
constexpr size_t kIndexCap = 50;
constexpr size_t kActiveCap = 64;  // abandoned-trace backstop (failed cycles)

struct StoredSpan {
  std::string span_id;  // 16 hex, assigned at attach
  Span s;
};

struct ActiveTrace {
  std::string trace_id, root_span_id, trigger;
  uint64_t cycle = 0;
  int64_t root_start_nanos = 0;
  int64_t ingress_lag_ms = 0;
  std::vector<StoredSpan> spans;
  bool armed = false;
  size_t expected = 0;        // actuations promised by arm()
  size_t done = 0;            // actuations landed (may precede arm)
  size_t actuations = 0;
  bool breached = false;
  int64_t worst_actuation_ms = 0;
};

struct FinishedTrace {
  std::string trace_id, root_span_id, trigger;
  uint64_t cycle = 0;
  int64_t root_start_nanos = 0, root_end_nanos = 0;
  int64_t ingress_lag_ms = 0;
  std::vector<StoredSpan> spans;
  size_t actuations = 0;
  bool breached = false;
  bool pinned = false;
  int64_t worst_actuation_ms = 0;

  double root_ms() const {
    return static_cast<double>(root_end_nanos - root_start_nanos) / 1e6;
  }
};

struct Engine {
  std::mutex mu;
  std::unordered_map<uint64_t, ActiveTrace> active;
  std::deque<std::shared_ptr<FinishedTrace>> ring;    // newest at back
  std::deque<std::shared_ptr<FinishedTrace>> pinned;  // SLO breaches
  uint64_t completed_total = 0;
  uint64_t evicted_total = 0;
  uint64_t slo_good = 0, slo_bad = 0, slo_breaches = 0;
};

Engine& engine() {
  static Engine e;
  return e;
}

// Per-consumer-thread open actuation span: retry events append here
// LOCK-FREE (backoff::record_retry fires from arbitrary depths of the
// patch attempt); the span touches the engine mutex once, at end.
struct OpenActuation {
  bool open = false;
  uint64_t cycle = 0;
  Span span;
};
thread_local OpenActuation t_act;

std::string new_span_id() { return util::random_hex32().substr(16); }

std::string fmt_double(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%g", v);
  return buf;
}

void export_otlp_locked(const FinishedTrace& ft) {
  if (!otlp::recording()) return;
  otlp::FinishedSpan root;
  root.name = "evaluate";
  root.trace_id = ft.trace_id;
  root.span_id = ft.root_span_id;
  root.start_nanos = ft.root_start_nanos;
  root.end_nanos = ft.root_end_nanos;
  root.str_attrs.emplace_back("trigger", ft.trigger);
  root.int_attrs.emplace_back("cycle", static_cast<int64_t>(ft.cycle));
  if (ft.breached) root.int_attrs.emplace_back("slo_breached", 1);
  otlp::buffer_finished_span(std::move(root));
  for (const StoredSpan& ss : ft.spans) {
    otlp::FinishedSpan child;
    child.name = ss.s.name;
    child.trace_id = ft.trace_id;
    child.span_id = ss.span_id;
    child.parent_span_id = ft.root_span_id;
    child.start_nanos = ss.s.start_nanos;
    child.end_nanos = ss.s.end_nanos;
    child.str_attrs = ss.s.str_attrs;
    child.int_attrs = ss.s.int_attrs;
    child.error = ss.s.error;
    child.error_message = ss.s.error_message;
    for (const Event& ev : ss.s.events) {
      otlp::SpanEvent oe;
      oe.time_nanos = ev.time_nanos;
      oe.name = ev.name;
      oe.str_attrs = ev.str_attrs;
      oe.int_attrs = ev.int_attrs;
      child.events.push_back(std::move(oe));
    }
    otlp::buffer_finished_span(std::move(child));
  }
}

void seal_locked(Engine& e, std::unordered_map<uint64_t, ActiveTrace>::iterator it) {
  ActiveTrace& a = it->second;
  auto ft = std::make_shared<FinishedTrace>();
  ft->trace_id = a.trace_id;
  ft->root_span_id = a.root_span_id;
  ft->trigger = a.trigger;
  ft->cycle = a.cycle;
  ft->root_start_nanos = a.root_start_nanos;
  ft->ingress_lag_ms = a.ingress_lag_ms;
  ft->actuations = a.actuations;
  ft->breached = a.breached;
  ft->worst_actuation_ms = a.worst_actuation_ms;
  ft->spans = std::move(a.spans);
  // Root ends when its last child does (the final actuation for acting
  // evaluations — detect→action joins on this); a childless evaluation
  // ends at seal time.
  int64_t end = a.root_start_nanos;
  for (const StoredSpan& ss : ft->spans) end = std::max(end, ss.s.end_nanos);
  if (end <= a.root_start_nanos) end = util::now_unix_nanos();
  ft->root_end_nanos = end;
  e.active.erase(it);

  ++e.completed_total;
  if (ft->breached) ++e.slo_breaches;
  export_otlp_locked(*ft);

  if (ft->breached) {
    ft->pinned = true;
    e.pinned.push_back(std::move(ft));
    if (e.pinned.size() > kPinnedCap) {
      e.pinned.pop_front();
      ++e.evicted_total;
    }
    return;
  }
  e.ring.push_back(std::move(ft));
  if (e.ring.size() > kRingCap) {
    e.ring.pop_front();
    ++e.evicted_total;
  }
}

// All retained traces, newest root-end first (pinned interleaved).
std::vector<std::shared_ptr<FinishedTrace>> retained_locked(Engine& e) {
  std::vector<std::shared_ptr<FinishedTrace>> all;
  all.reserve(e.ring.size() + e.pinned.size());
  for (const auto& t : e.ring) all.push_back(t);
  for (const auto& t : e.pinned) all.push_back(t);
  std::stable_sort(all.begin(), all.end(), [](const auto& x, const auto& y) {
    return x->root_end_nanos > y->root_end_nanos;
  });
  return all;
}

Value attrs_json(const std::vector<std::pair<std::string, std::string>>& strs,
                 const std::vector<std::pair<std::string, int64_t>>& ints) {
  Value attrs = Value::object();
  for (const auto& [k, v] : strs) attrs.set(k, Value(v));
  for (const auto& [k, v] : ints) attrs.set(k, Value(v));
  return attrs;
}

Value span_json(const FinishedTrace& ft, const StoredSpan& ss) {
  Value s = Value::object();
  s.set("span_id", Value(ss.span_id));
  s.set("parent_span_id", Value(ft.root_span_id));
  s.set("name", Value(ss.s.name));
  s.set("start_us", Value((ss.s.start_nanos - ft.root_start_nanos) / 1000));
  s.set("end_us", Value((ss.s.end_nanos - ft.root_start_nanos) / 1000));
  if (!ss.s.str_attrs.empty() || !ss.s.int_attrs.empty())
    s.set("attrs", attrs_json(ss.s.str_attrs, ss.s.int_attrs));
  if (!ss.s.events.empty()) {
    Value events = Value::array();
    for (const Event& ev : ss.s.events) {
      Value e = Value::object();
      e.set("time_us", Value((ev.time_nanos - ft.root_start_nanos) / 1000));
      e.set("name", Value(ev.name));
      if (!ev.str_attrs.empty() || !ev.int_attrs.empty())
        e.set("attrs", attrs_json(ev.str_attrs, ev.int_attrs));
      events.push_back(std::move(e));
    }
    s.set("events", std::move(events));
  }
  if (ss.s.error) {
    s.set("error", Value(true));
    s.set("error_message", Value(ss.s.error_message));
  }
  return s;
}

Value summary_json(const FinishedTrace& ft) {
  Value t = Value::object();
  t.set("trace_id", Value(ft.trace_id));
  t.set("cycle", Value(static_cast<int64_t>(ft.cycle)));
  t.set("trigger", Value(ft.trigger));
  t.set("root_ms", Value(ft.root_ms()));
  t.set("spans", Value(static_cast<int64_t>(ft.spans.size())));
  t.set("actuations", Value(static_cast<int64_t>(ft.actuations)));
  t.set("breached", Value(ft.breached));
  t.set("pinned", Value(ft.pinned));
  return t;
}

Value slo_summary_locked(Engine& e) {
  Value doc = Value::object();
  int64_t slo = g_slo_ms.load(std::memory_order_relaxed);
  doc.set("enabled", Value(slo > 0));
  doc.set("slo_ms", Value(slo));
  doc.set("good", Value(static_cast<int64_t>(e.slo_good)));
  doc.set("bad", Value(static_cast<int64_t>(e.slo_bad)));
  doc.set("breaches", Value(static_cast<int64_t>(e.slo_breaches)));
  uint64_t total = e.slo_good + e.slo_bad;
  doc.set("burn_ratio", Value(total ? static_cast<double>(e.slo_bad) / total : 0.0));
  Value worst = Value::array();
  auto all = retained_locked(e);
  std::stable_sort(all.begin(), all.end(), [](const auto& x, const auto& y) {
    return x->root_ms() > y->root_ms();
  });
  for (size_t i = 0; i < all.size() && i < 5; ++i) {
    Value w = Value::object();
    w.set("trace_id", Value(all[i]->trace_id));
    w.set("cycle", Value(static_cast<int64_t>(all[i]->cycle)));
    w.set("trigger", Value(all[i]->trigger));
    w.set("root_ms", Value(all[i]->root_ms()));
    w.set("breached", Value(all[i]->breached));
    worst.push_back(std::move(w));
  }
  doc.set("worst", std::move(worst));
  return doc;
}

}  // namespace

void configure(bool on, int64_t slo) {
  g_enabled.store(on, std::memory_order_relaxed);
  g_slo_ms.store(slo, std::memory_order_relaxed);
}

bool enabled() { return g_enabled.load(std::memory_order_relaxed); }
int64_t slo_ms() { return g_slo_ms.load(std::memory_order_relaxed); }

std::string begin(uint64_t cycle, const std::string& trigger, int64_t ingress_lag_ms,
                  const std::string& hint_trace_id) {
  if (!enabled()) return "";
  ActiveTrace a;
  a.trace_id = hint_trace_id.size() == 32 ? hint_trace_id : util::random_hex32();
  a.root_span_id = new_span_id();
  a.trigger = trigger;
  a.cycle = cycle;
  a.ingress_lag_ms = std::max<int64_t>(0, ingress_lag_ms);
  a.root_start_nanos = util::now_unix_nanos() - a.ingress_lag_ms * 1000000ll;
  std::string id = a.trace_id;
  std::lock_guard<std::mutex> lock(engine().mu);
  Engine& e = engine();
  e.active[cycle] = std::move(a);
  // Abandoned-trace backstop: a cycle that dies before arm() (failed
  // query, shutdown) would leak its entry; bound the map by dropping the
  // oldest unarmed trace.
  if (e.active.size() > kActiveCap) {
    auto oldest = e.active.end();
    for (auto it = e.active.begin(); it != e.active.end(); ++it) {
      if (it->second.armed || it->first == cycle) continue;
      if (oldest == e.active.end() || it->first < oldest->first) oldest = it;
    }
    if (oldest != e.active.end()) {
      e.active.erase(oldest);
      ++e.evicted_total;
    }
  }
  return id;
}

std::string trace_id_of(uint64_t cycle) {
  if (!enabled()) return "";
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  auto it = e.active.find(cycle);
  if (it != e.active.end()) return it->second.trace_id;
  for (auto r = e.ring.rbegin(); r != e.ring.rend(); ++r)
    if ((*r)->cycle == cycle) return (*r)->trace_id;
  for (auto r = e.pinned.rbegin(); r != e.pinned.rend(); ++r)
    if ((*r)->cycle == cycle) return (*r)->trace_id;
  return "";
}

std::string traceparent(uint64_t cycle) {
  if (!enabled()) return "";
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  auto it = e.active.find(cycle);
  if (it == e.active.end()) return "";
  return "00-" + it->second.trace_id + "-" + it->second.root_span_id + "-01";
}

void add_span(uint64_t cycle, Span span) {
  if (!enabled()) return;
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  auto it = e.active.find(cycle);
  if (it == e.active.end()) return;
  // Clamp into the root window so a backdated debounce span can never
  // start before trigger ingress (clock skew between stamping sites).
  span.start_nanos = std::max(span.start_nanos, it->second.root_start_nanos);
  it->second.spans.push_back(StoredSpan{new_span_id(), std::move(span)});
}

void add_phase_span(uint64_t cycle, const std::string& name, double seconds) {
  if (!enabled()) return;
  Span s;
  s.name = name;
  s.end_nanos = util::now_unix_nanos();
  s.start_nanos = s.end_nanos - static_cast<int64_t>(seconds * 1e9);
  add_span(cycle, std::move(s));
}

void actuation_begin(uint64_t cycle, const std::string& identity) {
  if (!enabled()) return;
  t_act.open = true;
  t_act.cycle = cycle;
  t_act.span = Span{};
  t_act.span.name = "actuate";
  t_act.span.start_nanos = util::now_unix_nanos();
  t_act.span.str_attrs.emplace_back("identity", identity);
}

void thread_retry_event(const std::string& endpoint, const std::string& cause,
                        double backoff_seconds) {
  if (!t_act.open) return;
  Event ev;
  ev.time_nanos = util::now_unix_nanos();
  ev.name = "retry";
  ev.str_attrs.emplace_back("endpoint", endpoint);
  ev.str_attrs.emplace_back("cause", cause);
  ev.int_attrs.emplace_back("backoff_ms", static_cast<int64_t>(backoff_seconds * 1000.0));
  t_act.span.events.push_back(std::move(ev));
}

void actuation_end(uint64_t cycle, const std::string& outcome, bool error,
                   const std::string& error_message) {
  if (!t_act.open) return;
  t_act.open = false;
  Span span = std::move(t_act.span);
  span.end_nanos = util::now_unix_nanos();
  span.str_attrs.emplace_back("outcome", outcome);
  if (!span.events.empty())
    span.int_attrs.emplace_back("retries", static_cast<int64_t>(span.events.size()));
  span.error = error;
  span.error_message = error_message;
  if (!enabled()) return;
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  auto it = e.active.find(cycle);
  if (it == e.active.end()) return;
  ActiveTrace& a = it->second;
  ++a.actuations;
  // SLO judgment: the actuation's root-relative latency IS the
  // detect→action latency (root starts at trigger ingress).
  int64_t latency_ms = (span.end_nanos - a.root_start_nanos) / 1000000ll;
  a.worst_actuation_ms = std::max(a.worst_actuation_ms, latency_ms);
  int64_t slo = g_slo_ms.load(std::memory_order_relaxed);
  if (slo > 0) {
    if (latency_ms > slo) {
      ++e.slo_bad;
      a.breached = true;
    } else {
      ++e.slo_good;
    }
  }
  a.spans.push_back(StoredSpan{new_span_id(), std::move(span)});
  ++a.done;
  if (a.armed && a.done >= a.expected) seal_locked(e, it);
}

void arm(uint64_t cycle, size_t expected) {
  if (!enabled()) return;
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  auto it = e.active.find(cycle);
  if (it == e.active.end()) return;
  it->second.armed = true;
  it->second.expected = expected;
  if (it->second.done >= expected) seal_locked(e, it);
}

json::Value capsule_stamp(uint64_t cycle) {
  if (!enabled()) return Value();
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  auto it = e.active.find(cycle);
  if (it == e.active.end()) return Value();
  const ActiveTrace& a = it->second;
  Value doc = Value::object();
  doc.set("trace_id", Value(a.trace_id));
  doc.set("trigger", Value(a.trigger));
  doc.set("root_start_nanos", Value(a.root_start_nanos));
  Value spans = Value::array();
  for (const StoredSpan& ss : a.spans) {
    Value s = Value::object();
    s.set("name", Value(ss.s.name));
    s.set("start_us", Value((ss.s.start_nanos - a.root_start_nanos) / 1000));
    s.set("end_us", Value((ss.s.end_nanos - a.root_start_nanos) / 1000));
    if (!ss.s.str_attrs.empty() || !ss.s.int_attrs.empty())
      s.set("attrs", attrs_json(ss.s.str_attrs, ss.s.int_attrs));
    spans.push_back(std::move(s));
  }
  doc.set("spans", std::move(spans));
  return doc;
}

json::Value index_json() {
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  Value doc = Value::object();
  doc.set("cluster", Value(fleet::cluster_name()));
  doc.set("enabled", Value(enabled()));
  Value traces = Value::array();
  auto all = retained_locked(e);
  for (size_t i = 0; i < all.size() && i < kIndexCap; ++i)
    traces.push_back(summary_json(*all[i]));
  doc.set("traces", std::move(traces));
  doc.set("retained", Value(static_cast<int64_t>(e.ring.size() + e.pinned.size())));
  doc.set("pinned", Value(static_cast<int64_t>(e.pinned.size())));
  doc.set("completed_total", Value(static_cast<int64_t>(e.completed_total)));
  doc.set("evicted_total", Value(static_cast<int64_t>(e.evicted_total)));
  doc.set("slo", slo_summary_locked(e));
  return doc;
}

std::string trace_json(const std::string& id) {
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  std::shared_ptr<FinishedTrace> found;
  for (const auto& t : e.pinned)
    if (t->trace_id == id) found = t;
  if (!found)
    for (const auto& t : e.ring)
      if (t->trace_id == id) found = t;
  if (!found) return "";
  const FinishedTrace& ft = *found;
  Value doc = summary_json(ft);
  doc.set("cluster", Value(fleet::cluster_name()));
  Value root = Value::object();
  root.set("span_id", Value(ft.root_span_id));
  root.set("name", Value("evaluate"));
  root.set("start_nanos", Value(ft.root_start_nanos));
  root.set("end_nanos", Value(ft.root_end_nanos));
  root.set("duration_ms", Value(ft.root_ms()));
  root.set("ingress_lag_ms", Value(ft.ingress_lag_ms));
  doc.set("root", std::move(root));
  doc.set("worst_actuation_ms", Value(ft.worst_actuation_ms));
  Value spans = Value::array();
  for (const StoredSpan& ss : ft.spans) spans.push_back(span_json(ft, ss));
  doc.set("span_tree", std::move(spans));
  return doc.dump();
}

json::Value slo_summary() {
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  return slo_summary_locked(e);
}

const std::vector<std::string>& metric_families() {
  static const std::vector<std::string> families = {
      "tpu_pruner_trace_completed_total", "tpu_pruner_trace_retained",
      "tpu_pruner_trace_pinned",          "tpu_pruner_trace_evicted_total",
      "tpu_pruner_slo_good_total",        "tpu_pruner_slo_bad_total",
      "tpu_pruner_slo_breaches_total",    "tpu_pruner_slo_burn_ratio",
  };
  return families;
}

std::string render_metrics(bool openmetrics) {
  if (!enabled()) return "";
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  // OpenMetrics reserves the `counter` type for suffix-transformed names;
  // keep the 0.0.4-compatible rendering the other families use.
  const std::string ctype = openmetrics ? "unknown" : "counter";
  std::string out;
  auto counter = [&](const char* name, const char* help, uint64_t v) {
    out += "# HELP " + std::string(name) + " " + help + "\n";
    out += "# TYPE " + std::string(name) + " " + ctype + "\n";
    out += std::string(name) + " " + std::to_string(v) + "\n";
  };
  auto gauge = [&](const char* name, const char* help, const std::string& v) {
    out += "# HELP " + std::string(name) + " " + help + "\n";
    out += "# TYPE " + std::string(name) + " gauge\n";
    out += std::string(name) + " " + v + "\n";
  };
  counter("tpu_pruner_trace_completed_total",
          "Evaluation traces sealed into the retention ring", e.completed_total);
  gauge("tpu_pruner_trace_retained",
        "Traces currently retained (ring + pinned SLO breaches)",
        std::to_string(e.ring.size() + e.pinned.size()));
  gauge("tpu_pruner_trace_pinned",
        "SLO-breaching traces pinned past normal ring eviction",
        std::to_string(e.pinned.size()));
  counter("tpu_pruner_trace_evicted_total",
          "Traces evicted from the bounded ring (or abandoned before seal)",
          e.evicted_total);
  counter("tpu_pruner_slo_good_total",
          "Actuations inside the --slo-detect-to-action-ms budget", e.slo_good);
  counter("tpu_pruner_slo_bad_total",
          "Actuations past the --slo-detect-to-action-ms budget", e.slo_bad);
  counter("tpu_pruner_slo_breaches_total",
          "Traces with at least one SLO-breaching actuation (each pinned)",
          e.slo_breaches);
  uint64_t total = e.slo_good + e.slo_bad;
  gauge("tpu_pruner_slo_burn_ratio",
        "Fraction of SLO budget burnt: bad / (good + bad) actuations",
        fmt_double(total ? static_cast<double>(e.slo_bad) / total : 0.0));
  return out;
}

void reset_for_test() {
  Engine& e = engine();
  std::lock_guard<std::mutex> lock(e.mu);
  e.active.clear();
  e.ring.clear();
  e.pinned.clear();
  e.completed_total = e.evicted_total = 0;
  e.slo_good = e.slo_bad = e.slo_breaches = 0;
  t_act = OpenActuation{};
  g_enabled.store(false, std::memory_order_relaxed);
  g_slo_ms.store(0, std::memory_order_relaxed);
}

}  // namespace tpupruner::trace
