#include "tpupruner/k8s.hpp"

#include <algorithm>
#include <chrono>
#include <ctime>
#include <iomanip>
#include <sstream>
#include <stdexcept>
#include <thread>

#include "tpupruner/backoff.hpp"
#include "tpupruner/kubeconfig.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::k8s {

namespace {
constexpr const char* kSaDir = "/var/run/secrets/kubernetes.io/serviceaccount";
}

Config Config::infer() {
  Config c;
  // 1. explicit env (hermetic tests, kubectl-proxy setups)
  if (auto url = util::env("KUBE_API_URL")) {
    c.api_url = *url;
    if (auto t = util::env("KUBE_TOKEN")) c.token = *t;
    else if (auto tf = util::env("KUBE_TOKEN_FILE")) {
      if (auto content = util::read_file(*tf)) c.token = util::trim(*content);
    }
    if (auto ca = util::env("KUBE_CA_FILE")) c.ca_file = *ca;
    c.tls_skip = util::env("KUBE_TLS_SKIP").has_value();
    return c;
  }

  // 2. in-cluster (the deployment path, hack/deployment.yaml analog)
  auto host = util::env("KUBERNETES_SERVICE_HOST");
  if (host) {
    std::string port = util::env("KUBERNETES_SERVICE_PORT").value_or("443");
    std::string h = *host;
    if (h.find(':') != std::string::npos) h = "[" + h + "]";  // IPv6
    c.api_url = "https://" + h + ":" + port;
    std::string sa_dir = util::env("TPU_PRUNER_SA_DIR").value_or(kSaDir);
    if (auto token = util::read_file(sa_dir + "/token")) c.token = util::trim(*token);
    c.ca_file = sa_dir + "/ca.crt";
    return c;
  }

  // 3. kubeconfig scan (token-auth users only)
  if (auto info = kubeconfig::scan()) {
    c.api_url = info->server;
    c.token = info->token;
    c.tls_skip = info->tls_skip;
    return c;
  }

  throw std::runtime_error(
      "no kubernetes config: set KUBE_API_URL, run in-cluster "
      "(KUBERNETES_SERVICE_HOST), or provide a kubeconfig with token auth");
}

Client::Client(Config config)
    : config_(std::move(config)),
      http_(h2::default_mode(),
            config_.tls_skip ? http::TlsMode::Skip : http::TlsMode::Verify, config_.ca_file) {}

http::Response Client::issue(http::Request& req, const std::string& method,
                             const std::string& path, bool retry_throttle) const {
  api_calls_.fetch_add(1, std::memory_order_relaxed);
  http::Response resp = http_.request(req);
  // API Priority & Fairness throttling (stock GKE behavior): the server
  // sheds load with 429 + Retry-After. Honoring it with a bounded wait
  // turns a throttled burst into a short stall instead of a failed
  // request — which otherwise escalates into a fail-closed namespace
  // veto (resolve phase) or a consumed failure-budget tick. All verbs
  // here are safe to retry: GET/LIST trivially, PATCH/POST because
  // a 429 is shed BEFORE admission (nothing was applied). Two retries,
  // waits capped at 10 s, keeps the worst case << one check interval.
  for (int attempt = 0; resp.status == 429 && retry_throttle && attempt < 2; ++attempt) {
    int64_t hint_ms = 1000;
    if (auto it = resp.headers.find("retry-after"); it != resp.headers.end())
      hint_ms = backoff::parse_retry_after_ms(it->second);
    // Deterministic per-path jitter (backoff::Policy): every throttled
    // worker receives the same Retry-After, and waking them in lockstep
    // would re-hammer the already-shedding apiserver. The hint is capped
    // BEFORE the jitter so the documented 10 s worst case per attempt
    // still holds without collapsing long Retry-After values onto one
    // identical wake time.
    int64_t wait_ms = backoff::policy().hinted_delay_ms(path, hint_ms);
    log::warn("k8s", "HTTP 429 (apiserver throttling) on " + method + " " + path +
              "; retrying in " + std::to_string(wait_ms) + "ms");
    backoff::record_retry("k8s", "http429", static_cast<double>(wait_ms) / 1000.0);
    // Chunked, shutdown-interruptible wait (the daemon's sleep convention):
    // a SIGTERM mid-backoff aborts the retry so the drain starts promptly.
    if (!backoff::sleep_interruptible(wait_ms)) break;
    resp = http_.request(req);
  }
  return resp;
}

json::Value Client::request_json(const std::string& method, const std::string& path,
                                 const std::string& body, const std::string& content_type,
                                 int* status_out, bool retry_throttle,
                                 json::DocPtr* doc_out) const {
  http::Request req;
  req.method = method;
  req.url = config_.api_url + path;
  req.timeout_ms = config_.timeout_ms;
  req.headers.push_back({"Accept", "application/json"});
  if (!config_.token.empty())
    req.headers.push_back({"Authorization", "Bearer " + config_.token});
  if (!content_type.empty()) req.headers.push_back({"Content-Type", content_type});
  req.body = body;

  http::Response resp = issue(req, method, path, retry_throttle);
  if (status_out) *status_out = resp.status;
  if (resp.status >= 200 && resp.status < 300) {
    if (resp.body.empty()) {
      if (doc_out) *doc_out = json::Doc::parse("{}");
      return json::Value::object();
    }
    try {
      if (doc_out) {
        // Zero-copy delivery: the response body MOVES into the Doc and the
        // arena nodes view into it; no Value tree is built here at all.
        *doc_out = json::Doc::parse(std::move(resp.body));
        return json::Value();
      }
      return json::Value::parse(resp.body);
    } catch (const json::ParseError& e) {
      throw std::runtime_error("k8s: unparseable response body from " + path + ": " + e.what());
    }
  }
  if (status_out && resp.status == 404) return json::Value();  // caller handles
  // Surface the API server's message (Status object) for logs.
  std::string message;
  try {
    json::Value status = json::Value::parse(resp.body);
    message = status.get_string("message", resp.body.substr(0, 256));
  } catch (const std::exception&) {
    message = resp.body.substr(0, 256);
  }
  throw ApiError(resp.status, "k8s: " + method + " " + path + " → HTTP " +
                                  std::to_string(resp.status) + ": " + message);
}

std::optional<json::Value> Client::get_opt(const std::string& path,
                                           bool retry_throttle) const {
  int status = 0;
  json::Value v = request_json("GET", path, "", "", &status, retry_throttle);
  if (status == 404) return std::nullopt;
  return v;
}

json::Value Client::get(const std::string& path) const {
  return request_json("GET", path, "", "", nullptr);
}

json::Value Client::list(const std::string& path, const std::string& label_selector,
                         int64_t limit) const {
  // Follow metadata.continue. Stock apiservers only paginate when the
  // client sends `limit` (resolution LISTs don't; the informer does), but
  // an intermediary cache or aggregated apiserver may chunk anyway —
  // ignoring the token would silently truncate batched resolution (e.g. a
  // JobSet's all-idle gate deciding on half its worker pods).
  std::string base_query;
  if (!label_selector.empty()) base_query = "labelSelector=" + util::url_encode(label_selector);
  if (limit > 0) {
    if (!base_query.empty()) base_query += "&";
    base_query += "limit=" + std::to_string(limit);
  }

  json::Value out;
  std::string continue_token;
  constexpr int kMaxPages = 1000;  // runaway-server guard, not a size cap
  for (int page = 0; page < kMaxPages; ++page) {
    std::string query = base_query;
    if (!continue_token.empty()) {
      if (!query.empty()) query += "&";
      query += "continue=" + util::url_encode(continue_token);
    }
    json::Value chunk =
        request_json("GET", query.empty() ? path : path + "?" + query, "", "", nullptr);

    std::string next;
    if (const json::Value* c = chunk.at_path("metadata.continue"); c && c->is_string()) {
      next = c->as_string();
    }
    if (page == 0) {
      out = std::move(chunk);
    } else {
      if (const json::Value* items = chunk.find("items"); items && items->is_array()) {
        const json::Value* out_items = out.find("items");
        if (out_items && out_items->is_array()) {
          json::Value& dst = out.as_object()["items"];
          for (json::Value& item : chunk.as_object()["items"].as_array()) {
            dst.push_back(std::move(item));
          }
        } else {
          out.set("items", std::move(chunk.as_object()["items"]));
        }
      }
      // Carry the LAST page's metadata: its resourceVersion is the newest
      // snapshot a future watch/precondition caller could legally use;
      // page 1's would be the stalest.
      if (const json::Value* meta = chunk.find("metadata"); meta && meta->is_object()) {
        out.set("metadata", std::move(chunk.as_object()["metadata"]));
      }
    }
    if (next.empty()) {
      // drop the consumed token so callers never see a half-used cursor
      if (page > 0) {
        if (const json::Value* meta = out.find("metadata"); meta && meta->is_object()) {
          out.as_object()["metadata"].as_object().erase("continue");
        }
      }
      return out;
    }
    continue_token = next;
  }
  throw std::runtime_error("k8s: LIST " + path + " did not terminate after " +
                           std::to_string(kMaxPages) + " continue pages");
}

std::string Client::list_pages(const std::string& path, const std::string& label_selector,
                               int64_t limit,
                               const std::function<void(const json::DocPtr&)>& on_page) const {
  std::string base_query;
  if (!label_selector.empty()) base_query = "labelSelector=" + util::url_encode(label_selector);
  if (limit > 0) {
    if (!base_query.empty()) base_query += "&";
    base_query += "limit=" + std::to_string(limit);
  }
  std::string rv;
  std::string continue_token;
  constexpr int kMaxPages = 1000;  // same runaway-server guard as list()
  for (int page = 0; page < kMaxPages; ++page) {
    std::string query = base_query;
    if (!continue_token.empty()) {
      if (!query.empty()) query += "&";
      query += "continue=" + util::url_encode(continue_token);
    }
    json::DocPtr doc;
    request_json("GET", query.empty() ? path : path + "?" + query, "", "", nullptr,
                 /*retry_throttle=*/true, &doc);
    proto::counters().k8s_json_bytes.fetch_add(doc->body().size(), std::memory_order_relaxed);
    std::string next;
    if (auto meta = doc->root().find("metadata"); meta && meta->is_object()) {
      if (auto c = meta->find("continue"); c && c->is_string()) next = c->as_string();
      if (auto v = meta->find("resourceVersion"); v && v->is_string()) {
        // Last page's version wins — the newest legal watch resume point,
        // same rule as list()'s metadata carry.
        rv = v->as_string();
      }
    }
    on_page(doc);
    if (next.empty()) return rv;
    continue_token = next;
  }
  throw std::runtime_error("k8s: LIST " + path + " did not terminate after " +
                           std::to_string(kMaxPages) + " continue pages");
}

std::string Client::list_pages_wire(const std::string& path, const std::string& label_selector,
                                    int64_t limit,
                                    const std::function<void(const WirePage&)>& on_page) const {
  std::string base_query;
  if (!label_selector.empty()) base_query = "labelSelector=" + util::url_encode(label_selector);
  if (limit > 0) {
    if (!base_query.empty()) base_query += "&";
    base_query += "limit=" + std::to_string(limit);
  }
  std::string rv;
  std::string continue_token;
  constexpr int kMaxPages = 1000;  // same runaway-server guard as list()
  for (int page_i = 0; page_i < kMaxPages; ++page_i) {
    std::string query = base_query;
    if (!continue_token.empty()) {
      if (!query.empty()) query += "&";
      query += "continue=" + util::url_encode(continue_token);
    }
    const std::string full_path = query.empty() ? path : path + "?" + query;
    http::Request req;
    req.url = config_.api_url + full_path;
    req.timeout_ms = config_.timeout_ms;
    const bool want_proto = proto::k8s_proto_wanted();
    req.headers.push_back(
        {"Accept", want_proto ? std::string(proto::kK8sProtoAccept) : "application/json"});
    if (!config_.token.empty())
      req.headers.push_back({"Authorization", "Bearer " + config_.token});
    http::Response resp = issue(req, "GET", full_path, /*retry_throttle=*/true);
    if (resp.status < 200 || resp.status >= 300) {
      std::string message;
      try {
        message = json::Value::parse(resp.body).get_string("message", resp.body.substr(0, 256));
      } catch (const std::exception&) {
        message = resp.body.substr(0, 256);
      }
      throw ApiError(resp.status, "k8s: GET " + full_path + " → HTTP " +
                                      std::to_string(resp.status) + ": " + message);
    }
    std::string content_type;
    if (auto it = resp.headers.find("content-type"); it != resp.headers.end()) {
      content_type = it->second;
    }
    WirePage page;
    std::string next;
    if (proto::is_k8s_proto(content_type)) {
      proto::counters().k8s_proto_bytes.fetch_add(resp.body.size(), std::memory_order_relaxed);
      try {
        page.pb = proto::parse_list(std::move(resp.body));
      } catch (const json::ParseError& e) {
        throw std::runtime_error("k8s: unparseable protobuf LIST from " + path + ": " +
                                 e.what());
      }
      next = page.pb->continue_token;
      if (!page.pb->resource_version.empty()) rv = page.pb->resource_version;
    } else {
      if (want_proto) proto::note_k8s_fallback();
      proto::counters().k8s_json_bytes.fetch_add(resp.body.size(), std::memory_order_relaxed);
      try {
        page.doc = json::Doc::parse(std::move(resp.body));
      } catch (const json::ParseError& e) {
        throw std::runtime_error("k8s: unparseable response body from " + path + ": " +
                                 e.what());
      }
      if (auto meta = page.doc->root().find("metadata"); meta && meta->is_object()) {
        if (auto c = meta->find("continue"); c && c->is_string()) next = c->as_string();
        if (auto v = meta->find("resourceVersion"); v && v->is_string()) rv = v->as_string();
      }
    }
    on_page(page);
    if (next.empty()) return rv;
    continue_token = next;
  }
  throw std::runtime_error("k8s: LIST " + path + " did not terminate after " +
                           std::to_string(kMaxPages) + " continue pages");
}

json::Value Client::patch_merge(const std::string& path, const json::Value& body,
                                bool retry_throttle) const {
  // fieldValidation=Strict (server-side field validation, K8s >= 1.25):
  // without it a typo'd CR patch path (spec.suspended, minReplica) is
  // silently PRUNED by the structural schema — the patch "succeeds" and
  // nothing pauses. Strict turns that into a loud 400, matching the
  // hermetic fake's validator. Older apiservers ignore unknown query
  // params, so this degrades safely.
  return request_json("PATCH", path + "?fieldValidation=Strict", body.dump(),
                      "application/merge-patch+json", nullptr, retry_throttle);
}

json::Value Client::post(const std::string& path, const json::Value& body,
                         bool retry_throttle) const {
  return request_json("POST", path, body.dump(), "application/json", nullptr, retry_throttle);
}

void Client::watch(const std::string& path, const WatchOptions& opts,
                   const std::function<bool(const json::Value&)>& on_event) const {
  watch_impl(path, opts, [&](std::string_view line) {
    json::Value event;
    try {
      event = json::Value::parse(line);
    } catch (const json::ParseError& e) {
      throw std::runtime_error(std::string("k8s: unparseable watch event: ") + e.what());
    }
    return on_event(event);
  });
}

void Client::watch_doc(const std::string& path, const WatchOptions& opts,
                       const std::function<bool(const json::DocPtr&)>& on_event) const {
  watch_impl(path, opts, [&](std::string_view line) {
    json::DocPtr event;
    try {
      event = json::Doc::parse(std::string(line));
    } catch (const json::ParseError& e) {
      throw std::runtime_error(std::string("k8s: unparseable watch event: ") + e.what());
    }
    return on_event(event);
  });
}

void Client::watch_impl(const std::string& path, const WatchOptions& opts,
                        const std::function<bool(std::string_view)>& on_line) const {
  api_calls_.fetch_add(1, std::memory_order_relaxed);
  std::string query = "watch=true";
  if (!opts.resource_version.empty())
    query += "&resourceVersion=" + util::url_encode(opts.resource_version);
  if (opts.bookmarks) query += "&allowWatchBookmarks=true";

  http::Request req;
  req.url = config_.api_url + path +
            (path.find('?') == std::string::npos ? "?" : "&") + query;
  req.timeout_ms = opts.read_timeout_ms;
  req.headers.push_back({"Accept", "application/json"});
  if (!config_.token.empty())
    req.headers.push_back({"Authorization", "Bearer " + config_.token});

  // Watch frames are newline-delimited JSON objects; transport chunks do
  // not align with them, so carry the partial tail between deliveries.
  // On a non-200 the body is the apiserver's Status object, not events —
  // it accumulates verbatim for the ApiError message.
  std::string pending;
  int status = 0;
  http::Response resp = http_.request_stream(
      req,
      [&](const char* data, size_t n) {
        pending.append(data, n);
        if (pending.size() > (64u << 20)) {
          throw std::runtime_error("k8s: watch frame exceeds 64 MiB without newline");
        }
        if (status != 200) return pending.size() < 65536;  // error body, bounded
        size_t start = 0;
        while (true) {
          size_t nl = pending.find('\n', start);
          if (nl == std::string::npos) break;
          std::string_view line(pending.data() + start, nl - start);
          start = nl + 1;
          if (util::trim(line).empty()) continue;
          proto::counters().k8s_json_bytes.fetch_add(line.size(), std::memory_order_relaxed);
          if (!on_line(line)) {
            pending.clear();
            return false;
          }
        }
        pending.erase(0, start);
        return true;
      },
      opts.abort,
      [&](const http::Response& r) { status = r.status; });
  if (resp.status != 200) {
    std::string message;
    try {
      message = json::Value::parse(pending).get_string("message", pending.substr(0, 256));
    } catch (const std::exception&) {
      message = pending.substr(0, 256);
    }
    throw ApiError(resp.status, "k8s: WATCH " + path + " → HTTP " +
                                    std::to_string(resp.status) + ": " + message);
  }
}

void Client::watch_wire(const std::string& path, const WatchOptions& opts,
                        const std::function<bool(const WireWatchEvent&)>& on_event) const {
  api_calls_.fetch_add(1, std::memory_order_relaxed);
  std::string query = "watch=true";
  if (!opts.resource_version.empty())
    query += "&resourceVersion=" + util::url_encode(opts.resource_version);
  if (opts.bookmarks) query += "&allowWatchBookmarks=true";

  http::Request req;
  req.url = config_.api_url + path +
            (path.find('?') == std::string::npos ? "?" : "&") + query;
  req.timeout_ms = opts.read_timeout_ms;
  const bool want_proto = proto::k8s_proto_wanted();
  req.headers.push_back(
      {"Accept", want_proto ? std::string(proto::kK8sProtoWatchAccept) : "application/json"});
  if (!config_.token.empty())
    req.headers.push_back({"Authorization", "Bearer " + config_.token});

  // Framing depends on the NEGOTIATED content type (known from the
  // response headers before the first body byte): protobuf streams are
  // 4-byte big-endian length-delimited runtime.Unknown(WatchEvent)
  // frames; JSON streams are newline-delimited events. Error bodies
  // (non-200) are always the apiserver's JSON Status object.
  std::string pending;
  int status = 0;
  bool proto_stream = false;
  http::Response resp = http_.request_stream(
      req,
      [&](const char* data, size_t n) {
        pending.append(data, n);
        if (pending.size() > (64u << 20)) {
          throw std::runtime_error("k8s: watch frame exceeds 64 MiB");
        }
        if (status != 200) return pending.size() < 65536;  // error body, bounded
        if (proto_stream) {
          while (pending.size() >= 4) {
            uint32_t len = (static_cast<uint32_t>(static_cast<unsigned char>(pending[0])) << 24) |
                           (static_cast<uint32_t>(static_cast<unsigned char>(pending[1])) << 16) |
                           (static_cast<uint32_t>(static_cast<unsigned char>(pending[2])) << 8) |
                           static_cast<uint32_t>(static_cast<unsigned char>(pending[3]));
            if (len > (64u << 20)) {
              throw std::runtime_error("k8s: watch frame exceeds 64 MiB");
            }
            if (pending.size() < 4u + len) break;
            std::string frame = pending.substr(4, len);
            pending.erase(0, 4u + len);
            proto::counters().k8s_proto_bytes.fetch_add(len + 4, std::memory_order_relaxed);
            WireWatchEvent ev;
            try {
              // ONE scan per frame: type + object slice + store key +
              // fingerprint come out of this parse; the reflector's fused
              // apply path touches the journal and store directly.
              ev.pb = proto::parse_watch_event(std::move(frame));
            } catch (const json::ParseError& e) {
              throw std::runtime_error(std::string("k8s: unparseable watch frame: ") +
                                       e.what());
            }
            if (!on_event(ev)) {
              pending.clear();
              return false;
            }
          }
          return true;
        }
        size_t start = 0;
        while (true) {
          size_t nl = pending.find('\n', start);
          if (nl == std::string::npos) break;
          std::string_view line(pending.data() + start, nl - start);
          start = nl + 1;
          if (util::trim(line).empty()) continue;
          proto::counters().k8s_json_bytes.fetch_add(line.size(), std::memory_order_relaxed);
          WireWatchEvent ev;
          try {
            ev.doc = json::Doc::parse(std::string(line));
          } catch (const json::ParseError& e) {
            throw std::runtime_error(std::string("k8s: unparseable watch event: ") + e.what());
          }
          if (!on_event(ev)) {
            pending.clear();
            return false;
          }
        }
        pending.erase(0, start);
        return true;
      },
      opts.abort,
      [&](const http::Response& r) {
        status = r.status;
        if (status == 200) {
          std::string content_type;
          if (auto it = r.headers.find("content-type"); it != r.headers.end()) {
            content_type = it->second;
          }
          proto_stream = proto::is_k8s_proto(content_type);
          if (want_proto && !proto_stream) proto::note_k8s_fallback();
        }
      });
  if (resp.status != 200) {
    std::string message;
    try {
      message = json::Value::parse(pending).get_string("message", pending.substr(0, 256));
    } catch (const std::exception&) {
      message = pending.substr(0, 256);
    }
    throw ApiError(resp.status, "k8s: WATCH " + path + " → HTTP " +
                                    std::to_string(resp.status) + ": " + message);
  }
}

std::string Client::pod_path(const std::string& ns, const std::string& name) {
  return "/api/v1/namespaces/" + ns + "/pods/" + name;
}
std::string Client::pods_path(const std::string& ns) {
  return "/api/v1/namespaces/" + ns + "/pods";
}
std::string Client::events_path(const std::string& ns) {
  return "/api/v1/namespaces/" + ns + "/events";
}

std::string Client::object_path(core::Kind kind, const std::string& ns, const std::string& name) {
  return collection_path(kind, ns) + "/" + name;
}

std::string Client::collection_path(core::Kind kind, const std::string& ns) {
  std::string group_version(core::api_version(kind));  // e.g. "apps/v1"
  return "/apis/" + group_version + "/namespaces/" + ns + "/" +
         std::string(core::plural(kind));
}

std::string Client::jobs_path(const std::string& ns) {
  return "/apis/batch/v1/namespaces/" + ns + "/jobs";
}
std::string Client::job_path(const std::string& ns, const std::string& name) {
  return jobs_path(ns) + "/" + name;
}

std::string Client::scale_path(core::Kind kind, const std::string& ns, const std::string& name) {
  return object_path(kind, ns, name) + "/scale";
}

}  // namespace tpupruner::k8s
