#include "tpupruner/ledger.hpp"

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <deque>
#include <map>
#include <mutex>
#include <string>

#include "tpupruner/fleet.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::ledger {

namespace {

// Scale-event history per account. Big enough for months of normal
// pause/resume churn, small enough that a flapping workload can't grow
// the checkpoint without bound.
constexpr size_t kEventCap = 32;

struct ScaleEventRec {
  uint64_t cycle = 0;
  int64_t ts_unix = 0;
  std::string action;  // "paused" | "resumed"
  std::string reason;  // audit reason code on pauses; "" on resumes
  std::string actor;   // "tpu-pruner" | "external"
};

struct Account {
  std::string kind, ns, name;
  int64_t chips = 0;  // latest observed per-root request (sum over idle pods)
  double idle_seconds = 0;
  double active_seconds = 0;
  double reclaimed_chip_seconds = 0;
  uint64_t idle_streak_cycles = 0;
  bool paused = false;
  // Right-sized accounts are "paused" with chips_when_paused = the FREED
  // chips only (partial reclaim = freed × time); the kept replicas are
  // still serving, so the informer resume sweep skips these accounts
  // (already_paused would read the non-zero replica count as an external
  // resume every cycle).
  bool right_sized = false;
  bool idle_now = false;  // observed idle in the most recent cycle
  int64_t paused_since_unix = 0;
  int64_t chips_when_paused = 0;
  uint64_t pauses = 0, resumes = 0;
  uint64_t first_seen_cycle = 0, last_seen_cycle = 0;
  std::deque<ScaleEventRec> events;

  const char* state() const {
    if (paused) return right_sized ? "right_sized" : "paused";
    return idle_now ? "idle" : "active";
  }
};

struct Registry {
  std::mutex mutex;
  // std::map: deterministic iteration for serialization and tests.
  std::map<std::string, Account> accounts;  // key "Kind/ns/name"
  int64_t prev_cycle_unix = 0;  // 0 = no cycle integrated yet (fresh start)
  std::string file_path;
  // Checkpoint epoch: increments on every checkpoint write and restores
  // as the max of the loaded lines' epochs, so it is monotonic across
  // restarts. Merge consumers (analyze --fleet-report over N ledgers)
  // use it to pick the fresher of two checkpoints claiming the same
  // cluster.
  uint64_t epoch = 0;
  // Checkpoint amortization: record_pause/right_size/resume land once per
  // TARGET, and each checkpoint rewrites one line per ACCOUNT — a
  // fleet-scale reclaim cycle (thousands of pauses against thousands of
  // accounts) made eager per-record rewrites O(n^2): ~90 s of pure
  // serialization in the actuation drain at 3.7k roots, stretching the
  // cycle past any sane --check-interval. Throttled instead: a record_*
  // rewrite runs at most once per second; skipped writes set `dirty` and
  // are flushed by the next observe_cycle (every cycle checkpoints
  // unconditionally) or by ledger::flush() at daemon shutdown — the
  // durability loss window is <=1 s of telemetry on a kill -9.
  std::chrono::steady_clock::time_point last_checkpoint{};
  bool dirty = false;
};

Registry& reg() {
  static Registry r;
  return r;
}

std::string key_of(const std::string& kind, const std::string& ns, const std::string& name) {
  return kind + "/" + ns + "/" + name;
}

double round3(double v) { return std::round(v * 1000.0) / 1000.0; }

json::Value account_to_json(const std::string& key, const Account& a, uint64_t epoch) {
  json::Value v = json::Value::object();
  // Merge-safe checkpoint schema (v2): cluster identity + monotonic epoch
  // on EVERY line, so N clusters' JSONL checkpoints merge without
  // guessing and a stale duplicate of one cluster loses deterministically.
  v.set("schema", json::Value(static_cast<int64_t>(2)));
  v.set("cluster", json::Value(fleet::cluster_name()));
  v.set("epoch", json::Value(static_cast<int64_t>(epoch)));
  v.set("workload", json::Value(key));
  v.set("kind", json::Value(a.kind));
  v.set("namespace", json::Value(a.ns));
  v.set("name", json::Value(a.name));
  v.set("chips", json::Value(a.chips));
  v.set("state", json::Value(std::string(a.state())));
  v.set("idle_seconds", json::Value(round3(a.idle_seconds)));
  v.set("active_seconds", json::Value(round3(a.active_seconds)));
  v.set("reclaimed_chip_seconds", json::Value(round3(a.reclaimed_chip_seconds)));
  v.set("idle_streak_cycles", json::Value(static_cast<int64_t>(a.idle_streak_cycles)));
  v.set("pauses", json::Value(static_cast<int64_t>(a.pauses)));
  v.set("resumes", json::Value(static_cast<int64_t>(a.resumes)));
  v.set("first_seen_cycle", json::Value(static_cast<int64_t>(a.first_seen_cycle)));
  v.set("last_seen_cycle", json::Value(static_cast<int64_t>(a.last_seen_cycle)));
  if (a.paused) {
    v.set("paused_since", json::Value(util::format_rfc3339(a.paused_since_unix)));
    v.set("paused_since_unix", json::Value(a.paused_since_unix));
    v.set("chips_when_paused", json::Value(a.chips_when_paused));
  }
  json::Value events = json::Value::array();
  for (const ScaleEventRec& e : a.events) {
    json::Value ev = json::Value::object();
    ev.set("cycle", json::Value(static_cast<int64_t>(e.cycle)));
    ev.set("ts", json::Value(util::format_rfc3339(e.ts_unix)));
    ev.set("ts_unix", json::Value(e.ts_unix));
    ev.set("action", json::Value(e.action));
    if (!e.reason.empty()) ev.set("reason", json::Value(e.reason));
    ev.set("actor", json::Value(e.actor));
    events.push_back(std::move(ev));
  }
  v.set("events", std::move(events));
  return v;
}

// Rewrite the JSONL checkpoint (one account per line) atomically: a crash
// mid-write must never destroy the accumulated savings, so write a
// same-directory temp file and rename over the target. Caller holds the
// registry lock. Failures are log-only — the ledger is telemetry.
void checkpoint_locked(Registry& r) {
  if (r.file_path.empty()) return;
  std::string tmp = r.file_path + ".tmp";
  std::FILE* f = std::fopen(tmp.c_str(), "w");
  if (!f) {
    log::warn("ledger", "cannot write --ledger-file " + tmp + "; checkpointing disabled");
    r.file_path.clear();
    return;
  }
  ++r.epoch;  // every rewrite advances the checkpoint epoch
  bool ok = true;
  for (const auto& [key, a] : r.accounts) {
    std::string line = account_to_json(key, a, r.epoch).dump();
    line += '\n';
    if (std::fwrite(line.data(), 1, line.size(), f) != line.size()) {
      ok = false;
      break;
    }
  }
  ok = std::fclose(f) == 0 && ok;
  if (!ok || std::rename(tmp.c_str(), r.file_path.c_str()) != 0) {
    std::remove(tmp.c_str());
    log::warn("ledger", "ledger checkpoint write failed; disabling --ledger-file sink");
    r.file_path.clear();
  }
  r.last_checkpoint = std::chrono::steady_clock::now();
  r.dirty = false;
}

// Throttled sibling for the per-target record_* paths (see Registry):
// rewrite at most once per second, mark dirty otherwise.
void maybe_checkpoint_locked(Registry& r) {
  if (r.file_path.empty()) return;
  if (std::chrono::steady_clock::now() - r.last_checkpoint >= std::chrono::seconds(1)) {
    checkpoint_locked(r);
  } else {
    r.dirty = true;
  }
}

void load_locked(Registry& r, const std::string& path) {
  auto content = util::read_file(path);
  if (!content) return;  // fresh file: nothing to restore
  size_t restored = 0, bad = 0;
  for (const std::string& line : util::split(*content, '\n')) {
    std::string t = util::trim(line);
    if (t.empty()) continue;
    json::Value v;
    try {
      v = json::Value::parse(t);
    } catch (const std::exception&) {
      ++bad;  // torn tail line (killed mid-write before the rename landed)
      continue;
    }
    Account a;
    a.kind = v.get_string("kind");
    a.ns = v.get_string("namespace");
    a.name = v.get_string("name");
    if (a.kind.empty() || a.name.empty()) {
      ++bad;
      continue;
    }
    auto num = [&](const char* k) -> double {
      const json::Value* x = v.find(k);
      return x && x->is_number() ? x->as_double() : 0.0;
    };
    r.epoch = std::max(r.epoch, static_cast<uint64_t>(num("epoch")));
    a.chips = static_cast<int64_t>(num("chips"));
    a.idle_seconds = num("idle_seconds");
    a.active_seconds = num("active_seconds");
    a.reclaimed_chip_seconds = num("reclaimed_chip_seconds");
    a.idle_streak_cycles = static_cast<uint64_t>(num("idle_streak_cycles"));
    a.pauses = static_cast<uint64_t>(num("pauses"));
    a.resumes = static_cast<uint64_t>(num("resumes"));
    a.first_seen_cycle = static_cast<uint64_t>(num("first_seen_cycle"));
    a.last_seen_cycle = static_cast<uint64_t>(num("last_seen_cycle"));
    a.paused = v.get_string("state") == "paused" || v.get_string("state") == "right_sized";
    a.right_sized = v.get_string("state") == "right_sized";
    a.idle_now = v.get_string("state") == "idle";
    if (a.paused) {
      a.paused_since_unix = static_cast<int64_t>(num("paused_since_unix"));
      a.chips_when_paused = static_cast<int64_t>(num("chips_when_paused"));
      if (a.chips_when_paused == 0) a.chips_when_paused = a.chips;
    }
    if (const json::Value* events = v.find("events"); events && events->is_array()) {
      for (const json::Value& ev : events->as_array()) {
        ScaleEventRec e;
        e.cycle = static_cast<uint64_t>(
            ev.find("cycle") && ev.find("cycle")->is_number() ? ev.find("cycle")->as_int() : 0);
        e.ts_unix = ev.find("ts_unix") && ev.find("ts_unix")->is_number()
                        ? ev.find("ts_unix")->as_int() : 0;
        e.action = ev.get_string("action");
        e.reason = ev.get_string("reason");
        e.actor = ev.get_string("actor");
        a.events.push_back(std::move(e));
        if (a.events.size() > kEventCap) a.events.pop_front();
      }
    }
    r.accounts[key_of(a.kind, a.ns, a.name)] = std::move(a);
    ++restored;
  }
  if (restored || bad) {
    log::info("ledger", "restored " + std::to_string(restored) + " workload account(s) from " +
              path + (bad ? " (" + std::to_string(bad) + " unparseable line(s) skipped)" : ""));
  }
}

void push_event_locked(Account& a, ScaleEventRec e) {
  a.events.push_back(std::move(e));
  while (a.events.size() > kEventCap) a.events.pop_front();
}

std::string fmt_value(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%.9g", v);
  return buf;
}

}  // namespace

void set_ledger_file(const std::string& path) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  r.file_path = path;
  if (path.empty()) return;
  load_locked(r, path);
  log::info("ledger", "checkpointing workload ledger to " + path);
}

void observe_cycle(uint64_t cycle, int64_t now_unix,
                   const std::vector<Observation>& idle_roots) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  // First cycle of the process integrates nothing: there is no previous
  // observation to span, and a restart from a checkpoint must reproduce
  // the stored totals exactly until new evidence accrues.
  double dt = 0;
  if (r.prev_cycle_unix > 0 && now_unix > r.prev_cycle_unix) {
    dt = static_cast<double>(now_unix - r.prev_cycle_unix);
  }
  r.prev_cycle_unix = now_unix;

  std::map<std::string, const Observation*> observed;
  for (const Observation& o : idle_roots) observed[key_of(o.kind, o.ns, o.name)] = &o;

  for (const auto& [key, o] : observed) {
    Account& a = r.accounts[key];
    if (a.kind.empty()) {
      a.kind = o->kind;
      a.ns = o->ns;
      a.name = o->name;
      a.first_seen_cycle = cycle;
    }
    a.chips = o->chips;
    a.last_seen_cycle = cycle;
  }
  for (auto& [key, a] : r.accounts) {
    bool was_observed = observed.count(key) != 0;
    if (a.first_seen_cycle == cycle && !a.paused) {
      // New this cycle: dt spans a period before the root was tracked, so
      // nothing accrues yet — the streak starts at 1.
      a.idle_now = was_observed;
      if (was_observed) a.idle_streak_cycles = 1;
      continue;
    }
    if (a.paused) {
      // Chips the pause freed keep accruing; series that outlive the
      // scaled-away pods (metric retention) never double-count as idle.
      a.reclaimed_chip_seconds += static_cast<double>(a.chips_when_paused) * dt;
    } else if (was_observed) {
      a.idle_seconds += dt;
      ++a.idle_streak_cycles;
    } else {
      a.active_seconds += dt;
      a.idle_streak_cycles = 0;
    }
    a.idle_now = was_observed;
  }
  checkpoint_locked(r);
}

void record_pause(uint64_t cycle, const std::string& kind, const std::string& ns,
                  const std::string& name, const std::string& reason) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  Account& a = r.accounts[key_of(kind, ns, name)];
  if (a.kind.empty()) {  // pause before any observation (shouldn't happen)
    a.kind = kind;
    a.ns = ns;
    a.name = name;
    a.first_seen_cycle = cycle;
  }
  if (a.paused && !a.right_sized) return;  // re-patch of an already-paused root
  if (a.paused && a.right_sized) {
    // Full pause upgrades a right-sized account: the previously freed
    // chips keep counting, and everything the current idle evidence
    // covers (the kept replicas' chips) is freed on top.
    a.right_sized = false;
    a.chips_when_paused += a.chips;
    a.paused_since_unix = util::now_unix();
    ++a.pauses;
    push_event_locked(a, {cycle, a.paused_since_unix, "paused", reason, "tpu-pruner"});
    maybe_checkpoint_locked(r);
    return;
  }
  a.paused = true;
  a.paused_since_unix = util::now_unix();
  a.chips_when_paused = a.chips;
  ++a.pauses;
  push_event_locked(a, {cycle, a.paused_since_unix, "paused", reason, "tpu-pruner"});
  maybe_checkpoint_locked(r);
}

void record_right_size(uint64_t cycle, const std::string& kind, const std::string& ns,
                       const std::string& name, int64_t freed_chips) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  Account& a = r.accounts[key_of(kind, ns, name)];
  if (a.kind.empty()) {
    a.kind = kind;
    a.ns = ns;
    a.name = name;
    a.first_seen_cycle = cycle;
  }
  if (a.paused && !a.right_sized) return;  // full pause already accounts more
  int64_t now = util::now_unix();
  if (a.paused && a.right_sized) {
    // Progressive consolidation: a deeper right-size frees more chips.
    a.chips_when_paused += freed_chips;
  } else {
    a.paused = true;
    a.right_sized = true;
    a.paused_since_unix = now;
    a.chips_when_paused = freed_chips;
  }
  ++a.pauses;
  push_event_locked(a, {cycle, now, "right_sized", "RIGHT_SIZED", "tpu-pruner"});
  maybe_checkpoint_locked(r);
}

void record_resume(uint64_t cycle, const std::string& kind, const std::string& ns,
                   const std::string& name, const std::string& actor) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  auto it = r.accounts.find(key_of(kind, ns, name));
  if (it == r.accounts.end() || !it->second.paused) return;
  Account& a = it->second;
  a.paused = false;
  a.right_sized = false;
  a.paused_since_unix = 0;
  ++a.resumes;
  push_event_locked(a, {cycle, util::now_unix(), "resumed", "", actor});
  maybe_checkpoint_locked(r);
}

void flush() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  if (r.dirty) checkpoint_locked(r);
}

std::vector<PausedRoot> paused_roots() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  std::vector<PausedRoot> out;
  for (const auto& [key, a] : r.accounts) {
    // Right-sized accounts keep serving replicas: already_paused() would
    // read them as externally resumed every sweep — skip them.
    if (a.paused && !a.right_sized) out.push_back({a.kind, a.ns, a.name});
  }
  return out;
}

std::vector<FreedAccount> freed_accounts() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  std::vector<FreedAccount> out;
  for (const auto& [key, a] : r.accounts) {
    if (!a.paused) continue;
    out.push_back({a.kind, a.ns, a.name, a.chips_when_paused, a.state()});
  }
  return out;
}

json::Value workloads_json(const std::string& query_string) {
  std::string want_ns, sort = "reclaimed";
  for (const std::string& pair : util::split(query_string, '&')) {
    size_t eq = pair.find('=');
    if (eq == std::string::npos) continue;
    std::string key = pair.substr(0, eq);
    std::string value = util::url_decode(pair.substr(eq + 1));
    if (key == "ns" || key == "namespace") want_ns = value;
    else if (key == "sort" && (value == "reclaimed" || value == "idle" || value == "chips")) {
      sort = value;
    }
  }

  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  std::vector<std::pair<const std::string*, const Account*>> rows;
  double total_idle = 0, total_active = 0, total_reclaimed = 0;
  for (const auto& [key, a] : r.accounts) {
    total_idle += a.idle_seconds;
    total_active += a.active_seconds;
    total_reclaimed += a.reclaimed_chip_seconds;
    if (!want_ns.empty() && a.ns != want_ns) continue;
    rows.push_back({&key, &a});
  }
  std::stable_sort(rows.begin(), rows.end(), [&](const auto& x, const auto& y) {
    const Account& a = *x.second;
    const Account& b = *y.second;
    if (sort == "idle") return a.idle_seconds > b.idle_seconds;
    if (sort == "chips") return a.chips > b.chips;
    return a.reclaimed_chip_seconds > b.reclaimed_chip_seconds;
  });

  json::Value workloads = json::Value::array();
  for (const auto& [key, a] : rows) workloads.push_back(account_to_json(*key, *a, r.epoch));
  json::Value totals = json::Value::object();
  totals.set("idle_seconds", json::Value(round3(total_idle)));
  totals.set("active_seconds", json::Value(round3(total_active)));
  totals.set("reclaimed_chip_seconds", json::Value(round3(total_reclaimed)));
  json::Value out = json::Value::object();
  out.set("schema", json::Value(static_cast<int64_t>(2)));
  out.set("cluster", json::Value(fleet::cluster_name()));
  out.set("epoch", json::Value(static_cast<int64_t>(r.epoch)));
  out.set("workloads", std::move(workloads));
  out.set("tracked", json::Value(static_cast<int64_t>(r.accounts.size())));
  out.set("totals", std::move(totals));
  out.set("sort", json::Value(sort));
  return out;
}

std::string render_metrics(int top_k, bool openmetrics) {
  if (top_k < 1) top_k = 1;
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);

  // Top-K accounts by chips (ties broken by key for determinism) get
  // their own series; everything else folds into one "_other" series per
  // family so totals still sum correctly but cardinality never scales
  // with fleet size.
  std::vector<std::pair<const std::string*, const Account*>> ranked;
  ranked.reserve(r.accounts.size());
  for (const auto& [key, a] : r.accounts) ranked.push_back({&key, &a});
  std::sort(ranked.begin(), ranked.end(), [](const auto& x, const auto& y) {
    if (x.second->chips != y.second->chips) return x.second->chips > y.second->chips;
    return *x.first < *y.first;
  });
  size_t named = std::min(ranked.size(), static_cast<size_t>(top_k));
  double other_idle = 0, other_reclaimed = 0;
  int64_t other_chips = 0;
  for (size_t i = named; i < ranked.size(); ++i) {
    other_idle += ranked[i].second->idle_seconds;
    other_reclaimed += ranked[i].second->reclaimed_chip_seconds;
    other_chips += ranked[i].second->chips;
  }
  bool has_other = named < ranked.size();

  auto family = [&](const std::string& name, const char* type, const std::string& help) {
    // OpenMetrics reserves the `counter` type for families whose samples
    // carry the _total suffix — the TYPE line then names the family
    // WITHOUT it; the classic 0.0.4 format types the full sample name.
    std::string fam = name;
    if (openmetrics && std::string(type) == "counter" && fam.size() > 6 &&
        fam.compare(fam.size() - 6, 6, "_total") == 0) {
      fam = fam.substr(0, fam.size() - 6);
    }
    return "# HELP " + fam + " " + help + "\n# TYPE " + fam + " " + type + "\n";
  };
  auto esc = [](const std::string& s) { return json::escape(s); };

  std::string body;
  body += family("tpu_pruner_workload_idle_seconds_total", "counter",
                 "Cumulative seconds a workload's TPU pods were observed idle "
                 "(top-K by chips; _other = rollup of the rest)");
  for (size_t i = 0; i < named; ++i) {
    body += "tpu_pruner_workload_idle_seconds_total{workload=\"" + esc(*ranked[i].first) +
            "\"} " + fmt_value(ranked[i].second->idle_seconds) + "\n";
  }
  if (has_other) {
    body += "tpu_pruner_workload_idle_seconds_total{workload=\"_other\"} " +
            fmt_value(other_idle) + "\n";
  }

  body += family("tpu_pruner_workload_reclaimed_chip_seconds_total", "counter",
                 "Cumulative chip-seconds reclaimed: chips x time the root spent "
                 "scaled-to-zero after the pruner paused it");
  for (size_t i = 0; i < named; ++i) {
    body += "tpu_pruner_workload_reclaimed_chip_seconds_total{workload=\"" +
            esc(*ranked[i].first) + "\"} " +
            fmt_value(ranked[i].second->reclaimed_chip_seconds) + "\n";
  }
  if (has_other) {
    body += "tpu_pruner_workload_reclaimed_chip_seconds_total{workload=\"_other\"} " +
            fmt_value(other_reclaimed) + "\n";
  }

  body += family("tpu_pruner_workload_chips", "gauge",
                 "Chips a tracked workload requests, labelled with its current "
                 "state (idle|active|paused; _other rollup carries state=_other)");
  for (size_t i = 0; i < named; ++i) {
    body += "tpu_pruner_workload_chips{workload=\"" + esc(*ranked[i].first) +
            "\",state=\"" + ranked[i].second->state() + "\"} " +
            std::to_string(ranked[i].second->chips) + "\n";
  }
  if (has_other) {
    body += "tpu_pruner_workload_chips{workload=\"_other\",state=\"_other\"} " +
            std::to_string(other_chips) + "\n";
  }

  body += family("tpu_pruner_workloads_tracked", "gauge",
                 "Workload accounts the utilization ledger tracks");
  body += "tpu_pruner_workloads_tracked " + std::to_string(r.accounts.size()) + "\n";
  return body;
}

std::vector<std::string> metric_families() {
  return {
      "tpu_pruner_workload_idle_seconds_total",
      "tpu_pruner_workload_reclaimed_chip_seconds_total",
      "tpu_pruner_workload_chips",
      "tpu_pruner_workloads_tracked",
  };
}

void reset_for_test() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  r.accounts.clear();
  r.prev_cycle_unix = 0;
  r.file_path.clear();
  r.epoch = 0;
}

}  // namespace tpupruner::ledger
