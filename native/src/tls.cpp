#include "tls.hpp"

#include <dlfcn.h>

#include <cerrno>
#include <cstdio>
#include <mutex>
#include <stdexcept>

namespace tpupruner::tls {

namespace {

// Subset of the OpenSSL 3 ABI used by a verifying TLS client.
struct Api {
  void* (*TLS_client_method)();
  void* (*SSL_CTX_new)(void*);
  void (*SSL_CTX_free)(void*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_set_default_verify_paths)(void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_pending)(const void*);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_shutdown)(void*);
  long (*SSL_ctrl)(void*, int, long, void*);
  int (*SSL_get_error)(const void*, int);
  long (*SSL_get_verify_result)(const void*);
  int (*SSL_set1_host)(void*, const char*);
  int (*SSL_set_alpn_protos)(void*, const unsigned char*, unsigned int);
  void (*SSL_get0_alpn_selected)(const void*, const unsigned char**, unsigned int*);
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);

  bool ok = false;
};

constexpr int kSslVerifyNone = 0x00;
constexpr int kSslVerifyPeer = 0x01;
constexpr int kSslCtrlSetTlsextHostname = 55;
constexpr long kTlsextNametypeHostName = 0;
constexpr long kX509VOk = 0;
constexpr int kSslErrorZeroReturn = 6;

const Api& api() {
  static Api a = [] {
    Api out{};
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    void* crypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (!ssl || !crypto) return out;
    bool all = true;
    auto load = [&](auto& fn, const char* name, void* lib) {
      fn = reinterpret_cast<std::decay_t<decltype(fn)>>(dlsym(lib, name));
      if (!fn) all = false;
    };
    load(out.TLS_client_method, "TLS_client_method", ssl);
    load(out.SSL_CTX_new, "SSL_CTX_new", ssl);
    load(out.SSL_CTX_free, "SSL_CTX_free", ssl);
    load(out.SSL_CTX_set_verify, "SSL_CTX_set_verify", ssl);
    load(out.SSL_CTX_set_default_verify_paths, "SSL_CTX_set_default_verify_paths", ssl);
    load(out.SSL_CTX_load_verify_locations, "SSL_CTX_load_verify_locations", ssl);
    load(out.SSL_new, "SSL_new", ssl);
    load(out.SSL_free, "SSL_free", ssl);
    load(out.SSL_set_fd, "SSL_set_fd", ssl);
    load(out.SSL_connect, "SSL_connect", ssl);
    load(out.SSL_read, "SSL_read", ssl);
    load(out.SSL_pending, "SSL_pending", ssl);
    load(out.SSL_write, "SSL_write", ssl);
    load(out.SSL_shutdown, "SSL_shutdown", ssl);
    load(out.SSL_ctrl, "SSL_ctrl", ssl);
    load(out.SSL_get_error, "SSL_get_error", ssl);
    load(out.SSL_get_verify_result, "SSL_get_verify_result", ssl);
    load(out.SSL_set1_host, "SSL_set1_host", ssl);
    load(out.SSL_set_alpn_protos, "SSL_set_alpn_protos", ssl);
    load(out.SSL_get0_alpn_selected, "SSL_get0_alpn_selected", ssl);
    load(out.ERR_get_error, "ERR_get_error", crypto);
    load(out.ERR_error_string_n, "ERR_error_string_n", crypto);
    out.ok = all;
    return out;
  }();
  return a;
}

std::string last_error(const std::string& what) {
  const Api& a = api();
  char buf[256] = "unknown";
  if (a.ok) {
    unsigned long code = a.ERR_get_error();
    if (code) a.ERR_error_string_n(code, buf, sizeof(buf));
  }
  return "tls: " + what + ": " + buf;
}

}  // namespace

bool available() { return api().ok; }

Conn::Conn(int fd, const std::string& sni_host, bool verify, const std::string& ca_file,
           const std::string& alpn) {
  // Single-protocol form: offering exactly one protocol and requiring it
  // be selected (the gRPC "h2" contract this ctor always carried).
  std::vector<std::string> protos;
  if (!alpn.empty()) protos.push_back(alpn);
  init(fd, sni_host, verify, ca_file, protos, /*require_alpn=*/true);
}

Conn::Conn(int fd, const std::string& sni_host, bool verify, const std::string& ca_file,
           const std::vector<std::string>& alpn_protos, bool require_alpn) {
  init(fd, sni_host, verify, ca_file, alpn_protos, require_alpn);
}

void Conn::init(int fd, const std::string& sni_host, bool verify, const std::string& ca_file,
                const std::vector<std::string>& alpn_protos, bool require_alpn) {
  const Api& a = api();
  if (!a.ok) {
    throw std::runtime_error(
        "tls: libssl.so.3 unavailable in this environment (https unsupported; "
        "use http or install OpenSSL 3)");
  }
  ctx_ = a.SSL_CTX_new(a.TLS_client_method());
  if (!ctx_) throw std::runtime_error(last_error("SSL_CTX_new"));

  if (verify) {
    a.SSL_CTX_set_verify(ctx_, kSslVerifyPeer, nullptr);
    if (!ca_file.empty()) {
      if (a.SSL_CTX_load_verify_locations(ctx_, ca_file.c_str(), nullptr) != 1) {
        std::string err = last_error("load CA bundle " + ca_file);
        a.SSL_CTX_free(ctx_);
        ctx_ = nullptr;
        throw std::runtime_error(err);
      }
    } else {
      a.SSL_CTX_set_default_verify_paths(ctx_);
    }
  } else {
    a.SSL_CTX_set_verify(ctx_, kSslVerifyNone, nullptr);
  }

  ssl_ = a.SSL_new(ctx_);
  if (!ssl_) {
    a.SSL_CTX_free(ctx_);
    ctx_ = nullptr;
    throw std::runtime_error(last_error("SSL_new"));
  }
  a.SSL_set_fd(ssl_, fd);
  a.SSL_ctrl(ssl_, kSslCtrlSetTlsextHostname, kTlsextNametypeHostName,
             const_cast<char*>(sni_host.c_str()));
  if (verify) a.SSL_set1_host(ssl_, sni_host.c_str());
  if (!alpn_protos.empty()) {
    // RFC 7301 wire format: length-prefixed protocol names, in client
    // preference order.
    std::string wire;
    for (const std::string& p : alpn_protos) {
      wire.push_back(static_cast<char>(p.size()));
      wire += p;
    }
    // Returns 0 on success (unlike most SSL_* APIs). A failure here means
    // the handshake would proceed WITHOUT offering the protocol, and the
    // post-handshake check below would then blame the server ("did not
    // negotiate ALPN") for a client-side setup error — fail distinctly.
    if (a.SSL_set_alpn_protos(ssl_, reinterpret_cast<const unsigned char*>(wire.data()),
                              static_cast<unsigned int>(wire.size())) != 0) {
      std::string err =
          last_error("failed to set ALPN protocol list \"" + alpn_protos.front() + "\"");
      a.SSL_free(ssl_);
      a.SSL_CTX_free(ctx_);
      ssl_ = ctx_ = nullptr;
      throw std::runtime_error(err);
    }
  }

  int rc = a.SSL_connect(ssl_);
  if (rc != 1) {
    std::string err = last_error("handshake failed");
    if (verify && a.SSL_get_verify_result(ssl_) != kX509VOk) {
      err += " (certificate verification failed)";
    }
    a.SSL_free(ssl_);
    a.SSL_CTX_free(ctx_);
    ssl_ = ctx_ = nullptr;
    throw std::runtime_error(err);
  }
  if (!alpn_protos.empty()) {
    const unsigned char* sel = nullptr;
    unsigned int sel_len = 0;
    a.SSL_get0_alpn_selected(ssl_, &sel, &sel_len);
    if (sel) alpn_selected_.assign(reinterpret_cast<const char*>(sel), sel_len);
    bool offered = false;
    for (const std::string& p : alpn_protos) offered = offered || p == alpn_selected_;
    // gRPC servers require the negotiated protocol, not just a working
    // TLS session: no/different selection means the peer would reset the
    // h2 stream anyway — fail with the actionable error instead. The
    // multi-protocol (require_alpn=false) form lets a no-selection
    // handshake through: the shared transport treats "" as HTTP/1.1.
    if (require_alpn && (!sel || !offered)) {
      std::string err =
          "tls: server did not negotiate ALPN \"" + alpn_protos.front() + "\" (selected " +
          (sel ? "\"" + alpn_selected_ + "\"" : "nothing") +
          "); the endpoint does not speak HTTP/2 — is it a gRPC listener?";
      a.SSL_free(ssl_);
      a.SSL_CTX_free(ctx_);
      ssl_ = ctx_ = nullptr;
      throw std::runtime_error(err);
    }
  }
}

Conn::~Conn() {
  const Api& a = api();
  if (ssl_) {
    a.SSL_shutdown(ssl_);
    a.SSL_free(ssl_);
  }
  if (ctx_) a.SSL_CTX_free(ctx_);
}

size_t Conn::read(char* buf, size_t n) {
  const Api& a = api();
  int rc = a.SSL_read(ssl_, buf, static_cast<int>(n));
  if (rc > 0) return static_cast<size_t>(rc);
  int err = a.SSL_get_error(ssl_, rc);
  if (err == kSslErrorZeroReturn) return 0;  // clean close_notify
  throw std::runtime_error(last_error("read failed"));
}

Conn::IoStatus Conn::read_nb(char* buf, size_t n, size_t& got) {
  const Api& a = api();
  got = 0;
  errno = 0;
  int rc = a.SSL_read(ssl_, buf, static_cast<int>(n));
  if (rc > 0) {
    got = static_cast<size_t>(rc);
    return IoStatus::Data;
  }
  int err = a.SSL_get_error(ssl_, rc);
  if (err == kSslErrorZeroReturn) return IoStatus::Eof;
  constexpr int kWantRead = 2, kWantWrite = 3, kSyscall = 5;
  if (err == kWantRead || err == kWantWrite) return IoStatus::WouldBlock;
  if (err == kSyscall && (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)) {
    return IoStatus::WouldBlock;
  }
  // SSL_ERROR_SYSCALL with errno 0 is the peer dropping without
  // close_notify — a dead session, not a retryable wait.
  throw std::runtime_error(last_error("read failed"));
}

bool Conn::pending() const {
  const Api& a = api();
  return a.SSL_pending(ssl_) > 0;
}

void Conn::write_all(const char* buf, size_t n) {
  const Api& a = api();
  size_t off = 0;
  while (off < n) {
    int rc = a.SSL_write(ssl_, buf + off, static_cast<int>(n - off));
    if (rc <= 0) throw std::runtime_error(last_error("write failed"));
    off += static_cast<size_t>(rc);
  }
}

}  // namespace tpupruner::tls
