#include "otlp.hpp"

#include <cctype>

#include "otlp_grpc.hpp"
#include "tpupruner/fleet.hpp"
#include "tpupruner/http.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::otlp {

using json::Value;

namespace {

Value data_point(uint64_t value, int64_t start_nanos, int64_t now_nanos) {
  Value dp = Value::object();
  dp.set("asInt", Value(std::to_string(value)));  // OTLP JSON: int64 as string
  dp.set("startTimeUnixNano", Value(std::to_string(start_nanos)));
  dp.set("timeUnixNano", Value(std::to_string(now_nanos)));
  return dp;
}

// service.name = tpu-pruner (reference Resource, main.rs:139-143), plus
// the fleet cluster identity so pushed telemetry merges like the pull
// surfaces do.
Value service_resource() {
  Value attr = Value::object();
  attr.set("key", Value("service.name"));
  attr.set("value", Value(json::Object{{"stringValue", Value("tpu-pruner")}}));
  Value cluster = Value::object();
  cluster.set("key", Value("cluster"));
  cluster.set("value",
              Value(json::Object{{"stringValue", Value(fleet::cluster_name())}}));
  Value resource = Value::object();
  resource.set("attributes", Value(json::Array{std::move(attr), std::move(cluster)}));
  return resource;
}

// ── span buffer ──
std::atomic<bool> g_recording{false};
std::mutex g_spans_mutex;
std::vector<FinishedSpan> g_spans;
uint64_t g_spans_dropped = 0;
constexpr size_t kSpanBufferCap = 4096;

void buffer_span(FinishedSpan&& span) {
  std::lock_guard<std::mutex> lock(g_spans_mutex);
  if (g_spans.size() >= kSpanBufferCap) {
    ++g_spans_dropped;  // exporter stalled or absent; telemetry never blocks
    return;
  }
  g_spans.push_back(std::move(span));
}

std::vector<FinishedSpan> drain_spans() {
  std::lock_guard<std::mutex> lock(g_spans_mutex);
  std::vector<FinishedSpan> out;
  out.swap(g_spans);
  if (g_spans_dropped > 0) {
    log::warn("otlp", "OTLP span buffer overflowed; dropped " + std::to_string(g_spans_dropped) +
              " spans");
    g_spans_dropped = 0;
  }
  return out;
}

}  // namespace

bool recording() { return g_recording.load(std::memory_order_relaxed); }
void set_recording_for_test(bool on) { g_recording.store(on); }
std::vector<FinishedSpan> drain_spans_for_test() { return drain_spans(); }

void buffer_finished_span(FinishedSpan&& span) {
  if (!recording()) return;
  buffer_span(std::move(span));
}

std::string traceparent(const SpanContext& ctx) {
  if (ctx.trace_id.empty() || ctx.span_id.empty()) return "";
  // version 00, sampled flag 01 (these spans are all exported).
  return "00-" + ctx.trace_id + "-" + ctx.span_id + "-01";
}

Span::Span(std::string name, const SpanContext* parent) : enabled_(recording()) {
  if (!enabled_) return;
  rec_.name = std::move(name);
  std::string rand = util::random_hex32();
  ctx_.trace_id = parent ? parent->trace_id : rand;
  ctx_.span_id = rand.substr(16);
  rec_.trace_id = ctx_.trace_id;
  rec_.span_id = ctx_.span_id;
  if (parent) rec_.parent_span_id = parent->span_id;
  rec_.start_nanos = util::now_unix_nanos();
}

Span::~Span() {
  if (!enabled_) return;
  rec_.end_nanos = util::now_unix_nanos();
  buffer_span(std::move(rec_));
}

void Span::attr(std::string key, std::string value) {
  if (enabled_) rec_.str_attrs.emplace_back(std::move(key), std::move(value));
}

void Span::attr(std::string key, int64_t value) {
  if (enabled_) rec_.int_attrs.emplace_back(std::move(key), value);
}

void Span::set_error(std::string message) {
  if (!enabled_) return;
  rec_.error = true;
  rec_.error_message = std::move(message);
}

Exporter::Exporter(std::string endpoint, int interval_ms)
    : interval_ms_(interval_ms),
      start_unix_nanos_(util::now_unix() * 1000000000ll) {
  while (!endpoint.empty() && endpoint.back() == '/') endpoint.pop_back();

  // Per-signal protocol (OTEL spec): signal-specific var wins, then the
  // base var, default http (this exporter's JSON flavor). "grpc" selects
  // the OTLP/gRPC transport (otlp_grpc.cpp) — the reference's transport
  // (main.rs:146-155) — over plaintext h2c.
  auto signal_grpc = [](const char* signal_var) -> bool {
    std::string p;
    if (auto v = util::env(signal_var); v && !v->empty()) p = *v;
    else if (auto v = util::env("OTEL_EXPORTER_OTLP_PROTOCOL"); v && !v->empty()) p = *v;
    return p.rfind("grpc", 0) == 0;
  };
  metrics_grpc_ = signal_grpc("OTEL_EXPORTER_OTLP_METRICS_PROTOCOL");
  traces_grpc_ = signal_grpc("OTEL_EXPORTER_OTLP_TRACES_PROTOCOL");

  // OTEL_EXPORTER_OTLP[_SIGNAL]_HEADERS (OTEL spec): comma-separated
  // key=value pairs, values percent-decoded (W3C-baggage octets) — how
  // managed collectors take auth (e.g. "authorization=Bearer%20tok",
  // "api-key=..."). Applied on both transports; the reference's
  // opentelemetry-otlp honors the same variables.
  auto signal_headers = [](const char* signal_var) {
    std::vector<std::pair<std::string, std::string>> out;
    std::string raw;
    if (auto v = util::env(signal_var); v && !v->empty()) raw = *v;
    else if (auto v = util::env("OTEL_EXPORTER_OTLP_HEADERS"); v && !v->empty()) raw = *v;
    for (const std::string& pair : util::split(raw, ',')) {
      size_t eq = pair.find('=');
      if (eq == std::string::npos) continue;  // malformed entry: skip, per spec
      std::string key = util::trim(pair.substr(0, eq));
      std::string value = util::url_decode(util::trim(pair.substr(eq + 1)));
      // Decoded octets go verbatim into HTTP/1.1 header lines and HPACK
      // literals: a CR/LF (or other control char) in the value would split
      // the request / trip h2 PROTOCOL_ERROR, and a non-token key emits an
      // invalid header name — reject such entries loudly instead of
      // corrupting every export with no hint the env value is the cause.
      auto token_key = [](const std::string& k) {
        if (k.empty()) return false;
        for (unsigned char c : k) {
          bool tchar = std::isalnum(c) || std::string_view("!#$%&'*+-.^_`|~")
                                                  .find(static_cast<char>(c)) !=
                                              std::string_view::npos;
          if (!tchar) return false;
        }
        return true;
      };
      auto clean_value = [](const std::string& v) {
        for (unsigned char c : v)
          if (c < 0x20 || c == 0x7f) return false;
        return true;
      };
      if (!token_key(key) || !clean_value(value)) {
        // Key only — the value is typically a credential (that's what this
        // env is FOR) and must never land in logs, malformed or not. The
        // key itself may be rejected FOR containing raw control bytes, so
        // escape non-printables before they reach stderr (log injection).
        std::string safe_key;
        for (unsigned char c : key) {
          if (c >= 0x20 && c < 0x7f) {
            safe_key.push_back(static_cast<char>(c));
          } else {
            char buf[5];
            std::snprintf(buf, sizeof(buf), "\\x%02x", c);
            safe_key += buf;
          }
        }
        log::warn("otlp", "ignoring OTLP header entry with invalid key or "
                  "control characters in value (key: '" + safe_key +
                  "', value redacted)");
        continue;
      }
      out.emplace_back(std::move(key), std::move(value));
    }
    return out;
  };
  metrics_headers_ = signal_headers("OTEL_EXPORTER_OTLP_METRICS_HEADERS");
  traces_headers_ = signal_headers("OTEL_EXPORTER_OTLP_TRACES_HEADERS");

  // OTEL_EXPORTER_OTLP[_SIGNAL]_CERTIFICATE (OTEL spec): CA bundle for
  // TLS endpoints, same signal-specific-then-base fallback as every
  // other OTLP env this exporter reads.
  auto signal_ca = [](const char* signal_var) -> std::string {
    if (auto v = util::env(signal_var); v && !v->empty()) return *v;
    if (auto v = util::env("OTEL_EXPORTER_OTLP_CERTIFICATE"); v && !v->empty()) return *v;
    return "";
  };
  metrics_ca_ = signal_ca("OTEL_EXPORTER_OTLP_METRICS_CERTIFICATE");
  traces_ca_ = signal_ca("OTEL_EXPORTER_OTLP_TRACES_CERTIFICATE");

  // Per-signal endpoints (OTEL spec; the reference documents exactly this
  // env shape, README.md:79-98): signal endpoint vars are full URLs used
  // verbatim; `none` exporters disable the signal. For gRPC the service
  // path is fixed by the protocol, so no /v1/* suffix is appended.
  auto signal_url = [&](const char* endpoint_var, const char* exporter_var,
                        const char* default_path, bool grpc) -> std::string {
    if (auto ex = util::env(exporter_var); ex && *ex == "none") return "";
    if (auto url = util::env(endpoint_var); url && !url->empty()) return *url;
    // No signal override and no base endpoint → the signal is off (a
    // signal-only env configuration leaves the other signal disabled).
    if (endpoint.empty()) return "";
    // A grpc:// base endpoint selects the gRPC transport below; its
    // service path is fixed, so the HTTP /v1/* suffix must not stick.
    bool scheme_grpc = endpoint.rfind("grpc", 0) == 0;
    return (grpc || scheme_grpc) ? endpoint : endpoint + default_path;
  };
  metrics_url_ = signal_url("OTEL_EXPORTER_OTLP_METRICS_ENDPOINT",
                            "OTEL_METRICS_EXPORTER", "/v1/metrics", metrics_grpc_);
  traces_url_ = signal_url("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT",
                           "OTEL_TRACES_EXPORTER", "/v1/traces", traces_grpc_);

  // A grpc:// scheme on the endpoint also selects the gRPC transport
  // (normalized to http for parsing — plaintext h2c); grpcs:// and
  // https-with-grpc-protocol select gRPC over TLS (ALPN "h2" handshake in
  // otlp_grpc.cpp, tonic https-endpoint parity: main.rs:146-155).
  auto normalize = [](std::string& url, bool& grpc, const char*) {
    if (url.rfind("grpc://", 0) == 0) {
      url = "http://" + url.substr(7);
      grpc = true;
    } else if (url.rfind("grpcs://", 0) == 0) {
      url = "https://" + url.substr(8);
      grpc = true;
    }
  };
  normalize(metrics_url_, metrics_grpc_, "metrics");
  normalize(traces_url_, traces_grpc_, "traces");

  // Drop-in guardrail, inverted from rounds 2-3: with an HTTP-protocol
  // signal pointed at :4317 (the collector's gRPC port — the reference's
  // own deploy example, README.md:92-98), the fix now exists in-process:
  // set OTEL_EXPORTER_OTLP_PROTOCOL=grpc.
  auto warn_if_grpc_port = [](const std::string& url, bool grpc, const char* signal) {
    if (url.empty() || grpc) return;
    std::string authority = url;
    if (auto p = authority.find("://"); p != std::string::npos) authority = authority.substr(p + 3);
    if (auto p = authority.find('/'); p != std::string::npos) authority = authority.substr(0, p);
    if (authority.size() >= 5 && authority.compare(authority.size() - 5, 5, ":4317") == 0) {
      log::warn("otlp", std::string(signal) + " endpoint " + url +
                " looks like an OTLP/gRPC collector port but the transport is "
                "OTLP/HTTP JSON; a gRPC-only listener will reject it silently. "
                "Set OTEL_EXPORTER_OTLP_PROTOCOL=grpc (supported, h2c) or "
                "point at the collector's HTTP port (default 4318)");
    }
  };
  warn_if_grpc_port(metrics_url_, metrics_grpc_, "metrics");
  warn_if_grpc_port(traces_url_, traces_grpc_, "traces");

  if (metrics_url_.empty() && traces_url_.empty()) {
    // Reached via OTEL_*_EXPORTER=none on both signals.
    log::info("otlp", "OTLP export: no active signal; exporter inert");
    return;  // no thread, no recording — a fully inert exporter
  }
  if (!traces_url_.empty()) g_recording.store(true);
  thread_ = std::thread([this] { loop(); });
  log::info("otlp", "OTLP export: metrics -> " +
            (metrics_url_.empty() ? "(off)"
                                  : metrics_url_ + (metrics_grpc_ ? " [grpc]" : " [http/json]")) +
            ", traces -> " +
            (traces_url_.empty() ? "(off)"
                                 : traces_url_ + (traces_grpc_ ? " [grpc]" : " [http/json]")) +
            " every " + std::to_string(interval_ms_) + "ms");
}

std::unique_ptr<Exporter> Exporter::from_config(const std::string& cli_endpoint) {
  auto env_nonempty = [](const char* var) -> std::string {
    if (auto v = util::env(var); v && !v->empty()) return *v;
    return "";
  };
  std::string base = cli_endpoint;
  if (base.empty()) base = env_nonempty("OTEL_EXPORTER_OTLP_ENDPOINT");
  bool signal_set = !env_nonempty("OTEL_EXPORTER_OTLP_METRICS_ENDPOINT").empty() ||
                    !env_nonempty("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT").empty();
  if (base.empty() && !signal_set) return nullptr;

  int interval_ms = 15000;
  if (auto iv = util::env("OTEL_METRIC_EXPORT_INTERVAL")) {
    try {
      interval_ms = std::max(100, std::stoi(*iv));
    } catch (const std::exception&) {
      log::warn("otlp", "ignoring unparseable OTEL_METRIC_EXPORT_INTERVAL: " + *iv);
    }
  }
  return std::make_unique<Exporter>(std::move(base), interval_ms);
}

Exporter::~Exporter() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stop_.store(true);
    cv_.notify_all();
  }
  if (thread_.joinable()) thread_.join();
  g_recording.store(false);
  export_once();  // shutdown flush (reference OtelGuard::drop, main.rs:262-271)
}

void Exporter::loop() {
  std::unique_lock<std::mutex> lock(mutex_);
  while (!stop_.load()) {
    cv_.wait_for(lock, std::chrono::milliseconds(interval_ms_),
                 [&] { return stop_.load(); });
    if (stop_.load()) break;
    lock.unlock();
    export_once();
    lock.lock();
  }
}

bool Exporter::export_once() {
  bool metrics_ok = metrics_url_.empty() || export_metrics(util::now_unix_nanos());
  bool traces_ok = traces_url_.empty() || export_traces();
  return metrics_ok && traces_ok;
}

bool Exporter::export_metrics(int64_t now_nanos) {
  if (metrics_grpc_) {
    return grpc_post(metrics_url_, otlp_grpc::kMetricsPath,
                     otlp_grpc::encode_metrics_request(
                         log::counters_snapshot(), start_unix_nanos_, now_nanos),
                     metrics_headers_, metrics_ca_);
  }
  Value metrics = Value::array();
  for (const auto& [name, counter] : log::counters_snapshot()) {
    Value metric = Value::object();
    metric.set("name", Value("tpu_pruner." + name));
    Value points = Value::array();
    points.push_back(data_point(counter.value, start_unix_nanos_, now_nanos));
    // Kind fixed at the call site (the reference's monotonic_counter.* vs
    // counter.* split, main.rs:300-321, 349-365).
    if (counter.gauge) {
      Value gauge = Value::object();
      gauge.set("dataPoints", std::move(points));
      metric.set("gauge", std::move(gauge));
    } else {
      Value sum = Value::object();
      sum.set("dataPoints", std::move(points));
      sum.set("aggregationTemporality", Value(2));  // CUMULATIVE
      sum.set("isMonotonic", Value(true));
      metric.set("sum", std::move(sum));
    }
    metrics.push_back(std::move(metric));
  }

  Value scope_metrics = Value::object();
  scope_metrics.set("scope", Value(json::Object{{"name", Value("tpu_pruner")}}));
  scope_metrics.set("metrics", std::move(metrics));

  Value rm = Value::object();
  rm.set("resource", service_resource());
  rm.set("scopeMetrics", Value(json::Array{std::move(scope_metrics)}));

  Value body = Value::object();
  body.set("resourceMetrics", Value(json::Array{std::move(rm)}));
  return post(metrics_url_, body.dump(), metrics_headers_, metrics_ca_);
}

bool Exporter::export_traces() {
  std::vector<FinishedSpan> finished = drain_spans();
  if (finished.empty()) return true;

  if (traces_grpc_) {
    return grpc_post(traces_url_, otlp_grpc::kTracesPath,
                     otlp_grpc::encode_traces_request(finished), traces_headers_,
                     traces_ca_);
  }
  Value spans = Value::array();
  for (FinishedSpan& fs : finished) {
    Value span = Value::object();
    span.set("traceId", Value(std::move(fs.trace_id)));
    span.set("spanId", Value(std::move(fs.span_id)));
    if (!fs.parent_span_id.empty()) span.set("parentSpanId", Value(std::move(fs.parent_span_id)));
    span.set("name", Value(std::move(fs.name)));
    span.set("kind", Value(1));  // SPAN_KIND_INTERNAL
    span.set("startTimeUnixNano", Value(std::to_string(fs.start_nanos)));
    span.set("endTimeUnixNano", Value(std::to_string(fs.end_nanos)));
    Value attrs = Value::array();
    for (auto& [k, v] : fs.str_attrs) {
      Value a = Value::object();
      a.set("key", Value(std::move(k)));
      a.set("value", Value(json::Object{{"stringValue", Value(std::move(v))}}));
      attrs.push_back(std::move(a));
    }
    for (auto& [k, v] : fs.int_attrs) {
      Value a = Value::object();
      a.set("key", Value(std::move(k)));
      a.set("value", Value(json::Object{{"intValue", Value(std::to_string(v))}}));
      attrs.push_back(std::move(a));
    }
    span.set("attributes", std::move(attrs));
    if (!fs.events.empty()) {
      Value events = Value::array();
      for (SpanEvent& ev : fs.events) {
        Value e = Value::object();
        e.set("timeUnixNano", Value(std::to_string(ev.time_nanos)));
        e.set("name", Value(std::move(ev.name)));
        Value eattrs = Value::array();
        for (auto& [k, v] : ev.str_attrs) {
          Value a = Value::object();
          a.set("key", Value(std::move(k)));
          a.set("value", Value(json::Object{{"stringValue", Value(std::move(v))}}));
          eattrs.push_back(std::move(a));
        }
        for (auto& [k, v] : ev.int_attrs) {
          Value a = Value::object();
          a.set("key", Value(std::move(k)));
          a.set("value", Value(json::Object{{"intValue", Value(std::to_string(v))}}));
          eattrs.push_back(std::move(a));
        }
        e.set("attributes", std::move(eattrs));
        events.push_back(std::move(e));
      }
      span.set("events", std::move(events));
    }
    Value status = Value::object();
    if (fs.error) {
      status.set("code", Value(2));  // STATUS_CODE_ERROR
      status.set("message", Value(std::move(fs.error_message)));
    }
    span.set("status", std::move(status));
    spans.push_back(std::move(span));
  }

  Value scope_spans = Value::object();
  scope_spans.set("scope", Value(json::Object{{"name", Value("tpu_pruner")}}));
  scope_spans.set("spans", std::move(spans));

  Value rs = Value::object();
  rs.set("resource", service_resource());
  rs.set("scopeSpans", Value(json::Array{std::move(scope_spans)}));

  Value body = Value::object();
  body.set("resourceSpans", Value(json::Array{std::move(rs)}));
  return post(traces_url_, body.dump(), traces_headers_, traces_ca_);
}

bool Exporter::grpc_post(const std::string& url, const char* path,
                         const std::string& proto,
                         const std::vector<std::pair<std::string, std::string>>& headers,
                         const std::string& ca_file) {
  auto parsed = http::parse_url(url);
  if (!parsed) {
    log::warn("otlp", "OTLP/gRPC endpoint unparseable: " + url);
    return false;
  }
  otlp_grpc::TlsOptions tls;
  if (parsed->scheme == "https") {
    tls.use_tls = true;
    tls.ca_file = ca_file;  // per-signal OTEL_*_CERTIFICATE chain (init)
  }
  otlp_grpc::CallResult res =
      otlp_grpc::unary_call(parsed->host, parsed->port, path, proto, 5000, headers, tls);
  if (!res.ok) {
    log::warn("otlp", "OTLP/gRPC export to " + url + path + " failed: " +
              (!res.error.empty() ? res.error
                                  : "grpc-status " + std::to_string(res.grpc_status) +
                                        (res.grpc_message.empty() ? "" : " (" + res.grpc_message + ")")));
    return false;
  }
  if (res.status_undecoded) {
    // warn, not debug: an undecodable grpc-status could hide a collector
    // rejection behind the inferred success (round-4 advisor finding).
    log::warn("otlp", "OTLP/gRPC export to " + url + path + ": trailers "
              "present but grpc-status undecodable (malformed huffman); "
              "success inferred from clean close on HTTP 200 — a rejection "
              "would be invisible");
  }
  return true;
}

bool Exporter::post(const std::string& url, const std::string& body_json,
                    const std::vector<std::pair<std::string, std::string>>& headers,
                    const std::string& ca_file) {
  try {
    // Same OTEL_EXPORTER_OTLP[_SIGNAL]_CERTIFICATE chain as the gRPC
    // transport — the spec defines the env for both.
    http::Client client(http::TlsMode::Verify, ca_file);
    http::Request req;
    req.method = "POST";
    req.url = url;
    req.headers.push_back({"Content-Type", "application/json"});
    for (const auto& [k, v] : headers) req.headers.push_back({k, v});
    req.body = body_json;
    req.timeout_ms = 5000;
    http::Response resp = client.request(req);
    if (resp.status < 200 || resp.status >= 300) {
      log::warn("otlp", "OTLP export to " + url + " got HTTP " + std::to_string(resp.status));
      return false;
    }
    return true;
  } catch (const std::exception& e) {
    log::warn("otlp", "OTLP export to " + url + " failed: " + e.what());
    return false;
  }
}

}  // namespace tpupruner::otlp
