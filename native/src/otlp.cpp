#include "otlp.hpp"

#include "tpupruner/http.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::otlp {

using json::Value;

namespace {

// Counter names ending in "returned_*" are last-cycle gauges; the rest are
// monotonic sums (the reference's monotonic_counter.* vs counter.* split,
// main.rs:300-321, 349-365).
bool is_gauge(const std::string& name) {
  return name.find("returned") != std::string::npos;
}

Value data_point(uint64_t value, int64_t start_nanos, int64_t now_nanos) {
  Value dp = Value::object();
  dp.set("asInt", Value(std::to_string(value)));  // OTLP JSON: int64 as string
  dp.set("startTimeUnixNano", Value(std::to_string(start_nanos)));
  dp.set("timeUnixNano", Value(std::to_string(now_nanos)));
  return dp;
}

}  // namespace

Exporter::Exporter(std::string endpoint, int interval_ms)
    : endpoint_(std::move(endpoint)),
      interval_ms_(interval_ms),
      start_unix_nanos_(util::now_unix() * 1000000000ll) {
  while (!endpoint_.empty() && endpoint_.back() == '/') endpoint_.pop_back();
  thread_ = std::thread([this] { loop(); });
  log::info("OTLP metrics export to " + endpoint_ + "/v1/metrics every " +
            std::to_string(interval_ms_) + "ms");
}

Exporter::~Exporter() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stop_.store(true);
    cv_.notify_all();
  }
  if (thread_.joinable()) thread_.join();
  export_once();  // shutdown flush (reference OtelGuard::drop, main.rs:262-271)
}

void Exporter::loop() {
  std::unique_lock<std::mutex> lock(mutex_);
  while (!stop_.load()) {
    cv_.wait_for(lock, std::chrono::milliseconds(interval_ms_),
                 [&] { return stop_.load(); });
    if (stop_.load()) break;
    lock.unlock();
    export_once();
    lock.lock();
  }
}

bool Exporter::export_once() {
  int64_t now_nanos = util::now_unix() * 1000000000ll;
  Value metrics = Value::array();
  for (const auto& [name, value] : log::counters_snapshot()) {
    Value metric = Value::object();
    metric.set("name", Value("tpu_pruner." + name));
    Value points = Value::array();
    points.push_back(data_point(value, start_unix_nanos_, now_nanos));
    if (is_gauge(name)) {
      Value gauge = Value::object();
      gauge.set("dataPoints", std::move(points));
      metric.set("gauge", std::move(gauge));
    } else {
      Value sum = Value::object();
      sum.set("dataPoints", std::move(points));
      sum.set("aggregationTemporality", Value(2));  // CUMULATIVE
      sum.set("isMonotonic", Value(true));
      metric.set("sum", std::move(sum));
    }
    metrics.push_back(std::move(metric));
  }

  Value scope_metrics = Value::object();
  scope_metrics.set("scope", Value(json::Object{{"name", Value("tpu_pruner")}}));
  scope_metrics.set("metrics", std::move(metrics));

  Value attr = Value::object();
  attr.set("key", Value("service.name"));
  attr.set("value", Value(json::Object{{"stringValue", Value("tpu-pruner")}}));
  Value resource = Value::object();
  resource.set("attributes", Value(json::Array{std::move(attr)}));

  Value rm = Value::object();
  rm.set("resource", std::move(resource));
  rm.set("scopeMetrics", Value(json::Array{std::move(scope_metrics)}));

  Value body = Value::object();
  body.set("resourceMetrics", Value(json::Array{std::move(rm)}));

  try {
    http::Client client;
    http::Request req;
    req.method = "POST";
    req.url = endpoint_ + "/v1/metrics";
    req.headers.push_back({"Content-Type", "application/json"});
    req.body = body.dump();
    req.timeout_ms = 5000;
    http::Response resp = client.request(req);
    if (resp.status < 200 || resp.status >= 300) {
      log::warn("OTLP export got HTTP " + std::to_string(resp.status));
      return false;
    }
    return true;
  } catch (const std::exception& e) {
    log::warn(std::string("OTLP export failed: ") + e.what());
    return false;
  }
}

}  // namespace tpupruner::otlp
