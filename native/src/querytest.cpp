// `tpu-pruner querytest <promql> <prometheus-url>` — ad-hoc query runner.
//
// Reference analog: the querytest debug binary
// (gpu-pruner/src/bin/querytest.rs): runs one instant query, prints the
// label table to stdout, and writes output.csv. Vector and matrix results
// supported; auth goes through the same token chain as the daemon.
#include "querytest.hpp"

#include <algorithm>
#include <cstdio>
#include <fstream>
#include <set>
#include <vector>

#include "tpupruner/auth.hpp"
#include "tpupruner/h2.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/prom.hpp"
#include "tpupruner/proto.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::querytest {

using json::Value;

namespace {

// Collect the union of label names across series, sorted, __name__ first.
std::vector<std::string> collect_columns(const json::Array& result) {
  std::set<std::string> names;
  for (const Value& series : result) {
    const Value* metric = series.find("metric");
    if (!metric || !metric->is_object()) continue;
    for (const auto& [k, _] : metric->as_object()) names.insert(k);
  }
  std::vector<std::string> cols(names.begin(), names.end());
  auto it = std::find(cols.begin(), cols.end(), "__name__");
  if (it != cols.end()) {
    cols.erase(it);
    cols.insert(cols.begin(), "__name__");
  }
  cols.push_back("value");
  return cols;
}

std::string csv_quote(const std::string& s) {
  if (s.find_first_of(",\"\n") == std::string::npos) return s;
  std::string out = "\"";
  for (char c : s) {
    if (c == '"') out += "\"\"";
    else out.push_back(c);
  }
  out += "\"";
  return out;
}

std::string series_value(const Value& series) {
  // vector: "value": [ts, "v"]; matrix: "values": [[ts,"v"],...] → last
  const Value* v = series.find("value");
  if (v && v->is_array() && v->as_array().size() == 2) {
    const Value& x = v->as_array()[1];
    return x.is_string() ? x.as_string() : x.dump();
  }
  const Value* vs = series.find("values");
  if (vs && vs->is_array() && !vs->as_array().empty()) {
    const Value& last = vs->as_array().back();
    if (last.is_array() && last.as_array().size() == 2) {
      const Value& x = last.as_array()[1];
      return x.is_string() ? x.as_string() : x.dump();
    }
  }
  return "";
}

}  // namespace

int run(const std::string& promql, const std::string& url, const std::string& csv_path) {
  auth::TokenOptions topts;
  std::string token = auth::get_bearer_token(topts).value_or("");
  prom::Client client(url, token);

  Value response;
  try {
    response = client.instant_query(promql);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "querytest: %s\n", e.what());
    return 1;
  }

  const Value* status = response.find("status");
  if (!status || !status->is_string() || status->as_string() != "success") {
    std::fprintf(stderr, "querytest: query failed: %s\n",
                 response.get_string("error", response.dump()).c_str());
    return 1;
  }
  const Value* result = response.at_path("data.result");
  if (!result || !result->is_array()) {
    std::fprintf(stderr, "querytest: no data.result in response\n");
    return 1;
  }
  const json::Array& series_list = result->as_array();
  const Value* rtype = response.at_path("data.resultType");
  std::string rtype_s = (rtype && rtype->is_string()) ? rtype->as_string() : "unknown";
  std::printf("resultType: %s, %zu series\n", rtype_s.c_str(), series_list.size());

  std::vector<std::string> cols = collect_columns(series_list);

  // column widths for the stdout table
  std::vector<size_t> widths;
  for (const std::string& c : cols) widths.push_back(c.size());
  std::vector<std::vector<std::string>> rows;
  for (const Value& series : series_list) {
    std::vector<std::string> row;
    const Value* metric = series.find("metric");
    for (size_t i = 0; i + 1 < cols.size(); ++i) {
      std::string cell = metric ? metric->get_string(cols[i]) : "";
      widths[i] = std::max(widths[i], cell.size());
      row.push_back(std::move(cell));
    }
    std::string val = series_value(series);
    widths.back() = std::max(widths.back(), val.size());
    row.push_back(std::move(val));
    rows.push_back(std::move(row));
  }

  auto print_row = [&](const std::vector<std::string>& row) {
    std::printf("|");
    for (size_t i = 0; i < row.size(); ++i) {
      std::printf(" %-*s |", static_cast<int>(widths[i]), row[i].c_str());
    }
    std::printf("\n");
  };
  print_row(cols);
  {
    std::vector<std::string> sep;
    for (size_t w : widths) sep.push_back(std::string(w, '-'));
    print_row(sep);
  }
  for (const auto& row : rows) print_row(row);

  std::ofstream csv(csv_path);
  for (size_t i = 0; i < cols.size(); ++i) {
    csv << (i ? "," : "") << csv_quote(cols[i]);
  }
  csv << "\n";
  for (const auto& row : rows) {
    for (size_t i = 0; i < row.size(); ++i) {
      csv << (i ? "," : "") << csv_quote(row[i]);
    }
    csv << "\n";
  }
  std::printf("wrote %zu rows to %s\n", rows.size(), csv_path.c_str());
  return 0;
}

int run_wire(const std::string& promql, const std::string& url, const std::string& wire) {
  if (wire != "proto" && wire != "json") {
    std::fprintf(stderr, "querytest: --wire must be proto or json (got '%s')\n", wire.c_str());
    return 2;
  }
  auth::TokenOptions topts;
  std::string token = auth::get_bearer_token(topts).value_or("");

  std::string base = url;
  while (!base.empty() && base.back() == '/') base.pop_back();
  h2::Transport http(h2::default_mode());
  http::Request req;
  req.method = "POST";
  req.url = base + "/api/v1/query";
  req.headers.push_back({"Content-Type", "application/x-www-form-urlencoded"});
  req.headers.push_back({"Accept", wire == "proto" ? std::string(proto::kPromProtoAccept)
                                                   : std::string("application/json")});
  if (!token.empty()) req.headers.push_back({"Authorization", "Bearer " + token});
  req.body = "query=" + util::url_encode(promql);

  http::Response resp;
  try {
    resp = http.request(req);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "querytest: %s\n", e.what());
    return 1;
  }
  std::string content_type = "unknown";
  if (auto it = resp.headers.find("content-type"); it != resp.headers.end()) {
    content_type = it->second;
  }
  std::printf("HTTP %d  content-type: %s  (%zu bytes, asked for %s)\n", resp.status,
              content_type.c_str(), resp.body.size(), wire.c_str());
  if (wire == "proto" && !proto::is_prom_proto(content_type)) {
    std::printf("note: server answered JSON — the negotiation-fallback path "
                "(--wire auto would now stop asking this endpoint)\n");
  }

  // Classic offset | hex | ascii dump, capped so a multi-megabyte matrix
  // doesn't flood the terminal.
  constexpr size_t kDumpCap = 4096;
  const size_t n = std::min(resp.body.size(), kDumpCap);
  for (size_t off = 0; off < n; off += 16) {
    std::printf("%08zx ", off);
    for (size_t i = 0; i < 16; ++i) {
      if (i == 8) std::printf(" ");
      if (off + i < n)
        std::printf(" %02x", static_cast<unsigned char>(resp.body[off + i]));
      else
        std::printf("   ");
    }
    std::printf("  |");
    for (size_t i = 0; i < 16 && off + i < n; ++i) {
      unsigned char c = static_cast<unsigned char>(resp.body[off + i]);
      std::printf("%c", (c >= 0x20 && c < 0x7F) ? c : '.');
    }
    std::printf("|\n");
  }
  if (resp.body.size() > kDumpCap) {
    std::printf("... (%zu more bytes)\n", resp.body.size() - kDumpCap);
  }
  return (resp.status >= 200 && resp.status < 300) ? 0 : 1;
}

}  // namespace tpupruner::querytest
