// Fleet federation hub: `tpu-pruner hub --member <url> [--member <url>...]`.
//
// One daemon per cluster, one hub per fleet. The hub polls each member
// daemon's metrics port (/debug/workloads, /debug/signals,
// /debug/decisions) on --poll-interval, folds the snapshots through
// fleet::aggregate into the merged fleet view, and serves it on its own
// metrics port:
//
//   /debug/fleet/workloads   per-cluster ledger sections + fleet totals
//   /debug/fleet/signals     per-cluster-MINIMUM coverage, named brownout
//                            and unreachable clusters
//   /debug/fleet/decisions   recent DecisionRecords per member cluster
//   /debug/fleet/clusters    member status table (OK/PENDING/UNREACHABLE)
//   /metrics                 tpu_pruner_fleet_* families + the
//                            fleet_merge_seconds poll-round histogram
//
// A member going dark becomes an explicit UNREACHABLE row (and pins the
// fleet coverage minimum to 0) rather than silently dropping out of an
// average; its last-known ledger data is kept, flagged by status.
// /readyz fails until at least one member has been polled successfully —
// a hub that has never seen a member has no fleet view to serve.
#include <chrono>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "metrics_http.hpp"
#include "tpupruner/fleet.hpp"
#include "tpupruner/http.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/shard.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::hub {

namespace {

struct Options {
  std::vector<std::string> members;
  int metrics_port = 8080;  // 0 = ephemeral ("auto")
  int64_t poll_interval_s = 10;
  int64_t stale_after_s = 0;  // 0 → 3 × poll interval
  int64_t member_timeout_ms = 5000;
  std::string cluster_name;  // hub's own identity ("" → heuristic)
  std::string log_format = "default";
};

struct FlagError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Per-member poll state: the fleet::MemberSnapshot facts plus the
// monotonic clock of the last success (staleness is derived per round).
struct MemberState {
  fleet::MemberSnapshot snap;
  int64_t last_success_mono = -1;
};

std::atomic<int>& g_shutdown = util::shutdown_flag();

extern "C" void on_hub_signal(int signum) {
  g_shutdown = signum;
  std::signal(signum, SIG_DFL);  // graceful once, lethal twice
}

int64_t parse_int(const std::string& flag, const std::string& v) {
  try {
    size_t idx = 0;
    int64_t out = std::stoll(v, &idx);
    if (idx != v.size()) throw std::invalid_argument("trailing");
    return out;
  } catch (const std::exception&) {
    throw FlagError("invalid integer for " + flag + ": '" + v + "'");
  }
}

Options parse(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto value = [&]() -> std::string {
      if (i + 1 >= argc) throw FlagError(arg + " requires a value");
      return argv[++i];
    };
    if (arg == "--member") {
      std::string url = value();
      while (!url.empty() && url.back() == '/') url.pop_back();
      if (!util::starts_with(url, "http://") && !util::starts_with(url, "https://")) {
        url = "http://" + url;  // bare host:port convenience
      }
      opt.members.push_back(std::move(url));
    } else if (arg == "--metrics-port") {
      std::string v = value();
      if (v == "auto") {
        opt.metrics_port = 0;
      } else {
        int64_t port = parse_int("--metrics-port", v);
        if (port < 1 || port > 65535) throw FlagError("--metrics-port out of range");
        opt.metrics_port = static_cast<int>(port);
      }
    } else if (arg == "--poll-interval") {
      opt.poll_interval_s = parse_int("--poll-interval", value());
      if (opt.poll_interval_s < 1) throw FlagError("--poll-interval must be >= 1 second");
    } else if (arg == "--stale-after") {
      opt.stale_after_s = parse_int("--stale-after", value());
      if (opt.stale_after_s < 1) throw FlagError("--stale-after must be >= 1 second");
    } else if (arg == "--member-timeout-ms") {
      opt.member_timeout_ms = parse_int("--member-timeout-ms", value());
      if (opt.member_timeout_ms < 1) throw FlagError("--member-timeout-ms must be >= 1");
    } else if (arg == "--cluster-name") {
      opt.cluster_name = value();
    } else if (arg == "--log-format") {
      opt.log_format = value();
      if (opt.log_format != "default" && opt.log_format != "json" &&
          opt.log_format != "pretty") {
        throw FlagError("invalid value for --log-format: '" + opt.log_format + "'");
      }
    } else {
      throw FlagError("unknown hub flag: " + arg + " (see tpu-pruner hub --help)");
    }
  }
  if (opt.members.empty()) {
    throw FlagError("tpu-pruner hub needs at least one --member <url> (see --help)");
  }
  if (opt.stale_after_s == 0) opt.stale_after_s = 3 * opt.poll_interval_s;
  return opt;
}

// One member poll: the three /debug documents, all-or-nothing. Throws a
// descriptive error on any transport/HTTP/parse failure.
void poll_member(const http::Client& client, const Options& opt, MemberState& m) {
  auto fetch = [&](const char* path) {
    http::Request req;
    req.url = m.snap.url + path;
    req.timeout_ms = static_cast<int>(opt.member_timeout_ms);
    http::Response resp = client.request(req);
    if (resp.status != 200) {
      throw std::runtime_error(std::string(path) + " returned HTTP " +
                               std::to_string(resp.status));
    }
    return json::Value::parse(resp.body);
  };
  json::Value workloads = fetch("/debug/workloads");
  json::Value signals = fetch("/debug/signals");
  json::Value decisions = fetch("/debug/decisions");
  m.snap.workloads = std::move(workloads);
  m.snap.signals = std::move(signals);
  m.snap.decisions = std::move(decisions);
  // Every member payload is cluster-stamped; keep the last known name so
  // an UNREACHABLE row still says WHICH cluster went dark.
  std::string cluster = m.snap.workloads.get_string("cluster");
  if (cluster.empty()) cluster = m.snap.signals.get_string("cluster");
  if (!cluster.empty()) m.snap.cluster = cluster;
}

}  // namespace

std::string usage() {
  return R"(tpu-pruner hub — fleet federation hub

Polls N member daemons' metrics ports and serves the merged fleet view:
per-cluster workload ledgers with fleet totals that provably sum,
per-cluster-MINIMUM signal coverage (a browned-out or unreachable cluster
can never hide in a fleet average), recent decisions per cluster, and a
member status table with explicit UNREACHABLE rows.

USAGE:
  tpu-pruner hub --member <url> [--member <url> ...] [FLAGS]

FLAGS:
      --member <URL>            a member daemon's metrics base URL
                                (http://host:port); repeatable, >= 1 required
      --metrics-port <P>        serve the fleet view on this port
                                ("auto" = ephemeral, logged at startup)
                                [default: 8080]
      --poll-interval <SEC>     seconds between member poll rounds [default: 10]
      --stale-after <SEC>       a member last polled successfully longer ago
                                than this reads UNREACHABLE
                                [default: 3x --poll-interval]
      --member-timeout-ms <MS>  per-request member poll timeout [default: 5000]
      --cluster-name <NAME>     the hub's own cluster identity (stamps its
                                fleet-scoped metric rows; per-member rows keep
                                their member's label) [default: heuristic —
                                $TPU_PRUNER_CLUSTER_NAME, in-cluster namespace,
                                kubeconfig current-context, "default"]
      --log-format <F>          default | json | pretty [default: default]
  -h, --help                    print this help
)";
}

int run(int argc, char** argv) {
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-h") == 0 || std::strcmp(argv[i], "--help") == 0) {
      std::fprintf(stdout, "%s\n", usage().c_str());
      return 0;
    }
  }
  Options opt;
  try {
    opt = parse(argc, argv);
  } catch (const FlagError& e) {
    std::fprintf(stderr, "%s\n", e.what());
    return 2;
  }
  log::init(opt.log_format == "json"
                ? log::Format::Json
                : opt.log_format == "pretty" ? log::Format::Pretty : log::Format::Default);
  fleet::set_cluster_name(fleet::resolve_cluster_name(opt.cluster_name));
  std::signal(SIGTERM, on_hub_signal);
  std::signal(SIGINT, on_hub_signal);

  std::vector<MemberState> members(opt.members.size());
  for (size_t i = 0; i < opt.members.size(); ++i) {
    members[i].snap.url = opt.members[i];
    members[i].snap.cluster = opt.members[i];  // until the first payload names it
  }
  log::info("hub", "federating " + std::to_string(members.size()) + " member(s), poll every " +
            std::to_string(opt.poll_interval_s) + "s, stale after " +
            std::to_string(opt.stale_after_s) + "s");

  std::mutex view_mutex;
  // Latest merged view. Seeded from the unpolled snapshots so the fleet
  // endpoints serve well-formed documents (every member PENDING) from
  // the first request, not "{}" until a poll round lands.
  fleet::FleetView view = [&] {
    std::vector<fleet::MemberSnapshot> snaps;
    for (const MemberState& m : members) snaps.push_back(m.snap);
    return fleet::aggregate(snaps, opt.stale_after_s);
  }();
  bool ever_synced = false;
  auto last_round = std::make_shared<std::atomic<int64_t>>(util::mono_secs());

  metrics_http::Server server(opt.metrics_port);
  server.set_fleet_provider([&](const std::string& sub, const std::string&) -> std::string {
    std::lock_guard<std::mutex> lock(view_mutex);
    if (sub == "workloads") return view.workloads.is_null() ? "{}" : view.workloads.dump();
    if (sub == "signals") return view.signals.is_null() ? "{}" : view.signals.dump();
    if (sub == "decisions") return view.decisions.is_null() ? "{}" : view.decisions.dump();
    if (sub == "clusters" || sub.empty())
      return view.clusters.is_null() ? "{}" : view.clusters.dump();
    return "";
  });
  server.set_extra_metrics_provider([&](bool openmetrics) {
    std::lock_guard<std::mutex> lock(view_mutex);
    return openmetrics ? view.metrics_openmetrics : view.metrics_text;
  });
  // Ready = member sync happened: at least one member answered a full
  // poll at least once. A hub that never reached anyone has no fleet
  // view and must not pass readiness.
  server.set_ready_probe([&] {
    std::lock_guard<std::mutex> lock(view_mutex);
    return ever_synced;
  });
  // Alive = the poll loop keeps rounding (3 intervals of slack, floor 60s
  // — same shape as the daemon's cycle-staleness probe).
  const int64_t stalled_after = std::max<int64_t>(3 * opt.poll_interval_s, 60);
  server.set_health_probe([last_round, stalled_after] {
    return util::mono_secs() - last_round->load() <= stalled_after;
  });

  http::Client client;
  // Member polls fan out over the shared worker pool: each member writes
  // only its own MemberState slot and http::Client::request is
  // thread-safe, so one slow (or timing-out) member costs the round
  // max(member latencies) instead of the sum — fleet_merge_seconds no
  // longer stretches for everyone when a single cluster drags.
  shard::Pool& poll_pool =
      shard::pool(std::min<size_t>(std::max<size_t>(members.size(), 1), 16));
  while (!g_shutdown.load()) {
    auto round_start = std::chrono::steady_clock::now();
    poll_pool.run(members.size(), [&](size_t i) {
      MemberState& m = members[i];
      ++m.snap.polls;
      try {
        poll_member(client, opt, m);
        m.snap.reachable = true;
        m.snap.ever_reached = true;
        m.snap.last_error.clear();
        m.last_success_mono = util::mono_secs();
      } catch (const std::exception& e) {
        m.snap.reachable = false;
        ++m.snap.failures;
        m.snap.last_error = e.what();
        log::warn("hub", "poll of " + m.snap.url + " (" + m.snap.cluster + ") failed: " +
                  e.what());
      }
      m.snap.staleness_s =
          m.last_success_mono < 0 ? -1 : util::mono_secs() - m.last_success_mono;
    });
    {
      std::vector<fleet::MemberSnapshot> snaps;
      snaps.reserve(members.size());
      for (const MemberState& m : members) snaps.push_back(m.snap);
      fleet::FleetView next = fleet::aggregate(snaps, opt.stale_after_s);
      std::lock_guard<std::mutex> lock(view_mutex);
      view = std::move(next);
      for (const MemberState& m : members) {
        if (m.snap.ever_reached) ever_synced = true;
      }
    }
    double round_secs =
        std::chrono::duration<double>(std::chrono::steady_clock::now() - round_start).count();
    log::histogram_observe("fleet_merge_seconds", "", round_secs);
    last_round->store(util::mono_secs());

    // Interruptible interval sleep (same idiom as the daemon loop).
    auto interval = std::chrono::seconds(opt.poll_interval_s);
    while (!g_shutdown.load() &&
           std::chrono::steady_clock::now() - round_start < interval) {
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
      last_round->store(util::mono_secs());  // sleeping != stalled
    }
  }
  log::info("hub", std::string("Received ") +
            (g_shutdown.load() == SIGINT ? "SIGINT" : "SIGTERM") + ", shutting down");
  return 0;
}

}  // namespace tpupruner::hub
