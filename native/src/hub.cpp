// Fleet federation hub: `tpu-pruner hub --member <url> [--member <url>...]`.
//
// One daemon per cluster, one hub per fleet (and, since the
// delta-federation work, one hub per REGION under a parent hub). The hub
// polls each member daemon's metrics port, folds the snapshots through
// fleet::aggregate into the merged fleet view, and serves it on its own
// metrics port:
//
//   /debug/fleet/workloads   per-cluster ledger sections + fleet totals
//   /debug/fleet/signals     per-cluster-MINIMUM coverage, named brownout
//                            and unreachable clusters
//   /debug/fleet/decisions   recent DecisionRecords per member cluster
//   /debug/fleet/clusters    member status table (OK/PENDING/UNREACHABLE)
//   /metrics                 tpu_pruner_fleet_* families + the
//                            fleet_merge_seconds poll-round histogram
//   /debug/{workloads,signals,decisions}
//                            member-compatible ROLLUP documents
//                            ("rollup": true + per-cluster sections) so
//                            this hub can itself be a --member of a
//                            parent hub (region → global)
//   /debug/delta             the hub's own change journal over those
//                            rollup documents (a parent hub polls it
//                            exactly like a member daemon's)
//
// Scaling like the daemon (--fleet-delta on): member polls become
// /debug/delta cursor polls over ONE pooled connection per member (the
// shared h2 transport), a quiesced member costs a ~100-byte round, and
// the merge is CHANGE-GATED — a round in which no member changed (and no
// status flipped) skips fleet::aggregate entirely, so hub CPU is
// O(churn), not O(members x fleet-size). --fleet-stream on turns the
// cursor polls into long-polls (one parked request per member; a change
// publishes within milliseconds, a quiet interval costs one empty
// response). Members that do not serve /debug/delta (older daemons)
// transparently demote to snapshot polling, counted in
// tpu_pruner_fleet_delta_fallbacks_total.
//
// A member going dark becomes an explicit UNREACHABLE row (and pins the
// fleet coverage minimum to 0) rather than silently dropping out of an
// average; its last-known ledger data is kept, flagged by status. Failed
// members are re-polled under exponential backoff with jitter (capped at
// --stale-after, counted per member in
// tpu_pruner_fleet_member_backoff_total) so one dead member cannot burn a
// poll slot every round. /readyz fails until at least one member has been
// polled successfully — a hub that has never seen a member has no fleet
// view to serve.
#include <chrono>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "metrics_http.hpp"
#include "tpupruner/delta.hpp"
#include "tpupruner/fleet.hpp"
#include "tpupruner/h2.hpp"
#include "tpupruner/http.hpp"
#include "tpupruner/json.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/shard.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::hub {

namespace {

struct Options {
  std::vector<std::string> members;
  int metrics_port = 8080;  // 0 = ephemeral ("auto")
  int64_t poll_interval_s = 10;
  int64_t stale_after_s = 0;  // 0 → 3 × poll interval
  int64_t member_timeout_ms = 5000;
  std::string cluster_name;  // hub's own identity ("" → heuristic)
  std::string log_format = "default";
  std::string fleet_delta = "off";   // on = cursor polls over /debug/delta
  std::string fleet_stream = "off";  // on = long-poll member updates
};

struct FlagError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Per-member poll state: the fleet::MemberSnapshot facts plus the
// monotonic clock of the last success, the delta cursor, and the
// failure-backoff window.
struct MemberState {
  fleet::MemberSnapshot snap;
  int64_t last_success_mono = -1;
  delta::DeltaState delta;
  bool delta_unsupported = false;  // member 404s /debug/delta → snapshot polls
  int64_t backoff_until_mono = 0;
  int64_t backoff_s = 0;
  uint32_t jitter_seed = 0;
  uint64_t snapshot_fp = 0;    // snapshot mode: fingerprint of the 3 bodies
  uint64_t slo_fp = 0;         // change gate for the member's SLO summary
  std::string last_status;     // status at the last aggregate (change gate)
  uint64_t merged_backoffs = 0;  // backoffs folded into the served view
  bool changed = true;         // this member needs folding into a new view
};

std::atomic<int>& g_shutdown = util::shutdown_flag();

extern "C" void on_hub_signal(int signum) {
  g_shutdown = signum;
  std::signal(signum, SIG_DFL);  // graceful once, lethal twice
}

int64_t parse_int(const std::string& flag, const std::string& v) {
  try {
    size_t idx = 0;
    int64_t out = std::stoll(v, &idx);
    if (idx != v.size()) throw std::invalid_argument("trailing");
    return out;
  } catch (const std::exception&) {
    throw FlagError("invalid integer for " + flag + ": '" + v + "'");
  }
}

std::string parse_on_off(const std::string& flag, const std::string& v) {
  if (v != "on" && v != "off") {
    throw FlagError("invalid value for " + flag + ": '" + v + "' (on|off)");
  }
  return v;
}

Options parse(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto value = [&]() -> std::string {
      if (i + 1 >= argc) throw FlagError(arg + " requires a value");
      return argv[++i];
    };
    if (arg == "--member") {
      std::string url = value();
      while (!url.empty() && url.back() == '/') url.pop_back();
      if (!util::starts_with(url, "http://") && !util::starts_with(url, "https://")) {
        url = "http://" + url;  // bare host:port convenience
      }
      opt.members.push_back(std::move(url));
    } else if (arg == "--metrics-port") {
      std::string v = value();
      if (v == "auto") {
        opt.metrics_port = 0;
      } else {
        int64_t port = parse_int("--metrics-port", v);
        if (port < 1 || port > 65535) throw FlagError("--metrics-port out of range");
        opt.metrics_port = static_cast<int>(port);
      }
    } else if (arg == "--poll-interval") {
      opt.poll_interval_s = parse_int("--poll-interval", value());
      if (opt.poll_interval_s < 1) throw FlagError("--poll-interval must be >= 1 second");
    } else if (arg == "--stale-after") {
      opt.stale_after_s = parse_int("--stale-after", value());
      if (opt.stale_after_s < 1) throw FlagError("--stale-after must be >= 1 second");
    } else if (arg == "--member-timeout-ms") {
      opt.member_timeout_ms = parse_int("--member-timeout-ms", value());
      if (opt.member_timeout_ms < 1) throw FlagError("--member-timeout-ms must be >= 1");
    } else if (arg == "--cluster-name") {
      opt.cluster_name = value();
    } else if (arg == "--fleet-delta") {
      opt.fleet_delta = parse_on_off("--fleet-delta", value());
    } else if (arg == "--fleet-stream") {
      opt.fleet_stream = parse_on_off("--fleet-stream", value());
    } else if (arg == "--log-format") {
      opt.log_format = value();
      if (opt.log_format != "default" && opt.log_format != "json" &&
          opt.log_format != "pretty") {
        throw FlagError("invalid value for --log-format: '" + opt.log_format + "'");
      }
    } else {
      throw FlagError("unknown hub flag: " + arg + " (see tpu-pruner hub --help)");
    }
  }
  if (opt.members.empty()) {
    throw FlagError("tpu-pruner hub needs at least one --member <url> (see --help)");
  }
  if (opt.stale_after_s == 0) opt.stale_after_s = 3 * opt.poll_interval_s;
  if (opt.fleet_stream == "on" && opt.fleet_delta != "on") {
    throw FlagError("--fleet-stream on requires --fleet-delta on");
  }
  return opt;
}

// One full-snapshot member poll: the three /debug documents,
// all-or-nothing. Throws a descriptive error on any transport/HTTP/parse
// failure. Returns true when any document's bytes changed.
bool poll_member_snapshot(const h2::Transport& transport, const Options& opt,
                          MemberState& m) {
  uint64_t fp = 1469598103934665603ULL;
  auto fetch = [&](const char* path) {
    http::Request req;
    req.url = m.snap.url + path;
    req.timeout_ms = static_cast<int>(opt.member_timeout_ms);
    http::Response resp = transport.request(req);
    if (resp.status != 200) {
      throw std::runtime_error(std::string(path) + " returned HTTP " +
                               std::to_string(resp.status));
    }
    log::counter_add("fleet_poll_bytes_total", resp.body.size());
    fp = fp * 1099511628211ULL ^ shard::stable_hash(resp.body);
    return json::Value::parse(resp.body);
  };
  json::Value workloads = fetch("/debug/workloads");
  json::Value signals = fetch("/debug/signals");
  json::Value decisions = fetch("/debug/decisions");
  // The capacity surface is optional (members predating it, or running
  // --capacity off, 404 it): absent folds as a null document, exactly
  // like the delta path's missing "capacity" surface — so snapshot and
  // delta polling stay byte-identical member by member.
  json::Value capacity;
  {
    http::Request req;
    req.url = m.snap.url + "/debug/capacity";
    req.timeout_ms = static_cast<int>(opt.member_timeout_ms);
    http::Response resp = transport.request(req);
    if (resp.status == 200) {
      log::counter_add("fleet_poll_bytes_total", resp.body.size());
      fp = fp * 1099511628211ULL ^ shard::stable_hash(resp.body);
      capacity = json::Value::parse(resp.body);
    } else if (resp.status != 404) {
      throw std::runtime_error("/debug/capacity returned HTTP " +
                               std::to_string(resp.status));
    }
  }
  m.snap.workloads = std::move(workloads);
  m.snap.signals = std::move(signals);
  m.snap.decisions = std::move(decisions);
  m.snap.capacity = std::move(capacity);
  bool changed = fp != m.snapshot_fp;
  m.snapshot_fp = fp;
  // Every member payload is cluster-stamped; keep the last known name so
  // an UNREACHABLE row still says WHICH cluster went dark.
  std::string cluster = m.snap.workloads.get_string("cluster");
  if (cluster.empty()) cluster = m.snap.signals.get_string("cluster");
  if (!cluster.empty()) m.snap.cluster = cluster;
  return changed;
}

// One delta-cursor poll: a single /debug/delta request carrying the
// member's cursor, applied through delta::apply_delta so the held
// documents stay EQUAL to what snapshot polling would have parsed.
// Falls back to snapshot polling (sticky) when the member 404s the
// endpoint. Returns true when anything changed.
bool poll_member_delta(const h2::Transport& transport, const Options& opt,
                       MemberState& m, int64_t wait_ms) {
  if (m.delta_unsupported) return poll_member_snapshot(transport, opt, m);
  http::Request req;
  req.url = m.snap.url + "/debug/delta?" + delta::cursor_query(m.delta, wait_ms);
  req.timeout_ms = static_cast<int>(opt.member_timeout_ms + wait_ms);
  http::Response resp = transport.request(req);
  if (resp.status == 404) {
    // Pre-delta member: demote to snapshot polling and remember it.
    m.delta_unsupported = true;
    log::counter_add("fleet_delta_fallbacks_total", 1);
    log::warn("hub", m.snap.url + " does not serve /debug/delta; " +
              "falling back to snapshot polls for this member");
    return poll_member_snapshot(transport, opt, m);
  }
  if (resp.status != 200) {
    throw std::runtime_error("/debug/delta returned HTTP " + std::to_string(resp.status));
  }
  log::counter_add("fleet_poll_bytes_total", resp.body.size());
  json::Value parsed = json::Value::parse(resp.body);
  delta::MemberDocs docs;
  delta::ApplyResult res = delta::apply_delta(m.delta, parsed, docs);
  if (!res.ok) {
    // Protocol violation (or cursor rejected without a resync body):
    // drop the cursor so the next poll asks for a full snapshot.
    m.delta = delta::DeltaState{};
    throw std::runtime_error("/debug/delta response not applicable; cursor reset");
  }
  if (res.resync) log::counter_add("fleet_delta_resyncs_total", 1);
  if (res.changed) {
    if (!docs.workloads.is_null()) m.snap.workloads = std::move(docs.workloads);
    if (!docs.signals.is_null()) m.snap.signals = std::move(docs.signals);
    if (!docs.decisions.is_null()) m.snap.decisions = std::move(docs.decisions);
    if (!docs.capacity.is_null()) m.snap.capacity = std::move(docs.capacity);
    std::string cluster = m.snap.workloads.get_string("cluster");
    if (cluster.empty()) cluster = m.snap.signals.get_string("cluster");
    if (!cluster.empty()) m.snap.cluster = cluster;
  }
  return res.changed;
}

// The trace/SLO surface is optional (members predating it, or running
// --trace off, 404 it): absent folds as a null document. Fetched on both
// poll modes — the trace ring is not delta-journaled — and change-gated
// by its own fingerprint over just the "slo" key, so a member whose
// trace LIST churns but whose burn counters are quiet stays quiet.
bool poll_member_slo(const h2::Transport& transport, const Options& opt,
                     MemberState& m) {
  http::Request req;
  req.url = m.snap.url + "/debug/traces";
  req.timeout_ms = static_cast<int>(opt.member_timeout_ms);
  http::Response resp = transport.request(req);
  uint64_t fp = 0;
  json::Value slo;
  if (resp.status == 200) {
    log::counter_add("fleet_poll_bytes_total", resp.body.size());
    json::Value doc = json::Value::parse(resp.body);
    if (const json::Value* v = doc.find("slo"); v && v->is_object()) slo = *v;
    if (!slo.is_null()) fp = shard::stable_hash(slo.dump());
  } else if (resp.status != 404) {
    throw std::runtime_error("/debug/traces returned HTTP " +
                             std::to_string(resp.status));
  }
  m.snap.slo = std::move(slo);
  bool changed = fp != m.slo_fp;
  m.slo_fp = fp;
  return changed;
}

// Shared post-poll bookkeeping for one member attempt (either mode).
// Returns true when the member changed (data or reachability).
bool poll_member_once(const h2::Transport& transport, const Options& opt,
                      MemberState& m, int64_t now_mono, int64_t wait_ms) {
  bool changed = false;
  ++m.snap.polls;
  try {
    bool data_changed = opt.fleet_delta == "on"
                            ? poll_member_delta(transport, opt, m, wait_ms)
                            : poll_member_snapshot(transport, opt, m);
    bool slo_changed = poll_member_slo(transport, opt, m);
    changed = data_changed || slo_changed || !m.snap.reachable;
    m.snap.reachable = true;
    m.snap.ever_reached = true;
    m.snap.last_error.clear();
    m.last_success_mono = util::mono_secs();
    m.backoff_s = 0;
    m.backoff_until_mono = 0;
  } catch (const std::exception& e) {
    changed = m.snap.reachable;  // reachability flip needs a re-merge
    m.snap.reachable = false;
    ++m.snap.failures;
    m.snap.last_error = e.what();
    // Exponential backoff with jitter, capped at --stale-after: a dead
    // member is re-dialed at interval, 2x, 4x, ... never rarer than the
    // staleness window (so recovery is seen within one UNREACHABLE
    // period), and never burns a poll slot every round.
    m.backoff_s = std::min(std::max<int64_t>(m.backoff_s * 2, opt.poll_interval_s),
                           opt.stale_after_s);
    uint32_t r = m.jitter_seed = m.jitter_seed * 1664525u + 1013904223u;
    double jitter = 0.75 + 0.5 * (static_cast<double>(r % 1000) / 1000.0);
    m.backoff_until_mono =
        now_mono + std::max<int64_t>(1, static_cast<int64_t>(m.backoff_s * jitter));
    log::warn("hub", "poll of " + m.snap.url + " (" + m.snap.cluster + ") failed: " +
              std::string(e.what()) + "; backing off " +
              std::to_string(m.backoff_until_mono - now_mono) + "s");
  }
  m.snap.staleness_s =
      m.last_success_mono < 0 ? -1 : util::mono_secs() - m.last_success_mono;
  return changed;
}

}  // namespace

std::string usage() {
  return R"(tpu-pruner hub — fleet federation hub

Polls N member daemons' metrics ports and serves the merged fleet view:
per-cluster workload ledgers with fleet totals that provably sum,
per-cluster-MINIMUM signal coverage (a browned-out or unreachable cluster
can never hide in a fleet average), recent decisions per cluster, and a
member status table with explicit UNREACHABLE rows. A hub can itself be a
--member of a parent hub (region -> global rollup): it serves
member-compatible /debug documents stamped "rollup": true, which the
parent expands back into per-cluster leaves.

USAGE:
  tpu-pruner hub --member <url> [--member <url> ...] [FLAGS]

FLAGS:
      --member <URL>            a member daemon's (or child hub's) metrics
                                base URL (http://host:port); repeatable,
                                >= 1 required
      --metrics-port <P>        serve the fleet view on this port
                                ("auto" = ephemeral, logged at startup)
                                [default: 8080]
      --poll-interval <SEC>     seconds between member poll rounds [default: 10]
      --stale-after <SEC>       a member last polled successfully longer ago
                                than this reads UNREACHABLE; also caps the
                                failed-member poll backoff
                                [default: 3x --poll-interval]
      --member-timeout-ms <MS>  per-request member poll timeout [default: 5000]
      --fleet-delta <on|off>    poll members through their /debug/delta
                                change journals: O(churn) bytes + CPU per
                                round, byte-identical merged views
                                (members without the endpoint demote to
                                snapshot polls) [default: off]
      --fleet-stream <on|off>   long-poll member deltas over the pooled
                                per-member connection (quiesced members
                                cost one empty response per interval);
                                requires --fleet-delta on [default: off]
      --cluster-name <NAME>     the hub's own cluster identity (stamps its
                                fleet-scoped metric rows; per-member rows keep
                                their member's label) [default: heuristic —
                                $TPU_PRUNER_CLUSTER_NAME, in-cluster namespace,
                                kubeconfig current-context, "default"]
      --log-format <F>          default | json | pretty [default: default]
  -h, --help                    print this help
)";
}

int run(int argc, char** argv) {
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-h") == 0 || std::strcmp(argv[i], "--help") == 0) {
      std::fprintf(stdout, "%s\n", usage().c_str());
      return 0;
    }
  }
  Options opt;
  try {
    opt = parse(argc, argv);
  } catch (const FlagError& e) {
    std::fprintf(stderr, "%s\n", e.what());
    return 2;
  }
  log::init(opt.log_format == "json"
                ? log::Format::Json
                : opt.log_format == "pretty" ? log::Format::Pretty : log::Format::Default);
  fleet::set_cluster_name(fleet::resolve_cluster_name(opt.cluster_name));
  std::signal(SIGTERM, on_hub_signal);
  std::signal(SIGINT, on_hub_signal);

  // Register the poll counters up front so the families serve (as zeros)
  // from the first scrape, not only after the first event.
  log::counter_add("fleet_poll_bytes_total", 0);
  if (opt.fleet_delta == "on") {
    log::counter_add("fleet_delta_resyncs_total", 0);
    log::counter_add("fleet_delta_fallbacks_total", 0);
  }

  std::mutex members_mutex;  // guards every MemberState (stream pollers write them)
  std::vector<MemberState> members(opt.members.size());
  for (size_t i = 0; i < opt.members.size(); ++i) {
    members[i].snap.url = opt.members[i];
    members[i].snap.cluster = opt.members[i];  // until the first payload names it
    members[i].jitter_seed = static_cast<uint32_t>(i * 2654435761u + 1);
  }
  log::info("hub", "federating " + std::to_string(members.size()) + " member(s), poll every " +
            std::to_string(opt.poll_interval_s) + "s, stale after " +
            std::to_string(opt.stale_after_s) + "s, delta " + opt.fleet_delta +
            ", stream " + opt.fleet_stream);

  std::mutex view_mutex;
  // Latest merged view + the member-compatible rollup documents a parent
  // hub consumes. Seeded from the unpolled snapshots so the fleet
  // endpoints serve well-formed documents (every member PENDING) from
  // the first request, not "{}" until a poll round lands.
  fleet::FleetView view;
  json::Value roll_wl, roll_sig, roll_dec, roll_cap, roll_slo;
  const std::string hub_cluster = fleet::cluster_name();
  auto remerge = [&](std::vector<fleet::MemberSnapshot> snaps) {
    fleet::FleetView next = fleet::aggregate(snaps, opt.stale_after_s);
    json::Value wl = fleet::rollup_workloads(next, hub_cluster);
    json::Value sig = fleet::rollup_signals(next, hub_cluster);
    json::Value dec = fleet::rollup_decisions(next, hub_cluster);
    json::Value cap = fleet::rollup_capacity(next, hub_cluster);
    json::Value slo = fleet::rollup_slo(next, hub_cluster);
    std::lock_guard<std::mutex> lock(view_mutex);
    view = std::move(next);
    roll_wl = std::move(wl);
    roll_sig = std::move(sig);
    roll_dec = std::move(dec);
    roll_cap = std::move(cap);
    roll_slo = std::move(slo);
  };
  {
    std::vector<fleet::MemberSnapshot> snaps;
    for (const MemberState& m : members) snaps.push_back(m.snap);
    remerge(std::move(snaps));
  }
  bool ever_synced = false;
  auto last_round = std::make_shared<std::atomic<int64_t>>(util::mono_secs());

  // The hub's own change journal over the rollup documents: a parent hub
  // polls this hub's /debug/delta exactly as this hub polls a member's.
  delta::Journal hub_journal;
  hub_journal.set_renderers(delta::Renderers{
      [&] { std::lock_guard<std::mutex> lock(view_mutex); return roll_wl; },
      [&] { std::lock_guard<std::mutex> lock(view_mutex); return roll_sig; },
      [&] { std::lock_guard<std::mutex> lock(view_mutex); return roll_dec; },
      [&] { std::lock_guard<std::mutex> lock(view_mutex); return roll_cap; },
  });

  metrics_http::Server server(opt.metrics_port);
  // The server binds here (port final) but answers nothing until
  // start() below — after every probe and provider is registered, so no
  // request can race the wiring and read 404/ready from a half-built hub.
  server.set_ready_probe([&] {
    std::lock_guard<std::mutex> lock(view_mutex);
    return ever_synced;
  });
  const int64_t stalled_after = std::max<int64_t>(3 * opt.poll_interval_s, 60);
  server.set_health_probe([last_round, stalled_after] {
    return util::mono_secs() - last_round->load() <= stalled_after;
  });
  server.set_fleet_provider([&](const std::string& sub, const std::string&) -> std::string {
    std::lock_guard<std::mutex> lock(view_mutex);
    if (sub == "workloads") return view.workloads.is_null() ? "{}" : view.workloads.dump();
    if (sub == "signals") return view.signals.is_null() ? "{}" : view.signals.dump();
    if (sub == "decisions") return view.decisions.is_null() ? "{}" : view.decisions.dump();
    if (sub == "capacity") return view.capacity.is_null() ? "{}" : view.capacity.dump();
    if (sub == "slo") return view.slo.is_null() ? "{}" : view.slo.dump();
    if (sub == "clusters" || sub.empty())
      return view.clusters.is_null() ? "{}" : view.clusters.dump();
    return "";
  });
  // Member-compatible rollup surfaces (hub-of-hubs): the same paths a
  // daemon serves, carrying per-cluster sections a parent hub expands.
  server.set_workloads_provider([&](const std::string&) {
    std::lock_guard<std::mutex> lock(view_mutex);
    return roll_wl.is_null() ? std::string("{}") : roll_wl.dump();
  });
  server.set_signals_provider([&] {
    std::lock_guard<std::mutex> lock(view_mutex);
    return roll_sig.is_null() ? std::string("{}") : roll_sig.dump();
  });
  server.set_decisions_provider([&](const std::string&) {
    std::lock_guard<std::mutex> lock(view_mutex);
    return roll_dec.is_null() ? std::string("{}") : roll_dec.dump();
  });
  server.set_capacity_provider([&] {
    std::lock_guard<std::mutex> lock(view_mutex);
    return roll_cap.is_null() ? std::string("{}") : roll_cap.dump();
  });
  // Member-compatible SLO surface: a parent hub polls /debug/traces and
  // reads the "slo" key, so serve the rollup doc there (the hub retains
  // no member trace trees — only the burn summaries).
  server.set_traces_provider([&](const std::string& id) -> std::string {
    if (!id.empty()) return "";
    std::lock_guard<std::mutex> lock(view_mutex);
    json::Value doc = json::Value::object();
    doc.set("cluster", json::Value(hub_cluster));
    doc.set("slo", roll_slo.is_null() ? json::Value::object() : roll_slo);
    return doc.dump();
  });
  server.set_delta_provider([&](const std::string& query, const std::function<bool()>& abort) {
    return hub_journal.handle_request(query, abort);
  });
  server.set_extra_metrics_provider([&](bool openmetrics) {
    std::lock_guard<std::mutex> lock(view_mutex);
    return openmetrics ? view.metrics_openmetrics : view.metrics_text;
  });
  server.start();
  // Readiness above = member sync happened: at least one member answered
  // a full poll at least once. Liveness = the poll loop keeps rounding
  // (3 intervals of slack, floor 60s — the daemon's cycle-staleness
  // probe's shape).

  // One pooled connection per member endpoint (h2 when the member speaks
  // it, keep-alive HTTP/1.1 otherwise) — a poll round opens ZERO new
  // connections in steady state, where the old per-request client paid a
  // fresh TCP handshake per document per member per round.
  h2::Transport transport(h2::Mode::Auto);
  const bool streaming = opt.fleet_stream == "on";
  std::atomic<bool> need_merge{true};

  // Streaming mode: one long-poll loop per member. The thread parks
  // inside the member's /debug/delta for up to ~one interval; a change
  // lands here within milliseconds of the member publishing it.
  std::vector<std::thread> pollers;
  if (streaming) {
    // Park each long-poll for up to half the staleness window, clamped to
    // [1s, 5s]: a quiesced member then costs one ~100-byte response per
    // PARK (not per round), its last-success clock refreshes comfortably
    // inside --stale-after, and an in-flight park bounds shutdown drain
    // to ~5s (a parked request cannot be interrupted mid-read).
    const int64_t wait_ms = std::min<int64_t>(
        std::max<int64_t>(opt.stale_after_s * 500, 1000), 5000);
    for (size_t i = 0; i < members.size(); ++i) {
      pollers.emplace_back([&, i, wait_ms] {
        while (!g_shutdown.load()) {
          int64_t now = util::mono_secs();
          bool backing_off;
          {
            std::lock_guard<std::mutex> lock(members_mutex);
            backing_off = members[i].backoff_until_mono > now;
            if (backing_off) ++members[i].snap.backoffs;
          }
          if (backing_off) {
            std::this_thread::sleep_for(std::chrono::milliseconds(200));
            continue;
          }
          // The long poll itself runs OUTSIDE members_mutex (it can park
          // for a whole interval); only the state apply takes the lock.
          MemberState scratch;
          {
            std::lock_guard<std::mutex> lock(members_mutex);
            scratch = members[i];
          }
          bool changed = poll_member_once(transport, opt, scratch, now, wait_ms);
          {
            std::lock_guard<std::mutex> lock(members_mutex);
            scratch.snap.backoffs = members[i].snap.backoffs;  // kept by the skip path
            members[i] = std::move(scratch);
            if (changed) members[i].changed = true;
          }
          if (changed) need_merge.store(true);
        }
      });
    }
  }

  shard::Pool& poll_pool =
      shard::pool(std::min<size_t>(std::max<size_t>(members.size(), 1), 16));
  while (!g_shutdown.load()) {
    auto round_start = std::chrono::steady_clock::now();
    if (!streaming) {
      // Member polls fan out over the shared worker pool: each member
      // writes only its own MemberState slot, so one slow (or
      // timing-out) member costs the round max(member latencies), not
      // the sum.
      int64_t now = util::mono_secs();
      poll_pool.run(members.size(), [&](size_t i) {
        MemberState& m = members[i];
        if (m.backoff_until_mono > now) {
          // Failure backoff: skip the slot, keep serving last-known data.
          ++m.snap.backoffs;
          m.snap.staleness_s =
              m.last_success_mono < 0 ? -1 : util::mono_secs() - m.last_success_mono;
          return;
        }
        if (poll_member_once(transport, opt, m, now, 0)) m.changed = true;
      });
    }
    // Change-gated merge: re-aggregate when any member's data changed OR
    // any member's derived status flipped (staleness can flip a member
    // UNREACHABLE without any poll succeeding). With --fleet-delta off
    // every successful snapshot round re-merges (exact legacy parity);
    // with delta on, a fully quiesced round skips the merge — the hub's
    // cost becomes O(churn).
    {
      std::lock_guard<std::mutex> lock(members_mutex);
      bool any_changed = need_merge.exchange(false);
      for (MemberState& m : members) {
        std::string status = fleet::member_status(m.snap, opt.stale_after_s);
        // A backoff tick must surface in the served counters even though
        // no member data changed (outage rounds re-merge; bounded by the
        // outage itself).
        if (m.changed || status != m.last_status ||
            m.snap.backoffs != m.merged_backoffs) {
          any_changed = true;
        }
        m.last_status = std::move(status);
        m.merged_backoffs = m.snap.backoffs;
      }
      if (opt.fleet_delta != "on") any_changed = true;
      if (any_changed) {
        std::vector<fleet::MemberSnapshot> snaps;
        snaps.reserve(members.size());
        for (MemberState& m : members) {
          snaps.push_back(m.snap);
          m.changed = false;
        }
        remerge(std::move(snaps));
        {
          std::lock_guard<std::mutex> lock2(view_mutex);
          for (const MemberState& m : members) {
            if (m.snap.ever_reached) ever_synced = true;
          }
        }
        if (hub_journal.active()) hub_journal.publish();
      }
    }
    double round_secs =
        std::chrono::duration<double>(std::chrono::steady_clock::now() - round_start).count();
    log::histogram_observe("fleet_merge_seconds", "", round_secs);
    last_round->store(util::mono_secs());

    // Interruptible interval sleep (same idiom as the daemon loop). In
    // streaming mode a member change wakes the merge early.
    auto interval = std::chrono::seconds(opt.poll_interval_s);
    while (!g_shutdown.load() &&
           std::chrono::steady_clock::now() - round_start < interval) {
      if (streaming && need_merge.load()) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
      last_round->store(util::mono_secs());  // sleeping != stalled
    }
  }
  hub_journal.wake_all();
  for (std::thread& t : pollers) {
    if (t.joinable()) t.join();
  }
  log::info("hub", std::string("Received ") +
            (g_shutdown.load() == SIGINT ? "SIGINT" : "SIGTERM") + ", shutting down");
  return 0;
}

}  // namespace tpupruner::hub
