#include "tpupruner/metrics.hpp"

#include <stdexcept>
#include <unordered_set>

namespace tpupruner::metrics {

namespace {

// Label lookup with the exported_*/native fallback chain (lib.rs:161-175).
const std::string* label(const json::Value& metric, const std::string& exported,
                         const std::string& native) {
  const json::Value* v = metric.find(exported);
  if (v && v->is_string()) return &v->as_string();
  v = metric.find(native);
  if (v && v->is_string()) return &v->as_string();
  return nullptr;
}

}  // namespace

DecodeResult decode_instant_vector(const json::Value& response, const std::string& device,
                                   const std::string& schema) {
  if (schema != "gmp" && schema != "gke-system") {
    // Same strictness as build_idle_query: a typo'd schema must not
    // silently decode with gmp semantics.
    throw std::runtime_error("unknown metric schema: " + schema + " (expected gmp|gke-system)");
  }
  const json::Value* status = response.find("status");
  if (!status || !status->is_string() || status->as_string() != "success") {
    std::string err = response.get_string("error", "unknown error");
    throw std::runtime_error("prometheus query failed: " + err);
  }
  const json::Value* rtype = response.at_path("data.resultType");
  if (!rtype || !rtype->is_string() || rtype->as_string() != "vector") {
    throw std::runtime_error("expected vector response from prometheus");
  }
  const json::Value* result = response.at_path("data.result");
  if (!result || !result->is_array()) {
    throw std::runtime_error("malformed vector response: missing data.result");
  }

  DecodeResult out;
  out.num_series = result->as_array().size();
  // Dedup by (pod, namespace): multi-chip pods emit one series per chip but
  // the owner chain only needs resolving once (main.rs:416-437).
  std::unordered_set<std::string> seen;

  for (const json::Value& series : result->as_array()) {
    const json::Value* metric = series.find("metric");
    if (!metric || !metric->is_object()) {
      out.errors.push_back("series missing metric labels");
      continue;
    }
    const std::string* pod = label(*metric, "exported_pod", "pod");
    if (!pod) {
      out.errors.push_back("the data for key `exported_pod/pod` is not available");
      continue;
    }
    const std::string* ns = label(*metric, "exported_namespace", "namespace");
    if (!ns) {
      out.errors.push_back("the data for key `exported_namespace/namespace` is not available");
      continue;
    }
    const std::string* container = label(*metric, "exported_container", "container");
    if (!container && schema != "gke-system") {
      out.errors.push_back("the data for key `exported_container/container` is not available");
      continue;
    }

    core::PodMetricSample sample;
    sample.name = *pod;
    sample.ns = *ns;
    sample.container = container ? *container : "unknown";
    // gke-system rows carry the accelerator model but no node_type label.
    sample.node_type = metric->get_string("node_type", metric->get_string("model", "unknown"));

    if (device == "gpu") {
      const json::Value* model = metric->find("modelName");
      if (!model || !model->is_string()) {
        out.errors.push_back("the data for key `modelName` is not available");
        continue;
      }
      sample.accelerator = model->as_string();
    } else {
      // GKE TPU label enrichment is optional; never reject a series for it.
      // gke-system series name the accelerator in `model` instead.
      sample.accelerator =
          metric->get_string("accelerator_type", metric->get_string("model", "unknown"));
    }

    // value: [<unix ts>, "<string float>"]
    const json::Value* value = series.find("value");
    if (!value || !value->is_array() || value->as_array().size() != 2) {
      out.errors.push_back("series missing sample value");
      continue;
    }
    const json::Value& v = value->as_array()[1];
    try {
      sample.value = v.is_string() ? std::stod(v.as_string()) : v.as_double();
    } catch (const std::exception&) {
      out.errors.push_back("unparseable sample value for pod " + sample.name);
      continue;
    }

    if (seen.insert(sample.ns + "/" + sample.name).second) {
      out.samples.push_back(std::move(sample));
    }
  }
  return out;
}

namespace {

// Doc twin of label(): exported_*/native fallback chain over arena nodes.
std::optional<std::string_view> label_doc(const json::Doc::Node& metric,
                                          std::string_view exported, std::string_view native) {
  if (auto v = metric.find(exported); v && v->is_string()) return v->as_sv();
  if (auto v = metric.find(native); v && v->is_string()) return v->as_sv();
  return std::nullopt;
}

}  // namespace

DecodeResult decode_instant_vector(const json::Doc& response, const std::string& device,
                                   const std::string& schema) {
  if (schema != "gmp" && schema != "gke-system") {
    throw std::runtime_error("unknown metric schema: " + schema + " (expected gmp|gke-system)");
  }
  json::Doc::Node root = response.root();
  auto status = root.find("status");
  if (!status || !status->is_string() || status->as_sv() != "success") {
    std::string err(root.get_string("error", "unknown error"));
    throw std::runtime_error("prometheus query failed: " + err);
  }
  auto rtype = root.at_path("data.resultType");
  if (!rtype || !rtype->is_string() || rtype->as_sv() != "vector") {
    throw std::runtime_error("expected vector response from prometheus");
  }
  auto result = root.at_path("data.result");
  if (!result || !result->is_array()) {
    throw std::runtime_error("malformed vector response: missing data.result");
  }

  DecodeResult out;
  out.num_series = result->size();
  std::unordered_set<std::string> seen;

  json::Doc::Node series = result->first_child();
  for (size_t i = 0; i < result->size(); ++i, series = series.next_sibling()) {
    auto metric = series.find("metric");
    if (!metric || !metric->is_object()) {
      out.errors.push_back("series missing metric labels");
      continue;
    }
    auto pod = label_doc(*metric, "exported_pod", "pod");
    if (!pod) {
      out.errors.push_back("the data for key `exported_pod/pod` is not available");
      continue;
    }
    auto ns = label_doc(*metric, "exported_namespace", "namespace");
    if (!ns) {
      out.errors.push_back("the data for key `exported_namespace/namespace` is not available");
      continue;
    }
    auto container = label_doc(*metric, "exported_container", "container");
    if (!container && schema != "gke-system") {
      out.errors.push_back("the data for key `exported_container/container` is not available");
      continue;
    }

    core::PodMetricSample sample;
    sample.name = std::string(*pod);
    sample.ns = std::string(*ns);
    sample.container = container ? std::string(*container) : "unknown";
    sample.node_type =
        std::string(metric->get_string("node_type", metric->get_string("model", "unknown")));

    if (device == "gpu") {
      auto model = metric->find("modelName");
      if (!model || !model->is_string()) {
        out.errors.push_back("the data for key `modelName` is not available");
        continue;
      }
      sample.accelerator = std::string(model->as_sv());
    } else {
      sample.accelerator = std::string(
          metric->get_string("accelerator_type", metric->get_string("model", "unknown")));
    }

    auto value = series.find("value");
    if (!value || !value->is_array() || value->size() != 2) {
      out.errors.push_back("series missing sample value");
      continue;
    }
    json::Doc::Node v = value->child(1);
    try {
      sample.value = v.is_string() ? std::stod(std::string(v.as_sv())) : v.as_double();
    } catch (const std::exception&) {
      out.errors.push_back("unparseable sample value for pod " + sample.name);
      continue;
    }

    if (seen.insert(sample.ns + "/" + sample.name).second) {
      out.samples.push_back(std::move(sample));
    }
  }
  return out;
}

namespace {

// Wire-series twin of label(): first matching label wins (labels are
// unique per series — Prometheus label sets are maps), exported_*/native
// fallback chain preserved.
const std::string* label_wire(const proto::PromSeries& series, std::string_view exported,
                              std::string_view native) {
  const std::string* native_hit = nullptr;
  for (const auto& [name, value] : series.labels) {
    if (name == exported) return &value;
    if (!native_hit && name == native) native_hit = &value;
  }
  return native_hit;
}

std::string label_wire_or(const proto::PromSeries& series, std::string_view key,
                          std::string fallback) {
  for (const auto& [name, value] : series.labels) {
    if (name == key) return value;
  }
  return fallback;
}

}  // namespace

DecodeResult decode_instant_vector(const proto::PromVector& response, const std::string& device,
                                   const std::string& schema) {
  if (schema != "gmp" && schema != "gke-system") {
    throw std::runtime_error("unknown metric schema: " + schema + " (expected gmp|gke-system)");
  }
  if (response.status != "success") {
    throw std::runtime_error("prometheus query failed: " +
                             (response.error.empty() ? "unknown error" : response.error));
  }

  DecodeResult out;
  out.num_series = response.result.size();
  std::unordered_set<std::string> seen;

  for (const proto::PromSeries& series : response.result) {
    const std::string* pod = label_wire(series, "exported_pod", "pod");
    if (!pod) {
      out.errors.push_back("the data for key `exported_pod/pod` is not available");
      continue;
    }
    const std::string* ns = label_wire(series, "exported_namespace", "namespace");
    if (!ns) {
      out.errors.push_back("the data for key `exported_namespace/namespace` is not available");
      continue;
    }
    const std::string* container = label_wire(series, "exported_container", "container");
    if (!container && schema != "gke-system") {
      out.errors.push_back("the data for key `exported_container/container` is not available");
      continue;
    }

    core::PodMetricSample sample;
    sample.name = *pod;
    sample.ns = *ns;
    sample.container = container ? *container : "unknown";
    sample.node_type =
        label_wire_or(series, "node_type", label_wire_or(series, "model", "unknown"));

    if (device == "gpu") {
      const std::string* model = label_wire(series, "modelName", "modelName");
      if (!model) {
        out.errors.push_back("the data for key `modelName` is not available");
        continue;
      }
      sample.accelerator = *model;
    } else {
      sample.accelerator =
          label_wire_or(series, "accelerator_type", label_wire_or(series, "model", "unknown"));
    }

    try {
      sample.value = std::stod(series.value_text);
    } catch (const std::exception&) {
      out.errors.push_back("unparseable sample value for pod " + sample.name);
      continue;
    }

    if (seen.insert(sample.ns + "/" + sample.name).second) {
      out.samples.push_back(std::move(sample));
    }
  }
  return out;
}

uint64_t sample_fingerprint(const core::PodMetricSample& s) {
  // FNV-1a, field-delimited so ("ab","c") never collides with ("a","bc").
  // Not std::hash for the same reason shard placement isn't: the value
  // participates in a cross-cycle contract and must be stable.
  uint64_t h = 0xcbf29ce484222325ull;
  auto mix = [&](const void* p, size_t n) {
    const unsigned char* b = static_cast<const unsigned char*>(p);
    for (size_t i = 0; i < n; ++i) {
      h ^= b[i];
      h *= 0x100000001b3ull;
    }
  };
  auto mix_str = [&](const std::string& v) {
    mix(v.data(), v.size());
    h ^= 0xffu;  // field delimiter (never a UTF-8 byte in label values)
    h *= 0x100000001b3ull;
  };
  mix_str(s.name);
  mix_str(s.ns);
  mix_str(s.container);
  mix_str(s.node_type);
  mix_str(s.accelerator);
  double value = s.value;
  mix(&value, sizeof(value));
  return h;
}

}  // namespace tpupruner::metrics
