#include "tpupruner/delta.hpp"

#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>

#include "tpupruner/fleet.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/shard.hpp"
#include "tpupruner/util.hpp"

namespace tpupruner::delta {

using json::Value;

namespace {

uint64_t fp_of(const Value& v) { return shard::stable_hash(v.dump()); }

// Row identity inside a workloads document: the ledger's account key.
std::string row_key(const Value& row) {
  std::string key = row.get_string("workload");
  if (!key.empty()) return key;
  return row.get_string("kind") + "/" + row.get_string("namespace") + "/" +
         row.get_string("name");
}

// Everything in the document EXCEPT the row array — totals, tracked,
// cluster, epoch, sort... The hub re-attaches the reconstructed array
// under `array_key`, so meta + rows rebuild the document exactly.
Value doc_meta(const Value& doc, const char* array_key) {
  Value meta = Value::object();
  if (!doc.is_object()) return meta;
  for (const auto& [k, v] : doc.as_object()) {
    if (k != array_key) meta.set(k, v);
  }
  return meta;
}

int64_t int_at(const Value& doc, const char* key, int64_t dflt) {
  const Value* v = doc.find(key);
  return v && v->is_number() ? static_cast<int64_t>(v->as_double()) : dflt;
}

double sort_field(const Value& row, const std::string& sort) {
  const char* field = sort == "idle" ? "idle_seconds"
                      : sort == "chips" ? "chips"
                                        : "reclaimed_chip_seconds";
  const Value* v = row.find(field);
  return v && v->is_number() ? v->as_double() : 0.0;
}

// Rebuild a workloads document from meta + rows, replicating the member's
// own ordering (ledger::workloads_json): rows enter in ascending account
// key order (its accounts map), then a STABLE sort by the sort field,
// descending — so the reconstructed array is byte-identical to the
// member's render.
Value rebuild_workloads(const Value& meta, const std::map<std::string, Value>& rows) {
  Value doc = meta;  // COW copy
  if (!doc.is_object()) doc = Value::object();
  std::string sort = meta.get_string("sort", "reclaimed");
  std::vector<const Value*> ordered;
  ordered.reserve(rows.size());
  for (const auto& [k, row] : rows) ordered.push_back(&row);
  std::stable_sort(ordered.begin(), ordered.end(),
                   [&](const Value* a, const Value* b) {
                     return sort_field(*a, sort) > sort_field(*b, sort);
                   });
  Value arr = Value::array();
  for (const Value* row : ordered) arr.push_back(*row);
  doc.set("workloads", std::move(arr));
  return doc;
}

Value rebuild_decisions(const Value& meta, const std::deque<Value>& ring) {
  Value doc = meta;  // COW copy
  if (!doc.is_object()) doc = Value::object();
  Value arr = Value::array();
  for (const Value& rec : ring) arr.push_back(rec);
  doc.set("decisions", std::move(arr));
  return doc;
}

}  // namespace

namespace {
// Journal generations must never repeat across journal lifetimes — a
// member restart is DETECTED by the mismatch (the informer's
// resourceVersion analog), so "<unix>-<pid>-<seq>" carries a process-wide
// sequence in case two journals are born within the same second.
std::string next_generation() {
  static std::atomic<uint64_t> seq{0};
  return std::to_string(util::now_unix()) + "-" + std::to_string(::getpid()) + "-" +
         std::to_string(seq.fetch_add(1));
}
}  // namespace

Journal::Journal() {
  gen_ = next_generation();
  if (auto cap = util::env("TPU_PRUNER_DELTA_JOURNAL_CAP"); cap && !cap->empty()) {
    try {
      log_cap_ = static_cast<size_t>(std::stoull(*cap));
    } catch (const std::exception&) {
      // ignore: keep the default — a bad env var must not kill the daemon
    }
  }
}

void Journal::set_renderers(Renderers r) {
  std::lock_guard<std::mutex> lock(mutex_);
  renderers_ = std::move(r);
}

void Journal::set_log_cap(size_t cap) {
  std::lock_guard<std::mutex> lock(mutex_);
  log_cap_ = cap == 0 ? 1 : cap;
}

bool Journal::active() const {
  std::lock_guard<std::mutex> lock(mutex_);
  return active_;
}

uint64_t Journal::epoch() const {
  std::lock_guard<std::mutex> lock(mutex_);
  return epoch_;
}

std::string Journal::generation() const {
  std::lock_guard<std::mutex> lock(mutex_);
  return gen_;
}

void Journal::note_change_locked(uint64_t epoch) {
  log_.push_back(epoch);
  while (log_.size() > log_cap_) {
    // The popped change has aged out of the window: cursors at or before
    // its epoch can no longer be served a faithful diff (the informer's
    // 410 analog — the hub resyncs from a full snapshot).
    min_since_ = std::max(min_since_, log_.front());
    log_.pop_front();
  }
}

void Journal::publish() {
  std::lock_guard<std::mutex> lock(mutex_);
  if (!active_) return;
  publish_locked();
}

void Journal::publish_locked() {
  const uint64_t next = epoch_ + 1;
  bool changed = false;

  if (renderers_.workloads) {
    Value doc = renderers_.workloads();
    Value meta = doc_meta(doc, "workloads");
    uint64_t mfp = fp_of(meta);
    if (!wl_.have || mfp != wl_.meta_fp) {
      wl_.meta = std::move(meta);
      wl_.meta_fp = mfp;
      wl_.meta_epoch = next;
      note_change_locked(next);
      changed = true;
    }
    std::map<std::string, uint64_t> seen;
    if (const Value* arr = doc.find("workloads"); arr && arr->is_array()) {
      for (const Value& row : arr->as_array()) {
        std::string key = row_key(row);
        uint64_t fp = fp_of(row);
        seen.emplace(key, fp);
        auto it = wl_.row_fp.find(key);
        if (it == wl_.row_fp.end() || it->second != fp) {
          wl_.row_fp[key] = fp;
          wl_.rows[key] = row;
          wl_.row_epoch[key] = next;
          wl_.removed.erase(key);
          note_change_locked(next);
          changed = true;
        }
      }
    }
    for (auto it = wl_.rows.begin(); it != wl_.rows.end();) {
      if (seen.count(it->first)) {
        ++it;
        continue;
      }
      wl_.removed[it->first] = next;
      wl_.row_fp.erase(it->first);
      wl_.row_epoch.erase(it->first);
      note_change_locked(next);
      changed = true;
      it = wl_.rows.erase(it);
    }
    wl_.have = true;
  }

  if (renderers_.signals) {
    Value doc = renderers_.signals();
    uint64_t fp = fp_of(doc);
    if (!sig_.have || fp != sig_.fp) {
      sig_.doc = std::move(doc);
      sig_.fp = fp;
      sig_.doc_epoch = next;
      sig_.have = true;
      note_change_locked(next);
      changed = true;
    }
  }

  if (renderers_.capacity) {
    Value doc = renderers_.capacity();
    uint64_t fp = fp_of(doc);
    if (!cap_.have || fp != cap_.fp) {
      cap_.doc = std::move(doc);
      cap_.fp = fp;
      cap_.doc_epoch = next;
      cap_.have = true;
      note_change_locked(next);
      changed = true;
    }
  }

  if (renderers_.decisions) {
    Value doc = renderers_.decisions();
    Value meta = doc_meta(doc, "decisions");
    uint64_t mfp = fp_of(meta);
    int64_t capacity = int_at(doc, "capacity", 0);
    int64_t dropped = int_at(doc, "dropped", 0);
    const Value* arr = doc.find("decisions");
    size_t len = arr && arr->is_array() ? arr->as_array().size() : 0;
    uint64_t total = static_cast<uint64_t>(dropped) + len;
    bool discontinuity =
        dec_.have && (total < dec_.appended_total || capacity != dec_.capacity);
    if (discontinuity || !dec_.have) {
      dec_.ring.clear();
      dec_.appended_total = static_cast<uint64_t>(dropped);
      // Ring rebuilt wholesale below (every record reads as an append).
    }
    uint64_t fresh = total - dec_.appended_total;
    if (fresh > 0 && arr) {
      const auto& records = arr->as_array();
      size_t start = records.size() >= fresh ? records.size() - fresh : 0;
      for (size_t i = start; i < records.size(); ++i) {
        dec_.ring.emplace_back(next, records[i]);
        note_change_locked(next);
      }
      while (capacity > 0 && dec_.ring.size() > static_cast<size_t>(capacity)) {
        dec_.ring.pop_front();
      }
      changed = true;
    }
    if (!dec_.have || mfp != dec_.meta_fp) {
      dec_.meta = std::move(meta);
      dec_.meta_fp = mfp;
      dec_.meta_epoch = next;
      note_change_locked(next);
      changed = true;
    }
    dec_.capacity = capacity;
    dec_.dropped = dropped;
    dec_.appended_total = total;
    dec_.have = true;
  }

  if (changed) {
    epoch_ = next;
    cv_.notify_all();
  }
  primed_ = true;
}

json::Value Journal::full_docs_locked() const {
  Value full = Value::object();
  if (wl_.have) full.set("workloads", rebuild_workloads(wl_.meta, wl_.rows));
  if (sig_.have) full.set("signals", sig_.doc);
  if (cap_.have) full.set("capacity", cap_.doc);
  if (dec_.have) {
    std::deque<Value> ring;
    for (const auto& [e, rec] : dec_.ring) ring.push_back(rec);
    full.set("decisions", rebuild_decisions(dec_.meta, ring));
  }
  return full;
}

std::string Journal::build_response_locked(int64_t since, bool resync, bool first) {
  Value resp = Value::object();
  resp.set("cluster", Value(fleet::cluster_name()));
  resp.set("gen", Value(gen_));
  resp.set("epoch", Value(static_cast<int64_t>(epoch_)));
  if (resync || first) {
    if (resync) resp.set("resync", Value(true));
    resp.set("full", full_docs_locked());
    return resp.dump();
  }
  resp.set("since", Value(since));
  const uint64_t u_since = static_cast<uint64_t>(since);
  Value surfaces = Value::object();

  if (wl_.have) {
    bool meta_changed = wl_.meta_epoch > u_since;
    Value upserts = Value::array();
    for (const auto& [key, e] : wl_.row_epoch) {
      if (e > u_since) upserts.push_back(wl_.rows.at(key));
    }
    Value removes = Value::array();
    for (const auto& [key, e] : wl_.removed) {
      if (e > u_since) removes.push_back(Value(key));
    }
    if (meta_changed || !upserts.as_array().empty() || !removes.as_array().empty()) {
      Value s = Value::object();
      s.set("meta", wl_.meta);
      s.set("upserts", std::move(upserts));
      s.set("removes", std::move(removes));
      surfaces.set("workloads", std::move(s));
    }
  }
  if (sig_.have && sig_.doc_epoch > u_since) {
    Value s = Value::object();
    s.set("doc", sig_.doc);
    surfaces.set("signals", std::move(s));
  }
  if (cap_.have && cap_.doc_epoch > u_since) {
    Value s = Value::object();
    s.set("doc", cap_.doc);
    surfaces.set("capacity", std::move(s));
  }
  if (dec_.have) {
    size_t fresh = 0;
    for (auto it = dec_.ring.rbegin(); it != dec_.ring.rend() && it->first > u_since; ++it) {
      ++fresh;
    }
    if (fresh > 0 || dec_.meta_epoch > u_since) {
      Value s = Value::object();
      s.set("meta", dec_.meta);
      Value appends = Value::array();
      for (size_t i = dec_.ring.size() - fresh; i < dec_.ring.size(); ++i) {
        appends.push_back(dec_.ring[i].second);
      }
      s.set("appends", std::move(appends));
      // When every retained record is fresh, the appends ARE the member's
      // whole current ring — the hub REPLACES its copy (its older records
      // may have wrapped out on the member side) instead of extending.
      s.set("replace", Value(fresh == dec_.ring.size()));
      surfaces.set("decisions", std::move(s));
    }
  }
  if (!surfaces.as_object().empty()) resp.set("surfaces", std::move(surfaces));
  return resp.dump();
}

std::string Journal::handle_request(const std::string& query,
                                    const std::function<bool()>& abort) {
  int64_t since = -1;
  std::string want_gen;
  int64_t wait_ms = 0;
  for (const std::string& pair : util::split(query, '&')) {
    size_t eq = pair.find('=');
    if (eq == std::string::npos) continue;
    std::string key = pair.substr(0, eq);
    std::string value = util::url_decode(pair.substr(eq + 1));
    try {
      if (key == "since") since = std::stoll(value);
      else if (key == "gen") want_gen = value;
      else if (key == "wait_ms") wait_ms = std::stoll(value);
    } catch (const std::exception&) {
      since = -1;  // malformed cursor → full snapshot
    }
  }
  wait_ms = std::min<int64_t>(std::max<int64_t>(wait_ms, 0), 55000);
  log::counter_add("delta_requests_total", 1);

  std::unique_lock<std::mutex> lock(mutex_);
  if (!active_) {
    active_ = true;
    log::info("delta", "first /debug/delta poll: change journal activated "
              "(gen " + gen_ + ")");
  }
  if (!primed_) publish_locked();  // self-prime so the first poll sees state

  bool first = since < 0;
  bool resync = !first && (want_gen != gen_ || static_cast<uint64_t>(since) > epoch_ ||
                           static_cast<uint64_t>(since) < min_since_);
  if (resync) log::counter_add("delta_resyncs_served_total", 1);

  if (!first && !resync && static_cast<uint64_t>(since) == epoch_ && wait_ms > 0) {
    // Long poll: hold until something changes, the deadline passes, or
    // the server is shutting down. Quiesced members cost ~zero bytes per
    // round in this mode.
    auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(wait_ms);
    while (epoch_ == static_cast<uint64_t>(since) &&
           std::chrono::steady_clock::now() < deadline && !(abort && abort())) {
      cv_.wait_for(lock, std::chrono::milliseconds(200));
    }
  }
  return build_response_locked(since, resync, first);
}

void Journal::wake_all() { cv_.notify_all(); }

void Journal::reset_for_test() {
  std::lock_guard<std::mutex> lock(mutex_);
  renderers_ = {};
  epoch_ = 0;
  min_since_ = 0;
  log_.clear();
  active_ = false;
  primed_ = false;
  wl_ = {};
  sig_ = {};
  dec_ = {};
  cap_ = {};
  gen_ = next_generation();
}

Journal& journal() {
  static Journal j;
  return j;
}

// ── hub side ──

std::string cursor_query(const DeltaState& st, int64_t wait_ms) {
  // Generations are "<unix>-<pid>" — URL-safe by construction, no
  // encoding needed.
  std::string q = "since=" + (st.primed ? std::to_string(st.epoch) : std::string("-1"));
  if (st.primed && !st.gen.empty()) q += "&gen=" + st.gen;
  if (wait_ms > 0) q += "&wait_ms=" + std::to_string(wait_ms);
  return q;
}

namespace {

void prime_workloads(DeltaState& st, const Value& doc) {
  st.wl_meta = doc_meta(doc, "workloads");
  st.wl_rows.clear();
  if (const Value* arr = doc.find("workloads"); arr && arr->is_array()) {
    for (const Value& row : arr->as_array()) st.wl_rows[row_key(row)] = row;
  }
}

void prime_decisions(DeltaState& st, const Value& doc) {
  st.dec_ring.clear();
  st.dec_capacity = int_at(doc, "capacity", 0);
  st.dec_dropped = int_at(doc, "dropped", 0);
  if (const Value* arr = doc.find("decisions"); arr && arr->is_array()) {
    for (const Value& rec : arr->as_array()) st.dec_ring.push_back(rec);
  }
}

}  // namespace

ApplyResult apply_delta(DeltaState& st, const Value& resp, MemberDocs& out) {
  ApplyResult res;
  if (!resp.is_object()) return res;
  const Value* gen = resp.find("gen");
  const Value* epoch = resp.find("epoch");
  if (!gen || !gen->is_string() || !epoch || !epoch->is_number()) return res;

  if (const Value* full = resp.find("full"); full && full->is_object()) {
    // Full snapshot (first poll or resync): the documents arrive verbatim
    // — adopt them and rebuild the reconstruction state from scratch.
    st = DeltaState{};
    st.gen = gen->as_string();
    st.epoch = static_cast<uint64_t>(epoch->as_double());
    st.primed = true;
    if (const Value* wl = full->find("workloads")) {
      prime_workloads(st, *wl);
      out.workloads = *wl;
    }
    if (const Value* sig = full->find("signals")) {
      st.signals = *sig;
      out.signals = *sig;
    }
    if (const Value* cap = full->find("capacity")) {
      st.capacity = *cap;
      out.capacity = *cap;
    }
    if (const Value* dec = full->find("decisions")) {
      prime_decisions(st, *dec);
      out.decisions = *dec;
    }
    res.ok = true;
    const Value* r = resp.find("resync");
    res.resync = r && r->is_bool() && r->as_bool();
    res.changed = true;
    return res;
  }

  if (!st.primed || gen->as_string() != st.gen) return res;  // caller resets cursor
  uint64_t new_epoch = static_cast<uint64_t>(epoch->as_double());
  if (new_epoch < st.epoch) return res;

  const Value* surfaces = resp.find("surfaces");
  if (surfaces && surfaces->is_object()) {
    if (const Value* wl = surfaces->find("workloads"); wl && wl->is_object()) {
      if (const Value* meta = wl->find("meta"); meta && meta->is_object()) {
        st.wl_meta = *meta;
      }
      if (const Value* ups = wl->find("upserts"); ups && ups->is_array()) {
        for (const Value& row : ups->as_array()) st.wl_rows[row_key(row)] = row;
      }
      if (const Value* rms = wl->find("removes"); rms && rms->is_array()) {
        for (const Value& key : rms->as_array()) {
          if (key.is_string()) st.wl_rows.erase(key.as_string());
        }
      }
      out.workloads = rebuild_workloads(st.wl_meta, st.wl_rows);
      res.changed = true;
    }
    if (const Value* sig = surfaces->find("signals"); sig && sig->is_object()) {
      if (const Value* doc = sig->find("doc")) {
        st.signals = *doc;
        out.signals = *doc;
        res.changed = true;
      }
    }
    if (const Value* cap = surfaces->find("capacity"); cap && cap->is_object()) {
      if (const Value* doc = cap->find("doc")) {
        st.capacity = *doc;
        out.capacity = *doc;
        res.changed = true;
      }
    }
    if (const Value* dec = surfaces->find("decisions"); dec && dec->is_object()) {
      const Value* meta = dec->find("meta");
      Value meta_doc = meta && meta->is_object() ? *meta : Value::object();
      st.dec_capacity = int_at(meta_doc, "capacity", st.dec_capacity);
      st.dec_dropped = int_at(meta_doc, "dropped", st.dec_dropped);
      const Value* rep = dec->find("replace");
      if (rep && rep->is_bool() && rep->as_bool()) st.dec_ring.clear();
      if (const Value* app = dec->find("appends"); app && app->is_array()) {
        for (const Value& rec : app->as_array()) st.dec_ring.push_back(rec);
      }
      while (st.dec_capacity > 0 &&
             st.dec_ring.size() > static_cast<size_t>(st.dec_capacity)) {
        st.dec_ring.pop_front();
      }
      out.decisions = rebuild_decisions(meta_doc, st.dec_ring);
      res.changed = true;
    }
  }
  st.epoch = new_epoch;
  res.ok = true;
  return res;
}

}  // namespace tpupruner::delta
