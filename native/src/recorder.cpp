#include "tpupruner/recorder.hpp"

#include <algorithm>
#include <cctype>
#include <filesystem>
#include <fstream>
#include <map>
#include <mutex>
#include <set>
#include <stdexcept>
#include <tuple>

#include "tpupruner/audit.hpp"
#include "tpupruner/capacity.hpp"
#include "tpupruner/core.hpp"
#include "tpupruner/fleet.hpp"
#include "tpupruner/gym.hpp"
#include "tpupruner/k8s.hpp"
#include "tpupruner/log.hpp"
#include "tpupruner/metrics.hpp"
#include "tpupruner/query.hpp"
#include "tpupruner/signal.hpp"
#include "tpupruner/util.hpp"
#include "tpupruner/walker.hpp"

namespace tpupruner::recorder {

namespace fs = std::filesystem;
using json::Value;

namespace {

// ── capture state ──

struct OpenCapsule {
  int64_t ts_unix = 0;
  int64_t ts_ms = 0;        // capsule id component (restart-unique)
  int64_t now_unix = 0;     // eligibility clock (resolve phase)
  std::string prom_body;
  std::string evidence_body;  // signal watchdog's raw evidence response
  Value signal_assessment;    // derived verdicts (forensics; replay recomputes)
  Value pods = Value::object();         // "ns/name" → acquisition evidence
  Value resolutions = Value::object();  // "ns/name" → walk result
  Value objects = Value::object();      // API path → object | null (miss)
  Value root_flags = Value::object();   // identity → {root_opted_out, ...}
  Value actuations = Value::object();   // identity → {reason, action, detail}
  // Consumer actuations that reported back BEFORE arm() (the incremental
  // fast path enqueues first and arms after the cached records emit):
  // arm() credits them against `expected` so the capsule still seals.
  size_t early_actuations = 0;
  Value vetoed_roots = Value::array();
  Value vetoed_namespaces = Value::object();
  Value ledger;                         // {now_unix, observations} — the observe_cycle feed
  Value breaker;                        // {limit, actionable, deferred, tripped}
  Value stats;                          // {num_series, num_pods, shutdown_events}
  Value incremental;                    // differential-engine provenance (dirty set, hits)
  Value reconcile;                      // event-engine provenance (mode + trigger)
  Value capacity;                       // {inputs, doc} — the capacity observatory stamp
  Value trace;                          // normalized trace stamp (--trace on)
  std::vector<Value> decisions;         // verbatim DecisionRecord JSON
  bool armed = false;
  size_t remaining = 0;
};

struct IndexEntry {
  std::string id;
  Value summary;  // {id, cycle, ts, decisions, scale_downs, breaker_tripped}
};

struct Registry {
  std::mutex mutex;
  bool enabled = false;
  std::string dir;
  size_t keep = 64;
  Value config;       // run config fingerprint
  std::string query;  // rendered idle query
  std::string evidence_query;  // rendered evidence query ("" = guard off)
  std::map<uint64_t, OpenCapsule> open;
  std::vector<IndexEntry> index;  // oldest first (ids sort chronologically)
};

Registry& reg() {
  static Registry r;
  return r;
}

std::string pad(uint64_t n, int width) {
  std::string s = std::to_string(n);
  return s.size() >= static_cast<size_t>(width)
             ? s
             : std::string(static_cast<size_t>(width) - s.size(), '0') + s;
}

bool id_safe(const std::string& id) {
  if (id.empty() || id.size() > 128) return false;
  for (char c : id) {
    if (!(std::isalnum(static_cast<unsigned char>(c)) || c == '-' || c == '_')) return false;
  }
  return true;
}

Value summarize(const std::string& id, const Value& doc) {
  Value s = Value::object();
  s.set("id", Value(id));
  if (const Value* c = doc.find("cycle")) s.set("cycle", *c);
  s.set("ts", Value(doc.get_string("ts")));
  int64_t decisions = 0, scale_downs = 0;
  if (const Value* d = doc.find("decisions"); d && d->is_array()) {
    decisions = static_cast<int64_t>(d->as_array().size());
    for (const Value& rec : d->as_array()) {
      if (rec.get_string("action") == "scale_down") ++scale_downs;
    }
  }
  s.set("decisions", Value(decisions));
  s.set("scale_downs", Value(scale_downs));
  bool tripped = false;
  if (const Value* b = doc.at_path("breaker.tripped"); b && b->is_bool()) tripped = b->as_bool();
  s.set("breaker_tripped", Value(tripped));
  return s;
}

void prune_locked(Registry& r) {
  while (r.index.size() > r.keep) {
    std::error_code ec;
    fs::remove(fs::path(r.dir) / (r.index.front().id + ".json"), ec);
    r.index.erase(r.index.begin());
  }
}

OpenCapsule* open_capsule_locked(Registry& r, uint64_t cycle) {
  auto it = r.open.find(cycle);
  return it == r.open.end() ? nullptr : &it->second;
}

// Assemble, atomically write and index the capsule, then drop it.
void seal_locked(Registry& r, uint64_t cycle) {
  auto it = r.open.find(cycle);
  if (it == r.open.end()) return;
  OpenCapsule& c = it->second;

  // Deterministic decision order (capture lands them from fan-out threads).
  std::sort(c.decisions.begin(), c.decisions.end(), [](const Value& a, const Value& b) {
    return std::make_tuple(a.get_string("namespace"), a.get_string("pod")) <
           std::make_tuple(b.get_string("namespace"), b.get_string("pod"));
  });
  Value decisions = Value::array();
  for (Value& d : c.decisions) decisions.push_back(std::move(d));

  std::string id = "cycle-" + pad(static_cast<uint64_t>(c.ts_ms), 13) + "-" + pad(cycle, 6);
  Value doc = Value::object();
  doc.set("version", Value(1));
  doc.set("cluster", Value(fleet::cluster_name()));
  doc.set("id", Value(id));
  doc.set("cycle", Value(static_cast<int64_t>(cycle)));
  doc.set("ts", Value(util::format_rfc3339(c.ts_unix)));
  doc.set("ts_unix", Value(c.ts_unix));
  doc.set("now_unix", Value(c.now_unix ? c.now_unix : c.ts_unix));
  doc.set("query", Value(r.query));
  doc.set("config", r.config);
  Value prom = Value::object();
  prom.set("body", Value(c.prom_body));
  doc.set("prom", std::move(prom));
  if (!c.evidence_body.empty() || !r.evidence_query.empty()) {
    Value evidence = Value::object();
    evidence.set("query", Value(r.evidence_query));
    evidence.set("body", Value(c.evidence_body));
    doc.set("evidence", std::move(evidence));
  }
  if (!c.signal_assessment.is_null()) doc.set("signal", std::move(c.signal_assessment));
  doc.set("pods", std::move(c.pods));
  doc.set("resolutions", std::move(c.resolutions));
  doc.set("objects", std::move(c.objects));
  doc.set("vetoed_roots", std::move(c.vetoed_roots));
  doc.set("vetoed_namespaces", std::move(c.vetoed_namespaces));
  if (!c.ledger.is_null()) doc.set("ledger", std::move(c.ledger));
  doc.set("root_flags", std::move(c.root_flags));
  if (!c.breaker.is_null()) doc.set("breaker", std::move(c.breaker));
  if (!c.stats.is_null()) doc.set("stats", std::move(c.stats));
  // Provenance, not evidence: how the differential engine assembled this
  // cycle's view (dirty set + cache hits). Replay recomputes in full and
  // never consults it — byte-identity comparisons across --incremental
  // modes normalize this key away, like ts/trace_id.
  if (!c.incremental.is_null()) doc.set("incremental", std::move(c.incremental));
  // Same provenance-not-evidence contract for the event engine's trigger
  // stamp: absent in cycle mode, normalized away in cross-mode diffs.
  if (!c.reconcile.is_null()) doc.set("reconcile", std::move(c.reconcile));
  // Capacity observatory stamp (--capacity on): the canonical {inputs,
  // doc} pair `analyze --capacity-report` recomputes bit-for-bit.
  if (!c.capacity.is_null()) doc.set("capacity", std::move(c.capacity));
  // Trace stamp (--trace on): the evaluation's span-tree-so-far, keyed by
  // trace id — `analyze --trace <flight-dir>` renders waterfalls offline
  // and joins them with this capsule's decisions. Provenance, not
  // evidence: replay never reads it; cross-mode byte-identity
  // comparisons normalize the key away like "incremental"/"reconcile".
  if (!c.trace.is_null()) doc.set("trace", std::move(c.trace));
  doc.set("decisions", std::move(decisions));

  fs::path final_path = fs::path(r.dir) / (id + ".json");
  fs::path tmp_path = fs::path(r.dir) / (id + ".json.tmp");
  {
    std::ofstream out(tmp_path, std::ios::binary | std::ios::trunc);
    out << doc.dump() << "\n";
    if (!out.good()) {
      log::warn("recorder", "capsule write failed for " + id + "; dropping it");
      std::error_code ec;
      fs::remove(tmp_path, ec);
      r.open.erase(it);
      return;
    }
  }
  std::error_code ec;
  fs::rename(tmp_path, final_path, ec);
  if (ec) {
    log::warn("recorder", "capsule rename failed for " + id + ": " + ec.message());
    fs::remove(tmp_path, ec);
    r.open.erase(it);
    return;
  }
  r.index.push_back({id, summarize(id, doc)});
  prune_locked(r);
  r.open.erase(it);
}

}  // namespace

void configure(const std::string& dir, int keep) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  r.open.clear();
  r.index.clear();
  r.dir = dir;
  r.keep = keep < 1 ? 1 : static_cast<size_t>(keep);
  r.enabled = !dir.empty();
  if (!r.enabled) return;
  std::error_code ec;
  fs::create_directories(dir, ec);
  if (ec) {
    log::warn("recorder", "cannot create --flight-dir " + dir + ": " + ec.message() +
              "; flight recorder disabled");
    r.enabled = false;
    return;
  }
  // Rebuild the index from whatever a previous run left behind, then
  // prune — the ring survives restarts.
  std::vector<std::string> ids;
  for (const auto& entry : fs::directory_iterator(dir, ec)) {
    std::string name = entry.path().filename().string();
    if (name.rfind("cycle-", 0) == 0 && name.size() > 5 &&
        name.substr(name.size() - 5) == ".json") {
      ids.push_back(name.substr(0, name.size() - 5));
    }
  }
  std::sort(ids.begin(), ids.end());
  for (const std::string& id : ids) {
    auto text = util::read_file((fs::path(dir) / (id + ".json")).string());
    if (!text) continue;
    try {
      r.index.push_back({id, summarize(id, Value::parse(*text))});
    } catch (const std::exception&) {
      log::warn("recorder", "skipping unparseable capsule " + id + ".json");
    }
  }
  prune_locked(r);
  log::info("recorder", "flight recorder on: " + dir + " (keep " + std::to_string(r.keep) +
            ", " + std::to_string(r.index.size()) + " capsule(s) reloaded)");
}

bool enabled() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  return r.enabled;
}

void set_run_context(Value config, std::string query, std::string evidence_query) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  r.config = std::move(config);
  r.query = std::move(query);
  r.evidence_query = std::move(evidence_query);
}

void begin_cycle(uint64_t cycle, int64_t ts_unix) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  if (!r.enabled) return;
  // A cycle that failed before arm() (query error) left its capsule open
  // with no drain to seal it — drop such strays rather than leak them.
  // Keep the IMMEDIATELY preceding cycle: under --overlap, cycle N+1's
  // prepare opens its capsule while cycle N is still mid-resolve (not yet
  // armed), and dropping it would lose a healthy cycle's flight data.
  for (auto it = r.open.begin(); it != r.open.end();) {
    it = (it->first + 1 < cycle && !it->second.armed) ? r.open.erase(it) : std::next(it);
  }
  OpenCapsule c;
  c.ts_unix = ts_unix;
  c.ts_ms = util::now_unix_nanos() / 1000000;
  r.open[cycle] = std::move(c);
}

void record_prom_body(uint64_t cycle, const std::string& body) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  if (OpenCapsule* c = open_capsule_locked(r, cycle)) c->prom_body = body;
}

void record_evidence_body(uint64_t cycle, const std::string& body) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  if (OpenCapsule* c = open_capsule_locked(r, cycle)) c->evidence_body = body;
}

void record_signal(uint64_t cycle, Value assessment) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  if (OpenCapsule* c = open_capsule_locked(r, cycle)) c->signal_assessment = std::move(assessment);
}

void record_resolve_now(uint64_t cycle, int64_t now_unix) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  if (OpenCapsule* c = open_capsule_locked(r, cycle)) c->now_unix = now_unix;
}

void record_pod(uint64_t cycle, const std::string& key, const Value* pod,
                bool store_missed, const std::string& fetch_error) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  Value ev = Value::object();
  ev.set("present", Value(pod != nullptr));
  if (pod) ev.set("pod", *pod);
  if (store_missed) ev.set("store_missed", Value(true));
  if (!fetch_error.empty()) ev.set("fetch_error", Value(fetch_error));
  c->pods.set(key, std::move(ev));
}

void record_resolution(uint64_t cycle, const std::string& key,
                       const std::vector<std::string>& chain, const std::string& root_kind,
                       const std::string& root_ns, const std::string& root_name,
                       const std::string& identity, const std::string& error) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  Value res = Value::object();
  Value hops = Value::array();
  for (const std::string& hop : chain) hops.push_back(Value(hop));
  res.set("chain", std::move(hops));
  if (!error.empty()) {
    res.set("error", Value(error));
  } else {
    Value root = Value::object();
    root.set("kind", Value(root_kind));
    root.set("namespace", Value(root_ns));
    root.set("name", Value(root_name));
    res.set("root", std::move(root));
    res.set("identity", Value(identity));
  }
  c->resolutions.set(key, std::move(res));
}

void record_object(uint64_t cycle, const std::string& path, const Value* object) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  c->objects.set(path, object ? *object : Value(nullptr));
}

void record_ledger(uint64_t cycle, int64_t now_unix,
                   const std::vector<ledger::Observation>& observations) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  // Deterministic order: the daemon feeds from an unordered map.
  std::vector<const ledger::Observation*> sorted;
  for (const ledger::Observation& o : observations) sorted.push_back(&o);
  std::sort(sorted.begin(), sorted.end(),
            [](const ledger::Observation* a, const ledger::Observation* b) {
              return std::tie(a->kind, a->ns, a->name) < std::tie(b->kind, b->ns, b->name);
            });
  Value obs = Value::array();
  for (const ledger::Observation* o : sorted) {
    Value v = Value::object();
    v.set("kind", Value(o->kind));
    v.set("namespace", Value(o->ns));
    v.set("name", Value(o->name));
    v.set("chips", Value(o->chips));
    v.set("pods", Value(o->pods));
    obs.push_back(std::move(v));
  }
  Value led = Value::object();
  led.set("now_unix", Value(now_unix));
  led.set("observations", std::move(obs));
  c->ledger = std::move(led);
}

void record_vetoes(uint64_t cycle, const std::vector<std::string>& vetoed_roots,
                   const std::vector<std::pair<std::string, std::string>>& vetoed_namespaces) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  for (const std::string& id : vetoed_roots) c->vetoed_roots.push_back(Value(id));
  for (const auto& [ns, cause] : vetoed_namespaces) c->vetoed_namespaces.set(ns, Value(cause));
}

void flag_root(uint64_t cycle, const std::string& identity, const char* flag) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  const Value* existing = c->root_flags.find(identity);
  Value flags = existing ? *existing : Value::object();
  flags.set(flag, Value(true));
  c->root_flags.set(identity, std::move(flags));
}

void record_incremental(uint64_t cycle, Value provenance) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  c->incremental = std::move(provenance);
}

void record_reconcile(uint64_t cycle, Value info) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  c->reconcile = std::move(info);
}

void record_trace(uint64_t cycle, Value stamp) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  c->trace = std::move(stamp);
}

void record_capacity(uint64_t cycle, Value stamp) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  c->capacity = std::move(stamp);
}

void record_breaker(uint64_t cycle, int64_t limit, size_t actionable, size_t deferred) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  Value b = Value::object();
  b.set("limit", Value(limit));
  b.set("actionable", Value(static_cast<int64_t>(actionable)));
  b.set("deferred", Value(static_cast<int64_t>(deferred)));
  b.set("tripped", Value(deferred > 0));
  c->breaker = std::move(b);
}

void record_stats(uint64_t cycle, size_t num_series, size_t num_pods, size_t shutdown_events) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  Value s = Value::object();
  s.set("num_series", Value(static_cast<int64_t>(num_series)));
  s.set("num_pods", Value(static_cast<int64_t>(num_pods)));
  s.set("shutdown_events", Value(static_cast<int64_t>(shutdown_events)));
  c->stats = std::move(s);
}

void record_decision(uint64_t cycle, Value decision) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  c->decisions.push_back(std::move(decision));
}

void arm(uint64_t cycle, size_t expected) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  c->armed = true;
  // Credit consumer outcomes that landed before arming (see
  // early_actuations above) so a fast drain can never wedge the seal.
  c->remaining = expected > c->early_actuations ? expected - c->early_actuations : 0;
  c->early_actuations = 0;
  if (c->remaining == 0) seal_locked(r, cycle);
}

void record_actuation(uint64_t cycle, const std::string& identity, const std::string& reason,
                      const std::string& action, const std::string& detail,
                      bool counts_toward_seal) {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  OpenCapsule* c = open_capsule_locked(r, cycle);
  if (!c) return;
  Value a = Value::object();
  a.set("reason", Value(reason));
  a.set("action", Value(action));
  if (!detail.empty()) a.set("detail", Value(detail));
  c->actuations.set(identity, std::move(a));
  if (!counts_toward_seal) return;  // producer-side cached no-op stamps
  if (c->armed) {
    if (c->remaining > 0 && --c->remaining == 0) seal_locked(r, cycle);
  } else {
    ++c->early_actuations;
  }
}

void seal_all() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  // Armed capsules still waiting on a drained queue flush (their dropped
  // targets already landed SHUTDOWN_ABORTED decisions); unarmed strays
  // (mid-cycle shutdown) are dropped — a capsule without its decisions
  // would replay as drift, which helps nobody.
  std::vector<uint64_t> cycles;
  for (const auto& [cycle, c] : r.open) {
    if (c.armed) cycles.push_back(cycle);
  }
  for (uint64_t cycle : cycles) seal_locked(r, cycle);
  r.open.clear();
}

json::Value index_json() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  Value capsules = Value::array();
  for (const IndexEntry& e : r.index) capsules.push_back(e.summary);
  Value out = Value::object();
  out.set("cluster", Value(fleet::cluster_name()));
  out.set("capsules", std::move(capsules));
  out.set("dir", Value(r.dir));
  out.set("keep", Value(static_cast<int64_t>(r.keep)));
  return out;
}

std::string capsule_body(const std::string& id) {
  if (!id_safe(id)) return "";
  Registry& r = reg();
  std::string path;
  {
    std::lock_guard<std::mutex> lock(r.mutex);
    if (!r.enabled) return "";
    path = (fs::path(r.dir) / (id + ".json")).string();
  }
  return util::read_file(path).value_or("");
}

void reset_for_test() {
  Registry& r = reg();
  std::lock_guard<std::mutex> lock(r.mutex);
  r.enabled = false;
  r.dir.clear();
  r.keep = 64;
  r.config = Value();
  r.query.clear();
  r.evidence_query.clear();
  r.open.clear();
  r.index.clear();
}

// ── replay engine ─────────────────────────────────────────────────────────

namespace {

int64_t parse_duration_secs(const std::string& key, const Value& v) {
  if (v.is_number()) return v.as_int();
  if (!v.is_string()) throw std::runtime_error("what-if " + key + ": expected duration");
  const std::string& s = v.as_string();
  try {
    size_t idx = 0;
    long long n = std::stoll(s, &idx);
    if (idx == s.size()) return n;  // bare number: seconds
    if (idx == s.size() - 1) {
      switch (s[idx]) {
        case 's': return n;
        case 'm': return n * 60;
        case 'h': return n * 3600;
      }
    }
  } catch (const std::exception&) {
  }
  throw std::runtime_error("what-if " + key + ": invalid duration '" + s +
                           "' (expected e.g. 30m, 600s, 2h, or bare seconds)");
}

int64_t parse_int_value(const std::string& key, const Value& v) {
  if (v.is_number()) return v.as_int();
  if (v.is_string()) {
    try {
      size_t idx = 0;
      long long n = std::stoll(v.as_string(), &idx);
      if (idx == v.as_string().size()) return n;
    } catch (const std::exception&) {
    }
  }
  throw std::runtime_error("what-if " + key + ": invalid integer");
}

double parse_double_value(const std::string& key, const Value& v) {
  if (v.is_number()) return v.as_double();
  if (v.is_string()) {
    try {
      size_t idx = 0;
      double d = std::stod(v.as_string(), &idx);
      if (idx == v.as_string().size()) return d;
    } catch (const std::exception&) {
    }
  }
  throw std::runtime_error("what-if " + key + ": invalid number");
}

std::string value_string(const std::string& key, const Value& v) {
  if (!v.is_string()) throw std::runtime_error("what-if " + key + ": expected a string");
  return v.as_string();
}

// Volatile fields stripped before the bit-for-bit comparison: wall-clock
// timestamps and OTLP trace ids legitimately differ between a live cycle
// and its offline replay; everything else must match byte-identically.
Value normalize_decision(const Value& d) {
  Value c = d;
  c.as_object().erase("ts");
  c.as_object().erase("trace_id");
  // The replay process stamps ITS cluster identity into rebuilt records
  // (DecisionRecord::to_json reads the process-wide name), which is not
  // the recording daemon's — identity is provenance, not a decision.
  c.as_object().erase("cluster");
  return c;
}

bool is_actuation_reason(const std::string& reason) {
  return reason == "SCALED" || reason == "ALREADY_PAUSED" || reason == "SCALE_FAILED" ||
         reason == "KIND_DISABLED" || reason == "SHUTDOWN_ABORTED" ||
         reason == "RIGHT_SIZED";
}

}  // namespace

Value replay(const Value& capsule, const Value& what_if) {
  auto require = [&](const char* key) -> const Value& {
    const Value* v = capsule.find(key);
    if (!v) throw std::runtime_error(std::string("malformed capsule: missing ") + key);
    return *v;
  };

  // ── effective config = capsule fingerprint + what-if overlay ──
  const Value& cfg = require("config");
  const Value* qa = cfg.find("query_args");
  if (!qa) throw std::runtime_error("malformed capsule: config missing query_args");
  query::QueryArgs qargs = query::args_from_json(*qa);
  std::string run_mode = cfg.get_string("run_mode", "dry-run");
  std::string enabled_flags = cfg.get_string("enabled_resources", "drsinjl");
  auto cfg_int = [&](const char* key, int64_t dflt) {
    const Value* v = cfg.find(key);
    return (v && v->is_number()) ? v->as_int() : dflt;
  };
  int64_t grace_s = cfg_int("grace_s", 300);
  int64_t lookback_s = cfg_int("lookback_s", qargs.duration_min * 60 + grace_s);
  const int64_t recorded_max_scale = cfg_int("max_scale_per_cycle", 0);
  int64_t max_scale = recorded_max_scale;
  // Replica right-sizing config (absent on pre-gym capsules → off,
  // exactly how those cycles ran).
  std::string right_size = cfg.get_string("right_size", "off");
  double rs_threshold = 0.8;
  if (const Value* t = cfg.find("right_size_threshold"); t && t->is_number()) {
    rs_threshold = t->as_double();
  }
  const std::string recorded_right_size = right_size;
  const double recorded_rs_threshold = rs_threshold;
  // Slice-topology gate config (absent on pre-capacity capsules → off,
  // exactly how those cycles ran). The gate's verdicts are cycle facts
  // (the slice_shared_busy root flag); what-if slice_gate=off re-opens
  // the held roots, =on on a capsule recorded without the gate is a
  // no-op (no flags were captured to honor).
  std::string slice_gate = cfg.get_string("slice_gate", "off");
  // Signal-quality watchdog config (absent on pre-watchdog capsules →
  // guard off, exactly how those cycles ran).
  std::string signal_guard = cfg.get_string("signal_guard", "off");
  signal::Config scfg;
  scfg.scrape_interval_s = cfg_int("signal_scrape_interval_s", 30);
  scfg.max_age_s = cfg_int("signal_max_age_s", 300);
  if (const Value* mc = cfg.find("signal_min_coverage"); mc && mc->is_number()) {
    scfg.min_coverage = mc->as_double();
  }

  bool breaker_overridden = false, lookback_explicit = false, window_derived = false;
  bool has_what_if = what_if.is_object() && !what_if.as_object().empty();
  if (what_if.is_object()) {
    for (const auto& [key, val] : what_if.as_object()) {
      if (key == "lookback") {
        lookback_s = parse_duration_secs(key, val);
        lookback_explicit = true;
      } else if (key == "duration") {
        // Plain numbers are minutes (the -t flag's unit); suffixed
        // durations ("45m", "3600s") convert through seconds.
        if (val.is_string() && !val.as_string().empty() &&
            !std::isdigit(static_cast<unsigned char>(val.as_string().back()))) {
          qargs.duration_min = parse_duration_secs(key, val) / 60;
        } else {
          qargs.duration_min = parse_int_value(key, val);
        }
        window_derived = true;
      } else if (key == "grace") {
        grace_s = parse_duration_secs(key, val);
        window_derived = true;
      } else if (key == "run_mode") {
        run_mode = value_string(key, val);
        if (run_mode != "scale-down" && run_mode != "dry-run") {
          throw std::runtime_error("what-if run_mode: expected scale-down|dry-run");
        }
      } else if (key == "enabled_resources") {
        enabled_flags = value_string(key, val);
      } else if (key == "max_scale_per_cycle") {
        max_scale = parse_int_value(key, val);
        breaker_overridden = true;
      } else if (key == "hbm_threshold") {
        qargs.hbm_threshold = parse_double_value(key, val);
      } else if (key == "signal_min_coverage") {
        scfg.min_coverage = parse_double_value(key, val);
        if (scfg.min_coverage < 0.0 || scfg.min_coverage > 1.0) {
          throw std::runtime_error("what-if signal_min_coverage: expected 0..1");
        }
      } else if (key == "signal_guard") {
        signal_guard = value_string(key, val);
        if (signal_guard != "on" && signal_guard != "off") {
          throw std::runtime_error("what-if signal_guard: expected on|off");
        }
      } else if (key == "right_size") {
        right_size = value_string(key, val);
        if (right_size != "on" && right_size != "off") {
          throw std::runtime_error("what-if right_size: expected on|off");
        }
      } else if (key == "right_size_threshold") {
        rs_threshold = parse_double_value(key, val);
        if (!(rs_threshold > 0.0 && rs_threshold <= 1.0)) {
          throw std::runtime_error("what-if right_size_threshold: expected (0, 1]");
        }
      } else if (key == "slice_gate") {
        slice_gate = value_string(key, val);
        if (slice_gate != "on" && slice_gate != "off") {
          throw std::runtime_error("what-if slice_gate: expected on|off");
        }
      } else {
        throw std::runtime_error(
            "unknown what-if key: " + key +
            " (supported: lookback, duration, grace, run_mode, enabled_resources, "
            "max_scale_per_cycle, hbm_threshold, signal_min_coverage, signal_guard, "
            "right_size, right_size_threshold, slice_gate)");
      }
    }
    if (window_derived && !lookback_explicit) lookback_s = qargs.duration_min * 60 + grace_s;
  }
  const bool dry_run = run_mode != "scale-down";
  const core::ResourceSet enabled = core::parse_enabled_resources(enabled_flags);

  // Query-shaping keys (duration window, hbm_threshold) re-render the
  // PromQL; the recorded response can't be re-queried offline, so the
  // changed query is REPORTED while decisions evaluate recorded evidence.
  std::string replay_query = query::build_idle_query(qargs);
  const bool query_changed = replay_query != capsule.get_string("query");

  // ── decode the verbatim recorded body (zero network) ──
  metrics::DecodeResult decoded = metrics::decode_instant_vector(
      Value::parse(require("prom").get_string("body")), qargs.device, qargs.metric_schema);

  const int64_t now = require("now_unix").as_int();
  const uint64_t cycle = static_cast<uint64_t>(require("cycle").as_int());

  // ── signal watchdog: re-derive every verdict from the recorded raw
  //    evidence body (never from the stamped assessment), so the veto
  //    and brownout decisions below are recomputed facts, bit-for-bit ──
  scfg.window_s = qargs.duration_min * 60;
  const bool guard_on = signal_guard == "on";
  signal::Assessment sig;
  std::map<std::string, const signal::PodSignal*> signal_by_pod;
  if (guard_on) {
    const Value* evidence = capsule.find("evidence");
    if (!evidence) {
      throw std::runtime_error(
          "signal_guard=on but the capsule carries no evidence recording "
          "(the cycle ran without --signal-guard on)");
    }
    sig = signal::assess(Value::parse(evidence->get_string("body")), decoded.samples, scfg,
                         cycle);
    for (const signal::PodSignal& p : sig.pods) signal_by_pod[p.ns + "/" + p.pod] = &p;
  }
  const bool signal_brownout = guard_on && sig.brownout;

  const Value* pods_ev = capsule.find("pods");
  const Value* resolutions = capsule.find("resolutions");
  const Value* objects = capsule.find("objects");
  const Value* root_flags = capsule.find("root_flags");
  const Value* actuations = capsule.find("actuations");
  std::set<std::string> vetoed_roots;
  if (const Value* vr = capsule.find("vetoed_roots"); vr && vr->is_array()) {
    for (const Value& v : vr->as_array()) vetoed_roots.insert(v.as_string());
  }
  std::map<std::string, std::string> vetoed_ns;
  if (const Value* vn = capsule.find("vetoed_namespaces"); vn && vn->is_object()) {
    for (const auto& [k, v] : vn->as_object()) vetoed_ns[k] = v.as_string();
  }

  // The REAL owner walk over the capsule's recorded object snapshot —
  // used only for pods the captured cycle never walked (a gate the
  // what-if re-opened). A path absent from the snapshot answers like a
  // 404: the offline store cannot invent topology it never saw.
  walker::ObjectFetcher fetcher = [&](const std::string& path) -> std::optional<Value> {
    const Value* o = objects ? objects->find(path) : nullptr;
    if (!o || o->is_null()) return std::nullopt;
    return *o;
  };

  const std::string signal_metric =
      qargs.device == "gpu" ? "dcgm/gr_engine_active" : "tensorcore/duty_cycle";

  struct PendingT {
    audit::DecisionRecord rec;
    std::string identity;
    core::Kind kind = core::Kind::Deployment;
    int64_t chips = 0;  // pod chip request (right-size evidence)
  };
  // Recorded decisions, keyed by pod — the comparison baseline, the
  // per-pod fallback for actuation outcomes, and the held-fixed source
  // for signal-vetoed pods whose cluster evidence was never captured.
  std::map<std::string, Value> recorded_by_pod;
  if (const Value* recs = capsule.find("decisions"); recs && recs->is_array()) {
    for (const Value& d : recs->as_array()) {
      recorded_by_pod[d.get_string("namespace") + "/" + d.get_string("pod")] = d;
    }
  }
  std::vector<audit::DecisionRecord> finals;
  std::vector<PendingT> pendings;
  std::map<std::string, bool> predicted_by_pod;

  // Deterministic order (capture fan-out order is thread-dependent; the
  // comparison is keyed by pod, so only tie-breaking cares).
  std::vector<const core::PodMetricSample*> samples;
  for (const core::PodMetricSample& s : decoded.samples) samples.push_back(&s);
  std::sort(samples.begin(), samples.end(),
            [](const core::PodMetricSample* a, const core::PodMetricSample* b) {
              return std::tie(a->ns, a->name) < std::tie(b->ns, b->name);
            });

  struct Res {
    bool resolved = false;
    std::vector<std::string> chain;
    std::string kind, ns, name, identity, error;
  };

  for (const core::PodMetricSample* s : samples) {
    const std::string key = s->ns + "/" + s->name;
    audit::DecisionRecord rec;
    rec.cycle = cycle;
    rec.ns = s->ns;
    rec.pod = s->name;
    rec.signal_metric = signal_metric;
    rec.signal_value = s->value;
    rec.has_signal = true;
    rec.accelerator = s->accelerator;
    rec.lookback_s = lookback_s;
    auto decide = [&](audit::Reason reason, const std::string& detail = "") {
      rec.reason = reason;
      rec.action = "none";
      rec.detail = detail;
      finals.push_back(rec);
    };

    // Signal vetoes run BEFORE pod acquisition, exactly as in the daemon:
    // a vetoed candidate never reached resolution, so the capsule holds
    // no pod evidence for it either.
    if (guard_on) {
      auto sp = signal_by_pod.find(key);
      if (sp != signal_by_pod.end() && sp->second->verdict != signal::Verdict::Healthy) {
        decide(signal::veto_reason(sp->second->verdict),
               signal::veto_detail(*sp->second, scfg));
        continue;
      }
    }

    const Value* ev = pods_ev ? pods_ev->find(key) : nullptr;
    if (!ev) {
      // A candidate without acquisition evidence was signal-vetoed at
      // record time: the guard stops vetoed pods BEFORE any cluster
      // fetch, so the capsule never saw their Pod JSON or owner chain.
      // When a what-if re-opens that path (signal_guard=off), the
      // offline store cannot re-derive what was never captured — hold
      // the recorded veto fixed, like the other cluster-state facts.
      if (auto recd = recorded_by_pod.find(key); recd != recorded_by_pod.end()) {
        const std::string recorded_reason = recd->second.get_string("reason");
        if (recorded_reason.rfind("SIGNAL_", 0) == 0) {
          decide(audit::reason_from_name(recorded_reason).value_or(audit::Reason::SignalAbsent),
                 recd->second.get_string("detail"));
          continue;
        }
      }
      throw std::runtime_error("malformed capsule: no pod evidence for candidate " + key);
    }
    if (std::string fetch_error = ev->get_string("fetch_error"); !fetch_error.empty()) {
      decide(audit::Reason::FetchError, "pod GET failed, namespace vetoed: " + fetch_error);
      continue;
    }
    const Value* present = ev->find("present");
    if (!(present && present->is_bool() && present->as_bool())) {
      const Value* sm = ev->find("store_missed");
      bool store_missed = sm && sm->is_bool() && sm->as_bool();
      decide(store_missed ? audit::Reason::WatchCacheMiss : audit::Reason::PodGone,
             store_missed ? "absent from the synced watch store and from the live GET"
                          : "in the metric plane but not in the cluster");
      continue;
    }
    const Value* pod = ev->find("pod");
    if (!pod) throw std::runtime_error("malformed capsule: pod evidence without object for " + key);

    auto resolve = [&]() -> Res {
      Res r;
      if (const Value* rv = resolutions ? resolutions->find(key) : nullptr) {
        if (const Value* c = rv->find("chain"); c && c->is_array()) {
          for (const Value& hop : c->as_array()) r.chain.push_back(hop.as_string());
        }
        if (const Value* root = rv->find("root")) {
          r.resolved = true;
          r.kind = root->get_string("kind");
          r.ns = root->get_string("namespace");
          r.name = root->get_string("name");
          r.identity = rv->get_string("identity");
        } else {
          r.error = rv->get_string("error",
                                   "no scalable root object found for pod " + key);
        }
        return r;
      }
      try {
        core::ScaleTarget t = walker::find_root_object_from(fetcher, *pod, &r.chain);
        r.resolved = true;
        r.kind = std::string(core::kind_name(t.kind));
        r.ns = t.ns().value_or("");
        r.name = t.name();
        r.identity = t.identity();
      } catch (const std::exception& e) {
        r.error = e.what();
      }
      return r;
    };

    core::Eligibility elig = core::check_eligibility(*pod, now, lookback_s);
    if (elig == core::Eligibility::Pending) {
      decide(audit::Reason::PendingPod);
      continue;
    }
    if (elig == core::Eligibility::NoCreationTs) {
      decide(audit::Reason::NoCreationTimestamp);
      continue;
    }
    if (elig == core::Eligibility::BadTimestamp) {
      decide(audit::Reason::BadCreationTimestamp);
      continue;
    }
    if (elig == core::Eligibility::TooYoung) {
      decide(audit::Reason::BelowMinAge,
             "created within the " + std::to_string(lookback_s) + "s lookback window");
      continue;
    }
    if (elig == core::Eligibility::OptedOut) {
      Res r = resolve();
      rec.owner_chain = r.chain;
      if (!r.resolved) {
        decide(audit::Reason::OptedOut,
               "annotated pod with unresolvable root; namespace vetoed: " + r.error);
      } else {
        rec.root_kind = r.kind;
        rec.root_ns = r.ns;
        rec.root_name = r.name;
        decide(audit::Reason::OptedOut,
               "pod annotation vetoes its root for every kind this cycle");
      }
      continue;
    }
    // Eligible
    Res r = resolve();
    rec.owner_chain = r.chain;
    if (!r.resolved) {
      decide(audit::Reason::NoScalableOwner, r.error);
      continue;
    }
    rec.root_kind = r.kind;
    rec.root_ns = r.ns;
    rec.root_name = r.name;
    PendingT p;
    p.rec = std::move(rec);
    p.identity = r.identity;
    if (auto k = core::kind_from_name(r.kind)) p.kind = *k;
    p.chips = core::pod_chip_count(*pod, qargs.device);
    pendings.push_back(std::move(p));
  }

  // ── target-level gates (same order as run_cycle: valves → group gate →
  //    breaker → dry-run / consumer) over unique root identities ──
  std::vector<std::string> order;
  std::map<std::string, core::Kind> kind_of;
  std::map<std::string, std::string> ns_of, name_of;
  for (const PendingT& p : pendings) {
    if (!kind_of.count(p.identity)) {
      order.push_back(p.identity);
      kind_of[p.identity] = p.kind;
      ns_of[p.identity] = p.rec.root_ns;
      name_of[p.identity] = p.rec.root_name;
    }
  }

  // Replica right-sizing: re-derive each candidate root's plan from the
  // capsule's own evidence (root object snapshot + per-pod chip
  // requests) with the SAME math the daemon runs (gym::right_size_plan),
  // so RIGHT_SIZED / RIGHT_SIZE_HELD decisions replay offline — and flip
  // under what-if right_size / right_size_threshold overlays.
  const bool right_size_on = right_size == "on";
  const bool rs_config_changed =
      right_size != recorded_right_size || rs_threshold != recorded_rs_threshold;
  std::map<std::string, gym::RightSizePlan> rs_plans;
  if (right_size_on) {
    std::map<std::string, std::pair<int64_t, int64_t>> stats;  // identity → {pods, chips}
    for (const PendingT& p : pendings) {
      auto& s = stats[p.identity];
      s.first += 1;
      s.second += p.chips;
    }
    for (const auto& [id, s] : stats) {
      const Value* root_obj =
          objects ? objects->find(
                        k8s::Client::object_path(kind_of[id], ns_of[id], name_of[id]))
                  : nullptr;
      if (root_obj && !root_obj->is_null()) {
        rs_plans[id] = gym::right_size_plan(kind_of[id], *root_obj, s.first, s.second,
                                            rs_threshold);
      }
    }
  }
  auto flag_set = [&](const std::string& id, const char* f) {
    const Value* fl = root_flags ? root_flags->find(id) : nullptr;
    if (!fl) return false;
    const Value* b = fl->find(f);
    return b && b->is_bool() && b->as_bool();
  };

  struct Outcome {
    audit::Reason reason = audit::Reason::DryRun;
    std::string action = "none";
    std::string detail;
    bool pending_actuation = false;  // enabled survivor awaiting per-pod join
    bool predicted = false;
  };
  std::map<std::string, Outcome> outcomes;
  std::vector<std::string> survivors;
  for (const std::string& id : order) {
    if (flag_set(id, "root_opted_out")) {
      outcomes[id] = {audit::Reason::RootOptedOut, "none",
                      "annotated " + std::string(core::kSkipAnnotation) + "=true", false, false};
    } else if (vetoed_roots.count(id)) {
      outcomes[id] = {audit::Reason::VetoedByAnnotatedPod, "none", "vetoed by an annotated pod",
                      false, false};
    } else if (auto it = vetoed_ns.find(ns_of[id]); it != vetoed_ns.end()) {
      outcomes[id] = {audit::Reason::NamespaceVetoed, "none",
                      "namespace vetoed (" + it->second + ")", false, false};
    } else if (flag_set(id, "group_not_idle")) {
      outcomes[id] = {audit::Reason::GroupNotIdle, "none",
                      "group has active (or too-young) TPU hosts", false, false};
    } else if (slice_gate == "on" && flag_set(id, "slice_shared_busy")) {
      // Cycle fact like the group verdict: the slice-topology co-tenancy
      // came from a cluster LIST the capsule can't re-derive. What-if
      // slice_gate=off re-opens the root (it falls through to the
      // breaker/actuation stages as a predicted flip).
      outcomes[id] = {audit::Reason::SliceSharedBusy, "none",
                      capacity::kSliceSharedBusyDetail, false, false};
    } else {
      survivors.push_back(id);
    }
  }
  auto final_stage = [&](const std::string& id) {
    Outcome o;
    if (signal_brownout) {
      // The daemon clears every post-breaker survivor under a brownout
      // (disabled kinds and dry-run included) — the outcome map wins
      // over the dry-run/pending paths, so mirror that precedence here.
      outcomes[id] = {audit::Reason::SignalBrownout, "none",
                      signal::brownout_detail(sig, scfg), false, false};
      return;
    }
    if (dry_run) {
      o = {audit::Reason::DryRun, "none", "would have paused (run-mode dry-run)", false, false};
    } else if (!(enabled & core::flag(kind_of[id]))) {
      o = {audit::Reason::KindDisabled, "none", "", false, false};
    } else if (right_size_on && rs_plans.count(id) && rs_plans[id].applicable &&
               rs_plans[id].held) {
      // Same precedence as the daemon: the right-size split runs
      // producer-side for enabled kinds in scale-down mode, after the
      // breaker and the brownout.
      o = {audit::Reason::RightSizeHeld, "none", rs_plans[id].detail, false, false};
    } else {
      o.pending_actuation = true;
    }
    outcomes[id] = o;
  };
  if (!breaker_overridden) {
    // Breaker deferrals are recorded cluster-time facts; held fixed.
    for (const std::string& id : survivors) {
      if (flag_set(id, "deferred")) {
        outcomes[id] = {audit::Reason::Deferred, "none",
                        "over --max-scale-per-cycle=" + std::to_string(recorded_max_scale),
                        false, false};
      } else {
        final_stage(id);
      }
    }
  } else if (max_scale > 0) {
    size_t budget = static_cast<size_t>(max_scale);
    for (const std::string& id : survivors) {
      if (!(enabled & core::flag(kind_of[id]))) {
        final_stage(id);  // disabled kinds never consume breaker slots
        continue;
      }
      if (budget > 0) {
        --budget;
        final_stage(id);
      } else {
        outcomes[id] = {audit::Reason::Deferred, "none",
                        "over --max-scale-per-cycle=" + std::to_string(max_scale), false, false};
      }
    }
  } else {
    for (const std::string& id : survivors) final_stage(id);
  }

  for (PendingT& p : pendings) {
    const std::string key = p.rec.ns + "/" + p.rec.pod;
    Outcome o = outcomes[p.identity];
    if (o.pending_actuation) {
      // Recorded actuation outcomes are cluster facts — trusted verbatim
      // UNLESS a right-size what-if changed the decision itself: a
      // record that was (or was not) RIGHT_SIZED under the recorded
      // config is stale once the overlay flips the plan, and the replay
      // predicts the new outcome instead.
      const bool expect_rs = right_size_on && rs_plans.count(p.identity) &&
                             rs_plans[p.identity].applicable && !rs_plans[p.identity].held;
      auto stale_record = [&](const std::string& reason) {
        if (!rs_config_changed) return false;
        if (expect_rs) return true;  // plan (R→N, freed chips) may differ
        return reason == "RIGHT_SIZED";  // was partial, now a full pause
      };
      auto predict = [&] {
        if (expect_rs) {
          o.reason = audit::Reason::RightSized;
          o.detail = rs_plans[p.identity].detail;
        } else {
          o.reason = audit::Reason::Scaled;
          o.detail = "";
        }
        o.action = "scale_down";
        o.predicted = true;
      };
      const Value* act = actuations ? actuations->find(p.identity) : nullptr;
      if (act && !stale_record(act->get_string("reason"))) {
        o.reason = audit::reason_from_name(act->get_string("reason"))
                       .value_or(audit::Reason::Scaled);
        o.action = act->get_string("action", "none");
        o.detail = act->get_string("detail");
      } else if (auto it = recorded_by_pod.find(key);
                 it != recorded_by_pod.end() &&
                 is_actuation_reason(it->second.get_string("reason")) &&
                 !stale_record(it->second.get_string("reason"))) {
        o.reason = audit::reason_from_name(it->second.get_string("reason"))
                       .value_or(audit::Reason::Scaled);
        o.action = it->second.get_string("action", "none");
        o.detail = it->second.get_string("detail");
      } else {
        // What-if opened a path the recorded cycle never actuated (or
        // the right-size overlay invalidated the recorded outcome).
        predict();
      }
    }
    p.rec.reason = o.reason;
    p.rec.action = o.action;
    p.rec.detail = o.detail;
    if (o.predicted) predicted_by_pod[key] = true;
    finals.push_back(std::move(p.rec));
  }

  // ── bit-for-bit comparison over normalized records ──
  std::map<std::string, Value> replayed_by_pod;
  for (const audit::DecisionRecord& rec : finals) {
    replayed_by_pod[rec.ns + "/" + rec.pod] = normalize_decision(rec.to_json());
  }
  std::map<std::string, Value> recorded_norm;
  for (const auto& [key, d] : recorded_by_pod) recorded_norm[key] = normalize_decision(d);

  Value drift = Value::array();
  Value flips = Value::array();
  std::set<std::string> keys;
  for (const auto& [k, _] : replayed_by_pod) keys.insert(k);
  for (const auto& [k, _] : recorded_norm) keys.insert(k);
  for (const std::string& k : keys) {
    auto rep = replayed_by_pod.find(k);
    auto recd = recorded_norm.find(k);
    const bool have_rep = rep != replayed_by_pod.end();
    const bool have_rec = recd != recorded_norm.end();
    if (have_rep && have_rec && rep->second.dump() == recd->second.dump()) continue;
    Value entry = Value::object();
    entry.set("pod", Value(k));
    entry.set("recorded", have_rec ? recd->second : Value(nullptr));
    entry.set("replayed", have_rep ? rep->second : Value(nullptr));
    drift.push_back(std::move(entry));
    if (has_what_if && have_rep && have_rec) {
      const std::string from_reason = recd->second.get_string("reason");
      const std::string to_reason = rep->second.get_string("reason");
      const std::string from_action = recd->second.get_string("action");
      const std::string to_action = rep->second.get_string("action");
      if (from_reason != to_reason || from_action != to_action) {
        Value flip = Value::object();
        flip.set("pod", Value(k));
        Value from = Value::object();
        from.set("reason", Value(from_reason));
        from.set("action", Value(from_action));
        Value to = Value::object();
        to.set("reason", Value(to_reason));
        to.set("action", Value(to_action));
        flip.set("from", std::move(from));
        flip.set("to", std::move(to));
        flip.set("predicted", Value(predicted_by_pod.count(k) > 0));
        flips.push_back(std::move(flip));
      }
    }
  }

  int64_t recorded_scale_downs = 0, replayed_scale_downs = 0;
  for (const auto& [_, d] : recorded_norm) {
    if (d.get_string("action") == "scale_down") ++recorded_scale_downs;
  }
  Value replayed = Value::array();
  for (const auto& [_, d] : replayed_by_pod) {
    if (d.get_string("action") == "scale_down") ++replayed_scale_downs;
    replayed.push_back(d);
  }
  Value recorded = Value::array();
  for (const auto& [_, d] : recorded_norm) recorded.push_back(d);

  Value out = Value::object();
  out.set("cycle", Value(static_cast<int64_t>(cycle)));
  out.set("match", Value(drift.as_array().empty()));
  out.set("replayed", std::move(replayed));
  out.set("recorded", std::move(recorded));
  out.set("drift", std::move(drift));
  if (has_what_if) {
    out.set("flips", std::move(flips));
    out.set("what_if", what_if);
  }
  out.set("query_changed", Value(query_changed));
  if (query_changed) out.set("replay_query", Value(replay_query));
  Value actions = Value::object();
  actions.set("recorded_scale_downs", Value(recorded_scale_downs));
  actions.set("replayed_scale_downs", Value(replayed_scale_downs));
  out.set("actions", std::move(actions));
  return out;
}

}  // namespace tpupruner::recorder
