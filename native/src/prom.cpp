#include "tpupruner/prom.hpp"

#include <stdexcept>

#include "tpupruner/util.hpp"

namespace tpupruner::prom {

Client::Client(std::string base_url, std::string bearer_token, http::TlsMode tls_mode,
               std::string ca_file, int timeout_ms)
    : base_url_(std::move(base_url)),
      token_(std::move(bearer_token)),
      http_(h2::default_mode(), tls_mode, std::move(ca_file)),
      timeout_ms_(timeout_ms) {
  while (!base_url_.empty() && base_url_.back() == '/') base_url_.pop_back();
}

http::Response Client::query_once(const std::string& promql, std::string_view accept) const {
  http::Request req;
  req.method = "POST";
  req.url = base_url_ + "/api/v1/query";
  req.headers.push_back({"Content-Type", "application/x-www-form-urlencoded"});
  req.headers.push_back({"Accept", std::string(accept)});
  {
    std::lock_guard<std::mutex> lock(token_mutex_);
    if (!token_.empty()) req.headers.push_back({"Authorization", "Bearer " + token_});
  }
  req.body = "query=" + util::url_encode(promql);
  req.timeout_ms = timeout_ms_;

  http::Response resp = http_.request(req);
  if (resp.status < 200 || resp.status >= 300) {
    // Prometheus error bodies are JSON {"status":"error","error":...};
    // surface them verbatim (truncated) for the failure-budget log line.
    std::string snippet = resp.body.substr(0, 512);
    throw std::runtime_error("prometheus returned HTTP " + std::to_string(resp.status) + ": " +
                             snippet);
  }
  return resp;
}

json::Value Client::instant_query(const std::string& promql, std::string* raw_body) const {
  http::Response resp = query_once(promql);
  if (raw_body) *raw_body = resp.body;
  proto::counters().prom_json_bytes.fetch_add(resp.body.size(), std::memory_order_relaxed);
  try {
    return json::Value::parse(resp.body);
  } catch (const json::ParseError& e) {
    throw std::runtime_error(std::string("prometheus returned unparseable body: ") + e.what());
  }
}

json::DocPtr Client::instant_query_doc(const std::string& promql, std::string* raw_body) const {
  http::Response resp = query_once(promql);
  if (raw_body) *raw_body = resp.body;  // verbatim copy BEFORE the body moves
  proto::counters().prom_json_bytes.fetch_add(resp.body.size(), std::memory_order_relaxed);
  try {
    return json::Doc::parse(std::move(resp.body));
  } catch (const json::ParseError& e) {
    throw std::runtime_error(std::string("prometheus returned unparseable body: ") + e.what());
  }
}

Client::WireVector Client::instant_query_wire(const std::string& promql,
                                              std::string* raw_body) const {
  const bool want_proto = proto::prom_proto_wanted();
  http::Response resp = query_once(
      promql, want_proto ? proto::kPromProtoAccept : std::string_view("application/json"));
  WireVector out;
  std::string content_type;
  if (auto it = resp.headers.find("content-type"); it != resp.headers.end()) {
    content_type = it->second;
  }
  if (proto::is_prom_proto(content_type)) {
    proto::counters().prom_proto_bytes.fetch_add(resp.body.size(), std::memory_order_relaxed);
    try {
      // Fused decode: ONE scan of the body yields the per-series labels
      // and the exact timestamp/value text — no tree, no arena.
      out.pv = proto::parse_prom_vector(resp.body);
    } catch (const json::ParseError& e) {
      throw std::runtime_error(std::string("prometheus returned unparseable body: ") +
                               e.what());
    }
    out.proto = true;
    // Canonical JSON reconstruction for the flight recorder: replay and
    // `--wire json` capsules must carry the SAME bytes.
    if (raw_body) *raw_body = proto::prom_canonical_body(out.pv);
    return out;
  }
  if (want_proto) proto::note_prom_fallback();
  if (raw_body) *raw_body = resp.body;
  proto::counters().prom_json_bytes.fetch_add(resp.body.size(), std::memory_order_relaxed);
  try {
    if (json::zero_copy_enabled()) {
      out.doc = json::Doc::parse(std::move(resp.body));
    } else {
      out.response = json::Value::parse(resp.body);
    }
  } catch (const json::ParseError& e) {
    throw std::runtime_error(std::string("prometheus returned unparseable body: ") + e.what());
  }
  return out;
}

}  // namespace tpupruner::prom
